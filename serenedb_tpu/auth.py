"""Roles and ACLs.

Reference analog: server/auth/{acl,role_closure}.cpp + the RBAC statements
in server/pg/commands/rbac.cpp and AclMode bitmask checks at catalog
snapshot reads (SURVEY.md §2.4). Model: flat roles with per-table privilege
sets; the built-in superuser role `serene` (and any SUPERUSER role)
bypasses checks; `public` grants apply to every role.
"""

from __future__ import annotations

import threading
from typing import Optional

from . import errors, scram

PRIVILEGES = {"select", "insert", "update", "delete"}
SUPERUSER = "serene"


class Roles:
    def __init__(self):
        self._lock = threading.Lock()
        self.roles: dict[str, dict] = {
            SUPERUSER: {"password": None, "login": True, "superuser": True}}
        # acls[table_key][role] = set of privileges
        self.acls: dict[str, dict[str, set]] = {}
        # memberships[member] = roles granted to it (GRANT role TO member)
        self.memberships: dict[str, set] = {}

    # -- role management ---------------------------------------------------

    def create(self, name: str, password: Optional[str], login: bool,
               superuser: bool, if_not_exists: bool):
        key = name.lower()
        with self._lock:
            if key in self.roles:
                if if_not_exists:
                    return
                raise errors.SqlError(errors.DUPLICATE_OBJECT,
                                      f'role "{name}" already exists')
            entry = {"password": None, "login": login,
                     "superuser": superuser}
            if password is not None:
                # only the SCRAM verifier is stored, never the plaintext
                # (reference: PG stores scram-sha-256 verifiers in
                # pg_authid.rolpassword)
                entry["scram"] = scram.build_verifier(password)
            self.roles[key] = entry

    def alter(self, name: str, set_password: bool = False,
              password: Optional[str] = None, login=None, superuser=None):
        """ALTER ROLE: rotate/clear credentials, flip LOGIN/SUPERUSER.
        Passwords become SCRAM verifiers; the bootstrap superuser can
        change its password but never lose LOGIN/SUPERUSER."""
        key = name.lower()
        with self._lock:
            r = self.roles.get(key)
            if r is None:
                raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                      f'role "{name}" does not exist')
            if key == SUPERUSER and (login is False or superuser is False):
                raise errors.SqlError(
                    errors.FEATURE_NOT_SUPPORTED,
                    "cannot demote the bootstrap superuser")
            if set_password:
                r["password"] = None
                if password is None:
                    r.pop("scram", None)
                else:
                    r["scram"] = scram.build_verifier(password)
            if login is not None:
                r["login"] = login
            if superuser is not None:
                r["superuser"] = superuser

    def drop(self, name: str, if_exists: bool):
        key = name.lower()
        with self._lock:
            if key not in self.roles:
                if if_exists:
                    return
                raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                      f'role "{name}" does not exist')
            if key == SUPERUSER:
                raise errors.SqlError(errors.FEATURE_NOT_SUPPORTED,
                                      "cannot drop the bootstrap superuser")
            del self.roles[key]
            for acl in self.acls.values():
                acl.pop(key, None)
            self.memberships.pop(key, None)
            for g in self.memberships.values():
                g.discard(key)

    def exists(self, name: str) -> bool:
        with self._lock:
            return name.lower() in self.roles

    def scram_verifier(self, name: str) -> Optional[dict]:
        with self._lock:
            r = self.roles.get(name.lower())
            return dict(r["scram"]) if r and r.get("scram") else None

    def is_superuser(self, name: str) -> bool:
        with self._lock:
            r = self.roles.get(name.lower())
            return bool(r and r.get("superuser"))

    def can_login(self, name: str) -> bool:
        with self._lock:
            r = self.roles.get(name.lower())
            return bool(r and r.get("login", True))

    def has_password(self, name: str) -> bool:
        with self._lock:
            r = self.roles.get(name.lower())
            return bool(r and (r.get("scram") or
                               r.get("password") is not None))

    def check_password(self, name: str, password: str) -> bool:
        """Cleartext check; SCRAM-only roles verify by re-deriving the
        stored key. Fails CLOSED when no credential is on record — a
        cleartext exchange against a passwordless role must not succeed
        (the HBA 'password' method made this path reachable)."""
        with self._lock:
            r = self.roles.get(name.lower())
            if r is None or not r.get("login", True):
                return False
            stored = r.get("password")
            verifier = r.get("scram")
        if stored is not None:
            return stored == password
        if verifier:
            from . import scram
            return scram.verify_cleartext(verifier, password)
        return False

    # -- grants ------------------------------------------------------------

    def grant(self, table_key: str, role: str, privileges: list[str],
              revoke: bool = False):
        role = role.lower()
        privs = set()
        for p in privileges:
            if p == "all":
                privs |= PRIVILEGES
            elif p in PRIVILEGES:
                privs.add(p)
            else:
                raise errors.SqlError("0LP01",
                                      f"unknown privilege {p!r}")
        with self._lock:
            if role != "public" and role not in self.roles:
                raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                      f'role "{role}" does not exist')
            acl = self.acls.setdefault(table_key, {})
            cur = acl.setdefault(role, set())
            if revoke:
                cur -= privs
            else:
                cur |= privs

    def grant_role(self, granted: str, member: str,
                   revoke: bool = False):
        """Role membership: `GRANT granted TO member` — member inherits
        granted's privileges transitively (reference: auth::RoleClosure,
        server/auth/role_closure.cpp)."""
        granted, member = granted.lower(), member.lower()
        with self._lock:
            for r in (granted, member):
                if r not in self.roles:
                    raise errors.SqlError(
                        errors.UNDEFINED_OBJECT,
                        f'role "{r}" does not exist')
            ms = self.memberships.setdefault(member, set())
            if revoke:
                ms.discard(granted)
            else:
                if member in self._closure(granted):
                    raise errors.SqlError(
                        "0LP01", f'role "{member}" is a member of role '
                                 f'"{granted}"')  # cycle
                ms.add(granted)

    def _closure(self, role: str) -> set:
        """role + every role reachable through memberships (under lock
        or on a consistent snapshot)."""
        out, stack = set(), [role]
        while stack:
            r = stack.pop()
            if r in out:
                continue
            out.add(r)
            stack.extend(self.memberships.get(r, ()))
        return out

    def allowed(self, role: str, table_key: str, privilege: str) -> bool:
        role = role.lower()
        with self._lock:
            r = self.roles.get(role)
            if r and r.get("superuser"):
                return True
            acl = self.acls.get(table_key, {})
            for g in self._closure(role):
                if privilege in acl.get(g, ()):
                    return True
            return privilege in acl.get("public", ())

    def require(self, role: str, table_key: str, privilege: str):
        if not self.allowed(role, table_key, privilege):
            raise errors.SqlError(
                errors.INSUFFICIENT_PRIVILEGE,
                f"permission denied for table {table_key.split('.')[-1]}")

    # -- persistence -------------------------------------------------------

    def to_meta(self) -> dict:
        with self._lock:
            return {
                "roles": {k: dict(v) for k, v in self.roles.items()},
                "acls": {t: {r: sorted(p) for r, p in acl.items()}
                         for t, acl in self.acls.items()},
                "memberships": {m: sorted(g)
                                for m, g in self.memberships.items() if g},
            }

    def load_meta(self, meta: dict):
        with self._lock:
            if meta.get("roles"):
                self.roles = {k: dict(v) for k, v in meta["roles"].items()}
                self.roles.setdefault(
                    SUPERUSER,
                    {"password": None, "login": True, "superuser": True})
            self.acls = {t: {r: set(p) for r, p in acl.items()}
                         for t, acl in meta.get("acls", {}).items()}
            self.memberships = {m: set(g) for m, g in
                                meta.get("memberships", {}).items()}
