"""SCRAM-SHA-256 (RFC 5802/7677) for the PG wire protocol.

Reference analog: the reference's PG auth accepts cleartext and SCRAM
(server/pg/auth*, SURVEY.md §2.2 "PG wire session"); PG itself defaults to
scram-sha-256. Verifiers are stored, never the password: the role meta
holds (salt, iterations, StoredKey, ServerKey) exactly like pg_authid's
rolpassword SCRAM verifier.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import secrets
import unicodedata

ITERATIONS = 4096
MECHANISM = "SCRAM-SHA-256"


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


# RFC 3454 table B.1 (commonly-mapped-to-nothing), as in pg_saslprep
_MAP_TO_NOTHING = {
    0x00AD, 0x034F, 0x1806, 0x180B, 0x180C, 0x180D, 0x200B, 0x200C,
    0x200D, 0x2060, 0xFEFF, *range(0xFE00, 0xFE10),
}


def saslprep(password: str) -> str:
    """RFC 4013 stringprep for SCRAM passwords, matching pg_saslprep:
    non-ASCII spaces -> space, strip mapped-to-nothing chars, NFKC.
    Like PG, falls back to the raw string when the result would be
    prohibited (control chars) or empty."""
    if all(ord(c) < 0x80 for c in password):
        return password
    out = []
    for c in password:
        if ord(c) in _MAP_TO_NOTHING:
            continue
        out.append(" " if unicodedata.category(c) == "Zs" else c)
    normalized = unicodedata.normalize("NFKC", "".join(out))
    if not normalized or any(
            unicodedata.category(c) in ("Cc", "Cf") or
            0xFDD0 <= ord(c) <= 0xFDEF or (ord(c) & 0xFFFE) == 0xFFFE
            for c in normalized):
        return password
    return normalized


def build_verifier(password: str, salt: bytes = None,
                   iterations: int = ITERATIONS) -> dict:
    """PG-style SCRAM verifier parts, base64-encoded for meta storage."""
    salt = salt or secrets.token_bytes(16)
    salted = hashlib.pbkdf2_hmac("sha256", saslprep(password).encode(),
                                 salt, iterations)
    client_key = _hmac(salted, b"Client Key")
    return {
        "salt": base64.b64encode(salt).decode(),
        "iterations": iterations,
        "stored_key": base64.b64encode(_h(client_key)).decode(),
        "server_key": base64.b64encode(
            _hmac(salted, b"Server Key")).decode(),
    }


class ScramServer:
    """One authentication exchange. Usage:
    first() -> server-first-message; final() -> (ok, server-final)."""

    def __init__(self, verifier: dict):
        self.verifier = verifier
        self.client_first_bare = None
        self.server_first = None
        self.nonce = None

    def first(self, client_first: str) -> str:
        # gs2 header: 'n' (no channel binding) or 'y' (client supports none
        # advertised); 'p=' would demand TLS channel binding we don't have
        if client_first[:2] not in ("n,", "y,"):
            raise ValueError("unsupported gs2 channel-binding flag")
        rest = client_first.split(",", 2)[2]
        self.client_first_bare = rest
        attrs = dict(a.split("=", 1) for a in rest.split(",") if "=" in a)
        cnonce = attrs.get("r", "")
        if not cnonce:
            raise ValueError("missing client nonce")
        self.nonce = cnonce + base64.b64encode(
            secrets.token_bytes(18)).decode()
        self.server_first = (
            f"r={self.nonce},s={self.verifier['salt']},"
            f"i={self.verifier['iterations']}")
        return self.server_first

    def final(self, client_final: str) -> tuple[bool, str]:
        attrs = dict(a.split("=", 1) for a in client_final.split(",")
                     if "=" in a)
        if attrs.get("r") != self.nonce:
            return False, ""
        proof_b64 = attrs.get("p", "")
        without_proof = client_final.rsplit(",p=", 1)[0]
        auth_message = (f"{self.client_first_bare},{self.server_first},"
                        f"{without_proof}").encode()
        stored_key = base64.b64decode(self.verifier["stored_key"])
        client_signature = _hmac(stored_key, auth_message)
        try:
            proof = base64.b64decode(proof_b64)
        except Exception:
            return False, ""
        if len(proof) != len(client_signature):
            return False, ""
        client_key = bytes(a ^ b for a, b in zip(proof, client_signature))
        if not hmac.compare_digest(_h(client_key), stored_key):
            return False, ""
        server_key = base64.b64decode(self.verifier["server_key"])
        server_sig = base64.b64encode(
            _hmac(server_key, auth_message)).decode()
        return True, f"v={server_sig}"


def client_exchange(password: str, username: str = ""):
    """Minimal SCRAM client (for tests/tools): returns (client_first,
    continue_fn(server_first) -> client_final, verify_fn(server_final) ->
    bool)."""
    cnonce = base64.b64encode(secrets.token_bytes(18)).decode()
    bare = f"n=,r={cnonce}"
    state = {}

    def cont(server_first: str) -> str:
        attrs = dict(a.split("=", 1) for a in server_first.split(",")
                     if "=" in a)
        salt = base64.b64decode(attrs["s"])
        iters = int(attrs["i"])
        nonce = attrs["r"]
        if not nonce.startswith(cnonce):
            raise ValueError("server nonce does not extend client nonce")
        salted = hashlib.pbkdf2_hmac("sha256",
                                     saslprep(password).encode(), salt,
                                     iters)
        client_key = _hmac(salted, b"Client Key")
        without_proof = f"c=biws,r={nonce}"
        auth_message = f"{bare},{server_first},{without_proof}".encode()
        sig = _hmac(_h(client_key), auth_message)
        proof = bytes(a ^ b for a, b in zip(client_key, sig))
        state["server_sig"] = base64.b64encode(
            _hmac(_hmac(salted, b"Server Key"), auth_message)).decode()
        return f"{without_proof},p={base64.b64encode(proof).decode()}"

    def verify(server_final: str) -> bool:
        return server_final == f"v={state['server_sig']}"

    return f"n,,{bare}", cont, verify


def verify_cleartext(verifier: dict, password: str) -> bool:
    """Check a cleartext password against a stored SCRAM verifier by
    re-deriving the stored key with the verifier's salt/iterations
    (constant-time compare). Powers HBA method=password for roles whose
    password exists only as a SCRAM verifier."""
    import hmac as hmac_mod
    try:
        salt = base64.b64decode(verifier["salt"])
        iterations = int(verifier["iterations"])
        salted = hashlib.pbkdf2_hmac("sha256", saslprep(password).encode(),
                                     salt, iterations)
        stored = base64.b64decode(verifier["stored_key"])
        return hmac_mod.compare_digest(_h(_hmac(salted, b"Client Key")),
                                       stored)
    except (KeyError, ValueError, TypeError):
        return False
