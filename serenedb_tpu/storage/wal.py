"""Per-database write-ahead log with tick-banded commits and delta replay.

Reference analog: SearchDbWal — ONE WAL per database shared by all tables so
cross-table commits are atomic; tick-banded records; zstd-compressed inline
chunks; 16 MB segment seal; GC by min committed tick; delta replay on boot
(reference: server/search/search_db_wal.h:50-205, .cpp, SURVEY.md §3.4/§3.5).

Record frame: [u32 len][u32 crc32(tick||payload)][u64 tick][payload];
payload is a zstd-1 compressed JSON header + arrow-IPC chunk blobs:

    {ops: [{table, kind: insert|delete|truncate, ...}]}

The tick lives OUTSIDE the compressed payload so the expensive encoding
(arrow IPC + zstd — the reference's per-thread ChunkWriter work,
duckdb_physical_search_insert.cpp:107-369) happens before the tick is
assigned: concurrent committers encode in parallel, enqueue (tick order ==
queue order), and a group-commit leader writes every pending frame with
ONE fsync.

Commit protocol (mirrors SearchTableTransaction::Commit,
search_table_transaction.cpp:117-211):
    1. fault point  crash_before_search_wal_commit
    2. append record, flush, fsync          ← durability point
    3. fault point  crash_after_search_wal_commit
    4. apply to in-memory tables (memory-only publish)
Recovery replays records with tick > the table's checkpointed tick.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

try:
    import zstandard
except ImportError:          # optional codec: zlib fallback below
    zstandard = None

from .. import errors
from ..columnar.arrow_io import batch_to_bytes, bytes_to_batch
from ..columnar.column import Batch
from ..utils import faults, log, metrics

SEGMENT_SEAL_BYTES = 16 << 20   # reference: 16 MB segment seal
_HDR = struct.Struct("<II")
# Segment header: magic + format version. Frames follow the 8-byte header.
# A version bump makes old segments fail loudly ("incompatible WAL version")
# instead of decoding as torn/corrupt frames.
# v3: delete_pk op kind (PK-based remove filters) — a v2 reader would
# silently skip the delete and resurrect rows, exactly what versioning
# is for.
SEGMENT_MAGIC = b"SDBWAL\x00\x03"


def _group_commit() -> bool:
    """serene_group_commit global: widens the leader's write window with a
    bounded queue re-drain before the fsync (off = one drain per fsync,
    the parity oracle for recovery tests)."""
    from ..utils.config import REGISTRY
    try:
        return bool(REGISTRY.get_global("serene_group_commit"))
    except KeyError:
        return True


@dataclass
class WalOp:
    table: str
    kind: str                       # insert | delete | delete_pk | truncate
    batch: Optional[Batch] = None   # insert payload
    #: delete: positional row indices (int64 array);
    #: delete_pk: {"cols": [pk column names], "keys": [key bytes]} —
    #: an order-preserving PK remove filter (reference:
    #: server/connector/key_encoding.cpp + search_remove_filter.*)
    rows: Optional[object] = None


@dataclass
class CommitRecord:
    tick: int
    ops: list[WalOp]


@dataclass
class _Pending:
    """One queued group-commit entry."""
    done: threading.Event
    tick: int = 0
    payload: bytes = b""
    error: Optional[BaseException] = None


def _encode_ops(ops: list[WalOp]) -> bytes:
    """Encode a commit's ops (tick-independent — the expensive leg, done
    OUTSIDE any commit lock)."""
    header = {"ops": []}
    blobs: list[bytes] = []
    for op in ops:
        entry = {"table": op.table, "kind": op.kind}
        if op.batch is not None:
            blob = batch_to_bytes(op.batch)
            entry["blob"] = len(blobs)
            blobs.append(blob)
        if op.kind == "delete_pk":
            import base64
            entry["pk_cols"] = list(op.rows["cols"])
            entry["keys"] = [base64.b64encode(k).decode()
                             for k in op.rows["keys"]]
        elif op.rows is not None:
            entry["rows"] = np.asarray(op.rows, dtype=np.int64).tolist()
        header["ops"].append(entry)
    hj = json.dumps(header).encode()
    parts = [struct.pack("<I", len(hj)), hj,
             struct.pack("<I", len(blobs))]
    for b in blobs:
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
    raw = b"".join(parts)
    return _compress(raw)


#: zstd frame magic — payloads self-describe their codec (zstd frames
#: start with this magic, zlib streams with 0x78), so a zlib-written
#: datadir always reads back under either install; zstd-written frames
#: fail loudly (58030) on a zlib-only install instead of decoding as
#: garbage
_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"


def _compress(raw: bytes) -> bytes:
    """zstd-1 when the optional module is present, zlib-1 otherwise.
    Both stamp a self-identifying header (zstd's frame magic vs zlib's
    0x78), so decode never needs out-of-band codec metadata."""
    if zstandard is not None:
        return zstandard.ZstdCompressor(level=1).compress(raw)
    return zlib.compress(raw, 1)


def _decompress(payload: bytes) -> bytes:
    if payload[:4] == _ZSTD_MAGIC:
        if zstandard is None:
            raise errors.SqlError(
                "58030", "WAL payload is zstd-compressed but the "
                "zstandard module is not installed")
        return zstandard.ZstdDecompressor().decompress(payload)
    return zlib.decompress(payload)


def _decode_record(tick: int, payload: bytes) -> CommitRecord:
    raw = _decompress(payload)
    off = 0
    (hlen,) = struct.unpack_from("<I", raw, off)
    off += 4
    header = json.loads(raw[off:off + hlen].decode())
    off += hlen
    (nblobs,) = struct.unpack_from("<I", raw, off)
    off += 4
    blobs = []
    for _ in range(nblobs):
        (blen,) = struct.unpack_from("<I", raw, off)
        off += 4
        blobs.append(raw[off:off + blen])
        off += blen
    ops = []
    for entry in header["ops"]:
        batch = bytes_to_batch(blobs[entry["blob"]]) \
            if "blob" in entry else None
        if entry["kind"] == "delete_pk":
            import base64
            rows = {"cols": entry["pk_cols"],
                    "keys": [base64.b64decode(k) for k in entry["keys"]]}
        else:
            rows = np.asarray(entry["rows"], dtype=np.int64) \
                if "rows" in entry else None
        ops.append(WalOp(entry["table"], entry["kind"], batch, rows))
    return CommitRecord(tick, ops)


class SearchDbWal:
    """Append-only segmented WAL for one database directory."""

    def __init__(self, wal_dir: str):
        self.dir = wal_dir
        os.makedirs(wal_dir, exist_ok=True)
        self._lock = threading.Lock()
        # group-commit queue: (tick, payload, Event) triples appended under
        # _pending_lock (tick order == queue order); a leader holding _lock
        # drains and writes all of them with one fsync
        self._pending_lock = threading.Lock()
        self._pending: list = []
        self._fh = None
        self._gen = 0
        self._bytes = 0
        self._poisoned: Optional[str] = None
        # per-segment max tick, maintained on append so GC doesn't re-read
        # sealed segments; lazily scanned for segments found at boot
        self._seg_max_tick: dict[int, int] = {}
        gens = self._generations()
        self._gen = (gens[-1] if gens else 0)

    # -- segment files -----------------------------------------------------

    def _seg_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"{gen:012d}.wal")

    def _generations(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.endswith(".wal"):
                try:
                    out.append(int(name[:-4]))
                except ValueError:
                    continue
        return sorted(out)

    def _open_for_append(self):
        if self._fh is None:
            path = self._seg_path(self._gen)
            self._fh = open(path, "ab")
            self._bytes = self._fh.tell()
            if self._bytes == 0:
                try:
                    self._fh.write(SEGMENT_MAGIC)
                    self._fh.flush()
                except BaseException:
                    # a partial header must not stay ahead of later frames
                    # (the segment would be unrecoverable); reset so the
                    # next open retries a fresh header, poison if we can't
                    try:
                        self._fh.truncate(0)
                        self._fh.close()
                    except BaseException as exc2:
                        self._poisoned = repr(exc2)
                    self._fh = None
                    raise
                self._bytes = len(SEGMENT_MAGIC)

    def _seal_if_needed(self):
        if self._bytes >= SEGMENT_SEAL_BYTES:
            self._fh.close()
            self._fh = None
            self._gen += 1
            self._open_for_append()

    # -- commit ------------------------------------------------------------

    def commit_ops(self, ops: list[WalOp], ticks, on_tick=None) -> int:
        """Durably commit ops; returns the assigned tick. Encoding happens
        before the tick is assigned (parallel across committers); the tick
        is taken under the queue lock so queue order == tick order; a
        group-commit leader writes every queued frame with one fsync
        (reference: parallel sink ChunkWriters combined at Finalize,
        duckdb_physical_search_insert.h:46-61)."""
        faults.if_failure("search_wal_append_error")
        faults.crash_if_armed("crash_before_search_wal_commit")
        payload = _encode_ops(ops)
        entry = _Pending(threading.Event())
        with self._pending_lock:
            tick = ticks.next()
            entry.tick = tick
            entry.payload = payload
            self._pending.append(entry)
            if on_tick is not None:
                # runs under the queue lock: callers that sequence their
                # in-memory publishes by tick see every EARLIER-enqueued
                # commit's tick already recorded (enqueue order == tick
                # order, and both happen atomically here)
                on_tick(tick)
        while not entry.done.is_set():
            with self._lock:
                if entry.done.is_set():
                    break
                with self._pending_lock:
                    batch, self._pending = self._pending, []
                if not batch:
                    continue
                start_bytes = None
                try:
                    if self._poisoned is not None:
                        raise errors.SqlError(
                            "58030", "WAL poisoned by earlier write "
                            f"failure: {self._poisoned}")
                    self._open_for_append()
                    start_bytes = self._bytes
                    max_tick = 0
                    for e in batch:
                        tb = struct.pack("<Q", e.tick)
                        frame = _HDR.pack(
                            len(e.payload),
                            zlib.crc32(tb + e.payload)) + tb + e.payload
                        self._fh.write(frame)
                        self._bytes += len(frame)
                        max_tick = max(max_tick, e.tick)
                    # group-commit window: re-drain the queue for commits
                    # that enqueued while this leader was writing, so they
                    # ride THIS fsync instead of forcing their own. Bounded
                    # passes keep leader latency predictable; the rollback
                    # below covers every frame written since start_bytes,
                    # drained entries are failed with the batch on error.
                    if _group_commit():
                        for _ in range(4):
                            with self._pending_lock:
                                extra, self._pending = self._pending, []
                            if not extra:
                                break
                            for e in extra:
                                tb = struct.pack("<Q", e.tick)
                                frame = _HDR.pack(
                                    len(e.payload),
                                    zlib.crc32(tb + e.payload)) \
                                    + tb + e.payload
                                self._fh.write(frame)
                                self._bytes += len(frame)
                                max_tick = max(max_tick, e.tick)
                                batch.append(e)
                    t0 = time.perf_counter_ns()
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    metrics.WAL_FSYNCS.add()
                    metrics.WAL_FSYNC_HIST.observe_ns(
                        time.perf_counter_ns() - t0)
                    self._seg_max_tick[self._gen] = max(
                        self._seg_max_tick.get(self._gen, 0), max_tick)
                except BaseException as exc:
                    # Partially-written frames of the FAILED batch must not
                    # become durable behind a later commit's fsync — callers
                    # were told the commit failed and never published it, so
                    # recovery would replay ghosts. Roll the segment back to
                    # its pre-batch offset; if even that fails, poison the
                    # WAL so nothing can append after the garbage.
                    try:
                        if self._fh is not None and start_bytes is not None:
                            self._fh.truncate(start_bytes)
                            self._fh.seek(start_bytes)
                            # make the truncation itself durable: without
                            # this the failed frames may still hit disk via
                            # background writeback and replay as ghosts
                            os.fsync(self._fh.fileno())
                            self._bytes = start_bytes
                    except BaseException:
                        self._poisoned = repr(exc)
                    # the leader must fail EVERY drained follower — their
                    # frames were lost with this write and they would
                    # otherwise spin forever on an empty queue
                    for e in batch:
                        e.error = exc
                        e.done.set()
                    raise
                for e in batch:
                    e.done.set()
                # Seal OUTSIDE the rollback-protected region: the batch IS
                # durable, and rolling back to the old segment's pre-batch
                # offset after _fh swapped to the next generation would
                # zero-extend the fresh segment. A seal failure can leave
                # the open segment header-less, so poison instead of
                # letting later appends land in an unrecoverable file.
                try:
                    self._seal_if_needed()
                except BaseException as exc:
                    self._poisoned = repr(exc)
                    raise
        if entry.error is not None:
            raise entry.error
        metrics.WAL_COMMITS.add()
        faults.crash_if_armed("crash_after_search_wal_commit")
        return tick

    def append_commit(self, rec: CommitRecord) -> None:
        """Single-record append at a caller-chosen tick (tests/tools; the
        engine path is commit_ops)."""
        class _Fixed:
            def __init__(self, t):
                self.t = t

            def next(self):
                return self.t
        self.commit_ops(rec.ops, _Fixed(rec.tick))

    # -- recovery ----------------------------------------------------------

    def recover(self, committed_of: Callable[[str], int],
                apply_op: Callable[[int, WalOp], None]) -> int:
        """Delta replay: for every record, ops whose table's committed tick
        is below the record tick are re-applied (reference:
        SearchDbWal::Recover, search_db_wal.h:175-179). A torn/corrupt frame
        in the LAST segment is the uncommitted tail: it is truncated away so
        later appends never land behind garbage (which would make them
        unreachable on the next recovery). Corruption in an earlier, sealed
        segment aborts replay loudly; a segment written by a different WAL
        format version is an explicit 58030 "incompatible WAL version", not
        corruption semantics. Returns the highest tick seen."""
        max_tick = 0
        gens = self._generations()
        for gi, gen in enumerate(gens):
            path = self._seg_path(gen)
            with open(path, "rb") as f:
                data = f.read()
            if len(data) == 0:
                self._seg_max_tick[gen] = 0
                continue
            if data[:len(SEGMENT_MAGIC)] != SEGMENT_MAGIC:
                if SEGMENT_MAGIC.startswith(data):
                    # torn header write (strict prefix of the magic)
                    if gi == len(gens) - 1:
                        # last segment: the uncommitted tail — truncate
                        with open(path, "r+b") as f:
                            f.truncate(0)
                        self._seg_max_tick[gen] = 0
                        continue
                    # a sealed segment can never legitimately hold a bare
                    # prefix of the magic: that is corruption, not a
                    # format mismatch
                    raise errors.SqlError(
                        "58030",
                        f"WAL corruption in sealed segment {path}: torn "
                        "header")
                raise errors.SqlError(
                    "58030",
                    f"incompatible WAL version in {path}: expected format "
                    f"{SEGMENT_MAGIC[-1]} (header {SEGMENT_MAGIC!r})")
            off = len(SEGMENT_MAGIC)
            seg_max = 0
            while off + _HDR.size + 8 <= len(data):
                ln, crc = _HDR.unpack_from(data, off)
                start = off + _HDR.size + 8      # u64 tick after the crc
                end = start + ln
                torn = end > len(data)
                if not torn:
                    tick_bytes = data[off + _HDR.size:start]
                    payload = data[start:end]
                    torn = zlib.crc32(tick_bytes + payload) != crc
                if torn:
                    if gi != len(gens) - 1:
                        raise errors.SqlError(
                            "58030",
                            f"WAL corruption in sealed segment {path}")
                    log.warn("wal", f"torn tail in {path}: truncating at "
                                    f"{off}")
                    with open(path, "r+b") as f:
                        f.truncate(off)
                    self._seg_max_tick[gen] = seg_max
                    return max_tick
                rec = _decode_record(
                    struct.unpack("<Q", tick_bytes)[0], payload)
                max_tick = max(max_tick, rec.tick)
                seg_max = max(seg_max, rec.tick)
                for op in rec.ops:
                    if committed_of(op.table) < rec.tick:
                        apply_op(rec.tick, op)
                off = end
            # trailing partial header bytes (fewer than a frame header)
            if off < len(data):
                if gi != len(gens) - 1:
                    raise errors.SqlError(
                        "58030", f"WAL corruption in sealed segment {path}")
                log.warn("wal", f"partial tail header in {path}: truncating")
                with open(path, "r+b") as f:
                    f.truncate(off)
            self._seg_max_tick[gen] = seg_max
        return max_tick

    # -- GC ----------------------------------------------------------------

    def gc(self, min_committed_tick: int) -> int:
        """Drop sealed segments whose every record tick ≤ min committed tick
        across tables. Returns number of segments removed. Uses the
        in-memory per-segment max-tick map (maintained on append / replay)
        instead of re-reading segment contents."""
        removed = 0
        with self._lock:
            gens = self._generations()
            for gen in gens[:-1] if self._fh else gens:  # never the open one
                max_tick = self._seg_max_tick.get(gen)
                if max_tick is not None and max_tick <= min_committed_tick:
                    os.remove(self._seg_path(gen))
                    self._seg_max_tick.pop(gen, None)
                    removed += 1
                else:
                    break
        return removed

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
