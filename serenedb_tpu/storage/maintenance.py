"""Background maintenance: refresh + checkpoint loops.

Reference analog: per-target RefreshLoop + CompactionCoordinator coroutines
on the background pool, with a global compaction-slot semaphore
max(1, min(4, cores/2)) and idle backoff ×1.5 up to 5× (reference:
server/storage_engine/search_engine.h:46-123, server/search/task.cpp:85-380).

Here: a refresh thread rebuilds stale search indexes (publish = atomic dict
swap), and a checkpoint thread snapshots dirty stored tables so WAL segments
can be garbage-collected. Heavy rebuilds take a global slot, mirroring the
compaction cap. `run_once()` gives tests a deterministic handle."""

from __future__ import annotations

import os
import threading
import time

from ..utils import log, metrics

MAX_SLOTS = max(1, min(4, (os.cpu_count() or 2) // 2))


class MaintenanceManager:
    def __init__(self, db, refresh_interval: float = 0.25,
                 checkpoint_interval: float = 30.0,
                 checkpoint_wal_bytes: int = 8 << 20):
        self.db = db
        self.refresh_interval = refresh_interval
        self.checkpoint_interval = checkpoint_interval
        self.checkpoint_wal_bytes = checkpoint_wal_bytes
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._slots = threading.Semaphore(MAX_SLOTS)
        self._checkpointed_version: dict[str, int] = {}
        self._last_checkpoint = time.monotonic()
        #: set by the write path after an append publishes: the ticker
        #: wakes immediately (instead of riding out its idle backoff) to
        #: build the enqueued delta segments off the query path
        self._wake = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        t = threading.Thread(target=self._loop, name="serene-maintenance",
                             daemon=True)
        self._threads.append(t)
        t.start()

    def stop(self):
        """Join loops before teardown (the reference's stop protocol joins
        search loops before the pool dies, serened.cpp:86-130)."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)

    # -- loops -------------------------------------------------------------

    def notify_append(self):
        """Wake the ticker: an append just published, so a delta range is
        waiting to become a segment (one lock-free Event.set — cheap
        enough for the per-statement write path)."""
        self._wake.set()

    def _loop(self):
        idle = self.refresh_interval
        while not self._stop.is_set():
            self._wake.clear()
            did_work = False
            try:
                did_work = self.run_once()
            except Exception as e:  # maintenance must never die
                log.error("maintenance", f"loop error: {e!r}")
            if did_work:
                idle = self.refresh_interval
            else:
                # idle stretch ×1.5 capped at 5× (reference task.cpp:85-95)
                idle = min(idle * 1.5, self.refresh_interval * 5)
            if self._stop.is_set():
                break
            # appends cut the idle wait short so delta segments build
            # promptly in the background, narrowing the tail queries pay
            self._wake.wait(idle)

    def run_once(self) -> bool:
        """One maintenance pass; returns True if any work was done."""
        if getattr(self.db, "_crashed", False):
            return False  # abandoned db must not checkpoint post-"kill"
        # process-level gauges (RSS/uptime/GC) ride the existing ticker
        # so sdb_metrics stays fresh between scrapes; the /metrics and
        # /_stats renderers also sample at scrape time
        from ..obs.resources import sample_process_gauges
        sample_process_gauges()
        did = self._refresh_pass()
        did = self._checkpoint_pass() or did
        did = self._drop_gc_pass() or did
        return did

    def _drop_gc_pass(self) -> bool:
        """Reclaim tombstoned snapshots of dropped tables (the async-drop
        background half; reference: server/catalog/drop_task.cpp)."""
        store = self.db.store
        if store is None:
            return False
        n = store.gc_tombstones()
        if n:
            log.info("maintenance", f"reclaimed {n} dropped snapshot(s)")
        return bool(n)

    def _refresh_pass(self) -> bool:
        from ..engine import _refresh_indexes
        from ..search.index import needs_merge
        did = False
        with self.db.lock:
            tables = [t for s in self.db.schemas.values()
                      for t in s.tables.values()]
        for t in tables:
            idxs = getattr(t, "indexes", {})
            if any(ix.data_version != t.data_version or needs_merge(ix)
                   for ix in idxs.values()):
                with self._slots:
                    with metrics.REFRESH_ACTIVE.scoped():
                        _refresh_indexes(self.db, t)
                did = True
        return did

    def _checkpoint_pass(self) -> bool:
        store = self.db.store
        if store is None:
            return False
        due = (time.monotonic() - self._last_checkpoint
               >= self.checkpoint_interval) or \
            self._wal_bytes() >= self.checkpoint_wal_bytes
        if not due:
            return False
        from ..engine import StoredTable
        did = False
        with self.db.lock:
            tables = [t for s in self.db.schemas.values()
                      for t in s.tables.values()
                      if isinstance(t, StoredTable)]
        for t in tables:
            if self._checkpointed_version.get(t.key) == t.data_version:
                continue
            # batch + tick captured atomically vs DML of THIS table:
            # committed-but-unpublished fast-path inserts would be
            # missing from the batch yet covered by the tick
            with self.db.quiesced([t]):
                batch = t.full_batch()
                version = t.data_version
                tick = store.ticks.current()
            with metrics.COMPACTION_ACTIVE.scoped():
                store.checkpoint_table(t.key, t.table_id, batch, tick)
            self._checkpointed_version[t.key] = version
            did = True
        self._last_checkpoint = time.monotonic()
        return did

    def _wal_bytes(self) -> int:
        store = self.db.store
        total = 0
        try:
            for name in os.listdir(store.wal.dir):
                if name.endswith(".wal"):
                    total += os.path.getsize(os.path.join(store.wal.dir, name))
        except OSError:
            pass
        return total
