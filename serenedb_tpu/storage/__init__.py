"""Durability layer: WAL, store (snapshots + catalog persistence), and
background maintenance loops."""

from . import maintenance, store, wal

__all__ = ["maintenance", "store", "wal"]
