"""Durable store: data directory, catalog persistence, table snapshots.

Reference analog: server/catalog/store/ (definitions persisted transactional
via the __sdb_store DuckDB file; SURVEY.md §2.4) + the checkpoint/WAL split
of §5.4: two durability domains — (1) catalog + table *snapshots*
(parquet files + an atomically-replaced catalog.json), and (2) the
per-database WAL (storage/wal.py) holding everything since each table's
checkpoint tick. Recovery = snapshots + delta replay.

Layout:
    <datadir>/catalog.json        definitions + per-table checkpoint ticks
    <datadir>/tables/<id>.parquet table snapshots (written at checkpoint)
    <datadir>/wal/*.wal           commit records since the checkpoints
    <datadir>/LOCK                single-process lockfile
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.arrow_io import (read_parquet_snapshot,
                                 write_parquet_snapshot)
from ..columnar.column import Batch, Column
from ..utils import faults, log
from ..utils.ticks import TickServer
from .wal import CommitRecord, SearchDbWal, WalOp


class Store:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        os.makedirs(os.path.join(path, "tables"), exist_ok=True)
        self._lockfile = os.path.join(path, "LOCK")
        self._acquire_lock()
        self.catalog_path = os.path.join(path, "catalog.json")
        self.wal = SearchDbWal(os.path.join(path, "wal"))
        self.ticks = TickServer()
        # RLock: meta mutations + save_meta happen from connection threads
        # AND the maintenance thread; all must serialize on this lock
        self._lock = threading.RLock()
        self.meta: dict = {"next_table_id": 1, "schemas": ["main"],
                           "tables": {}, "views": {}, "indexes": {}}
        # a crash between DROP's tombstone rename and the maintenance GC
        # leaves .dropped files — reclaim them on boot
        self.gc_tombstones()

    def _acquire_lock(self):
        # datadir lockfile (reference: libs/basics lockfile)
        if os.path.exists(self._lockfile):
            try:
                pid = int(open(self._lockfile).read().strip() or 0)
            except ValueError:
                pid = 0
            if pid and _pid_alive(pid):
                raise errors.SqlError(
                    "55000", f"data directory {self.path} is locked by "
                             f"running process {pid}")
        with open(self._lockfile, "w") as f:
            f.write(str(os.getpid()))

    def release(self):
        self.wal.close()
        try:
            os.remove(self._lockfile)
        except OSError:
            pass

    # -- catalog persistence ------------------------------------------------

    def load_meta(self) -> dict:
        if os.path.exists(self.catalog_path):
            with open(self.catalog_path) as f:
                self.meta = json.load(f)
        return self.meta

    def save_meta(self) -> None:
        """Atomic catalog write: tmp + fsync + rename (the definitions
        equivalent of the reference's transactional WriteContext batches)."""
        faults.if_failure("catalog_write_error")
        faults.crash_if_armed("crash_before_catalog_write")
        with self._lock:
            tmp = self.catalog_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.meta, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.catalog_path)
        faults.crash_if_armed("crash_after_catalog_write")

    def update_meta(self, mutator) -> None:
        """Serialize a meta mutation + save against concurrent writers
        (connection DDL vs. the maintenance checkpoint thread)."""
        with self._lock:
            mutator(self.meta)
            self.save_meta()

    def new_table_id(self) -> int:
        with self._lock:
            tid = self.meta["next_table_id"]
            self.meta["next_table_id"] = tid + 1
            return tid

    # -- snapshots -----------------------------------------------------------

    def snapshot_path(self, table_id: int) -> str:
        return os.path.join(self.path, "tables", f"{table_id}.parquet")

    def write_snapshot(self, table_id: int, batch: Batch) -> None:
        path = self.snapshot_path(table_id)
        tmp = path + ".tmp"
        write_parquet_snapshot(tmp, batch)
        os.replace(tmp, path)

    def read_snapshot(self, table_id: int,
                      names: list[str],
                      types: list[dt.SqlType]) -> Batch:
        path = self.snapshot_path(table_id)
        if not os.path.exists(path):
            from ..exec.plan import empty_batch
            return empty_batch(names, types)
        batch = read_parquet_snapshot(path)
        # re-stamp logical types the physical snapshot can't carry
        # (ARRAY/RECORD as JSON text, INTERVAL as int64 micros, reg* as
        # int64 oids): the catalog's declared type wins over inference
        _RESTAMP = (dt.TypeId.ARRAY, dt.TypeId.RECORD, dt.TypeId.INTERVAL,
                    dt.TypeId.OID, dt.TypeId.REGCLASS, dt.TypeId.REGTYPE,
                    dt.TypeId.REGPROC, dt.TypeId.REGNAMESPACE)
        for name, t in zip(names, types):
            if t.id in _RESTAMP and name in batch and \
                    batch.column(name).type != t:
                batch.column(name).type = t
        return batch

    # -- async drops (reference: server/catalog/drop_task.cpp — the DROP
    # statement only tombstones data files; a background task reclaims
    # them, so large drops never stall the DDL path) -----------------------

    def tombstone_snapshot(self, table_id: int) -> None:
        """Rename the snapshot to a .dropped tombstone (atomic, O(1));
        gc_tombstones() reclaims it from the maintenance loop."""
        path = self.snapshot_path(table_id)
        try:
            os.replace(path, f"{path}.dropped")
        except OSError:
            pass   # no snapshot yet (never checkpointed) — nothing to do

    def gc_tombstones(self) -> int:
        """Delete tombstoned snapshots; returns the number reclaimed.
        Also called at startup, so tombstones from a crash between DROP
        and GC are reclaimed on the next boot."""
        tables_dir = os.path.join(self.path, "tables")
        n = 0
        try:
            entries = os.listdir(tables_dir)
        except OSError:
            return 0
        for name in entries:
            if name.endswith(".dropped"):
                try:
                    os.remove(os.path.join(tables_dir, name))
                    n += 1
                except OSError:
                    pass
        return n

    # -- commit / checkpoint --------------------------------------------------

    def commit(self, ops: list[WalOp], on_tick=None) -> int:
        """Durably log one commit; returns its tick. The caller applies the
        ops to memory AFTER this returns (WAL-then-publish, §3.4). Tick
        assignment happens inside the WAL's group-commit queue so WAL file
        order always matches tick order."""
        return self.wal.commit_ops(ops, self.ticks, on_tick=on_tick)

    def checkpoint_table(self, key: str, table_id: int, batch: Batch,
                         tick: int) -> None:
        """Snapshot a table and advance its checkpoint cursor to `tick`.
        The caller must capture (batch, tick) atomically under the database
        DML lock — a tick read after the batch would let a concurrent commit
        land in the gap and be skipped on recovery. Sealed WAL segments
        below the min cursor become garbage."""
        self.write_snapshot(table_id, batch)
        with self._lock:
            entry = self.meta["tables"].get(key)
            if entry is not None:
                entry["checkpoint_tick"] = tick
            self.save_meta()
        self.gc()

    def gc(self) -> int:
        with self._lock:
            ticks = [t.get("checkpoint_tick", 0)
                     for t in self.meta["tables"].values()]
        if not ticks:
            return 0
        return self.wal.gc(min(ticks))


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def serialize_type(t: dt.SqlType) -> str:
    # "ELEM[]" for arrays so the element type round-trips through boot
    return str(t)


def table_def(name_key: str, table_id: int, names: list[str],
              types: list[dt.SqlType], meta: dict, start_tick: int) -> dict:
    """start_tick must be the store's current tick at creation: a freshly
    created table must never replay WAL records of an earlier same-named
    (dropped) table."""
    import base64
    import pickle
    return {
        "id": table_id,
        "columns": [{"name": n, "type": serialize_type(t)}
                    for n, t in zip(names, types)],
        "engine": meta.get("engine", "columnar"),
        "options": meta.get("options", {}),
        "primary_key": meta.get("primary_key", []),
        "not_null": meta.get("not_null", []),
        # DEFAULT expressions persist as pickled ASTs (same encoding as
        # view definitions)
        "defaults": {n: base64.b64encode(pickle.dumps(e)).decode()
                     for n, e in (meta.get("defaults") or {}).items()},
        "tokenizers": meta.get("tokenizers", {}),
        "enums": meta.get("enums", {}),
        "checkpoint_tick": start_tick,
    }
