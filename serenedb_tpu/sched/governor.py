"""Workload governor: admission control for concurrent statements.

Reference analog: the reference engine survives production traffic
because a scheduler arbitrates many concurrent statements over shared
task queues and bounded memory (PAPER.md: DuckDB's task scheduler plus
three thread pools; SURVEY.md §3.2). This module is the statement-level
half of that story — the layer between statement dispatch (engine.py)
and the shared worker pool (parallel/pool.py):

- **Admission control** — at most `serene_max_concurrent_statements`
  statements EXECUTE at once; later arrivals wait in a bounded FIFO
  queue (`serene_admission_queue_depth`), visible as pg_stat_activity
  state ``queued`` with an ``Admission/AdmissionQueue`` wait event and
  a ``queue_wait``-category span in the statement's timeline trace.
  Queue overflow rejects immediately with SQLSTATE 53300 —
  backpressure, not an unbounded convoy. Waiting statements keep
  honoring cancel and statement timeouts (the wait loop polls
  `Connection.check_cancel`), so a queued statement can be cancelled
  exactly like a running one.

- **Statement identity for fair-share scheduling** — every statement
  gets a scheduling tag + weight (`serene_priority`) published on its
  connection (`Connection._sched`) and overridable through the
  `CURRENT_SCHED` contextvar; the worker pool keys its stride
  scheduler on it (parallel/pool.py).

The governor steers WHEN statements run, never what they return:
admission order and fair-share picking change scheduling only, and the
deterministic merge sinks guarantee bit-identical results at any
setting (tests/test_admission.py parity matrix). Memory budgets
(`serene_work_mem` → SQLSTATE 53200) and `serene_statement_timeout_ms`
are enforced cooperatively at the existing `check_cancel` sites in
engine.py — the governor only provides the queueing tier they pair
with.

Exemptions: utility statements (SET/SHOW/txn control — engine.py's
`_UNTRACED_STATEMENTS` gate) and catalog-only introspection reads
(`admission_exempt`) bypass admission, so the dashboards that diagnose
an overloaded server never queue behind the overload they are
diagnosing.
"""

from __future__ import annotations

import collections
import contextvars
import itertools
import threading
import time
from typing import Optional

from .. import errors
from ..utils import metrics

#: explicit (tag, weight) scheduling override for code that submits
#: pool tasks outside any statement (tests, maintenance); when unset,
#: the pool falls back to the submitting connection's `_sched` pair
CURRENT_SCHED: contextvars.ContextVar = contextvars.ContextVar(
    "sdb_current_sched", default=None)

_STMT_TAGS = itertools.count(1)

#: seconds between cancel/timeout polls while queued for admission — a
#: queued statement reacts to CancelRequest / statement_timeout within
#: one poll interval
_QUEUE_POLL_S = 0.02


def next_stmt_tag() -> int:
    """Process-unique scheduling tag for one statement's pool tasks."""
    return next(_STMT_TAGS)


class AdmissionTicket:
    """Proof of one admit() — released exactly once at statement end.
    `nested` tickets (a statement on a connection that already holds a
    slot, e.g. interleaved with its own suspended streaming portal)
    never count against the limit: a single session cannot deadlock
    itself at serene_max_concurrent_statements = 1."""

    __slots__ = ("conn", "nested", "released")

    def __init__(self, conn, nested: bool):
        self.conn = conn
        self.nested = nested
        self.released = False


class Governor:
    """Process-wide admission gate (one instance, like the worker pool).

    `_running` counts only statements that went through `admit()`; the
    engine skips the whole gate while `enabled()` is false, so arming
    `serene_max_concurrent_statements` mid-traffic applies to
    statements STARTED after arming (see `enabled()`) — the trade for
    a default path that costs one global read."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._running = 0
        self._queue: collections.deque = collections.deque()   # waiter ids

    # -- config ------------------------------------------------------------

    @staticmethod
    def _limits() -> tuple[int, int]:
        from ..utils.config import REGISTRY
        try:
            maxc = int(REGISTRY.get_global("serene_max_concurrent_statements"))
        except KeyError:                # pragma: no cover — always declared
            maxc = 0
        try:
            depth = int(REGISTRY.get_global("serene_admission_queue_depth"))
        except KeyError:                # pragma: no cover — always declared
            depth = 64
        return maxc, depth

    # -- admission ---------------------------------------------------------

    def enabled(self) -> bool:
        """Admission armed? Callers skip the whole gate (including the
        admission_exempt AST walk) when the limit is 0 — the default
        path costs one global read. Consequence: statements already
        running when the limit is first armed are not counted against
        it; the limit applies to statements admitted after arming."""
        return self._limits()[0] > 0

    def admit(self, conn=None, label: str = "",
              trace=None) -> AdmissionTicket:
        """Block until this statement may execute (or raise).

        Raises SqlError 53300 when the admission queue is at capacity,
        and re-raises whatever `conn.check_cancel()` raises while
        queued (57014 on cancel or statement timeout) — the waiter is
        dequeued on every exit path. On a waited admission the queue
        time lands in the Admission* gauges and, when `trace` is
        given, as a ``queue_wait``-category span."""
        held = getattr(conn, "_admission_held", 0) if conn is not None else 0
        if held > 0:
            # nested statement on a slot-holding connection: never a
            # second slot (self-deadlock at max=1), never a release of
            # the outer statement's slot
            conn._admission_held = held + 1
            return AdmissionTicket(conn, nested=True)
        maxc, depth = self._limits()
        w: Optional[object] = None
        with self._cv:
            if maxc <= 0 or (self._running < maxc and not self._queue):
                self._running += 1
                if conn is not None:
                    conn._admission_held = 1
                return AdmissionTicket(conn, nested=False)
            if len(self._queue) >= depth:
                metrics.ADMISSION_REJECTED.add()
                raise errors.SqlError(
                    errors.TOO_MANY_CONNECTIONS,
                    "statement rejected: admission queue is full "
                    f"({len(self._queue)} queued, "
                    f"serene_admission_queue_depth = {depth})",
                    hint="retry later, or raise "
                         "serene_max_concurrent_statements / "
                         "serene_admission_queue_depth")
            w = object()
            self._queue.append(w)
        # -- queued: surface it, then poll-wait honoring cancel/timeout
        metrics.ADMISSION_QUEUED.add()
        metrics.ADMISSION_QUEUE_DEPTH.add()
        t0 = time.perf_counter_ns()
        sess = None
        prev = (None, None, None)
        if conn is not None:
            sess = conn.db.sessions.get(conn._session_id)
        if sess is not None:
            prev = (sess.get("state"), sess.get("wait_event_type"),
                    sess.get("wait_event"))
            sess["state"] = "queued"
            sess["wait_event_type"] = "Admission"
            sess["wait_event"] = "AdmissionQueue"
        admitted = False
        try:
            while not admitted:
                with self._cv:
                    maxc, _ = self._limits()
                    if (maxc <= 0 or self._running < maxc) and \
                            self._queue and self._queue[0] is w:
                        self._queue.popleft()
                        self._running += 1
                        admitted = True
                        self._cv.notify_all()
                        break
                    self._cv.wait(timeout=_QUEUE_POLL_S)
                if conn is not None:
                    conn.check_cancel()     # 57014 → finally dequeues
        finally:
            t1 = time.perf_counter_ns()
            metrics.ADMISSION_WAIT_NS.add(t1 - t0)
            metrics.ADMISSION_QUEUE_DEPTH.sub()
            if not admitted:
                with self._cv:
                    try:
                        self._queue.remove(w)
                    except ValueError:      # already popped
                        pass
                    self._cv.notify_all()
            if sess is not None:
                sess["state"], sess["wait_event_type"], \
                    sess["wait_event"] = prev
            if trace is not None:
                trace.add("queue_wait", "admission", t0, t1, label="queued")
        if conn is not None:
            conn._admission_held = 1
        return AdmissionTicket(conn, nested=False)

    def release(self, ticket: Optional[AdmissionTicket]) -> None:
        """Return a statement's hold; idempotent per ticket. The
        governor SLOT follows the connection's LAST outstanding hold,
        not the first-admitted ticket: a session that opens portal P1
        (slot), opens nested P2 on that slot, then closes P1 first
        must keep the slot occupied until P2 drains too — else the
        concurrency limit is exceeded while P2 still executes. Wakes
        the queue head so admission stays FIFO."""
        if ticket is None or ticket.released:
            return
        ticket.released = True
        conn = ticket.conn
        if conn is not None:
            held = max(0, getattr(conn, "_admission_held", 1) - 1)
            conn._admission_held = held
            if held > 0:
                return              # a sibling hold still owns the slot
        elif ticket.nested:
            return
        with self._cv:
            self._running = max(0, self._running - 1)
            self._cv.notify_all()

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """One point-in-time governor view for /_stats, sdb_admission
        and tests."""
        maxc, depth = self._limits()
        with self._lock:
            running, queued = self._running, len(self._queue)
        return {"running": running, "queued": queued,
                "max_concurrent_statements": maxc,
                "queue_depth": depth,
                "queued_total": metrics.ADMISSION_QUEUED.value,
                "rejected_total": metrics.ADMISSION_REJECTED.value,
                "wait_ns_total": metrics.ADMISSION_WAIT_NS.value,
                "preemptions_total": metrics.SCHED_PREEMPTIONS.value}


#: process-wide governor (one per process, like the worker pool)
GOVERNOR = Governor()


# -- admission exemption ------------------------------------------------------

#: relation-name prefixes that mark catalog/introspection sources
_CATALOG_PREFIXES = ("pg_", "sdb_", "information_schema")


def _catalog_name(name: str) -> bool:
    return name.lower().startswith(_CATALOG_PREFIXES)


def admission_exempt(st) -> bool:
    """True when a statement may bypass admission control: a read
    (Select/SetOp) whose every table source is a system catalog
    (pg_* / sdb_* / information_schema relations or table functions) —
    or that references no table at all (``SELECT 1``). The dashboards
    that diagnose an overloaded server (`pg_stat_activity`,
    `sdb_admission`, `sdb_query_progress`) must not queue behind the
    overload they are diagnosing. Any user relation, and any table
    source the walk does not positively recognize as catalog, makes
    the statement admissible like normal work."""
    import dataclasses

    from ..sql import ast

    if not isinstance(st, (ast.Select, ast.SetOp)):
        return False

    def walk(node, depth: int = 0) -> bool:
        """False the moment a non-catalog table source is seen."""
        if depth > 200:
            return False    # fail CLOSED: an unwalkably deep statement
            #                 is admitted like normal work, never exempt
        if node is None:
            return True
        if isinstance(node, ast.NamedTable):
            # the relation name or its schema qualifier may mark the
            # catalog: information_schema.tables, pg_catalog.pg_class
            return _catalog_name(node.parts[-1]) or \
                (len(node.parts) >= 2 and _catalog_name(node.parts[-2]))
        if isinstance(node, ast.TableFunction):
            return _catalog_name(node.name)
        if isinstance(node, ast.TableRef) and \
                not isinstance(node, (ast.SubqueryRef, ast.JoinRef)):
            # a table-source kind this walk doesn't know (file sources,
            # future VALUES lists): not provably catalog → admit
            return False
        if isinstance(node, (list, tuple)):
            return all(walk(v, depth + 1) for v in node)
        if isinstance(node, dict):
            return all(walk(v, depth + 1) for v in node.values())
        if dataclasses.is_dataclass(node) and not isinstance(node, type):
            return all(walk(getattr(node, f.name), depth + 1)
                       for f in dataclasses.fields(node))
        return True

    return walk(st)


# -- socket-level admission ---------------------------------------------------


class ConnInfo:
    """One open front-door socket (server/frontdoor.py, server/pgwire.py)
    as the connection gate tracks it: a virtual pid (process-unique,
    monotonically assigned — the sdb_connections analog of a backend
    pid), the protocol frontend, a coarse state machine
    (active ⇄ idle), and activity timestamps for idle_s."""

    __slots__ = ("pid", "protocol", "peer", "state", "connected_ns",
                 "last_ns", "buffered")

    def __init__(self, pid: int, protocol: str, peer: str):
        self.pid = pid
        self.protocol = protocol
        self.peer = peer
        self.state = "active"        # accept/handshake counts as active
        self.connected_ns = time.monotonic_ns()
        self.last_ns = self.connected_ns
        #: callable -> bytes currently sitting in this connection's
        #: transport write buffer (set by the owning frontend; sampled
        #: for the SocketBytesBuffered gauge and sdb_connections)
        self.buffered = None


class ConnectionGate:
    """Admission control at the SOCKET, the layer below the statement
    governor above: `serene_max_connections` caps how many sockets the
    front door holds open across BOTH protocols, and an accept past the
    cap is rejected before a single byte of the session is parsed
    (pgwire: a clean 53300 ErrorResponse; HTTP: 429 + Retry-After).
    The statement governor then arbitrates what the admitted
    connections may RUN — two gates, one backpressure story.

    Also the socket layer's observability spine: the
    Connections{Open,Idle,Active,Rejected} gauges, the AcceptQueueWait
    histogram, `/_stats.connections` and the `sdb_connections()`
    relation all read from here."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conns: dict[int, ConnInfo] = {}
        self._pids = itertools.count(1)
        self._pauses = 0

    @staticmethod
    def limit() -> int:
        from ..utils.config import REGISTRY
        return int(REGISTRY.get_global("serene_max_connections") or 0)

    def try_admit(self, protocol: str, peer,
                  accept_ns: Optional[int] = None) -> Optional[ConnInfo]:
        """Admit one socket or return None (caller sends the protocol's
        rejection packet and closes). accept_ns is the monotonic stamp
        taken when the OS handed us the socket — the gap to now is the
        event-loop accept backlog (AcceptQueueWait)."""
        if accept_ns is not None:
            metrics.ACCEPT_QUEUE_WAIT_HIST.observe_ns(
                max(0, time.monotonic_ns() - accept_ns))
        if isinstance(peer, tuple):
            peer = f"{peer[0]}:{peer[1]}"
        limit = self.limit()
        with self._lock:
            if limit and len(self._conns) >= limit:
                metrics.CONNECTIONS_REJECTED.add(1)
                return None
            info = ConnInfo(next(self._pids), protocol, str(peer or ""))
            self._conns[info.pid] = info
        metrics.CONNECTIONS_OPEN.add(1)
        metrics.CONNECTIONS_ACTIVE.add(1)
        return info

    def set_state(self, info: ConnInfo, state: str) -> None:
        """active ⇄ idle transition; maintains the live gauges and the
        idle_s clock (touch on every transition)."""
        if info.state == state:
            return
        if info.state == "idle":
            metrics.CONNECTIONS_IDLE.sub(1)
        elif info.state == "active":
            metrics.CONNECTIONS_ACTIVE.sub(1)
        info.state = state
        info.last_ns = time.monotonic_ns()
        if state == "idle":
            metrics.CONNECTIONS_IDLE.add(1)
        else:
            metrics.CONNECTIONS_ACTIVE.add(1)

    def note_pause(self) -> None:
        """A frontend paused reading on a slow-writer connection."""
        with self._lock:
            self._pauses += 1

    def release(self, info: Optional[ConnInfo]) -> None:
        if info is None:
            return
        with self._lock:
            if self._conns.pop(info.pid, None) is None:
                return
        metrics.CONNECTIONS_OPEN.sub(1)
        if info.state == "idle":
            metrics.CONNECTIONS_IDLE.sub(1)
        else:
            metrics.CONNECTIONS_ACTIVE.sub(1)

    # -- introspection ----------------------------------------------------

    def buffered_bytes(self) -> int:
        """Sum of transport write-buffer bytes across open connections
        (sampled — feeds the SocketBytesBuffered gauge at scrape)."""
        total = 0
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            fn = c.buffered
            if fn is not None:
                try:
                    total += int(fn())
                except Exception:  # noqa: BLE001 — transport closing
                    pass
        metrics.SOCKET_BYTES_BUFFERED.set(total)
        return total

    def rows(self) -> list[dict]:
        """sdb_connections(): one row per open front-door socket —
        the pg_stat_activity analog for the socket layer."""
        now = time.monotonic_ns()
        out = []
        with self._lock:
            conns = sorted(self._conns.values(), key=lambda c: c.pid)
        for c in conns:
            buffered = 0
            if c.buffered is not None:
                try:
                    buffered = int(c.buffered())
                except Exception:  # noqa: BLE001
                    pass
            out.append({
                "pid": c.pid, "protocol": c.protocol, "state": c.state,
                "idle_s": round((now - c.last_ns) / 1e9, 3)
                if c.state == "idle" else 0.0,
                "peer": c.peer,
                "connected_s": round((now - c.connected_ns) / 1e9, 3),
                "buffered_bytes": buffered})
        return out

    def snapshot(self) -> dict:
        """The `/_stats.connections` section."""
        with self._lock:
            open_ = len(self._conns)
            idle = sum(1 for c in self._conns.values()
                       if c.state == "idle")
            pauses = self._pauses
        return {"open": open_, "idle": idle, "active": open_ - idle,
                "max_connections": self.limit(),
                "rejected_total": metrics.CONNECTIONS_REJECTED.value,
                "pause_reads_total": pauses,
                "buffered_bytes": self.buffered_bytes()}


#: process-wide socket gate (one per process, like GOVERNOR above)
CONNGATE = ConnectionGate()
