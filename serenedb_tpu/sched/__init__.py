"""Workload governor subsystem: admission control between statement
dispatch and the shared worker pool, plus the per-statement scheduling
identity (tag + `serene_priority` weight) the pool's fair-share stride
scheduler keys on. See sched/governor.py for the full contract."""

from .governor import (CURRENT_SCHED, GOVERNOR, AdmissionTicket, Governor,
                       admission_exempt, next_stmt_tag)

__all__ = ["CURRENT_SCHED", "GOVERNOR", "AdmissionTicket", "Governor",
           "admission_exempt", "next_stmt_tag"]
