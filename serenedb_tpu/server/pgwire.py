"""PostgreSQL wire protocol (v3) server.

Reference analog: server/network/pg/pg_wire_session.{h,cpp} (3.4 kLoC C++ —
startup/TLS negotiation, auth, simple+extended protocol, portals, COPY;
SURVEY.md §2.2). This asyncio implementation covers the surface drivers
need: startup + cleartext/trust auth, ParameterStatus, simple queries,
extended protocol (Parse/Bind/Describe/Execute/Close/Sync/Flush) with named
statements and portals, text-format results, SQLSTATE error responses,
implicit transaction status, and CancelRequest keys.

Message framing: [type:1][len:4 incl itself][payload]; startup has no type.
"""

from __future__ import annotations

import asyncio
import functools
import os
import secrets
import struct
import time
from dataclasses import dataclass, field
from typing import Optional

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch
from .. import scram
from ..engine import Connection, Database, QueryResult
from ..sql import ast, parser
from ..utils import log, metrics
from . import hba

PROTOCOL_VERSION = 196608          # 3.0
SSL_REQUEST = 80877103
GSS_REQUEST = 80877104
CANCEL_REQUEST = 80877102

# PG type OIDs
_OID = {
    dt.TypeId.BOOL: 16, dt.TypeId.TINYINT: 21, dt.TypeId.SMALLINT: 21,
    dt.TypeId.INT: 23, dt.TypeId.BIGINT: 20, dt.TypeId.FLOAT: 700,
    dt.TypeId.DOUBLE: 701, dt.TypeId.VARCHAR: 25,
    dt.TypeId.TIMESTAMP: 1114, dt.TypeId.DATE: 1082,
    dt.TypeId.INTERVAL: 1186, dt.TypeId.NULL: 25,
    dt.TypeId.OID: 26, dt.TypeId.REGCLASS: 2205,
    dt.TypeId.REGTYPE: 2206, dt.TypeId.REGPROC: 24,
    dt.TypeId.REGNAMESPACE: 4089, dt.TypeId.RECORD: 2249,
}
_TYPLEN = {16: 1, 21: 2, 23: 4, 20: 8, 700: 4, 701: 8, 25: -1, 1114: 8,
           1082: 4, 1186: 16, 26: 4, 2205: 4, 2206: 4, 24: 4, 4089: 4,
           2249: -1}

#: element TypeId → array OID (PG catalog values)
_ARRAY_OID = {
    dt.TypeId.BOOL: 1000, dt.TypeId.SMALLINT: 1005, dt.TypeId.TINYINT: 1005,
    dt.TypeId.INT: 1007, dt.TypeId.BIGINT: 1016, dt.TypeId.FLOAT: 1021,
    dt.TypeId.DOUBLE: 1022, dt.TypeId.VARCHAR: 1009,
    dt.TypeId.DATE: 1182, dt.TypeId.TIMESTAMP: 1115,
}


def oid_of_type(t: dt.SqlType) -> int:
    if t.id is dt.TypeId.ARRAY:
        return _ARRAY_OID.get(t.elem or dt.TypeId.VARCHAR, 1009)
    return _OID.get(t.id, 25)


def _pg_array_text(json_text: str, elem=None, db=None) -> bytes:
    """JSON array text (the physical representation) → PG {...} output
    (reference: server/pg/serialize.cpp array_out). One renderer for
    arrays everywhere — record fields included — lives in
    columnar/pgcopy so the two can never drift."""
    from ..columnar.pgcopy import _array_field_text
    return _array_field_text(json_text, elem).encode()


def pg_text(value, typ: dt.SqlType, db=None) -> Optional[bytes]:
    """PG text-format encoding (reference: server/pg/serialize.cpp)."""
    if value is None:
        return None
    tid = typ.id
    if tid is dt.TypeId.ARRAY:
        return _pg_array_text(str(value), typ.elem, db)
    if tid is dt.TypeId.RECORD:
        from ..columnar.pgcopy import record_text
        return record_text(str(value)).encode()
    if tid is dt.TypeId.BOOL:
        return b"t" if value else b"f"
    if tid in (dt.TypeId.REGCLASS, dt.TypeId.REGTYPE, dt.TypeId.REGPROC,
               dt.TypeId.REGNAMESPACE):
        # PG renders reg* as names in text format (binary stays the oid)
        from .. import pgcatalog as _pgcat
        if tid is dt.TypeId.REGTYPE:
            s = _pgcat.regtype_render(value)
        elif tid is dt.TypeId.REGPROC:
            s = _pgcat.proc_name_of(value) or str(int(value))
        elif tid is dt.TypeId.REGNAMESPACE:
            s = _pgcat.namespace_render(db, int(value))
        else:
            s = _pgcat.regclass_render(db, int(value))
        return s.encode()
    if tid is dt.TypeId.TIMESTAMP:
        from ..sql.binder import format_timestamp
        return format_timestamp(int(value)).encode()
    if tid is dt.TypeId.DATE:
        import numpy as np
        return str(np.datetime64(int(value), "D")).encode()
    if tid is dt.TypeId.INTERVAL:
        from ..sql.binder import format_interval
        return format_interval(int(value)).encode()
    if isinstance(value, float):
        import math
        if math.isnan(value):
            return b"NaN"
        if math.isinf(value):
            return b"Infinity" if value > 0 else b"-Infinity"
        return repr(value).encode()
    return str(value).encode()


def _fmt_for(fmts, i: int) -> int:
    """Result-format code for column i (PG Bind semantics: none = all
    text, one = applies to every column, else positional)."""
    if not fmts:
        return 0
    if len(fmts) == 1:
        return fmts[0]
    return fmts[i] if i < len(fmts) else 0


def pg_binary(value, typ: dt.SqlType) -> Optional[bytes]:
    """PG binary-format encoding for result columns (reference:
    server/pg/serialize.cpp binary send functions). Delegates to the
    shared COPY codec — one source of truth for binary sends."""
    from ..columnar.pgcopy import encode_value
    return encode_value(value, typ)


async def upgrade_writer_tls(writer: asyncio.StreamWriter, ctx) -> None:
    """In-band TLS upgrade of an established stream pair.

    `StreamWriter.start_tls` is 3.11+; on 3.10 run `loop.start_tls`
    over the writer's transport/protocol directly and re-point the
    writer, the protocol, and the reader's flow-control transport at
    the SSL transport (exactly what 3.11's implementation does —
    `loop.start_tls` wraps with call_connection_made=False, so none of
    this re-runs `connection_made`)."""
    if hasattr(writer, "start_tls"):        # 3.11+
        await writer.start_tls(ctx)
        return
    await writer.drain()
    loop = asyncio.get_running_loop()
    transport = writer.transport
    protocol = transport.get_protocol()
    new_transport = await loop.start_tls(
        transport, protocol, ctx, server_side=True)
    writer._transport = new_transport
    protocol._transport = new_transport
    protocol._over_ssl = True
    reader = getattr(protocol, "_stream_reader", None)
    if reader is not None:
        reader._transport = new_transport


class Writer:
    def __init__(self, transport: asyncio.StreamWriter, db=None):
        self.t = transport
        self._buf = bytearray()
        #: the session's Database — reg* text rendering resolves names
        self.db = db

    def msg(self, kind: bytes, payload: bytes = b""):
        self._buf += kind + struct.pack("!I", len(payload) + 4) + payload

    async def flush(self):
        if self._buf:
            self.t.write(bytes(self._buf))
            self._buf.clear()
            await self.t.drain()

    # -- common messages ---------------------------------------------------

    def auth_ok(self):
        self.msg(b"R", struct.pack("!I", 0))

    def auth_cleartext(self):
        self.msg(b"R", struct.pack("!I", 3))

    def auth_sasl(self, mechanisms: list[str]):
        body = b"".join(m.encode() + b"\x00" for m in mechanisms) + b"\x00"
        self.msg(b"R", struct.pack("!I", 10) + body)

    def auth_sasl_continue(self, data: str):
        self.msg(b"R", struct.pack("!I", 11) + data.encode())

    def auth_sasl_final(self, data: str):
        self.msg(b"R", struct.pack("!I", 12) + data.encode())

    def notification(self, pid: int, channel: str, payload: str):
        self.msg(b"A", struct.pack("!I", pid) + channel.encode() +
                 b"\x00" + payload.encode() + b"\x00")

    def parameter_status(self, k: str, v: str):
        self.msg(b"S", k.encode() + b"\x00" + v.encode() + b"\x00")

    def backend_key(self, pid: int, key: int):
        self.msg(b"K", struct.pack("!II", pid, key))

    def ready(self, status: bytes):
        self.msg(b"Z", status)

    def row_description(self, names: list[str], types: list[dt.SqlType],
                        fmts: tuple = ()):
        out = [struct.pack("!H", len(names))]
        for i, (name, t) in enumerate(zip(names, types)):
            oid = oid_of_type(t)
            out.append(name.encode() + b"\x00")
            out.append(struct.pack("!IHIhih", 0, 0, oid,
                                   _TYPLEN.get(oid, -1), -1,
                                   _fmt_for(fmts, i)))
        self.msg(b"T", b"".join(out))

    def data_rows(self, batch: Batch, fmts: tuple = ()):
        types = [c.type for c in batch.columns]
        cols_text = []
        for ci, (col, t) in enumerate(zip(batch.columns, types)):
            vals = col.to_pylist()
            if _fmt_for(fmts, ci) == 1:
                cols_text.append([pg_binary(v, t) for v in vals])
            else:
                cols_text.append([pg_text(v, t, self.db) for v in vals])
        for i in range(batch.num_rows):
            parts = [struct.pack("!H", len(types))]
            for ci in range(len(types)):
                v = cols_text[ci][i]
                if v is None:
                    parts.append(struct.pack("!i", -1))
                else:
                    parts.append(struct.pack("!i", len(v)) + v)
            self.msg(b"D", b"".join(parts))

    def command_complete(self, tag: str):
        self.msg(b"C", tag.encode() + b"\x00")

    def empty_query(self):
        self.msg(b"I")

    def parse_complete(self):
        self.msg(b"1")

    def bind_complete(self):
        self.msg(b"2")

    def close_complete(self):
        self.msg(b"3")

    def no_data(self):
        self.msg(b"n")

    def param_description(self, n: int):
        self.msg(b"t", struct.pack("!H", n) + struct.pack("!I", 25) * n)

    def error(self, e: errors.SqlError):
        fields = [b"SERROR", b"VERROR",
                  b"C" + e.sqlstate.encode(),
                  b"M" + e.message.encode()]
        if e.detail:
            fields.append(b"D" + e.detail.encode())
        if e.hint:
            fields.append(b"H" + e.hint.encode())
        self.msg(b"E", b"\x00".join(fields) + b"\x00\x00")


@dataclass
class Prepared:
    sql: str
    statements: list[ast.Statement]
    n_params: int
    param_oids: tuple = ()   # client-declared OIDs from Parse (may be 0s)


@dataclass
class Portal:
    prepared: Prepared
    params: list
    result_fmts: tuple = ()    # Bind result-format codes (0 text, 1 binary)
    pending: object = None     # QueryResult with rows not yet sent
    sent: int = 0
    #: streaming SELECT state: {"it": batch iterator, "leftover": Batch
    #: remainder after a row-budget split, "total": rows sent} — rows leave
    #: the socket as the executor produces them (wire_collector.h:20-60)
    stream: object = None


def _close_portal_stream(portal: Optional["Portal"]) -> None:
    """Close a suspended streaming portal's executor generator eagerly —
    its session scope (pg_stat_activity 'active', QUERIES_ACTIVE gauge)
    must end now, never at GC time."""
    if portal is not None and portal.stream is not None:
        try:
            portal.stream["it"].close()
        except Exception:
            pass
        portal.stream = None


class PgSession:
    def __init__(self, server: "PgServer", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, gate_info=None):
        self.server = server
        self.reader = reader
        self.w = Writer(writer, db=server.db)
        self.conn: Optional[Connection] = None
        self.prepared: dict[str, Prepared] = {}
        self.portals: dict[str, Portal] = {}
        self.pid = os.getpid()
        self.secret = secrets.randbits(31)
        self.ignore_till_sync = False
        self.tls_active = False
        #: the connection gate's record for this socket (None when the
        #: session is driven outside the accept path, e.g. tests)
        self.gate_info = gate_info

    # -- startup -----------------------------------------------------------

    def _set_gate(self, state: str) -> None:
        if self.gate_info is not None:
            from ..sched.governor import CONNGATE
            CONNGATE.set_state(self.gate_info, state)

    @staticmethod
    def _idle_conn_timeout() -> Optional[float]:
        from ..utils.config import REGISTRY as _settings
        t = float(_settings.get_global("serene_idle_conn_timeout_s") or 0.0)
        return t if t > 0 else None

    async def _handshake(self) -> bool:
        if not await self._consume_proxy_preface():
            return False
        return await self._startup()

    async def run(self):
        with metrics.PG_CONNECTIONS.scoped():
            try:
                # the whole handshake honors the idle timeout: a
                # half-open client (SYN, then silence) is reaped without
                # ever burning a pool slot
                t = self._idle_conn_timeout()
                if t:
                    ok = await asyncio.wait_for(self._handshake(), t)
                else:
                    ok = await self._handshake()
                if not ok:
                    return
                await self._command_loop()
            except (asyncio.IncompleteReadError, ConnectionResetError):
                pass
            except asyncio.TimeoutError:
                log.info("pg", "idle connection reaped "
                         "(serene_idle_conn_timeout_s)")
            finally:
                self.server.unregister_cancel(self.pid, self.secret)
                for p in self.portals.values():
                    _close_portal_stream(p)
                if self.conn is not None:
                    self.conn.close()
                self.w.t.close()

    #: PROXY v2 signature (HAProxy spec); v1 is the ASCII "PROXY " line
    _PP2_SIG = b"\r\n\r\n\x00\r\nQUIT\n"

    async def _consume_proxy_preface(self) -> bool:
        """HAProxy PROXY protocol v1/v2 (reference:
        server/network/proxy_protocol.cpp). off: never read one;
        optional: consume if present; require: reject clients without
        one. The advertised source address replaces the socket peer for
        HBA matching and pg_stat_activity."""
        mode = self.server.proxy_protocol
        if mode == "off":
            return True
        # peek: v2 starts with a 12-byte binary signature, v1 with
        # ASCII "PROXY "; anything else is a plain client
        head = await self.reader.readexactly(1)
        if head == b"\r":
            sig = head + await self.reader.readexactly(11)
            if sig != self._PP2_SIG:
                self.w.t.close()
                return False
            vercmd = await self.reader.readexactly(1)
            fam = await self.reader.readexactly(1)
            (plen,) = struct.unpack("!H", await self.reader.readexactly(2))
            payload = await self.reader.readexactly(plen)
            if vercmd[0] >> 4 != 2:
                self.w.t.close()
                return False
            if (vercmd[0] & 0xF) == 1 and fam[0] >> 4 == 1 and plen >= 12:
                import socket as _socket
                src = _socket.inet_ntoa(payload[0:4])
                sport = struct.unpack("!H", payload[8:10])[0]
                self.proxied_peer = (src, sport)
            elif (vercmd[0] & 0xF) == 1 and fam[0] >> 4 == 2 and plen >= 36:
                import socket as _socket
                src = _socket.inet_ntop(_socket.AF_INET6, payload[0:16])
                sport = struct.unpack("!H", payload[32:34])[0]
                self.proxied_peer = (src, sport)
            # LOCAL command / UNSPEC: keep the socket peer
            return True
        if head == b"P":
            rest = await self.reader.readexactly(5)
            if head + rest != b"PROXY ":
                self.w.t.close()
                return False
            line = bytearray()
            while not line.endswith(b"\r\n"):
                line += await self.reader.readexactly(1)
                if len(line) > 100:          # spec: max 107 bytes total
                    self.w.t.close()
                    return False
            parts = line[:-2].decode("ascii", "replace").split(" ")
            # TCP4/TCP6 src dst sport dport; UNKNOWN keeps the peer;
            # malformed fields drop the connection cleanly (spec) —
            # never an unhandled task exception an unauthenticated
            # peer can spam
            if parts and parts[0] in ("TCP4", "TCP6"):
                try:
                    self.proxied_peer = (parts[1], int(parts[3]))
                except (IndexError, ValueError):
                    self.w.t.close()
                    return False
            return True
        if mode == "require":
            self.w.t.close()
            return False
        # optional + not a preface: stash the byte for the startup reader
        self._preread = head
        return True

    async def _read_exactly(self, n: int) -> bytes:
        """readexactly honoring a byte pre-read by the proxy sniffer."""
        pre = getattr(self, "_preread", b"")
        if pre:
            self._preread = b""
            return pre + await self.reader.readexactly(n - len(pre))
        return await self.reader.readexactly(n)

    async def _startup(self) -> bool:
        while True:
            raw = await self._read_exactly(4)
            (ln,) = struct.unpack("!I", raw)
            body = await self.reader.readexactly(ln - 4)
            (code,) = struct.unpack("!I", body[:4])
            if code == SSL_REQUEST:
                ctx = self.server.tls_context
                if ctx is not None and not self.tls_active:
                    self.w.t.write(b"S")
                    await self.w.t.drain()
                    # in-band upgrade (reference: MaybeTls,
                    # tls_context.cpp); the stream pair survives start_tls
                    await upgrade_writer_tls(self.w.t, ctx)
                    self.tls_active = True
                else:
                    self.w.t.write(b"N")
                    await self.w.t.drain()
                continue
            if code == GSS_REQUEST:
                self.w.t.write(b"N")
                await self.w.t.drain()
                continue
            if code == CANCEL_REQUEST:
                pid, key = struct.unpack("!II", body[4:12])
                self.server.cancel(pid, key)
                return False
            if code != PROTOCOL_VERSION:
                self.w.error(errors.SqlError(
                    "08P01", f"unsupported protocol version {code >> 16}"))
                await self.w.flush()
                return False
            break
        params = {}
        parts = body[4:].split(b"\x00")
        for k, v in zip(parts[::2], parts[1::2]):
            if k:
                params[k.decode()] = v.decode()
        user = params.get("user", "serene")
        database = params.get("database", user)
        roles = self.server.db.roles
        role_known = roles.exists(user)
        if role_known and not roles.can_login(user):
            self.w.error(errors.SqlError(
                "28000", f'role "{user}" is not permitted to log in'))
            await self.w.flush()
            return False
        # HBA: first matching rule decides the auth method (reference:
        # server/network/pg/hba.cpp). Without an HBA config, fall back to
        # the implicit policy (server password / role password / trust).
        method = None
        if self.server.hba_rules is not None:
            peer = getattr(self, "proxied_peer", None) or \
                self.w.t.get_extra_info("peername")
            if isinstance(peer, tuple):
                addr = peer[0]
            else:
                # unix-socket peers have a path (or empty) peername —
                # they match `local` HBA rules
                addr = str(peer) if peer else "/unix-socket"
            rule = hba.match_rule(self.server.hba_rules, database, user,
                                  addr, self.tls_active)
            if rule is None or rule.method == "reject":
                self.w.error(errors.SqlError(
                    "28000",
                    f'no pg_hba.conf entry for host "{addr}", user '
                    f'"{user}", database "{database}"' if rule is None
                    else f'pg_hba.conf rejects connection for host '
                         f'"{addr}", user "{user}", database "{database}"'))
                await self.w.flush()
                return False
            method = rule.method
        if method is None:
            needs_password = self.server.password is not None or (
                role_known and roles.has_password(user))
            method = "implicit-password" if needs_password else "trust"
        if method != "trust":
            if self.server.password is not None:
                # a server-wide password gates EVERY login, including
                # passwordless roles — no bypass via user=serene
                verifier = self.server.password_verifier
            else:
                verifier = roles.scram_verifier(user)
            if method in ("password", "md5") or (
                    method == "implicit-password" and verifier is None):
                # cleartext exchange (md5 verifiers are never stored; the
                # md5 method degrades to password, as documented in hba.py)
                self.w.auth_cleartext()
                await self.w.flush()
                kind, payload = await self._read_msg()
                supplied = payload[:-1].decode() if kind == b"p" else ""
                if self.server.password is not None:
                    ok = kind == b"p" and supplied == self.server.password
                else:
                    ok = kind == b"p" and role_known and \
                        roles.check_password(user, supplied)
            elif verifier is not None:
                ok = await self._scram_auth(verifier)
            else:
                # scram demanded by HBA but the role has no password
                ok = False
            if not ok:
                self.w.error(errors.SqlError(
                    "28P01",
                    f'password authentication failed for user "{user}"'))
                await self.w.flush()
                return False
        # known roles get their own privileges; unknown users fall back to
        # the bootstrap superuser (trust mode, matching default pg_hba)
        self.conn = Connection(self.server.db,
                               user if role_known else None)
        for k, v in params.items():
            if k in ("user", "database", "options", "replication"):
                continue
            try:
                self.conn.settings.set(k, v)
            except (KeyError, ValueError):
                pass
        # the session registry id IS the backend pid clients see: a
        # BackendKeyData pid must find its own row in pg_stat_activity
        self.pid = self.conn._session_id
        # idle NOTIFY delivery: the engine bus wakes this loop from any
        # thread; the task only writes while the session is idle (a
        # client blocked in select() on the socket sees the 'A' push)
        loop = asyncio.get_running_loop()
        self._idle = False
        self.conn.notify_hook = lambda: loop.call_soon_threadsafe(
            lambda: loop.create_task(self._push_notifications()))
        self.w.auth_ok()
        for k, v in [("server_version", "16.0 (serenedb_tpu)"),
                     ("server_encoding", "UTF8"),
                     ("client_encoding", "UTF8"),
                     ("DateStyle", "ISO, MDY"),
                     ("TimeZone", "UTC"),
                     ("integer_datetimes", "on"),
                     ("standard_conforming_strings", "on"),
                     ("application_name",
                      params.get("application_name", ""))]:
            self.w.parameter_status(k, v)
        self.w.backend_key(self.pid, self.secret)
        self.server.register_cancel(self.pid, self.secret, self)
        self._drain_notifications()
        self.w.ready(self._txn_status())
        await self.w.flush()
        return True

    async def _scram_auth(self, verifier: dict) -> bool:
        """SCRAM-SHA-256 SASL exchange (RFC 7677 over the PG SASL
        messages: AuthenticationSASL → SASLInitialResponse →
        SASLContinue → SASLResponse → SASLFinal)."""
        self.w.auth_sasl([scram.MECHANISM])
        await self.w.flush()
        kind, payload = await self._read_msg()
        if kind != b"p":
            return False
        try:
            end = payload.index(b"\x00")
            mech = payload[:end].decode()
            (ln,) = struct.unpack_from("!i", payload, end + 1)
            data = payload[end + 5:end + 5 + ln].decode() if ln >= 0 else ""
            if mech != scram.MECHANISM:
                return False
            srv = scram.ScramServer(verifier)
            self.w.auth_sasl_continue(srv.first(data))
            await self.w.flush()
            kind, payload = await self._read_msg()
            if kind != b"p":
                return False
            ok, final = srv.final(payload.decode())
        except (ValueError, IndexError, struct.error, UnicodeDecodeError):
            return False
        if ok:
            self.w.auth_sasl_final(final)
        return ok

    async def _push_notifications(self):
        """Async NotificationResponse push while the session is idle."""
        if not self._idle or self.conn is None:
            return   # mid-command: the boundary drain will deliver
        try:
            self._drain_notifications()
            await self.w.flush()
        except (ConnectionResetError, RuntimeError):
            pass

    def _drain_notifications(self):
        """NotificationResponse delivery at statement boundaries (PG also
        delivers when idle; boundary delivery covers the standard driver
        poll loop)."""
        if self.conn is None:
            return
        for pid, channel, payload in self.conn.take_notifications():
            self.w.notification(pid, channel, payload)

    def _txn_status(self) -> bytes:
        if self.conn is None:
            return b"I"
        if self.conn.txn_failed:
            return b"E"
        return b"T" if self.conn.in_txn else b"I"

    async def _read_msg(self) -> tuple[bytes, bytes]:
        kind = await self.reader.readexactly(1)
        (ln,) = struct.unpack("!I", await self.reader.readexactly(4))
        payload = await self.reader.readexactly(ln - 4)
        return kind, payload

    # -- command loop ------------------------------------------------------

    async def _command_loop(self):
        while True:
            self._idle = True
            self._set_gate("idle")
            # close the missed-wakeup window: anything enqueued before
            # _idle flipped is delivered here; later arrivals take the
            # hook path
            self._drain_notifications()
            await self.w.flush()
            t = self._idle_conn_timeout()
            if t:
                # reap abandoned sessions between commands; propagates
                # to run()'s TimeoutError handler which closes the
                # transport (a statement in flight is never interrupted
                # — the timeout only guards this idle read)
                kind, payload = await asyncio.wait_for(
                    self._read_msg(), t)
            else:
                kind, payload = await self._read_msg()
            self._idle = False
            self._set_gate("active")
            if kind == b"X":
                return
            if self.ignore_till_sync and kind not in (b"S",):
                continue
            handler = {
                b"Q": self._on_query,
                b"P": self._on_parse,
                b"B": self._on_bind,
                b"D": self._on_describe,
                b"E": self._on_execute,
                b"C": self._on_close,
                b"S": self._on_sync,
                b"H": self._on_flush,
            }.get(kind)
            if handler is None:
                self.w.error(errors.SqlError(
                    "08P01", f"unknown message type {kind!r}"))
                self.ignore_till_sync = True
                await self.w.flush()
                continue
            await handler(payload)

    async def _on_query(self, payload: bytes):
        sql = payload[:-1].decode()
        loop = asyncio.get_running_loop()
        try:
            stmts = parser.parse(sql)
            if not stmts:
                self.w.empty_query()
            for st in stmts:
                if isinstance(st, ast.CopyStmt) and \
                        st.target in ("STDIN", "STDOUT"):
                    await self._run_copy(st)
                    continue
                if isinstance(st, (ast.Select, ast.SetOp)):
                    await self._stream_select(st, sql)
                    continue
                res = await loop.run_in_executor(
                    self.server.pool,
                    functools.partial(self.conn.execute_statement, st, [],
                                      sql_text=sql))
                self._send_result(res, describe=True)
        except errors.SqlError as e:
            self._note_error()
            self.w.error(e)
        except Exception as e:  # engine bug: surface as internal error
            log.error("pg", f"internal error: {e!r}")
            self._note_error()
            self.w.error(errors.SqlError("XX000", f"internal error: {e}"))
        self._drain_notifications()
        self.w.ready(self._txn_status())
        await self.w.flush()

    async def _run_copy(self, st):
        """COPY ... FROM STDIN / TO STDOUT sub-protocol (reference:
        pg_wire_session COPY in/out legs, SURVEY.md §2.2)."""
        if self.conn.txn_failed:
            raise errors.SqlError(
                errors.IN_FAILED_TRANSACTION,
                "current transaction is aborted, commands ignored until "
                "end of transaction block")
        loop = asyncio.get_running_loop()
        is_bin = str(st.options.get("format", "")).lower() == "binary"
        ov_fmt = 1 if is_bin else 0
        if st.direction == "from":
            ncols = len(st.columns) if st.columns else \
                len(self.conn.db.resolve_table(st.table).column_names)
            self.w.msg(b"G", struct.pack("!bH", ov_fmt, ncols) +
                       struct.pack("!h", ov_fmt) * ncols)
            await self.w.flush()
            chunks = []
            failed = None
            while True:
                kind, payload = await self._read_msg()
                if kind == b"d":
                    chunks.append(payload)
                elif kind == b"c":
                    break
                elif kind == b"f":
                    failed = payload[:-1].decode() or "COPY terminated"
                    break
                elif kind == b"X":
                    raise ConnectionResetError
                # 'H'/'S' flush/sync during copy: ignore
            if failed is not None:
                raise errors.SqlError(errors.QUERY_CANCELED,
                                      f"COPY from stdin failed: {failed}")
            data = b"".join(chunks)
            res = await loop.run_in_executor(
                self.server.pool, self.conn.copy_in_data, st, data)
            self.w.command_complete(res.command_tag)
            return
        # COPY TO STDOUT
        rows, n, ncols = await loop.run_in_executor(
            self.server.pool, self.conn.copy_out_data, st)
        self.w.msg(b"H", struct.pack("!bH", ov_fmt, ncols) +
                   struct.pack("!h", ov_fmt) * ncols)
        for row in rows:
            self.w.msg(b"d", row)
        self.w.msg(b"c")
        self.w.command_complete(f"COPY {n}")

    def _note_error(self):
        """Any error inside an explicit transaction block aborts it (the
        engine only marks this for errors it raises during execution)."""
        if self.conn is not None and self.conn.in_txn:
            self.conn.txn_failed = True

    async def _stream_select(self, st, sql: str):
        """Streaming wire collector for simple-protocol SELECTs: encode +
        flush per executor batch (reference: wire_collector.h:20-60 —
        rows leave the socket during execution, bounding session memory
        and time-to-first-row)."""
        loop = asyncio.get_running_loop()
        names, types, it = await loop.run_in_executor(
            self.server.pool,
            functools.partial(self.conn.execute_streaming, st, [],
                              sql_text=sql))
        self.w.row_description(names, types)
        n = 0
        try:
            while True:
                b = await loop.run_in_executor(self.server.pool,
                                               lambda: next(it, None))
                if b is None:
                    break
                if b.num_rows:
                    self.w.data_rows(b)
                    n += b.num_rows
                    # flush per batch: backpressure via the transport drain
                    await self.w.flush()
        finally:
            # deterministic engine-side cleanup (session state, metrics) on
            # error/disconnect — never wait for GC to finalize the generator
            await loop.run_in_executor(self.server.pool, it.close)
        self.w.command_complete(f"SELECT {n}")

    def _send_result(self, res: QueryResult, describe: bool,
                     fmts: tuple = ()):
        if res.batch.num_columns:
            if describe:
                self.w.row_description(
                    res.batch.names, [c.type for c in res.batch.columns],
                    fmts)
            self.w.data_rows(res.batch, fmts)
        self.w.command_complete(res.command_tag or "OK")

    # -- extended protocol -------------------------------------------------

    async def _on_parse(self, payload: bytes):
        try:
            name_end = payload.index(b"\x00")
            name = payload[:name_end].decode()
            sql_end = payload.index(b"\x00", name_end + 1)
            sql = payload[name_end + 1:sql_end].decode()
            (n_oids,) = struct.unpack_from("!H", payload, sql_end + 1)
            oids = struct.unpack_from(f"!{n_oids}I", payload, sql_end + 3)
            stmts = parser.parse(sql)
            if len(stmts) > 1:
                raise errors.syntax(
                    "cannot insert multiple commands into a prepared "
                    "statement")
            n_params = _count_params(stmts[0]) if stmts else 0
            self.prepared[name] = Prepared(sql, stmts, n_params, oids)
            self.w.parse_complete()
        except errors.SqlError as e:
            self._note_error()
            self.w.error(e)
            self.ignore_till_sync = True
        await self.w.flush()

    async def _on_bind(self, payload: bytes):
        try:
            off = 0
            pend = payload.index(b"\x00", off)
            portal = payload[off:pend].decode()
            send = payload.index(b"\x00", pend + 1)
            stmt_name = payload[pend + 1:send].decode()
            off = send + 1
            (n_fmt,) = struct.unpack_from("!H", payload, off)
            off += 2
            fmts = struct.unpack_from(f"!{n_fmt}h", payload, off)
            off += 2 * n_fmt
            prep = self.prepared.get(stmt_name)
            if prep is None:
                raise errors.SqlError(
                    "26000", f'prepared statement "{stmt_name}" does not '
                             "exist")
            (n_params,) = struct.unpack_from("!H", payload, off)
            off += 2
            params = []
            for i in range(n_params):
                (ln,) = struct.unpack_from("!i", payload, off)
                off += 4
                if ln < 0:
                    params.append(None)
                else:
                    raw = payload[off:off + ln]
                    off += ln
                    fmt = fmts[i] if i < len(fmts) else \
                        (fmts[0] if len(fmts) == 1 else 0)
                    oid = prep.param_oids[i] if i < len(prep.param_oids) \
                        else 0
                    params.append(_decode_param(raw, fmt, oid))
            rfmts: tuple = ()
            if off + 2 <= len(payload):   # tolerate clients omitting it
                (n_rfmt,) = struct.unpack_from("!H", payload, off)
                off += 2
                rfmts = struct.unpack_from(f"!{n_rfmt}h", payload, off)
            if any(f not in (0, 1) for f in rfmts):
                raise errors.SqlError(
                    "08P01", f"invalid result format code "
                             f"{[f for f in rfmts if f not in (0, 1)][0]}")
            _close_portal_stream(self.portals.get(portal))
            self.portals[portal] = Portal(prep, params, rfmts)
            self.w.bind_complete()
        except errors.SqlError as e:
            self._note_error()
            self.w.error(e)
            self.ignore_till_sync = True
        except Exception as e:
            # malformed Bind payloads (struct/index errors) must answer
            # 08P01, not tear the connection down silently
            self._note_error()
            self.w.error(errors.SqlError(
                "08P01", f"malformed Bind message: {e!r}"))
            self.ignore_till_sync = True
        await self.w.flush()

    async def _on_describe(self, payload: bytes):
        kind = payload[:1]
        name = payload[1:-1].decode()
        try:
            if kind == b"S":
                prep = self.prepared.get(name)
                if prep is None:
                    raise errors.SqlError(
                        "26000", f'prepared statement "{name}" does not exist')
                self.w.param_description(prep.n_params)
                self._describe_statement(prep)
            else:
                portal = self.portals.get(name)
                if portal is None:
                    raise errors.SqlError(
                        "34000", f'portal "{name}" does not exist')
                self._describe_statement(portal.prepared,
                                         portal.result_fmts)
        except errors.SqlError as e:
            self._note_error()
            self.w.error(e)
            self.ignore_till_sync = True
        await self.w.flush()

    def _describe_statement(self, prep: Prepared, fmts: tuple = ()):
        st = prep.statements[0] if prep.statements else None
        if isinstance(st, (ast.Select, ast.SetOp, ast.ShowStmt,
                           ast.Explain)):
            try:
                if isinstance(st, (ast.Select, ast.SetOp)):
                    plan = self.conn._plan(st, [None] * prep.n_params)
                    self.w.row_description(plan.names, plan.types, fmts)
                    return
            except errors.SqlError:
                pass
            self.w.no_data()
        elif isinstance(st, (ast.Insert, ast.Update, ast.Delete)) and \
                getattr(st, "returning", None):
            # drivers need the RETURNING row shape from Describe
            try:
                names, types = self.conn._describe_returning(
                    st, [None] * prep.n_params)
                self.w.row_description(names, types, fmts)
            except errors.SqlError:
                self.w.no_data()
        else:
            self.w.no_data()

    async def _on_execute(self, payload: bytes):
        end = payload.index(b"\x00")
        name = payload[:end].decode()
        loop = asyncio.get_running_loop()
        try:
            (max_rows,) = struct.unpack_from("!I", payload, end + 1)
            portal = self.portals.get(name)
            if portal is None:
                raise errors.SqlError("34000",
                                      f'portal "{name}" does not exist')
            if not portal.prepared.statements:
                self.w.empty_query()
                return
            st0 = portal.prepared.statements[0]
            if portal.stream is not None or (
                    portal.pending is None and
                    isinstance(st0, (ast.Select, ast.SetOp))):
                try:
                    await self._execute_streaming_portal(portal, st0,
                                                         max_rows)
                except Exception:
                    # never resume a broken iterator — and close it NOW so
                    # session-scope state (pg_stat_activity 'active',
                    # QUERIES_ACTIVE) never waits for GC
                    _close_portal_stream(portal)
                    raise
                await self.w.flush()
                return
            if portal.pending is None:
                portal.pending = await loop.run_in_executor(
                    self.server.pool,
                    functools.partial(self.conn.execute_statement, st0,
                                      portal.params,
                                      sql_text=portal.prepared.sql))
                portal.sent = 0
            res = portal.pending
            total = res.batch.num_rows
            if max_rows and res.batch.num_columns and \
                    portal.sent + max_rows < total:
                # partial page: rows then PortalSuspended (reference:
                # portals with row-budget paging, pg_wire_session.h:293-300)
                page = res.batch.slice(portal.sent,
                                       portal.sent + max_rows)
                portal.sent += max_rows
                self.w.data_rows(page, portal.result_fmts)
                self.w.msg(b"s")           # PortalSuspended
            else:
                remainder = res
                if res.batch.num_columns and portal.sent:
                    from ..engine import QueryResult as _QR
                    remainder = _QR(res.batch.slice(portal.sent, total),
                                    res.command_tag)
                self._send_result(remainder, describe=False,
                                  fmts=portal.result_fmts)
                portal.pending = None
                portal.sent = 0
        except errors.SqlError as e:
            self._note_error()
            self.w.error(e)
            self.ignore_till_sync = True
        except Exception as e:
            log.error("pg", f"internal error: {e!r}")
            self._note_error()
            self.w.error(errors.SqlError("XX000", f"internal error: {e}"))
            self.ignore_till_sync = True
        await self.w.flush()

    async def _execute_streaming_portal(self, portal: Portal, st,
                                        max_rows: int):
        """Extended-protocol streaming Execute: DataRows flush per
        executor batch; a row budget suspends the portal mid-stream
        without materializing the rest (reference: wire_collector.h:20-60
        + portal row-budget paging, pg_wire_session.h:293-300)."""
        loop = asyncio.get_running_loop()
        if portal.stream is None:
            names, types, it = await loop.run_in_executor(
                self.server.pool,
                functools.partial(self.conn.execute_streaming, st,
                                  portal.params,
                                  sql_text=portal.prepared.sql))
            portal.stream = {"it": it, "leftover": None, "total": 0}
        s = portal.stream
        it = s["it"]
        budget = max_rows if max_rows else None
        while True:
            b = s["leftover"]
            s["leftover"] = None
            if b is None:
                b = await loop.run_in_executor(self.server.pool,
                                               lambda: next(it, None))
            if b is None:
                self.w.command_complete(f"SELECT {s['total']}")
                portal.stream = None
                break
            if budget is not None and b.num_rows > budget:
                s["leftover"] = b.slice(budget, b.num_rows)
                b = b.slice(0, budget)
            if b.num_rows:
                self.w.data_rows(b, portal.result_fmts)
                s["total"] += b.num_rows
                if budget is not None:
                    budget -= b.num_rows
                await self.w.flush()   # backpressure via transport drain
            if budget == 0:
                self.w.msg(b"s")       # PortalSuspended
                break

    async def _on_close(self, payload: bytes):
        kind = payload[:1]
        name = payload[1:-1].decode()
        if kind == b"S":
            self.prepared.pop(name, None)
        else:
            _close_portal_stream(self.portals.pop(name, None))
        self.w.close_complete()
        await self.w.flush()

    async def _on_sync(self, payload: bytes):
        self.ignore_till_sync = False
        self._drain_notifications()
        self.w.ready(self._txn_status())
        await self.w.flush()

    async def _on_flush(self, payload: bytes):
        await self.w.flush()


def _decode_param(raw: bytes, fmt: int, oid: int = 0):
    if fmt == 1:
        # binary params: the Parse-declared OID disambiguates same-width
        # types (float8 vs int8); length alone is a fallback for OID 0
        if oid == 700:
            return struct.unpack("!f", raw)[0]
        if oid == 701:
            return struct.unpack("!d", raw)[0]
        if oid == 16:
            return raw != b"\x00"
        if oid == 25 or oid == 1043:
            return raw.decode()
        if len(raw) == 4:
            return struct.unpack("!i", raw)[0]
        if len(raw) == 8:
            return struct.unpack("!q", raw)[0]
        if len(raw) == 2:
            return struct.unpack("!h", raw)[0]
        raise errors.unsupported("binary parameter format for this type")
    text = raw.decode()
    # the wire gives no context for parameter typing here (the reference
    # resolves param types at bind through the planner); numeric-looking
    # text coerces to numbers, and _coerce casts on insert fix up the rest
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    if text.lower() in ("true", "false"):
        return text.lower() == "true"
    return text


def _count_params(st: ast.Statement) -> int:
    mx = 0

    def walk_expr(e):
        nonlocal mx
        if isinstance(e, ast.Param):
            mx = max(mx, e.index)
        for attr in ("left", "right", "operand", "low", "high", "pattern",
                     "else_"):
            v = getattr(e, attr, None)
            if isinstance(v, ast.Expr):
                walk_expr(v)
        for attr in ("args", "items"):
            for v in getattr(e, attr, []) or []:
                if isinstance(v, ast.Expr):
                    walk_expr(v)
        if isinstance(e, ast.Case):
            for c, v in e.branches:
                walk_expr(c)
                walk_expr(v)

    def walk_stmt(s):
        if isinstance(s, ast.Select):
            for it in s.items:
                walk_expr(it.expr)
            for e in ([s.where] if s.where else []) + s.group_by + \
                    ([s.having] if s.having else []):
                walk_expr(e)
            for oi in s.order_by:
                walk_expr(oi.expr)
        elif isinstance(s, ast.Insert):
            for row in s.values or []:
                for e in row:
                    walk_expr(e)
            if s.query:
                walk_stmt(s.query)
        elif isinstance(s, (ast.Delete, ast.Update)):
            if s.where:
                walk_expr(s.where)
            if isinstance(s, ast.Update):
                for _, e in s.assignments:
                    walk_expr(e)

    walk_stmt(st)
    return mx


def _remove_stale_unix_socket(path: str) -> None:
    """Unlink `path` only when it is a socket nobody answers on — a live
    server's socket raises 98 (address in use) instead of being stolen,
    and a regular file at the path is never deleted."""
    import socket as _socket
    import stat as _stat
    try:
        st = os.stat(path)
    except OSError:
        return
    if not _stat.S_ISSOCK(st.st_mode):
        raise errors.SqlError(
            "58030", f"listen path {path!r} exists and is not a socket")
    probe = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    probe.settimeout(1.0)
    try:
        probe.connect(path)
        probe.close()
        raise errors.SqlError(
            "55006", f"unix socket {path!r} is in use by a live server")
    except _socket.timeout:
        # a connect timeout is NOT proof of death — a live server with a
        # full accept backlog looks exactly like this. Never steal the
        # path; report it busy (reference: 55006 object_in_use).
        probe.close()
        raise errors.SqlError(
            "55006", f"unix socket {path!r} did not answer within 1s; "
            "assuming a live (busy) server owns it")
    except (ConnectionRefusedError, FileNotFoundError):
        probe.close()
        try:
            os.unlink(path)   # stale socket from a crashed process
        except OSError:
            pass
    except OSError:
        probe.close()


class PgServer:
    def __init__(self, db: Database, host: str = "127.0.0.1",
                 port: int = 5432, password: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 hba_conf: Optional[str] = None,
                 proxy_protocol: str = "off",
                 listen: Optional[list[str]] = None,
                 pool=None):
        self.db = db
        #: extra listener specs (tcp://… / unix://…) beyond host:port
        #: (reference: listen_spec.h multi-spec --listen)
        self.listen_specs = list(listen or [])
        #: HAProxy PROXY preface handling: off | optional | require
        #: (reference: server/network/proxy_protocol.cpp)
        self.proxy_protocol = proxy_protocol
        self.host = host
        self.port = port
        self.password = password
        self.password_verifier = None
        if password is not None:
            self.password_verifier = scram.build_verifier(password)
        # TLS: in-band upgrade on SSLRequest (reference: tls_context.cpp)
        self.tls_context = None
        if tls_cert is not None:
            import ssl as ssl_mod
            ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
            ctx.minimum_version = ssl_mod.TLSVersion.TLSv1_2
            ctx.load_cert_chain(tls_cert, tls_key)
            self.tls_context = ctx
        # HBA: None = implicit policy; text/path = pg_hba-style rules
        self.hba_rules = None
        if hba_conf is not None:
            self.set_hba(hba_conf)
        self._cancel_keys: dict[tuple[int, int], PgSession] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        # the session executor (the engine boundary): when the front
        # door hosts this server it passes its shared pool so BOTH
        # protocols draw on one bounded executor
        if pool is not None:
            self.pool = pool
            self._owns_pool = False
        else:
            import concurrent.futures
            self.pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=max(4, (os.cpu_count() or 4)))
            self._owns_pool = True

    def set_hba(self, conf: str) -> None:
        """Install pg_hba rules from conf text or a file path (runtime
        reconfigurable, matching the reference's SET hba)."""
        if "\n" not in conf and os.path.exists(conf):
            with open(conf) as f:
                conf = f.read()
        self.hba_rules = hba.parse_hba(conf)

    def register_cancel(self, pid: int, key: int, session: PgSession):
        self._cancel_keys[(pid, key)] = session

    def unregister_cancel(self, pid: int, key: int):
        self._cancel_keys.pop((pid, key), None)

    def cancel(self, pid: int, key: int):
        """CancelRequest: interrupt the session's in-flight statement
        (reference: CancelRegistry, cancel_registry.h). Cooperative — the
        executor raises 57014 at its next batch boundary."""
        session = self._cancel_keys.get((pid, key))
        if session is None or session.conn is None:
            log.info("pg", f"cancel request for unknown {pid}/{key}")
            return
        log.info("pg", f"cancel request for {pid}/{key}")
        session.conn.request_cancel()

    def _accept(self, reader, writer):
        # sync accept callback (runs inside connection_made): stamp NOW
        # so the accept→serve gap feeds the AcceptQueueWait histogram
        return self._client(reader, writer, time.monotonic_ns())

    async def _client(self, reader, writer, accept_ns=None):
        from ..sched.governor import CONNGATE
        info = CONNGATE.try_admit(
            "pg", writer.get_extra_info("peername"), accept_ns)
        if info is None:
            # socket-level admission: a clean 53300 ErrorResponse before
            # reading — let alone parsing — a single byte of the session
            w = Writer(writer)
            w.error(errors.SqlError(
                errors.TOO_MANY_CONNECTIONS,
                "sorry, too many clients already",
                hint="raise serene_max_connections or close idle "
                     "connections"))
            try:
                await w.flush()
            except (ConnectionResetError, RuntimeError):
                pass
            writer.close()
            return
        conns = getattr(self, "_live_writers", None)
        if conns is None:
            conns = self._live_writers = set()
        conns.add(writer)
        info.buffered = writer.transport.get_write_buffer_size
        try:
            await PgSession(self, reader, writer, gate_info=info).run()
        finally:
            CONNGATE.release(info)
            conns.discard(writer)

    async def start(self):
        from .listen import parse_listen_spec

        # warm the SHARED morsel worker pool at server start: every
        # session's parallel pipelines run on this one pool, so worker
        # count never multiplies with connection count (reference: one
        # TaskScheduler shared by all DuckDB connections)
        from ..parallel.pool import get_pool
        get_pool().ensure_started()
        self._server = await asyncio.start_server(
            self._accept, self.host, self.port)
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]
        log.info("pg", f"listening on {addr[0]}:{addr[1]}")
        self._extra_servers = []
        self._unix_paths = []
        for raw in self.listen_specs:
            spec = parse_listen_spec(raw, default_host=self.host)
            if spec.kind == "unix":
                _remove_stale_unix_socket(spec.path)
                srv = await asyncio.start_unix_server(
                    self._accept, path=spec.path)
                self._unix_paths.append(spec.path)
            else:
                srv = await asyncio.start_server(
                    self._accept, spec.host, spec.port)
            self._extra_servers.append(srv)
            log.info("pg", f"listening on {spec}")

    async def stop(self):
        # ordered teardown (reference serened.cpp): stop accepting, then
        # close live client transports — wait_closed() would otherwise
        # block forever on an idle connected client
        if self._server is not None:
            self._server.close()
        for srv in getattr(self, "_extra_servers", []):
            srv.close()
        for w in list(getattr(self, "_live_writers", ())):
            try:
                w.close()
            except Exception:  # noqa: BLE001
                pass
        if self._server is not None:
            await self._server.wait_closed()
        for srv in getattr(self, "_extra_servers", []):
            await srv.wait_closed()
        for path in getattr(self, "_unix_paths", []):
            try:
                os.unlink(path)
            except OSError:
                pass
        if getattr(self, "_owns_pool", True):
            self.pool.shutdown(wait=False)

    def run_forever(self):
        async def main():
            await self.start()
            await asyncio.Event().wait()
        asyncio.run(main())
