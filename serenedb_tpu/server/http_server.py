"""HTTP routing for the ES-compatible API (+ /_sql and health) and the
legacy thread-per-connection server.

Reference analog: server/network/http/ (h1 codec + router with :param
patterns; SURVEY.md §2.2). The route table lives here as a PURE
request→response function (`Router.handle`: bytes in, status/bytes out,
no transport knowledge), shared by BOTH transports:

- `server/frontdoor.py` — the asyncio front door (default,
  `serene_frontdoor = on`): connections are event-loop tasks, the
  route runs on the executor via run_in_executor.
- `LegacyHttpServer` below — stdlib ThreadingHTTPServer, kept ONE
  release as the bit-identity parity oracle (`serene_frontdoor = off`);
  same Router, so the two paths cannot drift.

`HttpServer` is the facade every caller constructs; the setting picks
the transport at construction time.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .. import errors
from ..engine import Database
from ..utils import log, metrics
from ..utils.config import REGISTRY as _settings
from .es_api import EsApi, EsError

JSON_CTYPE = "application/json"


def _json_body(body: str) -> Optional[dict]:
    if not body.strip():
        return None
    try:
        return json.loads(body)
    except json.JSONDecodeError as e:
        raise EsError(400, "parsing_exception", f"invalid JSON: {e}")


def encode_payload(payload) -> bytes:
    data = (json.dumps(payload) if not isinstance(payload, (str, bytes))
            else payload)
    return data.encode() if isinstance(data, str) else data


class Router:
    """The entire HTTP surface as a pure function: (method, target,
    body) → (status, body bytes, content type). No sockets, no
    threads — both transports call this and nothing else, which is
    what makes the frontdoor-on/off parity a structural guarantee
    rather than a test hope."""

    def __init__(self, es: EsApi):
        self.es = es

    def handle(self, method: str, target: str,
               body: bytes = b"") -> tuple[int, bytes, str]:
        url = urlparse(target)
        parts = [p for p in url.path.split("/") if p]
        try:
            raw = body.decode() if isinstance(body, (bytes, bytearray)) \
                else (body or "")
            status, payload, ctype = self._route(
                method, parts, parse_qs(url.query), raw)
        except EsError as e:
            status, payload, ctype = e.status, e.body(), JSON_CTYPE
        except errors.SqlError as e:
            status, payload, ctype = 400, {"error": {
                "type": "sql_exception", "reason": e.message,
                "sqlstate": e.sqlstate}, "status": 400}, JSON_CTYPE
        except Exception as e:  # pragma: no cover
            log.error("http", f"internal error: {e!r}")
            status, payload, ctype = 500, {
                "error": {"type": "internal_error",
                          "reason": str(e)}, "status": 500}, JSON_CTYPE
        return status, encode_payload(payload), ctype

    # -- routing -----------------------------------------------------------

    def _route(self, method: str, p: list[str], q: dict,
               body: str) -> tuple[int, object, str]:
        es = self.es
        if not p:
            return 200, {"name": "serenedb_tpu", "cluster_name":
                         "serenedb_tpu", "version": {"number": "8.0.0"},
                         "tagline": "You Know, for Search"}, JSON_CTYPE
        if p[0] == "_cluster" and len(p) > 1 and p[1] == "health":
            return 200, es.cluster_health(), JSON_CTYPE
        if p[0] == "trace" and method == "GET" and \
                (len(p) == 1 or
                 (len(p) == 2 and (p[1] == "last" or p[1].isdigit()))):
            # flight-recorder timelines as Chrome trace-event JSON:
            # /trace lists recorded entries, /trace/<id> (or
            # /trace/last) returns one timeline loadable in Perfetto /
            # chrome://tracing. Deliberately NARROW (exact /trace, or a
            # numeric/last second segment, GET only) so an ES index
            # named "trace" keeps its whole /trace/_search, /trace/_doc
            # ... API surface — the same tradeoff as /metrics above.
            from ..obs.trace import FLIGHT, chrome_trace, flight_summary
            if len(p) == 1:
                return 200, [flight_summary(e)
                             for e in FLIGHT.snapshot()], JSON_CTYPE
            entry = FLIGHT.last() if p[1] == "last" \
                else FLIGHT.get(int(p[1]))
            if entry is None:
                raise EsError(404, "resource_not_found_exception",
                              f"no recorded trace [{p[1]}] (the "
                              "flight recorder keeps the last "
                              "serene_flight_recorder_queries "
                              "completed queries)")
            return 200, chrome_trace(entry), JSON_CTYPE
        if p == ["device"] and method == "GET":
            # device telemetry (obs/device.py): per-device dispatch /
            # transfer / HBM-estimate rows, the XLA compile ledger and
            # cache summaries. Exactly GET /device — deeper paths still
            # reach the ES API for an index of that name (the /metrics
            # tradeoff).
            from ..obs.device import stats_section
            return 200, stats_section(), JSON_CTYPE
        if p == ["progress"] and method == "GET":
            # live query progress (sdb_query_progress as JSON): one
            # object per running statement with its current operator,
            # morsel/row/byte counters and accounted live/peak bytes.
            # Exactly GET /progress — deeper paths still reach the ES
            # API for an index of that name (the /metrics tradeoff).
            from ..obs.resources import ACTIVE
            return 200, ACTIVE.snapshot(), JSON_CTYPE
        if p == ["metrics"] and method == "GET":
            # Prometheus exposition: the whole gauge registry (one
            # consistent snapshot) + per-statement series (obs/export).
            # Exactly /metrics — deeper paths (/metrics/_doc/1) still
            # reach the ES API for an index of that name.
            from ..obs.export import prometheus_text
            return 200, prometheus_text(), \
                "text/plain; version=0.0.4; charset=utf-8"
        if p[0] == "_cat" and len(p) > 1:
            if p[1] == "indices":
                rows = es.cat_indices()
            elif p[1] == "health":
                rows = es.cat_health()
            elif p[1] == "count":
                rows = es.cat_count(p[2] if len(p) > 2 else None)
            else:
                raise EsError(400, "illegal_argument_exception",
                              f"unknown _cat endpoint [{p[1]}]")
            if "format" in q and q["format"][0] == "json":
                return 200, rows, JSON_CTYPE
            if p[1] == "indices":
                # fixed 4-column layout — positional consumers rely on
                # docs.count being field 4
                text = "\n".join(
                    f"{r['health']} {r['status']} {r['index']} "
                    f"{r['docs.count']}" for r in rows) + "\n"
            else:
                text = "\n".join(" ".join(str(v) for v in r.values())
                                 for r in rows) + "\n"
            return 200, text, "text/plain"
        if p[0] == "_msearch" and method == "POST":
            return 200, es.msearch(body), JSON_CTYPE
        if p[0] == "_analyze" and method in ("GET", "POST"):
            return 200, es.analyze(_json_body(body)), JSON_CTYPE
        if p[0] == "_bulk" and method == "POST":
            return 200, es.bulk(body), JSON_CTYPE
        if p[0] == "_search" and len(p) > 1 and p[1] == "scroll":
            b = _json_body(body) or {}
            if method == "DELETE":
                return 200, es.delete_scroll(
                    b.get("scroll_id", [])), JSON_CTYPE
            size = b.get("size")
            sid = b.get("scroll_id", "")
            if isinstance(sid, list):
                sid = sid[0] if sid else ""
            return 200, es.search_scroll_next(
                str(sid), int(size) if size is not None else None,
                b.get("scroll")), JSON_CTYPE
        if p[0] == "_stats":
            # ES index stats, extended with the engine's observability
            # section (gauge snapshot + sdb_stat_statements) — ES
            # clients read _all/indices and ignore the extra keys
            from ..obs.export import stats_json
            payload = es.stats()
            payload.update(stats_json())
            return 200, payload, JSON_CTYPE
        if p[0] == "_mget" and method == "POST":
            b = _json_body(body) or {}
            return 200, es.mget(b.get("index"), b), JSON_CTYPE
        if p[0] == "_sql" and method == "POST":
            b = _json_body(body) or {}
            # fresh connection per request: /_sql session state (BEGIN,
            # SET, failed-txn) must never poison the shared API connection
            conn = es.db.connect()
            res = conn.execute(b.get("query", ""))
            return 200, {
                "columns": [{"name": n} for n in res.names],
                "rows": [list(r) for r in res.rows()]}, JSON_CTYPE
        if p[0] == "_test" and len(p) > 1:
            return self._test_endpoint(method, p[1:], q, body)
        if p[0].startswith("_"):
            raise EsError(400, "illegal_argument_exception",
                          f"unknown endpoint [{p[0]}]")

        index = p[0]
        rest = p[1:]
        if not rest:
            if method == "PUT":
                return 200, es.create_index(index, _json_body(body)), \
                    JSON_CTYPE
            if method == "DELETE":
                return 200, es.delete_index(index), JSON_CTYPE
            if method == "HEAD":
                return (200 if es.exists(index) else 404), "", JSON_CTYPE
            if method == "GET":
                return 200, es.mapping(index), JSON_CTYPE
            raise EsError(405, "method_not_allowed",
                          f"{method} not allowed on /{index}")
        verb = rest[0]
        if verb == "_doc":
            if method in ("PUT", "POST"):
                doc = _json_body(body) or {}
                doc_id = rest[1] if len(rest) > 1 else None
                return 201, es.index_doc(index, doc, doc_id), JSON_CTYPE
            if method == "GET" and len(rest) > 1:
                r = es.get_doc(index, rest[1])
                return (200 if r.get("found") else 404), r, JSON_CTYPE
            if method == "DELETE" and len(rest) > 1:
                return 200, es.delete_doc(index, rest[1]), JSON_CTYPE
            raise EsError(405, "method_not_allowed",
                          f"{method} on _doc requires an id")
        if verb == "_delete_by_query" and method == "POST":
            return 200, es.delete_by_query(index, _json_body(body)), \
                JSON_CTYPE
        if verb == "_update" and method == "POST" and len(rest) > 1:
            return 200, es.update_doc(index, rest[1],
                                      _json_body(body) or {}), JSON_CTYPE
        if verb == "_search":
            b = _json_body(body)
            if "scroll" in q:
                return 200, es.search_scroll_start(
                    index, b, q["scroll"][0]), JSON_CTYPE
            return 200, es.search(index, b), JSON_CTYPE
        if verb == "_mget" and method == "POST":
            return 200, es.mget(index, _json_body(body) or {}), JSON_CTYPE
        if verb == "_msearch" and method == "POST":
            return 200, es.msearch(body, default_index=index), JSON_CTYPE
        if verb == "_analyze" and method in ("GET", "POST"):
            return 200, es.analyze(_json_body(body), index), JSON_CTYPE
        if verb == "_stats":
            return 200, es.stats(index), JSON_CTYPE
        if verb == "_count":
            return 200, es.count(index, _json_body(body)), JSON_CTYPE
        if verb == "_refresh":
            return 200, es.refresh(index), JSON_CTYPE
        if verb == "_mapping":
            return 200, es.mapping(index), JSON_CTYPE
        if verb == "_bulk" and method == "POST":
            # index-scoped bulk: inject default _index
            lines = []
            for ln in body.split("\n"):
                if not ln.strip():
                    continue
                obj = json.loads(ln)
                op = next(iter(obj))
                if op in ("index", "create", "delete", "update") and \
                        isinstance(obj[op], dict) and "_index" not in obj[op]:
                    obj[op]["_index"] = index
                lines.append(json.dumps(obj))
            return 200, es.bulk("\n".join(lines)), JSON_CTYPE
        raise EsError(400, "illegal_argument_exception",
                      f"unknown verb [{verb}]")

    def _test_endpoint(self, method: str, parts: list[str], q: dict,
                       body: str) -> tuple[int, object, str]:
        """Transport test endpoints (reference:
        server/network/http/test/handlers.h: /_test/{echo,ping,...})."""
        if parts[0] == "ping":
            return 200, {"ok": True}, JSON_CTYPE
        if parts[0] == "echo":
            return 200, body or "{}", JSON_CTYPE
        if parts[0] == "sleep":
            # deterministic slow handler for transport concurrency
            # tests (serialized-per-connection vs concurrent-across-
            # connections); capped so a stray client can't park an
            # executor thread for long
            ms = min(2000, int(q.get("ms", ["100"])[0]))
            time.sleep(ms / 1000.0)
            return 200, {"ok": True, "slept_ms": ms}, JSON_CTYPE
        raise EsError(404, "not_found", f"unknown test [{parts[0]}]")


class Handler(BaseHTTPRequestHandler):
    server_version = "serenedb-tpu/0.1"
    protocol_version = "HTTP/1.1"
    router: Router = None  # class attr set by LegacyHttpServer

    def log_message(self, fmt, *args):
        log.debug("http", fmt % args)

    def _body(self) -> bytes:
        ln = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(ln) if ln else b""

    def _dispatch(self, method: str):
        with metrics.HTTP_CONNECTIONS.scoped():
            status, data, ctype = self.router.handle(
                method, self.path, self._body())
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.send_header("X-Elastic-Product", "Elasticsearch")
            self.end_headers()
            self.wfile.write(data)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_PUT(self):
        self._dispatch("PUT")

    def do_DELETE(self):
        self._dispatch("DELETE")

    def do_HEAD(self):
        self._dispatch("HEAD")


class LegacyHttpServer:
    """stdlib ThreadingHTTPServer transport — one OS thread per
    connection. Kept ONE release as the parity oracle for the asyncio
    front door (`serene_frontdoor = off`); scheduled for removal once
    the frontdoor has soaked."""

    def __init__(self, db: Database, host: str = "127.0.0.1",
                 port: int = 0):
        self.db = db
        handler = type("BoundHandler", (Handler,),
                       {"router": Router(EsApi(db))})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="serene-http", daemon=True)
        self._thread.start()
        log.info("http", f"listening on port {self.port} (legacy "
                 "thread-per-connection tier)")

    def stop(self):
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=10)
            if self._thread.is_alive():  # pragma: no cover
                # the known legacy leak (a stuck per-connection thread
                # outlives shutdown) — loud, because the frontdoor was
                # built to make this impossible
                log.error("http", "legacy HTTP thread leaked past "
                          "shutdown (use serene_frontdoor=on)")
        self.httpd.server_close()


class HttpServer:
    """The facade every caller constructs: `serene_frontdoor` (GLOBAL,
    default on) picks the asyncio front door; off falls back to the
    legacy ThreadingHTTPServer parity oracle. Same constructor, same
    start()/stop()/.port surface either way."""

    def __init__(self, db: Database, host: str = "127.0.0.1",
                 port: int = 0):
        self.db = db
        if bool(_settings.get_global("serene_frontdoor")):
            from .frontdoor import FrontDoor
            self._impl = FrontDoor(db, host=host, http_port=port)
        else:
            self._impl = LegacyHttpServer(db, host, port)

    @property
    def port(self) -> int:
        return self._impl.port

    def start(self):
        self._impl.start()

    def stop(self):
        self._impl.stop()
