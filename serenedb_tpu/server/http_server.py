"""HTTP/1.1 server exposing the ES-compatible API (+ /_sql and health).

Reference analog: server/network/http/ (h1 codec + router with :param
patterns; SURVEY.md §2.2). stdlib ThreadingHTTPServer carries the protocol;
routing lives here.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .. import errors
from ..engine import Database
from ..utils import log, metrics
from .es_api import EsApi, EsError


class Handler(BaseHTTPRequestHandler):
    server_version = "serenedb-tpu/0.1"
    protocol_version = "HTTP/1.1"
    es: EsApi = None  # class attr set by serve()

    def log_message(self, fmt, *args):
        log.debug("http", fmt % args)

    # -- helpers -----------------------------------------------------------

    def _body(self) -> str:
        ln = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(ln).decode() if ln else ""

    def _json_body(self) -> Optional[dict]:
        raw = self._body()
        if not raw.strip():
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise EsError(400, "parsing_exception", f"invalid JSON: {e}")

    def _send(self, status: int, payload, content_type="application/json"):
        data = (json.dumps(payload) if not isinstance(payload, (str, bytes))
                else payload)
        if isinstance(data, str):
            data = data.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("X-Elastic-Product", "Elasticsearch")
        self.end_headers()
        self.wfile.write(data)

    def _dispatch(self, method: str):
        with metrics.HTTP_CONNECTIONS.scoped():
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            try:
                self._route(method, parts, parse_qs(url.query))
            except EsError as e:
                self._send(e.status, e.body())
            except errors.SqlError as e:
                self._send(400, {"error": {
                    "type": "sql_exception", "reason": e.message,
                    "sqlstate": e.sqlstate}, "status": 400})
            except Exception as e:  # pragma: no cover
                log.error("http", f"internal error: {e!r}")
                self._send(500, {"error": {"type": "internal_error",
                                           "reason": str(e)}, "status": 500})

    # -- routing -----------------------------------------------------------

    def _route(self, method: str, p: list[str], q: dict):
        es = self.es
        if not p:
            self._send(200, {"name": "serenedb_tpu", "cluster_name":
                             "serenedb_tpu", "version": {"number": "8.0.0"},
                             "tagline": "You Know, for Search"})
            return
        if p[0] == "_cluster" and len(p) > 1 and p[1] == "health":
            self._send(200, es.cluster_health())
            return
        if p[0] == "trace" and method == "GET" and \
                (len(p) == 1 or
                 (len(p) == 2 and (p[1] == "last" or p[1].isdigit()))):
            # flight-recorder timelines as Chrome trace-event JSON:
            # /trace lists recorded entries, /trace/<id> (or
            # /trace/last) returns one timeline loadable in Perfetto /
            # chrome://tracing. Deliberately NARROW (exact /trace, or a
            # numeric/last second segment, GET only) so an ES index
            # named "trace" keeps its whole /trace/_search, /trace/_doc
            # ... API surface — the same tradeoff as /metrics above.
            from ..obs.trace import FLIGHT, chrome_trace, flight_summary
            if len(p) == 1:
                self._send(200, [flight_summary(e)
                                 for e in FLIGHT.snapshot()])
                return
            entry = FLIGHT.last() if p[1] == "last" \
                else FLIGHT.get(int(p[1]))
            if entry is None:
                raise EsError(404, "resource_not_found_exception",
                              f"no recorded trace [{p[1]}] (the "
                              "flight recorder keeps the last "
                              "serene_flight_recorder_queries "
                              "completed queries)")
            self._send(200, chrome_trace(entry))
            return
        if p == ["device"] and method == "GET":
            # device telemetry (obs/device.py): per-device dispatch /
            # transfer / HBM-estimate rows, the XLA compile ledger and
            # cache summaries. Exactly GET /device — deeper paths still
            # reach the ES API for an index of that name (the /metrics
            # tradeoff).
            from ..obs.device import stats_section
            self._send(200, stats_section())
            return
        if p == ["progress"] and method == "GET":
            # live query progress (sdb_query_progress as JSON): one
            # object per running statement with its current operator,
            # morsel/row/byte counters and accounted live/peak bytes.
            # Exactly GET /progress — deeper paths still reach the ES
            # API for an index of that name (the /metrics tradeoff).
            from ..obs.resources import ACTIVE
            self._send(200, ACTIVE.snapshot())
            return
        if p == ["metrics"] and method == "GET":
            # Prometheus exposition: the whole gauge registry (one
            # consistent snapshot) + per-statement series (obs/export).
            # Exactly /metrics — deeper paths (/metrics/_doc/1) still
            # reach the ES API for an index of that name.
            from ..obs.export import prometheus_text
            self._send(200, prometheus_text(),
                       "text/plain; version=0.0.4; charset=utf-8")
            return
        if p[0] == "_cat" and len(p) > 1:
            if p[1] == "indices":
                rows = es.cat_indices()
            elif p[1] == "health":
                rows = es.cat_health()
            elif p[1] == "count":
                rows = es.cat_count(p[2] if len(p) > 2 else None)
            else:
                raise EsError(400, "illegal_argument_exception",
                              f"unknown _cat endpoint [{p[1]}]")
            if "format" in q and q["format"][0] == "json":
                self._send(200, rows)
            else:
                if p[1] == "indices":
                    # fixed 4-column layout — positional consumers rely on
                    # docs.count being field 4
                    text = "\n".join(
                        f"{r['health']} {r['status']} {r['index']} "
                        f"{r['docs.count']}" for r in rows) + "\n"
                else:
                    text = "\n".join(" ".join(str(v) for v in r.values())
                                     for r in rows) + "\n"
                self._send(200, text, "text/plain")
            return
        if p[0] == "_msearch" and method == "POST":
            self._send(200, es.msearch(self._body()))
            return
        if p[0] == "_analyze" and method in ("GET", "POST"):
            self._send(200, es.analyze(self._json_body()))
            return
        if p[0] == "_bulk" and method == "POST":
            self._send(200, es.bulk(self._body()))
            return
        if p[0] == "_search" and len(p) > 1 and p[1] == "scroll":
            body = self._json_body() or {}
            if method == "DELETE":
                self._send(200, es.delete_scroll(
                    body.get("scroll_id", [])))
            else:
                size = body.get("size")
                sid = body.get("scroll_id", "")
                if isinstance(sid, list):
                    sid = sid[0] if sid else ""
                self._send(200, es.search_scroll_next(
                    str(sid),
                    int(size) if size is not None else None,
                    body.get("scroll")))
            return
        if p[0] == "_stats":
            # ES index stats, extended with the engine's observability
            # section (gauge snapshot + sdb_stat_statements) — ES
            # clients read _all/indices and ignore the extra keys
            from ..obs.export import stats_json
            payload = es.stats()
            payload.update(stats_json())
            self._send(200, payload)
            return
        if p[0] == "_mget" and method == "POST":
            body = self._json_body() or {}
            self._send(200, es.mget(body.get("index"), body))
            return
        if p[0] == "_sql" and method == "POST":
            body = self._json_body() or {}
            # fresh connection per request: /_sql session state (BEGIN,
            # SET, failed-txn) must never poison the shared API connection
            conn = es.db.connect()
            res = conn.execute(body.get("query", ""))
            self._send(200, {
                "columns": [{"name": n} for n in res.names],
                "rows": [list(r) for r in res.rows()]})
            return
        if p[0] == "_test" and len(p) > 1:
            self._test_endpoint(method, p[1:])
            return
        if p[0].startswith("_"):
            raise EsError(400, "illegal_argument_exception",
                          f"unknown endpoint [{p[0]}]")

        index = p[0]
        rest = p[1:]
        if not rest:
            if method == "PUT":
                self._send(200, es.create_index(index, self._json_body()))
            elif method == "DELETE":
                self._send(200, es.delete_index(index))
            elif method == "HEAD":
                self._send(200 if es.exists(index) else 404, "")
            elif method == "GET":
                self._send(200, es.mapping(index))
            else:
                raise EsError(405, "method_not_allowed",
                              f"{method} not allowed on /{index}")
            return
        verb = rest[0]
        if verb == "_doc":
            if method in ("PUT", "POST"):
                doc = self._json_body() or {}
                doc_id = rest[1] if len(rest) > 1 else None
                self._send(201, es.index_doc(index, doc, doc_id))
            elif method == "GET" and len(rest) > 1:
                r = es.get_doc(index, rest[1])
                self._send(200 if r.get("found") else 404, r)
            elif method == "DELETE" and len(rest) > 1:
                self._send(200, es.delete_doc(index, rest[1]))
            else:
                raise EsError(405, "method_not_allowed",
                              f"{method} on _doc requires an id")
            return
        if verb == "_delete_by_query" and method == "POST":
            self._send(200, es.delete_by_query(index, self._json_body()))
            return
        if verb == "_update" and method == "POST" and len(rest) > 1:
            self._send(200, es.update_doc(index, rest[1],
                                          self._json_body() or {}))
            return
        if verb == "_search":
            body = self._json_body()
            if "scroll" in q:
                self._send(200, es.search_scroll_start(
                    index, body, q["scroll"][0]))
            else:
                self._send(200, es.search(index, body))
            return
        if verb == "_mget" and method == "POST":
            self._send(200, es.mget(index, self._json_body() or {}))
            return
        if verb == "_msearch" and method == "POST":
            self._send(200, es.msearch(self._body(), default_index=index))
            return
        if verb == "_analyze" and method in ("GET", "POST"):
            self._send(200, es.analyze(self._json_body(), index))
            return
        if verb == "_stats":
            self._send(200, es.stats(index))
            return
        if verb == "_count":
            self._send(200, es.count(index, self._json_body()))
            return
        if verb == "_refresh":
            self._send(200, es.refresh(index))
            return
        if verb == "_mapping":
            self._send(200, es.mapping(index))
            return
        if verb == "_bulk" and method == "POST":
            # index-scoped bulk: inject default _index
            lines = []
            for ln in self._body().split("\n"):
                if not ln.strip():
                    continue
                obj = json.loads(ln)
                op = next(iter(obj))
                if op in ("index", "create", "delete", "update") and \
                        isinstance(obj[op], dict) and "_index" not in obj[op]:
                    obj[op]["_index"] = index
                lines.append(json.dumps(obj))
            self._send(200, es.bulk("\n".join(lines)))
            return
        raise EsError(400, "illegal_argument_exception",
                      f"unknown verb [{verb}]")

    def _test_endpoint(self, method: str, parts: list[str]):
        """Transport test endpoints (reference:
        server/network/http/test/handlers.h: /_test/{echo,ping,...})."""
        if parts[0] == "ping":
            self._send(200, {"ok": True})
        elif parts[0] == "echo":
            self._send(200, self._body() or "{}")
        else:
            raise EsError(404, "not_found", f"unknown test [{parts[0]}]")

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_PUT(self):
        self._dispatch("PUT")

    def do_DELETE(self):
        self._dispatch("DELETE")

    def do_HEAD(self):
        self._dispatch("HEAD")


class HttpServer:
    def __init__(self, db: Database, host: str = "127.0.0.1", port: int = 0):
        self.db = db
        handler = type("BoundHandler", (Handler,), {"es": EsApi(db)})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="serene-http", daemon=True)
        self._thread.start()
        log.info("http", f"listening on port {self.port}")

    def stop(self):
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=10)
        self.httpd.server_close()
