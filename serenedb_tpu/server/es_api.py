"""Elasticsearch-compatible REST API.

Reference analog: server/network/http/es/ — `_bulk`, `_doc`, `_search`
(+DSL→engine translation), `_count`, `_cat/*`, `_cluster/*`, `_mapping`,
`_refresh` (handlers.cpp:1383-1458, dsl.cpp; SURVEY.md §2.2).

Model: an ES index is a table whose columns grow dynamically from indexed
documents (`_id` TEXT + `_source` TEXT + one column per scalar field);
text fields get inverted indexes and the DSL translates onto the engine's
search surface (match → `@@` OR-query, match_phrase → `##`, bool →
AND/OR/NOT, range/term → SQL predicates) with BM25 scores.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Any, Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column
from ..engine import Connection, Database, MemTable, StoredTable


class EsError(Exception):
    def __init__(self, status: int, kind: str, reason: str):
        super().__init__(reason)
        self.status = status
        self.kind = kind
        self.reason = reason

    def body(self) -> dict:
        return {"error": {"type": self.kind, "reason": self.reason},
                "status": self.status}


class EsApi:
    def __init__(self, db: Database):
        self.db = db
        self.conn = db.connect()
        # reentrant: update_doc holds it across a read-merge-write
        # while _index_doc_locked may re-enter via create_index
        self._lock = threading.RLock()
        self._scrolls: dict[str, dict] = {}
        #: per-thread READ connections (see _rconn)
        self._tl = threading.local()

    def _rconn(self) -> Connection:
        """Per-thread read connection for the search paths. Concurrent
        _search/_msearch items run on server and worker-pool threads, and
        a Connection carries per-statement session state (the
        CURRENT_CONNECTION contextvar target, now() stability, cancel
        flag) — sharing self.conn across threads would race it. Reads get
        a thread-cached connection instead; writes keep self.conn under
        self._lock. Thread count is bounded (pool workers + HTTP handler
        threads), and dead threads' connections retire via their weakref
        finalizers."""
        conn = getattr(self._tl, "conn", None)
        if conn is None:
            conn = self._tl.conn = self.db.connect()
        return conn

    # -- index management --------------------------------------------------

    def _table(self, index: str, create: bool = False) -> MemTable:
        key = index.lower()
        with self.db.lock:
            t = self.db.schemas["main"].tables.get(key)
        if t is None:
            if not create:
                raise EsError(404, "index_not_found_exception",
                              f"no such index [{index}]")
            self.create_index(index)
            with self.db.lock:
                t = self.db.schemas["main"].tables.get(key)
        return t

    def create_index(self, index: str, body: Optional[dict] = None) -> dict:
        if not re.match(r"^[a-z][a-z0-9_\-]*$", index):
            raise EsError(400, "invalid_index_name_exception",
                          f"invalid index name [{index}]")
        with self._lock:
            try:
                self.conn.execute(
                    f'CREATE TABLE "{index}" ("_id" TEXT, "_source" TEXT)')
            except errors.SqlError as e:
                if e.sqlstate == errors.DUPLICATE_TABLE:
                    raise EsError(400, "resource_already_exists_exception",
                                  f"index [{index}] already exists")
                raise
            props = ((body or {}).get("mappings", {}) or {}) \
                .get("properties", {}) or {}
            t = self._table(index)
            for fname, fdef in props.items():
                ftype = (fdef or {}).get("type", "text")
                self._ensure_column(t, fname, _es_type_to_sql(ftype),
                                    text_index=(ftype != "dense_vector"))
                if ftype == "dense_vector":
                    dims = int((fdef or {}).get("dims", 0))
                    opts = f" WITH (dim = {dims})" if dims else ""
                    self.conn.execute(
                        f'CREATE INDEX ON {_ident(t.name)} USING ivf '
                        f'({_ident(fname)}){opts}')
        return {"acknowledged": True, "shards_acknowledged": True,
                "index": index}

    def delete_index(self, index: str) -> dict:
        self._table(index)
        self.conn.execute(f'DROP TABLE "{index}"')
        return {"acknowledged": True}

    def exists(self, index: str) -> bool:
        try:
            self._table(index)
            return True
        except EsError:
            return False

    def mapping(self, index: str) -> dict:
        t = self._table(index)
        props = {}
        for name, typ in zip(t.column_names, t.column_types):
            if name.startswith("_"):
                continue
            props[name] = {"type": _sql_type_to_es(typ)}
        return {index: {"mappings": {"properties": props}}}

    def _ensure_column(self, t: MemTable, name: str, typ: dt.SqlType,
                       text_index: bool = True):
        if name in t.column_names:
            return
        # quiesced([t]) — not db.lock — excludes concurrent DML writers
        # of THIS table: a read-modify-write under db.lock alone would
        # republish a stale batch over rows an insert just committed
        with self.db.quiesced([t]):
            full = t.full_batch()
            if name in full.names:
                return
            col = Column.from_pylist([None] * full.num_rows, typ)
            t.replace(Batch(list(full.names) + [name],
                            list(full.columns) + [col]),
                      rows_preserved=True)
        if text_index and typ.is_string and not name.startswith("_"):
            # text fields get inverted indexes so match/bm25 use the TPU
            # scoring path (refreshed by maintenance / _refresh)
            try:
                self.conn.execute(
                    f'CREATE INDEX ON "{t.name}" USING inverted ("{name}")')
            except errors.SqlError:
                pass
            if isinstance(t, StoredTable) and self.db.store is not None:
                from ..storage.store import table_def
                key = t.key
                tdef = table_def(key, t.table_id, t.column_names,
                                 t.column_types, getattr(t, "table_meta", {}),
                                 self.db.store.ticks.current())
                self.db.store.write_snapshot(t.table_id, t.full_batch())
                tdef["checkpoint_tick"] = self.db.store.ticks.current()
                self.db.store.update_meta(
                    lambda m: m["tables"].__setitem__(key, tdef))

    # -- document indexing -------------------------------------------------

    def index_doc(self, index: str, doc: dict,
                  doc_id: Optional[str] = None) -> dict:
        with self._lock:
            return self._index_doc_locked(index, doc, doc_id)

    def _index_doc_locked(self, index: str, doc: dict,
                          doc_id: Optional[str] = None) -> dict:
        """index_doc body; caller holds self._lock."""
        t = self._table(index, create=True)
        doc_id = doc_id or _gen_id()
        self._delete_by_id(t, doc_id)
        row = {"_id": doc_id, "_source": json.dumps(doc)}
        for k, v in doc.items():
            if isinstance(v, list) and v and \
                    all(isinstance(x, (int, float)) and
                        not isinstance(x, bool) for x in v):
                # numeric arrays = dense vectors, stored as JSON text
                self._ensure_column(t, k, dt.VARCHAR, text_index=False)
                row[k] = json.dumps(v)
                continue
            if isinstance(v, (dict, list)):
                continue  # other objects/arrays live in _source only
            self._ensure_column(t, k, _value_sql_type(v))
            row[k] = v
        incoming = Batch.from_pydict(
            {name: [row.get(name)] for name in t.column_names})
        self.conn._insert_batch(t, incoming)
        return {"_index": index, "_id": doc_id, "result": "created",
                "_version": 1, "_shards": {"total": 1, "successful": 1,
                                           "failed": 0}}

    def update_doc(self, index: str, doc_id: str, body: dict) -> dict:
        """_update: partial-document merge, script-free (reference: the ES
        update action). `doc` merges into the existing source; a missing
        doc falls back to `upsert` (or 404 without one);
        doc_as_upsert=true uses `doc` for both. Read-merge-write runs
        under one lock so concurrent updates never lose fields."""
        if not isinstance(body, dict):
            raise EsError(400, "parsing_exception",
                          "_update body must be a JSON object")
        partial = body.get("doc")
        upsert = body.get("upsert")
        if partial is not None and not isinstance(partial, dict):
            raise EsError(400, "parsing_exception",
                          "_update doc must be a JSON object")
        if upsert is not None and not isinstance(upsert, dict):
            raise EsError(400, "parsing_exception",
                          "_update upsert must be a JSON object")
        if partial is None and upsert is None:
            raise EsError(400, "illegal_argument_exception",
                          "_update requires doc or upsert")
        can_create = upsert is not None or bool(body.get("doc_as_upsert"))
        self._table(index, create=can_create)   # 404 unless upserting
        with self._lock:
            existing = self.get_doc(index, doc_id)
            if existing.get("found"):
                merged = dict(existing["_source"])
                merged.update(partial or {})
                result = "updated"
                if merged == existing["_source"]:
                    result = "noop"
            elif body.get("doc_as_upsert") and partial is not None:
                merged = dict(partial)
                result = "created"
            elif upsert is not None:
                merged = dict(upsert)
                result = "created"
            else:
                raise EsError(404, "document_missing_exception",
                              f"[{doc_id}]: document missing")
            if result != "noop":
                self._index_doc_locked(index, merged, doc_id)
        return {"_index": index, "_id": doc_id, "result": result,
                "_version": 1,
                "_shards": {"total": 1,
                            "successful": 0 if result == "noop" else 1,
                            "failed": 0}}

    def get_doc(self, index: str, doc_id: str) -> dict:
        t = self._table(index)
        full = t.full_batch(["_id", "_source"])
        ids = full.column("_id").to_pylist()
        try:
            i = ids.index(doc_id)
        except ValueError:
            return {"_index": index, "_id": doc_id, "found": False}
        return {"_index": index, "_id": doc_id, "found": True,
                "_source": json.loads(full.column("_source").decode(i))}

    def delete_doc(self, index: str, doc_id: str) -> dict:
        t = self._table(index)
        with self._lock:
            n = self._delete_by_id(t, doc_id)
        return {"_index": index, "_id": doc_id,
                "result": "deleted" if n else "not_found"}

    def _delete_by_id(self, t: MemTable, doc_id: str) -> int:
        esc = doc_id.replace("'", "''")
        res = self.conn.execute(
            f'DELETE FROM "{t.name}" WHERE "_id" = \'{esc}\'')
        return int(res.command_tag.split()[-1])

    def bulk(self, body: str) -> dict:
        lines = [ln for ln in body.split("\n") if ln.strip()]
        items = []
        had_errors = False
        i = 0
        while i < len(lines):
            action = json.loads(lines[i])
            i += 1
            op = next(iter(action))
            meta = action[op] if isinstance(action[op], dict) else {}
            index = meta.get("_index")
            doc_id = meta.get("_id")
            # consume the doc line BEFORE validation so a failed item never
            # desyncs the ndjson stream
            doc_line = None
            if op in ("index", "create", "update") and i < len(lines):
                doc_line = lines[i]
                i += 1
            try:
                if index is not None and \
                        not re.match(r"^[a-z][a-z0-9_\-]*$", str(index)):
                    raise EsError(400, "invalid_index_name_exception",
                                  f"invalid index name [{index}]")
                if op in ("index", "create"):
                    doc = json.loads(doc_line)
                    r = self.index_doc(index, doc, doc_id)
                    items.append({op: {**r, "status": 201}})
                elif op == "delete":
                    r = self.delete_doc(index, doc_id)
                    items.append({op: {**r, "status": 200}})
                elif op == "update":
                    r = self.update_doc(index, doc_id,
                                        json.loads(doc_line))
                    items.append({op: {**r, "status": 200}})
                else:
                    raise EsError(400, "illegal_argument_exception",
                                  f"unknown bulk op [{op}]")
            except EsError as e:
                had_errors = True
                items.append({op: {"_index": index, "_id": doc_id,
                                   "status": e.status,
                                   "error": e.body()["error"]}})
            except errors.SqlError as e:
                # per-item failure, never abort a partially-applied batch
                had_errors = True
                items.append({op: {"_index": index, "_id": doc_id,
                                   "status": 400,
                                   "error": {"type": "mapper_parsing_exception",
                                             "reason": e.message}}})
        return {"took": 1, "errors": had_errors, "items": items}

    # -- search ------------------------------------------------------------

    def delete_by_query(self, index: str, body: Optional[dict]) -> dict:
        """_delete_by_query: DSL → DELETE (reference: the ES task-based
        deletion; ours is synchronous). max_docs caps the deletion by
        _id order."""
        t = self._table(index)
        body = body or {}
        if not isinstance(body, dict):
            raise EsError(400, "parsing_exception",
                          "_delete_by_query body must be a JSON object")
        q = body.get("query")
        if q is None:
            raise EsError(400, "parsing_exception",
                          "_delete_by_query requires a query")
        where, _ = self._translate_query(q)
        max_docs = body.get("max_docs")
        with self._lock:
            if max_docs is not None:
                # cap via an id subselect (deterministic by _id order)
                inner = f'SELECT "_id" FROM {_ident(t.name)}'
                if where:
                    inner += f" WHERE {where}"
                inner += f' ORDER BY "_id" LIMIT {int(max_docs)}'
                sql = (f'DELETE FROM {_ident(t.name)} WHERE "_id" IN '
                       f"({inner})")
            else:
                sql = f"DELETE FROM {_ident(t.name)}"
                if where:
                    sql += f" WHERE {where}"
            res = self.conn.execute(sql)
        deleted = int(res.command_tag.split()[-1])
        return {"took": 1, "timed_out": False, "total": deleted,
                "deleted": deleted, "failures": []}

    def refresh(self, index: Optional[str] = None) -> dict:
        self.conn.execute(f'VACUUM REFRESH "{index}"' if index
                          else "VACUUM REFRESH")
        return {"_shards": {"total": 1, "successful": 1, "failed": 0}}

    def count(self, index: str, body: Optional[dict] = None) -> dict:
        self._table(index)  # 404 for unknown index, not a SQL error
        where, _ = self._translate_query((body or {}).get("query"))
        sql = f'SELECT count(*) FROM "{index}"'
        if where:
            sql += f" WHERE {where}"
        n = self._rconn().execute(sql).scalar()
        return {"count": int(n),
                "_shards": {"total": 1, "successful": 1, "failed": 0}}

    def search(self, index: str, body: Optional[dict] = None) -> dict:
        body = body or {}
        t = self._table(index)
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        if "knn" in body:
            return self._search_knn(index, body, size, from_)
        if "_id" not in t.column_names or "_source" not in t.column_names:
            # a plain SQL table is not an ES document index — surface a
            # clear contract error instead of a cryptic 42703
            raise EsError(
                400, "illegal_argument_exception",
                f"[{index}] is a SQL table, not an ES document index — "
                "query it over the PG wire, or ingest documents through "
                "the ES API (_doc/_bulk) to search here")
        where, score_col = self._translate_query(body.get("query"))
        multi_claims = score_col if isinstance(score_col, list) else None
        cols = '"_id", "_source"'
        order = ""
        if score_col and multi_claims is None:
            cols += f", {score_col} AS _score"
            order = " ORDER BY _score DESC"
        sort = body.get("sort")
        if sort:
            order = " ORDER BY " + ", ".join(_sort_clause(s) for s in sort)
            multi_claims = None     # explicit sort: no score ordering
        sql = f'SELECT {cols} FROM "{index}"'
        if where:
            sql += f" WHERE {where}"
        if multi_claims is not None:
            # multi-field scoring, rank-first (Lucene BooleanQuery: doc
            # score = sum of its matching clauses' scores): one scored
            # pass per claim builds the score map, then the page is
            # assembled with BOUNDED fetches — scored candidates probe
            # WHERE membership in rank-ordered chunks with early exit,
            # and the zero-score tail pages through ORDER BY/LIMIT. No
            # whole-table id fetch, whatever the index size.
            scores: dict[str, float] = {}
            for f, w, pred in multi_claims:
                pass_sql = (f'SELECT "_id", bm25({_ident(f)}) '
                            f'FROM "{index}" WHERE {pred}')
                for did, sc in self._rconn().execute(pass_sql).rows():
                    if sc:
                        scores[did] = scores.get(did, 0.0) + w * float(sc)
            total_sql = f'SELECT count(*) FROM "{index}"'
            if where:
                total_sql += f" WHERE {where}"
            total = int(self._rconn().execute(total_sql).scalar())
            page = self._multi_claim_page(index, where, scores,
                                          from_ + size)[from_:from_ + size]
            rows = []
            if page:
                lits = ", ".join(_sql_str(d) for d in page)
                src = dict(self._rconn().execute(
                    f'SELECT "_id", "_source" FROM "{index}" '
                    f'WHERE "_id" IN ({lits})').rows())
                rows = [(d, src.get(d), scores.get(d, 0.0)) for d in page]
            score_col = "multi"
        else:
            sql += order + f" LIMIT {size} OFFSET {from_}"
            rows = list(self._rconn().execute(sql).rows())
            total_sql = f'SELECT count(*) FROM "{index}"'
            if where:
                total_sql += f" WHERE {where}"
            total = int(self._rconn().execute(total_sql).scalar())
        hits = []
        max_score = 0.0
        for row in rows:
            score = float(row[2]) if score_col and len(row) > 2 and \
                row[2] is not None else 1.0
            max_score = max(max_score, score)
            hits.append({"_index": index, "_id": row[0], "_score": score,
                         "_source": json.loads(row[1]) if row[1] else {}})
        return {
            "took": 1, "timed_out": False,
            "_shards": {"total": 1, "successful": 1, "skipped": 0,
                        "failed": 0},
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max_score if hits else None,
                     "hits": hits},
        }

    def _multi_claim_page(self, index: str, where: str,
                          scores: dict[str, float],
                          needed: int) -> list[str]:
        """First `needed` WHERE-matching ids in (-score, id) order,
        fetched boundedly: positive-scored candidates are membership-
        checked in rank-ordered chunks (early exit once the page is
        covered), the zero-score middle pages via ORDER BY "_id" LIMIT,
        and negative-scored candidates close the ranking."""
        ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
        pos = [d for d, s in ranked if s > 0]
        neg = [d for d, s in ranked if s < 0]

        def matching(cands: list, stop_at) -> list:
            out: list[str] = []
            for i in range(0, len(cands), 500):
                if stop_at is not None and len(out) >= stop_at:
                    break
                chunk = cands[i:i + 500]
                cond = '"_id" IN (%s)' % ", ".join(
                    _sql_str(d) for d in chunk)
                if where:
                    cond = f"({where}) AND {cond}"
                hit = {r[0] for r in self._rconn().execute(
                    f'SELECT "_id" FROM "{index}" WHERE {cond}').rows()}
                out.extend(d for d in chunk if d in hit)
            return out

        head = matching(pos, needed)
        if len(head) >= needed:
            return head[:needed]
        # ids whose accumulated score is exactly 0.0 (zero boosts) rank
        # with the unscored tail — they must stay IN the ORDER BY window
        scored_set = {d for d, s in scores.items() if s != 0.0}
        rest = needed - len(head)
        mid_sql = f'SELECT "_id" FROM "{index}"'
        if where:
            mid_sql += f" WHERE {where}"
        # over-fetch by the candidate count: every scored id that sneaks
        # into the window gets filtered back out client-side
        mid_sql += f' ORDER BY "_id" LIMIT {rest + len(scored_set)}'
        mid = [r[0] for r in self._rconn().execute(mid_sql).rows()
               if r[0] not in scored_set][:rest]
        seq = head + mid
        if len(seq) < needed and neg:
            seq += matching(neg, needed - len(seq))
        return seq[:needed]

    def _search_knn(self, index: str, body: dict, size: int,
                    from_: int) -> dict:
        """kNN search, optionally hybrid with a text query via RRF fusion
        (reference BASELINE config 5: BM25 + kNN with RRF top-k)."""
        knn = body["knn"]
        field = knn.get("field")
        qvec = json.dumps(knn.get("query_vector", []))
        k = int(knn.get("k", size))
        cand = max(k, int(knn.get("num_candidates", k * 4)))
        dist = f"vec_l2({_ident(field)}, {_sql_str(qvec)})"
        # no IS NOT NULL guard: it would block the IvfScan pushdown, and
        # both paths already handle NULL vectors (valid mask / NULLS LAST)
        sql = (f'SELECT "_id", "_source", {dist} AS _dist FROM '
               f'{_ident(index)} '
               f"ORDER BY _dist LIMIT {cand}")
        nprobe = knn.get("nprobe")
        conn = self._rconn()
        if nprobe is not None:
            conn.execute(f"SET serene_nprobe = {int(nprobe)}")
        try:
            knn_rows = [r for r in conn.execute(sql).rows()
                        if r[2] is not None]
        finally:
            if nprobe is not None:
                # 0 = back to the sdb_nprobe / built-in default chain
                conn.execute("SET serene_nprobe = 0")
        knn_ranked = [(r[0], r[1]) for r in knn_rows]
        if body.get("query") is None:
            hits = []
            page = knn_ranked[:k][from_:from_ + size]
            for off, (doc_id, src) in enumerate(page):
                d = float(knn_rows[from_ + off][2])
                hits.append({"_index": index, "_id": doc_id,
                             "_score": 1.0 / (1.0 + d),
                             "_source": json.loads(src) if src else {}})
            return _hits_response(hits, min(len(knn_ranked), k))
        # hybrid: text query ranking + knn ranking → reciprocal rank fusion
        text_res = self.search(index, {"query": body["query"],
                                       "size": cand, "from": 0})
        text_ranked = [(h["_id"], json.dumps(h["_source"]))
                       for h in text_res["hits"]["hits"]]
        RRF_K = 60
        scores: dict[str, float] = {}
        sources: dict[str, str] = {}
        for rank, (doc_id, src) in enumerate(knn_ranked):
            scores[doc_id] = scores.get(doc_id, 0.0) + 1.0 / (RRF_K + rank + 1)
            sources[doc_id] = src
        for rank, (doc_id, src) in enumerate(text_ranked):
            scores[doc_id] = scores.get(doc_id, 0.0) + 1.0 / (RRF_K + rank + 1)
            sources[doc_id] = src
        fused = sorted(scores.items(), key=lambda kv: -kv[1])
        hits = []
        for doc_id, score in fused[from_:from_ + size]:
            src = sources[doc_id]
            hits.append({"_index": index, "_id": doc_id, "_score": score,
                         "_source": json.loads(src) if src else {}})
        return _hits_response(hits, len(fused))

    # -- scroll ------------------------------------------------------------
    # (reference: ES _search?scroll + _search/scroll continuation)

    def _parse_keepalive(self, keep: str) -> float:
        import re as _re
        m = _re.match(r"^(\d+)(ms|s|m|h)?$", keep or "")
        if not m:
            return 60.0
        mult = {"ms": 0.001, "s": 1, "m": 60, "h": 3600}.get(
            m.group(2) or "s", 1)
        return min(float(m.group(1)) * mult, 24 * 3600)

    def _prune_scrolls(self):
        import time as _time
        now = _time.monotonic()
        for sid in [s for s, st in self._scrolls.items()
                    if st["expires"] < now]:
            del self._scrolls[sid]

    def search_scroll_start(self, index: str, body: Optional[dict],
                            keep: str) -> dict:
        import time as _time
        body = dict(body or {})
        size = int(body.get("size", 10))
        t = self._table(index)
        # materialize the whole match set up front (scroll = deep
        # pagination: the window must cover every hit, not a cap)
        body["size"] = max(t.row_count(), 1)
        body["from"] = 0
        res = self.search(index, body)
        hits = res["hits"]["hits"]
        sid = _gen_id()
        with self._lock:
            self._prune_scrolls()
            self._scrolls[sid] = {
                "hits": hits[size:],
                "total": res["hits"]["total"]["value"],
                "size": size,
                "keep": self._parse_keepalive(keep),
                "expires": _time.monotonic() + self._parse_keepalive(keep)}
        res["hits"]["hits"] = hits[:size]
        res["_scroll_id"] = sid
        return res

    def search_scroll_next(self, scroll_id: str,
                           size: Optional[int] = None,
                           keep: Optional[str] = None) -> dict:
        import time as _time
        with self._lock:
            self._prune_scrolls()
            st = self._scrolls.get(scroll_id)
            if st is None:
                raise EsError(404, "search_context_missing_exception",
                              f"No search context found for id [{scroll_id}]")
            # an active continuation refreshes the keepalive (ES semantics)
            ttl = self._parse_keepalive(keep) if keep else st["keep"]
            st["expires"] = _time.monotonic() + ttl
            page_size = size if size is not None else st["size"]
            page = st["hits"][:page_size]
            st["hits"] = st["hits"][page_size:]
            total = st["total"]
        out = _hits_response(page, total)
        out["_scroll_id"] = scroll_id
        return out

    def delete_scroll(self, scroll_ids) -> dict:
        if isinstance(scroll_ids, str):
            scroll_ids = [scroll_ids]
        freed = 0
        with self._lock:
            for sid in scroll_ids:
                if self._scrolls.pop(str(sid), None) is not None:
                    freed += 1
        return {"succeeded": freed > 0, "num_freed": freed}

    def mget(self, index: Optional[str], body: dict) -> dict:
        """ES shapes: {"ids": [...]} (index-scoped) or
        {"docs": [{"_index": ..., "_id": ...}, ...]} (per-doc index)."""
        wanted: list[tuple[str, str]] = []       # (index, id)
        if body.get("ids") is not None:
            if index is None:
                raise EsError(400, "action_request_validation_exception",
                              "index is missing")
            wanted = [(index, str(i)) for i in body["ids"]]
        else:
            for d in body.get("docs", []):
                doc_index = d.get("_index", index)
                doc_id = d.get("_id")
                if doc_index is None or doc_id is None:
                    raise EsError(400,
                                  "action_request_validation_exception",
                                  "_index and _id are required in docs")
                wanted.append((str(doc_index), str(doc_id)))
        lookups: dict[str, dict] = {}
        for idx_name in {w[0] for w in wanted}:
            t = self._table(idx_name)
            full = t.full_batch(["_id", "_source"])
            lookups[idx_name] = dict(zip(full.column("_id").to_pylist(),
                                         full.column("_source").to_pylist()))
        docs = []
        for idx_name, doc_id in wanted:
            src = lookups[idx_name].get(doc_id)
            if src is not None or doc_id in lookups[idx_name]:
                docs.append({"_index": idx_name, "_id": doc_id,
                             "found": True,
                             "_source": json.loads(src or "{}")})
            else:
                docs.append({"_index": idx_name, "_id": doc_id,
                             "found": False})
        return {"docs": docs}

    def stats(self, index: Optional[str] = None) -> dict:
        if index is not None:
            self._table(index)   # 404 for unknown index
        out = {}
        with self.db.lock:
            tables = list(self.db.schemas["main"].tables.items())
        for name, t in tables:
            if "_id" not in t.column_names:
                continue
            if index is not None and name != index.lower():
                continue
            out[name] = {"primaries": {
                "docs": {"count": t.row_count(), "deleted": 0},
                "store": {"size_in_bytes": sum(
                    c.data.nbytes for c in t.full_batch().columns)}}}
        return {"_all": {"primaries": {"docs": {"count": sum(
            v["primaries"]["docs"]["count"] for v in out.values())}}},
            "indices": out}

    def msearch(self, body: str, default_index: Optional[str] = None) -> dict:
        """_msearch: ndjson header/body pairs. Per-item errors are inline
        (ES semantics: a bad item never fails the whole request). Reference
        analog: the multi-search REST action the bulk/_msearch clients use."""
        # keep line positions: an EMPTY header line is valid ES syntax
        # ("use defaults"), so blanks must not be stripped before pairing
        lines = body.split("\n")
        # pop only the empty element from the terminal newline — a blank
        # line elsewhere is an empty header (valid) or empty body (error)
        if lines and not lines[-1].strip():
            lines.pop()
        if len(lines) % 2:
            raise EsError(400, "parsing_exception",
                          "_msearch body must be header/body line pairs")
        # two phases: (1) parse every header/body pair serially — a
        # malformed item becomes its own inline error response without
        # touching its siblings; (2) execute the valid items CONCURRENTLY
        # on the shared worker pool, so their top-k scans arrive at the
        # search batcher together and coalesce into shared scoring
        # dispatches (search/batcher.py). run_item swallows per-item
        # failures into inline responses — exceptions never cross item
        # boundaries, so a poisoned body in a coalesced batch can't fail
        # the request or its siblings (the batcher additionally retries a
        # failed dispatch serially per query).
        items: list[tuple] = []   # ("q", index, query) | ("err", response)
        for i in range(0, len(lines), 2):
            try:
                header = json.loads(lines[i]) if lines[i].strip() else {}
                if not lines[i + 1].strip():
                    raise EsError(400, "parsing_exception",
                                  "_msearch search body must not be empty")
                query = json.loads(lines[i + 1])
                if not isinstance(header, dict) or not isinstance(query, dict):
                    raise EsError(400, "parsing_exception",
                                  "_msearch lines must be JSON objects")
                index = header.get("index", default_index)
                if not index:
                    raise EsError(400, "illegal_argument_exception",
                                  "no index specified for _msearch item")
                if isinstance(index, list):
                    if len(index) != 1:
                        raise EsError(400, "illegal_argument_exception",
                                      "multi-index _msearch items are not "
                                      "supported")
                    index = index[0]
                items.append(("q", str(index), query))
            except json.JSONDecodeError as e:
                items.append(("err", {"error": {
                    "type": "parsing_exception",
                    "reason": f"invalid JSON: {e}"}, "status": 400}))
            except EsError as e:
                items.append(("err", {"error": e.body()["error"],
                                      "status": e.status}))

        def run_item(item: tuple) -> dict:
            if item[0] == "err":
                return item[1]
            try:
                return {**self.search(item[1], item[2]), "status": 200}
            except EsError as e:
                return {"error": e.body()["error"], "status": e.status}
            except errors.SqlError as e:
                return {"error": {
                    "type": "sql_exception", "reason": e.message,
                    "sqlstate": e.sqlstate}, "status": 400}

        from ..parallel.pool import parallel_map
        responses = parallel_map(None, run_item, items)
        return {"took": 1, "responses": responses}

    def analyze(self, body: Optional[dict],
                default_index: Optional[str] = None) -> dict:
        """_analyze: run an analyzer over text and return the tokens
        (reference: the analyzer-introspection REST action). ES's
        "standard" maps to our "simple" (lowercase word split, no
        stemming)."""
        from ..search.analysis import dictionary_exists, get_analyzer
        body = body or {}
        if not isinstance(body, dict):
            raise EsError(400, "parsing_exception",
                          "_analyze body must be a JSON object")
        text = body.get("text", "")
        if isinstance(text, list):
            text = " ".join(str(t) for t in text)
        name = body.get("analyzer")
        if name is None and default_index is not None:
            # ES precedence: explicit analyzer > field's analyzer > index
            # default — resolve through the index's inverted indexes
            t = self._table(default_index)   # 404 for unknown index
            field = body.get("field")
            name = "text"
            for idx in getattr(t, "indexes", {}).values():
                fn = getattr(idx, "analyzer_name_for", None)
                if fn is None:
                    continue
                if field is not None:
                    if field in getattr(idx, "columns", ()):
                        name = fn(field)
                        break
                elif idx.columns:
                    name = fn(idx.columns[0])
                    break
        name = str(name if name is not None else "standard")
        if name == "standard" and not dictionary_exists("standard"):
            name = "simple"   # ES "standard" = lowercase word split
        try:
            an = get_analyzer(name)
        except errors.SqlError:
            raise EsError(400, "illegal_argument_exception",
                          f"failed to find global analyzer [{name}]")
        return {"tokens": [
            {"token": t.term, "start_offset": t.start,
             "end_offset": t.end, "type": "<ALPHANUM>",
             "position": t.position}
            for t in an.tokenize(str(text))]}

    def cat_health(self) -> list[dict]:
        h = self.cluster_health()
        return [{"cluster": h["cluster_name"], "status": h["status"],
                 "node.total": str(h["number_of_nodes"]),
                 "shards": str(h["active_shards"]),
                 "unassign": str(h["unassigned_shards"])}]

    def cat_count(self, index: Optional[str] = None) -> list[dict]:
        if index is not None:
            return [{"count": str(self._table(index).row_count())}]
        total = sum(int(r["docs.count"]) for r in self.cat_indices())
        return [{"count": str(total)}]

    def cat_indices(self) -> list[dict]:
        out = []
        with self.db.lock:
            tables = list(self.db.schemas["main"].tables.items())
        for name, t in tables:
            if "_id" not in t.column_names:
                continue
            out.append({"health": "green", "status": "open", "index": name,
                        "pri": "1", "rep": "0",
                        "docs.count": str(t.row_count())})
        return out

    def cluster_health(self) -> dict:
        return {"cluster_name": "serenedb_tpu", "status": "green",
                "timed_out": False, "number_of_nodes": 1,
                "number_of_data_nodes": 1, "active_primary_shards": 1,
                "active_shards": 1, "unassigned_shards": 0}

    # -- query DSL ---------------------------------------------------------

    def _translate_query(self, q: Optional[dict],
                         ) -> tuple[str, Optional[str]]:
        """DSL → (SQL where clause, score expression or None). Stateless
        per call: concurrent searches on server threads must not share
        translation state."""
        if q is None:
            return "", None
        score_fields: list = []     # (field, boost, predicate_sql) triples
        where = self._tr(q, score_fields)
        score = _score_expr(score_fields)
        return where, score

    def _tr(self, q: dict, score_fields: list[str]) -> str:
        if not isinstance(q, dict) or len(q) != 1:
            raise EsError(400, "parsing_exception", "malformed query")
        kind, body = next(iter(q.items()))
        if kind == "match_all":
            return "TRUE"
        if kind == "match":
            field, spec = next(iter(body.items()))
            text = spec.get("query") if isinstance(spec, dict) else spec
            op = (spec.get("operator", "or") if isinstance(spec, dict)
                  else "or").lower()
            terms = [w for w in re.findall(r"\w+", str(text))]
            joiner = " & " if op == "and" else " | "
            pred = _ts_query(field, joiner.join(terms) or '""')
            score_fields.append((field, 1.0, pred))
            return pred
        if kind == "match_phrase":
            field, spec = next(iter(body.items()))
            text = spec.get("query") if isinstance(spec, dict) else spec
            pred = f'{_ident(field)} ## {_sql_str(str(text))}'
            score_fields.append((field, 1.0, pred))
            return pred
        if kind == "query_string":
            field = body.get("default_field", "_all")
            query = body.get("query", "")
            if field == "_all":
                raise EsError(400, "parsing_exception",
                              "query_string requires default_field")
            from ..search.lucene import (LuceneError, lower_to_sql,
                                         parse_lucene)
            try:
                ast = parse_lucene(
                    str(query),
                    str(body.get("default_operator", "OR")))
                sql, claims = lower_to_sql(ast, field, _ident)
            except LuceneError as e:
                raise EsError(400, "parsing_exception", str(e))
            # boost-weighted score claims: each scoring text leaf carries
            # its own predicate, so multi-field queries can score via
            # per-claim passes (Lucene: score = sum of matching clauses)
            score_fields.extend(claims)
            return sql
        if kind == "term":
            field, spec = next(iter(body.items()))
            value = spec.get("value") if isinstance(spec, dict) else spec
            return f'{_ident(field)} = {_sql_lit(value)}'
        if kind == "terms":
            field, values = next(iter(body.items()))
            lits = ", ".join(_sql_lit(v) for v in values)
            return f'{_ident(field)} IN ({lits})'
        if kind == "range":
            field, spec = next(iter(body.items()))
            parts = []
            for op_name, sym in (("gt", ">"), ("gte", ">="), ("lt", "<"),
                                 ("lte", "<=")):
                if op_name in spec:
                    parts.append(f'{_ident(field)} {sym} {_sql_lit(spec[op_name])}')
            return "(" + " AND ".join(parts) + ")" if parts else "TRUE"
        if kind == "exists":
            return f'{_ident(body.get("field"))} IS NOT NULL'
        if kind == "bool":
            clauses = []
            for must in _as_list(body.get("must")) + \
                    _as_list(body.get("filter")):
                clauses.append(self._tr(must, score_fields))
            shoulds = [self._tr(s, score_fields) for s in _as_list(body.get("should"))]
            if shoulds:
                clauses.append("(" + " OR ".join(shoulds) + ")")
            for must_not in _as_list(body.get("must_not")):
                # prohibited clauses never score (ES occur semantics) —
                # and must not drag their fields into the multi-claim path
                clauses.append(f"NOT ({self._tr(must_not, [])})")
            return "(" + " AND ".join(clauses) + ")" if clauses else "TRUE"
        if kind == "prefix":
            field, spec = next(iter(body.items()))
            value = spec.get("value") if isinstance(spec, dict) else spec
            pred = _ts_query(field, f"{value}*")
            score_fields.append((field, 1.0, pred))
            return pred
        if kind == "ids":
            lits = ", ".join(_sql_lit(v) for v in body.get("values", []))
            return f'"_id" IN ({lits})'
        if kind == "geo_bounding_box":
            field, spec = _geo_field(kind, body)
            tl = _es_point(spec.get("top_left"))
            br = _es_point(spec.get("bottom_right"))
            left, top = tl
            right, bottom = br
            poly = (f"POLYGON(({left!r} {bottom!r}, {right!r} {bottom!r}, "
                    f"{right!r} {top!r}, {left!r} {top!r}, "
                    f"{left!r} {bottom!r}))")
            return f'ST_Contains({_sql_str(poly)}, {_ident(field)})'
        if kind == "geo_distance":
            dist_m = _es_distance_m(body.get("distance"))
            field, origin = _geo_field(kind, body, extra=("distance",))
            lon, lat = _es_point(origin)
            pt = f"POINT({lon!r} {lat!r})"
            return (f'ST_DWithin({_ident(field)}, {_sql_str(pt)}, '
                    f'{dist_m!r})')
        if kind == "geo_polygon":
            field, spec = _geo_field(kind, body)
            pts = [_es_point(p) for p in spec.get("points", [])]
            if len(pts) < 3:
                raise EsError(400, "parsing_exception",
                              "geo_polygon needs at least 3 points")
            if pts[0] != pts[-1]:
                pts.append(pts[0])
            ring = ", ".join(f"{lon!r} {lat!r}" for lon, lat in pts)
            return f'ST_Contains({_sql_str(f"POLYGON(({ring}))")}, ' \
                   f'{_ident(field)})'
        if kind == "geo_shape":
            field, spec = _geo_field(kind, body)
            shape = spec.get("shape") if isinstance(spec, dict) else None
            if shape is None:
                raise EsError(400, "parsing_exception",
                              "geo_shape requires a shape")
            relation = str(spec.get("relation", "intersects")).lower()
            fn = {"intersects": "ST_Intersects", "within": "ST_Within",
                  "contains": "ST_Contains",
                  "disjoint": "ST_Disjoint"}.get(relation)
            if fn is None:
                raise EsError(400, "parsing_exception",
                              f"unknown geo_shape relation [{relation}]")
            return (f'{fn}({_ident(field)}, '
                    f'{_sql_str(json.dumps(shape))})')
        raise EsError(400, "parsing_exception",
                      f"unsupported query type [{kind}]")


def _as_list(v) -> list:
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


_GEO_OPTION_KEYS = ("validation_method", "ignore_unmapped", "_name",
                    "boost", "distance_type")


def _geo_field(kind: str, body: dict, extra: tuple = ()) -> tuple:
    """The (field, spec) pair of a geo query, skipping ES option keys;
    missing/ambiguous field answers parsing_exception, not a 500."""
    if not isinstance(body, dict):
        raise EsError(400, "parsing_exception", f"malformed {kind}")
    fields = [(k, v) for k, v in body.items()
              if k not in _GEO_OPTION_KEYS and k not in extra]
    if len(fields) != 1:
        raise EsError(400, "parsing_exception",
                      f"{kind} requires exactly one field")
    field, spec = fields[0]
    if kind != "geo_distance" and not isinstance(spec, dict):
        raise EsError(400, "parsing_exception", f"malformed {kind}")
    return field, spec


def _es_point(v) -> tuple:
    """ES point input ({'lat','lon'} / [lon,lat] / 'lat,lon' / WKT /
    geohash-free subset) → (lon, lat)."""
    from ..geo.shapes import parse_any
    try:
        g = parse_any(v)
    except Exception:
        raise EsError(400, "parsing_exception", f"invalid point {v!r}")
    if g.kind != "point":
        raise EsError(400, "parsing_exception", "expected a point")
    return g.coords


_DIST_UNITS_M = {
    "mm": 0.001, "cm": 0.01, "m": 1.0, "km": 1000.0,
    "in": 0.0254, "ft": 0.3048, "yd": 0.9144, "mi": 1609.344,
    "nmi": 1852.0, "nauticalmiles": 1852.0, "meters": 1.0,
    "kilometers": 1000.0, "miles": 1609.344, "feet": 0.3048,
    "yards": 0.9144, "inches": 0.0254,
}


def _es_distance_m(v) -> float:
    """'200km' / '1.5mi' / numeric meters → meters."""
    if v is None:
        raise EsError(400, "parsing_exception",
                      "geo_distance requires a distance")
    if isinstance(v, (int, float)):
        return float(v)
    m = re.match(r"^\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*$", str(v))
    if not m:
        raise EsError(400, "parsing_exception", f"invalid distance {v!r}")
    unit = m.group(2).lower() or "m"
    scale = _DIST_UNITS_M.get(unit)
    if scale is None:
        raise EsError(400, "parsing_exception",
                      f"unknown distance unit [{unit}]")
    return float(m.group(1)) * scale


def _hits_response(hits: list[dict], total: int) -> dict:
    return {
        "took": 1, "timed_out": False,
        "_shards": {"total": 1, "successful": 1, "skipped": 0, "failed": 0},
        "hits": {"total": {"value": total, "relation": "eq"},
                 "max_score": max((h["_score"] for h in hits), default=None),
                 "hits": hits},
    }


def _ident(name) -> str:
    """Validated, quoted SQL identifier — ES field names come from untrusted
    request bodies and must never inject SQL."""
    s = str(name)
    if not re.match(r"^[A-Za-z_][A-Za-z0-9_\-.]*$", s) or len(s) > 255:
        raise EsError(400, "illegal_argument_exception",
                      f"invalid field name [{s[:64]}]")
    return '"' + s + '"'


def _ts_query(field: str, q: str) -> str:
    return f"{_ident(field)} @@ {_sql_str(q)}"


def _sql_str(s: str) -> str:
    return "'" + s.replace("'", "''") + "'"


def _sql_lit(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return str(v)
    return _sql_str(str(v))


def _sort_clause(s) -> str:
    if isinstance(s, str):
        return _ident(s)
    field, spec = next(iter(s.items()))
    order = spec.get("order", "asc") if isinstance(spec, dict) else spec
    if str(order).lower() not in ("asc", "desc"):
        raise EsError(400, "illegal_argument_exception",
                      f"invalid sort order [{order}]")
    return f'{_ident(field)} {str(order).upper()}'


def _score_expr(score_fields: list):
    """Scoring plan from (field, boost, predicate) text claims.

    One distinct field → a SQL score expression (`bm25(f) [* w]`) the
    engine evaluates inline, pushing top-k into the index scan. Several
    fields → the claims list itself: the caller runs one scored pass per
    claim and sums weighted scores per doc (Lucene: a document's score
    is the sum of its matching clauses' scores; bm25() on a cross-field
    scan would be unclaimable and evaluate to 0)."""
    if not score_fields:
        return None
    fields = {f for f, _, _ in score_fields}
    if len(fields) == 1:
        f = next(iter(fields))
        w = max(b for _, b, _ in score_fields)
        term = f"bm25({_ident(f)})"
        return f"{term} * {w!r}" if w != 1.0 else term
    return list(score_fields)


def _value_sql_type(v) -> dt.SqlType:
    if isinstance(v, bool):
        return dt.BOOL
    if isinstance(v, int):
        return dt.BIGINT
    if isinstance(v, float):
        return dt.DOUBLE
    return dt.VARCHAR


def _es_type_to_sql(es_type: str) -> dt.SqlType:
    return {
        "text": dt.VARCHAR, "keyword": dt.VARCHAR, "long": dt.BIGINT,
        "integer": dt.INT, "short": dt.SMALLINT, "byte": dt.TINYINT,
        "double": dt.DOUBLE, "float": dt.FLOAT, "boolean": dt.BOOL,
        "date": dt.TIMESTAMP,
    }.get(es_type, dt.VARCHAR)


def _sql_type_to_es(t: dt.SqlType) -> str:
    return {
        dt.TypeId.VARCHAR: "text", dt.TypeId.BIGINT: "long",
        dt.TypeId.INT: "integer", dt.TypeId.SMALLINT: "short",
        dt.TypeId.TINYINT: "byte", dt.TypeId.DOUBLE: "double",
        dt.TypeId.FLOAT: "float", dt.TypeId.BOOL: "boolean",
        dt.TypeId.TIMESTAMP: "date", dt.TypeId.DATE: "date",
    }.get(t.id, "text")


_id_counter = [0]
_id_lock = threading.Lock()


def _gen_id() -> str:
    import time
    with _id_lock:
        _id_counter[0] += 1
        return f"{int(time.time() * 1000):x}-{_id_counter[0]:x}"
