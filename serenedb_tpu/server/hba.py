"""Host-based authentication: pg_hba.conf-style rules.

Reference analog: /root/reference/server/network/pg/hba.cpp — SereneDB
parses a pg_hba.conf-compatible rule list (configurable at boot and at
runtime via SET hba) and resolves the auth method for each incoming
connection by first match. This module re-implements that contract:

    # type  database  user  address       method
    host    all       all   127.0.0.1/32  trust
    hostssl all       app   0.0.0.0/0     scram-sha-256
    host    all       all   all           reject

- type: local (unix-socket peers only — PG semantics), host (TCP),
  hostssl (TLS only), hostnossl (non-TLS only); host-family rules never
  match unix peers and local rules never match TCP peers
- database/user: 'all', a name, or a comma-separated list
- address: CIDR ('10.0.0.0/8'), bare IP (host mask), 'all', or
  'samehost' (any of this machine's addresses); 'samenet' is rejected
  loudly (interface enumeration is out of scope)
- method: trust, reject, scram-sha-256, password (cleartext), md5
  (treated as password-equivalent: we never store md5 hashes)

First matching rule decides; NO match rejects the connection (PG
semantics: "no pg_hba.conf entry for host ...").
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Optional

METHODS = {"trust", "reject", "scram-sha-256", "password", "md5"}


class HbaError(ValueError):
    pass


@dataclass
class HbaRule:
    conn_type: str                 # local | host | hostssl | hostnossl
    databases: list[str]           # ['all'] or names
    users: list[str]
    network: Optional[ipaddress._BaseNetwork]  # None = all/local
    method: str
    line_no: int = 0
    samehost: bool = False         # match any of this machine's addresses

    def matches(self, database: str, user: str, addr: Optional[str],
                tls: bool) -> bool:
        is_unix = addr is not None and not _is_ip(addr)
        if self.conn_type == "hostssl" and not tls:
            return False
        if self.conn_type == "hostnossl" and tls:
            return False
        if self.conn_type == "local":
            # PG: local rules match unix-socket peers ONLY
            if addr is not None and not is_unix:
                return False
        elif is_unix:
            # PG: host/hostssl/hostnossl never match unix peers — a
            # 'host all all all trust' line must not fail open for them
            return False
        if "all" not in self.databases and database not in self.databases:
            return False
        if "all" not in self.users and user not in self.users:
            return False
        if self.samehost:
            return addr is not None and _is_local_address(addr)
        if self.network is not None and self.conn_type != "local":
            if addr is None:
                return False
            try:
                ip = ipaddress.ip_address(addr)
            except ValueError:
                return False
            if ip.version != self.network.version:
                # PG matches IPv4-mapped IPv6 against v4 rules
                if ip.version == 6 and getattr(ip, "ipv4_mapped", None):
                    ip = ip.ipv4_mapped
                    if ip.version != self.network.version:
                        return False
                else:
                    return False
            if ip not in self.network:
                return False
        return True


def _is_ip(addr: str) -> bool:
    try:
        ipaddress.ip_address(addr)
        return True
    except ValueError:
        return False


def _is_loopback(addr: str) -> bool:
    try:
        return ipaddress.ip_address(addr).is_loopback
    except ValueError:
        return True   # unix-socket style path → local


def parse_hba(text: str) -> list[HbaRule]:
    """Parse pg_hba.conf content. Raises HbaError on malformed lines —
    a broken auth config must fail loudly, not fall open."""
    rules: list[HbaRule] = []
    for ln_no, line in enumerate(text.splitlines(), 1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        fields = line.split()
        conn_type = fields[0]
        if conn_type == "local":
            if len(fields) != 4:
                raise HbaError(f"line {ln_no}: local rules take "
                               "4 fields (type db user method)")
            db_f, user_f, method = fields[1], fields[2], fields[3]
            network = None
        elif conn_type in ("host", "hostssl", "hostnossl"):
            if len(fields) == 5:
                db_f, user_f, addr_f, method = fields[1:5]
            elif len(fields) == 6:   # address + separate netmask
                db_f, user_f, addr_f, mask_f, method = fields[1:6]
                addr_f = f"{addr_f}/{_mask_bits(mask_f, ln_no)}"
            else:
                raise HbaError(f"line {ln_no}: host rules take 5 fields")
            if addr_f == "samenet":
                # PG matches any directly-connected subnet; interface
                # enumeration is out of scope — fail loudly rather than
                # silently narrowing the rule's meaning
                raise HbaError(f"line {ln_no}: samenet is not supported")
            if addr_f in ("all", "samehost"):
                network = None
                if addr_f == "samehost":
                    rules.append(HbaRule(conn_type, db_f.split(","),
                                         user_f.split(","), None,
                                         _check_method(fields[-1], ln_no),
                                         ln_no, samehost=True))
                    continue
            else:
                try:
                    if "/" in addr_f:
                        network = ipaddress.ip_network(addr_f, strict=False)
                    else:
                        network = ipaddress.ip_network(addr_f)
                except ValueError as e:
                    raise HbaError(f"line {ln_no}: bad address: {e}")
        else:
            raise HbaError(f"line {ln_no}: unknown connection type "
                           f"{conn_type!r}")
        rules.append(HbaRule(conn_type, db_f.split(","), user_f.split(","),
                             network, _check_method(method, ln_no), ln_no))
    return rules


def _check_method(method: str, ln_no: int) -> str:
    if method not in METHODS:
        raise HbaError(f"line {ln_no}: unknown auth method {method!r}")
    return method


def _is_local_address(addr: str) -> bool:
    """True if addr is one of this machine's addresses (PG samehost)."""
    try:
        ip = ipaddress.ip_address(addr)
    except ValueError:
        return True   # unix-socket path → local
    if ip.is_loopback:
        return True
    if getattr(ip, "ipv4_mapped", None) and ip.ipv4_mapped.is_loopback:
        return True
    return str(ip) in _machine_addresses()


_MACHINE_ADDRS: Optional[set] = None


def _machine_addresses() -> set:
    global _MACHINE_ADDRS
    if _MACHINE_ADDRS is None:
        import socket
        addrs = set()
        try:
            for info in socket.getaddrinfo(socket.gethostname(), None):
                addrs.add(str(ipaddress.ip_address(info[4][0])))
        except (socket.gaierror, ValueError, OSError):
            pass
        _MACHINE_ADDRS = addrs
    return _MACHINE_ADDRS


def _mask_bits(mask: str, ln_no: int) -> int:
    try:
        return ipaddress.ip_network(f"0.0.0.0/{mask}").prefixlen
    except ValueError:
        raise HbaError(f"line {ln_no}: bad netmask {mask!r}")


def match_rule(rules: list[HbaRule], database: str, user: str,
               addr: Optional[str], tls: bool) -> Optional[HbaRule]:
    """First matching rule, or None (→ reject per PG semantics)."""
    for r in rules:
        if r.matches(database, user, addr, tls):
            return r
    return None
