"""The front door: one asyncio event loop owning the sockets for BOTH
protocols.

Reference analog: the reference serves pgwire and HTTP/ES from one
asio+coroutine IO layer (PAPER.md §2.2 network/server layer) — idle
connections cost a suspended coroutine, not an OS thread, and overload
is shed at the SOCKET before it consumes engine resources. This module
is that layer for serenedb_tpu:

- **HTTP/ES on asyncio streams** — keep-alive, pipelining, chunked
  request bodies. The route table is the same pure request→response
  `Router` the legacy ThreadingHTTPServer uses (server/http_server.py),
  so frontdoor-on/off results are bit-identical by construction. The
  engine boundary stays synchronous: each request's route runs on the
  shared executor via `run_in_executor` (the pgwire session pool when
  pgwire is hosted here, so both protocols draw on ONE bounded pool).
- **pgwire on the same loop/lifecycle** — `PgServer` was already
  asyncio (the TLS backport, server/pgwire.py); hosting it here gives
  both protocols one loop, one executor, one ordered shutdown.
- **Socket-level admission** (sched/governor.py `ConnectionGate`) —
  `serene_max_connections` caps open sockets across both protocols;
  past it, a pgwire client gets a clean 53300 ErrorResponse and an
  HTTP client a 429 + Retry-After BEFORE any byte of the session is
  parsed. The statement governor (PR 13) still arbitrates what the
  admitted connections may run — two gates, one backpressure story.
- **Per-connection in-flight cap** — requests on one connection are
  strictly serialized: the next pipelined request is not even read
  until the current response has fully drained, so one firehose client
  holds at most one executor slot (concurrency comes from connections,
  which the accept gate bounds).
- **Slow-writer backpressure** — responses are written in chunks;
  past the `serene_conn_write_high_kb` transport high-water mark the
  session calls `transport.pause_reading()` and blocks in `drain()`
  until the client catches up, so a stalled reader never buffers
  unbounded result bytes.
- **Idle reaping** — `serene_idle_conn_timeout_s` bounds how long a
  connection may sit sending nothing (half-open clients, abandoned
  keep-alives) before its socket and admission slot are reclaimed.
- **Deterministic shutdown** — `stop()` closes listeners, cancels
  idle sessions, lets in-flight responses drain (bounded), then joins
  the loop thread and the executor with no silent leak — the fix for
  the legacy tier's join(timeout=10)-and-forget.

Embedding: `HttpServer` (server/http_server.py) constructs a
FrontDoor per `serene_frontdoor` and runs it threaded via
`start()`/`stop()`; serened runs `start_async()`/`stop_async()` inline
on the process's main loop with pgwire hosted alongside.
"""

from __future__ import annotations

import asyncio
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.client import responses as _http_reasons
from typing import Optional

from ..engine import Database
from ..sched.governor import CONNGATE
from ..utils import log, metrics
from ..utils.config import REGISTRY as _settings
from .es_api import EsApi
from .http_server import Router

#: bytes written to the transport per chunk between drain checks —
#: bounds the per-write buffer spike on top of the high-water mark
_WRITE_CHUNK = 64 * 1024

#: headers per request / bytes per header line an h1 peer may send
_MAX_HEADERS = 100


class _BadRequest(Exception):
    """Malformed HTTP/1.x framing: answered with a 400 and a close."""


def _idle_timeout() -> Optional[float]:
    t = float(_settings.get_global("serene_idle_conn_timeout_s") or 0.0)
    return t if t > 0 else None


def _write_high_water() -> int:
    return int(_settings.get_global("serene_conn_write_high_kb")) * 1024


async def _read_request(reader: asyncio.StreamReader,
                        timeout: Optional[float]):
    """One HTTP/1.x request off the stream: (method, target, headers,
    body, keep_alive), or None on a clean EOF between requests. Only
    the FIRST readline carries the idle timeout — once a request has
    started arriving the connection is active, not idle."""
    if timeout:
        line = await asyncio.wait_for(reader.readline(), timeout)
    else:
        line = await reader.readline()
    if not line:
        return None
    try:
        method, target, version = line.decode("latin-1").strip().split(" ", 2)
    except ValueError:
        raise _BadRequest("malformed request line")
    if not version.startswith("HTTP/1."):
        raise _BadRequest(f"unsupported protocol [{version}]")
    headers: dict[str, str] = {}
    for _ in range(_MAX_HEADERS):
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin-1").partition(":")
        headers[k.strip().lower()] = v.strip()
    else:
        raise _BadRequest("too many headers")
    conn_tok = headers.get("connection", "").lower()
    keep_alive = (version == "HTTP/1.1" and conn_tok != "close") or \
        (version == "HTTP/1.0" and conn_tok == "keep-alive")
    if headers.get("transfer-encoding", "").lower() == "chunked":
        body = bytearray()
        while True:
            size_line = await reader.readline()
            try:
                size = int(size_line.split(b";")[0].strip() or b"0", 16)
            except ValueError:
                raise _BadRequest("malformed chunk size")
            if size == 0:
                while True:       # trailers until the blank line
                    t = await reader.readline()
                    if t in (b"\r\n", b"\n", b""):
                        break
                break
            body += await reader.readexactly(size)
            await reader.readexactly(2)   # the chunk's trailing CRLF
        body = bytes(body)
    else:
        ln = int(headers.get("content-length") or 0)
        body = await reader.readexactly(ln) if ln else b""
    return method, target, headers, body, keep_alive


class FrontDoor:
    """One event loop, both protocols, connections as tasks."""

    def __init__(self, db: Database, host: str = "127.0.0.1",
                 http_port: int = 0, pg=None, drain_s: float = 5.0):
        self.db = db
        self.host = host
        self.router = Router(EsApi(db))
        #: optional PgServer hosted on this loop (serened); its session
        #: pool becomes the shared engine-boundary executor
        self.pg = pg
        self.drain_s = drain_s
        if pg is not None:
            self.executor = pg.pool
            self._owns_executor = False
        else:
            import os
            self.executor = ThreadPoolExecutor(
                max_workers=max(4, (os.cpu_count() or 4)),
                thread_name_prefix="serene-frontdoor-exec")
            self._owns_executor = True
        # pre-bind so .port is known at construction (the legacy
        # HttpServer contract); asyncio adopts the socket in start_async
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, http_port))
        self._sock.setblocking(False)
        self.port = self._sock.getsockname()[1]
        self._server: Optional[asyncio.AbstractServer] = None
        self._sessions: dict[asyncio.Task, object] = {}
        self._draining = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event: Optional[asyncio.Event] = None
        self._ready = threading.Event()
        self._startup_error: Optional[BaseException] = None

    # -- lifecycle (async core) -------------------------------------------

    async def start_async(self):
        self._loop = asyncio.get_running_loop()
        self._draining = False
        self._server = await asyncio.start_server(
            self._on_http_conn, sock=self._sock, backlog=2048)
        log.info("http", f"front door listening on port {self.port} "
                 "(asyncio tier)")
        if self.pg is not None:
            await self.pg.start()

    async def stop_async(self):
        """Graceful drain, then deterministic teardown: stop accepting,
        reap idle sessions now, give in-flight responses `drain_s` to
        finish, hard-cancel stragglers, and await every session task —
        nothing outlives this call on the loop."""
        self._draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # idle keep-alive sessions are parked in a read — cancel them
        # now; active ones get to finish their current response
        for task, info in list(self._sessions.items()):
            if info is None or getattr(info, "state", "") == "idle":
                task.cancel()
        pending = [t for t in self._sessions if not t.done()]
        if pending:
            done, pending = await asyncio.wait(
                pending, timeout=self.drain_s)
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.wait(pending, timeout=self.drain_s)
        self._sessions.clear()
        if self.pg is not None:
            await self.pg.stop()

    # -- lifecycle (threaded embedding) -----------------------------------

    def start(self):
        """Run the loop on a dedicated thread (test/embedded mode);
        returns once the listeners are live."""
        self._ready.clear()
        self._startup_error = None
        self._thread = threading.Thread(
            target=self._thread_main, name="serene-frontdoor", daemon=True)
        self._thread.start()
        self._ready.wait(timeout=30)
        if self._startup_error is not None:
            err, self._startup_error = self._startup_error, None
            self._thread.join(timeout=10)
            raise err

    def _thread_main(self):
        async def main():
            self._stop_event = asyncio.Event()
            try:
                await self.start_async()
            except BaseException as e:  # noqa: BLE001 — report to start()
                self._startup_error = e
                self._ready.set()
                return
            self._ready.set()
            await self._stop_event.wait()
            await self.stop_async()
        asyncio.run(main())

    def stop(self):
        """Deterministic shutdown from sync code: signal the loop, join
        the thread, join the executor. Raises instead of silently
        leaking a thread (the legacy tier's failure mode)."""
        if self._thread is None:
            self._sock.close()
            if self._owns_executor:
                self.executor.shutdown(wait=True)
            return
        if self._loop is not None and self._stop_event is not None:
            self._loop.call_soon_threadsafe(self._stop_event.set)
        self._thread.join(timeout=30)
        if self._thread.is_alive():
            raise RuntimeError(
                "frontdoor loop thread failed to stop within 30s")
        self._thread = None
        if self._owns_executor:
            self.executor.shutdown(wait=True)

    # -- HTTP sessions -----------------------------------------------------

    def _on_http_conn(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter):
        # sync accept callback: stamp NOW, so the gap to the session
        # task's first step measures the event-loop accept backlog
        accept_ns = time.monotonic_ns()
        task = asyncio.get_running_loop().create_task(
            self._http_session(reader, writer, accept_ns))
        self._sessions[task] = None
        task.add_done_callback(self._sessions.pop)

    async def _http_session(self, reader, writer, accept_ns: int):
        transport = writer.transport
        peer = writer.get_extra_info("peername")
        info = CONNGATE.try_admit("http", peer, accept_ns)
        if info is None:
            # rejected at the accept gate: answer 429 without having
            # read — let alone parsed — a single request byte
            writer.write(
                b"HTTP/1.1 429 Too Many Requests\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: 102\r\n"
                b"Retry-After: 1\r\nConnection: close\r\n\r\n"
                b'{"error": {"type": "too_many_connections", "reason": '
                b'"serene_max_connections reached"}, "status": 429}')
            await self._close(writer)
            return
        task = asyncio.current_task()
        if task in self._sessions:
            self._sessions[task] = info
        info.buffered = transport.get_write_buffer_size
        transport.set_write_buffer_limits(high=_write_high_water())
        loop = asyncio.get_running_loop()
        try:
            while not self._draining:
                CONNGATE.set_state(info, "idle")
                req = await _read_request(reader, _idle_timeout())
                if req is None:
                    break
                CONNGATE.set_state(info, "active")
                method, target, _headers, body, keep_alive = req
                # one request in flight per connection: the route runs
                # on the executor while this task — the connection's
                # only reader — awaits it, then fully drains the
                # response before reading the next pipelined request
                with metrics.HTTP_CONNECTIONS.scoped():
                    status, data, ctype = await loop.run_in_executor(
                        self.executor, self.router.handle,
                        method, target, body)
                    await self._write_response(
                        writer, status, data, ctype, keep_alive)
                if not keep_alive:
                    break
        except asyncio.TimeoutError:
            log.debug("http", "idle connection reaped "
                      "(serene_idle_conn_timeout_s)")
        except _BadRequest as e:
            try:
                await self._write_response(
                    writer, 400, encode_error(str(e)),
                    "application/json", False)
            except (ConnectionResetError, RuntimeError):
                pass
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, ValueError):
            pass        # peer vanished / overlong header line
        except asyncio.CancelledError:
            pass        # drain-time reap: close and release below
        finally:
            CONNGATE.release(info)
            await self._close(writer)

    async def _write_response(self, writer: asyncio.StreamWriter,
                              status: int, data: bytes, ctype: str,
                              keep_alive: bool):
        reason = _http_reasons.get(status, "Unknown")
        head = (f"HTTP/1.1 {status} {reason}\r\n"
                f"Server: serenedb-tpu/0.1\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(data)}\r\n"
                "X-Elastic-Product: Elasticsearch\r\n"
                + ("" if keep_alive else "Connection: close\r\n")
                + "\r\n").encode("latin-1")
        payload = memoryview(head + data)
        transport = writer.transport
        high = _write_high_water()
        for off in range(0, len(payload), _WRITE_CHUNK):
            writer.write(bytes(payload[off:off + _WRITE_CHUNK]))
            if transport.get_write_buffer_size() >= high:
                # slow reader: stop reading THIS connection until the
                # client drains us below the low-water mark — result
                # bytes stay bounded no matter how stalled the peer is
                paused = False
                try:
                    if transport.is_reading():
                        transport.pause_reading()
                        paused = True
                        CONNGATE.note_pause()
                except (AttributeError, RuntimeError):
                    pass
                try:
                    await writer.drain()
                finally:
                    if paused and not transport.is_closing():
                        transport.resume_reading()
        await writer.drain()

    @staticmethod
    async def _close(writer: asyncio.StreamWriter):
        try:
            writer.close()
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError, RuntimeError):
            pass


def encode_error(reason: str) -> bytes:
    import json
    return json.dumps({"error": {"type": "bad_request",
                                 "reason": reason}, "status": 400}).encode()
