"""Listen-spec parsing: tcp and unix-socket endpoints.

Reference analog: server/network/listen_spec.h:31-60 — the reference
accepts repeated --listen flags with tcp:// and unix:// schemes; the
same spec grammar is accepted here:

    tcp://HOST:PORT      explicit TCP endpoint
    unix:///path.sock    unix domain socket (also unix:/path.sock)
    HOST:PORT            bare TCP
    :PORT / PORT         TCP on all interfaces / default host
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ListenSpec:
    kind: str                   # "tcp" | "unix"
    host: Optional[str] = None  # tcp only
    port: Optional[int] = None  # tcp only
    path: Optional[str] = None  # unix only

    def __str__(self) -> str:
        if self.kind == "unix":
            return f"unix://{self.path}"
        return f"tcp://{self.host}:{self.port}"


def parse_listen_spec(spec: str, default_host: str = "127.0.0.1"
                      ) -> ListenSpec:
    s = spec.strip()
    if s.startswith("unix://"):
        path = s[len("unix://"):]
        if not path:
            raise ValueError(f"empty unix socket path in {spec!r}")
        return ListenSpec("unix", path=path)
    if s.startswith("unix:"):
        path = s[len("unix:"):]
        if not path:
            raise ValueError(f"empty unix socket path in {spec!r}")
        return ListenSpec("unix", path=path)
    if s.startswith("tcp://"):
        s = s[len("tcp://"):]
    if s.isdigit():
        return ListenSpec("tcp", host=default_host, port=int(s))
    try:
        # [v6]:port / host:port / :port
        if s.startswith("["):
            close = s.index("]")
            host = s[1:close]
            rest = s[close + 1:]
            if not rest.startswith(":"):
                raise ValueError
            return ListenSpec("tcp", host=host, port=int(rest[1:]))
        host, sep, port = s.rpartition(":")
        if not sep:
            raise ValueError
        return ListenSpec("tcp", host=host or "0.0.0.0", port=int(port))
    except ValueError:
        raise ValueError(f"cannot parse listen spec {spec!r}")
