from .es_api import EsApi
from .http_server import HttpServer
from .pgwire import PgServer

__all__ = ["EsApi", "HttpServer", "PgServer"]
