"""Column batch ABI — the unit of data exchange across the whole framework.

Reference analog: DuckDB's DataChunk/Vector flowing between physical operators
(the reference moves DataChunks through morsel-driven pipelines; see
SURVEY.md §3.2). Here the layout is chosen for HBM/TPU:

- struct-of-arrays: one contiguous numpy array per column
- validity as a separate bool array (None ⇒ all valid)
- VARCHAR is dictionary-encoded: `data` holds int32 codes into a host-side
  `dictionary` (numpy object array of python str), kept **lexicographically
  sorted** so integer code order == string order and device-side comparisons
  (<, <=, =, >, >=, GROUP BY, ORDER BY) are exact on codes.
- a NULL code of -1 is never used; validity carries nullness so codes stay
  non-negative and usable as gather indices.

Columns are immutable by convention: operators build new ones.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

import numpy as np

from . import dtypes as dt


@dataclass
class Column:
    type: dt.SqlType
    data: np.ndarray                       # 1-D, physical dtype of `type`
    validity: Optional[np.ndarray] = None  # 1-D bool; None ⇒ all valid
    dictionary: Optional[np.ndarray] = None  # VARCHAR only: sorted unique strs

    def __post_init__(self):
        assert self.data.ndim == 1
        if self.validity is not None:
            assert self.validity.shape == self.data.shape
            if bool(self.validity.all()):
                self.validity = None

    def __len__(self) -> int:
        return len(self.data)

    @property
    def has_nulls(self) -> bool:
        return self.validity is not None

    def valid_mask(self) -> np.ndarray:
        if self.validity is None:
            return np.ones(len(self.data), dtype=bool)
        return self.validity

    # -- construction ------------------------------------------------------

    @staticmethod
    def from_pylist(values: Sequence, typ: Optional[dt.SqlType] = None) -> "Column":
        """Build from python values (None ⇒ NULL). Infers type if not given."""
        non_null = [v for v in values if v is not None]
        if typ is None:
            typ = _infer_type(non_null)
        validity = np.array([v is not None for v in values], dtype=bool)
        n = len(values)
        if typ.is_string:
            strs = [("" if v is None else str(v)) for v in values]
            dictionary, codes = _encode_dictionary(strs)
            col = Column(typ, codes.astype(np.int32), validity, dictionary)
        elif typ.id is dt.TypeId.BOOL:
            data = np.array([bool(v) if v is not None else False for v in values],
                            dtype=np.bool_)
            col = Column(typ, data, validity)
        else:
            fill = 0
            try:
                data = np.array([fill if v is None else v for v in values],
                                dtype=typ.np_dtype)
            except OverflowError:
                from .. import errors
                raise errors.SqlError(
                    "22003",
                    f"value out of range for type "
                    f"{typ.id.name.lower()}")
            col = Column(typ, data, validity)
        if n == 0:
            col.validity = None
        return col

    @staticmethod
    def from_numpy(arr: np.ndarray, typ: Optional[dt.SqlType] = None,
                   validity: Optional[np.ndarray] = None) -> "Column":
        if arr.dtype.kind in ("U", "S", "O"):
            strs = [("" if v is None else str(v)) for v in arr.tolist()]
            dictionary, codes = _encode_dictionary(strs)
            return Column(dt.VARCHAR, codes.astype(np.int32), validity, dictionary)
        if typ is None:
            typ = dt.type_of_numpy(arr.dtype)
        return Column(typ, np.ascontiguousarray(arr, dtype=typ.np_dtype), validity)

    @staticmethod
    def const(value, n: int, typ: Optional[dt.SqlType] = None) -> "Column":
        """Constant column without the python-list round-trip: literals
        sit in EVERY expression eval, so this is np.full/np.zeros (which
        release the GIL) instead of from_pylist's per-element list build
        — the difference between host pipelines scaling and serializing
        on literal materialization."""
        if typ is None:
            typ = _infer_type([] if value is None else [value])
        if value is None:
            if typ.is_string:
                return Column(typ, np.zeros(n, dtype=np.int32),
                              np.zeros(n, dtype=bool),
                              np.asarray([""], dtype=object))
            return Column(typ, np.zeros(n, dtype=typ.np_dtype),
                          np.zeros(n, dtype=bool))
        if typ.is_string:
            return Column(typ, np.zeros(n, dtype=np.int32), None,
                          np.asarray([str(value)], dtype=object))
        if typ.id is dt.TypeId.BOOL:
            return Column(typ, np.full(n, bool(value), dtype=np.bool_))
        try:
            npd = np.dtype(typ.np_dtype)
            if npd.kind in "iu" and isinstance(value, int) and \
                    not (np.iinfo(npd).min <= value <= np.iinfo(npd).max):
                # np.full would silently wrap (np.array raises) — keep
                # from_pylist's 22003 out-of-range behavior
                raise OverflowError(value)
            return Column(typ, np.full(n, value, dtype=typ.np_dtype))
        except (OverflowError, ValueError, TypeError):
            return Column.from_pylist([value] * n, typ)

    # -- accessors ---------------------------------------------------------

    def to_pylist(self) -> list:
        out = []
        valid = self.valid_mask()
        if self.type.is_string:
            d = self.dictionary
            for i in range(len(self.data)):
                out.append(str(d[self.data[i]]) if valid[i] else None)
        else:
            for i in range(len(self.data)):
                v = self.data[i]
                out.append(v.item() if valid[i] else None)
        return out

    def decode(self, i: int):
        """Single-value accessor (python value or None)."""
        if self.validity is not None and not self.validity[i]:
            return None
        if self.type.is_string:
            return str(self.dictionary[self.data[i]])
        return self.data[i].item()

    def take(self, indices: np.ndarray) -> "Column":
        v = None if self.validity is None else self.validity[indices]
        return Column(self.type, self.data[indices], v, self.dictionary)

    def filter(self, mask: np.ndarray) -> "Column":
        return self.take(np.flatnonzero(mask))

    def slice(self, start: int, stop: int) -> "Column":
        v = None if self.validity is None else self.validity[start:stop]
        return Column(self.type, self.data[start:stop], v, self.dictionary)

    def re_dictionary(self) -> "Column":
        """Rebuild the dictionary to only the codes in use (post-filter)."""
        if not self.type.is_string or self.dictionary is None:
            return self
        used = np.unique(self.data)
        new_dict = self.dictionary[used]
        remap = np.zeros(len(self.dictionary), dtype=np.int32)
        remap[used] = np.arange(len(used), dtype=np.int32)
        return Column(self.type, remap[self.data], self.validity, new_dict)


def _infer_type(non_null: list) -> dt.SqlType:
    if not non_null:
        return dt.NULLTYPE
    if all(isinstance(v, bool) for v in non_null):
        return dt.BOOL
    if all(isinstance(v, int) and not isinstance(v, bool) for v in non_null):
        return dt.BIGINT
    if all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in non_null):
        return dt.DOUBLE
    return dt.VARCHAR


def _encode_dictionary(strs: Sequence[str]) -> tuple[np.ndarray, np.ndarray]:
    """Sorted-unique dictionary encode: codes compare like the strings."""
    arr = np.asarray(strs, dtype=object)
    uniq, codes = np.unique(arr.astype(str), return_inverse=True)
    return uniq.astype(object), codes.astype(np.int32)


def merge_dictionaries(cols: Iterable[Column]) -> list[Column]:
    """Re-encode VARCHAR columns from different batches onto one shared sorted
    dictionary (needed before concatenating or comparing code spaces)."""
    cols = list(cols)
    dicts = [c.dictionary for c in cols if c.dictionary is not None]
    if not dicts:
        return cols
    merged = np.unique(np.concatenate([d.astype(str) for d in dicts]))
    out = []
    for c in cols:
        if c.dictionary is None:
            out.append(c)
            continue
        remap = np.searchsorted(merged, c.dictionary.astype(str)).astype(np.int32)
        out.append(Column(c.type, remap[c.data], c.validity, merged.astype(object)))
    return out


@dataclass
class Batch:
    """An ordered set of equal-length named columns."""

    names: list[str]
    columns: list[Column]
    _index: dict = field(default_factory=dict, repr=False)

    def __post_init__(self):
        assert len(self.names) == len(self.columns)
        if self.columns:
            n = len(self.columns[0])
            assert all(len(c) == n for c in self.columns), "ragged batch"
        self._index = {n: i for i, n in enumerate(self.names)}

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name: str) -> Column:
        return self.columns[self._index[name]]

    def __contains__(self, name: str) -> bool:
        return name in self._index

    @staticmethod
    def from_pydict(d: dict) -> "Batch":
        names = list(d.keys())
        cols = [v if isinstance(v, Column)
                else (Column.from_numpy(v) if isinstance(v, np.ndarray)
                      else Column.from_pylist(v))
                for v in d.values()]
        return Batch(names, cols)

    def to_pydict(self) -> dict:
        return {n: c.to_pylist() for n, c in zip(self.names, self.columns)}

    def take(self, indices: np.ndarray) -> "Batch":
        return Batch(list(self.names), [c.take(indices) for c in self.columns])

    def filter(self, mask: np.ndarray) -> "Batch":
        return self.take(np.flatnonzero(mask))

    def slice(self, start: int, stop: int) -> "Batch":
        return Batch(list(self.names), [c.slice(start, stop) for c in self.columns])

    def rows(self) -> list[tuple]:
        cols = [c.to_pylist() for c in self.columns]
        return list(zip(*cols)) if cols else []


def concat_batches(batches: Sequence[Batch]) -> Batch:
    batches = [b for b in batches if b.num_rows > 0] or list(batches[:1])
    if len(batches) == 1:
        return batches[0]
    names = batches[0].names
    out_cols = []
    for i, name in enumerate(names):
        cols = merge_dictionaries([b.columns[i] for b in batches])
        data = np.concatenate([c.data for c in cols])
        if any(c.validity is not None for c in cols):
            validity = np.concatenate([c.valid_mask() for c in cols])
        else:
            validity = None
        typ = next((c.type for c in cols if c.type.id is not dt.TypeId.NULL),
                   cols[0].type)
        out_cols.append(Column(typ, data, validity, cols[0].dictionary))
    return Batch(list(names), out_cols)
