"""SQL type system mapped onto TPU-friendly physical dtypes.

The reference models types through DuckDB's LogicalType plus PG pseudo-types
(reference: server/pg/pg_types.cpp, server/query/server_engine.cpp:61-216).
Here the logical SQL type system is small and explicit, and every type has a
*physical* representation chosen for the TPU compute path:

- integers/floats/bools/timestamps: native numpy/jax dtypes
- VARCHAR: dictionary-encoded int32 codes on device; the dictionary
  (per-column, per-segment) stays host-side. String predicates are resolved
  against the dictionary on CPU and become integer-code predicates on device.
- DECIMAL is not implemented yet (DOUBLE covers the analytics benchmarks).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class TypeId(enum.Enum):
    BOOL = "BOOLEAN"
    TINYINT = "TINYINT"
    SMALLINT = "SMALLINT"
    INT = "INTEGER"
    BIGINT = "BIGINT"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    VARCHAR = "VARCHAR"
    TIMESTAMP = "TIMESTAMP"  # micros since epoch, int64
    DATE = "DATE"            # days since epoch, int32
    INTERVAL = "INTERVAL"    # duration in micros, int64 (fixed units only)
    NULL = "NULL"            # type of bare NULL literal
    # PG pseudo-types for catalog introspection (reference:
    # server/query/server_engine.cpp:61-216). Physically int64 object ids;
    # casting to/from text resolves names against the live catalog.
    OID = "OID"
    REGCLASS = "REGCLASS"
    REGTYPE = "REGTYPE"
    REGPROC = "REGPROC"
    REGNAMESPACE = "REGNAMESPACE"
    ARRAY = "ARRAY"          # element-typed; physically JSON text in a
                             # dictionary column (wire layer renders/encodes
                             # PG {…} text and the binary array format)
    RECORD = "RECORD"        # anonymous composite (ROW(...)); physically
                             # JSON {"o":[oid,...],"v":[...]} text in a
                             # dictionary column; wire layer renders PG
                             # (…) text / the binary record format (2249)


_NUMPY_OF = {
    TypeId.BOOL: np.dtype(np.bool_),
    TypeId.TINYINT: np.dtype(np.int8),
    TypeId.SMALLINT: np.dtype(np.int16),
    TypeId.INT: np.dtype(np.int32),
    TypeId.BIGINT: np.dtype(np.int64),
    TypeId.FLOAT: np.dtype(np.float32),
    TypeId.DOUBLE: np.dtype(np.float64),
    TypeId.VARCHAR: np.dtype(np.int32),   # dictionary codes
    TypeId.TIMESTAMP: np.dtype(np.int64),
    TypeId.DATE: np.dtype(np.int32),
    TypeId.INTERVAL: np.dtype(np.int64),
    TypeId.NULL: np.dtype(np.int32),
    TypeId.ARRAY: np.dtype(np.int32),     # dictionary codes (JSON text)
    TypeId.RECORD: np.dtype(np.int32),    # dictionary codes (JSON text)
    TypeId.OID: np.dtype(np.int64),
    TypeId.REGCLASS: np.dtype(np.int64),
    TypeId.REGTYPE: np.dtype(np.int64),
    TypeId.REGPROC: np.dtype(np.int64),
    TypeId.REGNAMESPACE: np.dtype(np.int64),
}

_INTEGERS = {TypeId.TINYINT, TypeId.SMALLINT, TypeId.INT, TypeId.BIGINT,
             TypeId.OID, TypeId.REGCLASS, TypeId.REGTYPE, TypeId.REGPROC,
             TypeId.REGNAMESPACE}
_FLOATS = {TypeId.FLOAT, TypeId.DOUBLE}


@dataclass(frozen=True)
class SqlType:
    """A logical SQL type. Kept as a dataclass so parametric types
    (DECIMAL(p,s), VARCHAR(n)) can be added without changing call sites."""

    id: TypeId
    #: ARRAY element type (None elsewhere); frozen+defaulted so equality
    #: and hashing of existing scalar types are unchanged
    elem: "TypeId | None" = None

    @property
    def np_dtype(self) -> np.dtype:
        return _NUMPY_OF[self.id]

    @property
    def is_integer(self) -> bool:
        return self.id in _INTEGERS

    @property
    def is_float(self) -> bool:
        return self.id in _FLOATS

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self.is_float or self.id is TypeId.BOOL

    @property
    def is_string(self) -> bool:
        # ARRAY/RECORD share the dictionary-string physical representation
        return self.id in (TypeId.VARCHAR, TypeId.ARRAY, TypeId.RECORD)

    def __str__(self) -> str:  # PG-style rendering
        if self.id is TypeId.ARRAY:
            return f"{(self.elem or TypeId.VARCHAR).value}[]"
        if self.id is TypeId.RECORD:
            return "record"
        return self.id.value


BOOL = SqlType(TypeId.BOOL)
TINYINT = SqlType(TypeId.TINYINT)
SMALLINT = SqlType(TypeId.SMALLINT)
INT = SqlType(TypeId.INT)
BIGINT = SqlType(TypeId.BIGINT)
FLOAT = SqlType(TypeId.FLOAT)
DOUBLE = SqlType(TypeId.DOUBLE)
VARCHAR = SqlType(TypeId.VARCHAR)
TIMESTAMP = SqlType(TypeId.TIMESTAMP)
DATE = SqlType(TypeId.DATE)
INTERVAL = SqlType(TypeId.INTERVAL)
OID = SqlType(TypeId.OID)
REGCLASS = SqlType(TypeId.REGCLASS)
REGTYPE = SqlType(TypeId.REGTYPE)
REGPROC = SqlType(TypeId.REGPROC)
REGNAMESPACE = SqlType(TypeId.REGNAMESPACE)
NULLTYPE = SqlType(TypeId.NULL)
RECORD = SqlType(TypeId.RECORD)


def array_of(elem: "SqlType | TypeId | None") -> SqlType:
    """Element-typed array (TEXT elements when unknown)."""
    if isinstance(elem, SqlType):
        elem = elem.id
    if elem in (None, TypeId.NULL, TypeId.ARRAY):
        elem = TypeId.VARCHAR
    return SqlType(TypeId.ARRAY, elem)

_BY_NAME = {
    "BOOLEAN": BOOL, "BOOL": BOOL,
    "TINYINT": TINYINT, "INT1": TINYINT,
    "SMALLINT": SMALLINT, "INT2": SMALLINT,
    "INTEGER": INT, "INT": INT, "INT4": INT,
    "BIGINT": BIGINT, "INT8": BIGINT, "LONG": BIGINT,
    "FLOAT": FLOAT, "REAL": FLOAT, "FLOAT4": FLOAT,
    "DOUBLE": DOUBLE, "FLOAT8": DOUBLE, "DOUBLE PRECISION": DOUBLE,
    "VARCHAR": VARCHAR, "TEXT": VARCHAR, "STRING": VARCHAR, "CHAR": VARCHAR,
    "TIMESTAMP": TIMESTAMP, "TIMESTAMPTZ": TIMESTAMP, "DATETIME": TIMESTAMP,
    "DATE": DATE,
    "INTERVAL": INTERVAL,
    "OID": OID, "REGCLASS": REGCLASS, "REGTYPE": REGTYPE,
    "REGPROC": REGPROC, "REGPROCEDURE": REGPROC,
    "REGNAMESPACE": REGNAMESPACE,
    "NAME": VARCHAR, "BPCHAR": VARCHAR, "JSON": VARCHAR, "JSONB": VARCHAR,
    "UUID": VARCHAR, "XID": BIGINT, "CID": BIGINT,
}

# numeric widening lattice for binary-op result typing
_RANK = {
    TypeId.BOOL: 0, TypeId.TINYINT: 1, TypeId.SMALLINT: 2, TypeId.INT: 3,
    TypeId.DATE: 3, TypeId.BIGINT: 4, TypeId.TIMESTAMP: 4,
    TypeId.OID: 4, TypeId.REGCLASS: 4, TypeId.REGTYPE: 4, TypeId.REGPROC: 4,
    TypeId.REGNAMESPACE: 4,
    TypeId.FLOAT: 5, TypeId.DOUBLE: 6,
}


def type_from_name(name: str) -> SqlType:
    key = name.upper().strip()
    if key.endswith("[]"):
        return array_of(type_from_name(key[:-2]))
    if key == "ARRAY":          # legacy/unparameterized
        return array_of(None)
    t = _BY_NAME.get(key)
    if t is None:
        raise ValueError(f"unknown type name: {name!r}")
    return t


def unify_pair(a: SqlType, b: SqlType) -> SqlType:
    """Branch-type unification (CASE/COALESCE/VALUES arms): NULL yields
    the other side, equal types stay, numerics widen via common_numeric,
    and any other mix keeps the first typed side (text-vs-x arms render
    through the first type, matching the engine's historical behavior)."""
    if a.id is TypeId.NULL:
        return b
    if b.id is TypeId.NULL or a == b:
        return a
    if a.is_numeric and b.is_numeric:
        return common_numeric(a, b)
    return a


def unify_all(types) -> SqlType:
    t = NULLTYPE
    for x in types:
        t = unify_pair(t, x)
    return t


def common_numeric(a: SqlType, b: SqlType) -> SqlType:
    """Widening for arithmetic/comparison between numeric types."""
    if a.id is TypeId.NULL:
        return b
    if b.id is TypeId.NULL:
        return a
    if not (a.is_numeric or a.id in (TypeId.TIMESTAMP, TypeId.DATE)):
        raise TypeError(f"non-numeric type {a}")
    if not (b.is_numeric or b.id in (TypeId.TIMESTAMP, TypeId.DATE)):
        raise TypeError(f"non-numeric type {b}")
    return a if _RANK[a.id] >= _RANK[b.id] else b


def type_of_numpy(dt: np.dtype) -> SqlType:
    for tid, nd in _NUMPY_OF.items():
        if tid in (TypeId.VARCHAR, TypeId.NULL, TypeId.DATE):
            continue
        if nd == dt:
            return SqlType(tid)
    if np.issubdtype(dt, np.integer):
        return BIGINT
    if np.issubdtype(dt, np.floating):
        return DOUBLE
    raise TypeError(f"unsupported numpy dtype {dt}")
