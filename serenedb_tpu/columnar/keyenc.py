"""Order-preserving composite primary-key byte encoding.

Reference analog: server/connector/key_encoding.cpp +
server/connector/duckdb_primary_key.h — composite PKs become memcomparable
byte strings, so PK terms support point lookups, PK RANGE scans over the
sorted key array, and PK-based remove filters for UPDATE/DELETE (replayed
identically after a crash regardless of physical row order).

Encoding rules (all big-endian, so bytewise compare == logical compare):
- integers / date / timestamp / interval: 8-byte big-endian with the sign
  bit flipped (two's complement order becomes unsigned byte order)
- floats: IEEE-754 bits; negative values flip ALL bits, positive flip the
  sign bit (standard total-order trick; -0.0 and +0.0 encode differently
  but PK equality uses the same transform on both sides)
- booleans: one byte
- strings: UTF-8 with 0x00 escaped as 0x00 0xFF, terminated by 0x00 0x01 —
  the terminator is lower than any escaped byte pair, so 'a' < 'ab' holds
  and concatenated composite keys stay prefix-free
- NULL never encodes: PKs reject NULLs before this layer (23502)

Composite keys concatenate the per-column encodings.
"""

from __future__ import annotations

import struct

import numpy as np

from . import dtypes as dt

_STR_TERM = b"\x00\x01"
_INT_TYPES = (dt.TypeId.TINYINT, dt.TypeId.SMALLINT, dt.TypeId.INT,
              dt.TypeId.BIGINT, dt.TypeId.DATE, dt.TypeId.TIMESTAMP,
              dt.TypeId.INTERVAL, dt.TypeId.OID)


def _enc_int(v: int) -> bytes:
    v = int(v)
    if not -(1 << 63) <= v < (1 << 63):
        # never wrap: a query literal beyond int64 must fall back to the
        # generic comparison path, not silently alias another key
        raise ValueError(f"integer key out of range: {v}")
    return struct.pack(">Q", v + (1 << 63))


def _enc_float(v: float) -> bytes:
    v = float(v)
    if v == 0.0:
        v = 0.0          # -0.0 == 0.0 in SQL: one canonical key
    elif v != v:
        v = float("nan")  # canonical NaN bits
    bits = struct.unpack(">Q", struct.pack(">d", v))[0]
    if bits & (1 << 63):
        bits = ~bits & ((1 << 64) - 1)     # negative: flip everything
    else:
        bits |= (1 << 63)                  # positive: flip sign bit
    return struct.pack(">Q", bits)


def _enc_str(v: str) -> bytes:
    return v.encode("utf-8").replace(b"\x00", b"\x00\xff") + _STR_TERM


def encode_value(v, t: dt.SqlType) -> bytes:
    if t.id in _INT_TYPES:
        return _enc_int(v)
    if t.id is dt.TypeId.BOOL:
        return b"\x01" if v else b"\x00"
    if t.is_float:
        return _enc_float(v)
    if t.is_string:
        return _enc_str(str(v))
    # catch-all: text encoding of the decoded value keeps equality exact
    # (order may not match SQL order for exotic types — PKs on them are
    # point-lookup only)
    return _enc_str(str(v))


def encode_row(values, types) -> bytes:
    return b"".join(encode_value(v, t) for v, t in zip(values, types))


def encode_key_columns(cols) -> np.ndarray:
    """Encode PK columns of a batch into an object array of key bytes.
    NULLs must have been rejected upstream (PK NOT NULL)."""
    n = len(cols[0]) if cols else 0
    parts = []
    for c in cols:
        t = c.type
        vals = c.to_pylist()
        parts.append([encode_value(v, t) for v in vals])
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = b"".join(p[i] for p in parts)
    return out


def prefix_upper_bound(prefix: bytes):
    """Smallest byte string greater than every key starting with
    `prefix` (for leading-column range scans): increment the last
    non-0xFF byte. None = unbounded above."""
    b = bytearray(prefix)
    while b and b[-1] == 0xFF:
        b.pop()
    if not b:
        return None
    b[-1] += 1
    return bytes(b)
