from . import dtypes
from .column import Batch, Column, concat_batches, merge_dictionaries
from .device import (BLOCK_ROWS, LANES, DeviceColumn, pad_len,
                     to_device_batch, to_device_column)

__all__ = [
    "dtypes", "Batch", "Column", "concat_batches", "merge_dictionaries",
    "BLOCK_ROWS", "LANES", "DeviceColumn", "pad_len", "to_device_batch",
    "to_device_column",
]
