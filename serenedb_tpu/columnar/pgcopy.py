"""PG binary COPY format codec.

Reference analog: server/connector/duckdb_pg_binary_copy.cpp — the
`PGCOPY\\n\\377\\r\\n\\0` signature, 4-byte flags + extension, per-tuple
int16 field count and int32-length-prefixed fields in PG binary send
format, int16 -1 trailer. Value encodings match server/pgwire.pg_binary
(network byte order; timestamps/dates on the 2000-01-01 PG epoch).
"""

from __future__ import annotations

import struct
from typing import Optional

from .. import errors
from . import dtypes as dt
from .column import Batch, Column

SIGNATURE = b"PGCOPY\n\xff\r\n\x00"

_PG_EPOCH_US = 946_684_800_000_000
_PG_EPOCH_DAYS = 10_957


_OID_IDS = (dt.TypeId.OID, dt.TypeId.REGCLASS, dt.TypeId.REGTYPE,
            dt.TypeId.REGPROC, dt.TypeId.REGNAMESPACE)


def encode_value(v, typ: dt.SqlType) -> Optional[bytes]:
    """One field's binary payload (no length prefix); None = NULL.
    Single source of truth for PG binary sends — the wire result encoder
    (server/pgwire.pg_binary) delegates here."""
    if v is None:
        return None
    tid = typ.id
    if tid is dt.TypeId.BOOL:
        return b"\x01" if v else b"\x00"
    if tid in (dt.TypeId.TINYINT, dt.TypeId.SMALLINT):
        return struct.pack("!h", int(v))
    if tid is dt.TypeId.INT:
        return struct.pack("!i", int(v))
    if tid is dt.TypeId.BIGINT:
        return struct.pack("!q", int(v))
    if tid is dt.TypeId.FLOAT:
        return struct.pack("!f", float(v))
    if tid is dt.TypeId.DOUBLE:
        return struct.pack("!d", float(v))
    if tid is dt.TypeId.TIMESTAMP:
        return struct.pack("!q", int(v) - _PG_EPOCH_US)
    if tid is dt.TypeId.DATE:
        return struct.pack("!i", int(v) - _PG_EPOCH_DAYS)
    if tid is dt.TypeId.INTERVAL:
        return struct.pack("!qii", int(v), 0, 0)
    if tid in _OID_IDS:
        return struct.pack("!I", int(v) & 0xFFFFFFFF)
    if tid is dt.TypeId.ARRAY:
        return _encode_array_binary(str(v), typ.elem or dt.TypeId.VARCHAR)
    if tid is dt.TypeId.RECORD:
        return _encode_record_binary(str(v))
    return str(v).encode()


#: element TypeId → array OID (PG catalog values; record fields carry
#: these so nested arrays render/encode as real arrays)
_ARRAY_OID_OF_ELEM = {
    dt.TypeId.BOOL: 1000, dt.TypeId.SMALLINT: 1005, dt.TypeId.TINYINT: 1005,
    dt.TypeId.INT: 1007, dt.TypeId.BIGINT: 1016, dt.TypeId.FLOAT: 1021,
    dt.TypeId.DOUBLE: 1022, dt.TypeId.VARCHAR: 1009,
    dt.TypeId.DATE: 1182, dt.TypeId.TIMESTAMP: 1115,
}

#: OID → SqlType for record field encoding/rendering (record values
#: carry per-field OIDs in their physical JSON)
_TYPE_OF_OID = {
    16: dt.BOOL, 21: dt.SMALLINT, 23: dt.INT, 20: dt.BIGINT,
    700: dt.FLOAT, 701: dt.DOUBLE, 25: dt.VARCHAR,
    1082: dt.DATE, 1114: dt.TIMESTAMP, 1186: dt.INTERVAL,
    2249: dt.RECORD,
}
for _e, _oid in _ARRAY_OID_OF_ELEM.items():
    _TYPE_OF_OID.setdefault(_oid, dt.SqlType(dt.TypeId.ARRAY, _e))

#: TypeId → field OID for ROW(...) construction (scalars; arrays and
#: records go through field_oid below)
FIELD_OID = {
    dt.TypeId.BOOL: 16, dt.TypeId.TINYINT: 21, dt.TypeId.SMALLINT: 21,
    dt.TypeId.INT: 23, dt.TypeId.BIGINT: 20, dt.TypeId.FLOAT: 700,
    dt.TypeId.DOUBLE: 701, dt.TypeId.VARCHAR: 25, dt.TypeId.NULL: 25,
    dt.TypeId.DATE: 1082, dt.TypeId.TIMESTAMP: 1114,
    dt.TypeId.INTERVAL: 1186, dt.TypeId.RECORD: 2249,
}


def field_oid(t: dt.SqlType) -> int:
    if t.id is dt.TypeId.ARRAY:
        return _ARRAY_OID_OF_ELEM.get(t.elem or dt.TypeId.VARCHAR, 1009)
    return FIELD_OID.get(t.id, 25)


def record_parts(json_text: str):
    """Physical record JSON → ([oid, ...], [value, ...]); None when the
    payload is not a record."""
    import json as _json
    try:
        obj = _json.loads(json_text)
    except Exception:
        return None
    if not (isinstance(obj, dict) and isinstance(obj.get("o"), list)
            and isinstance(obj.get("v"), list)
            and len(obj["o"]) == len(obj["v"])):
        return None
    return obj["o"], obj["v"]


def _scalar_field_text(t: dt.SqlType, v) -> str:
    if t.id is dt.TypeId.BOOL or isinstance(v, bool):
        return "t" if v else "f"
    if t.id is dt.TypeId.TIMESTAMP:
        from ..sql.binder import format_timestamp
        return format_timestamp(int(v))
    if t.id is dt.TypeId.DATE:
        import numpy as _np
        return str(_np.datetime64(int(v), "D"))
    if t.id is dt.TypeId.INTERVAL:
        from ..sql.binder import format_interval
        return format_interval(int(v))
    if isinstance(v, float):
        import math as _math
        if _math.isnan(v):
            return "NaN"
        if _math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))   # PG float8 out: 2, not 2.0
        return repr(v)
    return str(v)


def _array_field_text(json_text: str, elem) -> str:
    """JSON array payload → PG {…} text (element-level; no reg* types
    inside records)."""
    import json as _json
    try:
        vals = _json.loads(json_text)
    except Exception:
        return json_text
    if not isinstance(vals, list):
        return json_text
    et = dt.SqlType(elem) if elem is not None else dt.VARCHAR

    def one(v):
        if v is None:
            return "NULL"
        if isinstance(v, bool):
            return "t" if v else "f"
        if isinstance(v, list):
            return "{" + ",".join(one(x) for x in v) + "}"
        if et.id in (dt.TypeId.DATE, dt.TypeId.TIMESTAMP,
                     dt.TypeId.INTERVAL) and isinstance(v, int):
            return _scalar_field_text(et, v)
        if isinstance(v, str):
            if v == "" or any(ch in v for ch in ',{}"\\ ') or \
                    v.upper() == "NULL":
                return '"' + v.replace("\\", "\\\\").replace(
                    '"', '\\"') + '"'
            return v
        if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return str(v)
    return "{" + ",".join(one(v) for v in vals) + "}"


def _field_rank(v):
    """Type-class rank for cross-kind total ordering inside records."""
    if isinstance(v, bool):
        return 0
    if isinstance(v, (int, float)):
        return 1
    if isinstance(v, str):
        return 2
    return 3


def _cmp_fields(x, y) -> int:
    if isinstance(x, bool) or isinstance(y, bool):
        x, y = bool(x), bool(y)
    rx, ry = _field_rank(x), _field_rank(y)
    if rx != ry:
        return -1 if rx < ry else 1
    if x == y:
        return 0
    try:
        return -1 if x < y else 1
    except TypeError:
        sx, sy = str(x), str(y)
        return -1 if sx < sy else (1 if sx > sy else 0)


def record_cmp_sql(ta: str, tb: str):
    """SQL-operator record comparison: field-wise, first difference
    decides; a NULL field reached before a decision makes the result
    SQL NULL (returns None). PG: ROW(1,NULL)=ROW(2,NULL) is false,
    ROW(1,NULL)=ROW(1,NULL) is NULL. Raises on arity mismatch like PG's
    'cannot compare dissimilar column types'."""
    from .. import errors
    pa, pb = record_parts(ta), record_parts(tb)
    if pa is None or pb is None:
        return _cmp_fields(ta, tb)
    va, vb = pa[1], pb[1]
    if len(va) != len(vb):
        raise errors.SqlError(
            "42804", "cannot compare records with different numbers "
                     "of columns")
    for x, y in zip(va, vb):
        if x is None or y is None:
            return None
        c = _cmp_fields(x, y)
        if c != 0:
            return c
    return 0


def record_cmp_total(ta: str, tb: str) -> int:
    """Btree-style total order for sorting records (PG record_cmp):
    NULL fields sort after every value; NULL == NULL for ordering."""
    pa, pb = record_parts(ta), record_parts(tb)
    if pa is None or pb is None:
        return _cmp_fields(ta, tb)
    va, vb = pa[1], pb[1]
    if len(va) != len(vb):
        return -1 if len(va) < len(vb) else 1
    for x, y in zip(va, vb):
        if x is None and y is None:
            continue
        if x is None:
            return 1
        if y is None:
            return -1
        c = _cmp_fields(x, y)
        if c != 0:
            return c
    return 0


def record_text(json_text: str) -> str:
    """Physical record JSON → PG (…) output (reference:
    server/pg/serialize.cpp record_out): NULL fields are empty; fields
    containing , ( ) " \\ or any whitespace (or empty strings) are quoted
    with doubled quotes. Nested records and arrays render recursively."""
    parts = record_parts(json_text)
    if parts is None:
        return json_text
    oids, vals = parts
    out = []
    for oid, v in zip(oids, vals):
        if v is None:
            out.append("")
            continue
        t = _TYPE_OF_OID.get(int(oid), dt.VARCHAR)
        if t.id is dt.TypeId.RECORD:
            s = record_text(str(v))
        elif t.id is dt.TypeId.ARRAY:
            s = _array_field_text(str(v), t.elem)
        else:
            s = _scalar_field_text(t, v)
        if s == "" or any(ch in s for ch in ',()"\\') or \
                any(ch.isspace() for ch in s):
            s = '"' + s.replace("\\", "\\\\").replace('"', '""') + '"'
        out.append(s)
    return "(" + ",".join(out) + ")"


def _encode_record_binary(json_text: str) -> bytes:
    """PG binary record format: int32 nfields, then per field int32 OID +
    length-prefixed binary payload (reference: server/pg/serialize.cpp
    record_send)."""
    parts = record_parts(json_text)
    if parts is None:
        # not a record payload — one text field
        payload = json_text.encode()
        return struct.pack("!i", 1) + struct.pack("!Ii", 25, len(payload)) \
            + payload
    oids, vals = parts
    out = [struct.pack("!i", len(vals))]
    for oid, v in zip(oids, vals):
        t = _TYPE_OF_OID.get(int(oid), dt.VARCHAR)
        if v is None:
            out.append(struct.pack("!Ii", int(oid), -1))
            continue
        payload = encode_value(v, t)
        out.append(struct.pack("!Ii", int(oid), len(payload)) + payload)
    return b"".join(out)


#: element TypeId → (element OID, element SqlType) for array binary sends
_ARRAY_ELEM = {
    dt.TypeId.BOOL: 16, dt.TypeId.TINYINT: 21, dt.TypeId.SMALLINT: 21,
    dt.TypeId.INT: 23, dt.TypeId.BIGINT: 20, dt.TypeId.FLOAT: 700,
    dt.TypeId.DOUBLE: 701, dt.TypeId.VARCHAR: 25,
    dt.TypeId.DATE: 1082, dt.TypeId.TIMESTAMP: 1114,
}


def _encode_array_binary(json_text: str, elem: dt.TypeId) -> bytes:
    """PG binary array format: ndim, hasnull, elem oid, (dim, lbound),
    then length-prefixed elements (reference: server/pg/serialize.cpp
    array_send). One-dimensional; the physical JSON representation."""
    import json as _json
    try:
        vals = _json.loads(json_text)
    except Exception:
        vals = None
    if not isinstance(vals, list):
        # not an array payload after all — send as text elements
        vals = [json_text]
    hasnull = any(v is None for v in vals)
    et = dt.SqlType(elem)
    out = [struct.pack("!iiI", 1, 1 if hasnull else 0,
                       _ARRAY_ELEM.get(elem, 25)),
           struct.pack("!ii", len(vals), 1)]
    for v in vals:
        if v is None:
            out.append(struct.pack("!i", -1))
            continue
        if isinstance(v, list):
            payload = _json.dumps(v).encode()   # nested: text fallback
        else:
            payload = encode_value(v, et)
        out.append(struct.pack("!i", len(payload)) + payload)
    return b"".join(out)


def decode_value(raw: bytes, typ: dt.SqlType):
    tid = typ.id
    try:
        if tid is dt.TypeId.BOOL:
            if len(raw) != 1:
                raise struct.error("bool is 1 byte")
            return raw != b"\x00"
        if tid in (dt.TypeId.TINYINT, dt.TypeId.SMALLINT):
            return struct.unpack("!h", raw)[0]
        if tid is dt.TypeId.INT:
            return struct.unpack("!i", raw)[0]
        if tid is dt.TypeId.BIGINT:
            return struct.unpack("!q", raw)[0]
        if tid is dt.TypeId.FLOAT:
            return struct.unpack("!f", raw)[0]
        if tid is dt.TypeId.DOUBLE:
            return struct.unpack("!d", raw)[0]
        if tid is dt.TypeId.TIMESTAMP:
            return struct.unpack("!q", raw)[0] + _PG_EPOCH_US
        if tid is dt.TypeId.DATE:
            return struct.unpack("!i", raw)[0] + _PG_EPOCH_DAYS
        if tid is dt.TypeId.INTERVAL:
            us, days, months = struct.unpack("!qii", raw)
            # our intervals are µs-only; days/months fold in at PG's
            # nominal 24h/30d (the text parser makes the same choice)
            return us + (days + months * 30) * 86_400_000_000
        if tid in _OID_IDS:
            return struct.unpack("!I", raw)[0]
        return raw.decode("utf-8")
    except (struct.error, UnicodeDecodeError):
        raise errors.SqlError(
            "22P03", f"incorrect binary data format for type {typ}")


def header() -> bytes:
    return SIGNATURE + struct.pack("!II", 0, 0)   # flags, extension length


def trailer() -> bytes:
    return struct.pack("!h", -1)


def encode_rows(batch: Batch) -> list[bytes]:
    """Per-tuple CopyData payloads (header/trailer NOT included)."""
    types = [c.type for c in batch.columns]
    cols = [c.to_pylist() for c in batch.columns]
    n_fields = struct.pack("!h", len(types))
    out = []
    for i in range(batch.num_rows):
        parts = [n_fields]
        for ci, t in enumerate(types):
            payload = encode_value(cols[ci][i], t)
            if payload is None:
                parts.append(struct.pack("!i", -1))
            else:
                parts.append(struct.pack("!i", len(payload)) + payload)
        out.append(b"".join(parts))
    return out


def decode_to_batch(data: bytes, names: list, types: list) -> Batch:
    """Binary COPY payload → Batch with the given column names/types."""
    cols = decode_stream(data, types)
    return Batch(list(names), [Column.from_pylist(v, t)
                               for v, t in zip(cols, types)])


def encode_full(batch: Batch) -> list[bytes]:
    """header + per-tuple payloads + trailer, ready to stream/write."""
    return [header()] + encode_rows(batch) + [trailer()]


def decode_stream(data: bytes, types: list[dt.SqlType]) -> list[list]:
    """Binary COPY payload → per-column python value lists.

    Tolerates the trailer being absent (some clients close the stream
    instead) but rejects a bad signature or malformed tuples."""
    if not data.startswith(SIGNATURE):
        raise errors.SqlError("22P04",
                              "COPY binary signature not recognized")
    off = len(SIGNATURE)
    if off + 8 > len(data):
        raise errors.SqlError("22P04", "invalid COPY binary header")
    flags, ext = struct.unpack_from("!II", data, off)
    off += 8 + ext
    cols: list[list] = [[] for _ in types]
    n = len(data)
    while off + 2 <= n:
        (nf,) = struct.unpack_from("!h", data, off)
        off += 2
        if nf == -1:
            break                      # trailer
        if nf != len(types):
            raise errors.SqlError(
                "22P04", f"row field count {nf}, expected {len(types)}")
        for ci in range(nf):
            if off + 4 > n:
                raise errors.SqlError("22P04",
                                      "unexpected EOF in COPY binary data")
            (ln,) = struct.unpack_from("!i", data, off)
            off += 4
            if ln < 0:
                cols[ci].append(None)
                continue
            if off + ln > n:
                raise errors.SqlError("22P04",
                                      "unexpected EOF in COPY binary data")
            cols[ci].append(decode_value(data[off:off + ln], types[ci]))
            off += ln
    return cols
