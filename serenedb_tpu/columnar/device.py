"""Host↔device movement of column batches.

The reference has no device boundary (single-process C++; SURVEY.md §5.8) —
this module *is* the new architecture's offload seam. Columns go to HBM as
2-D (rows/LANES, LANES) tiles so Pallas kernels see lane-aligned data:

- 1-D column of n rows → padded to a multiple of BLOCK_ROWS = 8*128 = 1024,
  reshaped to (n_pad // 128, 128). float64 is narrowed to float32 on device
  (analytics kernels accumulate in f32/i64; exact-parity paths stay on CPU).
- validity travels as a mask array of the same shape (True = valid row);
  padding rows are invalid.

`DeviceColumn` carries the logical length so kernels can mask the tail.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt
from .column import Batch, Column

LANES = 128
SUBLANES = 8
BLOCK_ROWS = LANES * SUBLANES  # 1024: one (8,128) f32 tile worth of rows


def pad_len(n: int, multiple: int = BLOCK_ROWS) -> int:
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


_DEVICE_DTYPE = {
    np.dtype(np.bool_): jnp.int8,     # bool as i8 lanes (mask math)
    np.dtype(np.int8): jnp.int8,
    np.dtype(np.int16): jnp.int32,
    np.dtype(np.int32): jnp.int32,
    np.dtype(np.int64): jnp.int32,    # see note below
    np.dtype(np.float32): jnp.float32,
    np.dtype(np.float64): jnp.float32,
}


@dataclass
class DeviceColumn:
    """A column resident on device as (n_pad/128, 128) tiles.

    Integer tiles whose value RANGE fits 8/16 bits ship compressed as
    frame-of-reference deltas (scheme 'for8'/'for16': stored = value -
    offset in uint8/uint16) and decode in-kernel with one add — a 2-4×
    HBM footprint cut on the analytics working set (reference analog:
    the adaptive-compressed column formats of
    libs/iresearch/include/iresearch/formats/column/). Consumers that
    need the logical values call decode(x) on the gathered tiles."""

    type: dt.SqlType
    data: jax.Array                 # 2-D (rows, LANES)
    mask: jax.Array                 # 2-D bool, same shape; False on padding
    length: int                     # logical row count
    scheme: str = "raw"             # raw | for8 | for16
    offset: int = 0                 # frame of reference (for8/for16)
    wide: Optional[jax.Array] = None  # optional i64-precision residual (unused yet)

    @property
    def padded_rows(self) -> int:
        return self.data.shape[0] * LANES

    def decode(self, tiles: jax.Array) -> jax.Array:
        """Decompress (a slice of) this column's tiles to logical values
        — traced inside jitted programs; one widen + add."""
        if self.scheme == "raw":
            return tiles
        return tiles.astype(jnp.int32) + jnp.int32(self.offset)


class DeviceNarrowingError(ValueError):
    """A column cannot be represented exactly on device (e.g. int64 values
    outside int32 range with x64 off). Callers treat this like a
    NotCompilable: fall back to the exact CPU path. Silently narrowing to
    f32 would make device SUM/compare results diverge from CPU — a parity
    violation, not an optimization."""


def _narrow_exact(arr: np.ndarray, n: int) -> np.ndarray:
    """int64 → int32 when provably exact (TPU x64 is off); raises
    DeviceNarrowingError otherwise — shared by tile conversion paths."""
    if arr.dtype == np.dtype(np.int64):
        if n == 0 or (np.abs(arr, dtype=np.float64).max(initial=0.0) < 2**31):
            return arr.astype(np.int32)
        raise DeviceNarrowingError(
            "int64 column with |values| >= 2^31: no exact device "
            "representation")
    return arr


#: raw-scheme host dtype per source dtype (the numpy mirror of
#: _DEVICE_DTYPE, for tiles built host-side before a stacked upload)
_HOST_TILE_DTYPE = {
    np.dtype(np.bool_): np.int8,
    np.dtype(np.int8): np.int8,
    np.dtype(np.int16): np.int32,
    np.dtype(np.int32): np.int32,
    np.dtype(np.float32): np.float32,
    np.dtype(np.float64): np.float32,
}


def host_tile_arrays(col: Column, rows_pad: int, scheme: str = "raw",
                     offset: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """HOST-side tile arrays of one column padded to exactly `rows_pad`
    rows: (data (rows_pad/LANES, LANES), mask bool same shape). The
    sharded tier's stacked collective programs need an IDENTICAL
    dtype/offset for every shard slice of a column, so the caller
    decides the frame-of-reference scheme ONCE (from whole-column
    stats) and passes it in — 'for8'/'for16' store value - offset as
    uint8/uint16 (the to_device_column compression, decoded in-kernel
    with one widen + add), 'raw' ships the device dtype unchanged."""
    n = len(col)
    assert rows_pad % LANES == 0 and rows_pad >= n
    arr = _narrow_exact(col.data, n)
    if scheme == "for8":
        arr = (arr.astype(np.int64) - offset).astype(np.uint8)
        np_dt = np.uint8
    elif scheme == "for16":
        arr = (arr.astype(np.int64) - offset).astype(np.uint16)
        np_dt = np.uint16
    else:
        np_dt = _HOST_TILE_DTYPE.get(arr.dtype, np.float32)
    padded = np.zeros(rows_pad, dtype=np_dt)
    padded[:n] = arr.astype(np_dt, copy=False)
    mask = np.zeros(rows_pad, dtype=bool)
    mask[:n] = col.valid_mask()
    return padded.reshape(-1, LANES), mask.reshape(-1, LANES)


def to_device_column(col: Column, pad_multiple: int = BLOCK_ROWS) -> DeviceColumn:
    n = len(col)
    n_pad = pad_len(n, pad_multiple)
    arr = _narrow_exact(col.data, n)
    dev_dt = _DEVICE_DTYPE.get(arr.dtype, jnp.float32)
    scheme, offset = "raw", 0
    if arr.dtype.kind == "i" and arr.dtype.itemsize > 1 and n:
        # frame-of-reference narrowing: range-fitting int tiles ship as
        # uint8/uint16 deltas and decode in-kernel (+offset)
        vmin = int(arr.min())
        vmax = int(arr.max())
        rng = vmax - vmin
        if rng < (1 << 8):
            scheme, offset, dev_dt = "for8", vmin, jnp.uint8
            arr = (arr.astype(np.int64) - vmin).astype(np.uint8)
        elif rng < (1 << 16):
            scheme, offset, dev_dt = "for16", vmin, jnp.uint16
            arr = (arr.astype(np.int64) - vmin).astype(np.uint16)
    padded = np.zeros(n_pad, dtype=arr.dtype)
    padded[:n] = arr
    mask = np.zeros(n_pad, dtype=bool)
    mask[:n] = col.valid_mask()
    import time as _time

    from ..obs import device as _obsdev
    t0 = _time.perf_counter_ns() if _obsdev.enabled() else 0
    data2d = jnp.asarray(padded.reshape(-1, LANES), dtype=dev_dt)
    mask2d = jnp.asarray(mask.reshape(-1, LANES))
    if t0:
        # every device path funnels through this upload: per-device
        # transfer byte/time attribution happens exactly once, here
        _obsdev.note_upload(
            int(data2d.size * data2d.dtype.itemsize) + int(mask2d.size),
            _obsdev.array_device_ids(data2d),
            _time.perf_counter_ns() - t0)
    # note that the backend is up so serene_shard_combine=auto's PASSIVE
    # device-count probe (parallel/mesh.py) works even across
    # jax-internal drift
    from ..parallel import mesh as _mesh
    _mesh.note_backend_initialized()
    return DeviceColumn(col.type, data2d, mask2d, n, scheme, offset)


def commit_host_array(arr: np.ndarray):
    """Upload one raw host array through the accounted choke point —
    the non-Column sibling of to_device_column for device subsystems
    that ship bare numpy payloads (the posting pool's staged pages and
    batch descriptor tables). Same ledger contract: per-device transfer
    byte/time attribution happens exactly once, here."""
    import time as _time

    from ..obs import device as _obsdev
    t0 = _time.perf_counter_ns() if _obsdev.enabled() else 0
    dev = jnp.asarray(arr)
    if t0:
        _obsdev.note_upload(int(dev.size * dev.dtype.itemsize),
                            _obsdev.array_device_ids(dev),
                            _time.perf_counter_ns() - t0)
    return dev


def to_device_batch(batch: Batch, columns: Optional[list[str]] = None) -> dict:
    names = columns if columns is not None else batch.names
    return {name: to_device_column(batch.column(name)) for name in names}
