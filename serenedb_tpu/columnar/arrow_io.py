"""Batch ⇄ Arrow IPC bytes (WAL payloads, parquet snapshots).

Reference analog: DataChunk zstd-1 serde inside WAL INLINE ops
(reference: server/search/search_db_wal.h:50-205). Arrow IPC gives a
well-defined binary frame with zero-copy numeric columns; zstd applied by
the WAL layer."""

from __future__ import annotations

import io

import numpy as np
import pyarrow as pa

from . import dtypes as dt
from .column import Batch, Column


def batch_to_arrow(batch: Batch) -> pa.RecordBatch:
    arrays = []
    fields = []
    for name, col in zip(batch.names, batch.columns):
        mask = ~col.validity if col.validity is not None else None
        if col.type.is_string:
            strs = col.dictionary.astype(str)[col.data] if \
                col.dictionary is not None else col.data.astype(str)
            arr = pa.array(strs, type=pa.string(), mask=mask)
        elif col.type.id is dt.TypeId.TIMESTAMP:
            arr = pa.array(col.data, type=pa.timestamp("us"), mask=mask)
        elif col.type.id is dt.TypeId.DATE:
            arr = pa.array(col.data, type=pa.date32(), mask=mask)
        else:
            arr = pa.array(col.data, mask=mask)
        arrays.append(arr)
        fields.append(pa.field(name, arr.type))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


def batch_to_bytes(batch: Batch) -> bytes:
    rb = batch_to_arrow(batch)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def bytes_to_batch(data: bytes) -> Batch:
    from ..exec.tables import _arrow_to_column
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        tbl = r.read_all()
    names = list(tbl.schema.names)
    cols = [_arrow_to_column(tbl.column(n)) for n in names]
    return Batch(names, cols)


def write_parquet_snapshot(path: str, batch: Batch) -> None:
    import pyarrow.parquet as pq
    rb = batch_to_arrow(batch)
    pq.write_table(pa.Table.from_batches([rb]), path)


def read_parquet_snapshot(path: str) -> Batch:
    import pyarrow.parquet as pq
    from ..exec.tables import columns_parallel
    tbl = pq.read_table(path, use_threads=False)
    names = list(tbl.schema.names)
    cols = columns_parallel(tbl, names)
    return Batch(names, [cols[n] for n in names])
