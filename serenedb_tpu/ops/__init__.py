"""Device kernels (JAX/XLA + Pallas).

Policy: elementwise predicate/projection chains and simple reductions are
plain jnp — XLA already fuses them into single HBM passes, which is the win
for scan/filter/aggregate. Pallas is reserved for the shapes XLA can't fuse
well: posting-block BM25 scoring + top-k (ops/bm25.py), bitpacked posting
decode, and IVF scan.
"""

from . import agg

__all__ = ["agg"]
