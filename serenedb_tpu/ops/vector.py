"""Vector kernels: k-means training and IVF top-k search on the MXU.

Reference analog: libs/iresearch/formats/ivf/ (faiss-backed k-means
centroids, cluster posting lists, SQ8, nprobe/rerank knobs; SURVEY.md §2.7).

TPU re-design: distance computation IS a matmul, so both k-means Lloyd
iterations and search ride the MXU:

- kmeans: assignment = argmin over  ||x||² − 2·X·Cᵀ + ||c||²  tiles;
  centroid update = one-hot(assign)ᵀ @ X (another matmul).
- IVF search: query→centroid distances pick the nprobe nearest lists; the
  candidate mask (vector's list ∈ top-nprobe) is applied to a full Q×N
  distance matmul. On MXU hardware the full matmul is cheaper than gather
  plumbing at these shapes — IVF semantics (recall vs nprobe) are preserved
  exactly while compute stays dense. Queries batch per dispatch like BM25.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def pad_rows(a: np.ndarray, multiple: int = 8) -> np.ndarray:
    pad = (-a.shape[0]) % multiple
    if pad:
        a = np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
    return a


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def kmeans_fit(x: jax.Array, init: jax.Array, k: int,
               iters: int) -> jax.Array:
    """Lloyd's k-means on device. x: (N, D) f32 (padding rows must be far
    sentinels or excluded via weights — caller passes valid rows only,
    padded by repeating real rows). Returns (k, D) centroids."""

    def step(c, _):
        d = _sq_dists(x, c)
        assign = jnp.argmin(d, axis=1)
        oh = jax.nn.one_hot(assign, k, dtype=jnp.float32)   # (N, K)
        counts = oh.sum(axis=0)                              # (K,)
        sums = jnp.einsum("nk,nd->kd", oh, x)
        new_c = sums / jnp.maximum(counts[:, None], 1.0)
        # empty clusters keep their previous centroid
        new_c = jnp.where(counts[:, None] > 0, new_c, c)
        return new_c, None

    c, _ = jax.lax.scan(step, init, None, length=iters)
    return c


def _sq_dists(x: jax.Array, c: jax.Array) -> jax.Array:
    """Squared L2 distances (N, K) via the matmul identity."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(c * c, axis=1)[None, :]
    return x2 - 2.0 * (x @ c.T) + c2


@functools.partial(jax.jit, static_argnames=())
def assign_clusters(x: jax.Array, centroids: jax.Array) -> jax.Array:
    return jnp.argmin(_sq_dists(x, centroids), axis=1).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("k", "nprobe", "metric"))
def ivf_topk(queries: jax.Array, vectors: jax.Array, valid: jax.Array,
             centroids: jax.Array, codes: jax.Array, k: int, nprobe: int,
             metric: str) -> tuple[jax.Array, jax.Array]:
    """Batched IVF top-k. queries (Q,D); vectors (N,D) HBM-resident;
    valid (N,) bool (False = padding/NULL row); codes (N,) int32 cluster of
    each vector. Returns (distances (Q,k), indices (Q,k)); masked-out
    candidates get +inf distance.

    metric: l2 (squared L2), ip (negative inner product so smaller=better),
    cos (cosine distance)."""
    if metric == "l2":
        d_qc = _sq_dists(queries, centroids)
        d_qn = _sq_dists(queries, vectors)
    elif metric == "ip":
        d_qc = -(queries @ centroids.T)
        d_qn = -(queries @ vectors.T)
    else:  # cosine distance
        qn = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-9)
        cn = centroids / jnp.maximum(
            jnp.linalg.norm(centroids, axis=1, keepdims=True), 1e-9)
        vn = vectors / jnp.maximum(
            jnp.linalg.norm(vectors, axis=1, keepdims=True), 1e-9)
        d_qc = 1.0 - qn @ cn.T
        d_qn = 1.0 - qn @ vn.T
    # top-nprobe clusters per query → candidate mask over vectors
    # (via a (Q, K) probe bitmap gathered by vector code — never a
    # (Q, nprobe, N) broadcast)
    _, probe = jax.lax.top_k(-d_qc, nprobe)                 # (Q, nprobe)
    q_count = queries.shape[0]
    probemask = jnp.zeros((q_count, centroids.shape[0]), dtype=jnp.bool_)
    probemask = probemask.at[jnp.arange(q_count)[:, None], probe].set(True)
    in_probe = probemask[:, codes]                          # (Q, N)
    masked = jnp.where(jnp.logical_and(in_probe, valid[None, :]),
                       d_qn, jnp.inf)
    neg, idx = jax.lax.top_k(-masked, k)
    return -neg, idx


def init_centroids(x: np.ndarray, k: int, seed: int = 0) -> np.ndarray:
    """k-means++-lite init on host: random distinct samples."""
    rng = np.random.default_rng(seed)
    n = len(x)
    if n >= k:
        idx = rng.choice(n, k, replace=False)
    else:
        idx = rng.choice(max(n, 1), k, replace=True)
    return np.ascontiguousarray(x[idx], dtype=np.float32)


def sq8_quantize(x: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-dimension affine SQ8: x ≈ lo + (q/255)·(hi−lo), q ∈ uint8
    (reference: the IVF scalar quantizer, ivf_writer.hpp)."""
    lo = x.min(axis=0)
    hi = x.max(axis=0)
    scale = np.where(hi > lo, hi - lo, 1.0)
    q = np.clip(np.round((x - lo) / scale * 255.0), 0, 255).astype(np.uint8)
    return q, lo.astype(np.float32), scale.astype(np.float32)


def sq8_dequantize(q: np.ndarray, lo: np.ndarray,
                   scale: np.ndarray) -> np.ndarray:
    return (lo + q.astype(np.float32) / 255.0 * scale).astype(np.float32)


def sq8_roundtrip(x: np.ndarray) -> np.ndarray:
    """Quantize+dequantize: the f32 values the device will score with."""
    q, lo, scale = sq8_quantize(x)
    return sq8_dequantize(q, lo, scale)
