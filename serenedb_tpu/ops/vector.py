"""Vector kernels: k-means training, IVF cluster-probe search, MaxSim.

Reference analog: libs/iresearch/formats/ivf/ (faiss-backed k-means
centroids, cluster posting lists, SQ8, nprobe/rerank knobs; SURVEY.md §2.7)
plus FLASH-MAXSIM's dimension-tiled late-interaction kernels.

TPU re-design: the seed's `ivf_topk` computed the full Q×N distance
matrix and only *masked* by probe bitmap — nprobe saved zero FLOPs and
zero HBM. The real pipeline here scales with probed clusters, not N:

- kmeans: assignment = argmin over  ||x||² − 2·X·Cᵀ + ||c||²  tiles;
  centroid update = one-hot(assign)ᵀ @ X (another matmul).
- probe: centroid distances (one small matmul-shaped reduce) pick the
  nprobe nearest lists; a scan walks the probed lists in fixed-size
  lane chunks, gathering candidate vectors from the paged HBM region
  through the slot map and exact-rescoring them with `dist_tail_expr`.
- selection: a running (distance, row) top-k carry merged per chunk
  with a two-key `lax.sort` — exact (score desc, doc asc) tie order by
  construction, no composite-key encoding (x64 stays off).

Bit-parity contract: probe, brute oracle and cold (pool-off) paths all
reduce identical `(Qp, MC, Dp)` gathered fragments through the same
`dist_tail_expr`, so per-(query,row) distance bits match and the exact
selection makes `nprobe=lists` bit-identical to host brute force.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import device as obs_device

#: row-id pad sentinel in sort keys: dead lanes carry (+inf, _PAD_ROW)
#: so they sort behind every live row; callers filter non-finite
#: distances (matches the posting-pool _PAD_DOC idiom)
_PAD_ROW = (1 << 31) - 1


def pad_rows(a: np.ndarray, multiple: int = 8) -> np.ndarray:
    pad = (-a.shape[0]) % multiple
    if pad:
        a = np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
    return a


def _pow2(n: int, floor: int = 1) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


# -- distance expression (THE parity-bearing fragment) -----------------------


def _chain_sum(terms) -> jax.Array:
    """Left-to-right f32 add chain over an iterator of equal-shape
    arrays. The chain is explicit in the HLO graph, so XLA cannot
    reassociate it (the `_accumulate` idiom from the posting pool), and
    it fuses into one kernel vectorized across the batch lanes. One
    backend freedom remains: instruction selection may contract a
    product feeding an add into an fma (observed on XLA:CPU even with
    fast-math off and an optimization_barrier — the machine combiner
    fires below HLO). Contraction only SKIPS a rounding, so whenever
    the chain arithmetic is exact the bits are grouping-independent;
    see `host_dist` for how the parity contract uses that."""
    acc = None
    for t in terms:
        acc = t if acc is None else acc + t
    return acc


def dist_tail_expr(x: jax.Array, q: jax.Array, metric: str) -> jax.Array:
    """Distance over the LAST axis — elementwise ops + a sequential add
    chain, never the matmul identity. Every scoring path (probe
    rescore, brute oracle, cold fallback) funnels through this one
    expression, and `host_dist` mirrors it add-for-add in numpy. The
    association order is graph-fixed, so trailing zero-padded
    dimensions are exact no-ops and batch/padding shapes never move the
    bits — that is what makes `nprobe=lists` ≡ brute-force parity hold
    per-row instead of per-launch-shape. l2 = squared L2, ip = negative
    inner product (smaller = better), cos = cosine distance."""
    d = x.shape[-1]
    if metric == "l2":
        dv = x - q
        return _chain_sum(dv[..., j] * dv[..., j] for j in range(d))
    if metric == "ip":
        return -_chain_sum(x[..., j] * q[..., j] for j in range(d))
    nx = jnp.sqrt(_chain_sum(x[..., j] * x[..., j] for j in range(d)))
    nq = jnp.sqrt(_chain_sum(q[..., j] * q[..., j] for j in range(d)))
    dot = _chain_sum(x[..., j] * q[..., j] for j in range(d))
    return 1.0 - dot / jnp.maximum(nx * nq, 1e-9)


def host_dist(x: np.ndarray, q: np.ndarray, metric: str) -> np.ndarray:
    """Numpy mirror of `dist_tail_expr`: identical elementwise ops in
    the identical left-to-right order over the last axis, all f32.
    Subtract/multiply/add/sqrt/divide are correctly rounded in both
    numpy and XLA, so the only device freedom left is fma contraction
    inside the chain (see `_chain_sum`). Contraction skips a rounding,
    so the mirror is BIT-exact whenever the chain arithmetic is exact —
    in particular for grid-quantized vectors (entries k/2^g with
    products and partial sums under 2^24 ulps), which is what the
    parity suites and the bench parity leg use. On arbitrary real data
    the mirror is exact to ≤1 ulp per distance, and the top-k ROW order
    still matches except between rows whose distances collide within
    that ulp. The `+ 0.0` canonicalizes -0.0 like the device programs."""
    x = np.asarray(x, np.float32)
    q = np.asarray(q, np.float32)
    d = x.shape[-1]

    def chain(terms):
        acc = None
        for t in terms:
            acc = t if acc is None else acc + t
        return acc

    if metric == "l2":
        dv = x - q
        return chain(dv[..., j] * dv[..., j] for j in range(d)) + \
            np.float32(0.0)
    if metric == "ip":
        return -chain(x[..., j] * q[..., j] for j in range(d)) + \
            np.float32(0.0)
    nx = np.sqrt(chain(x[..., j] * x[..., j] for j in range(d)))
    nq = np.sqrt(chain(q[..., j] * q[..., j] for j in range(d)))
    dot = chain(x[..., j] * q[..., j] for j in range(d))
    return (np.float32(1.0) -
            dot / np.maximum(nx * nq, np.float32(1e-9))) + np.float32(0.0)


def _merge_topk(best_d, best_r, d, r, kk: int):
    """Merge one chunk's (distance, row) lanes into the running top-kk
    carry: two-key `lax.sort` on (f32 distance asc, i32 row asc) — the
    PR 11 exact tie order without any composite encode (int64 would
    silently truncate with x64 off). Rows are distinct across chunks,
    so the selection is exact and chunk-order independent."""
    cd = jnp.concatenate([best_d, d], axis=1)
    cr = jnp.concatenate([best_r, r], axis=1)
    sd, sr = jax.lax.sort((cd, cr), num_keys=2)
    return sd[:, :kk], sr[:, :kk]


# -- k-means (ledger-routed; matmul identity is fine here — no parity
#    contract binds training to the scoring expression) ----------------------


def _sq_dists(x: jax.Array, c: jax.Array) -> jax.Array:
    """Squared L2 distances (N, K) via the matmul identity."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(c * c, axis=1)[None, :]
    return x2 - 2.0 * (x @ c.T) + c2


def _kmeans_program(k: int, iters: int):
    def run(x, init):
        def step(c, _):
            d = _sq_dists(x, c)
            assign = jnp.argmin(d, axis=1)
            oh = jax.nn.one_hot(assign, k, dtype=jnp.float32)   # (N, K)
            counts = oh.sum(axis=0)                              # (K,)
            sums = jnp.einsum("nk,nd->kd", oh, x)
            new_c = sums / jnp.maximum(counts[:, None], 1.0)
            # empty clusters keep their previous centroid
            new_c = jnp.where(counts[:, None] > 0, new_c, c)
            return new_c, None

        c, _ = jax.lax.scan(step, init, None, length=iters)
        return c

    return run


def kmeans_fit(x: jax.Array, init: jax.Array, k: int,
               iters: int) -> jax.Array:
    """Lloyd's k-means on device. x: (N, D) f32 (caller passes valid
    rows only, padded by repeating real rows). Returns (k, D)
    centroids."""
    prog = obs_device.compiled(
        "vector_kmeans", (x.shape[0], x.shape[1], k, iters),
        lambda: _kmeans_program(k, iters))
    return prog(x, init)


def _assign_program():
    def run(x, centroids):
        return jnp.argmin(_sq_dists(x, centroids), axis=1).astype(jnp.int32)

    return run


def assign_clusters(x: jax.Array, centroids: jax.Array) -> jax.Array:
    prog = obs_device.compiled(
        "vector_assign", (x.shape[0], x.shape[1], centroids.shape[0]),
        lambda: _assign_program())
    return prog(x, centroids)


# -- IVF probe / brute programs ----------------------------------------------


def probe_program(metric: str, dp: int, l_real: int, nprobe: int,
                  kk: int, mc: int):
    """Builder for the cluster-probe rescore program (one jitted
    dispatch per coalesced batch). Statics name the padded geometry;
    the caller's `obs_device.compiled` key adds the array shapes.

    Inputs: region (pages, PAGE_F32) or (npos_pad, dp) f32; slotmap
    (npos_pad,) i32 logical position → region row; offsets/counts (Lp,)
    i32 per-cluster logical extents; rowids (npos_pad,) i32 (pad =
    _PAD_ROW); cents (Lp, dp) f32; queries (Qp, dp) f32; tmap/jmap
    (nchunks, mc) i32 — the host-built flattening of the (nprobe, M)
    probe grid into mc-lane chunks (jmap pad = M → dead lane). Scan
    temps stay bounded at (Qp, mc, dp) regardless of N."""

    def run(region, slotmap, offsets, counts, rowids, cents, queries,
            tmap, jmap):
        rg = region.reshape(-1, dp)
        lp = cents.shape[0]
        qd = dist_tail_expr(queries[:, None, :], cents[None, :, :],
                            metric) + 0.0
        qd = jnp.where(jnp.arange(lp)[None, :] < l_real, qd, jnp.inf)
        # top-nprobe lists; top_k breaks distance ties by lower cluster
        # id — deterministic probe sets
        _, probe = jax.lax.top_k(-qd, nprobe)                 # (Q, nprobe)
        qp = queries.shape[0]

        def step(carry, chunk):
            best_d, best_r = carry
            tm, jm = chunk                                    # (mc,)
            cl = jnp.take(probe, tm, axis=1)                  # (Q, mc)
            base = jnp.take(offsets, cl)
            cnt = jnp.take(counts, cl)
            live = jm[None, :] < cnt
            pos = jnp.where(live, base + jm[None, :], 0)
            slot = jnp.take(slotmap, pos)
            x = jnp.take(rg, slot, axis=0)                    # (Q, mc, dp)
            d = dist_tail_expr(x, queries[:, None, :], metric) + 0.0
            row = jnp.take(rowids, pos)
            d = jnp.where(live, d, jnp.inf)
            row = jnp.where(live, row, _PAD_ROW)
            return _merge_topk(best_d, best_r, d, row, kk), None

        init = (jnp.full((qp, kk), jnp.inf, jnp.float32),
                jnp.full((qp, kk), _PAD_ROW, jnp.int32))
        (best_d, best_r), _ = jax.lax.scan(step, init, (tmap, jmap))
        return best_d, best_r

    return run


def chunk_maps(nprobe: int, m: int, mc: int) -> tuple[np.ndarray,
                                                      np.ndarray]:
    """Host-built flattening of the (nprobe, M) probe grid into mc-lane
    scan chunks: tmap = probe-slot index, jmap = within-cluster logical
    position (pad lanes get jmap = m, dead against every count)."""
    total = nprobe * m
    nchunks = max(1, -(-total // mc))
    tm = np.full(nchunks * mc, 0, np.int32)
    jm = np.full(nchunks * mc, m, np.int32)
    flat = np.arange(total, dtype=np.int64)
    tm[:total] = (flat // m).astype(np.int32)
    jm[:total] = (flat % m).astype(np.int32)
    return tm.reshape(nchunks, mc), jm.reshape(nchunks, mc)


# -- MaxSim late-interaction program -----------------------------------------


def maxsim_program(dp: int, tile: int, tmax: int, kk: int, dc: int):
    """Builder for the multi-vector MaxSim scorer (FLASH-MAXSIM shape):
    docs are the 'clusters' (one token matrix each), scanned in
    dc-doc chunks with tmax-token pads; the token×query-token similarity
    accumulates dimension-tiled (`tile` dims per einsum) so the
    (B, dc, tmax, S) similarity block is the only large temp. Query
    token rows padded with zeros add exactly 0.0 to every score (max
    over live tokens of zero dots is 0) — an exact no-op. Empty/pad
    docs score -inf → key +inf → filtered by the caller. Keys merge
    through the same two-key sort carry as the IVF probe, so the
    (score desc, doc asc) contract holds here too."""

    def run(region, slotmap, offsets, counts, rowids, queries,
            dmap):
        rg = region.reshape(-1, dp)
        b, s = queries.shape[0], queries.shape[1]

        def step(carry, dchunk):
            best_k, best_r = carry
            base = jnp.take(offsets, dchunk)                  # (dc,)
            cnt = jnp.take(counts, dchunk)
            t = jnp.arange(tmax, dtype=jnp.int32)
            live = t[None, :] < cnt[:, None]                  # (dc, tmax)
            pos = jnp.where(live, base[:, None] + t[None, :], 0)
            x = jnp.take(rg, jnp.take(slotmap, pos), axis=0)  # (dc,tmax,dp)
            sim = jnp.zeros((b, dc, tmax, s), jnp.float32)
            for i in range(0, dp, tile):
                sim = sim + jnp.einsum(
                    "dtx,bsx->bdts",
                    x[..., i:i + tile], queries[..., i:i + tile])
            sim = jnp.where(live[None, :, :, None], sim, -jnp.inf)
            score = jnp.sum(jnp.max(sim, axis=2), axis=2)     # (B, dc)
            key = -score + 0.0
            row = jnp.broadcast_to(jnp.take(rowids, dchunk)[None, :],
                                   (b, dc))
            return _merge_topk(best_k, best_r, key, row, kk), None

        init = (jnp.full((b, kk), jnp.inf, jnp.float32),
                jnp.full((b, kk), _PAD_ROW, jnp.int32))
        (best_k, best_r), _ = jax.lax.scan(step, init, dmap)
        return best_k, best_r

    return run


# -- host helpers -------------------------------------------------------------


def init_centroids(x: np.ndarray, k: int, seed: int = 0) -> np.ndarray:
    """k-means++-lite init on host: random distinct samples."""
    rng = np.random.default_rng(seed)
    n = len(x)
    if n >= k:
        idx = rng.choice(n, k, replace=False)
    else:
        idx = rng.choice(max(n, 1), k, replace=True)
    return np.ascontiguousarray(x[idx], dtype=np.float32)


def sq8_quantize(x: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-dimension affine SQ8: x ≈ lo + (q/255)·(hi−lo), q ∈ uint8
    (reference: the IVF scalar quantizer, ivf_writer.hpp)."""
    lo = x.min(axis=0)
    hi = x.max(axis=0)
    scale = np.where(hi > lo, hi - lo, 1.0)
    q = np.clip(np.round((x - lo) / scale * 255.0), 0, 255).astype(np.uint8)
    return q, lo.astype(np.float32), scale.astype(np.float32)


def sq8_dequantize(q: np.ndarray, lo: np.ndarray,
                   scale: np.ndarray) -> np.ndarray:
    return (lo + q.astype(np.float32) / 255.0 * scale).astype(np.float32)


def sq8_roundtrip(x: np.ndarray) -> np.ndarray:
    """Quantize+dequantize: the f32 values the device will score with."""
    q, lo, scale = sq8_quantize(x)
    return sq8_dequantize(q, lo, scale)
