"""Aggregation kernels: scalar reductions and hash GROUP BY.

Reference analog: DuckDB's vectorized (perfect-)hash aggregate operators (the
reference gets these from its DuckDB fork; SURVEY.md §1 L3). TPU re-design:

- Scalar aggregates are XLA reductions over (rows, 128) tiles with the
  validity mask folded in — XLA fuses predicate + mask + reduce into one HBM
  pass, the ClickBench Q1 shape.
- GROUP BY operates on *group codes* (dense ints in [0, G)). Dictionary
  VARCHAR columns already carry dense codes; other keys are factorized
  host-side per batch (np.unique-style).
- Exactness policy (PG parity: SUM(int) is BIGINT): JAX x64 stays off and
  TPU has no fast int64, so device kernels produce int32/f32 partials that
  are provably exact for their shapes, and the host combines them in numpy
  int64. Integer SUM scatters four 8-bit limbs into int32 group accumulators
  (exact while each group sees < 2^31/255 ≈ 8.4M rows per call; the executor
  chunks input below that). Small-G SUM/COUNT ride the MXU as one-hot f32
  matmuls over row chunks small enough that every partial stays within f32's
  exact-integer range.

All device entry points are jit-compiled with static group counts/ops.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

ONEHOT_MAX_GROUPS = 1024      # one-hot matmul path bound
ONEHOT_CHUNK = 2048           # rows per matmul chunk (f32-exactness bound)
SCATTER_SUM_MAX_ROWS = 4 << 20  # executor must chunk int-sum calls below this


# -- scalar reductions -----------------------------------------------------

@jax.jit
def masked_count(mask: jax.Array) -> jax.Array:
    return jnp.sum(mask, dtype=jnp.int32)


@jax.jit
def masked_sum_float(vals: jax.Array, mask: jax.Array) -> jax.Array:
    return jnp.sum(jnp.where(mask, vals, 0.0).astype(jnp.float32))


@jax.jit
def masked_sum_int_partials(vals: jax.Array, mask: jax.Array) -> jax.Array:
    """Per-tile-row int32 partial sums, split into 16-bit halves so each
    128-lane partial is exact in int32 for any int32 input (lo ≤ 128·65535,
    hi ≤ 128·2^15). Returns (rows, 2) [hi, lo]; host combines as
    (Σhi << 16) + Σlo in int64."""
    v = jnp.where(mask, vals, 0).astype(jnp.int32)
    lo = (v & 0xFFFF).astype(jnp.int32)
    hi = jnp.right_shift(v, 16)  # arithmetic shift: hi*2^16 + lo == v
    return jnp.stack([jnp.sum(hi, axis=1, dtype=jnp.int32),
                      jnp.sum(lo, axis=1, dtype=jnp.int32)], axis=1)


@functools.partial(jax.jit, static_argnames=("op",))
def masked_minmax(vals: jax.Array, mask: jax.Array, op: str) -> jax.Array:
    ident = _identity(vals.dtype, op)
    v = jnp.where(mask, vals, ident)
    return jnp.min(v) if op == "min" else jnp.max(v)


def masked_sum_int(vals: jax.Array, mask: jax.Array) -> int:
    parts = np.asarray(masked_sum_int_partials(vals, mask)).astype(np.int64)
    return int((parts[:, 0].sum() << 16) + parts[:, 1].sum())


def _identity(dtype, op):
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return info.max if op == "min" else info.min
    return jnp.inf if op == "min" else -jnp.inf


# -- grouped aggregation ---------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_groups",))
def group_count_onehot(codes: jax.Array, mask: jax.Array, num_groups: int) -> jax.Array:
    """(C-chunked one-hot matmul) per-group counts as f32 chunk partials
    (chunk, G); each entry ≤ ONEHOT_CHUNK so exact. Host sums in int64."""
    flat_codes = codes.reshape(-1)
    flat_mask = mask.reshape(-1).astype(jnp.float32)
    n = flat_codes.shape[0]
    c = -(-n // ONEHOT_CHUNK)
    pad = c * ONEHOT_CHUNK - n
    flat_codes = jnp.pad(flat_codes, (0, pad))
    flat_mask = jnp.pad(flat_mask, (0, pad))

    def chunk(_, args):
        cc, mm = args
        oh = jax.nn.one_hot(cc, num_groups, dtype=jnp.float32)
        return None, jnp.einsum("ng,n->g", oh, mm,
                                preferred_element_type=jnp.float32)

    _, ys = jax.lax.scan(
        chunk, None,
        (flat_codes.reshape(c, ONEHOT_CHUNK), flat_mask.reshape(c, ONEHOT_CHUNK)))
    return ys  # (c, G) f32, each exact


@functools.partial(jax.jit, static_argnames=("num_groups",))
def group_count_scatter(codes: jax.Array, mask: jax.Array, num_groups: int) -> jax.Array:
    flat_codes = codes.reshape(-1)
    flat_mask = mask.reshape(-1)
    safe = jnp.where(flat_mask, flat_codes, 0)
    zero = jnp.zeros((num_groups,), dtype=jnp.int32)
    return zero.at[safe].add(flat_mask.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("num_groups",))
def group_sum_float(codes: jax.Array, mask: jax.Array, vals: jax.Array,
                    num_groups: int) -> jax.Array:
    flat_codes = codes.reshape(-1)
    flat_mask = mask.reshape(-1)
    v = jnp.where(flat_mask, vals.reshape(-1), 0.0).astype(jnp.float32)
    safe = jnp.where(flat_mask, flat_codes, 0)
    return jnp.zeros((num_groups,), dtype=jnp.float32).at[safe].add(v)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def group_sum_int_limbs(codes: jax.Array, mask: jax.Array, vals: jax.Array,
                        num_groups: int) -> jax.Array:
    """Exact int sum via 8-bit limb scatter-adds of the two's-complement
    representation: sum(v) = Σ_i (limb_sum_i << 8i) − (neg_count << 32).

    Returns (G, 5) int32: four byte-limb sums + count of negative values.
    Exact while each group sees < 2^31/255 ≈ 8.4M rows per call (the
    executor chunks calls at SCATTER_SUM_MAX_ROWS).
    """
    flat_codes = codes.reshape(-1)
    flat_mask = mask.reshape(-1)
    v = vals.reshape(-1).astype(jnp.int32)
    vu = jax.lax.bitcast_convert_type(v, jnp.uint32)
    safe = jnp.where(flat_mask, flat_codes, 0)
    m32 = flat_mask.astype(jnp.int32)
    out = jnp.zeros((num_groups, 5), dtype=jnp.int32)
    for limb in range(4):
        byte = (jnp.right_shift(vu, 8 * limb) & jnp.uint32(0xFF)).astype(jnp.int32)
        out = out.at[safe, limb].add(byte * m32)
    out = out.at[safe, 4].add((v < 0).astype(jnp.int32) * m32)
    return out


def combine_sum_int_limbs(limbs: np.ndarray) -> np.ndarray:
    """(G,5) limb sums (+neg count) → exact int64 group sums. Accepts a
    chunked (C,G,5) array too (summed in int64 first)."""
    if limbs.ndim == 3:
        limbs = limbs.astype(np.int64).sum(axis=0)
    acc = np.zeros(limbs.shape[0], dtype=np.int64)
    for limb in range(4):
        acc += limbs[:, limb].astype(np.int64) << (8 * limb)
    return acc - (limbs[:, 4].astype(np.int64) << 32)


SCATTER_CHUNK_TILES = SCATTER_SUM_MAX_ROWS // 128


def group_sum_int_limbs_chunked(codes: jax.Array, mask: jax.Array,
                                vals: jax.Array, num_groups: int) -> jax.Array:
    """Row-chunked variant of group_sum_int_limbs for inputs whose per-group
    row count could exceed the int32 limb-accumulator bound (~8.4M rows).
    Returns (C, G, 5); combine_sum_int_limbs handles the extra axis."""
    r = codes.shape[0]
    c = -(-r // SCATTER_CHUNK_TILES)
    pad = c * SCATTER_CHUNK_TILES - r
    codes = jnp.pad(codes, ((0, pad), (0, 0)))
    mask = jnp.pad(mask, ((0, pad), (0, 0)))
    vals = jnp.pad(vals, ((0, pad), (0, 0)))
    shape = (c, SCATTER_CHUNK_TILES, codes.shape[1])

    def body(args):
        cc, mm, vv = args
        return group_sum_int_limbs(cc, mm, vv, num_groups)

    return jax.lax.map(body, (codes.reshape(shape), mask.reshape(shape),
                              vals.reshape(shape)))


@functools.partial(jax.jit, static_argnames=("num_groups", "op"))
def group_min_max(codes: jax.Array, mask: jax.Array, vals: jax.Array,
                  num_groups: int, op: str) -> jax.Array:
    flat_codes = codes.reshape(-1)
    flat_mask = mask.reshape(-1)
    v = vals.reshape(-1)
    ident = _identity(v.dtype, op)
    v = jnp.where(flat_mask, v, ident)
    safe = jnp.where(flat_mask, flat_codes, 0)
    init = jnp.full((num_groups,), ident, dtype=v.dtype)
    return init.at[safe].min(v) if op == "min" else init.at[safe].max(v)


# -- host-facing grouped API ----------------------------------------------

def group_count(codes: jax.Array, mask: jax.Array, num_groups: int) -> np.ndarray:
    if num_groups <= ONEHOT_MAX_GROUPS:
        ys = np.asarray(group_count_onehot(codes, mask, num_groups))
        return ys.astype(np.int64).sum(axis=0)
    return np.asarray(group_count_scatter(codes, mask, num_groups)).astype(np.int64)


def group_sum_int(codes: jax.Array, mask: jax.Array, vals: jax.Array,
                  num_groups: int) -> np.ndarray:
    """Exact per-group int64 sums (limb decomposition, see
    group_sum_int_limbs)."""
    limbs = group_sum_int_limbs(codes, mask, vals, num_groups)
    return combine_sum_int_limbs(np.asarray(limbs))


def group_min(codes, mask, vals, num_groups) -> np.ndarray:
    return np.asarray(group_min_max(codes, mask, vals, num_groups, "min"))


def group_max(codes, mask, vals, num_groups) -> np.ndarray:
    return np.asarray(group_min_max(codes, mask, vals, num_groups, "max"))


# -- host-side key factorization ------------------------------------------

def factorize_keys(key_arrays: list[np.ndarray],
                   valids: list[Optional[np.ndarray]]) -> tuple[np.ndarray, list[np.ndarray], np.ndarray]:
    """Composite GROUP BY keys → dense codes.

    Returns (codes int32 [n], unique_key_value_columns, unique_valid (k, G)).
    NULL keys group together (PG GROUP BY semantics). Host-side O(n log n).
    """
    n = len(key_arrays[0])
    rows = []
    for arr, valid in zip(key_arrays, valids):
        a = np.asarray(arr)
        if a.dtype == np.bool_:
            a = a.astype(np.int8)
        if valid is not None:
            a = np.where(valid, a, np.zeros((), dtype=a.dtype))
            rows.append((~valid).astype(a.dtype))
        else:
            rows.append(np.zeros(n, dtype=a.dtype))
        rows.append(a)
    composite = np.stack(rows) if rows else np.zeros((0, n))
    first_idx, inverse = _unique_columns(composite)
    codes = inverse.astype(np.int32)
    uniq_cols = [np.asarray(arr)[first_idx] for arr in key_arrays]
    uniq_valid = np.stack(
        [v[first_idx] if v is not None else np.ones(len(first_idx), dtype=bool)
         for v in valids]) if valids else np.ones((0, len(first_idx)), dtype=bool)
    return codes, uniq_cols, uniq_valid


def factorize_codes(key_arrays: list[np.ndarray],
                    valids: list[Optional[np.ndarray]]
                    ) -> tuple[np.ndarray, int]:
    """Composite keys → (dense int64 codes, group count), skipping the
    unique-key-value materialization `factorize_keys` does — the join /
    set-op / DISTINCT ON consumers only need the equality classes.

    Equality semantics match the legacy row-tuple tier exactly: NULL keys
    group together (set ops / DISTINCT ON treat NULL = NULL; the join
    masks NULL-key rows out separately so NULL never matches), and every
    NaN occurrence is its own group (the lexsort `!=` comparison keeps
    NaN ≠ NaN, the same way python tuple equality does). Each key
    factorizes in its OWN dtype and only the resulting int64 code rows
    stack — a composite mixing int64 and float keys must never promote
    the ints to float64, where values beyond 2**53 would collapse.
    """
    code_rows = []
    for arr, valid in zip(key_arrays, valids):
        a = np.asarray(arr)
        if a.dtype == np.bool_:
            a = a.astype(np.int8)
        rows = []
        if valid is not None:
            a = np.where(valid, a, np.zeros((), dtype=a.dtype))
            rows.append((~valid).astype(a.dtype))
        rows.append(a)
        _, codes_k = _unique_columns(np.stack(rows))
        code_rows.append(codes_k)
    if not code_rows:
        return np.zeros(0, dtype=np.int64), 0
    if len(code_rows) == 1:
        inverse = code_rows[0]
        return inverse, int(inverse.max()) + 1 if len(inverse) else 0
    first_idx, inverse = _unique_columns(np.stack(code_rows))
    return inverse, len(first_idx)


def _unique_columns(composite: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Unique over columns of a (k, n) matrix → (first-occurrence idx, inverse)."""
    n = composite.shape[1]
    if n == 0:
        return np.array([], dtype=np.int64), np.array([], dtype=np.int64)
    order = np.lexsort(composite[::-1])
    sorted_cols = composite[:, order]
    neq = np.any(sorted_cols[:, 1:] != sorted_cols[:, :-1], axis=0)
    group_of_sorted = np.concatenate([[0], np.cumsum(neq)])
    inverse = np.empty(n, dtype=np.int64)
    inverse[order] = group_of_sorted
    first_idx = np.empty(int(group_of_sorted[-1]) + 1, dtype=np.int64)
    first_idx[group_of_sorted[::-1]] = order[::-1]
    return first_idx, inverse
