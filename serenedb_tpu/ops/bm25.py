"""BM25 block scoring + top-k on device — the search-side flagship kernel.

Reference analog: the hot loop of SURVEY.md §3.3 — block_disjunction over
block_128 postings with BM25 ScoreFunction per 128-doc block and WAND
block-max skipping (libs/iresearch/search/bm25.hpp, block_disjunction.hpp,
formats/posting/wand_writer.hpp).

TPU re-formulation (zero per-query posting transfers):

- At index-build time, postings of *heavy* terms (df ≥ HEAVY_DF) are packed
  into device-resident (n_blocks, 128) doc/tf tiles — the block_128 layout
  is exactly one TPU lane row. Light terms stay in the flat arrays.
- A query ships only: the block-row indices of its heavy terms (a few KB),
  a gathered tail array for its light terms, and per-term idf weights.
- One fused XLA program gathers the tiles, computes BM25 contributions,
  scatter-adds into a dense per-doc accumulator, and takes top-k.

Block-max pruning re-enters as *masking* (drop block rows whose upper bound
can't reach a threshold) rather than branching; the dense pass is exact.

Scoring follows the Lucene/IResearch BM25 ("k1=1.2, b=0.75", reference
bm25.hpp:30-80): idf = ln(1 + (N - df + 0.5)/(df + 0.5)),
score = Σ_t idf_t · (k1 + 1) · tf/(tf + k1·(1 − b + b·dl/avgdl)).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

BLOCK = 128
HEAVY_DF = 32     # terms with at least this many postings get block tiles


def idf_lucene(n_docs: int, doc_freq: np.ndarray) -> np.ndarray:
    df = doc_freq.astype(np.float64)
    return np.log(1.0 + (n_docs - df + 0.5) / (df + 0.5)).astype(np.float32)


def idf_tfidf(n_docs: int, doc_freq: np.ndarray) -> np.ndarray:
    """IResearch TFIDF idf: 1 + ln(N / (df + 1)) (reference: tfidf.cpp)."""
    df = doc_freq.astype(np.float64)
    return (1.0 + np.log(max(n_docs, 1) / (df + 1.0))).astype(np.float32)


def idf_for(scorer: str, n_docs: int, doc_freq: np.ndarray) -> np.ndarray:
    return idf_tfidf(n_docs, doc_freq) if scorer == "tfidf" \
        else idf_lucene(n_docs, doc_freq)


# language-model scorer family (reference: libs/iresearch/search/
# lm_dirichlet.cpp, jelinek_mercer.cpp, dfi.cpp). Their per-term weight is
# the collection probability p_t = ctf_t / total_tokens, not an idf; the
# hyper-parameter (µ or λ) rides the k1 float slot of the shared kernel.
LM_SCORERS = ("lm_dirichlet", "jelinek_mercer", "dfi")
LM_MU = 2000.0     # Dirichlet µ (Lucene LMDirichletSimilarity default)
JM_LAMBDA = 0.1    # Jelinek-Mercer λ (short-query default)
#: per-matched-posting score floor: lm_dirichlet/dfi legitimately score 0
#: on weak matches, but downstream keep-filters use score>0 ⇔ matched.
#: Far below score resolution, so ranking is unchanged.
MATCH_EPS = 1e-6


def scorer_param(scorer: str, k1: float) -> float:
    """The value carried in the kernel's k1 slot for this scorer."""
    if scorer == "lm_dirichlet":
        return LM_MU
    if scorer == "jelinek_mercer":
        return JM_LAMBDA
    return k1


def term_weight_for(scorer: str, n_docs: int, doc_freq: np.ndarray,
                    ctf: Optional[np.ndarray] = None,
                    total_tokens: float = 0.0) -> np.ndarray:
    """Per-term weight: idf for bm25/tfidf, collection probability p_t for
    the LM family."""
    if scorer in LM_SCORERS:
        total = max(float(total_tokens), 1.0)
        p = np.asarray(ctf, dtype=np.float64) / total
        return np.maximum(p, 1e-12).astype(np.float32)
    return idf_for(scorer, n_docs, doc_freq)


@dataclass
class BlockStore:
    """Device-resident posting tiles for one field index.

    HBM layout (the reference's block_128 bitpacked format re-expressed for
    TPU lanes, formats/posting/format_block_128.cpp): each 128-posting row
    of a heavy term is COMPRESSED as one int32 base doc + 128 uint16
    doc-gaps + 128 uint8 tfs (7 bytes/posting → vs 8 raw ≈ 2.3×) and
    decoded INSIDE the scoring kernel (cumsum along the lane axis — a
    log-step scan the VPU handles without leaving registers). Rows that
    don't fit (a doc gap ≥ 2^16 or a tf ≥ 2^8) stay in a small raw int32
    exception plane, mirroring streamvbyte's escape path."""

    block_base: jax.Array      # (NP+1,) int32 — first doc of each packed row
    block_gaps: jax.Array      # (NP+1, 128) uint16 — doc deltas, slot0 = 0
    block_tfs8: jax.Array      # (NP+1, 128) uint8 — tf, 0 marks padding
    raw_docs: jax.Array        # (NR+1, 128) int32, -1 padding
    raw_tfs: jax.Array         # (NR+1, 128) int32
    norms: jax.Array           # (ndocs_pad,) int32
    block_offsets: np.ndarray  # (T+1,) int64 — heavy terms' GLOBAL row spans
    heavy: np.ndarray          # (T,) bool
    flat_docs: np.ndarray      # host copies for the light-term tail
    flat_tfs: np.ndarray
    offsets: np.ndarray
    ndocs_pad: int
    pad_row: int               # GLOBAL index of the all-padding block row
    row_plane: np.ndarray      # (NB_total+1,) uint8 — 0 packed, 1 raw
    row_slot: np.ndarray       # (NB_total+1,) int32 — index within plane
    n_packed: int              # NP (packed pad slot = NP)
    n_raw: int                 # NR (raw pad slot = NR)
    # block-max (WAND) metadata, host-resident: per heavy block row the max
    # tf and min doc length — a score upper bound valid for any avgdl
    # (reference: formats/posting/wand_writer.hpp impact pairs)
    block_bmax_tf: np.ndarray = None   # (NB_total+1,) int32
    block_bmin_dl: np.ndarray = None   # (NB_total+1,) int32
    norms_host: np.ndarray = None      # (num_docs,) int32

    @property
    def hbm_bytes(self) -> int:
        """Posting-tile HBM footprint (norms excluded — shared)."""
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in (self.block_base, self.block_gaps,
                             self.block_tfs8, self.raw_docs, self.raw_tfs))

    @property
    def hbm_bytes_raw_equiv(self) -> int:
        """What the same rows would cost as raw int32 doc+tf tiles."""
        n_rows = len(self.row_plane)
        return n_rows * BLOCK * 8


def build_block_store(offsets: np.ndarray, post_docs: np.ndarray,
                      post_tfs: np.ndarray, doc_freq: np.ndarray,
                      norms: np.ndarray, num_docs: int) -> BlockStore:
    T = len(doc_freq)
    heavy = doc_freq >= HEAVY_DF
    nb_per = np.where(heavy, -(-doc_freq.astype(np.int64) // BLOCK), 0)
    block_offsets = np.zeros(T + 1, dtype=np.int64)
    np.cumsum(nb_per, out=block_offsets[1:])
    nb_total = int(block_offsets[-1])
    norms_h = np.ascontiguousarray(norms[:num_docs], dtype=np.int32)

    # Vectorized tile assembly: scatter every heavy posting into its
    # (row, lane) slot, -1/0 padding elsewhere.
    bdocs = np.full((nb_total + 1, BLOCK), -1, dtype=np.int32)
    btfs = np.zeros((nb_total + 1, BLOCK), dtype=np.int32)
    heavy_tids = np.flatnonzero(heavy)
    if len(heavy_tids):
        df_h = doc_freq[heavy_tids].astype(np.int64)
        pt = np.repeat(heavy_tids, df_h)                # term of each posting
        within = np.arange(len(pt), dtype=np.int64) - \
            np.repeat(np.cumsum(df_h) - df_h, df_h)     # rank within term
        src = np.repeat(offsets[heavy_tids], df_h) + within
        grow = np.repeat(block_offsets[heavy_tids], df_h) + within // BLOCK
        lane = within % BLOCK
        bdocs[grow, lane] = post_docs[src]
        btfs[grow, lane] = post_tfs[src]
    bmax_tf = btfs.max(axis=1).astype(np.int32)
    # bmin_dl without a full-size dl temporary: mask pads to int32-max
    dl_vals = norms_h[np.clip(bdocs, 0, None)] if num_docs \
        else np.zeros_like(bdocs)
    np.putmask(dl_vals, bdocs < 0, np.iinfo(np.int32).max)
    bmin_dl = dl_vals.min(axis=1).astype(np.int32)
    del dl_vals
    bmin_dl[-1] = np.iinfo(np.int32).max   # all-pad row

    # Pack: forward-fill pads with the last real doc so gaps stay small,
    # then delta-encode along the lane axis (in place — the build holds at
    # most two full-size temporaries at a time; tiles reach GBs at the 8M
    # bench shape).
    docs_ff = np.maximum.accumulate(bdocs, axis=1)
    base = docs_ff[:, 0].copy()
    docs_ff[:, 1:] = docs_ff[:, 1:] - docs_ff[:, :-1]
    docs_ff[:, 0] = 0
    gaps = docs_ff                      # reuse: docs_ff IS the gap array now
    packable = ((gaps.max(axis=1) < (1 << 16)) &
                (bmax_tf < (1 << 8)) & (base >= 0))
    packable[-1] = False     # keep the global pad row in the raw plane
    row_plane = np.where(packable, 0, 1).astype(np.uint8)
    row_slot = np.zeros(nb_total + 1, dtype=np.int32)
    row_slot[packable] = np.arange(int(packable.sum()), dtype=np.int32)
    row_slot[~packable] = np.arange(int((~packable).sum()), dtype=np.int32)
    n_packed = int(packable.sum())
    n_raw = int((~packable).sum())

    pk_base = np.zeros(n_packed + 1, dtype=np.int32)
    pk_gaps = np.zeros((n_packed + 1, BLOCK), dtype=np.uint16)
    pk_tfs = np.zeros((n_packed + 1, BLOCK), dtype=np.uint8)
    pk_base[:n_packed] = base[packable]
    pk_gaps[:n_packed] = gaps[packable].astype(np.uint16)
    del gaps, docs_ff
    r_docs = np.full((n_raw + 1, BLOCK), -1, dtype=np.int32)
    r_tfs = np.zeros((n_raw + 1, BLOCK), dtype=np.int32)
    r_docs[:n_raw] = bdocs[~packable]
    del bdocs
    pk_tfs[:n_packed] = btfs[packable].astype(np.uint8)
    r_tfs[:n_raw] = btfs[~packable]
    del btfs

    nd_pad = max(1024, ((num_docs + 1023) // 1024) * 1024)
    norms_pad = np.zeros(nd_pad, dtype=np.int32)
    norms_pad[:num_docs] = norms[:num_docs]
    return BlockStore(
        block_base=jnp.asarray(pk_base),
        block_gaps=jnp.asarray(pk_gaps),
        block_tfs8=jnp.asarray(pk_tfs),
        raw_docs=jnp.asarray(r_docs),
        raw_tfs=jnp.asarray(r_tfs),
        norms=jnp.asarray(norms_pad),
        block_offsets=block_offsets,
        heavy=heavy,
        flat_docs=post_docs,
        flat_tfs=post_tfs,
        offsets=offsets,
        ndocs_pad=nd_pad,
        pad_row=nb_total,
        row_plane=row_plane,
        row_slot=row_slot,
        n_packed=n_packed,
        n_raw=n_raw,
        block_bmax_tf=bmax_tf,
        block_bmin_dl=bmin_dl,
        norms_host=norms_h,
    )


@dataclass
class QueryBatch:
    """Host-assembled inputs for one scoring dispatch covering B queries.
    All arrays are tiny relative to the posting store (KBs per query).
    Heavy-term rows split across the two tile planes (packed / raw)."""

    row_idx: np.ndarray    # (NB,) int32 PACKED-plane row gather indices
    row_w: np.ndarray      # (NB,) f32 idf weight of the row's term
    row_qid: np.ndarray    # (NB,) int32 query index of the row
    raw_idx: np.ndarray    # (NR,) int32 RAW-plane row gather indices
    raw_w: np.ndarray      # (NR,) f32
    raw_qid: np.ndarray    # (NR,) int32
    tail_docs: np.ndarray  # (TT,) int32 light-term postings (docs)
    tail_tfs: np.ndarray   # (TT,) int32
    tail_w: np.ndarray     # (TT,) f32
    tail_qid: np.ndarray   # (TT,) int32
    require: np.ndarray    # (B,) int32 — 0 = disjunction, else min hits
    n_queries: int         # logical B before pow2 padding


def _sat_exact(tfs: np.ndarray, dls: np.ndarray, k1: float, b: float,
               avg: float, scorer: str) -> np.ndarray:
    """Per-posting saturation term of the score (score = w · sat)."""
    tfs = tfs.astype(np.float64)
    if scorer == "tfidf":
        return np.sqrt(tfs)
    denom = tfs + k1 * (1.0 - b + b * dls.astype(np.float64) /
                        max(avg, 1e-9))
    return (k1 + 1.0) * tfs / np.maximum(denom, 1e-9)


def _sparse_table(arr: np.ndarray) -> np.ndarray:
    """Range-max sparse table: tab[j, i] = max(arr[i : i + 2^j])."""
    n = len(arr)
    levels = max(1, int(n).bit_length())
    tab = np.full((levels, n), -np.inf)
    tab[0] = arr
    for j in range(1, levels):
        half = 1 << (j - 1)
        m = n - (1 << j) + 1
        if m <= 0:
            break
        tab[j, :m] = np.maximum(tab[j - 1, :m], tab[j - 1, half:half + m])
    return tab


def _range_max(tab: np.ndarray, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Vectorized max(arr[lo..hi]) (inclusive) over a sparse table."""
    length = (hi - lo + 1).astype(np.float64)
    j = np.floor(np.log2(np.maximum(length, 1.0))).astype(np.int64)
    j = np.minimum(j, tab.shape[0] - 1)
    left = tab[j, lo]
    right = tab[j, np.maximum(hi + 1 - (1 << j), 0)]
    return np.maximum(left, right)


def _bucket_tables(store: BlockStore, tid: int, avg: float, k1: float,
                   b: float, scorer: str, shift: int) -> np.ndarray:
    """Sparse range-max table of the term's per-doc-bucket max *sat* value
    (w-free; the caller scales by idf). Cached on the store — segments are
    immutable and avg is fixed per (segment, collection-stats) pair."""
    cache = getattr(store, "_bucket_cache", None)
    if cache is None:
        cache = store._bucket_cache = {}
    if len(cache) > 512:  # tables are up to ~1MB each — bound host RAM
        cache.clear()
    key = (tid, round(avg, 6), scorer, shift, k1, b)
    hit = cache.get(key)
    if hit is not None:
        return hit
    n_buckets = (store.ndocs_pad >> shift) + 1
    arr = np.zeros(n_buckets)
    s, e = int(store.offsets[tid]), int(store.offsets[tid + 1])
    if store.heavy[tid]:
        b0, b1 = int(store.block_offsets[tid]), int(store.block_offsets[tid + 1])
        r = np.arange(b0, b1, dtype=np.int64)
        sat = _sat_exact(store.block_bmax_tf[r], store.block_bmin_dl[r],
                         k1, b, avg, scorer)
        loc = r - b0
        first = store.flat_docs[s + loc * BLOCK]
        last = store.flat_docs[np.minimum(s + (loc + 1) * BLOCK, e) - 1]
        bs, be = first >> shift, last >> shift
        np.maximum.at(arr, bs, sat)
        np.maximum.at(arr, be, sat)
        for i in np.flatnonzero(be - bs >= 2):  # blocks spanning ≥3 buckets
            arr[bs[i] + 1:be[i]] = np.maximum(arr[bs[i] + 1:be[i]], sat[i])
    elif e > s:
        d = store.flat_docs[s:e]
        sat = _sat_exact(store.flat_tfs[s:e], store.norms_host[d],
                         k1, b, avg, scorer)
        np.maximum.at(arr, d >> shift, sat)
    tab = _sparse_table(arr).astype(np.float32)  # bounds stay valid: the
    # float32 rounding of a float64 max can go either way, but callers add
    # an epsilon margin on θ, and the champion pass (exact) sets θ — a
    # half-ULP of slack on a bound dominated by that margin is immaterial
    cache[key] = tab
    return tab


@dataclass
class WandPlan:
    """Threshold + bounds for one pure-disjunction query (WAND family).

    theta: lower bound on the k-th final score (from exact champion
    scoring); maxscore: {tid: w·max sat} for every query term; kept:
    {tid: surviving global block-row indices} for heavy terms after
    block-max row pruning against theta."""

    theta: float
    maxscore: dict
    kept: dict


def wand_plan(store: BlockStore, term_ids, idf: np.ndarray, k: int,
              avg: float, k1: float, b: float, scorer: str,
              champions: int = 16) -> Optional[WandPlan]:
    """Block-max WAND planning for one pure-disjunction query.

    Reference analog: wand_writer.hpp block-max metadata consumed by
    block_disjunction's skip logic. TPU re-formulation: instead of
    data-dependent skipping inside the kernel (shape-hostile), the HOST
    derives a threshold θ — a lower bound on the k-th final score, from
    exact scoring of the `champions` best block rows plus all light-term
    tails. θ powers two exact optimizations chosen by the caller:

    1. MaxScore essential-list split: terms whose max scores sum below θ
       cannot alone lift a doc into the top-k, so candidate docs are the
       remaining ("essential") terms' postings only — selective queries
       collapse to a small sparse scoring problem.
    2. Block-row pruning for the dense path: a heavy block row is dropped
       when its own w·sat(block_max_tf, block_min_dl) plus, for every
       OTHER query term, the max of that term's per-bucket upper bounds
       over the row's doc range (sparse-table range-max, cached per
       segment) cannot reach θ.

    Both preserve exact top-k: any doc losing a contribution is provably
    below the true k-th score. Returns None when not applicable (θ=0 or
    no heavy terms).
    """
    heavy_ts, light_ts = [], []
    for j, tid in enumerate(term_ids):
        (heavy_ts if store.heavy[int(tid)] else light_ts).append(
            (int(tid), float(idf[j])))
    if not heavy_ts:
        return None
    norms = store.norms_host
    # per-row upper bounds of each heavy term
    rows_per, ub_per = [], []
    maxscore = {}
    for tid, w in heavy_ts:
        b0, b1 = int(store.block_offsets[tid]), int(store.block_offsets[tid + 1])
        r = np.arange(b0, b1, dtype=np.int64)
        ub = w * _sat_exact(store.block_bmax_tf[r], store.block_bmin_dl[r],
                            k1, b, avg, scorer)
        rows_per.append(r)
        ub_per.append(ub)
        maxscore[tid] = float(ub.max()) if len(ub) else 0.0
    light_contribs = []  # (docs, contribs) for the champion accumulation
    for tid, w in light_ts:
        s, e = int(store.offsets[tid]), int(store.offsets[tid + 1])
        if e <= s:
            maxscore[tid] = 0.0
            continue
        d = store.flat_docs[s:e]
        c = w * _sat_exact(store.flat_tfs[s:e], norms[d], k1, b, avg, scorer)
        light_contribs.append((d, c))
        maxscore[tid] = float(c.max())

    # champion pass: exact host scoring of the top-C rows by upper bound
    all_ub = np.concatenate(ub_per)
    all_rows = np.concatenate(rows_per)
    all_w = np.concatenate([np.full(len(r), w)
                            for (_, w), r in zip(heavy_ts, rows_per)])
    all_tid = np.concatenate([np.full(len(r), tid, dtype=np.int64)
                              for (tid, _), r in zip(heavy_ts, rows_per)])
    C = min(len(all_ub), max(champions, 2 * ((k + BLOCK - 1) // BLOCK)))
    champ = np.argpartition(-all_ub, C - 1)[:C] if C < len(all_ub) \
        else np.arange(len(all_ub))
    docs_parts, contrib_parts = [], []
    for ci in champ:
        tid, w, row = int(all_tid[ci]), float(all_w[ci]), int(all_rows[ci])
        b0 = int(store.block_offsets[tid])
        s = int(store.offsets[tid]) + (row - b0) * BLOCK
        e = min(s + BLOCK, int(store.offsets[tid + 1]))
        d = store.flat_docs[s:e]
        docs_parts.append(d)
        contrib_parts.append(w * _sat_exact(store.flat_tfs[s:e], norms[d],
                                            k1, b, avg, scorer))
    for d, c in light_contribs:
        docs_parts.append(d)
        contrib_parts.append(c)
    if not docs_parts:
        return None
    docs_all = np.concatenate(docs_parts)
    contrib_all = np.concatenate(contrib_parts)
    uniq, inv = np.unique(docs_all, return_inverse=True)
    totals = np.bincount(inv, weights=contrib_all)
    if len(totals) < k:
        return None  # fewer champion docs than k → no safe threshold
    theta = float(np.partition(totals, len(totals) - k)[len(totals) - k])
    # device scores are float32 while this pass is float64 — shave an
    # epsilon off θ so borderline rows are kept, never wrongly dropped
    theta *= 1.0 - 1e-5
    if theta <= 0.0:
        return None

    # doc-space bucket size: ≥1024 docs, ≤16384 buckets
    shift = 10
    while (store.ndocs_pad >> shift) + 1 > 16384:
        shift += 1
    kept = {}
    for (tid, _w), r, ub in zip(heavy_ts, rows_per, ub_per):
        if len(r) == 0:
            kept[tid] = r
            continue
        b0 = int(store.block_offsets[tid])
        s, e = int(store.offsets[tid]), int(store.offsets[tid + 1])
        loc = r - b0
        first = store.flat_docs[s + loc * BLOCK]
        last = store.flat_docs[np.minimum(s + (loc + 1) * BLOCK, e) - 1]
        lo_b, hi_b = first >> shift, last >> shift
        other = np.zeros(len(r))
        for tid2, w2 in heavy_ts + light_ts:
            if tid2 == tid:
                continue
            tab = _bucket_tables(store, tid2, avg, k1, b, scorer, shift)
            other += w2 * np.maximum(_range_max(tab, lo_b, hi_b), 0.0)
        kept[tid] = r[ub + other >= theta]
    return WandPlan(theta=theta, maxscore=maxscore, kept=kept)


def assemble_query_batch(store: BlockStore, n_docs: int,
                         queries: list[tuple[np.ndarray, int]],
                         doc_freq: np.ndarray,
                         scorer: str = "bm25", idf_of=None,
                         plans=None) -> QueryBatch:
    """queries: list of (term_ids, require_all) per query. Weights are the
    scorer's per-term idf (computed here so one dispatch covers all);
    idf_of overrides with global collection stats for multi-segment
    searches.

    plans: optional per-query WandPlan list (see wand_plan) — a plan's
    kept-rows replace the term's full block-row span, dropping rows
    provably unable to reach the top-k before the device gather.
    """
    rows, row_w, row_q = [], [], []
    rrows, rrow_w, rrow_q = [], [], []
    tails_d, tails_f, tails_w, tails_q = [], [], [], []
    require = []
    for qi, (term_ids, req) in enumerate(queries):
        require.append(req)
        tid_arr = np.asarray(term_ids, dtype=np.int64)
        if not len(term_ids):
            idf = np.empty(0, dtype=np.float32)
        elif idf_of is not None:
            idf = np.asarray(idf_of(tid_arr), dtype=np.float32)
        else:
            idf = idf_for(scorer, n_docs, doc_freq[tid_arr])
        kept = None
        if plans is not None and plans[qi] is not None and req == 0:
            kept = plans[qi].kept
        for k, tid in enumerate(term_ids):
            tid = int(tid)
            w = float(idf[k])
            if store.heavy[tid]:
                if kept is not None:
                    r = kept[tid].astype(np.int64)
                else:
                    b0 = int(store.block_offsets[tid])
                    b1 = int(store.block_offsets[tid + 1])
                    r = np.arange(b0, b1, dtype=np.int64)
                # split the term's global rows across the two planes
                plane = store.row_plane[r]
                pk = store.row_slot[r[plane == 0]]
                rw = store.row_slot[r[plane == 1]]
                if len(pk):
                    rows.append(pk)
                    row_w.append(np.full(len(pk), w, dtype=np.float32))
                    row_q.append(np.full(len(pk), qi, dtype=np.int32))
                if len(rw):
                    rrows.append(rw)
                    rrow_w.append(np.full(len(rw), w, dtype=np.float32))
                    rrow_q.append(np.full(len(rw), qi, dtype=np.int32))
            else:
                s, e = int(store.offsets[tid]), int(store.offsets[tid + 1])
                tails_d.append(store.flat_docs[s:e])
                tails_f.append(store.flat_tfs[s:e])
                tails_w.append(np.full(e - s, w, dtype=np.float32))
                tails_q.append(np.full(e - s, qi, dtype=np.int32))

    def cat(parts, dtype):
        return np.concatenate(parts).astype(dtype, copy=False) if parts \
            else np.empty(0, dtype=dtype)

    row_idx = cat(rows, np.int32)
    nb_pad = _pow2(len(row_idx), 8)
    raw_idx = cat(rrows, np.int32)
    nr_pad = _pow2(len(raw_idx), 8)
    tail_docs = cat(tails_d, np.int32)
    tt_pad = _pow2(len(tail_docs), BLOCK)
    return QueryBatch(
        row_idx=_pad_to(row_idx, nb_pad, store.n_packed),
        row_w=_pad_to(cat(row_w, np.float32), nb_pad, 0.0),
        row_qid=_pad_to(cat(row_q, np.int32), nb_pad, 0),
        raw_idx=_pad_to(raw_idx, nr_pad, store.n_raw),
        raw_w=_pad_to(cat(rrow_w, np.float32), nr_pad, 0.0),
        raw_qid=_pad_to(cat(rrow_q, np.int32), nr_pad, 0),
        tail_docs=_pad_to(tail_docs, tt_pad, -1),
        tail_tfs=_pad_to(cat(tails_f, np.int32), tt_pad, 0),
        tail_w=_pad_to(cat(tails_w, np.float32), tt_pad, 0.0),
        tail_qid=_pad_to(cat(tails_q, np.int32), tt_pad, 0),
        require=np.asarray(require, dtype=np.int32),
        n_queries=len(queries),
    )


def pack_query_batch(qb: QueryBatch) -> tuple[np.ndarray, np.ndarray,
                                              int, int, int, int]:
    """Pack the per-query arrays into ONE int32 + ONE f32 buffer so a
    dispatch costs two host→device transfers instead of fourteen (each
    transfer pays full RTT on tunneled TPUs).

    ints: [row_idx | row_qid | raw_idx | raw_qid
           | tail_docs | tail_tfs | tail_qid | require]
    floats: [row_w | raw_w | tail_w]
    """
    ints = np.concatenate([qb.row_idx, qb.row_qid, qb.raw_idx, qb.raw_qid,
                           qb.tail_docs, qb.tail_tfs,
                           qb.tail_qid, qb.require]).astype(np.int32)
    floats = np.concatenate([qb.row_w, qb.raw_w,
                             qb.tail_w]).astype(np.float32)
    return (ints, floats, len(qb.row_idx), len(qb.raw_idx),
            len(qb.tail_docs), qb.n_queries)


def _pow2(n: int, floor: int) -> int:
    return max(floor, 1 << max(n - 1, 0).bit_length())


def _pad_to(a: np.ndarray, n: int, fill) -> np.ndarray:
    out = np.full(n, fill, dtype=a.dtype if len(a) else np.int32)
    out[:len(a)] = a
    return out


@functools.partial(jax.jit,
                   static_argnames=("nb", "nr", "tt", "ndocs_pad", "k",
                                    "n_queries", "any_require", "scorer"))
def score_topk_packed(block_base: jax.Array, block_gaps: jax.Array,
                      block_tfs8: jax.Array, raw_docs: jax.Array,
                      raw_tfs: jax.Array,
                      norms: jax.Array, ints: jax.Array, floats: jax.Array,
                      nb: int, nr: int, tt: int, ndocs_pad: int, k: int,
                      n_queries: int, any_require: bool, k1: float,
                      b: float, avgdl: float,
                      scorer: str = "bm25") -> tuple[jax.Array, jax.Array]:
    """Packed-argument entry (2 transfers): unpack then score."""
    row_idx = ints[:nb]
    row_qid = ints[nb:2 * nb]
    o = 2 * nb
    raw_idx = ints[o:o + nr]
    raw_qid = ints[o + nr:o + 2 * nr]
    o += 2 * nr
    tail_docs = ints[o:o + tt]
    tail_tfs = ints[o + tt:o + 2 * tt]
    tail_qid = ints[o + 2 * tt:o + 3 * tt]
    require = ints[o + 3 * tt:o + 3 * tt + n_queries]
    row_w = floats[:nb]
    raw_w = floats[nb:nb + nr]
    tail_w = floats[nb + nr:nb + nr + tt]
    return _score_topk(block_base, block_gaps, block_tfs8, raw_docs,
                       raw_tfs, norms, row_idx, row_w, row_qid,
                       raw_idx, raw_w, raw_qid,
                       tail_docs, tail_tfs, tail_w, tail_qid,
                       require, ndocs_pad, k, n_queries, any_require,
                       k1, b, avgdl, scorer)


def _decode_rows(block_base, block_gaps, block_tfs8, row_idx):
    """In-kernel decompression of packed posting rows: docs = base +
    lane-axis prefix sum of the uint16 gaps (the TPU analog of the
    reference's SIMD streamvbyte/bitpack decode, format_block_128.cpp);
    tf=0 marks padding."""
    gaps = block_gaps[row_idx].astype(jnp.int32)        # (NB, 128)
    docs = block_base[row_idx][:, None] + jnp.cumsum(gaps, axis=1)
    tfs = block_tfs8[row_idx].astype(jnp.int32)
    valid = tfs > 0
    return jnp.where(valid, docs, -1), tfs


def _accumulate_scores(block_base, block_gaps, block_tfs8, raw_docs,
                       raw_tfs, norms, row_idx, row_w, row_qid, raw_idx,
                       raw_w, raw_qid, tail_docs, tail_tfs, tail_w,
                       tail_qid, ndocs_pad: int, n_queries: int,
                       with_hits: bool, k1: float, b: float, avgdl,
                       scorer: str = "bm25"):
    """Fused gather+decode → score → batched scatter-accumulate into
    (B, ndocs) score planes (+ hit counts when with_hits). Shared by the
    single-device top-k and the mesh-sharded path, whose shards each
    accumulate their posting-row slice before a psum merge."""
    avg = jnp.maximum(jnp.float32(avgdl), 1e-9)

    def contrib_of(docs, tfs, w):
        valid = jnp.logical_and(docs >= 0, tfs > 0)
        safe_docs = jnp.where(valid, docs, 0)
        tfsf = tfs.astype(jnp.float32)
        if scorer == "tfidf":
            c = w * jnp.sqrt(tfsf)
        elif scorer == "lm_dirichlet":
            # w = p_t (collection probability), k1 slot = µ. Lucene
            # LMDirichletSimilarity shape, clamped at 0
            # (reference: lm_dirichlet.cpp)
            dl = norms[safe_docs].astype(jnp.float32)
            mu = k1
            c = (jnp.log1p(tfsf / (mu * w)) +
                 jnp.log(mu / (dl + mu)))
            # + MATCH_EPS: LM scores clamp to 0 for weak matches, but the
            # engine's result filters rely on score>0 ⇔ matched
            c = jnp.maximum(c, 0.0) + MATCH_EPS
        elif scorer == "jelinek_mercer":
            # w = p_t, k1 slot = λ (reference: jelinek_mercer smoothing)
            dl = norms[safe_docs].astype(jnp.float32)
            lam = k1
            c = jnp.log1p(((1.0 - lam) * tfsf / jnp.maximum(dl, 1.0)) /
                          (lam * w))
        elif scorer == "dfi":
            # divergence from independence: expected tf under independence
            # is e = p_t·dl; score the standardized excess
            # (reference: dfi.cpp)
            dl = norms[safe_docs].astype(jnp.float32)
            e = w * dl
            excess = (tfsf - e) / jnp.sqrt(jnp.maximum(e, 1e-9))
            c = jnp.where(tfsf > e, jnp.log2(1.0 + excess), 0.0) + MATCH_EPS
        else:
            dl = norms[safe_docs].astype(jnp.float32)
            denom = tfsf + k1 * (1.0 - b + b * dl / avg)
            c = w * (k1 + 1.0) * tfsf / jnp.maximum(denom, 1e-9)
        return jnp.where(valid, c, 0.0), valid, safe_docs

    scores = jnp.zeros((n_queries * ndocs_pad,), dtype=jnp.float32)
    hits = jnp.zeros((n_queries * ndocs_pad,), dtype=jnp.int32) \
        if with_hits else None
    # packed plane: gather + in-kernel delta decode
    pdocs, ptfs = _decode_rows(block_base, block_gaps, block_tfs8, row_idx)
    wc, valid_b, safe_b = contrib_of(pdocs, ptfs, row_w[:, None])
    bidx = (row_qid[:, None] * ndocs_pad + safe_b).reshape(-1)
    scores = scores.at[bidx].add(wc.reshape(-1))
    # raw exception plane (rows whose gaps/tfs overflow the packed widths)
    rdocs = raw_docs[raw_idx]
    rtfs = raw_tfs[raw_idx]
    rc, valid_r, safe_r = contrib_of(rdocs, rtfs, raw_w[:, None])
    ridx = (raw_qid[:, None] * ndocs_pad + safe_r).reshape(-1)
    scores = scores.at[ridx].add(rc.reshape(-1))
    # light-term tails
    tc, valid_t, safe_t = contrib_of(tail_docs, tail_tfs, tail_w)
    tidx = tail_qid * ndocs_pad + safe_t
    scores = scores.at[tidx].add(tc)
    scores = scores.reshape(n_queries, ndocs_pad)
    if with_hits:
        hits = hits.at[bidx].add(valid_b.reshape(-1).astype(jnp.int32))
        hits = hits.at[ridx].add(valid_r.reshape(-1).astype(jnp.int32))
        hits = hits.at[tidx].add(valid_t.astype(jnp.int32))
        hits = hits.reshape(n_queries, ndocs_pad)
    return scores, hits


def _score_topk(block_base, block_gaps, block_tfs8, raw_docs, raw_tfs,
                norms, row_idx, row_w, row_qid, raw_idx, raw_w, raw_qid,
                tail_docs, tail_tfs, tail_w, tail_qid, require,
                ndocs_pad: int, k: int, n_queries: int, any_require: bool,
                k1: float, b: float, avgdl: float, scorer: str = "bm25"):
    """One dispatch scoring B queries: accumulate score planes →
    require-mask → per-query top-k. Batching amortizes host↔device
    dispatch latency — the QPS regime of the benchmark game.

    scorer: 'bm25' (k1/b saturation + length norm) or 'tfidf'
    (sqrt(tf)·w — the IResearch TFIDF shape, tfidf.cpp; the per-term idf
    part of w is supplied by the caller per scorer)."""
    scores, hits = _accumulate_scores(
        block_base, block_gaps, block_tfs8, raw_docs, raw_tfs, norms,
        row_idx, row_w, row_qid, raw_idx, raw_w, raw_qid,
        tail_docs, tail_tfs, tail_w, tail_qid, ndocs_pad, n_queries,
        any_require, k1, b, avgdl, scorer)
    if any_require:
        need = require[:, None]
        scores = jnp.where(jnp.logical_or(need <= 0, hits >= need),
                           scores, 0.0)
    vals, docs = jax.lax.top_k(scores, k)
    return vals, docs


def _mesh_score_fn(mesh_n: int, ndocs_pad: int, k: int, n_queries: int,
                   scorer: str, k1: float, b: float):
    """Mesh-sharded scoring program (cached per shape in the obs/device
    compile ledger — no local memo, so the bounded program LRU really
    owns these executables): posting-row sections shard across devices,
    each shard accumulates its slice with the SAME kernel as the
    single-device path, score planes psum over ICI, one top-k on the
    merged plane (reference analog: parallel per-segment top-k
    collectors, SURVEY.md §2.11 — re-expressed as XLA collectives; see
    also parallel/mesh.py)."""
    def build():
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import AXIS, make_mesh
        mesh = make_mesh(mesh_n)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=((P(),) * 6 + (P(), ) +        # store + avgdl
                      (P(AXIS),) * 10),             # posting-row sections
            out_specs=(P(), P()))
        def step(block_base, block_gaps, block_tfs8, raw_docs, raw_tfs,
                 norms, avgdl, row_idx, row_w, row_qid, raw_idx, raw_w,
                 raw_qid, tail_docs, tail_tfs, tail_w, tail_qid):
            scores, _ = _accumulate_scores(
                block_base, block_gaps, block_tfs8, raw_docs, raw_tfs,
                norms, row_idx, row_w, row_qid, raw_idx, raw_w, raw_qid,
                tail_docs, tail_tfs, tail_w, tail_qid, ndocs_pad,
                n_queries, False, k1, b, avgdl, scorer)
            scores = jax.lax.psum(scores, AXIS)
            return jax.lax.top_k(scores, k)

        return step

    from ..obs import device as obs_device
    return obs_device.compiled(
        "bm25_mesh",
        (mesh_n, ndocs_pad, k, n_queries, scorer, k1, b),
        build)


def score_topk_mesh(store, qb: "QueryBatch", ndocs_pad: int, k: int,
                    mesh_n: int, k1: float, b: float, avgdl: float,
                    scorer: str = "bm25"):
    """Score a require-free query batch over an N-device mesh. Sections
    pad to a mesh multiple with the no-op fills the packer already uses
    (w=0 rows contribute nothing)."""
    from ..parallel.mesh import pad_to_multiple

    def pad_sec(a, fill):
        return pad_to_multiple(np.asarray(a), mesh_n, fill)

    fn = _mesh_score_fn(mesh_n, ndocs_pad, k, qb.n_queries, scorer,
                        float(k1), float(b))
    return fn(store.block_base, store.block_gaps, store.block_tfs8,
              store.raw_docs, store.raw_tfs, store.norms,
              jnp.float32(avgdl),
              jnp.asarray(pad_sec(qb.row_idx, store.n_packed)),
              jnp.asarray(pad_sec(qb.row_w, np.float32(0.0))),
              jnp.asarray(pad_sec(qb.row_qid, 0)),
              jnp.asarray(pad_sec(qb.raw_idx, store.n_raw)),
              jnp.asarray(pad_sec(qb.raw_w, np.float32(0.0))),
              jnp.asarray(pad_sec(qb.raw_qid, 0)),
              jnp.asarray(pad_sec(qb.tail_docs, -1)),
              jnp.asarray(pad_sec(qb.tail_tfs, 0)),
              jnp.asarray(pad_sec(qb.tail_w, np.float32(0.0))),
              jnp.asarray(pad_sec(qb.tail_qid, 0)))




# ------------------------------------------------------------ dense path
#
# Small-corpus regime (benchmark-game scale): the scatter-accumulate kernel
# is bound by XLA's serialized scatter, not by FLOPs. When the dense
# (ndocs_pad, V_pad) saturation matrix fits an HBM budget, scoring becomes
# ONE MXU matmul: scores = S @ W with W[t, q] = idf weight of term t in
# query q — the TPU-first re-expression of "score every doc against the
# query" that turns the memory-bound scatter into compute the systolic
# array eats for breakfast. S is built ON DEVICE from the already-resident
# block tiles (+ a one-time light-term tail upload), so no dense matrix
# ever crosses the host↔device link.

DENSE_HBM_BUDGET = int(float(os.environ.get("SDB_DENSE_HBM_MB", "1024"))
                       * (1 << 20))


@dataclass
class DenseStore:
    """Device-resident dense saturation matrix for one (segment, scorer,
    avgdl) triple. S[d, t] = sat(tf_{d,t}, dl_d); 0 where the term is
    absent — so scores = S @ W sums exactly the per-term contributions and
    (S > 0) @ 1_q counts exactly the per-query term hits."""

    S: jax.Array        # (ndocs_pad, V_pad) f32
    ndocs_pad: int
    v_pad: int


@functools.partial(jax.jit, static_argnames=("ndocs_pad", "v_pad", "scorer"))
def _build_dense(block_base, block_gaps, block_tfs8, pk_tid,
                 raw_docs, raw_tfs, raw_tid, light_docs, light_tfs,
                 light_tid, norms, ndocs_pad: int, v_pad: int, k1: float,
                 b: float, avgdl: float, scorer: str) -> jax.Array:
    """One-time scatter of every posting (decoded from the packed planes)
    into a dense TF plane, then the scorer's saturation applied
    elementwise. Runs once per (segment, scorer, avgdl); per-query
    dispatches touch only the result."""
    tf = jnp.zeros((ndocs_pad, v_pad), dtype=jnp.float32)
    all_rows = jnp.arange(block_base.shape[0], dtype=jnp.int32)
    pdocs, ptfs = _decode_rows(block_base, block_gaps, block_tfs8, all_rows)
    pd = pdocs.reshape(-1)
    pt = ptfs.reshape(-1)
    ptid = jnp.broadcast_to(pk_tid[:, None], pdocs.shape).reshape(-1)
    pvalid = pd >= 0
    tf = tf.at[jnp.where(pvalid, pd, 0),
               jnp.where(pvalid, ptid, 0)].add(
        jnp.where(pvalid, pt.astype(jnp.float32), 0.0))
    rd = raw_docs.reshape(-1)
    rt = raw_tfs.reshape(-1)
    rtid = jnp.broadcast_to(raw_tid[:, None], raw_docs.shape).reshape(-1)
    rvalid = rd >= 0
    tf = tf.at[jnp.where(rvalid, rd, 0),
               jnp.where(rvalid, rtid, 0)].add(
        jnp.where(rvalid, rt.astype(jnp.float32), 0.0))
    lvalid = light_docs >= 0
    tf = tf.at[jnp.where(lvalid, light_docs, 0),
               jnp.where(lvalid, light_tid, 0)].add(
        jnp.where(lvalid, light_tfs.astype(jnp.float32), 0.0))
    if scorer == "tfidf":
        return jnp.sqrt(tf)
    alpha = k1 * (1.0 - b + b * norms[:ndocs_pad].astype(jnp.float32) /
                  jnp.maximum(jnp.float32(avgdl), 1e-9))
    return (k1 + 1.0) * tf / jnp.maximum(tf + alpha[:, None], 1e-9)


def dense_fits(ndocs_pad: int, vocab: int) -> bool:
    """True when the (ndocs_pad, V_pad) f32 saturation matrix fits the
    dense-path HBM budget. ndocs_pad is the block store's own padding so
    the estimate can't drift from the real allocation."""
    v_pad = max(128, ((vocab + 127) // 128) * 128)
    return ndocs_pad * v_pad * 4 <= DENSE_HBM_BUDGET


def build_dense_store(store: BlockStore, doc_freq: np.ndarray,
                      avgdl: float, k1: float, b: float,
                      scorer: str) -> DenseStore:
    T = len(doc_freq)
    v_pad = max(128, ((T + 127) // 128) * 128)
    nd_pad = store.ndocs_pad
    # heavy terms: already device-resident as block tiles; ship only the
    # per-row term id. Light terms: one-time flat upload (df < HEAVY_DF
    # each, so the tail is small).
    rows_per_term = np.diff(store.block_offsets).astype(np.int64)
    row_tid = np.zeros(len(store.row_plane), dtype=np.int32)
    row_tid[:int(rows_per_term.sum())] = np.repeat(
        np.arange(T, dtype=np.int32), rows_per_term)
    # split the global row→term map by plane (the planes' extra pad rows
    # keep tid 0 — their postings decode as invalid and never scatter)
    pk_tid = np.zeros(store.n_packed + 1, dtype=np.int32)
    raw_tid = np.zeros(store.n_raw + 1, dtype=np.int32)
    packed_rows = store.row_plane == 0
    pk_tid[store.row_slot[packed_rows]] = row_tid[packed_rows]
    raw_tid[store.row_slot[~packed_rows]] = row_tid[~packed_rows]
    # light terms: one boolean mask over the flat postings (vectorized —
    # vocab can reach ~260k at the budget boundary)
    df = np.diff(store.offsets).astype(np.int64)
    post_tid = np.repeat(np.arange(T, dtype=np.int32), df)
    light_mask = ~store.heavy[post_tid]
    light_docs = store.flat_docs[light_mask].astype(np.int32)
    light_tfs = store.flat_tfs[light_mask].astype(np.int32)
    light_tid = post_tid[light_mask]
    n_pad = _pow2(len(light_docs), BLOCK)
    S = _build_dense(
        store.block_base, store.block_gaps, store.block_tfs8,
        jnp.asarray(pk_tid), store.raw_docs, store.raw_tfs,
        jnp.asarray(raw_tid),
        jnp.asarray(_pad_to(light_docs, n_pad, -1)),
        jnp.asarray(_pad_to(light_tfs, n_pad, 0)),
        jnp.asarray(_pad_to(light_tid, n_pad, 0)),
        store.norms, nd_pad, v_pad, k1, b, avgdl, scorer)
    return DenseStore(S=S, ndocs_pad=nd_pad, v_pad=v_pad)


@functools.partial(jax.jit, static_argnames=("k", "any_require"))
def dense_topk(S: jax.Array, W: jax.Array, require: jax.Array, k: int,
               any_require: bool) -> tuple[jax.Array, jax.Array]:
    """scores = S @ W on the MXU; optional conjunction masking via an
    indicator matmul (hits = [S>0] @ [W>0]); exact per-query top-k."""
    scores = jax.lax.dot_general(
        S, W, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # (nd, B)
    if any_require:
        hits = jax.lax.dot_general(
            (S > 0).astype(jnp.float32), (W > 0).astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        scores = jnp.where(
            jnp.logical_or(require[None, :] <= 0,
                           hits >= require[None, :].astype(jnp.float32)),
            scores, 0.0)
    vals, docs = jax.lax.top_k(scores.T, k)
    return vals, docs


def assemble_dense_weights(v_pad: int,
                           queries: list[tuple[np.ndarray, int]],
                           n_docs: int, doc_freq: np.ndarray, scorer: str,
                           idf_of=None) -> tuple[np.ndarray, np.ndarray, int]:
    """(W, require, b_pad): W[t, q] = weight of term t in query q (tiny —
    V_pad × B f32). The batch dim pads to a power of two so jit caches stay
    small across varying batch sizes."""
    b_pad = _pow2(len(queries), 8)
    W = np.zeros((v_pad, b_pad), dtype=np.float32)
    require = np.zeros(b_pad, dtype=np.int32)
    for qi, (term_ids, req) in enumerate(queries):
        require[qi] = req
        if not len(term_ids):
            continue
        tid_arr = np.asarray(term_ids, dtype=np.int64)
        if idf_of is not None:
            idf = np.asarray(idf_of(tid_arr), dtype=np.float32)
        else:
            idf = idf_for(scorer, n_docs, doc_freq[tid_arr])
        np.add.at(W[:, qi], tid_arr, idf)
    return W, require, b_pad


# -------------------------------------------------- ragged batched serving
#
# QPS regime on the HOST backend: a (B, ndocs_pad) score plane per query is
# memory-bound work proportional to the corpus, while a 2-term top-10 query
# only ever touches its own postings. The batched ragged path flattens every
# query's (WAND-kept) postings into ONE (entries,) array — the ragged
# (terms, query-offsets) layout of Ragged Paged Attention — computes the
# per-posting saturation contributions in ONE tiny elementwise dispatch, and
# leaves accumulation + exact top-k to numpy over the candidate sets.
#
# Bit-parity contract: `contrib_flat` states the per-posting score with THE
# SAME expression tree as `_accumulate_scores.contrib_of`, so XLA applies
# the same algebraic simplification/contraction and the f32 contribution
# bits are identical to the plane kernel's (asserted by the search-batch
# parity suite; a numpy restatement of the formula is 1 ULP off under
# XLA's simplifier, which is why this stays a jitted kernel). Only bm25 and
# tfidf decompose this way — LM scorers never take the ragged path.

def contrib_expr(tfs: jax.Array, dls: jax.Array, w: jax.Array, k1,
                 b, avgdl, scorer: str = "bm25") -> jax.Array:
    """THE shared contribution expression tree — traced identically by
    `contrib_flat` (the host ragged path) and the posting-pool device
    program (search/posting_pool.py), so XLA applies the same algebraic
    simplification in both and their f32 contribution bits agree with
    each other and with the plane kernel's."""
    avg = jnp.maximum(jnp.float32(avgdl), 1e-9)
    tfsf = tfs.astype(jnp.float32)
    if scorer == "tfidf":
        return w * jnp.sqrt(tfsf)
    dl = dls.astype(jnp.float32)
    denom = tfsf + k1 * (1.0 - b + b * dl / avg)
    return w * (k1 + 1.0) * tfsf / jnp.maximum(denom, 1e-9)


@functools.partial(jax.jit, static_argnames=("scorer",))
def contrib_flat(tfs: jax.Array, dls: jax.Array, w: jax.Array, k1: float,
                 b: float, avgdl: float,
                 scorer: str = "bm25") -> jax.Array:
    """Per-posting score contribution w·sat(tf, dl) over flat arrays.
    Padding entries (tf=0, w=0) contribute exactly 0.0."""
    return contrib_expr(tfs, dls, w, k1, b, avgdl, scorer)


def ragged_contribs(tfs: np.ndarray, dls: np.ndarray, w: np.ndarray,
                    k1: float, b: float, avgdl: float,
                    scorer: str) -> np.ndarray:
    """contrib_flat over host arrays, padded to a power of two so the jit
    cache stays small across ragged batch sizes (pads score 0.0 and are
    sliced back off)."""
    n = len(tfs)
    n_pad = _pow2(n, 1024)

    def pad(a, fill, dtype):
        out = np.full(n_pad, fill, dtype=dtype)
        out[:n] = a
        return out

    c = contrib_flat(jnp.asarray(pad(tfs, 0, np.int32)),
                     jnp.asarray(pad(dls, 0, np.int32)),
                     jnp.asarray(pad(w, 0.0, np.float32)),
                     scorer_param(scorer, k1), b, avgdl, scorer)
    return np.asarray(c)[:n]


def topk_tie_exact(scores: np.ndarray, docs: np.ndarray, k: int,
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Exact (score desc, doc asc) top-k of a candidate set — the same
    selection jax.lax.top_k makes over a score plane (ties → lowest doc
    index first). Partition first so only the k-plus-ties head is sorted."""
    if len(scores) > max(k, 1):
        kth = np.partition(-scores, k - 1)[k - 1]
        sel = np.flatnonzero(-scores <= kth)     # score >= kth, ties incl.
        order = sel[np.argsort(-scores[sel], kind="stable")][:k]
    else:
        order = np.argsort(-scores, kind="stable")[:k]
    return scores[order], docs[order]


@functools.partial(jax.jit, static_argnames=("ndocs_pad",))
def match_bitmap(block_base: jax.Array, block_gaps: jax.Array,
                 block_tfs8: jax.Array, row_idx: jax.Array,
                 raw_docs: jax.Array, raw_idx: jax.Array,
                 tail_docs: jax.Array, ndocs_pad: int) -> jax.Array:
    """Disjunctive match bitmap (unscored filter pushdown)."""
    pdocs, _ = _decode_rows(block_base, block_gaps, block_tfs8, row_idx)
    pdocs = pdocs.reshape(-1)
    rdocs = raw_docs[raw_idx].reshape(-1)
    m = jnp.zeros((ndocs_pad,), dtype=jnp.bool_)
    m = m.at[jnp.where(pdocs >= 0, pdocs, 0)].max(pdocs >= 0)
    m = m.at[jnp.where(rdocs >= 0, rdocs, 0)].max(rdocs >= 0)
    m = m.at[jnp.where(tail_docs >= 0, tail_docs, 0)].max(tail_docs >= 0)
    return m


def pad_k(k: int) -> int:
    """Bucket k so jit caches stay small: 10 / 100 / 1000 / next pow2."""
    for bucket in (10, 100, 1000):
        if k <= bucket:
            return bucket
    return 1 << (k - 1).bit_length()
