"""Geometry model + WKT / WKB / GeoJSON codecs.

Reference analog: libs/geo/shape_container.{h,cpp} (tagged S2 geometry
union), libs/geo/wkb.cpp (byte-order-aware WKB), libs/geo/geo_json.cpp.
Coordinates are (lon, lat) pairs in degrees, like the reference's
GeoJSON/WKB surface.
"""

from __future__ import annotations

import json
import re
import struct
from dataclasses import dataclass
from typing import Iterable

from .. import errors


def _err(msg: str) -> errors.SqlError:
    return errors.SqlError(errors.INVALID_TEXT_REPRESENTATION, msg)


# kind ∈ point linestring polygon multipoint multilinestring multipolygon
# geometrycollection
@dataclass
class Geometry:
    kind: str
    # point: (x, y); linestring/multipoint: [(x,y)..]; polygon/
    # multilinestring: [[(x,y)..]..]; multipolygon: [[[..]..]..];
    # geometrycollection: [Geometry..]
    coords: object

    def polygons(self) -> list[list[list[tuple]]]:
        """All polygons (as ring lists) in this geometry."""
        if self.kind == "polygon":
            return [self.coords]
        if self.kind == "multipolygon":
            return list(self.coords)
        if self.kind == "geometrycollection":
            out = []
            for g in self.coords:
                out.extend(g.polygons())
            return out
        return []

    def points(self) -> list[tuple]:
        """Every vertex in the geometry."""
        k = self.kind
        if k == "point":
            return [self.coords]
        if k in ("linestring", "multipoint"):
            return list(self.coords)
        if k in ("polygon", "multilinestring"):
            return [p for ring in self.coords for p in ring]
        if k == "multipolygon":
            return [p for poly in self.coords for ring in poly
                    for p in ring]
        if k == "geometrycollection":
            return [p for g in self.coords for p in g.points()]
        return []

    def segments(self) -> list[tuple]:
        """Every line segment ((x1,y1),(x2,y2)); polygon rings closed."""
        k = self.kind
        if k == "linestring":
            return list(zip(self.coords, self.coords[1:]))
        if k == "multilinestring":
            return [s for ls in self.coords
                    for s in zip(ls, ls[1:])]
        if k in ("polygon", "multipolygon"):
            out = []
            for ring in ([r for r in self.coords] if k == "polygon"
                         else [r for poly in self.coords for r in poly]):
                closed = list(ring)
                if closed and closed[0] != closed[-1]:
                    closed.append(closed[0])
                out.extend(zip(closed, closed[1:]))
            return out
        if k == "geometrycollection":
            return [s for g in self.coords for g_s in [g.segments()]
                    for s in g_s]
        return []


# -- WKT -------------------------------------------------------------------

_WKT_KINDS = ("geometrycollection", "multipolygon", "multilinestring",
              "multipoint", "polygon", "linestring", "point")


def _parse_coord_pair(tok: str) -> tuple:
    parts = tok.split()
    if len(parts) < 2:
        raise _err(f"invalid coordinate {tok!r}")
    try:
        return (float(parts[0]), float(parts[1]))
    except ValueError:
        raise _err(f"invalid coordinate {tok!r}")


def _split_top(s: str) -> list[str]:
    """Split on commas at paren depth 0."""
    out, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    out.append(s[start:])
    return [p.strip() for p in out]


def _strip_parens(s: str) -> str:
    s = s.strip()
    if not (s.startswith("(") and s.endswith(")")):
        raise _err(f"expected parenthesized list near {s[:30]!r}")
    return s[1:-1].strip()


def _parse_ring_list(s: str) -> list[list[tuple]]:
    return [[_parse_coord_pair(c) for c in _split_top(_strip_parens(ring))]
            for ring in _split_top(s)]


def from_wkt(text: str) -> Geometry:
    s = text.strip()
    low = s.lower()
    for kind in _WKT_KINDS:
        if low.startswith(kind):
            rest = s[len(kind):].strip()
            break
    else:
        raise _err(f"unrecognized geometry {text[:40]!r}")
    if rest.lower() == "empty":
        return Geometry(kind, () if kind == "point" else [])
    body = _strip_parens(rest)
    if kind == "point":
        return Geometry("point", _parse_coord_pair(body))
    if kind == "linestring":
        return Geometry("linestring",
                        [_parse_coord_pair(c) for c in _split_top(body)])
    if kind == "multipoint":
        # both MULTIPOINT(1 2, 3 4) and MULTIPOINT((1 2), (3 4))
        pts = []
        for tok in _split_top(body):
            tok = tok.strip()
            if tok.startswith("("):
                tok = _strip_parens(tok)
            pts.append(_parse_coord_pair(tok))
        return Geometry("multipoint", pts)
    if kind == "polygon":
        return Geometry("polygon", _parse_ring_list(body))
    if kind == "multilinestring":
        return Geometry("multilinestring", _parse_ring_list(body))
    if kind == "multipolygon":
        return Geometry("multipolygon",
                        [_parse_ring_list(_strip_parens(p))
                         for p in _split_top(body)])
    # geometrycollection
    return Geometry("geometrycollection",
                    [from_wkt(g) for g in _split_top(body)])


def _fmt(v: float) -> str:
    return repr(float(v))


def _fmt_pair(p) -> str:
    return f"{_fmt(p[0])} {_fmt(p[1])}"


def to_wkt(g: Geometry) -> str:
    k = g.kind
    name = k.upper()
    if not g.coords and k != "point" or (k == "point" and g.coords == ()):
        return f"{name} EMPTY"
    if k == "point":
        return f"POINT({_fmt_pair(g.coords)})"
    if k in ("linestring", "multipoint"):
        return f"{name}({', '.join(_fmt_pair(p) for p in g.coords)})"
    if k in ("polygon", "multilinestring"):
        rings = ", ".join(
            "(" + ", ".join(_fmt_pair(p) for p in ring) + ")"
            for ring in g.coords)
        return f"{name}({rings})"
    if k == "multipolygon":
        polys = ", ".join(
            "(" + ", ".join(
                "(" + ", ".join(_fmt_pair(p) for p in ring) + ")"
                for ring in poly) + ")"
            for poly in g.coords)
        return f"MULTIPOLYGON({polys})"
    return ("GEOMETRYCOLLECTION(" +
            ", ".join(to_wkt(x) for x in g.coords) + ")")


# -- WKB -------------------------------------------------------------------

_WKB_CODE = {"point": 1, "linestring": 2, "polygon": 3, "multipoint": 4,
             "multilinestring": 5, "multipolygon": 6,
             "geometrycollection": 7}
_WKB_KIND = {v: k for k, v in _WKB_CODE.items()}


def to_wkb(g: Geometry) -> bytes:
    """Little-endian WKB."""
    out = bytearray()
    _wkb_emit(g, out)
    return bytes(out)


def _wkb_emit(g: Geometry, out: bytearray) -> None:
    out += b"\x01" + struct.pack("<I", _WKB_CODE[g.kind])
    k = g.kind
    if k == "point":
        x, y = (g.coords if g.coords else (float("nan"), float("nan")))
        out += struct.pack("<dd", x, y)
    elif k == "linestring":
        out += struct.pack("<I", len(g.coords))
        for x, y in g.coords:
            out += struct.pack("<dd", x, y)
    elif k == "polygon":
        out += struct.pack("<I", len(g.coords))
        for ring in g.coords:
            out += struct.pack("<I", len(ring))
            for x, y in ring:
                out += struct.pack("<dd", x, y)
    elif k in ("multipoint", "multilinestring", "multipolygon",
               "geometrycollection"):
        inner_kind = {"multipoint": "point",
                      "multilinestring": "linestring",
                      "multipolygon": "polygon"}.get(k)
        items = (g.coords if k == "geometrycollection"
                 else [Geometry(inner_kind, c) for c in g.coords])
        out += struct.pack("<I", len(items))
        for item in items:
            _wkb_emit(item, out)


def from_wkb(data: bytes) -> Geometry:
    g, off = _wkb_parse(data, 0)
    return g


def _wkb_parse(data: bytes, off: int) -> tuple[Geometry, int]:
    try:
        bo = "<" if data[off] == 1 else ">"
        (code,) = struct.unpack_from(bo + "I", data, off + 1)
        off += 5
        if code & 0x20000000:          # EWKB SRID flag: skip the srid
            code &= ~0x20000000
            off += 4
        code &= 0xFF
        kind = _WKB_KIND.get(code)
        if kind is None:
            raise _err(f"unknown WKB geometry code {code}")
        if kind == "point":
            x, y = struct.unpack_from(bo + "dd", data, off)
            return Geometry("point", (x, y)), off + 16
        if kind == "linestring":
            (n,) = struct.unpack_from(bo + "I", data, off)
            off += 4
            pts = [struct.unpack_from(bo + "dd", data, off + 16 * i)
                   for i in range(n)]
            return Geometry("linestring", [tuple(p) for p in pts]), \
                off + 16 * n
        if kind == "polygon":
            (nr,) = struct.unpack_from(bo + "I", data, off)
            off += 4
            rings = []
            for _ in range(nr):
                (n,) = struct.unpack_from(bo + "I", data, off)
                off += 4
                ring = [tuple(struct.unpack_from(bo + "dd", data,
                                                 off + 16 * i))
                        for i in range(n)]
                off += 16 * n
                rings.append(ring)
            return Geometry("polygon", rings), off
        # multi*/collection
        (n,) = struct.unpack_from(bo + "I", data, off)
        off += 4
        items = []
        for _ in range(n):
            item, off = _wkb_parse(data, off)
            items.append(item)
        if kind == "geometrycollection":
            return Geometry(kind, items), off
        return Geometry(kind, [i.coords for i in items]), off
    except (struct.error, IndexError):
        raise _err("malformed WKB geometry")


# -- GeoJSON ---------------------------------------------------------------

_GJ_NAME = {"point": "Point", "linestring": "LineString",
            "polygon": "Polygon", "multipoint": "MultiPoint",
            "multilinestring": "MultiLineString",
            "multipolygon": "MultiPolygon",
            "geometrycollection": "GeometryCollection"}
_GJ_KIND = {v.lower(): k for k, v in _GJ_NAME.items()}


def _tuples(x):
    if isinstance(x, (list, tuple)) and x and \
            isinstance(x[0], (int, float)):
        return (float(x[0]), float(x[1]))
    return [_tuples(i) for i in x]


def from_geojson(obj) -> Geometry:
    if isinstance(obj, str):
        try:
            obj = json.loads(obj)
        except json.JSONDecodeError as e:
            raise _err(f"invalid GeoJSON: {e}")
    if not isinstance(obj, dict):
        raise _err("GeoJSON geometry must be an object")
    t = str(obj.get("type", "")).lower()
    if t == "feature":
        return from_geojson(obj.get("geometry"))
    kind = _GJ_KIND.get(t)
    if kind is None:
        raise _err(f"unknown GeoJSON type {obj.get('type')!r}")
    if kind == "geometrycollection":
        return Geometry(kind, [from_geojson(g)
                               for g in obj.get("geometries", [])])
    coords = obj.get("coordinates")
    if coords is None:
        raise _err("GeoJSON geometry lacks coordinates")
    try:
        return Geometry(kind, _tuples(coords))
    except (TypeError, IndexError):
        raise _err("malformed GeoJSON coordinates")


def to_geojson(g: Geometry) -> dict:
    if g.kind == "geometrycollection":
        return {"type": "GeometryCollection",
                "geometries": [to_geojson(x) for x in g.coords]}

    def unpack(c):
        if isinstance(c, tuple):
            return [c[0], c[1]]
        return [unpack(i) for i in c]
    return {"type": _GJ_NAME[g.kind], "coordinates": unpack(g.coords)}


_LATLON_RE = re.compile(
    r"^\s*(-?\d+(?:\.\d+)?)\s*,\s*(-?\d+(?:\.\d+)?)\s*$")


def parse_any(text) -> Geometry:
    """WKT, GeoJSON, bare '[lon, lat]', ES {'lat':…,'lon':…} objects, or
    the ES 'lat,lon' string — the permissive input seam the ST_ functions
    and ES geo queries share."""
    if isinstance(text, dict):
        if "lat" in text and "lon" in text:
            return Geometry("point",
                            (float(text["lon"]), float(text["lat"])))
        return from_geojson(text)
    if isinstance(text, (list, tuple)):
        return Geometry("point", (float(text[0]), float(text[1])))
    t = str(text).strip()
    if t[:1] in "[{":
        v = json.loads(t)
        return parse_any(v)
    m = _LATLON_RE.match(t)
    if m:       # ES point string is LAT,LON order
        return Geometry("point", (float(m.group(2)), float(m.group(1))))
    return from_wkt(t)
