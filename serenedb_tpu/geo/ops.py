"""Geometry predicates and measures.

Reference analog: libs/geo/ shape predicates over S2. Design choice:
topological predicates (contains/intersects) run planar in lon/lat
degrees — correct for the region-scale shapes the reference's tests use
and orders simpler than S2; metric measures (distance, length, area) are
spherical on the mean-Earth radius, matching the reference's *_sphere
semantics and the existing point functions.
"""

from __future__ import annotations

import math

from .shapes import Geometry

EARTH_RADIUS_M = 6371008.8


# -- planar primitives -----------------------------------------------------

def _point_in_ring(p: tuple, ring: list) -> bool:
    """Ray casting; boundary counts as inside."""
    x, y = p
    n = len(ring)
    if n == 0:
        return False
    inside = False
    for i in range(n):
        x1, y1 = ring[i]
        x2, y2 = ring[(i + 1) % n]
        if _on_segment(p, (x1, y1), (x2, y2)):
            return True
        if (y1 > y) != (y2 > y):
            xi = x1 + (y - y1) * (x2 - x1) / (y2 - y1)
            if xi > x:
                inside = not inside
    return inside


def _on_segment(p, a, b, eps=1e-12) -> bool:
    (px, py), (ax, ay), (bx, by) = p, a, b
    cross = (bx - ax) * (py - ay) - (by - ay) * (px - ax)
    if abs(cross) > eps * max(1.0, abs(bx - ax) + abs(by - ay)):
        return False
    return (min(ax, bx) - eps <= px <= max(ax, bx) + eps and
            min(ay, by) - eps <= py <= max(ay, by) + eps)


def _point_in_polygon(p: tuple, rings: list) -> bool:
    if not rings or not _point_in_ring(p, rings[0]):
        return False
    for hole in rings[1:]:
        # strictly inside a hole = outside (hole boundary still counts in)
        if _point_in_ring(p, hole) and not any(
                _on_segment(p, hole[i], hole[(i + 1) % len(hole)])
                for i in range(len(hole))):
            return False
    return True


def _segs_intersect(s1, s2) -> bool:
    (a, b), (c, d) = s1, s2

    def orient(p, q, r):
        v = (q[0] - p[0]) * (r[1] - p[1]) - (q[1] - p[1]) * (r[0] - p[0])
        if abs(v) < 1e-18:
            return 0
        return 1 if v > 0 else -1
    o1, o2 = orient(a, b, c), orient(a, b, d)
    o3, o4 = orient(c, d, a), orient(c, d, b)
    if o1 != o2 and o3 != o4:
        return True
    return (_on_segment(c, a, b) or _on_segment(d, a, b) or
            _on_segment(a, c, d) or _on_segment(b, c, d))


# -- predicates ------------------------------------------------------------

def intersects(g1: Geometry, g2: Geometry) -> bool:
    # point fast paths
    if g1.kind == "point":
        return _point_touches(g1.coords, g2)
    if g2.kind == "point":
        return _point_touches(g2.coords, g1)
    # any vertex of one inside a polygon of the other
    for poly in g2.polygons():
        if any(_point_in_polygon(p, poly) for p in g1.points()):
            return True
    for poly in g1.polygons():
        if any(_point_in_polygon(p, poly) for p in g2.points()):
            return True
    # segment crossings
    s2 = g2.segments()
    return any(_segs_intersect(a, b) for a in g1.segments() for b in s2)


def _point_touches(p: tuple, g: Geometry) -> bool:
    k = g.kind
    if k == "point":
        return abs(p[0] - g.coords[0]) < 1e-12 and \
            abs(p[1] - g.coords[1]) < 1e-12
    if k == "multipoint":
        return any(abs(p[0] - q[0]) < 1e-12 and abs(p[1] - q[1]) < 1e-12
                   for q in g.coords)
    if k in ("linestring", "multilinestring"):
        return any(_on_segment(p, a, b) for a, b in g.segments())
    if k in ("polygon", "multipolygon"):
        return any(_point_in_polygon(p, poly) for poly in g.polygons())
    if k == "geometrycollection":
        return any(_point_touches(p, x) for x in g.coords)
    return False


def contains(g1: Geometry, g2: Geometry) -> bool:
    """g1 contains g2 (boundary-inclusive, like ST_Covers)."""
    if g1.kind in ("polygon", "multipolygon"):
        polys = g1.polygons()
        pts = g2.points()
        if not pts:
            return False
        if not all(any(_point_in_polygon(p, poly) for poly in polys)
                   for p in pts):
            return False
        # vertices inside is not sufficient for shapes with holes or
        # concavities: no g2 edge may cross a ring boundary
        ring_segs = [s for poly in polys
                     for ring in poly
                     for s in zip(ring, ring[1:] + ring[:1])]
        for seg in g2.segments():
            mid = ((seg[0][0] + seg[1][0]) / 2.0,
                   (seg[0][1] + seg[1][1]) / 2.0)
            if not any(_point_in_polygon(mid, poly) for poly in polys):
                return False
            for rs in ring_segs:
                if _segs_intersect(seg, rs) and not (
                        _on_segment(seg[0], *rs) or
                        _on_segment(seg[1], *rs)):
                    return False
        return True
    if g1.kind == "point":
        return g2.kind == "point" and _point_touches(g2.coords, g1)
    if g1.kind in ("linestring", "multilinestring"):
        return all(_point_touches(p, g1) for p in g2.points()) and \
            g2.kind in ("point", "multipoint", "linestring",
                        "multilinestring")
    if g1.kind == "multipoint":
        return g2.kind in ("point", "multipoint") and \
            all(_point_touches(p, g1) for p in g2.points())
    if g1.kind == "geometrycollection":
        return any(contains(x, g2) for x in g1.coords)
    return False


# -- measures --------------------------------------------------------------

def haversine_m(p1: tuple, p2: tuple) -> float:
    lat1, lat2 = math.radians(p1[1]), math.radians(p2[1])
    dlat = lat2 - lat1
    dlon = math.radians(p2[0] - p1[0])
    a = math.sin(dlat / 2) ** 2 + \
        math.cos(lat1) * math.cos(lat2) * math.sin(dlon / 2) ** 2
    return 2 * EARTH_RADIUS_M * math.asin(min(math.sqrt(a), 1.0))


def _point_seg_distance_m(p: tuple, a: tuple, b: tuple) -> float:
    """Great-circle point→segment distance via local equirectangular
    projection around the point (meter-accurate at region scale)."""
    lat0 = math.radians(p[1])
    kx = math.cos(lat0) * EARTH_RADIUS_M * math.pi / 180.0
    ky = EARTH_RADIUS_M * math.pi / 180.0

    def proj(q):
        return ((q[0] - p[0]) * kx, (q[1] - p[1]) * ky)
    ax, ay = proj(a)
    bx, by = proj(b)
    dx, dy = bx - ax, by - ay
    denom = dx * dx + dy * dy
    t = 0.0 if denom == 0 else max(
        0.0, min(1.0, -(ax * dx + ay * dy) / denom))
    cx, cy = ax + t * dx, ay + t * dy
    return math.hypot(cx, cy)


def distance_m(g1: Geometry, g2: Geometry) -> float:
    if intersects(g1, g2):
        return 0.0
    best = math.inf
    p1, p2 = g1.points(), g2.points()
    s1, s2 = g1.segments(), g2.segments()
    for p in p1:
        for q in p2:
            best = min(best, haversine_m(p, q))
        for a, b in s2:
            best = min(best, _point_seg_distance_m(p, a, b))
    for q in p2:
        for a, b in s1:
            best = min(best, _point_seg_distance_m(q, a, b))
    return best if best is not math.inf else 0.0


def length_m(g: Geometry) -> float:
    if g.kind in ("linestring", "multilinestring"):
        return sum(haversine_m(a, b) for a, b in g.segments())
    if g.kind == "geometrycollection":
        return sum(length_m(x) for x in g.coords)
    return 0.0


def perimeter_m(g: Geometry) -> float:
    if g.kind in ("polygon", "multipolygon"):
        return sum(haversine_m(a, b) for a, b in g.segments())
    if g.kind == "geometrycollection":
        return sum(perimeter_m(x) for x in g.coords)
    return 0.0


def _ring_area_sphere(ring: list) -> float:
    """Spherical polygon area via the spherical shoelace sum
    Σ (λ2−λ1)·(2 + sin φ1 + sin φ2) / 2 · R² — exact on great-circle
    edges at the small-edge limit."""
    if len(ring) < 3:
        return 0.0
    total = 0.0
    closed = list(ring)
    if closed[0] != closed[-1]:
        closed.append(closed[0])
    for i in range(len(closed) - 1):
        lon1, lat1 = map(math.radians, closed[i])
        lon2, lat2 = map(math.radians, closed[i + 1])
        total += (lon2 - lon1) * (2 + math.sin(lat1) + math.sin(lat2))
    return abs(total) / 2.0 * EARTH_RADIUS_M ** 2


def area_m2(g: Geometry) -> float:
    total = 0.0
    for poly in g.polygons():
        if poly:
            total += _ring_area_sphere(poly[0])
            for hole in poly[1:]:
                total -= _ring_area_sphere(hole)
    return max(total, 0.0)


def centroid(g: Geometry) -> tuple:
    """Vertex centroid for points/lines; area-weighted planar centroid
    for polygons (matches the ES/PG expectation at region scale)."""
    polys = g.polygons()
    if polys:
        ax = ay = aw = 0.0
        for poly in polys:
            ring = poly[0]
            closed = list(ring)
            if closed[0] != closed[-1]:
                closed.append(closed[0])
            a = cx = cy = 0.0
            for i in range(len(closed) - 1):
                x1, y1 = closed[i]
                x2, y2 = closed[i + 1]
                cross = x1 * y2 - x2 * y1
                a += cross
                cx += (x1 + x2) * cross
                cy += (y1 + y2) * cross
            if abs(a) > 1e-18:
                ax += cx / (3 * a) * abs(a)
                ay += cy / (3 * a) * abs(a)
                aw += abs(a)
        if aw > 0:
            return (ax / aw, ay / aw)
    pts = g.points()
    if not pts:
        return (0.0, 0.0)
    return (sum(p[0] for p in pts) / len(pts),
            sum(p[1] for p in pts) / len(pts))


def envelope(g: Geometry) -> Geometry:
    pts = g.points()
    if not pts:
        return Geometry("polygon", [])
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    x1, x2, y1, y2 = min(xs), max(xs), min(ys), max(ys)
    return Geometry("polygon", [[(x1, y1), (x2, y1), (x2, y2), (x1, y2),
                                 (x1, y1)]])


def bbox_contains(top: float, left: float, bottom: float, right: float,
                  p: tuple) -> bool:
    """geo_bounding_box semantics (ES): top-left / bottom-right corners."""
    return left <= p[0] <= right and bottom <= p[1] <= top
