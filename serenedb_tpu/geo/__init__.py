"""Geo shapes: WKT/WKB/GeoJSON codecs + spherical/planar geometry ops.

Reference analog: libs/geo/ (S2-backed shape_container, wkb.cpp,
geo_json.cpp). TPU re-design: geometries stay host-side text/bytes (geo
predicates are catalog-cardinality filter work, not MXU work); the batch
seam is the ST_* function layer, which evaluates whole columns per call.
"""

from .shapes import (Geometry, from_geojson, from_wkb, from_wkt,
                     to_geojson, to_wkb, to_wkt)

__all__ = ["Geometry", "from_wkt", "to_wkt", "from_wkb", "to_wkb",
           "from_geojson", "to_geojson"]
