"""Hierarchical grid cells for geo indexing (S2-cell-term analog).

Reference analog: server/connector/geo_filter_builder.cpp + the
iresearch GeoFilter — geometries are indexed as cell terms so geo
predicates become inverted-index candidate lookups with exact
post-verification, instead of per-row shape math over the whole table.

Scheme: equirectangular quadtree over (lon, lat). A level-L cell is one
tile of the 2^L x 2^L grid. Every geometry indexes its bbox covering at
the finest level of LEVELS whose covering stays within COVER_CAP cells,
PLUS the ancestors of those cells at every coarser level of LEVELS.
Queries expand the same way, so two intersecting shapes always share at
least one term: at the coarser of their two covering levels both emit
the cell containing any common point.

Cell ids pack (level, x, y) into one int: level << 56 | x << 28 | y.
"""

from __future__ import annotations

import math

#: covering levels, coarse → fine. Level L tiles are 360/2^L degrees
#: wide: ~22°, 1.4°, 5.3' (~9.8km), 20" (~600m), 1.2" (~38m). Level
#: selection is adaptive per geometry extent (_chosen_level picks the
#: finest level whose covering stays within COVER_CAP — the S2
#: RegionCoverer analog, reference: server/connector/
#: geo_filter_builder.cpp), so point-ish data lands on ~38m tiles while
#: continental polygons stay coarse. Extending this tuple is
#: backward-compatible with already-indexed terms: queries probe every
#: coarser level, a superset of any older scheme's levels.
LEVELS = (4, 8, 12, 16, 20)
COVER_CAP = 64          # max cells per covering at the chosen level


def _cell_id(level: int, x: int, y: int) -> int:
    return (level << 56) | (x << 28) | y


def _bbox(geom) -> tuple:
    """(min_lon, min_lat, max_lon, max_lat)."""
    pts = [p for p in geom.points()]
    for poly in geom.polygons():
        for ring in poly:
            pts.extend(ring)
    for seg in geom.segments():
        pts.extend(seg)
    if not pts:
        raise ValueError("empty geometry")
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    return (min(xs), min(ys), max(xs), max(ys))


def _clamp(v, lo, hi):
    return lo if v < lo else hi if v > hi else v


def _cell_range(bbox, level):
    """Inclusive (x0, x1, y0, y1) tile range covering the bbox."""
    n = 1 << level
    min_lon, min_lat, max_lon, max_lat = bbox
    x0 = int(_clamp((min_lon + 180.0) / 360.0, 0, 1 - 1e-12) * n)
    x1 = int(_clamp((max_lon + 180.0) / 360.0, 0, 1 - 1e-12) * n)
    y0 = int(_clamp((min_lat + 90.0) / 180.0, 0, 1 - 1e-12) * n)
    y1 = int(_clamp((max_lat + 90.0) / 180.0, 0, 1 - 1e-12) * n)
    return x0, x1, y0, y1


#: ancestor-space bit: terms emitted for a cell's COARSER parents live in
#: a separate term space so a fine query probing its own level never
#: pulls every finely-indexed row of a huge coarse tile
_ANC = 1 << 62


def _chosen_level(bbox) -> int:
    chosen = LEVELS[0]
    for lv in reversed(LEVELS):
        x0, x1, y0, y1 = _cell_range(bbox, lv)
        if (x1 - x0 + 1) * (y1 - y0 + 1) <= COVER_CAP:
            chosen = lv
            break
    return chosen


def _covering(bbox, level) -> list:
    x0, x1, y0, y1 = _cell_range(bbox, level)
    return [(x, y) for x in range(x0, x1 + 1) for y in range(y0, y1 + 1)]


def geometry_terms(geom) -> list:
    """Index terms for a geometry: covering cells at its chosen level
    (covering space) + those cells' ancestors at every coarser level of
    LEVELS (ancestor space). Matching invariant with query_terms: two
    intersecting shapes share a term at the coarser of their covering
    levels — as covering/covering, covering/ancestor, or
    ancestor/covering depending on which side is finer."""
    return _box_index_terms(_bbox(geom))


def expand_bbox_multi(bbox, radius_m: float) -> list:
    """Conservatively grow a bbox by a metre radius (for ST_DWithin):
    latitude pads by radius/111km; longitude by the same over cos(lat),
    degrading to the full circle near the poles. Longitude WRAPS at the
    antimeridian — the expansion may return TWO boxes (the exact
    haversine predicate is periodic; clamping would silently drop
    matches across +/-180)."""
    min_lon, min_lat, max_lon, max_lat = bbox
    dlat = radius_m / 111_000.0
    lat_lo = max(-90.0, min_lat - dlat)
    lat_hi = min(90.0, max_lat + dlat)
    # a circle that reaches a pole spans EVERY longitude (haversine is
    # periodic over the pole); and near the poles cos() shrinks the
    # metres-per-degree so fast that any clamped dlon understates the
    # true extent — widen to the full circle in both cases
    if lat_hi >= 90.0 - 1e-9 or lat_lo <= -90.0 + 1e-9:
        return [(-180.0, lat_lo, 180.0, lat_hi)]
    max_abs_lat = max(abs(lat_lo), abs(lat_hi))
    cosv = math.cos(math.radians(max_abs_lat))
    dlon = radius_m / (111_000.0 * cosv) if cosv > 1e-9 else 361.0
    lo = min_lon - dlon
    hi = max_lon + dlon
    if hi - lo >= 360.0 or dlon >= 180.0:
        return [(-180.0, lat_lo, 180.0, lat_hi)]
    if lo < -180.0:
        return [(lo + 360.0, lat_lo, 180.0, lat_hi),
                (-180.0, lat_lo, hi, lat_hi)]
    if hi > 180.0:
        return [(lo, lat_lo, 180.0, lat_hi),
                (-180.0, lat_lo, hi - 360.0, lat_hi)]
    return [(lo, lat_lo, hi, lat_hi)]


def point_terms(lon: float, lat: float) -> list:
    """Index terms for a single point — the degenerate-bbox case of
    geometry_terms, shared so the index build fast path can never
    diverge from the term scheme."""
    return _box_index_terms((lon, lat, lon, lat))


def _box_index_terms(box) -> list:
    chosen = _chosen_level(box)
    terms = set()
    for x, y in _covering(box, chosen):
        terms.add(_cell_id(chosen, x, y))
    for lv in LEVELS:
        if lv >= chosen:
            break
        for x, y in _covering(box, lv):
            terms.add(_ANC | _cell_id(lv, x, y))
    return sorted(terms)


def query_terms(geom, radius_m: float = 0.0) -> list:
    """Terms to PROBE for a query geometry (optionally dwithin-expanded):
    per covering cell q at the query's level — covering-space q (equal
    level matches), ancestor-space q (finer-indexed shapes below q), and
    covering-space ancestors of q (coarser-indexed shapes above q)."""
    box = _bbox(geom)
    boxes = expand_bbox_multi(box, radius_m) if radius_m > 0 else [box]
    terms = set()
    for b in boxes:
        chosen = _chosen_level(b)
        for x, y in _covering(b, chosen):
            terms.add(_cell_id(chosen, x, y))
            terms.add(_ANC | _cell_id(chosen, x, y))
        for lv in LEVELS:
            if lv >= chosen:
                break
            for x, y in _covering(b, lv):
                terms.add(_cell_id(lv, x, y))
    return sorted(terms)
