"""Deterministic fault-injection points.

Reference analog: SDB_IF_FAILURE / SDB_WAIT_ON_FAILURE named failure points
armed per session with `SET sdb_faults='name'` (reference:
libs/basics/debugging.h:28-99, server/query/config_variables.cpp:261-296).
Recovery tests arm a point (e.g. crash_before_search_wal_commit), crash the
process, restart, and verify the replayed state.

Unlike the reference these are always compiled in; arming is the gate.
`crash` uses os._exit to simulate a hard kill (no atexit/flush), which is
what recovery tests need.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_armed: set[str] = set()


class FaultInjected(RuntimeError):
    def __init__(self, name: str):
        super().__init__(f"fault injected: {name}")
        self.name = name


def arm_from_spec(spec: str) -> None:
    """Apply a `SET sdb_faults` spec: 'a,b' arms; '+a' adds; '-a' removes;
    empty string clears (RESET semantics)."""
    with _lock:
        names = [s.strip() for s in spec.split(",") if s.strip()]
        if not names:
            _armed.clear()
            return
        if not any(n.startswith(("+", "-")) for n in names):
            _armed.clear()
        for n in names:
            if n.startswith("+"):
                _armed.add(n[1:])
            elif n.startswith("-"):
                _armed.discard(n[1:])
            else:
                _armed.add(n)


def armed(name: str) -> bool:
    with _lock:
        return name in _armed


def if_failure(name: str) -> None:
    """Raise FaultInjected if `name` is armed."""
    if armed(name):
        raise FaultInjected(name)


#: 'exit' hard-kills the process (the real crash semantics); 'raise' throws
#: FaultInjected so an in-process recovery harness can abandon the Database
#: (no close/flush) and reopen from disk — equivalent on-disk state to a
#: kill at the fault point, but runnable inside one pytest process.
_crash_mode = "exit"


def set_crash_mode(mode: str) -> None:
    global _crash_mode
    assert mode in ("exit", "raise")
    _crash_mode = mode


def crash_if_armed(name: str) -> None:
    """Hard-kill the process if `name` is armed (crash-recovery testing)."""
    if armed(name):
        if _crash_mode == "raise":
            raise FaultInjected(name)
        os._exit(137)


def clear() -> None:
    with _lock:
        _armed.clear()
