"""Topic-based logging with an in-memory, SQL-queryable ring.

Reference analog: SDB_* macros routing into DuckDB's LogManager so logs are
queryable via `SELECT * FROM sdb_log` (reference: libs/basics/log.h:40-118,
CLAUDE.md:22-23). Here: a process-wide ring buffer of structured records that
the sdb_log system view reads, plus optional stdout/file emission.
"""

from __future__ import annotations

import collections
import enum
import os
import sys
import threading
import time
from dataclasses import dataclass


class Level(enum.IntEnum):
    TRACE = 0
    DEBUG = 1
    INFO = 2
    WARN = 3
    ERROR = 4
    FATAL = 5


@dataclass
class Record:
    ts: float
    level: Level
    topic: str
    message: str


class LogManager:
    def __init__(self, capacity: int = 8192):
        self._ring: collections.deque[Record] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.level = Level[os.environ.get("SERENE_LOG_LEVEL", "INFO").upper()] \
            if os.environ.get("SERENE_LOG_LEVEL", "INFO").upper() in Level.__members__ \
            else Level.INFO
        self.topic_levels: dict[str, Level] = {}
        self.stdout = os.environ.get("SERENE_LOG_STDOUT", "0") == "1"
        self._file = None

    def set_file(self, path: str) -> None:
        self._file = open(path, "a", buffering=1)

    def enabled(self, level: Level, topic: str) -> bool:
        return level >= self.topic_levels.get(topic, self.level)

    def log(self, level: Level, topic: str, message: str) -> None:
        if not self.enabled(level, topic):
            return
        rec = Record(time.time(), level, topic, message)
        with self._lock:
            self._ring.append(rec)
        if self.stdout or level >= Level.ERROR:
            line = f"[{level.name}] {topic}: {message}"
            print(line, file=sys.stderr)
        if self._file is not None:
            self._file.write(
                f"{rec.ts:.6f} {level.name} {topic} {message}\n")

    def records(self) -> list[Record]:
        with self._lock:
            return list(self._ring)


MANAGER = LogManager()


def trace(topic, msg): MANAGER.log(Level.TRACE, topic, msg)
def debug(topic, msg): MANAGER.log(Level.DEBUG, topic, msg)
def info(topic, msg): MANAGER.log(Level.INFO, topic, msg)
def warn(topic, msg): MANAGER.log(Level.WARN, topic, msg)
def error(topic, msg): MANAGER.log(Level.ERROR, topic, msg)
