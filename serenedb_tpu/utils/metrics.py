"""Fixed registry of atomic gauges, ClickHouse-CurrentMetrics style.

Reference analog: libs/basics/metrics.h:27-71 — relaxed-atomic gauges bumped
only at task/connection boundaries (never per row), surfaced via the
`sdb_metrics` system view. Python ints under a lock are cheap enough at those
boundaries.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional


class Gauge:
    __slots__ = ("name", "description", "_value", "_lock")

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def sub(self, n: int = 1) -> None:
        self.add(-n)

    def set(self, n: int) -> None:
        """Overwrite the level (byte-size gauges that track a cache's
        current footprint rather than accumulate a count)."""
        with self._lock:
            self._value = n

    def add_time_ns(self, start_ns: int,
                    now_ns: Optional[int] = None) -> int:
        """Accumulate one elapsed interval atomically: adds
        (now - start_ns) nanoseconds in a single locked update and
        returns `now`, so call sites chain consecutive intervals off one
        clock read instead of re-reading between add and next start."""
        if now_ns is None:
            now_ns = time.perf_counter_ns()
        self.add(now_ns - start_ns)
        return now_ns

    def delta(self, baseline: int) -> int:
        """Current value minus a snapshot baseline (one atomic read) —
        the scrape-side pairing of Registry.snapshot()."""
        return self.value - baseline

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    @contextmanager
    def scoped(self, n: int = 1):
        self.add(n)
        try:
            yield
        finally:
            self.sub(n)


class Registry:
    def __init__(self):
        self._gauges: dict[str, Gauge] = {}

    def gauge(self, name: str, description: str = "") -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge(name, description)
        return g

    def all(self) -> list[Gauge]:
        return [self._gauges[k] for k in sorted(self._gauges)]

    def snapshot(self) -> dict[str, int]:
        """One point-in-time {name: value} map for scrapes and tests:
        every gauge is read exactly once (each read atomic under its own
        lock), so a consumer iterating the result never races the
        per-gauge locks mid-scrape or sees a gauge twice at two
        values."""
        return {g.name: g.value for g in self.all()}


REGISTRY = Registry()

PG_CONNECTIONS = REGISTRY.gauge("PgConnections", "open PG wire connections")
HTTP_CONNECTIONS = REGISTRY.gauge("HttpConnections", "open HTTP connections")
QUERIES_ACTIVE = REGISTRY.gauge("QueriesActive", "queries currently executing")
REFRESH_ACTIVE = REGISTRY.gauge("RefreshActive", "running refresh tasks")
REFRESH_PENDING = REGISTRY.gauge("RefreshPending", "queued refresh tasks")
COMPACTION_ACTIVE = REGISTRY.gauge("CompactionActive", "running compactions")
COMPACTION_PENDING = REGISTRY.gauge("CompactionPending", "queued compactions")
CLEANUP_ACTIVE = REGISTRY.gauge("CleanupActive", "running cleanup tasks")
DEVICE_OFFLOADS = REGISTRY.gauge("DeviceOffloads", "batches dispatched to TPU")
DEVICE_BYTES = REGISTRY.gauge("DeviceBytesMoved", "bytes copied host->device")
DEVICE_CACHE_HITS = REGISTRY.gauge(
    "DeviceCacheHits",
    "device column cache probes served from HBM-resident uploads "
    "(host->device transfer skipped)")
DEVICE_CACHE_MISSES = REGISTRY.gauge(
    "DeviceCacheMisses",
    "device column cache probes that had to upload from host")
DEVICE_CACHE_EVICTIONS = REGISTRY.gauge(
    "DeviceCacheEvictions",
    "device column cache entries dropped (LRU past the byte cap or a "
    "superseded publication swept on store)")
DEVICE_CACHE_BYTES = REGISTRY.gauge(
    "DeviceCacheBytes",
    "current bytes held by the device column cache")
WAL_COMMITS = REGISTRY.gauge("WalCommits", "search WAL commit records written")
POOL_MORSELS = REGISTRY.gauge("PoolMorselsExecuted",
                              "morsel tasks executed by the worker pool")
POOL_QUEUE_WAIT_US = REGISTRY.gauge("PoolQueueWaitUs",
                                    "cumulative µs tasks waited queued")
POOL_BUSY_US = REGISTRY.gauge("PoolBusyUs",
                              "cumulative µs workers spent running tasks")
POOL_STEALS = REGISTRY.gauge("PoolSteals",
                             "tasks stolen from a sibling worker's deque")
ZONEMAP_PRUNED = REGISTRY.gauge(
    "ZonemapMorselsPruned",
    "scan/aggregate morsels skipped because block statistics proved no "
    "row could match")
ZONEMAP_SCANNED = REGISTRY.gauge(
    "ZonemapMorselsScanned",
    "morsels that passed zone-map analysis and were actually scanned")
JOIN_FILTER_PRUNED = REGISTRY.gauge(
    "JoinFilterMorselsPruned",
    "probe-side scan morsels skipped because the build side's published "
    "key range proved no row of the block could find a join partner")
JOIN_FILTER_SCANNED = REGISTRY.gauge(
    "JoinFilterMorselsScanned",
    "probe-side morsels that passed the join-filter key-range analysis "
    "and were actually scanned")
ZONEMAP_STALE_REBUILDS = REGISTRY.gauge(
    "ZonemapStaleRebuilds",
    "zone-map column stats rebuilt from scratch after a non-append "
    "mutation invalidated the cached version")
QUERIES_EXECUTED = REGISTRY.gauge(
    "QueriesExecuted", "statements completed (success) since start")
QUERY_TIME_NS = REGISTRY.gauge(
    "QueryTimeNs", "cumulative ns spent executing completed statements")
SLOW_QUERIES = REGISTRY.gauge(
    "SlowQueries",
    "statements that exceeded serene_log_min_duration_ms and were "
    "written to the slow-query log")
RESULT_CACHE_HITS = REGISTRY.gauge(
    "ResultCacheHits",
    "statements served from the result cache without executing")
RESULT_CACHE_MISSES = REGISTRY.gauge(
    "ResultCacheMisses",
    "cacheable statements that executed because no entry matched")
RESULT_CACHE_EVICTIONS = REGISTRY.gauge(
    "ResultCacheEvictions",
    "result-cache entries evicted (LRU byte pressure or a superseded "
    "publication swept)")
RESULT_CACHE_BYTES = REGISTRY.gauge(
    "ResultCacheBytes", "bytes currently held by the result cache")
FRAGMENT_CACHE_HITS = REGISTRY.gauge(
    "FragmentCacheHits",
    "per-segment search fragments (filter doc sets / top-k outputs) "
    "served from the fragment cache")
FRAGMENT_CACHE_MISSES = REGISTRY.gauge(
    "FragmentCacheMisses",
    "per-segment search fragments computed because no entry matched")
FRAGMENT_CACHE_BYTES = REGISTRY.gauge(
    "FragmentCacheBytes", "bytes currently held by the fragment cache")
SEARCH_BATCH_DISPATCHES = REGISTRY.gauge(
    "SearchBatchDispatches",
    "coalesced search scoring dispatches executed by the query batcher "
    "(each scores one or more top-k queries in one vectorized pass)")
SEARCH_BATCH_QUERIES = REGISTRY.gauge(
    "SearchBatchQueries",
    "top-k queries scored through batcher dispatches (QUERIES / "
    "DISPATCHES = mean batch size)")
SEARCH_BATCH_WINDOW_WAIT_NS = REGISTRY.gauge(
    "SearchBatchWindowWaitNs",
    "cumulative ns queries spent queued in the batcher before their "
    "dispatch started (coalescing latency cost)")
SEARCH_BATCH_COALESCED = REGISTRY.gauge(
    "SearchBatchCoalesced",
    "queries that shared their scoring dispatch with at least one other "
    "query (the batching win; singleton dispatches don't count)")
SHARD_PIPELINES = REGISTRY.gauge(
    "ShardPipelines",
    "per-shard pipeline executions launched by the sharded execution "
    "tier (serene_shards > 1): each morsel group, fused device dispatch "
    "or segment-set search run over one shard counts once")
SHARD_MORSELS_PRUNED = REGISTRY.gauge(
    "ShardMorselsPruned",
    "probe-side blocks pruned by the shard-to-shard join filter: the "
    "build side's PER-SHARD key min/max ranges proved no row of the "
    "block can find a partner in any build shard")
SHARD_BYTES_SKIPPED = REGISTRY.gauge(
    "ShardBytesSkipped",
    "host->device upload bytes skipped because per-shard pruning "
    "proved a probe shard's blocks partner-less before any transfer")
