"""Fixed registry of atomic gauges, ClickHouse-CurrentMetrics style.

Reference analog: libs/basics/metrics.h:27-71 — relaxed-atomic gauges bumped
only at task/connection boundaries (never per row), surfaced via the
`sdb_metrics` system view. Python ints under a lock are cheap enough at those
boundaries.
"""

from __future__ import annotations

import bisect
import threading
from contextlib import contextmanager


class Gauge:
    __slots__ = ("name", "description", "_value", "_lock")

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def sub(self, n: int = 1) -> None:
        self.add(-n)

    def set(self, n: int) -> None:
        """Overwrite the level (byte-size gauges that track a cache's
        current footprint rather than accumulate a count)."""
        with self._lock:
            self._value = n

    def delta(self, baseline: int) -> int:
        """Current value minus a snapshot baseline (one atomic read) —
        the scrape-side pairing of Registry.snapshot()."""
        return self.value - baseline

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    @contextmanager
    def scoped(self, n: int = 1):
        self.add(n)
        try:
            yield
        finally:
            self.sub(n)


#: log-spaced histogram bucket upper bounds in NANOSECONDS: powers of two
#: from 1 µs to ~137 s (28 buckets) plus the implicit +Inf overflow slot.
#: Log spacing keeps relative quantile error bounded (one octave) across
#: six decades of latency with a fixed, tiny footprint — the Prometheus
#: classic-histogram shape, shared by the process-wide `Histogram` gauges
#: and the per-fingerprint latency sketches in obs/statements.py.
HIST_BOUNDS_NS: tuple[int, ...] = tuple(1000 * (1 << k) for k in range(28))


def hist_bucket_index(ns: int) -> int:
    """Bucket slot for one observation: the first bound >= ns, or the
    +Inf slot (len(HIST_BOUNDS_NS)) past the last finite bound."""
    return bisect.bisect_left(HIST_BOUNDS_NS, max(int(ns), 0))


def hist_quantile_ns(counts, q: float) -> float:
    """Quantile estimate from bucket counts (len = len(HIST_BOUNDS_NS)+1)
    by linear interpolation inside the target bucket — the same estimate
    Prometheus' histogram_quantile() would derive from the exported
    buckets, so /_stats and a real Prometheus agree. Observations in the
    +Inf bucket clamp to the largest finite bound. Returns ns (0.0 when
    the histogram is empty)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            if i >= len(HIST_BOUNDS_NS):      # +Inf bucket: clamp
                return float(HIST_BOUNDS_NS[-1])
            lo = float(HIST_BOUNDS_NS[i - 1]) if i else 0.0
            hi = float(HIST_BOUNDS_NS[i])
            return lo + (hi - lo) * ((target - cum) / c)
        cum += c
    return float(HIST_BOUNDS_NS[-1])


class Histogram:
    """Fixed log-spaced-bucket histogram (Prometheus classic histogram
    semantics: cumulative `le` buckets + sum + count).

    Observed at task/statement boundaries only — one bisect over 28
    bounds plus one locked triple update per observation, never per row —
    so p50/p95/p99 become derivable from `/metrics` and `/_stats`
    without any per-request allocation.

    `unit` is "s" (observations in NANOSECONDS, exported as seconds —
    the latency histograms) or "bytes" (observations in bytes, exported
    raw — the memory histograms). The log-spaced bounds read naturally
    in both: 1 µs..137 s, or 1 kB..137 GB."""

    __slots__ = ("name", "description", "unit", "_counts", "_sum_ns",
                 "_lock")

    def __init__(self, name: str, description: str = "", unit: str = "s"):
        self.name = name
        self.description = description
        self.unit = unit
        self._counts = [0] * (len(HIST_BOUNDS_NS) + 1)
        self._sum_ns = 0
        self._lock = threading.Lock()

    def observe_ns(self, ns: int) -> None:
        i = hist_bucket_index(ns)
        with self._lock:
            self._counts[i] += 1
            self._sum_ns += max(int(ns), 0)

    def snapshot(self) -> tuple[list[int], int]:
        """(per-bucket counts, sum ns) under one lock acquisition."""
        with self._lock:
            return list(self._counts), self._sum_ns

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    def quantile_ns(self, q: float) -> float:
        counts, _ = self.snapshot()
        return hist_quantile_ns(counts, q)

    def percentiles_ms(self) -> dict:
        """{count, p50_ms, p95_ms, p99_ms} for the /_stats JSON."""
        counts, _ = self.snapshot()
        return {"count": sum(counts),
                "p50_ms": round(hist_quantile_ns(counts, 0.50) / 1e6, 3),
                "p95_ms": round(hist_quantile_ns(counts, 0.95) / 1e6, 3),
                "p99_ms": round(hist_quantile_ns(counts, 0.99) / 1e6, 3)}

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(HIST_BOUNDS_NS) + 1)
            self._sum_ns = 0


class Registry:
    def __init__(self):
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}

    def gauge(self, name: str, description: str = "") -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge(name, description)
        return g

    def histogram(self, name: str, description: str = "",
                  unit: str = "s") -> Histogram:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = Histogram(name, description, unit)
        return h

    def all(self) -> list[Gauge]:
        return [self._gauges[k] for k in sorted(self._gauges)]

    def all_histograms(self) -> list[Histogram]:
        return [self._hists[k] for k in sorted(self._hists)]

    def snapshot(self) -> dict[str, int]:
        """One point-in-time {name: value} map for scrapes and tests:
        every gauge is read exactly once (each read atomic under its own
        lock), so a consumer iterating the result never races the
        per-gauge locks mid-scrape or sees a gauge twice at two
        values."""
        return {g.name: g.value for g in self.all()}


REGISTRY = Registry()

PG_CONNECTIONS = REGISTRY.gauge("PgConnections", "open PG wire connections")
HTTP_CONNECTIONS = REGISTRY.gauge("HttpConnections", "open HTTP connections")
QUERIES_ACTIVE = REGISTRY.gauge("QueriesActive", "queries currently executing")
REFRESH_ACTIVE = REGISTRY.gauge("RefreshActive", "running refresh tasks")
REFRESH_PENDING = REGISTRY.gauge("RefreshPending", "queued refresh tasks")
COMPACTION_ACTIVE = REGISTRY.gauge("CompactionActive", "running compactions")
COMPACTION_PENDING = REGISTRY.gauge("CompactionPending", "queued compactions")
CLEANUP_ACTIVE = REGISTRY.gauge("CleanupActive", "running cleanup tasks")
DEVICE_OFFLOADS = REGISTRY.gauge("DeviceOffloads", "batches dispatched to TPU")
DEVICE_BYTES = REGISTRY.gauge("DeviceBytesMoved", "bytes copied host->device")
DEVICE_CACHE_HITS = REGISTRY.gauge(
    "DeviceCacheHits",
    "device column cache probes served from HBM-resident uploads "
    "(host->device transfer skipped)")
DEVICE_CACHE_MISSES = REGISTRY.gauge(
    "DeviceCacheMisses",
    "device column cache probes that had to upload from host")
DEVICE_CACHE_EVICTIONS = REGISTRY.gauge(
    "DeviceCacheEvictions",
    "device column cache entries dropped (LRU past the byte cap or a "
    "superseded publication swept on store)")
DEVICE_CACHE_BYTES = REGISTRY.gauge(
    "DeviceCacheBytes",
    "current bytes held by the device column cache")
DEVICE_PROGRAMS_COMPILED = REGISTRY.gauge(
    "DeviceProgramsCompiled",
    "jitted device programs built by the compile ledger "
    "(obs/device.py) — each is one XLA trace+compile on first dispatch")
DEVICE_PROGRAM_HITS = REGISTRY.gauge(
    "DeviceProgramCacheHits",
    "compile-ledger probes served by an already-compiled program "
    "(no retrace, no recompile)")
DEVICE_PROGRAM_MISSES = REGISTRY.gauge(
    "DeviceProgramCacheMisses",
    "compile-ledger probes that had to build a new program")
DEVICE_PROGRAM_EVICTIONS = REGISTRY.gauge(
    "DeviceProgramCacheEvictions",
    "compiled programs dropped by the bounded program LRU "
    "(serene_program_cache_entries); an evicted shape re-compiles on "
    "next use")
DEVICE_PROGRAM_ENTRIES = REGISTRY.gauge(
    "DeviceProgramCacheEntries",
    "compiled programs currently held by the program LRU (live)")
DEVICE_RECOMPILE_STORMS = REGISTRY.gauge(
    "DeviceRecompileStorms",
    "recompile-storm warnings fired: one program family compiled more "
    "than RECOMPILE_STORM_PER_MIN new shapes within a minute — repeat "
    "queries are not reusing cached executables")
DEVICE_TRANSFERS_UP = REGISTRY.gauge(
    "DeviceTransfersUp",
    "host->device transfers recorded by the device telemetry ledger "
    "(column uploads, code/rowmask tiles, stacked mesh commits, "
    "cached build-output commits)")
DEVICE_FETCH_BYTES = REGISTRY.gauge(
    "DeviceBytesFetched",
    "bytes copied device->host fetching program outputs (the "
    "readback sibling of DeviceBytesMoved)")
WAL_COMMITS = REGISTRY.gauge("WalCommits", "search WAL commit records written")
WAL_FSYNCS = REGISTRY.gauge(
    "WalFsyncs", "WAL group-commit fsync calls (commits per fsync = "
    "WalCommits / WalFsyncs — the group-commit amortization ratio)")
INGEST_DOCS = REGISTRY.gauge(
    "IngestDocs", "rows appended through the write path (INSERT/COPY)")
INGEST_BYTES = REGISTRY.gauge(
    "IngestBytes", "columnar bytes appended through the write path")
INGEST_BATCHES = REGISTRY.gauge(
    "IngestBatches", "write-path append batches (statements or COPY "
    "chunks; IngestDocs / IngestBatches = mean batch size)")
SEGMENT_BUILDS = REGISTRY.gauge(
    "SegmentBuilds", "inverted-index field segments built (initial "
    "builds + delta tails)")
SEGMENT_MERGES = REGISTRY.gauge(
    "SegmentMerges", "tiered segment merges (adjacent runs compacted "
    "into one segment)")
POOL_MORSELS = REGISTRY.gauge("PoolMorselsExecuted",
                              "morsel tasks executed by the worker pool")
POOL_QUEUE_WAIT_US = REGISTRY.gauge("PoolQueueWaitUs",
                                    "cumulative µs tasks waited queued")
POOL_BUSY_US = REGISTRY.gauge("PoolBusyUs",
                              "cumulative µs workers spent running tasks")
POOL_STEALS = REGISTRY.gauge("PoolSteals",
                             "tasks stolen from a sibling worker's deque")
ZONEMAP_PRUNED = REGISTRY.gauge(
    "ZonemapMorselsPruned",
    "scan/aggregate morsels skipped because block statistics proved no "
    "row could match")
ZONEMAP_SCANNED = REGISTRY.gauge(
    "ZonemapMorselsScanned",
    "morsels that passed zone-map analysis and were actually scanned")
JOIN_FILTER_PRUNED = REGISTRY.gauge(
    "JoinFilterMorselsPruned",
    "probe-side scan morsels skipped because the build side's published "
    "key range proved no row of the block could find a join partner")
JOIN_FILTER_SCANNED = REGISTRY.gauge(
    "JoinFilterMorselsScanned",
    "probe-side morsels that passed the join-filter key-range analysis "
    "and were actually scanned")
ZONEMAP_STALE_REBUILDS = REGISTRY.gauge(
    "ZonemapStaleRebuilds",
    "zone-map column stats rebuilt from scratch after a non-append "
    "mutation invalidated the cached version")
QUERIES_EXECUTED = REGISTRY.gauge(
    "QueriesExecuted", "statements completed (success) since start")
QUERY_TIME_NS = REGISTRY.gauge(
    "QueryTimeNs", "cumulative ns spent executing completed statements")
SLOW_QUERIES = REGISTRY.gauge(
    "SlowQueries",
    "statements that exceeded serene_log_min_duration_ms and were "
    "written to the slow-query log")
RESULT_CACHE_HITS = REGISTRY.gauge(
    "ResultCacheHits",
    "statements served from the result cache without executing")
RESULT_CACHE_MISSES = REGISTRY.gauge(
    "ResultCacheMisses",
    "cacheable statements that executed because no entry matched")
RESULT_CACHE_EVICTIONS = REGISTRY.gauge(
    "ResultCacheEvictions",
    "result-cache entries evicted (LRU byte pressure or a superseded "
    "publication swept)")
RESULT_CACHE_BYTES = REGISTRY.gauge(
    "ResultCacheBytes", "bytes currently held by the result cache")
FRAGMENT_CACHE_HITS = REGISTRY.gauge(
    "FragmentCacheHits",
    "per-segment search fragments (filter doc sets / top-k outputs) "
    "served from the fragment cache")
FRAGMENT_CACHE_MISSES = REGISTRY.gauge(
    "FragmentCacheMisses",
    "per-segment search fragments computed because no entry matched")
FRAGMENT_CACHE_BYTES = REGISTRY.gauge(
    "FragmentCacheBytes", "bytes currently held by the fragment cache")
SEARCH_BATCH_DISPATCHES = REGISTRY.gauge(
    "SearchBatchDispatches",
    "coalesced search scoring dispatches executed by the query batcher "
    "(each scores one or more top-k queries in one vectorized pass)")
SEARCH_BATCH_QUERIES = REGISTRY.gauge(
    "SearchBatchQueries",
    "top-k queries scored through batcher dispatches (QUERIES / "
    "DISPATCHES = mean batch size)")
SEARCH_BATCH_WINDOW_WAIT_NS = REGISTRY.gauge(
    "SearchBatchWindowWaitNs",
    "cumulative ns queries spent queued in the batcher before their "
    "dispatch started (coalescing latency cost)")
SEARCH_BATCH_COALESCED = REGISTRY.gauge(
    "SearchBatchCoalesced",
    "queries that shared their scoring dispatch with at least one other "
    "query (the batching win; singleton dispatches don't count)")
POSTING_POOL_HITS = REGISTRY.gauge(
    "PostingPoolHits",
    "posting-pool term lookups served by pages already resident in the "
    "device region (search/posting_pool.py) — each hit is one term's "
    "postings the batched ragged path did NOT re-upload")
POSTING_POOL_MISSES = REGISTRY.gauge(
    "PostingPoolMisses",
    "posting-pool term lookups that allocated and wrote fresh pages "
    "(first touch of a (segment, term) key, or re-entry after eviction)")
POSTING_POOL_EVICTIONS = REGISTRY.gauge(
    "PostingPoolEvictions",
    "resident terms evicted LRU from the posting pool to make room "
    "under the serene_posting_pages budget")
POSTING_POOL_PAGES_USED = REGISTRY.gauge(
    "PostingPoolPagesUsed",
    "pages of the device posting region currently holding resident "
    "terms (live; budget is serene_posting_pages)")
POSTING_POOL_BYTES = REGISTRY.gauge(
    "PostingPoolBytes",
    "bytes of the device posting region currently occupied by resident "
    "terms (live; PagesUsed x page size x docs+tfs)")
POSTING_POOL_DEVICE_QUERIES = REGISTRY.gauge(
    "PostingPoolDeviceQueries",
    "batched ragged queries scored fully on device because every slice "
    "was page-resident (final top-k left the device sorted)")
POSTING_POOL_PARTIAL = REGISTRY.gauge(
    "PostingPoolPartialQueries",
    "batched ragged queries whose resident prefix scored on device "
    "with the host merging the non-resident tail slices (deterministic "
    "same-order f32 adds — bit-identical to the all-host path)")
VECTOR_SEARCH_QUERIES = REGISTRY.gauge(
    "VectorSearchQueries",
    "knn / MaxSim queries scored by the vector subsystem "
    "(search/vector_store.py) — each member of a coalesced batch "
    "counts once")
VECTOR_SEARCH_DISPATCHES = REGISTRY.gauge(
    "VectorSearchDispatches",
    "jitted vector programs dispatched (probe, brute-oracle and MaxSim "
    "batches each count one; a warm coalesced batch is exactly one)")
VECTOR_PROBED_CLUSTERS = REGISTRY.gauge(
    "VectorProbedClusters",
    "IVF cluster lists probed across all vector queries (queries x "
    "effective nprobe) — the work that scales with nprobe, not N")
VECTOR_BYTES_RESIDENT = REGISTRY.gauge(
    "VectorBytesResident",
    "bytes of the device vector region currently occupied by resident "
    "segments (live pages x page size; budget is serene_vector_pages)")
VECTOR_POOL_HITS = REGISTRY.gauge(
    "VectorPoolHits",
    "vector-pool segment lookups served by pages already resident in "
    "the device region — a hit means the batch re-scored vectors "
    "without re-uploading them")
VECTOR_POOL_MISSES = REGISTRY.gauge(
    "VectorPoolMisses",
    "vector-pool segment lookups that allocated and wrote fresh pages "
    "(first touch of a segment, or re-entry after eviction)")
VECTOR_POOL_EVICTIONS = REGISTRY.gauge(
    "VectorPoolEvictions",
    "resident vector segments evicted LRU from the vector pool to make "
    "room under the serene_vector_pages budget")
SHARD_PIPELINES = REGISTRY.gauge(
    "ShardPipelines",
    "per-shard pipeline executions launched by the sharded execution "
    "tier (serene_shards > 1): each morsel group, fused device dispatch "
    "or segment-set search run over one shard counts once")
SHARD_MORSELS_PRUNED = REGISTRY.gauge(
    "ShardMorselsPruned",
    "probe-side blocks pruned by the shard-to-shard join filter: the "
    "build side's PER-SHARD key min/max ranges proved no row of the "
    "block can find a partner in any build shard")
SHARD_BYTES_SKIPPED = REGISTRY.gauge(
    "ShardBytesSkipped",
    "host->device upload bytes skipped because per-shard pruning "
    "proved a probe shard's blocks partner-less before any transfer")
COLLECTIVE_DISPATCHES = REGISTRY.gauge(
    "CollectiveDispatches",
    "shard_map-partitioned collective dispatches executed by the "
    "sharded tier with serene_shard_combine=device: each fused "
    "join/aggregate (psum/pmin/pmax cross-shard reduction) or search "
    "top-k merge (per-shard sort + all_gather) over the mesh data axis "
    "counts once — the single dispatch that replaces build+N probe "
    "dispatches plus the host-side numpy combine")
COLLECTIVE_COMBINE_NS = REGISTRY.gauge(
    "CollectiveCombineNs",
    "cumulative ns spent inside collective shard-combine dispatches "
    "(the in-program psum/pmin/pmax/all_gather sections, wall time of "
    "the whole one-dispatch program)")
POOL_QUEUE_DEPTH = REGISTRY.gauge(
    "PoolQueueDepth",
    "tasks currently queued in the worker pool (submitted, not yet "
    "picked up) — the live backpressure signal admission control reads")
POOL_RUNNING = REGISTRY.gauge(
    "PoolRunningTasks",
    "tasks currently executing on worker-pool threads")
POOL_TASK_WAIT_NS = REGISTRY.gauge(
    "PoolTaskWaitNs",
    "cumulative ns tasks spent queued before a worker picked them up "
    "(the ns-precision sibling of PoolQueueWaitUs)")
ADMISSION_QUEUED = REGISTRY.gauge(
    "AdmissionQueued",
    "statements that had to WAIT in the admission queue before "
    "executing (cumulative; sched/governor.py)")
ADMISSION_REJECTED = REGISTRY.gauge(
    "AdmissionRejected",
    "statements rejected with SQLSTATE 53300 because the admission "
    "queue was already serene_admission_queue_depth deep")
ADMISSION_WAIT_NS = REGISTRY.gauge(
    "AdmissionWaitNs",
    "cumulative ns statements spent queued for admission before "
    "starting (the statement-level sibling of PoolTaskWaitNs)")
ADMISSION_QUEUE_DEPTH = REGISTRY.gauge(
    "AdmissionQueueDepth",
    "statements currently waiting in the admission queue (live)")
CONNECTIONS_OPEN = REGISTRY.gauge(
    "ConnectionsOpen",
    "sockets currently open on the serving front door, both protocols "
    "(sched/governor.py ConnectionGate; server/frontdoor.py)")
CONNECTIONS_IDLE = REGISTRY.gauge(
    "ConnectionsIdle",
    "front-door connections waiting for the client's next request / "
    "command (live)")
CONNECTIONS_ACTIVE = REGISTRY.gauge(
    "ConnectionsActive",
    "front-door connections with a request or handshake in flight "
    "(live)")
CONNECTIONS_REJECTED = REGISTRY.gauge(
    "ConnectionsRejected",
    "connections rejected at the accept gate because "
    "serene_max_connections sockets were already open (cumulative; "
    "pgwire clients get a clean 53300 error packet, HTTP clients a "
    "429, both before a single byte of the session is parsed)")
SOCKET_BYTES_BUFFERED = REGISTRY.gauge(
    "SocketBytesBuffered",
    "bytes sitting in front-door transport write buffers (slow "
    "readers), sampled at scrape time; bounded per connection by "
    "serene_conn_write_high_kb + pause_reading")
SCHED_PREEMPTIONS = REGISTRY.gauge(
    "SchedPreemptions",
    "fair-share pool picks that ran a later-submitted statement's task "
    "ahead of the FIFO-oldest queued task (each one is an interleave "
    "plain FIFO would not have done; serene_fair_share)")
TRACES_RECORDED = REGISTRY.gauge(
    "TracesRecorded",
    "query timelines finalized into the flight recorder since start")
TRACE_SPANS_DROPPED = REGISTRY.gauge(
    "TraceSpansDropped",
    "span events dropped because a per-thread trace ring hit its cap "
    "(the timeline stays bounded; widest spans are still present)")
MEM_ACCOUNT_EVENTS = REGISTRY.gauge(
    "MemAccountEvents",
    "charge/release events recorded by per-query memory accounting "
    "(serene_mem_account) — the direct-decomposition input for the "
    "mem_overhead bench shape")
PROCESS_RSS_BYTES = REGISTRY.gauge(
    "ProcessRssBytes",
    "resident set size of this process (/proc/self/statm), sampled at "
    "scrape time and by the maintenance ticker")
PROCESS_UPTIME_SECONDS = REGISTRY.gauge(
    "ProcessUptimeSeconds",
    "seconds since this process initialized the metrics registry")
GC_GEN0_COLLECTIONS = REGISTRY.gauge(
    "GcGen0Collections", "CPython gc generation-0 collections")
GC_GEN1_COLLECTIONS = REGISTRY.gauge(
    "GcGen1Collections", "CPython gc generation-1 collections")
GC_GEN2_COLLECTIONS = REGISTRY.gauge(
    "GcGen2Collections", "CPython gc generation-2 collections")

#: latency histograms (log-spaced buckets; Prometheus histogram series
#: in /metrics, p50/p95/p99 in /_stats). Observed at statement / task /
#: dispatch boundaries only.
QUERY_LATENCY_HIST = REGISTRY.histogram(
    "QueryLatency",
    "end-to-end statement latency (success paths)")
POOL_QUEUE_WAIT_HIST = REGISTRY.histogram(
    "PoolQueueWait",
    "per-task worker-pool queue wait (submit -> pickup)")
ACCEPT_QUEUE_WAIT_HIST = REGISTRY.histogram(
    "AcceptQueueWait",
    "per-connection wait between the OS handing the front door a "
    "socket and the session coroutine starting to serve it (event-loop "
    "accept backlog; server/frontdoor.py)")
SEARCH_BATCH_WINDOW_HIST = REGISTRY.histogram(
    "SearchBatchWindow",
    "per-query search-batcher coalescing wait (submit -> dispatch "
    "start)")
DEVICE_DISPATCH_HIST = REGISTRY.histogram(
    "DeviceDispatch",
    "per-offload device execution time: the fused pipeline observes "
    "the dispatch section (post-upload; first call includes jit "
    "compile), device aggregates and top-N observe the whole offload "
    "(upload + compile-cache lookup + dispatch + readback)")
DEVICE_COMPILE_HIST = REGISTRY.histogram(
    "DeviceCompile",
    "first-dispatch latency of each jitted device program (XLA "
    "trace + compile + the first execution — the compile-stall a "
    "cold query pays; warm dispatches land in DeviceDispatch)")
WAL_FSYNC_HIST = REGISTRY.histogram(
    "WalFsync",
    "WAL group-commit flush+fsync latency (one observation per fsync, "
    "however many commit frames it covered)")
QUERY_PEAK_BYTES_HIST = REGISTRY.histogram(
    "QueryPeakBytes",
    "per-statement accounted peak memory (serene_mem_account): the "
    "sum of per-thread peak live bytes charged at materialization "
    "sites — an upper bound on the statement's true simultaneous peak",
    unit="bytes")
