"""Cross-session command progress registry.

Reference analog: server/pg/progress_registry.h:40-56 — atomics per phase
powering the pg_stat_progress_* views (CopyFrom/CopyTo/CreateIndex/CTAS/
Analyze/Vacuum commands).
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager


class ProgressRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._active: dict[int, dict] = {}
        self._next = 1

    @contextmanager
    def track(self, command: str, total: int = 0):
        with self._lock:
            pid = self._next
            self._next += 1
            rec = {"pid": pid, "command": command, "phase": "running",
                   "done": 0, "total": total}
            self._active[pid] = rec
        try:
            yield rec
        finally:
            with self._lock:
                self._active.pop(pid, None)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._active.values()]


REGISTRY = ProgressRegistry()
