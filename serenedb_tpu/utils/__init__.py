from . import config, faults, log, metrics, ticks

__all__ = ["config", "faults", "log", "metrics", "ticks"]
