"""Three-tier config system.

Reference analog (SURVEY.md §5.6): (1) process flags, (2) SQL-settable
session/global settings (`SET name = value` / `sdb_settings` introspection;
reference: server/query/config_variables.cpp), (3) per-object WITH options
(carried in the catalog, not here).

Settings are declared once in a registry with type/default/scope; sessions
hold sparse overrides over the global store.
"""

from __future__ import annotations

import enum
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


class Scope(enum.Enum):
    SESSION = "session"   # settable per session (and globally as default)
    GLOBAL = "global"     # process-wide only


#: binary factors for PG-style memory-size literals ('64MB', '512kB');
#: PG's guc memory units are binary too (1MB = 1024kB)
_MEM_UNIT_FACTORS = {"b": 1, "kb": 1 << 10, "mb": 1 << 20,
                     "gb": 1 << 30, "tb": 1 << 40}
_MEM_RE = re.compile(r"^\s*(\d+)\s*([a-zA-Z]*)\s*$")


def parse_memory_bytes(value: Any) -> int:
    """PG-style memory-size parsing for byte-denominated settings
    (`SET serene_work_mem = '64MB'`): a plain integer is BYTES (every
    number the accounting layer reports is bytes, so the two compare
    without a unit hop), a string may carry a B/kB/MB/GB/TB suffix
    with binary factors. Rejects negatives (the regex) and unknown
    units loudly."""
    if isinstance(value, bool):
        raise ValueError(f"invalid memory value: {value!r}")
    if isinstance(value, (int, float)):
        return int(value)
    m = _MEM_RE.match(str(value))
    if not m:
        raise ValueError(f"invalid memory value: {value!r}")
    n, unit = m.groups()
    if not unit:
        return int(n)
    factor = _MEM_UNIT_FACTORS.get(unit.lower())
    if factor is None:
        raise ValueError(
            f"invalid memory unit in {value!r} (use B, kB, MB, GB or TB)")
    return int(n) * factor


@dataclass
class Setting:
    name: str
    default: Any
    type: type
    scope: Scope = Scope.SESSION
    description: str = ""
    validator: Optional[Callable[[Any], Any]] = None
    #: byte-denominated setting: coerce accepts PG-style unit strings
    #: ('64MB') as well as plain integers (bytes)
    memory: bool = False

    def coerce(self, value: Any) -> Any:
        if self.memory:
            value = parse_memory_bytes(value)
        elif self.type is bool and isinstance(value, str):
            v = value.strip().lower()
            if v in ("on", "true", "1", "yes"):
                value = True
            elif v in ("off", "false", "0", "no"):
                value = False
            else:
                raise ValueError(f"invalid boolean: {value!r}")
        else:
            value = self.type(value)
        if self.validator:
            value = self.validator(value)
        return value


class SettingsRegistry:
    def __init__(self):
        self._defs: dict[str, Setting] = {}
        self._global: dict[str, Any] = {}
        self._lock = threading.Lock()

    def register(self, s: Setting) -> Setting:
        self._defs[s.name] = s
        return s

    def definition(self, name: str) -> Setting:
        s = self._defs.get(name.lower())
        if s is None:
            raise KeyError(f'unrecognized configuration parameter "{name}"')
        return s

    def names(self) -> list[str]:
        return sorted(self._defs)

    def set_global(self, name: str, value: Any) -> None:
        s = self.definition(name)
        with self._lock:
            self._global[s.name] = s.coerce(value)

    def get_global(self, name: str) -> Any:
        s = self.definition(name)
        with self._lock:
            return self._global.get(s.name, s.default)


REGISTRY = SettingsRegistry()


def declare(name: str, default: Any, typ: type, description: str = "",
            scope: Scope = Scope.SESSION,
            validator: Optional[Callable] = None,
            memory: bool = False) -> Setting:
    return REGISTRY.register(
        Setting(name.lower(), default, typ, scope, description, validator,
                memory))


class SessionSettings:
    """Per-session sparse overrides over the global registry."""

    def __init__(self, registry: SettingsRegistry = REGISTRY):
        self._registry = registry
        self._local: dict[str, Any] = {}

    def get(self, name: str) -> Any:
        s = self._registry.definition(name)
        if s.name in self._local:
            return self._local[s.name]
        return self._registry.get_global(s.name)

    def set(self, name: str, value: Any) -> None:
        s = self._registry.definition(name)
        if s.scope is Scope.GLOBAL:
            raise ValueError(f'parameter "{name}" cannot be changed per session')
        self._local[s.name] = s.coerce(value)

    def reset(self, name: str) -> None:
        s = self._registry.definition(name)
        self._local.pop(s.name, None)

    def snapshot(self) -> dict[str, Any]:
        return {n: self.get(n) for n in self._registry.names()}


# -- core settings (mirroring the reference's knob names where they exist) --

declare("application_name", "", str, "client-supplied application name")
declare("extra_float_digits", 1, int, "float output precision adjustment")
declare("statement_timeout", 0, int, "ms; 0 disables")
declare("search_path", "main", str, "schema search path")
declare("sdb_faults", "", str, "comma list of armed fault points (+name/-name)")
declare("sdb_nprobe", 8, int, "IVF probes per vector query")
declare("sdb_rerank_factor", 4, int, "ANN rerank multiplier")
declare("sdb_scored_terms_limit", 128, int,
        "max scored terms for multi-term expansion (wildcard/fuzzy)")
declare("sdb_strict_ddl", False, bool, "reject unknown WITH options")
declare("serene_device", "auto", str,
        "compute device policy: auto|tpu|cpu (auto: TPU when available "
        "and batch is large enough)")
declare("serene_device_min_rows", 16384, int,
        "below this row count the CPU path is used even when device=auto")
declare("serene_device_chunk_rows", 1 << 21, int,
        "device aggregate dispatches split into row chunks of this size "
        "so cancel/statement_timeout fire between chunks (~one chunk's "
        "latency); 0 disables chunking")
declare("serene_device_fused", True, bool,
        "fused device relational pipelines (exec/device_pipeline.py): "
        "Scan→Filter→Join→Aggregate chains and filtered top-N compile "
        "into ONE jitted device program over publication-cached HBM "
        "columns instead of one host kernel per operator; anything the "
        "fused compiler can't prove exact falls back to the host path, "
        "which stays on as the bit-identical parity oracle — results "
        "are identical on or off at any serene_workers setting")
declare("serene_device_fused_ext", True, bool,
        "extended fused-tier admission (PR 17): string aggregates via "
        "dictionary codes, FILTER aggregates as extra scatter masks, "
        "DISTINCT aggregates as presence grids, side-decomposable "
        "residual join predicates, LEFT/RIGHT/FULL outer joins, and "
        "the chained fused-aggregate→top-N device handoff. Off "
        "restores the PR 7 admission walls (those shapes decline to "
        "the host path) — the before/after lever of the "
        "fused_admission bench shape; results are bit-identical on or "
        "off because the host path is the oracle for every shape")
declare("serene_device_cache_trade", True, bool,
        "pressure-based budget trade between the device column cache "
        "(§19) and the posting pool (§27) inside the one "
        "serene_device_cache_mb envelope: the column cache's byte cap "
        "is the envelope minus the pool's LIVE page bytes (floored at "
        "a quarter of the envelope), so pool residency squeezes the "
        "cache instead of a static carve-out; and when the cache must "
        "evict, it first sheds the POOL's tail if that tail is colder "
        "(idle longer), which raises its own cap back. Off restores "
        "the static carve-out (serene_posting_pages bounds the pool; "
        "the column cache ignores pool occupancy)",
        scope=Scope.GLOBAL)
declare("serene_device_cache_mb", 256, int,
        "byte cap (MB) of the process-wide device column cache "
        "(exec/device_pipeline.DEVICE_CACHE): device-resident column "
        "tiles and join-code uploads keyed by publication tuples, so "
        "repeat queries over unchanged tables skip host→device "
        "transfer entirely; least-recently-used entries evict past the "
        "cap and superseded generations are swept eagerly on store",
        scope=Scope.GLOBAL, validator=lambda v: max(1, int(v)))
declare("serene_posting_pool", True, bool,
        "device-resident paged posting pool (search/posting_pool.py): "
        "the batched ragged search path uploads each (segment, term) "
        "posting list ONCE into a paged HBM region and scores "
        "page-resident coalesced batches as one jitted gather-and-"
        "accumulate program over page tables — zero host→device "
        "posting bytes on the warm path. Misses fall back per query to "
        "the host ragged path and partial residency merges host tails "
        "deterministically, so results are BIT-IDENTICAL on or off at "
        "any worker/shard/cache setting (off = the parity oracle) and "
        "the setting stays out of the result cache's settings digest",
        scope=Scope.GLOBAL)
declare("serene_posting_pages", 4096, int,
        "page budget of the posting pool's device region (pages of "
        "1024 postings; docs+tfs = 8 KiB/page, so the default 4096 is "
        "32 MiB of HBM). The region never exceeds the "
        "serene_device_cache_mb byte cap — the pool is carved out of "
        "the device-cache budget, not added to it. Least-recently-used "
        "terms evict past the budget; size from sdb_posting_pool() "
        "occupancy/hit rows",
        scope=Scope.GLOBAL, validator=lambda v: max(8, int(v)))
declare("serene_vector_pool", True, bool,
        "device-resident paged vector pool (search/vector_store.py): "
        "IVF and MaxSim indexes upload their cluster-major vector "
        "segments ONCE into a paged HBM region (16 KiB pages, LRU by "
        "segment) and warm coalesced knn batches run as ONE jitted "
        "centroid-probe → slotmap-gather → exact-rescore → top-k "
        "program with zero host→device vector bytes. Off (or under "
        "page starvation) every dispatch falls back to a per-call "
        "committed cold region running the SAME program, so results "
        "are bit-identical on or off and the setting stays out of the "
        "result cache's settings digest",
        scope=Scope.GLOBAL)
declare("serene_vector_pages", 4096, int,
        "page budget of the vector pool's device region (pages of "
        "4096 f32 = 16 KiB, so the default 4096 is 64 MiB of HBM). "
        "The region never exceeds the serene_device_cache_mb byte cap "
        "— the pool is carved out of the device-cache budget, not "
        "added to it. Whole segments evict LRU past the budget; size "
        "from sdb_vector_pool() residency/hit rows",
        scope=Scope.GLOBAL, validator=lambda v: max(4, int(v)))
declare("serene_nprobe", 0, int,
        "IVF clusters probed per vector query; 0 defers to the "
        "compat alias sdb_nprobe. More probes = higher recall and "
        "more work (nprobe = lists is exact brute force, the parity "
        "oracle). RESULT-AFFECTING: changes which rows a knn returns, "
        "so it is part of the result cache's settings digest",
        validator=lambda v: max(0, int(v)))
declare("serene_maxsim", True, bool,
        "serve vec_maxsim() late-interaction scoring on the device "
        "(dimension-tiled token-matrix MaxSim over the vector pool); "
        "off = exact float64 host oracle. RESULT-AFFECTING: device "
        "scores are f32, the host oracle is f64, so near-tied docs "
        "can order differently — part of the settings digest")
declare("serene_device_telemetry", True, bool,
        "device telemetry (obs/device.py): the XLA compile ledger "
        "(per-program-family compile counts/wall time, program-cache "
        "hit/miss gauges, recompile-storm warnings), host<->device "
        "transfer byte/time accounting and per-device dispatch counts "
        "+ HBM occupancy estimates, surfaced via sdb_device()/"
        "sdb_programs()/sdb_device_cache(), GET /device, /_stats and "
        "/metrics, plus device_compile trace spans and the EXPLAIN "
        "ANALYZE Device: compile=hit|miss key. Observation only: "
        "telemetry never changes which program runs — results are "
        "bit-identical on or off at any worker/shard/combine setting "
        "(<3% overhead budget, device_observe bench shape)",
        scope=Scope.GLOBAL)
declare("serene_program_cache_entries", 256, int,
        "entry cap of the process-wide compiled-program LRU "
        "(obs/device.py PROGRAMS — the _PROGRAM_CACHE successor): "
        "every jitted device program (fused pipelines, device "
        "aggregates/top-N, mesh/search programs) lives here keyed by "
        "(family, shape); least-recently-used executables evict past "
        "the cap instead of leaking one per novel query shape for "
        "process lifetime, and an evicted shape simply re-compiles on "
        "next use", scope=Scope.GLOBAL,
        validator=lambda v: max(1, int(v)))
declare("serene_mesh", 0, int,
        "shard device programs across an N-device jax mesh (0 = single "
        "device); grouped aggregates and BM25 top-k run as shard_map "
        "programs with psum/pmin/pmax merges over ICI")


def _cpu_count() -> int:
    import os
    return os.cpu_count() or 1


declare("serene_workers", _cpu_count(), int,
        "host worker-pool parallelism for morsel-driven execution "
        "(scans/aggregates, segment search, ingest parsing); the process "
        "pool is sized from the global value, sessions cap their own "
        "queries with SET serene_workers; 1 disables parallel scheduling "
        "(the same morsel plan runs inline — results are identical)",
        validator=lambda v: max(1, int(v)))
declare("serene_morsel_rows", 1 << 19, int,
        "rows per morsel for parallel host pipelines; the split is "
        "fixed-size and independent of worker count so partial-merge "
        "order (and thus every result bit) never depends on scheduling; "
        "large morsels amortize python dispatch overhead per task",
        validator=lambda v: max(1024, int(v)))
declare("serene_parallel_min_rows", 1 << 16, int,
        "below this input row count host pipelines stay single-threaded "
        "(morsel setup costs more than it buys)")
declare("serene_zonemap", True, bool,
        "zone maps: per-morsel block min/max/null statistics consulted "
        "before scanning — filter conjuncts that provably match no row "
        "of a block skip it entirely, conjuncts that provably match "
        "every row skip predicate evaluation, and the device paths "
        "shrink uploads to the surviving block range; off scans "
        "everything (results are identical either way)")
declare("serene_join_vectorized", True, bool,
        "vectorized relational tier: hash joins, set operations and "
        "DISTINCT ON run over dense int64 key codes with numpy array "
        "kernels (build-side offset index + morsel-parallel probe "
        "expansion on the shared worker pool); off interprets the same "
        "operators row-tuple-at-a-time in python (the parity oracle) — "
        "results are bit-identical either way")
declare("serene_join_filter", True, bool,
        "min/max sideways-information-passing join filter: after the "
        "build side of an inner/right hash join materializes, its key "
        "range is published to the zone-map analyzer so probe-side scan "
        "morsels whose block statistics prove no key can match are "
        "never enqueued; requires serene_zonemap, results are "
        "identical on or off")
declare("serene_profile", True, bool,
        "per-operator query profiling (obs/trace.py): every statement "
        "collects rows/time/morsel-prune spans per plan operator, feeds "
        "sdb_stat_statements, the slow-query log and pg_stat_activity "
        "query ids; results are bit-identical on or off (<3% overhead "
        "budget, profile_overhead bench shape)")
declare("serene_trace", True, bool,
        "query timeline tracing (obs/trace.py): every statement gets a "
        "trace id and timestamped span events — worker-pool queue waits, "
        "morsel pipeline fan-out, search-batcher coalescing windows, "
        "per-shard pipelines and device factorize/upload/dispatch "
        "phases — recorded into lock-free per-thread rings, finalized "
        "into the flight recorder ring, and served as Chrome "
        "trace-event JSON via sdb_trace(id) and GET /trace/<id>. "
        "Observation only: results are bit-identical on or off at any "
        "worker/shard count (<3% overhead budget, trace_overhead bench "
        "shape)")
declare("serene_mem_account", True, bool,
        "per-query resource accounting (obs/resources.py): every "
        "statement charges live/peak bytes at its materialization "
        "sites (operator batches, join build sides, sort buffers, "
        "morsel partials, device uploads, cache stores), feeds "
        "per-operator Memory lines in EXPLAIN ANALYZE, peak_mem "
        "columns in sdb_stat_statements, the QueryPeakBytes histogram, "
        "and registers live progress rows for sdb_query_progress() / "
        "GET /progress. Observation only: results are bit-identical "
        "on or off at any worker/shard count (<3% overhead budget, "
        "mem_overhead bench shape) — the prerequisite the "
        "admission-control / serene_work_mem roadmap item builds on")
declare("serene_flight_recorder_queries", 64, int,
        "size of the always-on flight recorder: the last N completed "
        "query timelines are kept in a bounded ring so the slow-query "
        "log and error paths can dump a stall's timeline after the "
        "fact; oldest entries evict past the cap",
        scope=Scope.GLOBAL, validator=lambda v: max(1, int(v)))
declare("serene_log_min_duration_ms", -1, int,
        "log statements running at least this many ms to the "
        "slow_query topic (profiled plan tree included when available); "
        "0 logs everything, -1 disables (PG log_min_duration_statement); "
        "requires serene_profile = on, like all of the obs subsystem")
declare("serene_stat_statements_max", 1000, int,
        "cap on distinct normalized statements tracked by "
        "sdb_stat_statements; least-recently-executed entries evict "
        "past the cap", scope=Scope.GLOBAL,
        validator=lambda v: max(1, int(v)))
declare("serene_result_cache", True, bool,
        "multi-tier query cache (cache/): tier 1 memoizes whole results "
        "of read-only statements whose plans touch only immutable "
        "expressions and catalog tables, keyed by (statement digest, "
        "parameter values, result-affecting settings digest, per-table "
        "publication tuples) — any write bumps a publication tuple, so "
        "a stale entry can never be returned; tier 2 caches per-segment "
        "search filter/top-k fragments (segments are immutable). "
        "Results are bit-identical on or off at any worker count; off "
        "disables both lookups and stores for this session")
declare("serene_result_cache_mb", 64, int,
        "byte cap (MB) of the process-wide result cache; entries evict "
        "least-recently-used past the cap and a single result larger "
        "than the cap is never stored", scope=Scope.GLOBAL,
        validator=lambda v: max(1, int(v)))
declare("serene_fragment_cache_mb", 32, int,
        "byte cap (MB) of the process-wide search fragment cache "
        "(per-segment filter doc sets and top-k collector outputs)",
        scope=Scope.GLOBAL, validator=lambda v: max(1, int(v)))
declare("serene_search_batch", True, bool,
        "batched ragged search serving (search/batcher.py): concurrent "
        "_search/@@@ top-k queries against the same index coalesce into "
        "ONE vectorized scoring dispatch over the shared postings, with "
        "ragged per-query term lists and per-query WAND thresholds "
        "preserved; per-query results are bit-identical to serial "
        "dispatch (scores, doc ids, tie order), so this setting is "
        "deliberately excluded from the result cache's settings digest; "
        "off dispatches every query alone (the parity oracle). A lone "
        "query never waits: coalescing only engages while other searches "
        "of the same (index, k, scorer) group are in flight")
declare("serene_search_batch_window_ms", 2.0, float,
        "upper bound (ms) a query waits to coalesce with concurrent "
        "arrivals when its group has other active-but-unqueued "
        "submitters; while a dispatch is in flight arrivals simply queue "
        "behind it (the dispatch IS the window under sustained load) and "
        "a query alone in its group dispatches immediately",
        scope=Scope.GLOBAL, validator=lambda v: max(0.0, float(v)))
declare("serene_search_batch_max", 128, int,
        "cap on queries per coalesced search scoring dispatch; overflow "
        "queries form the next dispatch", scope=Scope.GLOBAL,
        validator=lambda v: max(1, int(v)))
declare("serene_shards", 1, int,
        "sharded execution tier (exec/shard.py): table scans partition "
        "into N shards by round-robin morsel-block assignment and the "
        "morsel/fused pipelines run once per shard — as concurrent "
        "worker-pool tasks, with per-shard device programs pinned "
        "across jax.devices() when a multi-device mesh is present — "
        "while the deterministic merge sinks (ordered partial merge, "
        "single-heap top-k, partial-aggregate combine) act as the "
        "cross-shard combiners; the build side of a hash join publishes "
        "PER-SHARD key min/max so probe blocks outside every shard's "
        "range are pruned before any scan or device upload. Results are "
        "bit-identical at any shard count (1 = today's unsharded "
        "execution, the parity oracle), so this setting is deliberately "
        "excluded from the result cache's settings digest",
        validator=lambda v: max(1, int(v)))
def _validate_shard_combine(v):
    v = str(v).strip().lower()
    if v not in ("auto", "device", "host"):
        raise ValueError(
            f"invalid serene_shard_combine: {v!r} (auto|device|host)")
    return v


declare("serene_shard_combine", "auto", str,
        "where the sharded tier's cross-shard combine runs when "
        "serene_shards > 1: 'device' executes the fused join/aggregate "
        "as ONE shard_map-partitioned program over the mesh data axis "
        "with psum/pmin/pmax collectives reducing the integer "
        "accumulators in HBM (and merges sharded search top-k with an "
        "in-program per-shard sort + one all_gather hop); 'host' keeps "
        "the per-shard dispatches with the exact host-side integer "
        "combine (the PR 9 oracle); 'auto' resolves to device when the "
        "process sees more than one jax device, else host. Every "
        "accumulator is an integer add or a min/max selection, so the "
        "combine is exact in any reduction order and results are "
        "BIT-identical across all three values — this setting is "
        "deliberately excluded from the result cache's settings digest",
        validator=_validate_shard_combine)
# -- workload governor (sched/governor.py) ----------------------------------

declare("serene_max_concurrent_statements", 0, int,
        "admission control (sched/governor.py): max statements EXECUTING "
        "process-wide; further statements wait in a bounded FIFO "
        "admission queue (pg_stat_activity state 'queued', wait event "
        "Admission/AdmissionQueue, queue time as a queue_wait trace "
        "span) until a running statement finishes. 0 disables admission "
        "entirely. Utility statements (SET/SHOW/txn control) and "
        "catalog-only introspection reads (pg_*/sdb_*/"
        "information_schema) are exempt, so an overloaded server can "
        "still be diagnosed. Scheduling only — results are bit-identical "
        "at any limit", scope=Scope.GLOBAL,
        validator=lambda v: max(0, int(v)))
declare("serene_admission_queue_depth", 64, int,
        "bound on the admission queue: statements arriving when "
        "serene_max_concurrent_statements are running AND this many are "
        "already queued are rejected immediately with SQLSTATE 53300 "
        "(backpressure instead of an unbounded convoy)",
        scope=Scope.GLOBAL, validator=lambda v: max(1, int(v)))
declare("serene_max_connections", 0, int,
        "socket-level admission (sched/governor.py ConnectionGate): max "
        "sockets open across BOTH front-door protocols; a connection "
        "past the limit is rejected at accept — pgwire clients get a "
        "clean 53300 error packet, HTTP clients a 429 with Retry-After "
        "— before a single byte of the session is parsed, so overload "
        "never reaches the engine. 0 = unlimited. The statement-level "
        "sibling is serene_max_concurrent_statements",
        scope=Scope.GLOBAL, validator=lambda v: max(0, int(v)))
declare("serene_frontdoor", True, bool,
        "serve HTTP/ES on the unified asyncio front door "
        "(server/frontdoor.py: one event loop for both protocols, "
        "connections as tasks not threads, socket-level admission, "
        "pause-reading backpressure, idle reaping). off = the legacy "
        "thread-per-connection ThreadingHTTPServer, kept one release "
        "as the bit-identity parity oracle (both paths share the same "
        "request->response route table)", scope=Scope.GLOBAL)
declare("serene_idle_conn_timeout_s", 0.0, float,
        "reap front-door connections (both protocols) that have sent "
        "no bytes for this many seconds — half-open clients and "
        "abandoned keep-alive sessions release their socket (and "
        "serene_max_connections slot) instead of holding it forever. "
        "0 disables. Applies while a connection is idle or mid-"
        "handshake, never to a statement in flight",
        scope=Scope.GLOBAL, validator=lambda v: max(0.0, float(v)))
declare("serene_conn_write_high_kb", 256, int,
        "per-connection transport write-buffer high-water mark in KiB "
        "(server/frontdoor.py): past it the session stops reading "
        "(transport.pause_reading) and stops producing until the "
        "client drains below the low-water mark, so a stalled reader "
        "never buffers unbounded result bytes",
        scope=Scope.GLOBAL, validator=lambda v: max(16, int(v)))
declare("serene_fair_share", True, bool,
        "fair-share morsel scheduling (parallel/pool.py): the shared "
        "worker pool picks queued tasks by per-statement stride "
        "scheduling (weights from serene_priority) instead of global "
        "FIFO, so a heavy scan's morsels INTERLEAVE with, rather than "
        "run entirely before, every later statement's — a dashboard "
        "query's tasks wait ~one morsel, not the heavy query's whole "
        "backlog. Scheduling only: the deterministic merge sinks make "
        "results bit-identical with it on or off (ARCHITECTURE.md §25)",
        scope=Scope.GLOBAL)
declare("serene_priority", 100, int,
        "this session's fair-share weight (1..10000, default 100): a "
        "statement with weight 2w is picked twice as often as one with "
        "weight w while both have queued tasks (stride scheduling, "
        "higher = more worker-pool share); has no effect on results, "
        "only on scheduling order",
        validator=lambda v: min(10000, max(1, int(v))))
declare("serene_work_mem", 0, int,
        "per-statement memory ceiling in BYTES (PG-style unit strings "
        "accepted: '64MB', '1GB'); when the statement's accounted live "
        "bytes (serene_mem_account, obs/resources.py) exceed it, the "
        "statement aborts with SQLSTATE 53200 at the next cooperative "
        "cancellation point — the same drain cancel and "
        "statement_timeout use, so no partial state survives. 0 "
        "disables; enforcement requires serene_mem_account = on",
        memory=True, validator=lambda v: max(0, int(v)))
declare("serene_statement_timeout_ms", 0, int,
        "engine-level statement timeout (ms; 0 disables): combines with "
        "the PG-compatible statement_timeout setting (the LOWER positive "
        "value wins) and fires through the same cooperative cancellation "
        "drain (SQLSTATE 57014), including while a statement is QUEUED "
        "for admission", validator=lambda v: max(0, int(v)))
# -- streaming ingest (write path) ------------------------------------------

declare("serene_parallel_ingest", True, bool,
        "parallel write-path analysis: segment builds chunk-split their "
        "document batches across the shared worker pool (per-chunk "
        "tokenization + postings build, merged with a deterministic "
        "base-row-ordered concat) and parquet column decoding builds "
        "columns concurrently; the merged segment is BIT-IDENTICAL to "
        "the serial build — postings order, norms, WAND block metadata "
        "and every score — so this setting stays out of the result "
        "cache's settings digest; off runs the serial single-pass "
        "builder (the parity oracle)")
declare("serene_ingest_chunk_docs", 4096, int,
        "documents per analysis chunk for parallel segment builds; a "
        "corpus smaller than two chunks builds serially (chunk setup "
        "costs more than it buys). The chunk split is fixed-size and "
        "independent of worker count, so the merged postings are "
        "identical at any parallelism", validator=lambda v: max(64, int(v)))
declare("serene_group_commit", True, bool,
        "ingest-side group-commit windows: the WAL leader re-drains the "
        "commit queue for late arrivals before its single fsync, and "
        "concurrent fast-path INSERTs of one table coalesce their "
        "in-memory publications into ONE batch concat + version bump "
        "per window (per-table cache invalidation per WINDOW, not per "
        "statement). Durability and replay order are unchanged — every "
        "frame is fsynced before its statement returns, publishes stay "
        "sequenced by WAL tick — so results are bit-identical on or "
        "off; off restores one publish per statement (the parity "
        "oracle)", scope=Scope.GLOBAL)
declare("serene_background_merge", True, bool,
        "background segment maintenance: query-path read-repair of a "
        "stale inverted index only builds the bounded delta tail (the "
        "rows appended since the last refresh) and never pays "
        "compaction; the maintenance ticker — woken by appends — runs "
        "the tiered merge ladder off the query path, publishing via "
        "the same build-new-then-swap snapshot. Scores use global "
        "collection stats, so results are bit-identical at ANY segment "
        "layout; off restores foreground compaction at the segment cap "
        "(the parity oracle)", scope=Scope.GLOBAL)
declare("serene_max_segments", 8, int,
        "per-field segment-count threshold of the tiered merge ladder: "
        "at or above it, maintenance (or foreground refresh with "
        "serene_background_merge off) merges the smallest adjacent run "
        "of segments — O(run docs), not a full rebuild — until back "
        "under the cap. Lower values merge more eagerly",
        scope=Scope.GLOBAL, validator=lambda v: max(2, int(v)))
declare("serene_zonemap_verify", False, bool,
        "debug assert mode: re-scan every zone-map-pruned block with "
        "the real predicate and fail the query loudly if any row "
        "matched (catches block-statistics/data divergence "
        "structurally; the tier-1 verify script arms this once)")
