"""Server-wide monotonic tick counter.

Reference analog: server/database/ticks.h:28-33 — ticks order catalog and WAL
operations; commit ticks are handed out strictly in WAL-append order
(reference invariant: server/query/transaction.h:61-70).
"""

from __future__ import annotations

import threading


class TickServer:
    def __init__(self, start: int = 0):
        self._tick = start
        self._lock = threading.Lock()

    def next(self, n: int = 1) -> int:
        """Reserve a band of n ticks; returns the first."""
        with self._lock:
            first = self._tick + 1
            self._tick += n
            return first

    def current(self) -> int:
        with self._lock:
            return self._tick

    def advance_to(self, tick: int) -> None:
        """Recovery: fast-forward past replayed ticks."""
        with self._lock:
            self._tick = max(self._tick, tick)
