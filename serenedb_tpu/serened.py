"""serened — the server process entry point.

Reference analog: server/rest_server/serened.cpp (flag parsing, engine boot,
listener bring-up, signal-driven shutdown with ordered teardown;
SURVEY.md §3.1).

    python -m serenedb_tpu.serened <datadir> \
        --pg-port 5432 --http-port 9200 [--password secret]
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys

from .engine import Database
from .server.http_server import HttpServer
from .server.pgwire import PgServer
from .utils import log


def main(argv=None):
    ap = argparse.ArgumentParser(prog="serened")
    ap.add_argument("datadir", nargs="?", default=None,
                    help="data directory (omit for in-memory)")
    ap.add_argument("--pg-port", type=int, default=5432)
    ap.add_argument("--http-port", type=int, default=9200)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--password", default=None)
    ap.add_argument("--log-level", default="INFO")
    ap.add_argument("--tls-cert", default=None,
                    help="PEM certificate chain; enables in-band TLS "
                         "upgrade on SSLRequest")
    ap.add_argument("--tls-key", default=None, help="PEM private key")
    ap.add_argument("--hba-config", default=None,
                    help="pg_hba.conf-style rules file")
    ap.add_argument("--proxy-protocol", default="off",
                    choices=["off", "optional", "require"],
                    help="HAProxy PROXY v1/v2 preface handling")
    ap.add_argument("--listen", action="append", default=[],
                    metavar="SPEC",
                    help="additional PG listener: tcp://HOST:PORT or "
                         "unix:///path.sock (repeatable; reference: "
                         "listen_spec.h multi-spec --listen)")
    ap.add_argument("--version", action="store_true",
                    help="print version/build id and exit")
    args = ap.parse_args(argv)
    if args.version:
        from . import build_id
        print(build_id())
        return
    from .server.listen import parse_listen_spec
    for spec in args.listen:
        try:
            parse_listen_spec(spec, default_host=args.host)
        except ValueError as e:
            ap.error(str(e))
    if bool(args.tls_cert) != bool(args.tls_key):
        ap.error("--tls-cert and --tls-key must be given together")

    # environment-driven configuration: any registered setting may be
    # seeded at boot via its SHOUTING name (SERENE_MAX_CONNECTIONS=100,
    # SERENE_DEVICE=cpu, ...) — the standard server-deployment surface
    # for GLOBAL-scope knobs, which have no SQL-level setter
    from .utils.config import REGISTRY as settings
    for name in settings.names():
        env_val = os.environ.get(name.upper())
        if env_val is not None:
            try:
                settings.set_global(name, env_val)
            except ValueError as e:
                ap.error(f"{name.upper()}: {e}")

    log.MANAGER.stdout = True
    db = Database(args.datadir)
    pg = PgServer(db, args.host, args.pg_port, args.password,
                  tls_cert=args.tls_cert, tls_key=args.tls_key,
                  hba_conf=args.hba_config,
                  proxy_protocol=args.proxy_protocol,
                  listen=args.listen)

    if bool(settings.get_global("serene_frontdoor")):
        # the front door: BOTH protocols on the process's one event
        # loop, pgwire's session pool shared as the HTTP engine-boundary
        # executor, one ordered drain on shutdown (server/frontdoor.py)
        from .server.frontdoor import FrontDoor
        front = FrontDoor(db, args.host, http_port=args.http_port, pg=pg)

        async def run():
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, stop.set)
            await front.start_async()
            print(f"serened ready: pg={pg.port} http={front.port}",
                  flush=True)
            await stop.wait()
            # teardown order mirrors the reference: listeners drain,
            # sessions reaped, then the store closes
            await front.stop_async()

        try:
            asyncio.run(run())
        finally:
            db.close()
            log.info("serened", "shutdown complete")
        return

    # legacy split lifecycle (serene_frontdoor = off, one release):
    # HTTP on its own thread-per-connection server, pg on the main loop
    http = HttpServer(db, args.host, args.http_port)
    http.start()

    async def run_legacy():
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await pg.start()
        print(f"serened ready: pg={pg.port} http={http.port}",
              flush=True)
        await stop.wait()
        await pg.stop()

    try:
        asyncio.run(run_legacy())
    finally:
        http.stop()
        db.close()
        log.info("serened", "shutdown complete")


if __name__ == "__main__":
    main(sys.argv[1:])
