"""System catalog tables (pg_catalog emulation, sdb introspection).

Reference analog: server/pg/pg_catalog/ (67 system tables materialized from
catalog snapshots; SURVEY.md §2.3) + sdb_catalog (sdb_metrics, sdb_settings,
sdb_log). Starts with the tables clients/tests actually touch; grows toward
the full surface with the catalog layer.
"""

from __future__ import annotations

from typing import Optional

from .columnar.column import Batch
from .exec.tables import MemTable, TableProvider
from .utils import log as _log
from .utils import metrics as _metrics
from .utils.config import REGISTRY as _settings_registry


def system_table(db, parts: list[str]) -> Optional[TableProvider]:
    name = parts[-1].lower()
    qualified = len(parts) >= 2 and parts[-2].lower() in ("pg_catalog",
                                                          "information_schema",
                                                          "sdb_catalog")
    if len(parts) >= 2 and not qualified:
        return None
    if name == "pg_tables":
        rows = db.table_list()
        return MemTable("pg_tables", Batch.from_pydict({
            "schemaname": [r[0] for r in rows if r[2] == "table"],
            "tablename": [r[1] for r in rows if r[2] == "table"],
            "tableowner": ["serene" for r in rows if r[2] == "table"],
        }))
    if name == "pg_views":
        rows = db.table_list()
        return MemTable("pg_views", Batch.from_pydict({
            "schemaname": [r[0] for r in rows if r[2] == "view"],
            "viewname": [r[1] for r in rows if r[2] == "view"],
        }))
    if name == "pg_stat_activity":
        from .sql.binder import format_timestamp
        with db.lock:
            sess = [dict(v) for v in db.sessions.values()]
        sess.sort(key=lambda v: v["pid"])

        def ts(v):
            return (format_timestamp(int(v * 1_000_000))
                    if v is not None else None)
        return MemTable("pg_stat_activity", Batch.from_pydict({
            "pid": [v["pid"] for v in sess],
            "usename": [v["usename"] for v in sess],
            "application_name": [v["application_name"] for v in sess],
            "state": [v["state"] for v in sess],
            "query": [v["query"] for v in sess],
            "backend_start": [ts(v["backend_start"]) for v in sess],
            "query_start": [ts(v["query_start"]) for v in sess],
        }))
    if name == "pg_namespace":
        names = sorted(db.schemas)
        return MemTable("pg_namespace", Batch.from_pydict({
            "oid": list(range(1, len(names) + 1)),
            "nspname": names,
        }))
    if name == "pg_class":
        rows = db.table_list()
        return MemTable("pg_class", Batch.from_pydict({
            "oid": list(range(1, len(rows) + 1)),
            "relname": [r[1] for r in rows],
            "relkind": ["r" if r[2] == "table" else "v" for r in rows],
        }))
    if name in ("pg_attribute", "columns"):
        # pg_attribute / information_schema.columns: one row per column
        rows_s, rows_t, rows_c, rows_ty, rows_pos, rows_null = \
            [], [], [], [], [], []
        with db.lock:
            for sname, s in db.schemas.items():
                for tname, t in s.tables.items():
                    nn = set(getattr(t, "table_meta", {}).get("not_null", []))
                    for pos, (cn, ct) in enumerate(
                            zip(t.column_names, t.column_types), 1):
                        rows_s.append(sname)
                        rows_t.append(tname)
                        rows_c.append(cn)
                        rows_ty.append(str(ct).lower())
                        rows_pos.append(pos)
                        rows_null.append("NO" if cn in nn else "YES")
        if name == "columns":
            return MemTable("columns", Batch.from_pydict({
                "table_schema": rows_s, "table_name": rows_t,
                "column_name": rows_c, "ordinal_position": rows_pos,
                "data_type": rows_ty, "is_nullable": rows_null}))
        return MemTable("pg_attribute", Batch.from_pydict({
            "attrelid": [hash((a, b)) % (1 << 30)
                         for a, b in zip(rows_s, rows_t)],
            "attname": rows_c, "attnum": rows_pos,
            "atttypid": [25] * len(rows_c)}))
    if name == "tables" and len(parts) >= 2 and \
            parts[-2].lower() == "information_schema":
        rows = db.table_list()
        return MemTable("tables", Batch.from_pydict({
            "table_schema": [r[0] for r in rows],
            "table_name": [r[1] for r in rows],
            "table_type": ["BASE TABLE" if r[2] == "table" else "VIEW"
                           for r in rows]}))
    if name == "pg_type":
        from .columnar import dtypes as _dt
        type_rows = [(16, "bool"), (20, "int8"), (21, "int2"), (23, "int4"),
                     (25, "text"), (700, "float4"), (701, "float8"),
                     (1043, "varchar"), (1082, "date"), (1114, "timestamp")]
        return MemTable("pg_type", Batch.from_pydict({
            "oid": [r[0] for r in type_rows],
            "typname": [r[1] for r in type_rows]}))
    if name == "pg_index" or name == "pg_indexes":
        rows_t, rows_i, rows_d = [], [], []
        with db.lock:
            for sname, s in db.schemas.items():
                for tname, t in s.tables.items():
                    for iname, idx in getattr(t, "indexes", {}).items():
                        rows_t.append(tname)
                        rows_i.append(iname)
                        rows_d.append(
                            f"USING {idx.using} "
                            f"({', '.join(idx.columns)})")
        return MemTable("pg_indexes", Batch.from_pydict({
            "tablename": rows_t, "indexname": rows_i, "indexdef": rows_d}))
    if name == "pg_stat_progress_basebackup" or \
            name.startswith("pg_stat_progress"):
        from .utils.progress import REGISTRY as _progress
        recs = _progress.snapshot()
        return MemTable(name, Batch.from_pydict({
            "pid": [r["pid"] for r in recs],
            "command": [r["command"] for r in recs],
            "phase": [r["phase"] for r in recs],
            "tuples_done": [r["done"] for r in recs],
            "tuples_total": [r["total"] for r in recs]}))
    if name == "pg_settings":
        names = _settings_registry.names()
        return MemTable("pg_settings", Batch.from_pydict({
            "name": names,
            "setting": [str(_settings_registry.get_global(n))
                        for n in names],
            "short_desc": [_settings_registry.definition(n).description
                           for n in names]}))
    if name == "pg_roles" or name == "pg_user":
        with db.roles._lock:
            rn = sorted(db.roles.roles)
            infos = [db.roles.roles[r] for r in rn]
        return MemTable("pg_roles", Batch.from_pydict({
            "rolname": rn,
            "rolsuper": [bool(i.get("superuser")) for i in infos],
            "rolcanlogin": [bool(i.get("login", True)) for i in infos]}))
    if name == "pg_database":
        return MemTable("pg_database", Batch.from_pydict({
            "oid": [1], "datname": ["serene"], "encoding": [6]}))
    if name == "sdb_indexes":
        rows = {"schema": [], "table": [], "index": [], "type": [],
                "columns": [], "segments": [], "indexed_rows": [],
                "fresh": []}
        with db.lock:
            for sname, s in db.schemas.items():
                for tname, t in s.tables.items():
                    for iname, idx in getattr(t, "indexes", {}).items():
                        rows["schema"].append(sname)
                        rows["table"].append(tname)
                        rows["index"].append(iname)
                        rows["type"].append(idx.using)
                        rows["columns"].append(",".join(idx.columns))
                        segs = max((len(ms.segments) for ms in
                                    getattr(idx, "searchers", {}).values()),
                                   default=1)
                        rows["segments"].append(segs)
                        rows["indexed_rows"].append(
                            getattr(idx, "indexed_rows", t.row_count()))
                        rows["fresh"].append(
                            idx.data_version == t.data_version)
        return MemTable("sdb_indexes", Batch.from_pydict(rows))
    if name == "sdb_settings":
        names = _settings_registry.names()
        return MemTable("sdb_settings", Batch.from_pydict({
            "name": names,
            "setting": [str(_settings_registry.get_global(n)) for n in names],
            "description": [_settings_registry.definition(n).description
                            for n in names],
        }))
    if name == "sdb_metrics":
        return metrics_table()
    if name == "sdb_log":
        return log_table()
    return None


def metrics_table() -> TableProvider:
    gs = _metrics.REGISTRY.all()
    return MemTable("sdb_metrics", Batch.from_pydict({
        "metric": [g.name for g in gs],
        "value": [g.value for g in gs],
        "description": [g.description for g in gs],
    }))


def log_table() -> TableProvider:
    recs = _log.MANAGER.records()
    return MemTable("sdb_log", Batch.from_pydict({
        "ts": [r.ts for r in recs],
        "level": [r.level.name for r in recs],
        "topic": [r.topic for r in recs],
        "message": [r.message for r in recs],
    }))
