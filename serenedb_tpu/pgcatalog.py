"""System catalog tables (pg_catalog emulation, sdb introspection).

Reference analog: server/pg/pg_catalog/ (67 system tables materialized from
catalog snapshots; SURVEY.md §2.3) + sdb_catalog (sdb_metrics, sdb_settings,
sdb_log). Starts with the tables clients/tests actually touch; grows toward
the full surface with the catalog layer.
"""

from __future__ import annotations

from typing import Optional

from .columnar.column import Batch
from .exec.tables import MemTable, TableProvider
from .utils import log as _log
from .utils import metrics as _metrics
from .utils.config import REGISTRY as _settings_registry


def system_table(db, parts: list[str]) -> Optional[TableProvider]:
    name = parts[-1].lower()
    qualified = len(parts) >= 2 and parts[-2].lower() in ("pg_catalog",
                                                          "information_schema",
                                                          "sdb_catalog")
    if len(parts) >= 2 and not qualified:
        return None
    if name == "pg_tables":
        rows = db.table_list()
        return MemTable("pg_tables", Batch.from_pydict({
            "schemaname": [r[0] for r in rows if r[2] == "table"],
            "tablename": [r[1] for r in rows if r[2] == "table"],
            "tableowner": ["serene" for r in rows if r[2] == "table"],
        }))
    if name == "pg_views":
        rows = db.table_list()
        return MemTable("pg_views", Batch.from_pydict({
            "schemaname": [r[0] for r in rows if r[2] == "view"],
            "viewname": [r[1] for r in rows if r[2] == "view"],
        }))
    if name == "pg_namespace":
        names = sorted(db.schemas)
        return MemTable("pg_namespace", Batch.from_pydict({
            "oid": list(range(1, len(names) + 1)),
            "nspname": names,
        }))
    if name == "pg_class":
        rows = db.table_list()
        return MemTable("pg_class", Batch.from_pydict({
            "oid": list(range(1, len(rows) + 1)),
            "relname": [r[1] for r in rows],
            "relkind": ["r" if r[2] == "table" else "v" for r in rows],
        }))
    if name == "sdb_settings":
        names = _settings_registry.names()
        return MemTable("sdb_settings", Batch.from_pydict({
            "name": names,
            "setting": [str(_settings_registry.get_global(n)) for n in names],
            "description": [_settings_registry.definition(n).description
                            for n in names],
        }))
    if name == "sdb_metrics":
        return metrics_table()
    if name == "sdb_log":
        return log_table()
    return None


def metrics_table() -> TableProvider:
    gs = _metrics.REGISTRY.all()
    return MemTable("sdb_metrics", Batch.from_pydict({
        "metric": [g.name for g in gs],
        "value": [g.value for g in gs],
        "description": [g.description for g in gs],
    }))


def log_table() -> TableProvider:
    recs = _log.MANAGER.records()
    return MemTable("sdb_log", Batch.from_pydict({
        "ts": [r.ts for r in recs],
        "level": [r.level.name for r in recs],
        "topic": [r.topic for r in recs],
        "message": [r.message for r in recs],
    }))
