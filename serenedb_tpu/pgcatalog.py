"""System catalog tables (pg_catalog emulation, sdb introspection).

Reference analog: server/pg/pg_catalog/ (92 system-table files materialized
from catalog snapshots; SURVEY.md §2.3) + sdb_catalog (sdb_metrics,
sdb_settings, sdb_log). Covers the full psql \\d-family workflow: pg_class /
pg_namespace / pg_attribute / pg_index / pg_am / pg_constraint / pg_type /
pg_proc with stable OIDs (engine.Database.oid_of), plus empty-but-typed
stubs for every catalog psql and common ORMs introspect, so joins resolve
instead of erroring (reference: server/pg/pg_catalog/pg_locks.cpp etc. are
likewise synthesized-empty).
"""

from __future__ import annotations

from typing import Callable, Optional

from . import errors
from .columnar import dtypes as dt
from .columnar.column import Batch, Column
from .exec.tables import MemTable, TableProvider
from .utils import log as _log
from .utils import metrics as _metrics
from .utils.config import REGISTRY as _settings_registry

# -- static type catalog ---------------------------------------------------
# (oid, typname, typlen, typtype, typcategory, typelem, typarray)
# Standard PG OIDs so drivers/ORMs that hardcode them keep working.
TYPE_ROWS = [
    (16, "bool", 1, "b", "B", 0, 1000),
    (17, "bytea", -1, "b", "U", 0, 1001),
    (18, "char", 1, "b", "S", 0, 1002),
    (19, "name", 64, "b", "S", 18, 1003),
    (20, "int8", 8, "b", "N", 0, 1016),
    (21, "int2", 2, "b", "N", 0, 1005),
    (23, "int4", 4, "b", "N", 0, 1007),
    (24, "regproc", 4, "b", "N", 0, 1008),
    (25, "text", -1, "b", "S", 0, 1009),
    (26, "oid", 4, "b", "N", 0, 1028),
    (114, "json", -1, "b", "U", 0, 199),
    (700, "float4", 4, "b", "N", 0, 1021),
    (701, "float8", 8, "b", "N", 0, 1022),
    (1042, "bpchar", -1, "b", "S", 0, 1014),
    (1043, "varchar", -1, "b", "S", 0, 1015),
    (1082, "date", 4, "b", "D", 0, 1182),
    (1083, "time", 8, "b", "D", 0, 1183),
    (1114, "timestamp", 8, "b", "D", 0, 1115),
    (1184, "timestamptz", 8, "b", "D", 0, 1185),
    (1186, "interval", 16, "b", "T", 0, 1187),
    (1700, "numeric", -1, "b", "N", 0, 1231),
    (2205, "regclass", 4, "b", "N", 0, 2210),
    (2206, "regtype", 4, "b", "N", 0, 2211),
    (2950, "uuid", 16, "b", "U", 0, 2951),
    (4089, "regnamespace", 4, "b", "N", 0, 4090),
    (3614, "tsvector", -1, "b", "U", 0, 3643),
    (3615, "tsquery", -1, "b", "U", 0, 3645),
    (3802, "jsonb", -1, "b", "U", 0, 3807),
]

_TYPE_OID_BY_NAME = {r[1]: r[0] for r in TYPE_ROWS}
_TYPE_NAME_BY_OID = {r[0]: r[1] for r in TYPE_ROWS}

# SqlType → pg type oid (matches server/pgwire._OID)
_ATT_OID = {
    dt.TypeId.BOOL: 16, dt.TypeId.TINYINT: 21, dt.TypeId.SMALLINT: 21,
    dt.TypeId.INT: 23, dt.TypeId.BIGINT: 20, dt.TypeId.FLOAT: 700,
    dt.TypeId.DOUBLE: 701, dt.TypeId.VARCHAR: 25,
    dt.TypeId.TIMESTAMP: 1114, dt.TypeId.DATE: 1082,
    dt.TypeId.INTERVAL: 1186, dt.TypeId.NULL: 25, dt.TypeId.OID: 26,
    dt.TypeId.REGCLASS: 2205, dt.TypeId.REGTYPE: 2206,
    dt.TypeId.REGPROC: 24, dt.TypeId.REGNAMESPACE: 4089,
}

# type oid → SQL rendering for format_type()
_FORMAT_TYPE = {
    16: "boolean", 17: "bytea", 18: '"char"', 19: "name", 20: "bigint",
    21: "smallint", 23: "integer", 24: "regproc", 25: "text", 26: "oid",
    114: "json", 700: "real", 701: "double precision",
    1042: "character", 1043: "character varying", 1082: "date",
    1083: "time without time zone", 1114: "timestamp without time zone",
    1184: "timestamp with time zone", 1186: "interval", 1700: "numeric",
    2205: "regclass", 2206: "regtype", 2950: "uuid", 3614: "tsvector",
    3615: "tsquery", 3802: "jsonb", 4089: "regnamespace",
}

# fixed namespace OIDs (PG uses 11 for pg_catalog)
NS_PG_CATALOG = 11
NS_INFO_SCHEMA = 13
NS_SDB_CATALOG = 14

_PROC_OID_BASE = 10000


def type_oid_of(sql_type: dt.SqlType) -> int:
    return _ATT_OID.get(sql_type.id, 25)


def format_type_oid(oid: int, typmod: Optional[int] = None) -> Optional[str]:
    name = _FORMAT_TYPE.get(int(oid))
    if name is None:
        return "???"
    if typmod is not None and typmod >= 4 and name in (
            "character varying", "character", "numeric"):
        if name == "numeric":
            m = int(typmod) - 4
            return f"numeric({m >> 16},{m & 0xFFFF})"
        return f"{name}({int(typmod) - 4})"
    return name


def resolve_type_oid(text: str) -> int:
    """'::regtype' cast: SQL type name → pg_type oid."""
    from . import errors
    s = text.strip().lower()
    for pre in ("pg_catalog.",):
        if s.startswith(pre):
            s = s[len(pre):]
    alias = {"integer": "int4", "int": "int4", "bigint": "int8",
             "smallint": "int2", "boolean": "bool", "real": "float4",
             "double precision": "float8", "character varying": "varchar",
             "timestamp without time zone": "timestamp",
             "timestamp with time zone": "timestamptz",
             "character": "bpchar", "string": "text"}
    s = alias.get(s, s)
    oid = _TYPE_OID_BY_NAME.get(s)
    if oid is None:
        raise errors.SqlError(errors.UNDEFINED_OBJECT,
                              f'type "{text}" does not exist')
    return oid


def _proc_names() -> list[str]:
    from .functions import scalar as _scalar
    return sorted(_scalar._REGISTRY)


def resolve_proc_oid(text: str) -> int:
    from . import errors
    s = text.strip().lower()
    if s.startswith("pg_catalog."):
        s = s[len("pg_catalog."):]
    names = _proc_names()
    try:
        return _PROC_OID_BASE + names.index(s)
    except ValueError:
        raise errors.SqlError(errors.UNDEFINED_FUNCTION,
                              f'function "{text}" does not exist')


def proc_name_of(oid: int) -> Optional[str]:
    names = _proc_names()
    i = int(oid) - _PROC_OID_BASE
    return names[i] if 0 <= i < len(names) else None


def type_name_of(oid: int) -> Optional[str]:
    return _TYPE_NAME_BY_OID.get(int(oid))


def regtype_render(oid: int) -> str:
    """regtype → text renders the CANONICAL SQL name ('integer', not
    'int4') — PG's format_type() behavior."""
    name = _FORMAT_TYPE.get(int(oid))
    if name is not None:
        return name
    return type_name_of(oid) or str(int(oid))


def resolve_namespace_oid(db, text: str) -> int:
    """'::regnamespace' cast: schema name → pg_namespace oid."""
    from . import errors
    s = text.strip().strip('"')
    fixed = {"pg_catalog": NS_PG_CATALOG,
             "information_schema": NS_INFO_SCHEMA,
             "sdb_catalog": NS_SDB_CATALOG}
    if s in fixed:
        return fixed[s]
    if db is not None:
        with db.lock:
            if s in db.schemas:
                return db.oid_of("schema", "", s)
    raise errors.SqlError(errors.UNDEFINED_OBJECT,
                          f'schema "{text}" does not exist')


def namespace_render(db, oid: int) -> str:
    fixed = {NS_PG_CATALOG: "pg_catalog", NS_INFO_SCHEMA:
             "information_schema", NS_SDB_CATALOG: "sdb_catalog"}
    if oid in fixed:
        return fixed[oid]
    if db is not None:
        hit = db.oid_lookup(oid)
        if hit is not None and hit[0] == "schema":
            return hit[2]
    return str(int(oid))


def regclass_render(db, oid: int) -> str:
    """oid → relation name (search_path-aware: bare name for main)."""
    if db is not None:
        hit = db.oid_lookup(oid)
        if hit is not None:
            kind, schema, name = hit
            if kind in ("table", "view", "index", "sequence"):
                return name if schema == "main" else f"{schema}.{name}"
    return str(int(oid))


def current_db():
    """The Database bound to the executing connection, if any."""
    from .engine import CURRENT_CONNECTION
    conn = CURRENT_CONNECTION.get()
    return None if conn is None else conn.db


# -- table builders --------------------------------------------------------

def _typed(name: str, spec: list[tuple[str, dt.SqlType]],
           rows: dict[str, list]) -> MemTable:
    cols = [Column.from_pylist(rows.get(cn, []), ct) for cn, ct in spec]
    return MemTable(name, Batch([cn for cn, _ in spec], cols))


def _ns_oid(db, sname: str) -> int:
    return db.oid_of("schema", "", sname)


def _rel_rows(db):
    """One row per relation: (oid, schema, name, kind, provider_or_None)."""
    out = []
    with db.lock:
        for sname, s in db.schemas.items():
            for tname, t in s.tables.items():
                out.append((db.oid_of("table", sname, tname), sname, tname,
                            "r", t))
                for iname in getattr(t, "indexes", {}):
                    out.append((db.oid_of("index", sname, iname), sname,
                                iname, "i", t))
            for vname in s.views:
                out.append((db.oid_of("view", sname, vname), sname, vname,
                            "v", None))
        for qname in db.sequences:
            sch, _, nm = qname.rpartition(".")
            out.append((db.oid_of("sequence", sch or "main", nm),
                        sch or "main", nm, "S", None))
    return out


def _pg_namespace(db) -> MemTable:
    with db.lock:
        names = sorted(db.schemas)
    oids = [_ns_oid(db, n) for n in names]
    oids += [NS_PG_CATALOG, NS_INFO_SCHEMA, NS_SDB_CATALOG]
    names += ["pg_catalog", "information_schema", "sdb_catalog"]
    return _typed("pg_namespace", [
        ("oid", dt.OID), ("nspname", dt.VARCHAR), ("nspowner", dt.OID),
        ("nspacl", dt.VARCHAR)], {
        "oid": oids, "nspname": names, "nspowner": [10] * len(oids),
        "nspacl": [None] * len(oids)})


_PG_CLASS_SPEC = [
    ("oid", dt.OID), ("relname", dt.VARCHAR), ("relnamespace", dt.OID),
    ("reltype", dt.OID), ("relowner", dt.OID), ("relam", dt.OID),
    ("relfilenode", dt.OID), ("reltablespace", dt.OID),
    ("relpages", dt.INT), ("reltuples", dt.FLOAT),
    ("relallvisible", dt.INT), ("reltoastrelid", dt.OID),
    ("relhasindex", dt.BOOL), ("relisshared", dt.BOOL),
    ("relpersistence", dt.VARCHAR), ("relkind", dt.VARCHAR),
    ("relnatts", dt.SMALLINT), ("relchecks", dt.SMALLINT),
    ("relhasrules", dt.BOOL), ("relhastriggers", dt.BOOL),
    ("relhassubclass", dt.BOOL), ("relrowsecurity", dt.BOOL),
    ("relforcerowsecurity", dt.BOOL), ("relispopulated", dt.BOOL),
    ("relreplident", dt.VARCHAR), ("relispartition", dt.BOOL),
    ("reloftype", dt.OID), ("reloptions", dt.VARCHAR),
    ("relacl", dt.VARCHAR),
]


def _pg_class(db) -> MemTable:
    rows: dict[str, list] = {c: [] for c, _ in _PG_CLASS_SPEC}
    for oid, sname, name, kind, t in _rel_rows(db):
        n_rows = t.row_count() if (t is not None and kind == "r") else 0
        natts = len(t.column_names) if (t is not None and kind == "r") else 0
        rows["oid"].append(oid)
        rows["relname"].append(name)
        rows["relnamespace"].append(_ns_oid(db, sname))
        rows["reltype"].append(0)
        rows["relowner"].append(10)
        rows["relam"].append(2 if kind == "i" else 0)
        rows["relfilenode"].append(oid)
        rows["reltablespace"].append(0)
        rows["relpages"].append(max(1, n_rows // 128))
        rows["reltuples"].append(float(n_rows))
        rows["relallvisible"].append(0)
        rows["reltoastrelid"].append(0)
        rows["relhasindex"].append(
            bool(getattr(t, "indexes", {})) if kind == "r" else False)
        rows["relisshared"].append(False)
        rows["relpersistence"].append("p")
        rows["relkind"].append(kind)
        rows["relnatts"].append(natts)
        rows["relchecks"].append(0)
        rows["relhasrules"].append(False)
        rows["relhastriggers"].append(False)
        rows["relhassubclass"].append(False)
        rows["relrowsecurity"].append(False)
        rows["relforcerowsecurity"].append(False)
        rows["relispopulated"].append(True)
        rows["relreplident"].append("d")
        rows["relispartition"].append(False)
        rows["reloftype"].append(0)
        rows["reloptions"].append(None)
        rows["relacl"].append(None)
    return _typed("pg_class", _PG_CLASS_SPEC, rows)


_PG_ATTR_SPEC = [
    ("attrelid", dt.OID), ("attname", dt.VARCHAR), ("atttypid", dt.OID),
    ("attstattarget", dt.INT), ("attlen", dt.SMALLINT),
    ("attnum", dt.SMALLINT), ("attndims", dt.INT),
    ("attcacheoff", dt.INT), ("atttypmod", dt.INT), ("attbyval", dt.BOOL),
    ("attstorage", dt.VARCHAR), ("attalign", dt.VARCHAR),
    ("attnotnull", dt.BOOL), ("atthasdef", dt.BOOL),
    ("atthasmissing", dt.BOOL), ("attidentity", dt.VARCHAR),
    ("attgenerated", dt.VARCHAR), ("attisdropped", dt.BOOL),
    ("attislocal", dt.BOOL), ("attinhcount", dt.INT),
    ("attcollation", dt.OID),
]


_view_attr_guard = __import__("threading").local()


def _catalog_signature(db) -> int:
    """Cheap fingerprint of every table's shape + view definitions; when
    unchanged, cached view column layouts are still valid."""
    parts = []
    with db.lock:
        for sn in sorted(db.schemas):
            s = db.schemas[sn]
            for tn in sorted(s.tables):
                t = s.tables[tn]
                parts.append((sn, tn, tuple(t.column_names),
                              tuple(str(ct) for ct in t.column_types)))
            for vn in sorted(s.views):
                parts.append((sn, vn, getattr(s.views[vn], "sql", "")))
    return hash(tuple(parts))


def _view_columns(db) -> dict:
    """(schema, view) → [(name, SqlType)] by zero-row executing each view.
    Guarded against recursion (a view over pg_attribute would otherwise
    re-enter this builder) and cached per catalog signature — psql issues
    several pg_attribute scans per \\d and must not re-plan every view
    each time."""
    if getattr(_view_attr_guard, "busy", False):
        return {}
    sig = _catalog_signature(db)
    cached = getattr(db, "_view_cols_cache", None)
    if cached is not None and cached[0] == sig:
        return cached[1]
    out: dict = {}
    _view_attr_guard.busy = True
    try:
        conn = db.connect()
        try:
            with db.lock:
                names = [(sn, vn) for sn, s in db.schemas.items()
                         for vn in s.views]
            for sn, vn in names:
                try:
                    r = conn.execute(
                        f'SELECT * FROM "{sn}"."{vn}" LIMIT 0')
                    out[(sn, vn)] = list(zip(
                        r.batch.names, [c.type for c in r.batch.columns]))
                except Exception:
                    pass
        finally:
            conn.close()
    finally:
        _view_attr_guard.busy = False
    db._view_cols_cache = (sig, out)
    return out


def _pg_attribute(db) -> MemTable:
    rows: dict[str, list] = {c: [] for c, _ in _PG_ATTR_SPEC}
    vcols = _view_columns(db)
    with db.lock:
        rels = []
        for sname, s in db.schemas.items():
            for tname, t in s.tables.items():
                rels.append((db.oid_of("table", sname, tname), t))
        for (sname, vname), cols in vcols.items():
            rels.append((db.oid_of("view", sname, vname),
                         _typed(vname, cols, {})))
    for oid, t in rels:
        if t is None:
            continue
        nn = set((getattr(t, "table_meta", {}) or {}).get("not_null", []))
        pk = set((getattr(t, "table_meta", {}) or {}).get("primary_key", []))
        for pos, (cn, ct) in enumerate(
                zip(t.column_names, t.column_types), 1):
            rows["attrelid"].append(oid)
            rows["attname"].append(cn)
            rows["atttypid"].append(type_oid_of(ct))
            rows["attstattarget"].append(-1)
            rows["attlen"].append(-1)
            rows["attnum"].append(pos)
            rows["attndims"].append(0)
            rows["attcacheoff"].append(-1)
            rows["atttypmod"].append(-1)
            rows["attbyval"].append(True)
            rows["attstorage"].append("p")
            rows["attalign"].append("i")
            rows["attnotnull"].append(cn in nn or cn in pk)
            rows["atthasdef"].append(False)
            rows["atthasmissing"].append(False)
            rows["attidentity"].append("")
            rows["attgenerated"].append("")
            rows["attisdropped"].append(False)
            rows["attislocal"].append(True)
            rows["attinhcount"].append(0)
            rows["attcollation"].append(0)
    return _typed("pg_attribute", _PG_ATTR_SPEC, rows)


_PG_INDEX_SPEC = [
    ("indexrelid", dt.OID), ("indrelid", dt.OID), ("indnatts", dt.SMALLINT),
    ("indnkeyatts", dt.SMALLINT), ("indisunique", dt.BOOL),
    ("indisprimary", dt.BOOL), ("indisexclusion", dt.BOOL),
    ("indimmediate", dt.BOOL), ("indisclustered", dt.BOOL),
    ("indisvalid", dt.BOOL), ("indcheckxmin", dt.BOOL),
    ("indisready", dt.BOOL), ("indislive", dt.BOOL),
    ("indisreplident", dt.BOOL), ("indkey", dt.VARCHAR),
    ("indoption", dt.VARCHAR), ("indexprs", dt.VARCHAR),
    ("indpred", dt.VARCHAR),
]


def _index_entries(db):
    """(index_oid, table_oid, schema, iname, idx, table) rows."""
    out = []
    with db.lock:
        for sname, s in db.schemas.items():
            for tname, t in s.tables.items():
                toid = db.oid_of("table", sname, tname)
                for iname, idx in getattr(t, "indexes", {}).items():
                    out.append((db.oid_of("index", sname, iname), toid,
                                sname, iname, idx, t))
    return out


def _pg_index(db) -> MemTable:
    rows: dict[str, list] = {c: [] for c, _ in _PG_INDEX_SPEC}
    for ioid, toid, sname, iname, idx, t in _index_entries(db):
        cols = list(getattr(idx, "columns", []))
        attnums = []
        for c in cols:
            try:
                attnums.append(t.column_names.index(c) + 1)
            except ValueError:
                attnums.append(0)
        rows["indexrelid"].append(ioid)
        rows["indrelid"].append(toid)
        rows["indnatts"].append(len(cols))
        rows["indnkeyatts"].append(len(cols))
        rows["indisunique"].append(False)
        rows["indisprimary"].append(False)
        rows["indisexclusion"].append(False)
        rows["indimmediate"].append(True)
        rows["indisclustered"].append(False)
        rows["indisvalid"].append(True)
        rows["indcheckxmin"].append(False)
        rows["indisready"].append(True)
        rows["indislive"].append(True)
        rows["indisreplident"].append(False)
        rows["indkey"].append(" ".join(map(str, attnums)))
        rows["indoption"].append(" ".join("0" for _ in attnums))
        rows["indexprs"].append(None)
        rows["indpred"].append(None)
    return _typed("pg_index", _PG_INDEX_SPEC, rows)


def _pg_am(db) -> MemTable:
    ams = [(2, "btree"), (403, "btree"), (405, "hash"), (783, "gist"),
           (2742, "gin"), (4000, "spgist"), (9001, "inverted"),
           (9002, "ivf"), (9003, "maxsim")]
    return _typed("pg_am", [
        ("oid", dt.OID), ("amname", dt.VARCHAR), ("amhandler", dt.OID),
        ("amtype", dt.VARCHAR)], {
        "oid": [a[0] for a in ams], "amname": [a[1] for a in ams],
        "amhandler": [0] * len(ams), "amtype": ["i"] * len(ams)})


def _pg_constraint(db) -> MemTable:
    spec = [("oid", dt.OID), ("conname", dt.VARCHAR),
            ("connamespace", dt.OID), ("contype", dt.VARCHAR),
            ("condeferrable", dt.BOOL), ("condeferred", dt.BOOL),
            ("convalidated", dt.BOOL), ("conrelid", dt.OID),
            ("contypid", dt.OID), ("conindid", dt.OID),
            ("confrelid", dt.OID), ("conkey", dt.VARCHAR),
            ("confkey", dt.VARCHAR), ("conbin", dt.VARCHAR)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    with db.lock:
        for sname, s in db.schemas.items():
            for tname, t in s.tables.items():
                pk = (getattr(t, "table_meta", {}) or {}).get(
                    "primary_key") or []
                if not pk:
                    continue
                toid = db.oid_of("table", sname, tname)
                attnums = [t.column_names.index(c) + 1
                           for c in pk if c in t.column_names]
                rows["oid"].append(db.oid_of("constraint", sname,
                                             f"{tname}_pkey"))
                rows["conname"].append(f"{tname}_pkey")
                rows["connamespace"].append(_ns_oid(db, sname))
                rows["contype"].append("p")
                rows["condeferrable"].append(False)
                rows["condeferred"].append(False)
                rows["convalidated"].append(True)
                rows["conrelid"].append(toid)
                rows["contypid"].append(0)
                rows["conindid"].append(0)
                rows["confrelid"].append(0)
                rows["conkey"].append("{" + ",".join(map(str, attnums)) + "}")
                rows["confkey"].append(None)
                rows["conbin"].append(None)
    return _typed("pg_constraint", spec, rows)


def _pg_type(db) -> MemTable:
    spec = [("oid", dt.OID), ("typname", dt.VARCHAR),
            ("typnamespace", dt.OID), ("typowner", dt.OID),
            ("typlen", dt.SMALLINT), ("typbyval", dt.BOOL),
            ("typtype", dt.VARCHAR), ("typcategory", dt.VARCHAR),
            ("typispreferred", dt.BOOL), ("typisdefined", dt.BOOL),
            ("typdelim", dt.VARCHAR), ("typrelid", dt.OID),
            ("typelem", dt.OID), ("typarray", dt.OID),
            ("typbasetype", dt.OID), ("typtypmod", dt.INT),
            ("typnotnull", dt.BOOL), ("typcollation", dt.OID),
            ("typdefault", dt.VARCHAR)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    for oid, name, tlen, ttype, tcat, telem, tarr in TYPE_ROWS:
        rows["oid"].append(oid)
        rows["typname"].append(name)
        rows["typnamespace"].append(NS_PG_CATALOG)
        rows["typowner"].append(10)
        rows["typlen"].append(tlen)
        rows["typbyval"].append(tlen in (1, 2, 4, 8))
        rows["typtype"].append(ttype)
        rows["typcategory"].append(tcat)
        rows["typispreferred"].append(name in ("bool", "int4", "text",
                                               "float8"))
        rows["typisdefined"].append(True)
        rows["typdelim"].append(",")
        rows["typrelid"].append(0)
        rows["typelem"].append(telem)
        rows["typarray"].append(tarr)
        rows["typbasetype"].append(0)
        rows["typtypmod"].append(-1)
        rows["typnotnull"].append(False)
        rows["typcollation"].append(0)
        rows["typdefault"].append(None)
    return _typed("pg_type", spec, rows)


def _pg_proc(db) -> MemTable:
    spec = [("oid", dt.OID), ("proname", dt.VARCHAR),
            ("pronamespace", dt.OID), ("proowner", dt.OID),
            ("prolang", dt.OID), ("prokind", dt.VARCHAR),
            ("prosecdef", dt.BOOL), ("proretset", dt.BOOL),
            ("provolatile", dt.VARCHAR), ("pronargs", dt.SMALLINT),
            ("prorettype", dt.OID), ("proargtypes", dt.VARCHAR),
            ("proargnames", dt.VARCHAR), ("prosrc", dt.VARCHAR)]
    names = _proc_names()
    rows = {
        "oid": [_PROC_OID_BASE + i for i in range(len(names))],
        "proname": names,
        "pronamespace": [NS_PG_CATALOG] * len(names),
        "proowner": [10] * len(names),
        "prolang": [12] * len(names),
        "prokind": ["f"] * len(names),
        "prosecdef": [False] * len(names),
        "proretset": [False] * len(names),
        "provolatile": ["i"] * len(names),
        "pronargs": [0] * len(names),
        "prorettype": [25] * len(names),
        "proargtypes": [""] * len(names),
        "proargnames": [None] * len(names),
        "prosrc": names,
    }
    return _typed("pg_proc", spec, rows)


def _pg_roles(db) -> MemTable:
    spec = [("oid", dt.OID), ("rolname", dt.VARCHAR), ("rolsuper", dt.BOOL),
            ("rolinherit", dt.BOOL), ("rolcreaterole", dt.BOOL),
            ("rolcreatedb", dt.BOOL), ("rolcanlogin", dt.BOOL),
            ("rolreplication", dt.BOOL), ("rolconnlimit", dt.INT),
            ("rolpassword", dt.VARCHAR), ("rolvaliduntil", dt.VARCHAR),
            ("rolbypassrls", dt.BOOL), ("rolconfig", dt.VARCHAR)]
    with db.roles._lock:
        rn = sorted(db.roles.roles)
        infos = [db.roles.roles[r] for r in rn]
    rows = {
        "oid": [db.oid_of("role", "", r) for r in rn],
        "rolname": rn,
        "rolsuper": [bool(i.get("superuser")) for i in infos],
        "rolinherit": [True] * len(rn),
        "rolcreaterole": [bool(i.get("superuser")) for i in infos],
        "rolcreatedb": [bool(i.get("superuser")) for i in infos],
        "rolcanlogin": [bool(i.get("login", True)) for i in infos],
        "rolreplication": [False] * len(rn),
        "rolconnlimit": [-1] * len(rn),
        "rolpassword": ["********"] * len(rn),
        "rolvaliduntil": [None] * len(rn),
        "rolbypassrls": [bool(i.get("superuser")) for i in infos],
        "rolconfig": [None] * len(rn),
    }
    return _typed("pg_roles", spec, rows)


def _pg_database(db) -> MemTable:
    spec = [("oid", dt.OID), ("datname", dt.VARCHAR), ("datdba", dt.OID),
            ("encoding", dt.INT), ("datcollate", dt.VARCHAR),
            ("datctype", dt.VARCHAR), ("datistemplate", dt.BOOL),
            ("datallowconn", dt.BOOL), ("datconnlimit", dt.INT),
            ("dattablespace", dt.OID), ("datacl", dt.VARCHAR)]
    return _typed("pg_database", spec, {
        "oid": [1], "datname": ["serene"], "datdba": [10], "encoding": [6],
        "datcollate": ["C"], "datctype": ["C"], "datistemplate": [False],
        "datallowconn": [True], "datconnlimit": [-1], "dattablespace": [0],
        "datacl": [None]})


def _pg_tables(db) -> MemTable:
    rows = db.table_list()
    t = [r for r in rows if r[2] == "table"]
    return _typed("pg_tables", [
        ("schemaname", dt.VARCHAR), ("tablename", dt.VARCHAR),
        ("tableowner", dt.VARCHAR), ("tablespace", dt.VARCHAR),
        ("hasindexes", dt.BOOL), ("hasrules", dt.BOOL),
        ("hastriggers", dt.BOOL), ("rowsecurity", dt.BOOL)], {
        "schemaname": [r[0] for r in t], "tablename": [r[1] for r in t],
        "tableowner": ["serene"] * len(t), "tablespace": [None] * len(t),
        "hasindexes": [False] * len(t), "hasrules": [False] * len(t),
        "hastriggers": [False] * len(t), "rowsecurity": [False] * len(t)})


def _pg_views(db) -> MemTable:
    rows = db.table_list()
    v = [r for r in rows if r[2] == "view"]
    defs = []
    with db.lock:
        for sname, name, _ in v:
            vd = db.schemas[sname].views.get(name)
            defs.append(getattr(vd, "sql", "") or "")
    return _typed("pg_views", [
        ("schemaname", dt.VARCHAR), ("viewname", dt.VARCHAR),
        ("viewowner", dt.VARCHAR), ("definition", dt.VARCHAR)], {
        "schemaname": [r[0] for r in v], "viewname": [r[1] for r in v],
        "viewowner": ["serene"] * len(v), "definition": defs})


def _pg_indexes(db) -> MemTable:
    rows_s, rows_t, rows_i, rows_d = [], [], [], []
    for ioid, toid, sname, iname, idx, t in _index_entries(db):
        rows_s.append(sname)
        rows_t.append(t.name if hasattr(t, "name") else "")
        rows_i.append(iname)
        rows_d.append(f"CREATE INDEX {iname} ON {rows_t[-1]} "
                      f"USING {idx.using} ({', '.join(idx.columns)})")
    return _typed("pg_indexes", [
        ("schemaname", dt.VARCHAR), ("tablename", dt.VARCHAR),
        ("indexname", dt.VARCHAR), ("tablespace", dt.VARCHAR),
        ("indexdef", dt.VARCHAR)], {
        "schemaname": rows_s, "tablename": rows_t, "indexname": rows_i,
        "tablespace": [None] * len(rows_i), "indexdef": rows_d})


def _pg_sequences(db) -> MemTable:
    spec = [("schemaname", dt.VARCHAR), ("sequencename", dt.VARCHAR),
            ("sequenceowner", dt.VARCHAR), ("data_type", dt.VARCHAR),
            ("start_value", dt.BIGINT), ("min_value", dt.BIGINT),
            ("max_value", dt.BIGINT), ("increment_by", dt.BIGINT),
            ("cycle", dt.BOOL), ("cache_size", dt.BIGINT),
            ("last_value", dt.BIGINT)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    with db.lock:
        for qname, info in db.sequences.items():
            sch, _, nm = qname.rpartition(".")
            rows["schemaname"].append(sch or "main")
            rows["sequencename"].append(nm)
            rows["sequenceowner"].append("serene")
            rows["data_type"].append("bigint")
            rows["start_value"].append(int(info.get("start", 1)))
            rows["min_value"].append(1)
            rows["max_value"].append(2**63 - 1)
            rows["increment_by"].append(int(info.get("increment", 1)))
            rows["cycle"].append(False)
            rows["cache_size"].append(1)
            rows["last_value"].append(int(info.get("value", 0)))
    return _typed("pg_sequences", spec, rows)


def _pg_stat_user_tables(db) -> MemTable:
    spec = [("relid", dt.OID), ("schemaname", dt.VARCHAR),
            ("relname", dt.VARCHAR), ("seq_scan", dt.BIGINT),
            ("seq_tup_read", dt.BIGINT), ("idx_scan", dt.BIGINT),
            ("n_tup_ins", dt.BIGINT), ("n_tup_upd", dt.BIGINT),
            ("n_tup_del", dt.BIGINT), ("n_live_tup", dt.BIGINT),
            ("n_dead_tup", dt.BIGINT)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    with db.lock:
        for sname, s in db.schemas.items():
            for tname, t in s.tables.items():
                rows["relid"].append(db.oid_of("table", sname, tname))
                rows["schemaname"].append(sname)
                rows["relname"].append(tname)
                for c in ("seq_scan", "seq_tup_read", "idx_scan",
                          "n_tup_ins", "n_tup_upd", "n_tup_del",
                          "n_dead_tup"):
                    rows[c].append(0)
                rows["n_live_tup"].append(t.row_count())
    return _typed("pg_stat_user_tables", spec, rows)


def _pg_stat_activity(db) -> MemTable:
    from .sql.binder import format_timestamp
    with db.lock:
        sess = [dict(v) for v in db.sessions.values()]
    sess.sort(key=lambda v: v["pid"])

    def ts(v):
        return (format_timestamp(int(v * 1_000_000))
                if v is not None else None)
    return _typed("pg_stat_activity", [
        ("datid", dt.OID), ("datname", dt.VARCHAR), ("pid", dt.INT),
        ("usename", dt.VARCHAR), ("application_name", dt.VARCHAR),
        ("client_addr", dt.VARCHAR), ("backend_start", dt.VARCHAR),
        ("query_start", dt.VARCHAR), ("state", dt.VARCHAR),
        ("wait_event_type", dt.VARCHAR), ("wait_event", dt.VARCHAR),
        ("query_id", dt.BIGINT), ("query", dt.VARCHAR)], {
        "datid": [1] * len(sess), "datname": ["serene"] * len(sess),
        "pid": [v["pid"] for v in sess],
        "usename": [v["usename"] for v in sess],
        "application_name": [v["application_name"] for v in sess],
        "client_addr": [v.get("client_addr") for v in sess],
        "backend_start": [ts(v["backend_start"]) for v in sess],
        "query_start": [ts(v["query_start"]) for v in sess],
        "state": [v["state"] for v in sess],
        # live wait feed (obs/resources.wait_scope): what an ACTIVE
        # session is blocked on right now — worker-pool task waits,
        # search-batch coalescing, collective combines; NULL when
        # running on-CPU or idle (PG semantics)
        "wait_event_type": [v.get("wait_event_type") for v in sess],
        "wait_event": [v.get("wait_event") for v in sess],
        # normalized-statement fingerprint of the session's last
        # completed statement (sdb_stat_statements key), NULL before
        # any profiled execution
        "query_id": [v.get("query_id") for v in sess],
        "query": [v["query"] for v in sess]})


def _pg_settings(db) -> MemTable:
    names = _settings_registry.names()
    return _typed("pg_settings", [
        ("name", dt.VARCHAR), ("setting", dt.VARCHAR),
        ("unit", dt.VARCHAR), ("category", dt.VARCHAR),
        ("short_desc", dt.VARCHAR), ("context", dt.VARCHAR),
        ("vartype", dt.VARCHAR), ("source", dt.VARCHAR),
        ("boot_val", dt.VARCHAR), ("reset_val", dt.VARCHAR)], {
        "name": names,
        "setting": [str(_settings_registry.get_global(n)) for n in names],
        "unit": [None] * len(names),
        "category": ["serenedb"] * len(names),
        "short_desc": [_settings_registry.definition(n).description
                       for n in names],
        "context": ["user"] * len(names),
        "vartype": ["string"] * len(names),
        "source": ["default"] * len(names),
        "boot_val": [str(_settings_registry.get_global(n)) for n in names],
        "reset_val": [str(_settings_registry.get_global(n)) for n in names]})


# information_schema ------------------------------------------------------

#: ISO SQL feature taxonomy rows with THIS ENGINE's honest support flags
#: (reference: server/pg/information_schema/sql_features.txt). A curated
#: representative subset of the standard's feature list.
_SQL_FEATURES = [
    ("B012", "Embedded C", "NO"),
    ("E011", "Numeric data types", "YES"),
    ("E011-01", "INTEGER and SMALLINT data types", "YES"),
    ("E011-02", "REAL, DOUBLE PRECISION and FLOAT data types", "YES"),
    ("E011-04", "Arithmetic operators", "YES"),
    ("E011-05", "Numeric comparison", "YES"),
    ("E011-06", "Implicit casting among the numeric data types", "YES"),
    ("E021", "Character string types", "YES"),
    ("E021-01", "CHARACTER data type", "YES"),
    ("E021-02", "CHARACTER VARYING data type", "YES"),
    ("E021-03", "Character literals", "YES"),
    ("E021-04", "CHARACTER_LENGTH function", "YES"),
    ("E021-05", "OCTET_LENGTH function", "YES"),
    ("E021-06", "SUBSTRING function", "YES"),
    ("E021-07", "Character concatenation", "YES"),
    ("E021-08", "UPPER and LOWER functions", "YES"),
    ("E021-09", "TRIM function", "YES"),
    ("E021-10", "Implicit casting among character types", "YES"),
    ("E021-11", "POSITION function", "YES"),
    ("E031", "Identifiers", "YES"),
    ("E031-01", "Delimited identifiers", "YES"),
    ("E031-02", "Lower case identifiers", "YES"),
    ("E051", "Basic query specification", "YES"),
    ("E051-01", "SELECT DISTINCT", "YES"),
    ("E051-02", "GROUP BY clause", "YES"),
    ("E051-04", "GROUP BY can contain columns not in select list", "YES"),
    ("E051-05", "Select list items can be renamed", "YES"),
    ("E051-06", "HAVING clause", "YES"),
    ("E051-07", "Qualified * in select list", "YES"),
    ("E061", "Basic predicates and search conditions", "YES"),
    ("E061-01", "Comparison predicate", "YES"),
    ("E061-02", "BETWEEN predicate", "YES"),
    ("E061-03", "IN predicate with list of values", "YES"),
    ("E061-04", "LIKE predicate", "YES"),
    ("E061-05", "LIKE predicate: ESCAPE clause", "YES"),
    ("E061-06", "NULL predicate", "YES"),
    ("E061-08", "EXISTS predicate", "YES"),
    ("E061-09", "Subqueries in comparison predicate", "YES"),
    ("E061-11", "Subqueries in IN predicate", "YES"),
    ("E061-13", "Correlated subqueries", "YES"),
    ("E061-14", "Search condition", "YES"),
    ("E071", "Basic query expressions", "YES"),
    ("E071-01", "UNION DISTINCT table operator", "YES"),
    ("E071-02", "UNION ALL table operator", "YES"),
    ("E071-03", "EXCEPT DISTINCT table operator", "YES"),
    ("E071-05", "Columns combined via table operators need not have "
                "exactly the same data type", "YES"),
    ("E071-06", "Table operators in subqueries", "YES"),
    ("E081", "Basic privileges", "YES"),
    ("E081-01", "SELECT privilege at the table level", "YES"),
    ("E081-02", "DELETE privilege", "YES"),
    ("E081-03", "INSERT privilege at the table level", "YES"),
    ("E081-04", "UPDATE privilege at the table level", "YES"),
    ("E091", "Set functions", "YES"),
    ("E091-01", "AVG", "YES"),
    ("E091-02", "COUNT", "YES"),
    ("E091-03", "MAX", "YES"),
    ("E091-04", "MIN", "YES"),
    ("E091-05", "SUM", "YES"),
    ("E091-06", "ALL quantifier", "YES"),
    ("E091-07", "DISTINCT quantifier", "YES"),
    ("E101", "Basic data manipulation", "YES"),
    ("E101-01", "INSERT statement", "YES"),
    ("E101-03", "Searched UPDATE statement", "YES"),
    ("E101-04", "Searched DELETE statement", "YES"),
    ("E111", "Single row SELECT statement", "YES"),
    ("E121", "Basic cursor support", "NO"),
    ("E131", "Null value support (nulls in lieu of values)", "YES"),
    ("E141", "Basic integrity constraints", "YES"),
    ("E141-01", "NOT NULL constraints", "YES"),
    ("E141-03", "PRIMARY KEY constraints", "YES"),
    ("E141-04", "Basic FOREIGN KEY constraint", "NO"),
    ("E151", "Transaction support", "YES"),
    ("E151-01", "COMMIT statement", "YES"),
    ("E151-02", "ROLLBACK statement", "YES"),
    ("E152", "Basic SET TRANSACTION statement", "NO"),
    ("E153", "Updatable queries with subqueries", "YES"),
    ("E161", "SQL comments using leading double minus", "YES"),
    ("E171", "SQLSTATE support", "YES"),
    ("F031", "Basic schema manipulation", "YES"),
    ("F031-01", "CREATE TABLE statement to create persistent base "
                "tables", "YES"),
    ("F031-02", "CREATE VIEW statement", "YES"),
    ("F031-03", "GRANT statement", "YES"),
    ("F031-04", "ALTER TABLE statement: ADD COLUMN clause", "YES"),
    ("F041", "Basic joined table", "YES"),
    ("F041-01", "Inner join (but not necessarily the INNER keyword)",
     "YES"),
    ("F041-02", "INNER keyword", "YES"),
    ("F041-03", "LEFT OUTER JOIN", "YES"),
    ("F041-04", "RIGHT OUTER JOIN", "YES"),
    ("F041-05", "Outer joins can be nested", "YES"),
    ("F041-07", "The inner table in a left or right outer join can also "
                "be used in an inner join", "YES"),
    ("F051", "Basic date and time", "YES"),
    ("F051-01", "DATE data type", "YES"),
    ("F051-02", "TIME data type", "NO"),
    ("F051-03", "TIMESTAMP data type", "YES"),
    ("F081", "UNION and EXCEPT in views", "YES"),
    ("F131", "Grouped operations", "YES"),
    ("F181", "Multiple module support", "NO"),
    ("F201", "CAST function", "YES"),
    ("F221", "Explicit defaults", "YES"),
    ("F261", "CASE expression", "YES"),
    ("F311", "Schema definition statement", "YES"),
    ("F401", "Extended joined table", "YES"),
    ("F401-01", "NATURAL JOIN", "YES"),
    ("F401-02", "FULL OUTER JOIN", "YES"),
    ("F401-04", "CROSS JOIN", "YES"),
    ("F471", "Scalar subquery values", "YES"),
    ("F481", "Expanded NULL predicate", "YES"),
    ("S071", "SQL paths in function and type name resolution", "NO"),
    ("T031", "BOOLEAN data type", "YES"),
    ("T051", "Row types", "YES"),
    ("T071", "BIGINT data type", "YES"),
    ("T121", "WITH (excluding RECURSIVE) in query expression", "YES"),
    ("T321", "Basic SQL-invoked routines", "NO"),
    ("T611", "Elementary OLAP operations", "YES"),
    ("T621", "Enhanced numeric functions", "YES"),
]


def _info_role_table_grants(db) -> MemTable:
    """information_schema.role_table_grants / table_privileges
    (reference: server/pg/information_schema — ACL rows per grantee)."""
    spec = [("grantor", dt.VARCHAR), ("grantee", dt.VARCHAR),
            ("table_catalog", dt.VARCHAR), ("table_schema", dt.VARCHAR),
            ("table_name", dt.VARCHAR), ("privilege_type", dt.VARCHAR),
            ("is_grantable", dt.VARCHAR)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    roles = db.roles
    with roles._lock:
        acls = {k: {r: set(p) for r, p in v.items()}
                for k, v in roles.acls.items()}
    for tkey, acl in sorted(acls.items()):
        schema, _, tname = tkey.rpartition(".")
        for role, privs in sorted(acl.items()):
            for p in sorted(privs):
                rows["grantor"].append("serene")
                rows["grantee"].append(role)
                rows["table_catalog"].append("serene")
                rows["table_schema"].append(schema or "main")
                rows["table_name"].append(tname)
                rows["privilege_type"].append(p.upper())
                rows["is_grantable"].append("NO")
    return _typed("role_table_grants", spec, rows)


def _info_sql_features() -> MemTable:
    spec = [("feature_id", dt.VARCHAR), ("feature_name", dt.VARCHAR),
            ("sub_feature_id", dt.VARCHAR),
            ("sub_feature_name", dt.VARCHAR),
            ("is_supported", dt.VARCHAR),
            ("is_verified_by", dt.VARCHAR), ("comments", dt.VARCHAR)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    for fid, fname, supported in _SQL_FEATURES:
        # PG keeps the dashed id in feature_id and leaves the
        # sub_feature columns empty strings
        rows["feature_id"].append(fid)
        rows["feature_name"].append(fname)
        rows["sub_feature_id"].append("")
        rows["sub_feature_name"].append("")
        rows["is_supported"].append(supported)
        rows["is_verified_by"].append(None)
        rows["comments"].append(None)
    return _typed("sql_features", spec, rows)


def _info_sql_implementation_info() -> MemTable:
    items = [
        ("10003", "CATALOG NAME", None, "Y"),
        ("10004", "COLLATING SEQUENCE", None, "UCS_BASIC"),
        ("23", "MAXIMUM COLUMN NAME LENGTH", 63, None),
        ("17", "MAXIMUM COLUMNS IN GROUP BY", 0, None),
        ("18", "MAXIMUM COLUMNS IN ORDER BY", 0, None),
        ("19", "MAXIMUM COLUMNS IN SELECT", 0, None),
        ("30", "MAXIMUM ROW SIZE", 0, None),
        ("46", "MAXIMUM TABLE NAME LENGTH", 63, None),
        ("35", "MAXIMUM SCHEMA NAME LENGTH", 63, None),
        ("107", "MAXIMUM USER NAME LENGTH", 63, None),
        ("26", "MAXIMUM IDENTIFIER LENGTH", 63, None),
        ("85", "NULL COLLATION", 0, None),
        ("13", "CORRELATION NAME", None, "Y"),
    ]
    spec = [("implementation_info_id", dt.VARCHAR),
            ("implementation_info_name", dt.VARCHAR),
            ("integer_value", dt.INT), ("character_value", dt.VARCHAR),
            ("comments", dt.VARCHAR)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    for iid, name, iv, cv in items:
        rows["implementation_info_id"].append(iid)
        rows["implementation_info_name"].append(name)
        rows["integer_value"].append(iv)
        rows["character_value"].append(cv)
        rows["comments"].append(None)
    return _typed("sql_implementation_info", spec, rows)


def _info_sql_sizing() -> MemTable:
    items = [
        (34, "MAXIMUM CATALOG NAME LENGTH", 63),
        (30, "MAXIMUM ROW SIZE", 0),
        (25, "MAXIMUM IDENTIFIER LENGTH", 63),
        (97, "MAXIMUM COLUMNS IN TABLE", 1600),
        (99, "MAXIMUM TABLES IN SELECT", 0),
        (20, "MAXIMUM COLUMNS IN GROUP BY", 0),
        (21, "MAXIMUM COLUMNS IN INDEX", 32),
        (22, "MAXIMUM COLUMNS IN ORDER BY", 0),
        (23, "MAXIMUM COLUMNS IN SELECT", 0),
        (100, "MAXIMUM VALUE EXPRESSION LENGTH", 0),
    ]
    spec = [("sizing_id", dt.INT), ("sizing_name", dt.VARCHAR),
            ("supported_value", dt.INT), ("comments", dt.VARCHAR)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    for sid, name, val in items:
        rows["sizing_id"].append(sid)
        rows["sizing_name"].append(name)
        rows["supported_value"].append(val)
        rows["comments"].append(None)
    return _typed("sql_sizing", spec, rows)


def _info_tables(db) -> MemTable:
    rows = db.table_list()
    return _typed("tables", [
        ("table_catalog", dt.VARCHAR), ("table_schema", dt.VARCHAR),
        ("table_name", dt.VARCHAR), ("table_type", dt.VARCHAR),
        ("is_insertable_into", dt.VARCHAR)], {
        "table_catalog": ["serene"] * len(rows),
        "table_schema": [r[0] for r in rows],
        "table_name": [r[1] for r in rows],
        "table_type": ["BASE TABLE" if r[2] == "table" else "VIEW"
                       for r in rows],
        "is_insertable_into": ["YES" if r[2] == "table" else "NO"
                               for r in rows]})


def _info_columns(db) -> MemTable:
    spec = [("table_catalog", dt.VARCHAR), ("table_schema", dt.VARCHAR),
            ("table_name", dt.VARCHAR), ("column_name", dt.VARCHAR),
            ("ordinal_position", dt.INT), ("column_default", dt.VARCHAR),
            ("is_nullable", dt.VARCHAR), ("data_type", dt.VARCHAR),
            ("character_maximum_length", dt.INT),
            ("numeric_precision", dt.INT), ("udt_name", dt.VARCHAR)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    with db.lock:
        for sname, s in db.schemas.items():
            for tname, t in s.tables.items():
                nn = set((getattr(t, "table_meta", {}) or {}).get(
                    "not_null", []))
                pk = set((getattr(t, "table_meta", {}) or {}).get(
                    "primary_key", []))
                for pos, (cn, ct) in enumerate(
                        zip(t.column_names, t.column_types), 1):
                    rows["table_catalog"].append("serene")
                    rows["table_schema"].append(sname)
                    rows["table_name"].append(tname)
                    rows["column_name"].append(cn)
                    rows["ordinal_position"].append(pos)
                    rows["column_default"].append(None)
                    rows["is_nullable"].append(
                        "NO" if (cn in nn or cn in pk) else "YES")
                    rows["data_type"].append(
                        format_type_oid(type_oid_of(ct)))
                    rows["character_maximum_length"].append(None)
                    rows["numeric_precision"].append(None)
                    rows["udt_name"].append(
                        type_name_of(type_oid_of(ct)) or "text")
    return _typed("columns", spec, rows)


def _info_schemata(db) -> MemTable:
    with db.lock:
        names = sorted(db.schemas)
    names += ["pg_catalog", "information_schema"]
    return _typed("schemata", [
        ("catalog_name", dt.VARCHAR), ("schema_name", dt.VARCHAR),
        ("schema_owner", dt.VARCHAR)], {
        "catalog_name": ["serene"] * len(names), "schema_name": names,
        "schema_owner": ["serene"] * len(names)})


def _info_table_constraints(db) -> MemTable:
    spec = [("constraint_catalog", dt.VARCHAR),
            ("constraint_schema", dt.VARCHAR),
            ("constraint_name", dt.VARCHAR), ("table_schema", dt.VARCHAR),
            ("table_name", dt.VARCHAR), ("constraint_type", dt.VARCHAR)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    with db.lock:
        for sname, s in db.schemas.items():
            for tname, t in s.tables.items():
                pk = (getattr(t, "table_meta", {}) or {}).get(
                    "primary_key") or []
                if not pk:
                    continue
                rows["constraint_catalog"].append("serene")
                rows["constraint_schema"].append(sname)
                rows["constraint_name"].append(f"{tname}_pkey")
                rows["table_schema"].append(sname)
                rows["table_name"].append(tname)
                rows["constraint_type"].append("PRIMARY KEY")
    return _typed("table_constraints", spec, rows)


def _info_key_column_usage(db) -> MemTable:
    spec = [("constraint_name", dt.VARCHAR), ("table_schema", dt.VARCHAR),
            ("table_name", dt.VARCHAR), ("column_name", dt.VARCHAR),
            ("ordinal_position", dt.INT)]
    rows: dict[str, list] = {c: [] for c, _ in spec}
    with db.lock:
        for sname, s in db.schemas.items():
            for tname, t in s.tables.items():
                pk = (getattr(t, "table_meta", {}) or {}).get(
                    "primary_key") or []
                for i, cn in enumerate(pk, 1):
                    rows["constraint_name"].append(f"{tname}_pkey")
                    rows["table_schema"].append(sname)
                    rows["table_name"].append(tname)
                    rows["column_name"].append(cn)
                    rows["ordinal_position"].append(i)
    return _typed("key_column_usage", spec, rows)


# empty-but-typed catalogs: psql/ORM queries join them; zero rows is the
# truthful answer (no toast tables, no triggers, no row policies, ...)
_EMPTY_TABLES: dict[str, list[tuple[str, dt.SqlType]]] = {
    "pg_description": [("objoid", dt.OID), ("classoid", dt.OID),
                       ("objsubid", dt.INT), ("description", dt.VARCHAR)],
    "pg_shdescription": [("objoid", dt.OID), ("classoid", dt.OID),
                         ("description", dt.VARCHAR)],
    "pg_attrdef": [("oid", dt.OID), ("adrelid", dt.OID),
                   ("adnum", dt.SMALLINT), ("adbin", dt.VARCHAR)],
    "pg_trigger": [("oid", dt.OID), ("tgrelid", dt.OID),
                   ("tgname", dt.VARCHAR), ("tgfoid", dt.OID),
                   ("tgtype", dt.SMALLINT), ("tgenabled", dt.VARCHAR),
                   ("tgisinternal", dt.BOOL)],
    "pg_rewrite": [("oid", dt.OID), ("rulename", dt.VARCHAR),
                   ("ev_class", dt.OID), ("ev_type", dt.VARCHAR)],
    "pg_policy": [("oid", dt.OID), ("polname", dt.VARCHAR),
                  ("polrelid", dt.OID)],
    "pg_inherits": [("inhrelid", dt.OID), ("inhparent", dt.OID),
                    ("inhseqno", dt.INT)],
    "pg_enum": [("oid", dt.OID), ("enumtypid", dt.OID),
                ("enumsortorder", dt.FLOAT), ("enumlabel", dt.VARCHAR)],
    "pg_range": [("rngtypid", dt.OID), ("rngsubtype", dt.OID)],
    "pg_locks": [("locktype", dt.VARCHAR), ("database", dt.OID),
                 ("relation", dt.OID), ("pid", dt.INT),
                 ("mode", dt.VARCHAR), ("granted", dt.BOOL)],
    "pg_extension": [("oid", dt.OID), ("extname", dt.VARCHAR),
                     ("extowner", dt.OID), ("extnamespace", dt.OID),
                     ("extversion", dt.VARCHAR)],
    "pg_depend": [("classid", dt.OID), ("objid", dt.OID),
                  ("objsubid", dt.INT), ("refclassid", dt.OID),
                  ("refobjid", dt.OID), ("refobjsubid", dt.INT),
                  ("deptype", dt.VARCHAR)],
    "pg_event_trigger": [("oid", dt.OID), ("evtname", dt.VARCHAR)],
    "pg_foreign_server": [("oid", dt.OID), ("srvname", dt.VARCHAR)],
    "pg_foreign_table": [("ftrelid", dt.OID), ("ftserver", dt.OID)],
    "pg_foreign_data_wrapper": [("oid", dt.OID), ("fdwname", dt.VARCHAR)],
    "pg_partitioned_table": [("partrelid", dt.OID),
                             ("partstrat", dt.VARCHAR)],
    "pg_publication": [("oid", dt.OID), ("pubname", dt.VARCHAR)],
    "pg_subscription": [("oid", dt.OID), ("subname", dt.VARCHAR)],
    "pg_auth_members": [("roleid", dt.OID), ("member", dt.OID),
                        ("grantor", dt.OID), ("admin_option", dt.BOOL)],
    "pg_tablespace": [("oid", dt.OID), ("spcname", dt.VARCHAR),
                      ("spcowner", dt.OID)],
    "pg_collation": [("oid", dt.OID), ("collname", dt.VARCHAR),
                     ("collnamespace", dt.OID),
                     ("collcollate", dt.VARCHAR)],
    "pg_matviews": [("schemaname", dt.VARCHAR), ("matviewname", dt.VARCHAR),
                    ("matviewowner", dt.VARCHAR),
                    ("definition", dt.VARCHAR)],
    "pg_statio_user_tables": [("relid", dt.OID),
                              ("schemaname", dt.VARCHAR),
                              ("relname", dt.VARCHAR),
                              ("heap_blks_read", dt.BIGINT),
                              ("heap_blks_hit", dt.BIGINT)],
    "referential_constraints": [("constraint_catalog", dt.VARCHAR),
                                ("constraint_schema", dt.VARCHAR),
                                ("constraint_name", dt.VARCHAR),
                                ("unique_constraint_name", dt.VARCHAR)],
    "routines": [("routine_catalog", dt.VARCHAR),
                 ("routine_schema", dt.VARCHAR),
                 ("routine_name", dt.VARCHAR),
                 ("routine_type", dt.VARCHAR),
                 ("data_type", dt.VARCHAR)],
    "character_sets": [("character_set_catalog", dt.VARCHAR),
                       ("character_set_schema", dt.VARCHAR),
                       ("character_set_name", dt.VARCHAR)],
}

_BUILDERS: dict[str, Callable] = {
    "pg_namespace": _pg_namespace,
    "pg_class": _pg_class,
    "pg_attribute": _pg_attribute,
    "pg_index": _pg_index,
    "pg_am": _pg_am,
    "pg_constraint": _pg_constraint,
    "pg_type": _pg_type,
    "pg_proc": _pg_proc,
    "pg_roles": _pg_roles,
    "pg_user": _pg_roles,
    "pg_authid": _pg_roles,
    "pg_shadow": _pg_roles,
    "pg_database": _pg_database,
    "pg_tables": _pg_tables,
    "pg_views": _pg_views,
    "pg_indexes": _pg_indexes,
    "pg_sequences": _pg_sequences,
    "pg_stat_user_tables": _pg_stat_user_tables,
    "pg_stat_activity": _pg_stat_activity,
    "pg_settings": _pg_settings,
    "schemata": _info_schemata,
    "table_constraints": _info_table_constraints,
    "key_column_usage": _info_key_column_usage,
    "role_table_grants": lambda db: _info_role_table_grants(db),
    "table_privileges": lambda db: _info_role_table_grants(db),
    "sql_features": lambda db: _info_sql_features(),
    "sql_implementation_info": lambda db: _info_sql_implementation_info(),
    "sql_sizing": lambda db: _info_sql_sizing(),
}


def system_table(db, parts: list[str]) -> Optional[TableProvider]:
    name = parts[-1].lower()
    schema = parts[-2].lower() if len(parts) >= 2 else None
    if schema is not None and schema not in ("pg_catalog",
                                             "information_schema",
                                             "sdb_catalog"):
        return None
    # information_schema.tables/columns shadow unqualified pg names
    if name == "tables" and schema == "information_schema":
        return _info_tables(db)
    if name == "columns" and (schema == "information_schema" or
                              schema is None):
        return _info_columns(db)
    if name == "views" and schema == "information_schema":
        v = _pg_views(db)
        b = v.full_batch(None)
        return MemTable("views", Batch(
            ["table_catalog", "table_schema", "table_name",
             "view_definition"],
            [Column.from_pylist(["serene"] * b.num_rows, dt.VARCHAR),
             b.column("schemaname"), b.column("viewname"),
             b.column("definition")]))
    if name == "sequences" and schema == "information_schema":
        s = _pg_sequences(db)
        b = s.full_batch(None)
        return MemTable("sequences", Batch(
            ["sequence_catalog", "sequence_schema", "sequence_name",
             "data_type"],
            [Column.from_pylist(["serene"] * b.num_rows, dt.VARCHAR),
             b.column("schemaname"), b.column("sequencename"),
             b.column("data_type")]))
    builder = _BUILDERS.get(name)
    if builder is not None:
        return builder(db)
    if name in _EMPTY_TABLES:
        return _typed(name, _EMPTY_TABLES[name], {})
    if name.startswith("pg_stat_progress"):
        from .utils.progress import REGISTRY as _progress
        recs = _progress.snapshot()
        return _typed(name, [
            ("pid", dt.INT), ("command", dt.VARCHAR), ("phase", dt.VARCHAR),
            ("tuples_done", dt.BIGINT), ("tuples_total", dt.BIGINT)], {
            "pid": [r["pid"] for r in recs],
            "command": [r["command"] for r in recs],
            "phase": [r["phase"] for r in recs],
            "tuples_done": [r["done"] for r in recs],
            "tuples_total": [r["total"] for r in recs]})
    if name == "sdb_indexes":
        rows = {"schema": [], "table": [], "index": [], "type": [],
                "columns": [], "segments": [], "indexed_rows": [],
                "fresh": []}
        with db.lock:
            for sname, s in db.schemas.items():
                for tname, t in s.tables.items():
                    for iname, idx in getattr(t, "indexes", {}).items():
                        rows["schema"].append(sname)
                        rows["table"].append(tname)
                        rows["index"].append(iname)
                        rows["type"].append(idx.using)
                        rows["columns"].append(",".join(idx.columns))
                        segs = max((len(ms.segments) for ms in
                                    getattr(idx, "searchers", {}).values()),
                                   default=1)
                        rows["segments"].append(segs)
                        rows["indexed_rows"].append(
                            getattr(idx, "indexed_rows", t.row_count()))
                        rows["fresh"].append(
                            idx.data_version == t.data_version)
        return MemTable("sdb_indexes", Batch.from_pydict(rows))
    if name == "sdb_settings":
        names = _settings_registry.names()
        return MemTable("sdb_settings", Batch.from_pydict({
            "name": names,
            "setting": [str(_settings_registry.get_global(n))
                        for n in names],
            "description": [_settings_registry.definition(n).description
                            for n in names],
        }))
    if name == "sdb_metrics":
        return metrics_table()
    if name == "sdb_log":
        return log_table()
    if name == "sdb_stat_statements":
        return stat_statements_table()
    if name == "sdb_cache":
        return cache_table()
    if name == "sdb_trace":
        return trace_table([])
    if name == "sdb_query_progress":
        return query_progress_table()
    if name == "sdb_admission":
        return admission_table()
    if name == "sdb_connections":
        return connections_table()
    if name == "sdb_device":
        return device_table()
    if name == "sdb_programs":
        return programs_table()
    if name == "sdb_device_cache":
        return device_cache_table()
    if name == "sdb_posting_pool":
        return posting_pool_table()
    return None


def device_table() -> TableProvider:
    """sdb_device: one row per physical jax device — dispatches
    executed, transfer bytes/time host→device and device→host, and the
    HBM live-bytes estimate (device column cache occupancy split per
    holding device). The device telemetry ledger (obs/device.py,
    serene_device_telemetry); empty counters when telemetry is off."""
    from .obs.device import device_rows
    rows = device_rows()
    return _typed("sdb_device", [
        ("device", dt.INT), ("platform", dt.VARCHAR),
        ("kind", dt.VARCHAR), ("dispatches", dt.BIGINT),
        ("bytes_up", dt.BIGINT), ("transfers_up", dt.BIGINT),
        ("up_ms", dt.DOUBLE), ("bytes_down", dt.BIGINT),
        ("transfers_down", dt.BIGINT), ("down_ms", dt.DOUBLE),
        ("hbm_bytes_est", dt.BIGINT)], {
        "device": [r["device"] for r in rows],
        "platform": [r["platform"] for r in rows],
        "kind": [r["kind"] for r in rows],
        "dispatches": [r["dispatches"] for r in rows],
        "bytes_up": [r["bytes_up"] for r in rows],
        "transfers_up": [r["transfers_up"] for r in rows],
        "up_ms": [r["up_ms"] for r in rows],
        "bytes_down": [r["bytes_down"] for r in rows],
        "transfers_down": [r["transfers_down"] for r in rows],
        "down_ms": [r["down_ms"] for r in rows],
        "hbm_bytes_est": [r["hbm_bytes_est"] for r in rows]})


def programs_table() -> TableProvider:
    """sdb_programs: the XLA compile ledger — one row per program
    family (fused / fused_build / fused_probe / fused_collective /
    fused_topn / device_agg / device_topn / mesh_* / search programs)
    with live entry counts, cumulative compiles, cache hit/miss totals,
    LRU evictions, recompile-storm count, and compile wall time
    (first-dispatch trace)."""
    from .obs.device import PROGRAMS
    rows = PROGRAMS.snapshot()
    return _typed("sdb_programs", [
        ("family", dt.VARCHAR), ("entries", dt.BIGINT),
        ("compiles", dt.BIGINT), ("hits", dt.BIGINT),
        ("misses", dt.BIGINT), ("evictions", dt.BIGINT),
        ("storms", dt.BIGINT), ("compile_ms_total", dt.DOUBLE),
        ("compile_ms_mean", dt.DOUBLE), ("last_compile_ms", dt.DOUBLE)], {
        "family": [r["family"] for r in rows],
        "entries": [r["entries"] for r in rows],
        "compiles": [r["compiles"] for r in rows],
        "hits": [r["hits"] for r in rows],
        "misses": [r["misses"] for r in rows],
        "evictions": [r["evictions"] for r in rows],
        "storms": [r["storms"] for r in rows],
        "compile_ms_total": [r["compile_ms_total"] for r in rows],
        "compile_ms_mean": [r["compile_ms_mean"] for r in rows],
        "last_compile_ms": [r["last_compile_ms"] for r in rows]})


def device_cache_table() -> TableProvider:
    """sdb_device_cache: one row per live DEVICE_CACHE entry — which
    publication (table/version/epoch) and column occupies HBM, the
    entry kind (col = column tiles, arr = code/rowmask/build-output
    arrays), bytes, holding devices, hit count and idle time. The
    per-publication occupancy view the paged-postings roadmap item
    tunes against."""
    from .obs.device import device_cache_rows
    rows = device_cache_rows()
    return _typed("sdb_device_cache", [
        ("table_name", dt.VARCHAR), ("token", dt.BIGINT),
        ("data_version", dt.BIGINT), ("mutation_epoch", dt.BIGINT),
        ("column_name", dt.VARCHAR), ("kind", dt.VARCHAR),
        ("tag", dt.VARCHAR), ("bytes", dt.BIGINT),
        ("devices", dt.VARCHAR), ("hits", dt.BIGINT),
        ("idle_ms", dt.DOUBLE)], {
        "table_name": [r["table"] for r in rows],
        "token": [r["token"] for r in rows],
        "data_version": [r["data_version"] for r in rows],
        "mutation_epoch": [r["mutation_epoch"] for r in rows],
        "column_name": [r["column"] for r in rows],
        "kind": [r["kind"] for r in rows],
        "tag": [r["tag"] for r in rows],
        "bytes": [r["bytes"] for r in rows],
        "devices": [r["devices"] for r in rows],
        "hits": [r["hits"] for r in rows],
        "idle_ms": [r["idle_ms"] for r in rows]})


def posting_pool_table() -> TableProvider:
    """sdb_posting_pool: one row per (publication, segment) group of
    resident posting-pool terms — which table/version/epoch occupies
    the paged HBM region, how many terms/pages/bytes it holds, hit
    counts and idle time. The occupancy view operators size
    `serene_posting_pages` from (search/posting_pool.py)."""
    from .obs.device import provider_name
    from .search.posting_pool import POOL
    rows = POOL.snapshot()
    return _typed("sdb_posting_pool", [
        ("table_name", dt.VARCHAR), ("token", dt.BIGINT),
        ("data_version", dt.BIGINT), ("mutation_epoch", dt.BIGINT),
        ("segment", dt.BIGINT), ("terms", dt.BIGINT),
        ("pages", dt.BIGINT), ("bytes", dt.BIGINT),
        ("hits", dt.BIGINT), ("idle_ms", dt.DOUBLE)], {
        "table_name": [provider_name(r["token"]) for r in rows],
        "token": [r["token"] for r in rows],
        "data_version": [r["data_version"] for r in rows],
        "mutation_epoch": [r["mutation_epoch"] for r in rows],
        "segment": [r["segment"] for r in rows],
        "terms": [r["terms"] for r in rows],
        "pages": [r["pages"] for r in rows],
        "bytes": [r["bytes"] for r in rows],
        "hits": [r["hits"] for r in rows],
        "idle_ms": [r["idle_ms"] for r in rows]})


def cache_table() -> TableProvider:
    """sdb_cache: one row per live cache entry across both tiers —
    result entries carry their normalized query text and source tables,
    fragment entries their segment + shape digest."""
    from .cache.fragments import FRAGMENTS
    from .cache.result import RESULT_CACHE
    rows = RESULT_CACHE.snapshot() + FRAGMENTS.snapshot()
    return _typed("sdb_cache", [
        ("tier", dt.VARCHAR), ("key", dt.VARCHAR), ("query", dt.VARCHAR),
        ("queryid", dt.BIGINT), ("bytes", dt.BIGINT), ("hits", dt.BIGINT),
        ("rows", dt.BIGINT), ("objects", dt.VARCHAR)], {
        "tier": [e["tier"] for e in rows],
        "key": [e["key"] for e in rows],
        "query": [e["query"] for e in rows],
        "queryid": [e["queryid"] for e in rows],
        "bytes": [e["bytes"] for e in rows],
        "hits": [e["hits"] for e in rows],
        "rows": [e["rows"] for e in rows],
        "objects": [e["objects"] for e in rows]})


def stat_statements_table() -> TableProvider:
    """sdb_stat_statements: cumulative stats per normalized statement
    fingerprint (obs/statements.py), PG pg_stat_statements column
    shapes where they map, plus per-fingerprint latency percentiles
    derived from the entry's log-spaced histogram sketch. LRU-capped by
    serene_stat_statements_max."""
    from .obs.statements import STATEMENTS
    rows = STATEMENTS.snapshot()
    return _typed("sdb_stat_statements", [
        ("queryid", dt.BIGINT), ("query", dt.VARCHAR),
        ("calls", dt.BIGINT), ("total_time_ms", dt.DOUBLE),
        ("mean_time_ms", dt.DOUBLE), ("min_time_ms", dt.DOUBLE),
        ("max_time_ms", dt.DOUBLE), ("p50_time_ms", dt.DOUBLE),
        ("p95_time_ms", dt.DOUBLE), ("p99_time_ms", dt.DOUBLE),
        ("rows", dt.BIGINT),
        ("morsels_pruned", dt.BIGINT), ("cache_hits", dt.BIGINT),
        ("peak_mem_bytes", dt.BIGINT),
        ("last_peak_mem_bytes", dt.BIGINT)], {
        "queryid": [e["queryid"] for e in rows],
        "query": [e["query"] for e in rows],
        "calls": [e["calls"] for e in rows],
        "total_time_ms": [round(e["total_ms"], 6) for e in rows],
        "mean_time_ms": [round(e["total_ms"] / e["calls"], 6)
                         for e in rows],
        "min_time_ms": [round(e["min_ms"], 6) for e in rows],
        "max_time_ms": [round(e["max_ms"], 6) for e in rows],
        "p50_time_ms": [e.get("p50_ms", 0.0) for e in rows],
        "p95_time_ms": [e.get("p95_ms", 0.0) for e in rows],
        "p99_time_ms": [e.get("p99_ms", 0.0) for e in rows],
        "rows": [e["rows"] for e in rows],
        "morsels_pruned": [e["morsels_pruned"] for e in rows],
        "cache_hits": [e.get("cache_hits", 0) for e in rows],
        # max / most-recent accounted peak bytes across this
        # fingerprint's calls (0 when serene_mem_account was off)
        "peak_mem_bytes": [e.get("peak_mem_bytes", 0) for e in rows],
        "last_peak_mem_bytes": [e.get("last_peak_mem_bytes", 0)
                                for e in rows]})


def trace_table(args: list) -> TableProvider:
    """sdb_trace: the flight recorder as a relation. With no argument,
    one row per recorded query timeline (newest last — the listing to
    find a trace id). With a trace id argument, one row per span of
    that timeline, begin-ordered; unknown ids yield an empty relation
    (the entry may have aged out of the ring)."""
    import json as _json

    from .obs.trace import FLIGHT
    if not args or args[0] is None:
        entries = FLIGHT.snapshot()
        return _typed("sdb_trace", [
            ("trace_id", dt.BIGINT), ("query", dt.VARCHAR),
            ("duration_ms", dt.DOUBLE), ("spans", dt.BIGINT),
            ("spans_dropped", dt.BIGINT), ("peak_bytes", dt.BIGINT),
            ("error", dt.VARCHAR)], {
            "trace_id": [e["trace_id"] for e in entries],
            "query": [e["query"] for e in entries],
            "duration_ms": [round(e["duration_ns"] / 1e6, 3)
                            for e in entries],
            "spans": [len(e["spans"]) for e in entries],
            "spans_dropped": [e["spans_dropped"] for e in entries],
            # accounted peak memory of the statement (NULL when
            # serene_mem_account was off for it) — a memory-heavy
            # query is findable in the recorder after the fact
            "peak_bytes": [e.get("peak_bytes") for e in entries],
            "error": [e["error"] or "" for e in entries]})
    try:
        tid = int(args[0])
    except (TypeError, ValueError):
        raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                              "sdb_trace(id) requires an integer trace id")
    entry = FLIGHT.get(tid)
    spans = entry["spans"] if entry is not None else []
    return _typed("sdb_trace", [
        ("trace_id", dt.BIGINT), ("span", dt.VARCHAR),
        ("category", dt.VARCHAR), ("thread", dt.VARCHAR),
        ("begin_ms", dt.DOUBLE), ("end_ms", dt.DOUBLE),
        ("duration_ms", dt.DOUBLE), ("detail", dt.VARCHAR)], {
        "trace_id": [tid] * len(spans),
        "span": [s["name"] for s in spans],
        "category": [s["cat"] for s in spans],
        "thread": [str(s["thread"]) for s in spans],
        "begin_ms": [round(s["begin_ns"] / 1e6, 4) for s in spans],
        "end_ms": [round(s["end_ns"] / 1e6, 4) for s in spans],
        "duration_ms": [round((s["end_ns"] - s["begin_ns"]) / 1e6, 4)
                        for s in spans],
        "detail": [_json.dumps(s["args"]) if s["args"] else ""
                   for s in spans]})


def query_progress_table() -> TableProvider:
    """sdb_query_progress: one row per RUNNING statement — its current
    operator, morsels scheduled/completed, rows and bytes processed so
    far, live/peak accounted bytes and elapsed time (the
    pg_stat_progress_* analog for query execution, fed live from the
    obs/resources ACTIVE registry; requires serene_mem_account). The
    statement reading this view is itself running, so it appears in
    its own output (PG pg_stat_activity semantics)."""
    from .obs.resources import ACTIVE
    rows = ACTIVE.snapshot()
    return _typed("sdb_query_progress", [
        ("pid", dt.INT), ("query_id", dt.BIGINT), ("query", dt.VARCHAR),
        ("operator", dt.VARCHAR), ("morsels_scheduled", dt.BIGINT),
        ("morsels_done", dt.BIGINT), ("rows", dt.BIGINT),
        ("bytes", dt.BIGINT), ("live_bytes", dt.BIGINT),
        ("peak_bytes", dt.BIGINT), ("elapsed_ms", dt.DOUBLE)], {
        "pid": [r["pid"] for r in rows],
        "query_id": [r["query_id"] for r in rows],
        "query": [r["query"] for r in rows],
        "operator": [r["operator"] for r in rows],
        "morsels_scheduled": [r["morsels_scheduled"] for r in rows],
        "morsels_done": [r["morsels_done"] for r in rows],
        "rows": [r["rows"] for r in rows],
        "bytes": [r["bytes"] for r in rows],
        "live_bytes": [r["live_bytes"] for r in rows],
        "peak_bytes": [r["peak_bytes"] for r in rows],
        "elapsed_ms": [r["elapsed_ms"] for r in rows]})


def admission_table() -> TableProvider:
    """sdb_admission: the workload governor's one-row live view —
    statements running vs queued against the configured limits plus
    cumulative admission totals (sched/governor.py). An sdb_* relation
    on purpose: reads of it are admission-EXEMPT, so an operator can
    inspect a saturated governor without queueing behind it."""
    from .sched.governor import GOVERNOR
    s = GOVERNOR.snapshot()
    return _typed("sdb_admission", [
        ("running", dt.BIGINT), ("queued", dt.BIGINT),
        ("max_concurrent_statements", dt.BIGINT),
        ("queue_depth", dt.BIGINT), ("queued_total", dt.BIGINT),
        ("rejected_total", dt.BIGINT), ("wait_ns_total", dt.BIGINT),
        ("preemptions_total", dt.BIGINT)], {
        "running": [s["running"]], "queued": [s["queued"]],
        "max_concurrent_statements": [s["max_concurrent_statements"]],
        "queue_depth": [s["queue_depth"]],
        "queued_total": [s["queued_total"]],
        "rejected_total": [s["rejected_total"]],
        "wait_ns_total": [s["wait_ns_total"]],
        "preemptions_total": [s["preemptions_total"]]})


def connections_table() -> TableProvider:
    """sdb_connections: one row per open front-door socket — the
    pg_stat_activity analog for the SOCKET layer (sched/governor.py
    ConnectionGate). pid is a process-unique virtual backend id,
    protocol the frontend (pg | http), state the coarse machine
    (active ⇄ idle), idle_s the seconds since the last byte arrived
    on an idle connection. An sdb_* relation on purpose: reads are
    admission-exempt, so an operator can inspect a saturated front
    door without queueing behind it."""
    from .sched.governor import CONNGATE
    rows = CONNGATE.rows()
    return _typed("sdb_connections", [
        ("pid", dt.BIGINT), ("protocol", dt.VARCHAR),
        ("state", dt.VARCHAR), ("idle_s", dt.DOUBLE),
        ("peer", dt.VARCHAR), ("connected_s", dt.DOUBLE),
        ("buffered_bytes", dt.BIGINT)], {
        "pid": [r["pid"] for r in rows],
        "protocol": [r["protocol"] for r in rows],
        "state": [r["state"] for r in rows],
        "idle_s": [r["idle_s"] for r in rows],
        "peer": [r["peer"] for r in rows],
        "connected_s": [r["connected_s"] for r in rows],
        "buffered_bytes": [r["buffered_bytes"] for r in rows]})


def metrics_table() -> TableProvider:
    from .obs.resources import sample_process_gauges
    sample_process_gauges()
    gs = _metrics.REGISTRY.all()
    return MemTable("sdb_metrics", Batch.from_pydict({
        "metric": [g.name for g in gs],
        "value": [g.value for g in gs],
        "description": [g.description for g in gs],
    }))


def log_table() -> TableProvider:
    recs = _log.MANAGER.records()
    return MemTable("sdb_log", Batch.from_pydict({
        "ts": [r.ts for r in recs],
        "level": [r.level.name for r in recs],
        "topic": [r.topic for r in recs],
        "message": [r.message for r in recs],
    }))
