"""serenedb_tpu — a TPU-native real-time search analytics database framework.

Capability oracle: serenedb/serenedb (single-node, Postgres-wire-compatible
"Elasticsearch + ClickHouse" database; see /root/reference and SURVEY.md).
This implementation is architected TPU-first: columnar scan/filter/aggregate
and posting-block BM25/top-k scoring run as JAX/XLA/Pallas kernels on
HBM-resident column batches, with a CPU reference path for parity.

Layer map (mirrors SURVEY.md §1, re-expressed for TPU):

  server/    PG wire + ES-compatible HTTP frontends
  sql/       lexer / parser / binder / logical planner / optimizer
  exec/      physical operators; routes column batches to ops/ kernels
  ops/       JAX + Pallas kernels (filter, hash-agg, BM25, top-k, vector)
  search/    inverted index segments, analyzers, scorers (IResearch analog)
  storage/   WAL, segment persistence, refresh/compaction, recovery
  catalog/   versioned snapshot catalog, RBAC, persistence
  columnar/  column batch ABI (the HBM-friendly data layout)
  parallel/  device-mesh sharding of scans/aggregates/scoring
  sched/     workload governor: admission control, statement identity
  utils/     config, logging, metrics, fault injection, ticks
"""

__version__ = "0.1.0"


def build_id() -> str:
    """Version + git revision, the reference's build-id stamp analog
    (libs/basics/build_id). The revision is taken only when this package
    itself lives inside the git checkout (a venv nested under someone
    else's repo must not report that repo's HEAD); 'unknown' otherwise."""
    import os
    import subprocess
    rev = "unknown"
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=5, cwd=pkg_root)
        if top.returncode == 0 and \
                os.path.realpath(top.stdout.strip()) == \
                os.path.realpath(pkg_root):
            r = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5, cwd=pkg_root)
            if r.returncode == 0 and r.stdout.strip():
                rev = r.stdout.strip()
    except Exception:  # noqa: BLE001 — build id must never break boot
        pass
    return f"serenedb-tpu {__version__} ({rev})"

# Import pyarrow EAGERLY, on whatever thread first imports this package
# (normally the main thread). pyarrow's C++ initialization must not happen
# lazily inside a short-lived request/worker thread: when the importing
# thread exits, subsequent parquet reads from other threads segfault in
# this image's pyarrow build (reproduced: COPY ... (FORMAT parquet) on an
# HTTP worker thread, then read_parquet() anywhere → SIGSEGV in
# ParquetFile.read). Engine code may still `import pyarrow` locally for
# namespacing — those become no-op cache hits after this.
import pyarrow  # noqa: E402,F401
import pyarrow.parquet  # noqa: E402,F401
