"""Bytes-bounded LRU — the shared store under both cache tiers.

One lock, short critical sections (dict moves and integer bookkeeping;
values are stored by reference, never copied here). Recency is
last-ACCESS order: a get refreshes the entry, so a hot dashboard query
survives a scan of one-off statements.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Iterator, Optional


class _Entry:
    __slots__ = ("value", "nbytes", "hits")

    def __init__(self, value, nbytes: int):
        self.value = value
        self.nbytes = int(nbytes)
        self.hits = 0


class BytesLRU:
    """key → value with a byte budget. `on_evict(key, entry)` fires for
    every removal that is NOT an explicit caller `remove`/`clear` —
    callers use it to keep gauges honest."""

    def __init__(self, on_evict: Optional[Callable] = None):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[object, _Entry]" = OrderedDict()
        self._bytes = 0
        self._on_evict = on_evict

    def get(self, key):
        """The entry's value on a hit (recency refreshed), else None."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            e.hits += 1
            return e.value

    def put(self, key, value, nbytes: int, cap_bytes: int,
            cap_entries: int = 0) -> bool:
        """Insert/replace and evict LRU entries past `cap_bytes` (and
        past `cap_entries` when > 0 — many tiny entries cost sweep and
        lookup time even under the byte budget). A value larger than
        the whole cap is refused (False) — caching it would just evict
        everything else for a single entry."""
        nbytes = int(nbytes)
        if nbytes > cap_bytes:
            return False
        evicted = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _Entry(value, nbytes)
            self._bytes += nbytes
            while self._entries and (
                    self._bytes > cap_bytes or
                    (cap_entries and len(self._entries) > cap_entries)):
                k, e = self._entries.popitem(last=False)
                self._bytes -= e.nbytes
                evicted.append((k, e))
        if self._on_evict is not None:
            for k, e in evicted:
                self._on_evict(k, e)
        return True

    def remove(self, key) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._bytes -= e.nbytes
            return e

    def evict_where(self, pred: Callable) -> int:
        """Remove every entry where pred(key, entry) is true (the lazy
        sweep of superseded generations); fires on_evict per entry."""
        with self._lock:
            dead = [(k, e) for k, e in self._entries.items()
                    if pred(k, e)]
            for k, e in dead:
                del self._entries[k]
                self._bytes -= e.nbytes
        if self._on_evict is not None:
            for k, e in dead:
                self._on_evict(k, e)
        return len(dead)

    def items(self) -> Iterator[tuple]:
        """Point-in-time (key, entry) snapshot, LRU first."""
        with self._lock:
            return list(self._entries.items())

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
