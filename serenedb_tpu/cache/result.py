"""Tier 1: whole-statement result cache with publication-keyed
invalidation.

Reference analog: the reused whole-request results a search engine
serves repeated dashboard traffic from. A read-only statement whose
plan touches only IMMUTABLE expressions (functions/volatility.py) and
catalog tables is keyed by everything its result is a function of:

    (statement digest,             canonical AST repr — distinguishes
                                   literal values and statements that
                                   share one multi-statement text
     bound parameter values,
     result-affecting settings digest,
     sorted per-table publication tuples)

where a publication tuple is (catalog key, publication token,
data_version, mutation_epoch) — the token is a process-unique id
attached to the provider, so a DROP + CREATE of a same-named table can
never collide with the old generation's entries.

Invalidation proof sketch: the executor pins each table's publication
atomically (MemTable._pub); versions are monotone. The probe observes
every table's publication BEFORE execution and again AFTER — the entry
is stored only when both observations are equal, so the cached batch is
exactly the result of evaluating the statement against the keyed
publications. A later lookup builds its key from the CURRENT
publications; any interleaved write bumped a version, the keys differ,
and the stale entry is unreachable forever (a lazy sweep reclaims its
bytes). Therefore a hit returns bit-identical data to a fresh
execution, at any `serene_workers`, and a write between two identical
statements always surfaces fresh data.

The statement → table-set map learned at store time powers a fast path
that skips parse-free replanning entirely on repeat traffic: resolve
the remembered catalog keys, re-check ACLs, observe publications, and
serve. Any resolution hiccup (rename, drop, revoke, new generation)
falls back to the full plan path.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import weakref
from collections import OrderedDict
from typing import Optional

from ..functions.volatility import IMMUTABLE, volatility
from ..utils import metrics
from ..utils.config import REGISTRY as _settings_registry
from .lru import BytesLRU

#: session settings whose value changes what a result CONTAINS (device
#: summation order, ANN probe counts, scored-term expansion caps) — part
#: of the key, so two sessions with different knobs never share entries.
#: serene_search_batch is deliberately ABSENT: the search batcher's
#: contract is per-query bit-identity with serial dispatch (scores, doc
#: ids, tie order — enforced by the tests/test_search_batch.py parity
#: matrix and the verify_tier1.sh SERENE_SEARCH_BATCH=off pass), so
#: keying on it would only split the cache between identical entries.
#: serene_shards is deliberately ABSENT for the same reason: the
#: sharded execution tier's contract is bit-identity with shards=1 at
#: any worker/device count (the tests/test_shard_exec.py parity matrix
#: and the verify_tier1.sh SERENE_SHARDS=4 pass enforce it), so keying
#: on it would only split the cache between identical entries.
RESULT_AFFECTING_SETTINGS = (
    "serene_device", "serene_device_min_rows", "serene_device_chunk_rows",
    "serene_device_fused", "serene_mesh", "sdb_nprobe", "sdb_rerank_factor",
    "sdb_scored_terms_limit", "search_path",
    # serene_nprobe (and its compat alias sdb_nprobe above) changes
    # which rows a knn RETURNS — more probes, higher recall; and
    # serene_maxsim switches vec_maxsim between f32 device scoring and
    # the f64 host oracle, which can reorder near-tied docs
    "serene_nprobe", "serene_maxsim",
)
assert "serene_search_batch" not in RESULT_AFFECTING_SETTINGS
assert "serene_shards" not in RESULT_AFFECTING_SETTINGS
# serene_shard_combine picks WHERE the cross-shard combine runs (one
# in-program shard_map dispatch with psum/pmin/pmax vs per-shard
# dispatches with the host integer combine) — every accumulator is an
# integer add or min/max selection, exact in any reduction order, so
# device and host combines are bit-identical by construction (the
# tests/test_multichip.py parity matrix and the verify_tier1.sh
# SERENE_SHARD_COMBINE=device pass enforce it)
assert "serene_shard_combine" not in RESULT_AFFECTING_SETTINGS
# tracing observes, never steers (obs/trace.py): results are
# bit-identical with the timeline layer on or off, so a cached entry is
# valid across either setting
assert "serene_trace" not in RESULT_AFFECTING_SETTINGS
assert "serene_profile" not in RESULT_AFFECTING_SETTINGS
# memory accounting observes too (obs/resources.py): charge/release
# events never steer execution, so a cached entry is valid whether the
# statement that stored it was accounted or not
assert "serene_mem_account" not in RESULT_AFFECTING_SETTINGS
# the workload governor (sched/governor.py) steers WHEN statements run,
# never what they return: admission order, fair-share picking and
# priorities change scheduling only (the deterministic merge sinks
# guarantee bit-identity), and the budget/timeout settings produce
# ERRORS, not results — an aborted statement stores nothing, so no
# cached entry can ever encode a budget's effect
# device telemetry observes too (obs/device.py): the compile ledger /
# transfer accounting never change which program runs, and the bounded
# program LRU can only cause a re-compile of the SAME program — results
# are bit-identical with telemetry on or off at any cache cap
assert "serene_device_telemetry" not in RESULT_AFFECTING_SETTINGS
assert "serene_program_cache_entries" not in RESULT_AFFECTING_SETTINGS
assert "serene_max_concurrent_statements" not in RESULT_AFFECTING_SETTINGS
assert "serene_admission_queue_depth" not in RESULT_AFFECTING_SETTINGS
assert "serene_fair_share" not in RESULT_AFFECTING_SETTINGS
assert "serene_priority" not in RESULT_AFFECTING_SETTINGS
assert "serene_work_mem" not in RESULT_AFFECTING_SETTINGS
assert "serene_statement_timeout_ms" not in RESULT_AFFECTING_SETTINGS
# the streaming-ingest tier is bit-identical by contract: the parallel
# analysis merge reproduces the serial segment byte for byte, group-commit
# windows only coalesce WHEN publications land (every statement still
# fsyncs before returning), and background vs foreground maintenance only
# changes the segment LAYOUT — scores use global collection stats, so any
# layout returns identical results (tests/test_ingest_stream.py parity
# matrix and the verify_tier1.sh pass 17 enforce all three)
assert "serene_parallel_ingest" not in RESULT_AFFECTING_SETTINGS
assert "serene_ingest_chunk_docs" not in RESULT_AFFECTING_SETTINGS
assert "serene_group_commit" not in RESULT_AFFECTING_SETTINGS
assert "serene_background_merge" not in RESULT_AFFECTING_SETTINGS
# the vector pool only moves WHERE the probe program reads vectors from
# (paged HBM region vs a per-call cold commit of the same cluster-major
# layout); the distance chain is association-fixed in the graph, so
# resident and cold dispatches are bit-identical at any page budget
# (tests/test_vector_store.py pool on/off parity and the verify_tier1.sh
# pass 18 starvation leg enforce it) — unlike serene_nprobe/serene_maxsim
# above, which DO change results and ARE in the digest
assert "serene_vector_pool" not in RESULT_AFFECTING_SETTINGS
assert "serene_vector_pages" not in RESULT_AFFECTING_SETTINGS
assert "serene_nprobe" in RESULT_AFFECTING_SETTINGS
assert "serene_maxsim" in RESULT_AFFECTING_SETTINGS
assert "serene_max_segments" not in RESULT_AFFECTING_SETTINGS

#: remember the table set of at most this many distinct statements for
#: the plan-skipping fast path
_STMT_MAP_CAP = 4096

_token_counter = itertools.count(1)
_token_lock = threading.Lock()


def _provider_token(provider) -> int:
    """Process-unique publication token, lazily attached. Distinguishes
    generations: a recreated table starts a fresh token, so its
    (version 0, epoch 0) can never alias the old table's entries."""
    tok = getattr(provider, "_cache_token", None)
    if tok is None:
        with _token_lock:
            tok = getattr(provider, "_cache_token", None)
            if tok is None:
                tok = next(_token_counter)
                provider._cache_token = tok
    return tok


def _observe(provider) -> tuple:
    pin = provider.try_pin()
    if pin is not None:
        return (_provider_token(provider), pin[1], pin[2])
    return (_provider_token(provider),
            getattr(provider, "data_version", 0),
            getattr(provider, "mutation_epoch", 0))


def _detach_batch(batch):
    """Copy any column array that is a VIEW into a larger base array.
    A cached `... LIMIT 5` result sliced from a 6M-row table would
    otherwise pin the whole base array while its accounted size says a
    few hundred bytes — the cache must own exactly the bytes it
    accounts for. Non-view columns (aggregate outputs, fresh arrays)
    are stored as-is."""
    import numpy as np

    from ..columnar.column import Batch, Column
    cols = []
    changed = False
    for c in batch.columns:
        data, validity = c.data, c.validity
        if isinstance(data, np.ndarray) and data.base is not None:
            data = data.copy()
            changed = True
        if isinstance(validity, np.ndarray) and validity.base is not None:
            validity = validity.copy()
            changed = True
        cols.append(Column(c.type, data, validity, c.dictionary)
                    if (data is not c.data or validity is not c.validity)
                    else c)
    if not changed:
        return batch
    return Batch(list(batch.names), cols)


def _batch_nbytes(batch) -> int:
    total = 0
    for c in batch.columns:
        total += int(c.data.nbytes)
        if c.validity is not None:
            total += int(c.validity.nbytes)
        if c.dictionary is not None:
            total += sum(len(str(s)) for s in c.dictionary) + \
                8 * len(c.dictionary)
    return total


# -- statement-level cacheability ------------------------------------------

class _Uncacheable(Exception):
    pass


#: out-of-band attributes the parser attaches OUTSIDE the dataclass
#: fields. values_rows CARRIES STATEMENT CONTENT (bare `VALUES (1),(2)`
#: rows live only there) — a digest that missed it would collide every
#: VALUES statement with every other. The text spans are derivable from
#: the fields and excluded.
_AST_EXTRA_ATTRS = ("values_rows",)


def _ast_canon(node, out: list, depth: int = 0) -> None:
    """Canonical value-based serialization of a statement AST into
    `out`, refusing anything it cannot serialize by VALUE. This is the
    cache's statement identity — repr() is NOT usable here: default
    object reprs are address-based and addresses recycle, which would
    alias two different statements into one key.

    The same single walk enforces the volatility gate, and it runs
    BEFORE binding on purpose: the binder constant-folds STABLE calls
    (now() becomes a literal — that fold IS its statement-stability),
    so the bound plan can no longer testify that the statement depends
    on the clock."""
    import dataclasses

    from ..sql import ast as _ast
    if depth > 200:
        raise _Uncacheable
    if node is None or isinstance(node, (bool, int, float, str, bytes)):
        out.append(repr(node))
        return
    if isinstance(node, (list, tuple)):
        out.append("[")
        for v in node:
            _ast_canon(v, out, depth + 1)
        out.append("]")
        return
    if isinstance(node, dict):
        out.append("{")
        for k in node:
            out.append(repr(k))
            _ast_canon(node[k], out, depth + 1)
        out.append("}")
        return
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        if isinstance(node, _ast.FuncCall) and \
                volatility(node.name) is not IMMUTABLE:
            raise _Uncacheable
        # subquery EXPRESSIONS bind to stable scalar_subquery funcs, so
        # the plan walk rejects them — except inside VALUES, where the
        # planner evaluates them at plan time and materializes the rows,
        # leaving no expression to testify and no provider to key. The
        # subplan's tables are never in the publication key, so these
        # must be refused here. SubqueryRef (derived tables in FROM) is
        # fine: it plans as a real subtree whose scans are collected.
        if isinstance(node, (_ast.Subquery, _ast.InSubquery,
                             _ast.Exists, _ast.ArraySubquery)):
            raise _Uncacheable
        out.append(type(node).__name__)
        out.append("(")
        for f in dataclasses.fields(node):
            _ast_canon(getattr(node, f.name), out, depth + 1)
        for extra in _AST_EXTRA_ATTRS:
            v = getattr(node, extra, None)
            if v is not None:
                out.append(extra)
                _ast_canon(v, out, depth + 1)
        out.append(")")
        return
    raise _Uncacheable          # unknown object: no value identity


# -- plan cacheability analysis --------------------------------------------

def _exprs_immutable(exprs) -> bool:
    from ..sql.expr import BoundFunc
    for e in exprs:
        if e is None:
            continue
        for sub in e.walk():
            if isinstance(sub, BoundFunc) and \
                    volatility(sub.name) is not IMMUTABLE:
                return False
    return True


def _agg_exprs(node):
    out = list(node.group_exprs)
    for spec in node.aggs:
        out.append(spec.arg)
        out.append(spec.filter)
        for e, _d, _nf in (spec.order_by or []):
            out.append(e)
    return out


def _plan_sources(plan) -> Optional[list]:
    """Every table provider a plan reads, or None when the plan is not
    cacheable (unknown operator, non-catalog source handled by the
    caller, stable/volatile expression anywhere). The operator list is
    a WHITELIST: an operator this walk does not know is assumed to hide
    state and blocks caching — new operators opt in, they never leak
    in."""
    from ..exec import plan as P
    from ..exec.search_scan import (BtreeScanNode, IvfScanNode,
                                    SearchScanNode)
    providers = []

    def walk(node) -> bool:
        if isinstance(node, P.ScanNode):
            providers.append(node.provider)
            return _exprs_immutable([node.filter])
        if isinstance(node, SearchScanNode):
            providers.append(node.provider)
            return _exprs_immutable([node.residual])
        if isinstance(node, (BtreeScanNode, IvfScanNode)):
            providers.append(node.provider)
            return True
        if isinstance(node, P.ValuesNode):
            return True
        if isinstance(node, P.FilterNode):
            return _exprs_immutable([node.pred]) and walk(node.child)
        if isinstance(node, P.ProjectNode):
            return _exprs_immutable(node.exprs) and walk(node.child)
        if isinstance(node, P.JoinNode):
            return (_exprs_immutable(node.left_keys) and
                    _exprs_immutable(node.right_keys) and
                    _exprs_immutable([node.residual]) and
                    walk(node.left) and walk(node.right))
        if isinstance(node, P.AggregateNode):
            return _exprs_immutable(_agg_exprs(node)) and walk(node.child)
        if isinstance(node, (P.LimitNode, P.SortNode, P.DropColumnsNode,
                             P.RenameNode, P.DistinctOnNode)):
            return all(walk(c) for c in node.children())
        if isinstance(node, P.SetOpNode):
            return walk(node.left) and walk(node.right)
        return False

    return providers if walk(plan) else None


def _catalog_key(db, provider) -> Optional[tuple]:
    """("table", "schema.name") / ("parquet", path) when the provider is
    the catalog's own long-lived instance; None for per-query providers
    (system tables, table functions, txn pins) — those never cache."""
    from ..exec.tables import MemTable, ParquetTable
    if isinstance(provider, ParquetTable):
        if db._parquet_cache.get(provider.path) is provider:
            return ("parquet", provider.path)
        return None
    if not isinstance(provider, MemTable):
        return None
    key = db.catalog_key_of(provider)
    return None if key is None else ("table", key)


def _resolve_source(db, conn, kind: str, key: str):
    """Fast-path re-resolution of a remembered source; None on any
    mismatch (dropped, renamed, revoked) — the caller replans."""
    if kind == "parquet":
        return db._parquet_cache.get(key)
    schema, name = key.split(".", 1)
    with db.lock:
        s = db.schemas.get(schema)
        p = s.tables.get(name) if s is not None else None
    if p is None:
        return None
    try:
        db.roles.require(conn.current_role, key, "select")
    except Exception:
        return None                    # let the plan path raise properly
    return p


# -- entries ----------------------------------------------------------------

class _Entry:
    __slots__ = ("batch", "label", "qid", "pubs", "sources", "wrefs")

    def __init__(self, batch, label, qid, pubs, sources, wrefs):
        self.batch = batch
        self.label = label        # normalized query text (inspection)
        self.qid = qid            # lexer fingerprint for attribution
        self.pubs = pubs          # tuple of (kind, key, token, ver, epoch)
        self.sources = sources    # tuple of (kind, key)
        self.wrefs = wrefs        # weakrefs to providers (sweep)


class ResultCache:
    def __init__(self):
        self._lru = BytesLRU(on_evict=self._evicted)
        self._lock = threading.Lock()
        self._stmt_tables: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._gauge_bytes = 0
        self._stores = 0

    # -- gauges ------------------------------------------------------------

    def _evicted(self, key, entry):
        metrics.RESULT_CACHE_EVICTIONS.add()
        self._sync_bytes()

    def _sync_bytes(self):
        with self._lock:
            now = self._lru.total_bytes
            delta = now - self._gauge_bytes
            self._gauge_bytes = now
        if delta:
            metrics.RESULT_CACHE_BYTES.add(delta)

    # -- key pieces --------------------------------------------------------

    @staticmethod
    def _settings_digest(settings) -> str:
        return "\x1f".join(
            f"{n}={settings.get(n)}" for n in RESULT_AFFECTING_SETTINGS)

    @staticmethod
    def _stmt_hash(sel_ast, params, settings) -> Optional[bytes]:
        """None when the statement refuses canonical serialization
        (unknown AST payloads, stable/volatile function calls)."""
        parts: list = []
        try:
            _ast_canon(sel_ast, parts)
        except _Uncacheable:
            return None
        h = hashlib.blake2b(digest_size=16)
        h.update("\x1e".join(parts).encode())
        h.update(b"\x00")
        h.update(repr(tuple(params)).encode())
        h.update(b"\x00")
        h.update(ResultCache._settings_digest(settings).encode())
        return h.digest()

    # -- statement lifecycle ----------------------------------------------

    def begin(self, conn, sel_ast, params,
              sql_text: Optional[str]) -> Optional["_Probe"]:
        """None when caching is off for this session or the statement
        runs inside a transaction (snapshot pins + read-your-writes make
        the catalog publication meaningless for it)."""
        try:
            if not conn.settings.get("serene_result_cache"):
                return None
        except KeyError:                      # pragma: no cover
            return None
        if conn.in_txn:
            return None
        stmt_hash = self._stmt_hash(sel_ast, params, conn.settings)
        if stmt_hash is None:
            return None
        return _Probe(self, conn, stmt_hash, sql_text)

    def tables_for(self, stmt_hash: bytes) -> Optional[tuple]:
        with self._lock:
            return self._stmt_tables.get(stmt_hash)

    def remember_tables(self, stmt_hash: bytes, sources: tuple):
        with self._lock:
            self._stmt_tables[stmt_hash] = sources
            self._stmt_tables.move_to_end(stmt_hash)
            while len(self._stmt_tables) > _STMT_MAP_CAP:
                self._stmt_tables.popitem(last=False)

    def get(self, key) -> Optional[_Entry]:
        return self._lru.get(key)

    #: entry-count ceiling: lookup/sweep cost stays bounded even when
    #: every entry is tiny
    MAX_ENTRIES = 4096
    #: sweep cadence in stores — a dead table's entries linger at most
    #: this many stores before their bytes are reclaimed
    SWEEP_EVERY = 16

    def put(self, key, entry: _Entry, nbytes: int) -> bool:
        cap = int(_settings_registry.get_global(
            "serene_result_cache_mb")) << 20
        ok = self._lru.put(key, entry, nbytes, cap,
                           cap_entries=self.MAX_ENTRIES)
        self._sync_bytes()
        with self._lock:
            self._stores += 1
            do_sweep = self._stores % self.SWEEP_EVERY == 0
        if do_sweep:
            self.sweep()
        return ok

    def sweep(self) -> int:
        """Lazy reclamation of superseded generations: entries whose
        provider died or whose publication advanced can never be hit
        again (keys embed the publication) — drop their bytes."""

        def stale(key, lru_entry) -> bool:
            e = lru_entry.value
            for wref, pub in zip(e.wrefs, e.pubs):
                p = wref()
                if p is None or _observe(p) != pub[2:]:
                    return True
            return False

        n = self._lru.evict_where(stale)
        self._sync_bytes()
        return n

    def clear(self):
        self._lru.clear()
        with self._lock:
            self._stmt_tables.clear()
        self._sync_bytes()

    def snapshot(self) -> list[dict]:
        out = []
        for key, e in self._lru.items():
            out.append({
                "tier": "result",
                "key": key[0].hex() if isinstance(key, tuple) else str(key),
                "query": e.value.label,
                "queryid": e.value.qid,
                "bytes": e.nbytes,
                "hits": e.hits,
                "rows": e.value.batch.num_rows,
                "objects": ",".join(k for _kind, k in e.value.sources),
            })
        return out

    def stats(self) -> dict:
        return {
            "entries": len(self._lru),
            "bytes": self._lru.total_bytes,
            "hits": metrics.RESULT_CACHE_HITS.value,
            "misses": metrics.RESULT_CACHE_MISSES.value,
            "evictions": metrics.RESULT_CACHE_EVICTIONS.value,
        }


class _Probe:
    """One statement's interaction with the cache: fast_lookup before
    planning, prepare+lookup after planning, store after execution."""

    def __init__(self, cache: ResultCache, conn, stmt_hash: bytes,
                 sql_text: Optional[str]):
        self.cache = cache
        self.conn = conn
        self.stmt_hash = stmt_hash
        self.sql_text = sql_text
        self.cacheable = False
        self.providers = None        # [(kind, key, provider)]
        self.pubs = None             # observed pre-execution
        self._counted = False

    # -- key assembly ------------------------------------------------------

    def _full_key(self, pubs) -> tuple:
        return (self.stmt_hash, pubs)

    @staticmethod
    def _pubs_of(sources) -> tuple:
        return tuple(sorted(
            (kind, key) + _observe(p) for kind, key, p in sources))

    def _hit(self, entry) -> object:
        from ..columnar.column import Batch
        metrics.RESULT_CACHE_HITS.add()
        self.conn._cache_hit = True
        # shallow container copy: consumers may relabel columns, the
        # cached column objects themselves are immutable by convention
        return Batch(list(entry.batch.names), list(entry.batch.columns))

    # -- pre-plan fast path ------------------------------------------------

    def fast_lookup(self):
        """Serve without planning when the statement's table set is
        remembered from an earlier store and every source still
        resolves (ACL re-checked). None on any doubt."""
        sources = self.cache.tables_for(self.stmt_hash)
        if sources is None:
            return None
        resolved = []
        for kind, key in sources:
            p = _resolve_source(self.conn.db, self.conn, kind, key)
            if p is None:
                return None
            resolved.append((kind, key, p))
        entry = self.cache.get(self._full_key(self._pubs_of(resolved)))
        if entry is None:
            return None
        return self._hit(entry)

    # -- post-plan path ----------------------------------------------------

    def prepare(self, plan) -> None:
        """Analyze the built plan: collect sources, verify every
        expression is immutable and every source is a catalog-resident
        provider, observe publications. Not cacheable ⇒ inert probe."""
        if getattr(self.conn, "_plan_inlined_views", False):
            return                    # view identity is not in the key
        providers = _plan_sources(plan)
        if providers is None:
            return
        db = self.conn.db
        seen = {}
        for p in providers:
            if id(p) in seen:
                continue
            ck = _catalog_key(db, p)
            if ck is None:
                return
            seen[id(p)] = (ck[0], ck[1], p)
        self.providers = list(seen.values())
        self.pubs = self._pubs_of(self.providers)
        self.cacheable = True

    def lookup(self):
        if not self.cacheable:
            return None
        entry = self.cache.get(self._full_key(self.pubs))
        if entry is not None:
            return self._hit(entry)
        if not self._counted:
            metrics.RESULT_CACHE_MISSES.add()
            self._counted = True
        return None

    def peek(self) -> bool:
        """Would lookup() hit? No gauges, no hit attribution — EXPLAIN
        ANALYZE reports cache state without perturbing it."""
        return self.cacheable and \
            self.cache.get(self._full_key(self.pubs)) is not None

    def store(self, batch) -> bool:
        """Store only when the post-execution publication observation
        matches the pre-execution one — a write racing the execution
        makes the result unattributable to either publication, so it is
        simply not cached."""
        if not self.cacheable:
            return False
        if self._pubs_of(self.providers) != self.pubs:
            return False
        batch = _detach_batch(batch)
        label, qid = self._label()
        # wrefs must align with the SORTED pubs tuple: the sweep zips
        # them pairwise to re-observe each provider
        pairs = sorted((((kind, key) + _observe(p)), p)
                       for kind, key, p in self.providers)
        entry = _Entry(
            batch, label, qid, tuple(t[0] for t in pairs),
            tuple((kind, key) for kind, key, _p in self.providers),
            [weakref.ref(t[1]) for t in pairs])
        nbytes = _batch_nbytes(batch)
        from ..obs.resources import charge_cache_store
        charge_cache_store(nbytes)
        ok = self.cache.put(self._full_key(self.pubs), entry, nbytes)
        if ok:
            self.cache.remember_tables(self.stmt_hash, entry.sources)
        return ok

    def _label(self) -> tuple:
        if self.sql_text:
            from ..obs.statements import fingerprint, normalize
            norm = normalize(self.sql_text)
            # an entry stored by EXPLAIN ANALYZE is keyed on (and later
            # hit by) the INNER statement — label and attribute it as
            # that statement, not as the explain wrapper
            for prefix in ("explain analyze ", "explain "):
                if norm.startswith(prefix):
                    norm = norm[len(prefix):]
                    break
            return norm[:500], fingerprint(norm)
        return "<internal>", 0


#: process-wide store, one per process like the metrics registry
RESULT_CACHE = ResultCache()
