"""Multi-tier query cache (ISSUE 5).

Reference analog: the layered read caches a search engine serves
repeated dashboard/search traffic from — the shard request cache
(per-segment filter/agg fragments, valid while the segment set is
unchanged) and reused whole-request results. ColBERT-serve (PAPERS.md)
shows the same move at model-serving scale: keep hot query state
resident instead of recomputing the multi-stage pipeline.

Two tiers, two invalidation disciplines:

- `cache.result` — whole-statement result memoization. The key embeds
  every input the result is a function of: the statement's canonical
  AST digest, bound parameter values, a digest of result-affecting
  session settings, and the (publication-token, data_version,
  mutation_epoch) tuple of every table the plan scans. Writes bump the
  publication tuple, so a stale entry's key simply never matches again
  — invalidation is implicit and exact.
- `cache.fragments` — per-segment search fragments (filter doc sets,
  top-k collector outputs). Segments are immutable, so a fragment is
  valid for the segment's whole lifetime; appends add segments without
  touching existing entries (the shard-request-cache analog), while
  delete/update rebuilds replace the segment objects and their entries
  die with them.

Both tiers are process-wide bytes-bounded LRUs (`cache.lru.BytesLRU`),
surfaced through the `sdb_cache()` table function, ResultCache*/
FragmentCache* gauges, `/metrics`, `/_stats` and the `cache_hits`
column of `sdb_stat_statements`. `SET serene_result_cache = off`
disables both for a session; results are bit-identical either way.
"""

from .lru import BytesLRU  # noqa: F401
