"""Tier 2: per-segment search fragment cache.

Reference analog: the ES shard request cache — per-shard filter and
aggregation fragments keyed by the request digest, valid while the
shard's segment set is unchanged. Here the unit is the SEGMENT
(search/searcher.SegmentSearcher), which is immutable by construction:

- filter fragments (sorted doc-id sets for one query node) are a pure
  function of the segment — valid for the segment's whole lifetime.
  Appends only create NEW segments, so existing fragments survive them;
  delete/update rebuilds replace the segment objects and the dead
  segments' entries are purged by their weakref finalizers.
- top-k fragments (one segment's scored collector output) additionally
  depend on GLOBAL collection statistics (idf/avgdl span every
  segment), so their key includes the whole segment-set signature — an
  append changes the signature and the fragment recomputes, exactly as
  scores require.

Keys are (segment uid, shape digest). Each segment gets a
process-unique uid on first touch (never an id() — addresses recycle);
query nodes digest structurally via `qnode_sig`, and an unknown node
type simply bypasses the cache. Cached arrays are returned as COPIES so
no caller can corrupt a shared fragment in place.

Gated per session by `serene_result_cache` (read off the executing
connection's settings when one is current, else the global default);
bytes-bounded by the `serene_fragment_cache_mb` global.
"""

from __future__ import annotations

import itertools
import threading
import weakref
from collections import deque
from typing import Optional

import numpy as np

from ..utils import metrics
from ..utils.config import REGISTRY as _settings_registry
from .lru import BytesLRU

_uid_counter = itertools.count(1)
_uid_lock = threading.Lock()


def enabled() -> bool:
    """Session gate: the executing connection's serene_result_cache when
    a statement is running, else the global default."""
    from ..engine import CURRENT_CONNECTION
    conn = CURRENT_CONNECTION.get()
    try:
        if conn is not None:
            return bool(conn.settings.get("serene_result_cache"))
        return bool(_settings_registry.get_global("serene_result_cache"))
    except KeyError:                              # pragma: no cover
        return False


def qnode_sig(node) -> Optional[tuple]:
    """Structural, hashable signature of a query node; None for node
    types this walk does not know (those bypass the cache — default
    reprs are address-based and must never key anything)."""
    from ..search.query import (QAnd, QFuzzy, QNot, QNothing, QOr,
                                QPhrase, QPrefix, QRegex, QTerm)
    if isinstance(node, QTerm):
        return ("t", node.term)
    if isinstance(node, QPhrase):
        return ("p", tuple(tuple(g) for g in node.groups), node.slop)
    if isinstance(node, QPrefix):
        return ("pre", node.prefix)
    if isinstance(node, QFuzzy):
        return ("f", node.term, node.max_edits)
    if isinstance(node, QRegex):
        return ("re", node.pattern, getattr(node, "case_fold", False))
    if isinstance(node, QNothing):
        return ("0",)
    if isinstance(node, QNot):
        inner = qnode_sig(node.arg)
        return None if inner is None else ("!", inner)
    if isinstance(node, (QAnd, QOr)):
        parts = tuple(qnode_sig(a) for a in node.args)
        if any(p is None for p in parts):
            return None
        return ("&" if isinstance(node, QAnd) else "|",) + parts
    return None


def _copy_value(v):
    if isinstance(v, np.ndarray):
        return v.copy()
    if isinstance(v, tuple):
        return tuple(_copy_value(x) for x in v)
    if isinstance(v, list):
        return [_copy_value(x) for x in v]
    return v


def _value_nbytes(v) -> int:
    if isinstance(v, np.ndarray):
        return int(v.nbytes)
    if isinstance(v, (list, tuple)):
        return sum(_value_nbytes(x) for x in v) + 16 * len(v)
    return 64


class FragmentCache:
    def __init__(self):
        self._lru = BytesLRU(on_evict=self._evicted)
        self._lock = threading.Lock()
        self._seg_keys: dict[int, set] = {}   # uid → live keys
        self._gauge_bytes = 0
        #: uids of dead segments awaiting reclaim. The weakref finalizer
        #: ONLY appends here: a finalizer runs at an arbitrary
        #: allocation/GC point — possibly on a thread that already holds
        #: `_lock` or the LRU's lock (observed: GC inside
        #: `_sync_bytes`'s `total_bytes` call) — so taking any lock in
        #: it deadlocks against the very frame it interrupted.
        #: deque.append is atomic under the GIL; the next cache
        #: operation drains the queue with normal locking.
        self._pending_drops: deque = deque()

    def _evicted(self, key, entry):
        # keep the per-segment key sets in step with LRU pressure —
        # without this they grow one dead tuple per evicted fragment
        # for the segment's whole lifetime
        with self._lock:
            keys = self._seg_keys.get(key[0])
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._seg_keys[key[0]]
        self._sync_bytes()

    def _sync_bytes(self):
        with self._lock:
            now = self._lru.total_bytes
            delta = now - self._gauge_bytes
            self._gauge_bytes = now
        if delta:
            metrics.FRAGMENT_CACHE_BYTES.add(delta)

    def segment_uid(self, seg) -> int:
        """Process-unique id for a segment searcher; registering a
        finalizer so a rebuilt/dropped segment's fragments are purged
        when the object dies (never reachable again anyway — the uid
        dies with it — but the bytes are reclaimed eagerly)."""
        uid = getattr(seg, "_frag_uid", None)
        if uid is None:
            with _uid_lock:
                uid = getattr(seg, "_frag_uid", None)
                if uid is None:
                    uid = next(_uid_counter)
                    seg._frag_uid = uid
                    weakref.finalize(seg, self.drop_segment, uid)
        return uid

    def drop_segment(self, uid: int) -> None:
        """Weakref finalizer target — lock-free by contract (see
        `_pending_drops`); the entries are unreachable the moment the
        segment dies (its uid dies with it), this only defers reclaiming
        their bytes."""
        self._pending_drops.append(uid)

    def _drain_drops(self) -> None:
        if not self._pending_drops:   # steady state: no raise/catch tax
            return
        while True:
            try:
                uid = self._pending_drops.popleft()
            except IndexError:
                return
            with self._lock:
                keys = self._seg_keys.pop(uid, None)
            if keys:
                for k in keys:
                    self._lru.remove(k)
                self._sync_bytes()

    def probe(self, seg, shape: Optional[tuple]):
        """Pure lookup: a copy of the cached value, or None. Bumps NO
        hit/miss gauges and never stores — the batcher's pre-batch probe
        runs ahead of the real lookup, and counting here would double-bill
        fragments the batch dispatch re-probes. Callers that commit to a
        probe's value report it via `count_hits`."""
        self._drain_drops()
        if shape is None or not enabled():
            return None
        hit = self._lru.get((self.segment_uid(seg), shape))
        return None if hit is None else _copy_value(hit)

    def count_hits(self, n: int) -> None:
        """Attribute `n` fragment servings discovered via `probe`."""
        metrics.FRAGMENT_CACHE_HITS.add(n)

    def cached_batch(self, seg, shapes: list, compute_batch) -> list:
        """Per-item memoization over ONE batched compute: probe every
        shape, call compute_batch(miss_indices) once for the misses (it
        must return one value per index, in order), store each under its
        own key. This is what lets a coalesced search batch reuse — and
        feed — the same per-query fragments as solo dispatches. shape=None
        items always compute."""
        self._drain_drops()
        n = len(shapes)
        if not enabled():
            return compute_batch(list(range(n)))
        uid = self.segment_uid(seg)
        results: list = [None] * n
        miss: list[int] = []
        for i, shape in enumerate(shapes):
            hit = self._lru.get((uid, shape)) if shape is not None else None
            if hit is not None:
                metrics.FRAGMENT_CACHE_HITS.add()
                results[i] = _copy_value(hit)
            else:
                if shape is not None:
                    metrics.FRAGMENT_CACHE_MISSES.add()
                miss.append(i)
        if not miss:
            return results
        computed = compute_batch(miss)
        cap = int(_settings_registry.get_global(
            "serene_fragment_cache_mb")) << 20
        stored = False
        for i, value in zip(miss, computed):
            shape = shapes[i]
            if shape is None:
                results[i] = value
                continue
            key = (uid, shape)
            if not self._lru.put(key, value, _value_nbytes(value), cap):
                results[i] = value    # refused (over cap): sole reference
                continue
            with self._lock:
                self._seg_keys.setdefault(uid, set()).add(key)
            stored = True
            results[i] = _copy_value(value)
        if stored:
            self._sync_bytes()
        return results

    def cached(self, seg, shape: Optional[tuple], compute):
        """compute() memoized under (segment uid, shape). shape=None ⇒
        uncacheable query shape ⇒ straight computation. The cache is
        consulted only when the session gate is on, but a fragment
        stored by one session is served to any other — fragments are
        pure functions of immutable segments."""
        self._drain_drops()   # reclaim dead-segment bytes even when gated off
        if shape is None or not enabled():
            return compute()
        uid = self.segment_uid(seg)
        key = (uid, shape)
        hit = self._lru.get(key)
        if hit is not None:
            metrics.FRAGMENT_CACHE_HITS.add()
            return _copy_value(hit)
        metrics.FRAGMENT_CACHE_MISSES.add()
        value = compute()
        cap = int(_settings_registry.get_global(
            "serene_fragment_cache_mb")) << 20
        if not self._lru.put(key, value, _value_nbytes(value), cap):
            return value              # refused (over cap): sole reference
        with self._lock:
            self._seg_keys.setdefault(uid, set()).add(key)
        self._sync_bytes()
        return _copy_value(value)

    def clear(self):
        self._pending_drops.clear()
        self._lru.clear()
        with self._lock:
            self._seg_keys.clear()
        self._sync_bytes()

    def snapshot(self) -> list[dict]:
        self._drain_drops()
        out = []
        for key, e in self._lru.items():
            uid, shape = key
            out.append({
                "tier": "fragment",
                "key": f"seg{uid}:{shape[0]}",
                "query": repr(shape)[:200],
                "queryid": 0,
                "bytes": e.nbytes,
                "hits": e.hits,
                "rows": 0,
                "objects": f"segment:{uid}",
            })
        return out

    def stats(self) -> dict:
        self._drain_drops()
        return {
            "entries": len(self._lru),
            "bytes": self._lru.total_bytes,
            "hits": metrics.FRAGMENT_CACHE_HITS.value,
            "misses": metrics.FRAGMENT_CACHE_MISSES.value,
        }


#: process-wide store (segments are process-wide objects)
FRAGMENTS = FragmentCache()
