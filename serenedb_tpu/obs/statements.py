"""sdb_stat_statements: cumulative per-statement execution statistics.

Reference analog: PG's pg_stat_statements — statements aggregate under a
normalized query fingerprint (literals and bind parameters collapse to
`?`, keywords/identifiers lowercase, whitespace canonical), so
`SELECT * FROM t WHERE x = 5` and `select *  from T where x=$1` are one
entry. The registry is process-wide, capped by the
`serene_stat_statements_max` global with least-recently-executed
eviction, and surfaces as the `sdb_stat_statements` system view
(pgcatalog.py) and in the `/metrics` + `/_stats` HTTP exports.
"""

from __future__ import annotations

import functools
import hashlib
import threading
from collections import OrderedDict

from ..sql.lexer import T, tokenize
from ..utils.metrics import (HIST_BOUNDS_NS, hist_bucket_index,
                             hist_quantile_ns)


@functools.lru_cache(maxsize=512)
def normalize(sql: str) -> str:
    """Canonical fingerprint text: literals/params → `?`, identifiers and
    keywords lowercased, one space between tokens, no trailing `;`.
    Unlexable text falls back to lowercase whitespace collapse (the
    statement still aggregates, just less precisely)."""
    try:
        toks = tokenize(sql)
    except Exception:
        return " ".join(sql.lower().split()).rstrip(";").rstrip()
    parts: list[str] = []
    for t in toks:
        if t.kind is T.EOF:
            break
        if t.kind in (T.NUMBER, T.STRING, T.PARAM):
            parts.append("?")
        elif t.kind is T.IDENT:
            parts.append(t.value.lower())
        else:
            parts.append(t.value)
    while parts and parts[-1] == ";":
        parts.pop()
    return " ".join(parts)


def fingerprint(normalized: str) -> int:
    """Stable 63-bit query id of the normalized text (PG's queryid)."""
    h = hashlib.blake2b(normalized.encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") & ((1 << 63) - 1)


class StatementStore:
    """Fingerprint → cumulative stats, LRU-capped.

    One short critical section per statement END (never inside
    execution), so the store adds no contention to the operator hot
    path. Eviction order is last-execution recency: recording an
    existing entry refreshes it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: OrderedDict[int, dict] = OrderedDict()

    def record(self, query_text: str, elapsed_ns: int, rows: int,
               morsels_pruned: int, cap: int,
               cache_hit: bool = False, peak_bytes: int = 0) -> int:
        norm = normalize(query_text)
        qid = fingerprint(norm)
        ms = elapsed_ns / 1e6
        bucket = hist_bucket_index(elapsed_ns)
        peak = max(int(peak_bytes), 0)
        with self._lock:
            e = self._entries.get(qid)
            if e is None:
                while len(self._entries) >= max(int(cap), 1):
                    self._entries.popitem(last=False)
                hist = [0] * (len(HIST_BOUNDS_NS) + 1)
                hist[bucket] = 1
                self._entries[qid] = {
                    "queryid": qid, "query": norm, "calls": 1,
                    "total_ms": ms, "min_ms": ms, "max_ms": ms,
                    "rows": int(rows),
                    "morsels_pruned": int(morsels_pruned),
                    "cache_hits": int(bool(cache_hit)),
                    "peak_mem_bytes": peak,
                    "last_peak_mem_bytes": peak,
                    "hist": hist}
            else:
                self._entries.move_to_end(qid)
                e["calls"] += 1
                e["total_ms"] += ms
                e["min_ms"] = min(e["min_ms"], ms)
                e["max_ms"] = max(e["max_ms"], ms)
                e["rows"] += int(rows)
                e["morsels_pruned"] += int(morsels_pruned)
                # entries recorded before the cache subsystem existed in
                # this process lifetime may lack the key (same story for
                # the latency histogram and peak-memory columns below)
                e["cache_hits"] = e.get("cache_hits", 0) + \
                    int(bool(cache_hit))
                e["peak_mem_bytes"] = max(e.get("peak_mem_bytes", 0),
                                          peak)
                e["last_peak_mem_bytes"] = peak
                hist = e.setdefault("hist",
                                    [0] * (len(HIST_BOUNDS_NS) + 1))
                hist[bucket] += 1
        return qid

    def snapshot(self) -> list[dict]:
        """Point-in-time copy, most recently executed last. The raw
        per-entry latency histogram collapses into p50/p95/p99
        milliseconds (the per-fingerprint percentiles surfaced by
        sdb_stat_statements and /_stats)."""
        with self._lock:
            out = []
            for e in self._entries.values():
                d = dict(e)
                hist = d.pop("hist", None)
                if hist is not None:
                    d["p50_ms"] = round(
                        hist_quantile_ns(hist, 0.50) / 1e6, 3)
                    d["p95_ms"] = round(
                        hist_quantile_ns(hist, 0.95) / 1e6, 3)
                    d["p99_ms"] = round(
                        hist_quantile_ns(hist, 0.99) / 1e6, 3)
                out.append(d)
            return out

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: process-wide store (one per process, like the metrics registry)
STATEMENTS = StatementStore()
