"""Lock-cheap per-query span/profile collector.

Reference analog: ClickHouse's per-query ProfileEvents and PG's
EXPLAIN ANALYZE instrumentation, re-expressed for the morsel/batch
executor: every PlanNode's batch generator is wrapped (exec/plan.py
auto-wraps subclasses), and the fused morsel pipeline stamps its stage
work directly (exec/morsel.py), so both the streaming operator tree and
the worker-pool path are covered by ONE collector.

Determinism contract: profiling observes, never steers. Each executing
thread accumulates into its own bucket (a thread-local dict — no lock on
the hot path after first touch); the sink merges buckets by summing
integer counters, so the merged numbers are independent of scheduling
order and the query result is bit-identical with profiling on or off at
any `serene_workers`. Wall/CPU nanoseconds in morsel pipelines are
summed per-worker task times (they can exceed elapsed wall clock on
purpose — that is the work the pool did).
"""

from __future__ import annotations

import threading
import time
from typing import Iterator, Optional

#: additive per-operator counters (merge = sum; scheduling-order free)
_COUNTERS = ("wall_ns", "cpu_ns", "rows_out", "batches", "bytes_out",
             "loops", "morsels_scheduled", "morsels_pruned",
             "morsels_jf_pruned", "device_ns", "batch_queries",
             "batch_window_ns", "batch_scoring_ns", "shard_pipelines",
             "shard_pruned")


class OpStats:
    """One operator's accumulated span counters (one bucket's view)."""

    __slots__ = _COUNTERS + ("first_ns",)

    def __init__(self):
        for f in _COUNTERS:
            setattr(self, f, 0)
        #: the operator's accumulated wall ns at its FIRST emitted batch
        #: (PG "startup time"; merge = min, thread-order free)
        self.first_ns: Optional[int] = None

    def merge(self, other: "OpStats") -> None:
        for f in _COUNTERS:
            setattr(self, f, getattr(self, f) + getattr(other, f))
        if other.first_ns is not None:
            self.first_ns = other.first_ns if self.first_ns is None \
                else min(self.first_ns, other.first_ns)


def batch_nbytes(b) -> int:
    """Materialized bytes of a batch's physical arrays (dictionary pages
    are shared, not per-batch — excluded)."""
    return sum(int(c.data.nbytes) for c in b.columns)


class QueryProfile:
    """Per-query collector keyed by id(plan node).

    Hot-path cost is one thread-local dict lookup plus integer adds per
    BATCH (never per row); batches are morsel-sized, so the budget is
    <3% on the profile_overhead bench shape.
    """

    def __init__(self):
        self._register_lock = threading.Lock()
        self._buckets: list[dict[int, OpStats]] = []
        self._tl = threading.local()
        self.t0_ns = time.perf_counter_ns()

    # -- accumulation (any thread) ----------------------------------------

    def _bucket(self) -> dict[int, OpStats]:
        d = getattr(self._tl, "d", None)
        if d is None:
            d = self._tl.d = {}
            with self._register_lock:
                self._buckets.append(d)
        return d

    def stats(self, key: int) -> OpStats:
        d = self._bucket()
        s = d.get(key)
        if s is None:
            s = d[key] = OpStats()
        return s

    def add_scan_morsels(self, key: int, scheduled: int = 0,
                         pruned: int = 0, jf_pruned: int = 0) -> None:
        """Morsel scheduling outcome for one scan. The three counters are
        DISJOINT (scheduled + pruned + jf_pruned = blocks considered):
        `pruned` is zone-map-only pruning, `jf_pruned` join-filter
        pruning, a block both would skip counts once under the join
        filter — so roll-ups never double-count a block."""
        s = self.stats(key)
        s.morsels_scheduled += int(scheduled)
        s.morsels_pruned += int(pruned)
        s.morsels_jf_pruned += int(jf_pruned)

    def add_stage(self, key: int, rows_out: int, wall_ns: int,
                  cpu_ns: int = 0, bytes_out: int = 0) -> None:
        """Fused-pipeline stamp: one morsel's pass through one operator
        (the operator's own batches() never runs in the fused path)."""
        s = self.stats(key)
        s.rows_out += int(rows_out)
        s.wall_ns += int(wall_ns)
        s.cpu_ns += int(cpu_ns)
        s.bytes_out += int(bytes_out)
        s.batches += 1

    def add_device_ns(self, key: int, ns: int) -> None:
        self.stats(key).device_ns += int(ns)

    def add_search_batch(self, key: int, queries: int, window_ns: int,
                         scoring_ns: int) -> None:
        """Search-batcher span for one top-k scan: how many queries its
        dispatch carried (1 = no coalescing), how long this query waited
        queued, and the shared scoring time of the whole dispatch — so
        EXPLAIN ANALYZE attributes both the batching win and its latency
        cost."""
        s = self.stats(key)
        s.batch_queries += int(queries)
        s.batch_window_ns += int(window_ns)
        s.batch_scoring_ns += int(scoring_ns)

    def add_shards(self, key: int, pipelines: int, pruned: int = 0
                   ) -> None:
        """Sharded-tier span for one operator: how many per-shard
        pipelines its execution fanned out into (serene_shards > 1) and
        how many blocks the shard-to-shard join filter pruned — the
        `Shards:` EXPLAIN ANALYZE detail line."""
        s = self.stats(key)
        s.shard_pipelines += int(pipelines)
        s.shard_pruned += int(pruned)

    def wrap_batches(self, node, fn, ctx) -> Iterator:
        """Instrumented drive of a node's raw batch generator: wall time
        accrues only while inside next() (inclusive of children, PG
        semantics), rows/bytes per emitted batch."""
        key = id(node)
        self.stats(key).loops += 1
        it = fn(node, ctx)
        try:
            while True:
                t0 = time.perf_counter_ns()
                c0 = time.thread_time_ns()
                try:
                    b = next(it)
                except StopIteration:
                    s = self.stats(key)
                    s.wall_ns += time.perf_counter_ns() - t0
                    s.cpu_ns += time.thread_time_ns() - c0
                    return
                t1 = time.perf_counter_ns()
                s = self.stats(key)
                s.wall_ns += t1 - t0
                s.cpu_ns += time.thread_time_ns() - c0
                if s.first_ns is None:
                    s.first_ns = s.wall_ns
                s.rows_out += b.num_rows
                s.batches += 1
                s.bytes_out += batch_nbytes(b)
                yield b
        finally:
            it.close()

    # -- sink merge (call after execution has drained) --------------------

    def merged(self) -> dict[int, OpStats]:
        """Deterministic sink merge: per-thread buckets sum into one map.
        Integer addition is order-free, so the result is identical for
        any scheduling of the same work."""
        with self._register_lock:
            buckets = list(self._buckets)
        out: dict[int, OpStats] = {}
        for d in buckets:
            for key, s in d.items():
                agg = out.get(key)
                if agg is None:
                    out[key] = agg = OpStats()
                agg.merge(s)
        return out

    def totals(self) -> OpStats:
        """Whole-query roll-up of the prune counters (stat_statements
        attribution); rows/time roll-ups are per-operator, not summed."""
        t = OpStats()
        for s in self.merged().values():
            t.morsels_scheduled += s.morsels_scheduled
            t.morsels_pruned += s.morsels_pruned
            t.morsels_jf_pruned += s.morsels_jf_pruned
            t.device_ns += s.device_ns
        return t


def _ms(ns: int) -> str:
    return f"{ns / 1e6:.3f}"


def annotate_plan(plan, profile: QueryProfile) -> list[str]:
    """EXPLAIN ANALYZE rendering: the plan tree with PG-style
    `(actual time=first..total rows=N loops=L)` suffixes plus prune /
    device detail lines. Nodes the executor fused away (device offload)
    render `(never executed)` like PG's unvisited branches."""
    merged = profile.merged()

    def walk(node, depth: int) -> list[str]:
        pad = "  " * depth
        s = merged.get(id(node))
        if s is None:
            lines = [f"{pad}{node.label()} (never executed)"]
        else:
            first = s.first_ns if s.first_ns is not None else s.wall_ns
            lines = [f"{pad}{node.label()} "
                     f"(actual time={_ms(first)}..{_ms(s.wall_ns)} "
                     f"rows={s.rows_out} loops={max(s.loops, 1)})"]
            detail = pad + "  "
            if s.morsels_scheduled or s.morsels_pruned:
                jf = (f" join_filter_pruned={s.morsels_jf_pruned}"
                      if s.morsels_jf_pruned else "")
                lines.append(
                    f"{detail}Morsels: scheduled={s.morsels_scheduled} "
                    f"zonemap_pruned={s.morsels_pruned}{jf}")
            if s.device_ns:
                lines.append(f"{detail}Device: time={_ms(s.device_ns)} ms")
            if s.batch_queries:
                lines.append(
                    f"{detail}Batch: queries={s.batch_queries} "
                    f"window={_ms(s.batch_window_ns)} ms "
                    f"shared_scoring={_ms(s.batch_scoring_ns)} ms")
            if s.shard_pipelines or s.shard_pruned:
                lines.append(f"{detail}Shards: n={s.shard_pipelines} "
                             f"pruned={s.shard_pruned}")
        for c in node.children():
            lines.extend(walk(c, depth + 1))
        return lines

    return walk(plan, 0)
