"""Lock-cheap per-query span/profile collector.

Reference analog: ClickHouse's per-query ProfileEvents and PG's
EXPLAIN ANALYZE instrumentation, re-expressed for the morsel/batch
executor: every PlanNode's batch generator is wrapped (exec/plan.py
auto-wraps subclasses), and the fused morsel pipeline stamps its stage
work directly (exec/morsel.py), so both the streaming operator tree and
the worker-pool path are covered by ONE collector.

Determinism contract: profiling observes, never steers. Each executing
thread accumulates into its own bucket (a thread-local dict — no lock on
the hot path after first touch); the sink merges buckets by summing
integer counters, so the merged numbers are independent of scheduling
order and the query result is bit-identical with profiling on or off at
any `serene_workers`. Wall/CPU nanoseconds in morsel pipelines are
summed per-worker task times (they can exceed elapsed wall clock on
purpose — that is the work the pool did).
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from collections import OrderedDict
from typing import Iterator, Optional

from ..utils import metrics

#: additive per-operator counters (merge = sum; scheduling-order free)
_COUNTERS = ("wall_ns", "cpu_ns", "rows_out", "batches", "bytes_out",
             "loops", "morsels_scheduled", "morsels_pruned",
             "morsels_jf_pruned", "device_ns", "batch_queries",
             "batch_window_ns", "batch_scoring_ns", "shard_pipelines",
             "shard_pruned", "shard_collective",
             "device_prog_hits", "device_prog_misses")


class OpStats:
    """One operator's accumulated span counters (one bucket's view)."""

    __slots__ = _COUNTERS + ("first_ns", "device_declined")

    def __init__(self):
        for f in _COUNTERS:
            setattr(self, f, 0)
        #: the operator's accumulated wall ns at its FIRST emitted batch
        #: (PG "startup time"; merge = min, thread-order free)
        self.first_ns: Optional[int] = None
        #: fused-tier decline reason slug (non-additive: one execution
        #: declines for one reason; merge keeps any observed value)
        self.device_declined: Optional[str] = None

    def merge(self, other: "OpStats") -> None:
        for f in _COUNTERS:
            setattr(self, f, getattr(self, f) + getattr(other, f))
        if other.first_ns is not None:
            self.first_ns = other.first_ns if self.first_ns is None \
                else min(self.first_ns, other.first_ns)
        if other.device_declined is not None:
            self.device_declined = other.device_declined


def batch_nbytes(b) -> int:
    """Materialized bytes of a batch's physical arrays (dictionary pages
    are shared, not per-batch — excluded)."""
    return sum(int(c.data.nbytes) for c in b.columns)


class QueryProfile:
    """Per-query collector keyed by id(plan node).

    Hot-path cost is one thread-local dict lookup plus integer adds per
    BATCH (never per row); batches are morsel-sized, so the budget is
    <3% on the profile_overhead bench shape.
    """

    def __init__(self):
        self._register_lock = threading.Lock()
        self._buckets: list[dict[int, OpStats]] = []
        self._tl = threading.local()
        self.t0_ns = time.perf_counter_ns()

    # -- accumulation (any thread) ----------------------------------------

    def _bucket(self) -> dict[int, OpStats]:
        d = getattr(self._tl, "d", None)
        if d is None:
            d = self._tl.d = {}
            with self._register_lock:
                self._buckets.append(d)
        return d

    def stats(self, key: int) -> OpStats:
        d = self._bucket()
        s = d.get(key)
        if s is None:
            s = d[key] = OpStats()
        return s

    def add_scan_morsels(self, key: int, scheduled: int = 0,
                         pruned: int = 0, jf_pruned: int = 0) -> None:
        """Morsel scheduling outcome for one scan. The three counters are
        DISJOINT (scheduled + pruned + jf_pruned = blocks considered):
        `pruned` is zone-map-only pruning, `jf_pruned` join-filter
        pruning, a block both would skip counts once under the join
        filter — so roll-ups never double-count a block."""
        s = self.stats(key)
        s.morsels_scheduled += int(scheduled)
        s.morsels_pruned += int(pruned)
        s.morsels_jf_pruned += int(jf_pruned)

    def add_stage(self, key: int, rows_out: int, wall_ns: int,
                  cpu_ns: int = 0, bytes_out: int = 0) -> None:
        """Fused-pipeline stamp: one morsel's pass through one operator
        (the operator's own batches() never runs in the fused path)."""
        s = self.stats(key)
        s.rows_out += int(rows_out)
        s.wall_ns += int(wall_ns)
        s.cpu_ns += int(cpu_ns)
        s.bytes_out += int(bytes_out)
        s.batches += 1

    def add_device_ns(self, key: int, ns: int) -> None:
        self.stats(key).device_ns += int(ns)

    def add_search_batch(self, key: int, queries: int, window_ns: int,
                         scoring_ns: int) -> None:
        """Search-batcher span for one top-k scan: how many queries its
        dispatch carried (1 = no coalescing), how long this query waited
        queued, and the shared scoring time of the whole dispatch — so
        EXPLAIN ANALYZE attributes both the batching win and its latency
        cost."""
        s = self.stats(key)
        s.batch_queries += int(queries)
        s.batch_window_ns += int(window_ns)
        s.batch_scoring_ns += int(scoring_ns)

    def add_shards(self, key: int, pipelines: int, pruned: int = 0,
                   collective: int = 0) -> None:
        """Sharded-tier span for one operator: how many per-shard
        pipelines its execution fanned out into (serene_shards > 1),
        how many blocks the shard-to-shard join filter pruned, and how
        many of the pipelines were combined IN-PROGRAM by a collective
        shard_map dispatch (serene_shard_combine=device) — the
        `Shards:` EXPLAIN ANALYZE detail line's n=/pruned=/combine=.
        All three are additive ints, so the order-free sink merge
        applies unchanged."""
        s = self.stats(key)
        s.shard_pipelines += int(pipelines)
        s.shard_pruned += int(pruned)
        s.shard_collective += int(collective)

    def wrap_batches(self, node, fn, ctx) -> Iterator:
        """Instrumented drive of a node's raw batch generator: wall time
        accrues only while inside next() (inclusive of children, PG
        semantics), rows/bytes per emitted batch."""
        key = id(node)
        self.stats(key).loops += 1
        it = fn(node, ctx)
        try:
            while True:
                t0 = time.perf_counter_ns()
                c0 = time.thread_time_ns()
                try:
                    b = next(it)
                except StopIteration:
                    s = self.stats(key)
                    s.wall_ns += time.perf_counter_ns() - t0
                    s.cpu_ns += time.thread_time_ns() - c0
                    return
                t1 = time.perf_counter_ns()
                s = self.stats(key)
                s.wall_ns += t1 - t0
                s.cpu_ns += time.thread_time_ns() - c0
                if s.first_ns is None:
                    s.first_ns = s.wall_ns
                s.rows_out += b.num_rows
                s.batches += 1
                s.bytes_out += batch_nbytes(b)
                yield b
        finally:
            it.close()

    # -- sink merge (call after execution has drained) --------------------

    def merged(self) -> dict[int, OpStats]:
        """Deterministic sink merge: per-thread buckets sum into one map.
        Integer addition is order-free, so the result is identical for
        any scheduling of the same work."""
        with self._register_lock:
            buckets = list(self._buckets)
        out: dict[int, OpStats] = {}
        for d in buckets:
            for key, s in d.items():
                agg = out.get(key)
                if agg is None:
                    out[key] = agg = OpStats()
                agg.merge(s)
        return out

    def totals(self) -> OpStats:
        """Whole-query roll-up of the prune counters (stat_statements
        attribution); rows/time roll-ups are per-operator, not summed."""
        t = OpStats()
        for s in self.merged().values():
            t.morsels_scheduled += s.morsels_scheduled
            t.morsels_pruned += s.morsels_pruned
            t.morsels_jf_pruned += s.morsels_jf_pruned
            t.device_ns += s.device_ns
        return t


# -- timeline tracing (serene_trace) ------------------------------------------
#
# The QueryProfile above answers "how much" per operator; the timeline
# layer answers "WHEN": every query gets a trace id and timestamped span
# events — (name, category, begin ns, end ns, thread, detail) — recorded
# into per-thread rings (a plain-list append under the GIL, no lock on
# the hot path after first touch, the same bucket pattern QueryProfile
# uses), so the pool's queue waits, batcher coalescing windows, shard
# fan-outs and device dispatch phases become one Chrome-trace-loadable
# timeline. Spans propagate across the worker pool via the CURRENT_TRACE
# contextvar (pool tasks copy the submitter's context), and a coalesced
# search dispatch stamps its spans under EVERY member query's trace.
# Like the profiler, tracing observes only — results are bit-identical
# with it on or off at any worker/shard count.

#: per-thread span ring cap: a runaway span producer degrades to
#: counting drops instead of growing without bound
TRACE_RING_CAP = 8192

_TRACE_IDS = itertools.count(1)

#: the executing statement's QueryTrace (None outside a traced
#: statement). Pool tasks capture the submitter's context at submit
#: time, so worker-thread spans land in the right query's timeline.
CURRENT_TRACE: contextvars.ContextVar = contextvars.ContextVar(
    "sdb_current_trace", default=None)


def current_trace():
    """The executing statement's trace, or None (tracing off / outside
    a statement). One contextvar read — cheap enough for hot-ish paths."""
    return CURRENT_TRACE.get()


class _Ring:
    __slots__ = ("tid", "thread_name", "spans", "dropped")

    def __init__(self, tid: int, thread_name: str):
        self.tid = tid
        self.thread_name = thread_name
        self.spans: list[tuple] = []
        self.dropped = 0


class QueryTrace:
    """One query's span-event collector.

    Spans are recorded at END time with explicit (begin, end)
    perf_counter_ns stamps, so within a thread they nest properly by
    construction (a span closes only after every span it started inside
    it). `add` appends to the calling thread's ring; rings merge at
    `finish()` into one begin-ordered span list with ns offsets relative
    to the trace start."""

    __slots__ = ("trace_id", "query", "t0_ns", "t0_epoch_us", "end_ns",
                 "error", "_register_lock", "_rings", "_tl", "_cv_token")

    def __init__(self, query_text: str = ""):
        self.trace_id = next(_TRACE_IDS)
        self.query = query_text
        self.t0_ns = time.perf_counter_ns()
        self.t0_epoch_us = int(time.time() * 1e6)
        self.end_ns: Optional[int] = None
        self.error: Optional[str] = None
        self._register_lock = threading.Lock()
        self._rings: list[_Ring] = []
        self._tl = threading.local()
        self._cv_token = None

    def now(self) -> int:
        return time.perf_counter_ns()

    def add(self, name: str, cat: str, begin_ns: int, end_ns: int,
            **detail) -> None:
        """Record one span event from any thread. begin/end are
        perf_counter_ns stamps (end >= begin enforced); detail keys
        become Chrome trace `args`. Span names are free-form; the
        "device" category carries device_compile, collective_dispatch
        and the posting pool's posting_upload (staged page writes) /
        posting_dispatch (batched gather-accumulate scoring) spans,
        "search" the batcher's batch_wait / batch_dispatch pair."""
        r = getattr(self._tl, "r", None)
        if r is None:
            t = threading.current_thread()
            r = self._tl.r = _Ring(t.ident or 0, t.name)
            with self._register_lock:
                self._rings.append(r)
        if len(r.spans) >= TRACE_RING_CAP:
            r.dropped += 1
            return
        r.spans.append((name, cat, begin_ns, max(end_ns, begin_ns),
                        detail or None))

    def finish(self, error: Optional[str] = None) -> dict:
        """Close the trace: stamp the root `query` span, merge the
        per-thread rings into one begin-ordered span list (offsets
        relative to the trace start) and return the flight-recorder
        entry dict."""
        self.end_ns = time.perf_counter_ns()
        self.error = error
        dur = self.end_ns - self.t0_ns
        with self._register_lock:
            rings = list(self._rings)
        spans = [{"name": "query", "cat": "query", "tid": 0,
                  "thread": "query", "begin_ns": 0, "end_ns": dur,
                  "args": {"query": self.query[:500],
                           "trace_id": self.trace_id}}]
        dropped = 0
        for r in rings:
            dropped += r.dropped
            for name, cat, b, e, detail in r.spans:
                spans.append({"name": name, "cat": cat, "tid": r.tid,
                              "thread": r.thread_name,
                              "begin_ns": b - self.t0_ns,
                              "end_ns": e - self.t0_ns,
                              "args": detail})
        spans.sort(key=lambda s: (s["begin_ns"], -s["end_ns"]))
        if dropped:
            metrics.TRACE_SPANS_DROPPED.add(dropped)
        # statement text truncates at entry-build time: every consumer
        # (listing, /_stats, chrome otherData) shows <= 500 chars, and
        # the always-on ring must not pin multi-MB INSERT literals
        return {"trace_id": self.trace_id, "query": self.query[:500],
                "begin_epoch_us": self.t0_epoch_us,
                "duration_ns": dur, "error": error,
                "spans": spans, "spans_dropped": dropped,
                # stamped by the statement-end hook when
                # serene_mem_account ran (engine._finish_trace /
                # execute_streaming): the query's accounted peak bytes
                "peak_bytes": None}


class FlightRecorder:
    """Always-on bounded ring of the last N completed query timelines
    (`serene_flight_recorder_queries`, default 64): the slow-query log
    and error paths read a stall's timeline AFTER the fact instead of
    asking for a reproduction. One short lock per statement END."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[int, dict]" = OrderedDict()

    def _cap(self) -> int:
        from ..utils.config import REGISTRY
        try:
            return max(1, int(REGISTRY.get_global(
                "serene_flight_recorder_queries")))
        except KeyError:  # pragma: no cover — registry declares it
            return 64

    def record(self, entry: dict) -> dict:
        cap = self._cap()
        with self._lock:
            self._entries[entry["trace_id"]] = entry
            while len(self._entries) > cap:
                self._entries.popitem(last=False)   # oldest completes out
        metrics.TRACES_RECORDED.add()
        return entry

    def get(self, trace_id: int) -> Optional[dict]:
        with self._lock:
            return self._entries.get(int(trace_id))

    def last(self) -> Optional[dict]:
        with self._lock:
            if not self._entries:
                return None
            return next(reversed(self._entries.values()))

    def snapshot(self) -> list[dict]:
        """Newest-last entry list (shared references — treat as
        read-only)."""
        with self._lock:
            return list(self._entries.values())

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


#: process-wide flight recorder (one per process, like the metrics
#: registry)
FLIGHT = FlightRecorder()


def flight_summary(entry: dict) -> dict:
    """One flight entry as the compact listing dict — the single shape
    behind the GET /trace index and /_stats.traces, so the surfaces
    can't drift field by field."""
    return {"trace_id": entry["trace_id"],
            "query": entry["query"][:200],
            "duration_ms": round(entry["duration_ns"] / 1e6, 3),
            "spans": len(entry["spans"]),
            "spans_dropped": entry["spans_dropped"],
            "peak_bytes": entry.get("peak_bytes"),
            "error": entry["error"]}


def top_spans(entry: dict, n: int = 5) -> list[dict]:
    """The n widest non-root spans of a recorded timeline (slow-query
    log attachment)."""
    inner = [s for s in entry["spans"] if s["cat"] != "query"]
    inner.sort(key=lambda s: s["end_ns"] - s["begin_ns"], reverse=True)
    return inner[:n]


def format_top_spans(entry: dict, n: int = 5) -> list[str]:
    lines = [f"timeline: trace_id={entry['trace_id']} "
             f"duration={_ms(entry['duration_ns'])} ms "
             f"spans={len(entry['spans'])}"]
    for s in top_spans(entry, n):
        det = ""
        if s["args"]:
            det = " " + " ".join(f"{k}={v}" for k, v in s["args"].items())
        lines.append(
            f"  span {s['cat']}/{s['name']} "
            f"[{_ms(s['begin_ns'])}..{_ms(s['end_ns'])} ms] "
            f"thread={s['thread']}{det}")
    return lines


def chrome_trace(entry: dict) -> dict:
    """One flight-recorder entry as Chrome trace-event JSON (`ph: "X"`
    complete events, ts/dur in µs relative to the query start) —
    loadable in Perfetto / chrome://tracing as-is."""
    events: list[dict] = []
    tids = {0: "query"}
    for s in entry["spans"]:
        tids.setdefault(s["tid"], s["thread"])
        ev = {"name": s["name"], "cat": s["cat"], "ph": "X",
              "ts": s["begin_ns"] / 1e3,
              "dur": (s["end_ns"] - s["begin_ns"]) / 1e3,
              "pid": 1, "tid": s["tid"]}
        if s["args"]:
            ev["args"] = dict(s["args"])
        events.append(ev)
    meta = [{"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": f"serenedb query {entry['trace_id']}"}}]
    for tid, tname in tids.items():
        meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                     "tid": tid, "args": {"name": tname}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": entry["trace_id"],
                          "query": entry["query"][:500],
                          "begin_epoch_us": entry["begin_epoch_us"],
                          "duration_ms": entry["duration_ns"] / 1e6,
                          "error": entry["error"],
                          "peak_bytes": entry.get("peak_bytes"),
                          "spans_dropped": entry["spans_dropped"]}}


def _ms(ns: int) -> str:
    return f"{ns / 1e6:.3f}"


def annotate_plan(plan, profile: QueryProfile, mem=None) -> list[str]:
    """EXPLAIN ANALYZE rendering: the plan tree with PG-style
    `(actual time=first..total rows=N loops=L)` suffixes plus prune /
    device detail lines, and per-operator `Memory: peak=… live=…`
    lines when a MemoryAccountant ran (serene_mem_account). Nodes the
    executor fused away (device offload) render `(never executed)`
    like PG's unvisited branches."""
    from .resources import fmt_kb
    merged = profile.merged()
    mem_merged = mem.merged() if mem is not None else {}

    def mem_line(pad: str, node) -> list[str]:
        m = mem_merged.get(id(node))
        if m is None:
            return []
        live, peak = m
        return [f"{pad}Memory: peak={fmt_kb(peak)} "
                f"live={fmt_kb(max(live, 0))}"]

    def walk(node, depth: int) -> list[str]:
        pad = "  " * depth
        s = merged.get(id(node))
        if s is None:
            lines = [f"{pad}{node.label()} (never executed)"]
            lines.extend(mem_line(pad + "  ", node))
        else:
            first = s.first_ns if s.first_ns is not None else s.wall_ns
            lines = [f"{pad}{node.label()} "
                     f"(actual time={_ms(first)}..{_ms(s.wall_ns)} "
                     f"rows={s.rows_out} loops={max(s.loops, 1)})"]
            detail = pad + "  "
            if s.morsels_scheduled or s.morsels_pruned:
                jf = (f" join_filter_pruned={s.morsels_jf_pruned}"
                      if s.morsels_jf_pruned else "")
                lines.append(
                    f"{detail}Morsels: scheduled={s.morsels_scheduled} "
                    f"zonemap_pruned={s.morsels_pruned}{jf}")
            if s.device_ns or s.device_declined:
                comp = ""
                if s.device_prog_hits or s.device_prog_misses:
                    # any miss means this execution paid (at least one)
                    # XLA compile; all-hit means every program came
                    # from the ledger warm (obs/device.py)
                    comp = " compile=" + \
                        ("miss" if s.device_prog_misses else "hit")
                dec = (f" declined={s.device_declined}"
                       if s.device_declined else "")
                lines.append(
                    f"{detail}Device: time={_ms(s.device_ns)} "
                    f"ms{comp}{dec}")
            if s.batch_queries:
                lines.append(
                    f"{detail}Batch: queries={s.batch_queries} "
                    f"window={_ms(s.batch_window_ns)} ms "
                    f"shared_scoring={_ms(s.batch_scoring_ns)} ms")
            if s.shard_pipelines or s.shard_pruned:
                combine = "device" if s.shard_collective else "host"
                lines.append(f"{detail}Shards: n={s.shard_pipelines} "
                             f"pruned={s.shard_pruned} "
                             f"combine={combine}")
            lines.extend(mem_line(detail, node))
        for c in node.children():
            lines.extend(walk(c, depth + 1))
        return lines

    return walk(plan, 0)


def annotate_plan_json(plan, profile: Optional[QueryProfile],
                       mem=None) -> dict:
    """EXPLAIN (FORMAT JSON) rendering: the plan tree as a
    machine-readable object — PG's JSON key shapes where they map
    ("Node Type", "Actual Total Time", "Actual Rows", "Plans"), plus the
    engine's prune / device / batch / shard detail as flat keys instead
    of the text renderer's detail lines, and per-operator "Peak Memory
    Bytes" / "Live Memory Bytes" when a MemoryAccountant ran.
    profile=None renders structure only (plain EXPLAIN)."""
    merged = profile.merged() if profile is not None else {}
    mem_merged = mem.merged() if mem is not None else {}

    def stamp_mem(out: dict, node) -> None:
        m = mem_merged.get(id(node))
        if m is not None:
            out["Peak Memory Bytes"] = m[1]
            out["Live Memory Bytes"] = max(m[0], 0)

    def walk(node) -> dict:
        out: dict = {"Node Type": node.label()}
        if profile is not None:
            s = merged.get(id(node))
            if s is None:
                out["Never Executed"] = True
                stamp_mem(out, node)
            else:
                first = s.first_ns if s.first_ns is not None else s.wall_ns
                out["Actual Startup Time"] = round(first / 1e6, 3)
                out["Actual Total Time"] = round(s.wall_ns / 1e6, 3)
                out["Actual Rows"] = s.rows_out
                out["Actual Loops"] = max(s.loops, 1)
                if s.morsels_scheduled or s.morsels_pruned:
                    out["Morsels Scheduled"] = s.morsels_scheduled
                    out["Morsels Zonemap Pruned"] = s.morsels_pruned
                    if s.morsels_jf_pruned:
                        out["Morsels Join Filter Pruned"] = \
                            s.morsels_jf_pruned
                if s.device_ns:
                    out["Device Time"] = round(s.device_ns / 1e6, 3)
                    if s.device_prog_hits or s.device_prog_misses:
                        out["Device Compile"] = \
                            "miss" if s.device_prog_misses else "hit"
                if s.device_declined:
                    out["Device Declined"] = s.device_declined
                if s.batch_queries:
                    out["Batch Queries"] = s.batch_queries
                    out["Batch Window Time"] = \
                        round(s.batch_window_ns / 1e6, 3)
                    out["Batch Shared Scoring Time"] = \
                        round(s.batch_scoring_ns / 1e6, 3)
                if s.shard_pipelines or s.shard_pruned:
                    out["Shard Pipelines"] = s.shard_pipelines
                    out["Shard Morsels Pruned"] = s.shard_pruned
                    out["Shard Combine"] = \
                        "device" if s.shard_collective else "host"
                stamp_mem(out, node)
        kids = node.children()
        if kids:
            out["Plans"] = [walk(c) for c in kids]
        return out

    return walk(plan)
