"""Query observability: per-operator profiling, statement statistics,
metrics export (ISSUE 4).

The instrument panel for every later perf PR: `obs.trace` collects
per-operator spans (rows, wall+CPU time, morsel prune counters, bytes,
device time) with per-worker-thread accumulation and a deterministic
sink merge, `obs.statements` keeps the `sdb_stat_statements` registry
keyed by normalized query fingerprint, and `obs.export` renders the
Prometheus `/metrics` and JSON `/_stats` payloads. Everything is gated
by `serene_profile` (default on) and observes only — results are
bit-identical with profiling on or off, at any worker count.
"""
