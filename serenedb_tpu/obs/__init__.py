"""Query observability: per-operator profiling, timeline tracing,
statement statistics, metrics export (ISSUES 4 + 10).

The instrument panel for every later perf PR: `obs.trace` collects
per-operator spans (rows, wall+CPU time, morsel prune counters, bytes,
device time) with per-worker-thread accumulation and a deterministic
sink merge, AND the per-query timeline layer (trace ids, timestamped
span events in per-thread rings, the always-on flight recorder, Chrome
trace export); `obs.statements` keeps the `sdb_stat_statements`
registry keyed by normalized query fingerprint (with per-fingerprint
latency percentiles); `obs.device` is the device tier's nervous system
(ISSUE 15): the XLA compile ledger every `jax.jit` site routes through
(bounded program LRU, per-family compile stats, recompile-storm
detection), host↔device transfer accounting and per-device dispatch /
HBM attribution, surfaced via `sdb_device()`/`sdb_programs()`/
`sdb_device_cache()` and `GET /device`; `obs.export` renders the
Prometheus `/metrics` (gauges + latency histograms) and JSON `/_stats`
payloads. Profiling is gated by `serene_profile`, timelines by
`serene_trace`, device telemetry by `serene_device_telemetry` (all
default on) and all observe only — results are bit-identical with them
on or off, at any worker/shard count.
"""
