"""Query observability: per-operator profiling, timeline tracing,
statement statistics, metrics export (ISSUES 4 + 10).

The instrument panel for every later perf PR: `obs.trace` collects
per-operator spans (rows, wall+CPU time, morsel prune counters, bytes,
device time) with per-worker-thread accumulation and a deterministic
sink merge, AND the per-query timeline layer (trace ids, timestamped
span events in per-thread rings, the always-on flight recorder, Chrome
trace export); `obs.statements` keeps the `sdb_stat_statements`
registry keyed by normalized query fingerprint (with per-fingerprint
latency percentiles); `obs.export` renders the Prometheus `/metrics`
(gauges + latency histograms) and JSON `/_stats` payloads. Profiling
is gated by `serene_profile`, timelines by `serene_trace` (both default
on) and both observe only — results are bit-identical with them on or
off, at any worker/shard count.
"""
