"""Device telemetry: XLA compile ledger, transfer accounting, and
per-device HBM attribution (`serene_device_telemetry`, ISSUE 15).

PRs 7/9/11 made the device tier the execution flagship — one jitted
dispatch per query over publication-cached HBM columns — but it was the
only tier with no observability of its own: the program cache was an
unbounded bare dict, compiles were invisible, and nothing said which
physical device ran a dispatch or what occupied HBM. This module is the
device tier's nervous system, three ledgers behind one switch:

- **Compile ledger** (`compiled(family, key, builder)`): THE single
  entry point every `jax.jit` site routes through — `device_agg`,
  `device_topn`, `device_pipeline`'s single/build/probe/collective/
  top-N programs, plus the mesh/search/scoring programs — so a grep
  for bare `jax.jit(` outside this file comes back empty. It owns the program
  cache as a BOUNDED LRU (`serene_program_cache_entries`, default 256;
  the PR 7 dict leaked one compiled executable per novel query shape
  for process lifetime) and records per-family compile counts, compile
  wall time (first-call trace: the first invocation of a jitted
  program IS its compile, stamped into the `DeviceCompile` histogram
  and a `device_compile` trace span), hit/miss gauges, and
  recompile-storm detection (one family compiling
  > RECOMPILE_STORM_PER_MIN new shapes per minute → a `device`-topic
  warning + the `DeviceRecompileStorms` gauge — the "your cache key
  churns every query" alarm an ML serving stack fires on retrace
  storms).

- **Transfer + dispatch ledger**: byte/time accounting at every
  host→device commit (`columnar.device.to_device_column`, the
  DEVICE_CACHE typed helpers, the collective stacked-tile commits) and
  device→host fetch (`fetch_all` at the program-output readbacks),
  attributed per physical jax device id, plus per-device dispatch
  counts (stamped from each program invocation's output placement).

- **HBM attribution**: DEVICE_CACHE occupancy split per device (entry
  bytes divided across the devices holding them) — the live-bytes
  estimate `sdb_device()` reports, and the signal the ROADMAP's paged
  postings pool will be tuned against.

Surfaces: `sdb_device()` / `sdb_programs()` / `sdb_device_cache()`
relations (pgcatalog), `GET /device`, the `/_stats` `device` section,
Prometheus gauges + the `DeviceCompile` histogram in `/metrics`, and
the EXPLAIN ANALYZE `Device:` line's `compile=hit|miss` key.

Observe-only contract (the serene_profile/serene_trace discipline):
telemetry NEVER changes which program runs — the LRU is keyed
identically on or off, `compiled()` returns the same executable either
way, and every note_* call is a counter bump. Results are bit-identical
with telemetry on or off at any worker/shard/combine setting
(tests/test_device_obs.py parity matrix; the only behavioral change is
the cache BOUND itself, which can only cause a re-compile of the same
program, never a different one).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from ..utils import log, metrics
from ..utils.config import REGISTRY as _settings

#: new compiles of ONE family within a 60s window that trip the
#: recompile-storm warning (a healthy steady state compiles each query
#: shape once and hits forever after)
RECOMPILE_STORM_PER_MIN = 8
_STORM_WINDOW_S = 60.0
#: storms re-warn at most this often per family (the log is a signal,
#: not a flood)
_STORM_RELOG_S = 30.0


def enabled() -> bool:
    """One registry read — the whole module keys off this switch."""
    try:
        return bool(_settings.get_global("serene_device_telemetry"))
    except KeyError:  # pragma: no cover — registry declares it
        return True


def _cap() -> int:
    try:
        return max(1, int(_settings.get_global(
            "serene_program_cache_entries")))
    except KeyError:  # pragma: no cover — registry declares it
        return 256


# -- device-id helpers --------------------------------------------------------


def array_device_ids(arr) -> tuple:
    """Physical device ids holding a jax array (sorted; () when the
    placement cannot be read — accounting degrades, never raises)."""
    try:
        devs = arr.devices()                  # jax.Array: set of Device
        return tuple(sorted(d.id for d in devs))
    except Exception:  # noqa: BLE001 — older array types / numpy
        dev = getattr(arr, "device", None)
        if dev is not None and not callable(dev) and hasattr(dev, "id"):
            return (int(dev.id),)
    return ()


def value_device_ids(value) -> tuple:
    """Device ids of a cached value: a DeviceColumn (its data tiles), a
    tuple of arrays (union), or one array."""
    data = getattr(value, "data", None)
    if data is not None and hasattr(value, "mask"):    # DeviceColumn
        return array_device_ids(data)
    if isinstance(value, (tuple, list)):
        ids: set = set()
        for v in value:
            ids.update(value_device_ids(v))
        return tuple(sorted(ids))
    return array_device_ids(value)


def _first_jax_leaf(out):
    if isinstance(out, (tuple, list)):
        for e in out:
            leaf = _first_jax_leaf(e)
            if leaf is not None:
                return leaf
        return None
    return out if hasattr(out, "devices") or hasattr(out, "device") \
        else None


# -- transfer + dispatch ledger ----------------------------------------------


_DEV_FIELDS = ("dispatches", "bytes_up", "transfers_up", "up_ns",
               "bytes_down", "transfers_down", "down_ns")


class DeviceLedger:
    """Per-physical-device counters: dispatches executed, bytes/time
    moved host→device (uploads + stacked commits) and device→host
    (result fetches). Multi-device commits (mesh shardings, replicated
    build outputs) split bytes evenly across the participating devices
    — an attribution, not a wire measurement."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dev: dict[int, dict] = {}

    def _slot(self, did: int) -> dict:
        d = self._dev.get(did)
        if d is None:
            d = self._dev[did] = {f: 0 for f in _DEV_FIELDS}
        return d

    def note_dispatch(self, ids) -> None:
        with self._lock:
            for i in (ids or (0,)):
                self._slot(int(i))["dispatches"] += 1

    def note_upload(self, nbytes: int, ids, ns: int = 0) -> None:
        ids = ids or (0,)
        share = len(ids)
        with self._lock:
            for i in ids:
                s = self._slot(int(i))
                s["bytes_up"] += int(nbytes) // share
                s["transfers_up"] += 1
                s["up_ns"] += int(ns) // share

    def note_fetch(self, nbytes: int, ids, ns: int = 0) -> None:
        ids = ids or (0,)
        share = len(ids)
        with self._lock:
            for i in ids:
                s = self._slot(int(i))
                s["bytes_down"] += int(nbytes) // share
                s["transfers_down"] += 1
                s["down_ns"] += int(ns) // share

    def snapshot(self) -> dict[int, dict]:
        with self._lock:
            return {i: dict(v) for i, v in self._dev.items()}

    def reset(self) -> None:
        with self._lock:
            self._dev.clear()


LEDGER = DeviceLedger()


def note_upload(nbytes: int, ids, ns: int = 0) -> None:
    """Host→device transfer accounting choke point (observe-only; no-op
    when telemetry is off)."""
    if enabled():
        LEDGER.note_upload(nbytes, ids, ns)
        metrics.DEVICE_TRANSFERS_UP.add()


def note_fetch(nbytes: int, ids, ns: int = 0) -> None:
    if enabled():
        LEDGER.note_fetch(nbytes, ids, ns)
        metrics.DEVICE_FETCH_BYTES.add(int(nbytes))


def fetch_all(outs) -> list:
    """Device→host readback of a program's outputs (the np.asarray
    choke point): returns numpy arrays, accounting bytes/time per
    device. Conversion is what every call site did anyway — telemetry
    adds only the clock reads and one ledger bump."""
    import numpy as np
    if not enabled():
        return [np.asarray(o) for o in outs]
    leaf = _first_jax_leaf(outs)
    ids = array_device_ids(leaf) if leaf is not None else ()
    t0 = time.perf_counter_ns()
    arrs = [np.asarray(o) for o in outs]
    # direct ledger calls — the enabled() gate already ran above, and
    # re-checking inside note_fetch would take the settings-registry
    # lock a second time on the per-dispatch hot path
    nbytes = sum(int(a.nbytes) for a in arrs)
    LEDGER.note_fetch(nbytes, ids, time.perf_counter_ns() - t0)
    metrics.DEVICE_FETCH_BYTES.add(nbytes)
    return arrs


def commit(x, target):
    """`jax.device_put` with upload accounting — the direct-commit
    sites that bypass DEVICE_CACHE (the sharded search merge's
    candidate planes)."""
    import jax
    if not enabled():
        return jax.device_put(x, target)
    t0 = time.perf_counter_ns()
    arr = jax.device_put(x, target)
    LEDGER.note_upload(int(arr.size * arr.dtype.itemsize),
                       array_device_ids(arr),
                       time.perf_counter_ns() - t0)
    metrics.DEVICE_TRANSFERS_UP.add()
    return arr


# -- provider-token naming (sdb_device_cache's table column) ------------------

_TOKEN_NAMES: dict[int, str] = {}
_TOKEN_NAMES_MAX = 1024
_token_names_lock = threading.Lock()


def note_provider(token: int, name: str) -> None:
    """Remember a publication token's table name (DEVICE_CACHE keys
    carry only the token; the relation surface wants the name). Bounded
    FIFO — tokens are minted per provider OBJECT, so DROP+CREATE churn
    would otherwise grow this for process lifetime (the exact
    leak-per-novel-key shape this PR fixes in the program cache)."""
    if _TOKEN_NAMES.get(token) != name:
        with _token_names_lock:
            while len(_TOKEN_NAMES) >= _TOKEN_NAMES_MAX:
                _TOKEN_NAMES.pop(next(iter(_TOKEN_NAMES)))
            _TOKEN_NAMES[token] = str(name)


def provider_name(token: int) -> str:
    return _TOKEN_NAMES.get(token, "")


# -- compile ledger -----------------------------------------------------------


class CompiledProgram:
    """One ledger-owned jitted program. The FIRST invocation of a jit
    wrapper is its trace+compile; this wrapper times it (the tiny-input
    warm-call school of compile measurement: wall time of call #1),
    feeds the `DeviceCompile` histogram + family stats, stamps a
    `device_compile` trace span so flight-recorder timelines attribute
    first-query compile stalls, and counts a per-device dispatch on
    every call. Steady-state overhead is one flag read + one enabled()
    check per dispatch."""

    __slots__ = ("fn", "family", "compile_ns", "_timed")

    def __init__(self, fn: Callable, family: str):
        self.fn = fn
        self.family = family
        self.compile_ns: Optional[int] = None
        self._timed = False

    def __call__(self, *args):
        if self._timed:
            if enabled():
                out = self.fn(*args)
                leaf = _first_jax_leaf(out)
                LEDGER.note_dispatch(
                    array_device_ids(leaf) if leaf is not None else ())
                return out
            return self.fn(*args)
        # first call: benign race — two threads may both time; the
        # ledger records both observations, results are identical
        self._timed = True
        if not enabled():
            return self.fn(*args)
        t0 = time.perf_counter_ns()
        out = self.fn(*args)
        ns = time.perf_counter_ns() - t0
        self.compile_ns = ns
        PROGRAMS.record_compile_time(self.family, ns)
        from .trace import current_trace
        tr = current_trace()
        if tr is not None:
            tr.add("device_compile", "device", t0, t0 + ns,
                   family=self.family)
        leaf = _first_jax_leaf(out)
        LEDGER.note_dispatch(
            array_device_ids(leaf) if leaf is not None else ())
        return out


def _new_family() -> dict:
    return {"entries": 0, "compiles": 0, "hits": 0, "misses": 0,
            "evictions": 0, "compile_ns": 0, "timed": 0,
            "last_compile_ns": 0, "storms": 0}


class ProgramLedger:
    """THE process-wide program cache (the `_PROGRAM_CACHE` successor):
    a bounded LRU of CompiledProgram wrappers keyed by
    (family, site key), plus per-family compile statistics. The bound
    fixes the PR 7 leak — before this, every novel (publication, query
    shape) pair pinned a compiled XLA executable for process lifetime —
    and eviction genuinely frees: dropping the wrapper drops the jit
    object, and a re-request re-compiles through the same builder."""

    def __init__(self):
        self._lock = threading.Lock()
        self._progs: "OrderedDict[tuple, CompiledProgram]" = OrderedDict()
        self._fams: dict[str, dict] = {}
        self._storm_t: dict[str, deque] = {}
        self._storm_warned: dict[str, float] = {}

    def _fam(self, family: str) -> dict:
        f = self._fams.get(family)
        if f is None:
            f = self._fams[family] = _new_family()
        return f

    def get(self, family: str, key: tuple, builder: Callable,
            profile=None, node_key=None,
            donate_argnums=None) -> CompiledProgram:
        on = enabled()
        full = (family, key)
        with self._lock:
            prog = self._progs.get(full)
            if prog is not None:
                self._progs.move_to_end(full)
                if on:
                    self._fam(family)["hits"] += 1
                    metrics.DEVICE_PROGRAM_HITS.add()
                    if profile is not None and node_key is not None:
                        profile.stats(node_key).device_prog_hits += 1
                return prog
        # build OUTSIDE the lock: jit-wrapper creation is cheap but the
        # builder may construct meshes/shard_maps; a racing duplicate
        # build is wasted work, never wrong (the loser is discarded)
        import jax
        if donate_argnums:
            # chained-stage handoff: the caller proves the donated
            # buffers are dead after this dispatch (stage-1 outputs
            # consumed exactly once), so XLA may alias them into the
            # stage-2 outputs — zero-copy HBM reuse between stages
            fn = jax.jit(builder(),
                         donate_argnums=tuple(donate_argnums))
        else:
            fn = jax.jit(builder())
        prog = CompiledProgram(fn, family)
        with self._lock:
            cur = self._progs.get(full)
            if cur is not None:
                self._progs.move_to_end(full)
                if on:
                    self._fam(family)["hits"] += 1
                    metrics.DEVICE_PROGRAM_HITS.add()
                    if profile is not None and node_key is not None:
                        profile.stats(node_key).device_prog_hits += 1
                return cur
            self._progs[full] = prog
            if on:
                fam = self._fam(family)
                fam["misses"] += 1
                fam["compiles"] += 1
                metrics.DEVICE_PROGRAM_MISSES.add()
                metrics.DEVICE_PROGRAMS_COMPILED.add()
                if profile is not None and node_key is not None:
                    profile.stats(node_key).device_prog_misses += 1
                self._note_storm(family, fam)
            cap = _cap()
            # the cap is STRUCTURAL (it bounds HBM/host memory) and
            # applies with telemetry off too — but dark means dark:
            # the stats/gauges move only when the switch is on, so the
            # surfaces can never show evictions against frozen misses
            while len(self._progs) > cap:
                (efam, _ekey), _ = self._progs.popitem(last=False)
                if on:
                    metrics.DEVICE_PROGRAM_EVICTIONS.add()
                    self._fam(efam)["evictions"] += 1
            if on:
                metrics.DEVICE_PROGRAM_ENTRIES.set(len(self._progs))
        return prog

    def _note_storm(self, family: str, fam: dict) -> None:
        """Called under self._lock on every miss-compile: a family
        re-compiling > RECOMPILE_STORM_PER_MIN new shapes per minute
        means repeat queries are NOT reusing executables (a churning
        cache key — the retrace-storm failure mode of ML serving)."""
        now = time.monotonic()
        dq = self._storm_t.get(family)
        if dq is None:
            dq = self._storm_t[family] = deque()
        dq.append(now)
        while dq and now - dq[0] > _STORM_WINDOW_S:
            dq.popleft()
        if len(dq) > RECOMPILE_STORM_PER_MIN and \
                now - self._storm_warned.get(family, -1e18) >= \
                _STORM_RELOG_S:
            self._storm_warned[family] = now
            fam["storms"] += 1
            metrics.DEVICE_RECOMPILE_STORMS.add()
            log.warn("device",
                     f"recompile storm: program family '{family}' "
                     f"compiled {len(dq)} new shapes in the last 60s — "
                     "repeat queries are not reusing cached executables "
                     "(churning cache key, or serene_program_cache_"
                     "entries too small for the live query mix)")

    def record_compile_time(self, family: str, ns: int) -> None:
        with self._lock:
            f = self._fam(family)
            f["compile_ns"] += int(ns)
            f["timed"] += 1
            f["last_compile_ns"] = int(ns)
        metrics.DEVICE_COMPILE_HIST.observe_ns(ns)

    def entries(self) -> int:
        with self._lock:
            return len(self._progs)

    def snapshot(self) -> list[dict]:
        """One row per program family, sorted — the sdb_programs()
        relation body."""
        with self._lock:
            per_fam_entries: dict[str, int] = {}
            for fam, _k in self._progs:
                per_fam_entries[fam] = per_fam_entries.get(fam, 0) + 1
            rows = []
            for fam in sorted(self._fams):
                f = self._fams[fam]
                rows.append({
                    "family": fam,
                    "entries": per_fam_entries.get(fam, 0),
                    "compiles": f["compiles"],
                    "hits": f["hits"],
                    "misses": f["misses"],
                    "evictions": f["evictions"],
                    "storms": f["storms"],
                    "compile_ms_total": round(f["compile_ns"] / 1e6, 3),
                    "compile_ms_mean": round(
                        f["compile_ns"] / max(f["timed"], 1) / 1e6, 3),
                    "last_compile_ms": round(
                        f["last_compile_ns"] / 1e6, 3)})
        return rows

    def family(self, name: str) -> dict:
        with self._lock:
            return dict(self._fams.get(name) or _new_family())

    def clear(self) -> None:
        """Drop every cached program AND the family statistics (tests /
        bench cold-compile measurement)."""
        with self._lock:
            self._progs.clear()
            self._fams.clear()
            self._storm_t.clear()
            self._storm_warned.clear()
            metrics.DEVICE_PROGRAM_ENTRIES.set(0)


PROGRAMS = ProgramLedger()


def compiled(family: str, key: tuple, builder: Callable, *,
             profile=None, node_key=None,
             donate_argnums=None) -> CompiledProgram:
    """THE jit entry point (acceptance grep: no bare `jax.jit(` outside
    this module). `builder` is a zero-arg callable returning the python
    callable to jit (a traced program body, or a shard_map-wrapped
    one); it runs only on a ledger miss. `profile`/`node_key` stamp the
    hit/miss onto the plan operator so EXPLAIN ANALYZE's `Device:` line
    can say `compile=hit|miss`. `donate_argnums` forwards to jax.jit
    for chained-stage buffer handoff (and keys the cached executable
    implicitly: callers pass it consistently per cache key)."""
    return PROGRAMS.get(family, key, builder, profile=profile,
                        node_key=node_key, donate_argnums=donate_argnums)


# -- fused-tier decline accounting -------------------------------------------

#: reason slug → count of fused-tier declines (queries that fell back
#: to the host path and why) — the satellite-1 diagnosis surface
_FUSED_DECLINES: dict[str, int] = {}
_fused_declines_lock = threading.Lock()


def note_fused_decline(reason: str, profile=None, node_key=None) -> None:
    """One fused-tier fallback: count it per reason slug (bounded
    vocabulary — call sites pass short category strings, never query
    text), bump the per-reason `DeviceFusedDeclines_<reason>` gauge,
    and stamp the reason onto the plan operator so EXPLAIN ANALYZE's
    `Device:` line can say `declined=<reason>`."""
    reason = str(reason)[:64]
    with _fused_declines_lock:
        _FUSED_DECLINES[reason] = _FUSED_DECLINES.get(reason, 0) + 1
    metrics.REGISTRY.gauge(
        f"DeviceFusedDeclines_{reason}",
        "fused device pipeline declines for this reason (query fell "
        "back to the host path)").add()
    if profile is not None and node_key is not None:
        profile.stats(node_key).device_declined = reason


def fused_declines() -> dict[str, int]:
    with _fused_declines_lock:
        return dict(sorted(_FUSED_DECLINES.items()))


# -- surfaces -----------------------------------------------------------------


def device_rows() -> list[dict]:
    """One row per physical device: dispatches, transfer bytes/time
    up/down, and the HBM live-bytes estimate (DEVICE_CACHE occupancy —
    column tiles, code tiles, row masks, cached build outputs — split
    per holding device). Lists every jax device when a backend is
    already initialized (PASSIVE probe — a pure-host process must not
    pay backend init for a stats read), else only devices the ledger
    has seen."""
    from ..exec.device_pipeline import DEVICE_CACHE
    from ..parallel import mesh as mesh_mod
    from ..search.posting_pool import POOL
    from ..search.vector_store import VPOOL
    cache_bytes = DEVICE_CACHE.device_bytes()
    for pool in (POOL, VPOOL):
        # the posting pool's and vector pool's paged regions are
        # HBM-live alongside the column cache — one estimate covers
        # every tenant
        for i, n in pool.device_bytes().items():
            cache_bytes[i] = cache_bytes.get(i, 0) + n
    snap = LEDGER.snapshot()
    devs = {}
    if mesh_mod.device_count_if_initialized():
        import jax
        devs = {d.id: d for d in jax.devices()}
    ids = sorted(set(snap) | set(cache_bytes) | set(devs))
    zeros = {f: 0 for f in _DEV_FIELDS}
    rows = []
    for i in ids:
        s = snap.get(i, zeros)
        d = devs.get(i)
        rows.append({
            "device": i,
            "platform": getattr(d, "platform", ""),
            "kind": getattr(d, "device_kind", ""),
            "dispatches": s["dispatches"],
            "bytes_up": s["bytes_up"],
            "transfers_up": s["transfers_up"],
            "up_ms": round(s["up_ns"] / 1e6, 3),
            "bytes_down": s["bytes_down"],
            "transfers_down": s["transfers_down"],
            "down_ms": round(s["down_ns"] / 1e6, 3),
            "hbm_bytes_est": cache_bytes.get(i, 0)})
    return rows


def device_cache_rows() -> list[dict]:
    """One row per DEVICE_CACHE entry with the provider token resolved
    to its table name — the per-publication/column HBM occupancy view."""
    from ..exec.device_pipeline import DEVICE_CACHE
    rows = DEVICE_CACHE.snapshot()
    for r in rows:
        r["table"] = provider_name(r["token"])
    return rows


def stats_section() -> dict:
    """The `/_stats` / `GET /device` JSON payload: per-device ledger
    rows, the compile ledger, and the program/column cache summaries."""
    from ..exec.device_pipeline import DEVICE_CACHE
    from ..search.posting_pool import POOL
    from ..search.vector_store import VPOOL
    return {"devices": device_rows(),
            "programs": PROGRAMS.snapshot(),
            "program_cache": {"entries": PROGRAMS.entries(),
                              "cap": _cap()},
            "column_cache": DEVICE_CACHE.stats(),
            "posting_pool": POOL.stats(),
            "vector_pool": VPOOL.stats(),
            "fused_declines": fused_declines()}
