"""Resource observability: per-query memory accounting, live query
progress, and PG-style wait events (`serene_mem_account`).

PR 10 gave every query a TIME axis (span timelines, latency
histograms); this module is the RESOURCE axis — the prerequisite for
admission control and `serene_work_mem` budgets: you cannot enforce a
memory ceiling you cannot observe.

Three facilities share one per-statement object:

- **MemoryAccountant** — live/peak byte accounting charged at the
  sites the profiler already instruments: operator batch
  materialization (`batch_nbytes`), join build/probe sides and pair
  arrays, sort buffers, morsel partials, device uploads (the
  DEVICE_CACHE byte math), result-cache stores. Accumulation is
  per-worker-thread and lock-free after first touch (the QueryProfile
  bucket pattern); the sink merge SUMS per-thread peaks, so the merged
  peak is a sound upper bound on the true simultaneous peak: at any
  instant t, total live = Σ_threads live_t(thread) ≤ Σ_threads
  max_t live(thread). Charging at materialization sites bounds the
  true peak because every byte a query holds was materialized at one
  of them.

- **Query progress** — the same per-thread buckets count rows/bytes
  processed and morsels scheduled/completed, and the accountant
  registers in the process-wide ACTIVE registry for its statement's
  lifetime, so `sdb_query_progress()` / `GET /progress` show a RUNNING
  6M-row aggregate advancing instead of a blank until it finishes
  (the pg_stat_progress_* analog).

- **Wait events** — `wait_scope()` feeds the executing session's
  pg_stat_activity row live from the blocking sites the timeline layer
  already stamps retrospectively (worker-pool task waits, search-batch
  coalescing waits, collective shard combines), PG's
  wait_event_type/wait_event shape.

Determinism contract (same as `serene_profile`/`serene_trace`):
accounting observes, never steers. No executor reads the accountant
back, so results are bit-identical with `serene_mem_account` on or off
at any worker/shard count — asserted by tests/test_resources.py's
parity matrix, and the setting is deliberately NOT in the result
cache's RESULT_AFFECTING_SETTINGS digest.

Propagation rides the existing CURRENT_TRACE machinery: the statement
publishes its accountant through the CURRENT_MEM contextvar, pool
tasks capture the submitter's context at submit time
(contextvars.copy_context in parallel/pool.py), so worker-thread
charges land in the right query's account with zero extra plumbing.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from typing import Optional

from ..utils import metrics

#: the executing statement's MemoryAccountant (None outside an
#: accounted statement). Pool tasks capture the submitter's context at
#: submit time, so worker-thread charges land in the right query.
CURRENT_MEM: contextvars.ContextVar = contextvars.ContextVar(
    "sdb_current_mem", default=None)

_QUERY_IDS = itertools.count(1)


def current_accountant() -> Optional["MemoryAccountant"]:
    """The executing statement's accountant, or None (accounting off /
    outside a statement). One contextvar read — hot-path cheap."""
    return CURRENT_MEM.get()


class _MemBucket:
    """One thread's accumulation state: per-key [live, peak] pairs plus
    the thread-level live/peak roll-up and progress counters. Touched
    only by its owning thread (no lock after first touch)."""

    __slots__ = ("ops", "live", "peak", "rows", "bytes",
                 "morsels_done", "morsels_scheduled", "events")

    def __init__(self):
        self.ops: dict[object, list] = {}
        self.live = 0
        self.peak = 0
        self.rows = 0
        self.bytes = 0
        self.morsels_done = 0
        self.morsels_scheduled = 0
        self.events = 0


class MemoryAccountant:
    """Per-query live/peak byte accounting + progress counters.

    Charge/release are per-BATCH or per-morsel events (never per row),
    one thread-local dict access plus integer adds each — the same
    <3% budget as the profiler (mem_overhead bench shape). A release
    may land on a different thread than its charge (a coordinating
    thread retiring worker-produced partials): that thread's live goes
    negative, the SUMMED live stays exact, and per-thread peaks remain
    valid upper bounds on what each thread materialized.
    """

    __slots__ = ("query_id", "pid", "query", "t0_ns", "t0_epoch",
                 "current_op", "_register_lock", "_buckets", "_tl",
                 "_cv_token")

    def __init__(self, query_text: str = "", pid: int = 0):
        self.query_id = next(_QUERY_IDS)
        self.pid = pid
        self.query = (query_text or "")[:500]
        self.t0_ns = time.perf_counter_ns()
        self.t0_epoch = time.time()
        #: last operator label any thread stamped (single slot; racy
        #: writes are benign — any recently-active operator is a
        #: truthful answer to "what is it doing right now")
        self.current_op = ""
        self._register_lock = threading.Lock()
        self._buckets: list[_MemBucket] = []
        self._tl = threading.local()
        self._cv_token = None

    # -- accumulation (any thread) ----------------------------------------

    def _bucket(self) -> _MemBucket:
        b = getattr(self._tl, "b", None)
        if b is None:
            b = self._tl.b = _MemBucket()
            with self._register_lock:
                self._buckets.append(b)
        return b

    def charge(self, key, nbytes: int) -> None:
        """Materialization of `nbytes` attributed to operator `key`
        (id(plan node), or a string label for non-node sites)."""
        n = int(nbytes)
        b = self._bucket()
        e = b.ops.get(key)
        if e is None:
            e = b.ops[key] = [0, 0]
        e[0] += n
        if e[0] > e[1]:
            e[1] = e[0]
        b.live += n
        if b.live > b.peak:
            b.peak = b.live
        b.events += 1

    def release(self, key, nbytes: int) -> None:
        """The buffer charged to `key` was consumed/dropped."""
        n = int(nbytes)
        b = self._bucket()
        e = b.ops.get(key)
        if e is None:
            e = b.ops[key] = [0, 0]
        e[0] -= n
        b.live -= n
        b.events += 1

    def charge_once(self, key, nbytes: int) -> None:
        """A transient materialization (device upload, cache store)
        whose lifetime the query does not own: records the bytes in the
        key's and query's PEAK without leaving them live."""
        self.charge(key, nbytes)
        self.release(key, nbytes)

    def add_progress(self, rows: int = 0, nbytes: int = 0,
                     morsels: int = 0) -> None:
        b = self._bucket()
        b.rows += int(rows)
        b.bytes += int(nbytes)
        b.morsels_done += int(morsels)

    def add_morsels_scheduled(self, n: int) -> None:
        self._bucket().morsels_scheduled += int(n)

    def set_op(self, label: str) -> None:
        self.current_op = label

    # -- sink merge --------------------------------------------------------

    def merged(self) -> dict:
        """{key: (live, peak)} summed across thread buckets. Integer
        addition is order-free; per-key peak = Σ per-thread peaks (the
        upper-bound argument in the module docstring)."""
        with self._register_lock:
            buckets = list(self._buckets)
        out: dict = {}
        for b in buckets:
            for key, (live, peak) in b.ops.items():
                agg = out.get(key)
                if agg is None:
                    out[key] = [live, peak]
                else:
                    agg[0] += live
                    agg[1] += peak
        return {k: (v[0], v[1]) for k, v in out.items()}

    def totals(self) -> tuple[int, int]:
        """(live, peak) across all threads; peak is the query-level
        upper bound (Σ per-thread peaks)."""
        with self._register_lock:
            buckets = list(self._buckets)
        live = peak = 0
        for b in buckets:
            live += b.live
            peak += b.peak
        return live, peak

    def event_count(self) -> int:
        """Charge/release events recorded — the direct-decomposition
        input for the mem_overhead bench shape."""
        with self._register_lock:
            buckets = list(self._buckets)
        return sum(b.events for b in buckets)

    def progress(self) -> dict:
        """One live row for sdb_query_progress() / GET /progress."""
        with self._register_lock:
            buckets = list(self._buckets)
        rows = nbytes = done = sched = live = peak = 0
        for b in buckets:
            rows += b.rows
            nbytes += b.bytes
            done += b.morsels_done
            sched += b.morsels_scheduled
            live += b.live
            peak += b.peak
        return {"pid": self.pid, "query_id": self.query_id,
                "query": self.query[:200], "operator": self.current_op,
                "morsels_scheduled": sched, "morsels_done": done,
                "rows": rows, "bytes": nbytes,
                "live_bytes": live, "peak_bytes": peak,
                "elapsed_ms": round(
                    (time.perf_counter_ns() - self.t0_ns) / 1e6, 3)}

    # -- per-batch generator wrapper (exec/plan.py auto-wrap) --------------

    def wrap_batches(self, node, it):
        """Charge each batch an operator emits for exactly the window
        until its consumer pulls the next one (or the operator closes):
        the streaming tree's live set is then "one in-flight batch per
        operator", and peaks capture the widest batch each operator
        materialized. Also feeds rows/bytes progress and the
        current-operator label."""
        from .trace import batch_nbytes
        key = id(node)
        label = node.label()
        prev = 0
        try:
            for b in it:
                if prev:
                    self.release(key, prev)
                nb = batch_nbytes(b)
                self.charge(key, nb)
                prev = nb
                self.add_progress(rows=b.num_rows, nbytes=nb)
                self.current_op = label
                yield b
        finally:
            if prev:
                self.release(key, prev)
            close = getattr(it, "close", None)
            if close is not None:
                close()


# -- live-statement registry (sdb_query_progress / GET /progress) ------------


class ActiveQueries:
    """Process-wide registry of executing statements' accountants. One
    short lock per statement BEGIN/END (never inside execution);
    snapshots read each accountant's per-thread buckets live."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: dict[int, MemoryAccountant] = {}

    def register(self, acct: MemoryAccountant) -> None:
        with self._lock:
            self._active[acct.query_id] = acct

    def retire(self, acct: Optional[MemoryAccountant]) -> None:
        if acct is None:
            return
        with self._lock:
            self._active.pop(acct.query_id, None)

    def snapshot(self) -> list[dict]:
        """Progress rows of every running statement, oldest first."""
        with self._lock:
            accts = list(self._active.values())
        return [a.progress() for a in accts]


#: process-wide registry (one per process, like the flight recorder)
ACTIVE = ActiveQueries()


# -- wait events (pg_stat_activity) ------------------------------------------


class wait_scope:
    """Publish the executing session's current wait into its
    pg_stat_activity row (PG wait_event_type/wait_event) for the
    duration of a blocking section. Reads the connection from
    CURRENT_CONNECTION lazily; free when no session is executing.
    Nested scopes restore what they found. Plain class (not
    @contextmanager): the generator protocol costs a frame per entry
    and these sit on per-task wait paths."""

    __slots__ = ("etype", "event", "_sess", "_prev")

    def __init__(self, etype: str, event: str):
        self.etype = etype
        self.event = event
        self._sess = None
        self._prev = None

    def __enter__(self):
        from ..engine import CURRENT_CONNECTION
        conn = CURRENT_CONNECTION.get()
        if conn is not None:
            sess = conn.db.sessions.get(conn._session_id)
            if sess is not None:
                self._sess = sess
                self._prev = (sess.get("wait_event_type"),
                              sess.get("wait_event"))
                sess["wait_event_type"] = self.etype
                sess["wait_event"] = self.event
        return self

    def __exit__(self, *exc):
        sess = self._sess
        if sess is not None:
            sess["wait_event_type"], sess["wait_event"] = self._prev
            self._sess = None
        return False


# -- non-node charge sites (contextvar-routed) --------------------------------


def charge_device_upload(nbytes: int) -> None:
    """Device-cache upload attribution: the query that caused a
    host→device transfer records the bytes in its peak under the
    'device_upload' key (the upload outlives the query inside
    DEVICE_CACHE, so it is a charge_once — peak attribution, not a
    lasting live balance)."""
    acct = CURRENT_MEM.get()
    if acct is not None:
        acct.charge_once("device_upload", nbytes)


def charge_cache_store(nbytes: int) -> None:
    """Result-cache store attribution ('result_cache_store' key): the
    stored copy belongs to the cache, the store-time materialization
    belongs to this query's peak."""
    acct = CURRENT_MEM.get()
    if acct is not None:
        acct.charge_once("result_cache_store", nbytes)


# -- process-level gauges (RSS / uptime / GC) --------------------------------

#: process start reference for the uptime gauge
_PROCESS_T0 = time.monotonic()
_PAGE_SIZE: Optional[int] = None


def _page_size() -> int:
    global _PAGE_SIZE
    if _PAGE_SIZE is None:
        import os
        try:
            _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
        except (ValueError, OSError, AttributeError):
            _PAGE_SIZE = 4096
    return _PAGE_SIZE


def read_rss_bytes() -> int:
    """Resident set size from /proc/self/statm (field 2 × page size) —
    no psutil dependency; 0 on platforms without procfs."""
    try:
        with open("/proc/self/statm", "rb") as f:
            fields = f.read().split()
        return int(fields[1]) * _page_size()
    except (OSError, IndexError, ValueError):
        return 0


def sample_process_gauges() -> None:
    """Refresh the process-level gauges (RSS, uptime, GC collection
    counts). Called at scrape/render time (obs/export.py, the
    sdb_metrics view) and by the maintenance ticker — never on query
    hot paths."""
    import gc
    rss = read_rss_bytes()
    if rss:
        metrics.PROCESS_RSS_BYTES.set(rss)
    metrics.PROCESS_UPTIME_SECONDS.set(
        int(time.monotonic() - _PROCESS_T0))
    # socket write buffers (slow readers) across open front-door
    # connections — sampled here so /metrics and /_stats read fresh
    from ..sched.governor import CONNGATE
    CONNGATE.buffered_bytes()
    try:
        stats = gc.get_stats()
        gauges = (metrics.GC_GEN0_COLLECTIONS,
                  metrics.GC_GEN1_COLLECTIONS,
                  metrics.GC_GEN2_COLLECTIONS)
        for g, s in zip(gauges, stats):
            g.set(int(s.get("collections", 0)))
    except Exception:       # pragma: no cover — gc.get_stats is CPython
        pass


def fmt_kb(nbytes: int) -> str:
    """PG-style kB rendering for EXPLAIN ANALYZE Memory lines."""
    return f"{max(int(nbytes), 0) // 1024}kB"
