"""Prometheus-text `/metrics` and JSON `/_stats` payload rendering.

Reference analog: the reference's monitoring endpoint surface — here the
fixed gauge registry (utils/metrics.py) plus the statement store render
into the Prometheus exposition format (text/plain; version=0.0.4) and a
JSON object the ES-compatible `/_stats` route merges in.
"""

from __future__ import annotations

import re

from ..utils import metrics as _metrics
from .statements import STATEMENTS

_CAMEL = re.compile(r"(?<=[a-z0-9])(?=[A-Z])")


def _prom_name(gauge_name: str) -> str:
    return "serenedb_" + _CAMEL.sub("_", gauge_name).lower()


def _label_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def prometheus_text() -> str:
    """The whole registry as Prometheus gauges (one consistent
    Registry.snapshot(), not per-gauge reads mid-scrape), the latency
    histograms as classic-histogram series (cumulative `le` buckets in
    seconds + _sum/_count — p50/p99 derivable with
    histogram_quantile()), plus per-statement call/time/row series
    labeled by queryid."""
    lines: list[str] = []
    snap = _metrics.REGISTRY.snapshot()
    descs = {g.name: g.description for g in _metrics.REGISTRY.all()}
    for name in sorted(snap):
        pname = _prom_name(name)
        if descs.get(name):
            lines.append(f"# HELP {pname} {descs[name]}")
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {snap[name]}")
    for h in _metrics.REGISTRY.all_histograms():
        pname = _prom_name(h.name) + "_seconds"
        counts, sum_ns = h.snapshot()
        if h.description:
            lines.append(f"# HELP {pname} {h.description}")
        lines.append(f"# TYPE {pname} histogram")
        cum = 0
        for bound_ns, c in zip(_metrics.HIST_BOUNDS_NS, counts):
            cum += c
            lines.append(
                f'{pname}_bucket{{le="{bound_ns / 1e9:.6g}"}} {cum}')
        cum += counts[-1]
        lines.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{pname}_sum {sum_ns / 1e9:.9g}")
        lines.append(f"{pname}_count {cum}")
    stmts = STATEMENTS.snapshot()
    if stmts:
        for series, key in (("statement_calls", "calls"),
                            ("statement_total_ms", "total_ms"),
                            ("statement_rows", "rows"),
                            ("statement_cache_hits", "cache_hits")):
            pname = f"serenedb_{series}"
            lines.append(f"# TYPE {pname} counter")
            for e in stmts:
                q = _label_escape(e["query"][:200])
                lines.append(
                    f'{pname}{{queryid="{e["queryid"]}",query="{q}"}} '
                    f"{e.get(key, 0)}")
    return "\n".join(lines) + "\n"


def stats_json() -> dict:
    """Gauge snapshot + latency percentiles + statement stats + cache
    tier summaries + flight-recorder summary for the JSON `/_stats`
    route."""
    from ..cache.fragments import FRAGMENTS
    from ..cache.result import RESULT_CACHE
    from .trace import FLIGHT, flight_summary
    return {"metrics": _metrics.REGISTRY.snapshot(),
            "latency": {h.name: h.percentiles_ms()
                        for h in _metrics.REGISTRY.all_histograms()},
            "statements": STATEMENTS.snapshot(),
            "cache": {"result": RESULT_CACHE.stats(),
                      "fragments": FRAGMENTS.stats()},
            "traces": [flight_summary(e) for e in FLIGHT.snapshot()]}
