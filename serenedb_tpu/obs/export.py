"""Prometheus-text `/metrics` and JSON `/_stats` payload rendering.

Reference analog: the reference's monitoring endpoint surface — here the
fixed gauge registry (utils/metrics.py) plus the statement store render
into the Prometheus exposition format (text/plain; version=0.0.4) and a
JSON object the ES-compatible `/_stats` route merges in.
"""

from __future__ import annotations

import re

from ..utils import metrics as _metrics
from .statements import STATEMENTS

_CAMEL = re.compile(r"(?<=[a-z0-9])(?=[A-Z])")


def _prom_name(gauge_name: str) -> str:
    return "serenedb_" + _CAMEL.sub("_", gauge_name).lower()


def _label_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def prometheus_text() -> str:
    """The whole registry as Prometheus gauges (one consistent
    Registry.snapshot(), not per-gauge reads mid-scrape), the latency
    histograms as classic-histogram series (cumulative `le` buckets in
    seconds + _sum/_count — p50/p99 derivable with
    histogram_quantile()), plus per-statement call/time/row series
    labeled by queryid."""
    from .resources import sample_process_gauges
    sample_process_gauges()
    lines: list[str] = []
    snap = _metrics.REGISTRY.snapshot()
    descs = {g.name: g.description for g in _metrics.REGISTRY.all()}
    for name in sorted(snap):
        pname = _prom_name(name)
        if descs.get(name):
            lines.append(f"# HELP {pname} {descs[name]}")
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {snap[name]}")
    for h in _metrics.REGISTRY.all_histograms():
        # latency histograms observe ns and export as seconds; byte
        # histograms observe bytes and export raw (the shared
        # log-spaced bounds read as 1 kB..137 GB there)
        seconds = h.unit == "s"
        pname = _prom_name(h.name) + ("_seconds" if seconds else "")
        scale = 1e9 if seconds else 1.0
        counts, sum_raw = h.snapshot()
        if h.description:
            lines.append(f"# HELP {pname} {h.description}")
        lines.append(f"# TYPE {pname} histogram")
        cum = 0
        for bound, c in zip(_metrics.HIST_BOUNDS_NS, counts):
            cum += c
            lines.append(
                f'{pname}_bucket{{le="{bound / scale:.6g}"}} {cum}')
        cum += counts[-1]
        lines.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{pname}_sum {sum_raw / scale:.9g}")
        lines.append(f"{pname}_count {cum}")
    stmts = STATEMENTS.snapshot()
    if stmts:
        for series, key in (("statement_calls", "calls"),
                            ("statement_total_ms", "total_ms"),
                            ("statement_rows", "rows"),
                            ("statement_cache_hits", "cache_hits")):
            pname = f"serenedb_{series}"
            lines.append(f"# TYPE {pname} counter")
            for e in stmts:
                q = _label_escape(e["query"][:200])
                lines.append(
                    f'{pname}{{queryid="{e["queryid"]}",query="{q}"}} '
                    f"{e.get(key, 0)}")
    return "\n".join(lines) + "\n"


def _bytes_percentiles(h) -> dict:
    """{count, p50/p95/p99_bytes} for a byte-unit histogram."""
    counts, _ = h.snapshot()
    q = _metrics.hist_quantile_ns
    return {"count": sum(counts),
            "p50_bytes": int(q(counts, 0.50)),
            "p95_bytes": int(q(counts, 0.95)),
            "p99_bytes": int(q(counts, 0.99))}


def stats_json() -> dict:
    """Gauge snapshot + latency percentiles + statement stats + cache
    tier summaries + flight-recorder summary + the memory section
    (query-peak percentiles, process RSS/uptime/GC, live query
    progress) for the JSON `/_stats` route."""
    from ..cache.fragments import FRAGMENTS
    from ..cache.result import RESULT_CACHE
    from ..sched.governor import GOVERNOR
    from . import device as _device
    from .resources import ACTIVE, read_rss_bytes, sample_process_gauges
    from .trace import FLIGHT, flight_summary
    sample_process_gauges()
    snap = _metrics.REGISTRY.snapshot()
    from ..sched.governor import CONNGATE
    return {"metrics": snap,
            # workload governor: live running/queued counts + limits +
            # cumulative admission totals (sched/governor.py)
            "admission": GOVERNOR.snapshot(),
            # socket layer: open/idle/active connection counts against
            # serene_max_connections, accept-gate rejections,
            # pause-reading events and buffered write bytes
            # (sched/governor.py ConnectionGate; server/frontdoor.py)
            "connections": CONNGATE.snapshot(),
            # device telemetry: per-device dispatch/transfer/HBM rows,
            # the compile ledger, cache summaries (obs/device.py)
            "device": _device.stats_section(),
            "latency": {h.name: h.percentiles_ms()
                        for h in _metrics.REGISTRY.all_histograms()
                        if h.unit == "s"},
            # write path: append/segment/fsync counters + the group-commit
            # amortization signals (commits per fsync, fsync latency)
            "ingest": {
                "docs": snap.get("IngestDocs", 0),
                "bytes": snap.get("IngestBytes", 0),
                "batches": snap.get("IngestBatches", 0),
                "segment_builds": snap.get("SegmentBuilds", 0),
                "segment_merges": snap.get("SegmentMerges", 0),
                "wal_commits": snap.get("WalCommits", 0),
                "wal_fsyncs": snap.get("WalFsyncs", 0),
                "wal_fsync": _metrics.WAL_FSYNC_HIST.percentiles_ms()},
            "statements": STATEMENTS.snapshot(),
            "cache": {"result": RESULT_CACHE.stats(),
                      "fragments": FRAGMENTS.stats()},
            "traces": [flight_summary(e) for e in FLIGHT.snapshot()],
            "memory": {
                "query_peak": _bytes_percentiles(
                    _metrics.QUERY_PEAK_BYTES_HIST),
                "process": {
                    "rss_bytes": read_rss_bytes(),
                    "uptime_seconds": snap.get("ProcessUptimeSeconds", 0),
                    "gc_collections": [
                        snap.get("GcGen0Collections", 0),
                        snap.get("GcGen1Collections", 0),
                        snap.get("GcGen2Collections", 0)]},
                "progress": ACTIVE.snapshot()}}
