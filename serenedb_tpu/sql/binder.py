"""Name resolution and type checking: AST → bound expressions.

Reference analog: DuckDB's Binder (the reference's L3; SURVEY.md §3.2 —
"binding pins a catalog::Snapshot"). Here binding resolves against a Scope
of named/typed columns produced by the FROM clause, folds literals, resolves
functions through the registry, and rewrites aggregate calls into AggSpec +
BoundAggRef placeholders.
"""

from __future__ import annotations

import copy
import math
import re
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Column
from ..functions import scalar as fnlib
from . import ast
from .expr import (AggSpec, BoundAggRef, BoundCase, BoundColumn, BoundExpr,
                   BoundFunc, BoundLiteral, kleene_and, kleene_or)

def _from_aliases(ref) -> set:
    """Aliases / table names a FROM clause introduces (lowercased)."""
    if ref is None:
        return set()
    if isinstance(ref, ast.JoinRef):
        return _from_aliases(ref.left) | _from_aliases(ref.right)
    if isinstance(ref, ast.NamedTable):
        return {(ref.alias or ref.parts[-1]).lower()}
    if isinstance(ref, (ast.TableFunction, ast.SubqueryRef)):
        name = ref.alias or getattr(ref, "name", None) or "subquery"
        return {str(name).lower()}
    return set()


def _subst_colrefs(node, mapping: dict):
    """Deep-copy an AST substituting ColumnRefs whose part-tuple matches
    `mapping` (case-insensitive exact match) with Literal values
    (correlated-subquery lowering). Descending into a nested SELECT whose
    FROM re-introduces an alias drops the qualified entries that alias
    shadows, so `... EXISTS (SELECT .. FROM t d WHERE d.x ..)` inside a
    correlated subquery binds to the INNER d."""

    def rec(n, mp):
        if isinstance(n, ast.ColumnRef):
            for k, v in mp.items():
                if len(k) == len(n.parts) and \
                        tuple(x.lower() for x in k) == \
                        tuple(x.lower() for x in n.parts):
                    return ast.Literal(v)
            return n
        if isinstance(n, (ast.Select, ast.SetOp)):
            shadowed = _from_aliases(getattr(n, "from_", None))
            inner_mp = {k: v for k, v in mp.items()
                        if len(k) < 2 or k[0].lower() not in shadowed}
            out = copy.copy(n)
            for f in n.__dataclass_fields__:
                setattr(out, f, rec(getattr(n, f), inner_mp))
            return out
        if isinstance(n, list):
            return [rec(x, mp) for x in n]
        if isinstance(n, tuple):
            return tuple(rec(x, mp) for x in n)
        if isinstance(n, dict):
            return {k: rec(v, mp) for k, v in n.items()}
        if isinstance(n, (ast.Expr, ast.Statement, ast.SelectItem,
                          ast.TableRef, ast.OrderItem)):
            out = copy.copy(n)
            for f in n.__dataclass_fields__:
                setattr(out, f, rec(getattr(n, f), mp))
            return out
        return n
    return rec(node, mapping)


AGG_FUNCS = {"count", "sum", "min", "max", "avg", "count_star",
             "stddev", "stddev_samp", "var_samp", "variance",
             "stddev_pop", "var_pop",
             "string_agg", "array_agg", "bool_and", "bool_or", "every"}
AGG_TWO_ARG = {"string_agg"}


@dataclass
class ScopeColumn:
    table: Optional[str]
    name: str
    type: dt.SqlType
    index: int
    #: JOIN USING merges key columns: the non-merged side's copy stays
    #: qualified-resolvable but is skipped for bare names and SELECT *
    hidden: bool = False


@dataclass
class Scope:
    columns: list[ScopeColumn] = field(default_factory=list)

    @staticmethod
    def of(names: list[str], types: list[dt.SqlType],
           table: Optional[str] = None) -> "Scope":
        return Scope([ScopeColumn(table, n, t, i)
                      for i, (n, t) in enumerate(zip(names, types))])

    def resolve(self, parts: list[str]) -> ScopeColumn:
        if len(parts) == 1:
            name = parts[0]
            matches = [c for c in self.columns
                       if c.name.lower() == name.lower() and not c.hidden]
            if not matches:   # only hidden copies exist: take the first
                matches = [c for c in self.columns
                           if c.name.lower() == name.lower()][:1]
        elif len(parts) == 2:
            tbl, name = parts
            matches = [c for c in self.columns
                       if c.name.lower() == name.lower()
                       and c.table and c.table.lower() == tbl.lower()]
        else:
            tbl, name = parts[-2], parts[-1]
            matches = [c for c in self.columns
                       if c.name.lower() == name.lower()
                       and c.table and c.table.lower() == tbl.lower()]
        if not matches:
            raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                  f'column "{".".join(parts)}" does not exist')
        if len(matches) > 1:
            raise errors.SqlError(errors.AMBIGUOUS_COLUMN,
                                  f'column reference "{".".join(parts)}" is ambiguous')
        return matches[0]

    def star_columns(self, table: Optional[str] = None) -> list[ScopeColumn]:
        if table is None:
            return [c for c in self.columns if not c.hidden]
        out = [c for c in self.columns
               if c.table and c.table.lower() == table.lower()]
        if not out:
            raise errors.SqlError(errors.UNDEFINED_TABLE,
                                  f'missing FROM-clause entry for table "{table}"')
        return out


_LIT_TYPE = {bool: dt.BOOL, int: dt.BIGINT, float: dt.DOUBLE, str: dt.VARCHAR}


def literal_type(v) -> dt.SqlType:
    if v is None:
        return dt.NULLTYPE
    if isinstance(v, bool):
        return dt.BOOL
    if isinstance(v, int):
        return dt.INT if -2**31 <= v < 2**31 else dt.BIGINT
    return _LIT_TYPE.get(type(v), dt.VARCHAR)


class ExprBinder:
    """Binds expressions in a scope; collects aggregates when allowed.
    `planner` (when provided) enables uncorrelated subquery expressions."""

    def __init__(self, scope: Scope, params: Optional[list] = None,
                 allow_aggs: bool = False, planner=None):
        self.scope = scope
        self.params = params or []
        self.allow_aggs = allow_aggs
        self.planner = planner
        self.aggs: list[AggSpec] = []
        self._agg_keys: dict[str, int] = {}

    def bind(self, e: ast.Expr) -> BoundExpr:
        if isinstance(e, ast.Literal):
            return BoundLiteral(e.value, literal_type(e.value))
        if isinstance(e, ast.Param):
            if e.index > len(self.params):
                raise errors.SqlError("08P01",
                                      f"no value for parameter ${e.index}")
            v = self.params[e.index - 1]
            return BoundLiteral(v, literal_type(v))
        if isinstance(e, ast.ColumnRef):
            c = self.scope.resolve(e.parts)
            return BoundColumn(c.index, c.type, c.name)
        if isinstance(e, ast.BinaryOp):
            return self._bind_binary(e)
        if isinstance(e, ast.UnaryOp):
            if e.op == "NOT":
                arg = self.bind(e.operand)
                return self._call("opnot", [arg])
            if e.op == "-":
                return self._call("opneg", [self.bind(e.operand)])
            raise errors.unsupported(f"unary {e.op}")
        if isinstance(e, ast.Logical):
            args = [self.bind(a) for a in e.args]
            fn = kleene_and if e.op == "AND" else kleene_or
            def impl(cols, n, _fn=fn):
                return _fn(cols)
            return BoundFunc(e.op.lower(), args, dt.BOOL,
                             lambda cols, b, _fn=fn: _fn(cols))
        if isinstance(e, ast.IsNull):
            arg = self.bind(e.operand)
            neg = e.negated

            def impl(cols, batch, _neg=neg):
                c = cols[0]
                data = c.valid_mask() if _neg else ~c.valid_mask()
                return Column(dt.BOOL, data)
            # the name carries the negation: the device compiler keys on it
            return BoundFunc("is_not_null" if neg else "is_null",
                             [arg], dt.BOOL, impl)
        if isinstance(e, ast.InList):
            return self._bind_in(e)
        if isinstance(e, ast.Between):
            lo = ast.BinaryOp(">=", e.operand, e.low)
            hi = ast.BinaryOp("<=", e.operand, e.high)
            both: ast.Expr = ast.Logical("AND", [lo, hi])
            if e.negated:
                both = ast.UnaryOp("NOT", both)
            return self.bind(both)
        if isinstance(e, ast.Like):
            pattern = e.pattern
            esc = getattr(e, "escape", None)
            if esc is not None and isinstance(pattern, ast.Literal) \
                    and isinstance(pattern.value, str):
                pv = pattern.value
                if esc == "":
                    # ESCAPE '' disables escaping (PG): every character,
                    # including backslash, is literal to the impl
                    pattern = ast.Literal(pv.replace("\\", "\\\\"))
                else:
                    # normalize a custom ESCAPE char to the impl's backslash
                    out = []
                    i = 0
                    while i < len(pv):
                        ch = pv[i]
                        if ch == esc:
                            if i + 1 >= len(pv):
                                raise errors.SqlError(
                                    "22025", "LIKE pattern must not end "
                                    "with escape character")
                            out.append("\\" + pv[i + 1])
                            i += 2
                            continue
                        if ch == "\\":
                            out.append("\\\\")
                        else:
                            out.append(ch)
                        i += 1
                    pattern = ast.Literal("".join(out))
            elif esc is not None:
                raise errors.unsupported(
                    "ESCAPE with a non-constant pattern")
            args = [self.bind(e.operand), self.bind(pattern)]
            negated, ci = e.negated, e.case_insensitive

            def impl(cols, batch, _n=negated, _ci=ci):
                return fnlib.like_impl(cols, batch.num_rows, _n, _ci)
            return BoundFunc("like", args, dt.BOOL, impl)
        if isinstance(e, ast.FuncCall):
            return self._bind_func(e)
        if isinstance(e, ast.Cast):
            return self._bind_cast(e)
        if isinstance(e, ast.Case):
            return self._bind_case(e)
        if isinstance(e, ast.Subquery):
            return self._bind_scalar_subquery(e.query)
        if isinstance(e, ast.InSubquery):
            return self._bind_in_subquery(e)
        if isinstance(e, ast.Exists):
            return self._bind_exists(e)
        if isinstance(e, ast.ArraySubquery):
            return self._bind_array_subquery(e.query)
        if isinstance(e, ast.Star):
            raise errors.syntax("* not allowed here")
        raise errors.unsupported(f"expression {type(e).__name__}")

    def _bind_binary(self, e: ast.BinaryOp) -> BoundExpr:
        if e.op in ("##", "@@", "<->", "<#>", "<=>"):
            # full-text / vector operators — bound by the search layer
            from ..search import sqlfuncs
            return sqlfuncs.bind_operator(self, e)
        left = self.bind(e.left)
        right = self.bind(e.right)
        return self._call(f"op{e.op}", [left, right])

    def _bind_in(self, e: ast.InList) -> BoundExpr:
        operand = self.bind(e.operand)
        items = [self.bind(x) for x in e.items]
        # x IN (a,b,c) == (x=a OR x=b OR x=c) with PG null semantics
        cmps = [self._call("op=", [operand, it]) for it in items]
        if len(cmps) == 1:
            result = cmps[0]
        else:
            result = BoundFunc("or", cmps, dt.BOOL,
                               lambda cols, b: kleene_or(cols))
        if e.negated:
            result = self._call("opnot", [result])
        return result

    def _bind_func(self, e: ast.FuncCall) -> BoundExpr:
        name = e.name
        if name in AGG_FUNCS or (name == "count" and e.star):
            if not self.allow_aggs:
                raise errors.SqlError("42803",
                                      f"aggregate function {name} not allowed here")
            return self._bind_agg(e)
        if getattr(e, "filter", None) is not None:
            raise errors.SqlError(
                "42809",
                f"FILTER specified, but {name} is not an aggregate "
                "function")
        if getattr(e, "agg_order", None):
            raise errors.SqlError(
                "42809",
                f"ORDER BY specified, but {name} is not an ordered-set "
                "aggregate function")
        if name == "coalesce" and len(e.args) > 1:
            # short-circuit form (PG): later arguments must not be
            # evaluated on rows an earlier one already decided —
            # coalesce(x, 1/0) succeeds when x is never NULL
            bound = [self.bind(a) for a in e.args]
            t = dt.unify_all(b.type for b in bound)

            def notnull(b):
                def impl(cols, batch):
                    return Column(dt.BOOL, cols[0].valid_mask())
                return BoundFunc("is_not_null", [b], dt.BOOL, impl)
            return BoundCase([(notnull(b), b) for b in bound[:-1]],
                             bound[-1], t)
        from ..search import sqlfuncs
        if sqlfuncs.is_search_function(name):
            return sqlfuncs.bind_function(self, e)
        args = [self.bind(a) for a in e.args]
        return self._call(name, args)

    def _bind_agg(self, e: ast.FuncCall) -> BoundExpr:
        name = e.name
        if name == "every":   # SQL-standard alias of bool_and
            name = "bool_and"
        if e.star or (name == "count" and not e.args):
            spec = AggSpec("count_star", None, False, dt.BIGINT)
        elif name in AGG_TWO_ARG and len(e.args) == 2:
            arg = self.bind(e.args[0])
            sep_b = self.bind(e.args[1])
            if not isinstance(sep_b, BoundLiteral):
                raise errors.unsupported(
                    f"{name} separator must be a constant")
            out_t = _agg_result_type(name, arg.type)
            # PG: a NULL delimiter concatenates with no separator
            sep = "" if sep_b.value is None else str(sep_b.value)
            spec = AggSpec(name, arg, e.distinct, out_t, sep=sep)
        else:
            if len(e.args) != 1:
                raise errors.unsupported(f"{name} with {len(e.args)} args")
            arg = self.bind(e.args[0])
            out_t = _agg_result_type(name, arg.type)
            spec = AggSpec(name, arg, e.distinct, out_t)
        if getattr(e, "filter", None) is not None:
            spec.filter = self.bind(e.filter)
        if getattr(e, "agg_order", None):
            if name not in ("string_agg", "array_agg"):
                raise errors.unsupported(
                    f"ORDER BY inside {name}()")
            spec.order_by = [(self.bind(oi.expr), oi.desc,
                              oi.nulls_first)
                             for oi in e.agg_order]
        key = repr((spec.func, _expr_key(spec.arg), spec.distinct,
                    spec.sep, _expr_key(spec.filter),
                    tuple((_expr_key(k), d, nf)
                          for k, d, nf in (spec.order_by or []))))
        if key in self._agg_keys:
            idx = self._agg_keys[key]
            return BoundAggRef(idx, self.aggs[idx].type)
        self.aggs.append(spec)
        idx = len(self.aggs) - 1
        self._agg_keys[key] = idx
        return BoundAggRef(idx, spec.type)

    def _bind_cast(self, e: ast.Cast) -> BoundExpr:
        arg = self.bind(e.operand)
        try:
            target = dt.type_from_name(e.type_name)
        except (errors.SqlError, ValueError):
            # user-defined type (enum/domain): resolve via the planner's
            # database handle; enum casts validate labels (22P02)
            r = getattr(self.planner, "resolver", None) if self.planner \
                else None
            db = getattr(r, "db", None) or (r if hasattr(r, "types")
                                            else None)
            if db is None:
                raise
            target, labels = db.resolve_type_name(e.type_name)
            if labels is not None:
                lset = set(labels)
                tname = e.type_name.lower()

                def impl_enum(cols, batch, _t=target):
                    c = cast_column(cols[0], _t)
                    valid = c.valid_mask() if c.validity is not None \
                        else None
                    for i, v in enumerate(c.to_pylist()):
                        if v is None or (valid is not None
                                         and not valid[i]):
                            continue
                        if v not in lset:
                            raise errors.SqlError(
                                "22P02", "invalid input value for enum "
                                f'{tname}: "{v}"')
                    return c
                return BoundFunc("cast", [arg], target, impl_enum)

        def impl(cols, batch, _t=target):
            return cast_column(cols[0], _t)
        return BoundFunc("cast", [arg], target, impl)

    def _bind_case(self, e: ast.Case) -> BoundExpr:
        if e.operand is not None:
            branches = [(ast.BinaryOp("=", e.operand, cond), val)
                        for cond, val in e.branches]
        else:
            branches = e.branches
        bound = [(self.bind(c), self.bind(v)) for c, v in branches]
        else_b = self.bind(e.else_) if e.else_ is not None else None
        # result type unifies over EVERY branch INCLUDING ELSE (PG):
        # CASE WHEN .. THEN 1 ELSE 2.5 END is double precision, never a
        # truncating int
        arms = [v for _, v in bound] + ([else_b] if else_b is not None
                                        else [])
        t = dt.unify_all(v.type for v in arms)
        return BoundCase(bound, else_b, t)

    # -- subqueries --------------------------------------------------------
    # Uncorrelated: planned against their own scope, executed once per
    # statement and cached. Correlated (outer refs): lowered per outer
    # row by literal substitution with a per-key plan cache (below).

    def _subplan(self, query):
        if self.planner is None:
            raise errors.unsupported(
                "subqueries are not allowed in this context")
        return self.planner.plan_select(query)

    # -- correlated subqueries --------------------------------------------
    # The reference executes correlated subqueries via DuckDB's flattening;
    # here the correctness-first fallback is per-outer-row substitution of
    # the correlated column references, replanning the (cached-parse) AST
    # with literals. Uncorrelated subqueries never pay this cost.

    # the pattern matches Scope.resolve's message above — they live in
    # this same module, so wording changes must update both together
    _COLERR = re.compile(r'column "([^"]+)" does not exist')

    def _discover_correlation(self, query):
        """(outer_refs, trial_plan): iteratively plan the subquery,
        resolving each undefined column against the OUTER scope (inner
        scope wins by construction — only columns the inner plan cannot
        resolve are tried outside)."""
        outer_refs: list[list[str]] = []
        while True:
            trial = _subst_colrefs(query, {tuple(r): None
                                           for r in outer_refs})
            try:
                return outer_refs, self.planner.plan_select(trial)
            except errors.SqlError as e:
                if e.sqlstate != errors.UNDEFINED_COLUMN:
                    raise
                m = self._COLERR.search(e.message)
                if m is None:
                    raise
                parts = m.group(1).split(".")
                self.scope.resolve(parts)       # must exist OUTSIDE
                if parts in outer_refs:
                    raise                        # no progress — give up
                outer_refs.append(parts)

    def _correlated_rows(self, query, outer_refs, batch,
                         plan_cache: dict):
        """Execute the subquery once per outer row with the correlated
        refs substituted; yields (row_index, rows). plan_cache persists
        per bound expression so multi-batch execution and repeated keys
        pay one plan+execute per distinct key."""
        from ..exec.plan import ExecContext, check_cancel
        cols = {tuple(r): self.scope.resolve(r) for r in outer_refs}
        for i in range(batch.num_rows):
            check_cancel()
            key_vals = {}
            for parts, sc in cols.items():
                c = batch.columns[sc.index]
                v = None if (c.validity is not None and
                             not c.validity[i]) else c.decode(i)
                if isinstance(v, np.generic):
                    v = v.item()
                key_vals[parts] = v
            cache_key = tuple(sorted(key_vals.items()))
            rows = plan_cache.get(cache_key)
            if rows is None:
                sub = _subst_colrefs(query, key_vals)
                rows = self.planner.plan_select(sub).execute(
                    ExecContext()).rows()
                plan_cache[cache_key] = rows
            yield i, rows

    def _bind_scalar_subquery(self, query) -> BoundExpr:
        try:
            plan = self._subplan(query)
        except errors.SqlError as e:
            if e.sqlstate != errors.UNDEFINED_COLUMN:
                raise
            return self._bind_correlated_scalar(query)
        if len(plan.types) != 1:
            raise errors.SqlError("42601",
                                  "subquery must return only one column")
        t = plan.types[0]
        cache: list = []

        def impl(cols, batch, _plan=plan, _t=t, _cache=cache):
            if not _cache:
                from ..exec.plan import ExecContext
                rows = _plan.execute(ExecContext()).rows()
                if len(rows) > 1:
                    raise errors.SqlError(
                        "21000",
                        "more than one row returned by a subquery used as "
                        "an expression")
                _cache.append(rows[0][0] if rows else None)
            return Column.const(_cache[0], batch.num_rows, _t)
        return BoundFunc("scalar_subquery", [], t, impl)

    def _bind_array_subquery(self, query) -> BoundExpr:
        """ARRAY(SELECT ...) → JSON-array string (the array physical
        representation), correlated or not."""
        import json as _json
        try:
            plan = self._subplan(query)
        except errors.SqlError as e:
            if e.sqlstate != errors.UNDEFINED_COLUMN:
                raise
            outer_refs, trial = self._discover_correlation(query)
            if len(trial.types) != 1:
                raise errors.SqlError(
                    "42601", "subquery must return only one column")

            plan_cache: dict = {}

            def impl_corr(cols, batch, _q=query, _refs=outer_refs,
                          _pc=plan_cache):
                out = [None] * batch.num_rows
                for i, rows in self._correlated_rows(_q, _refs, batch, _pc):
                    out[i] = _json.dumps([r[0] for r in rows])
                from .expr import make_string_column
                return make_string_column(
                    np.asarray(out, dtype=object).astype(str), None)
            return BoundFunc("array_subquery", [], dt.VARCHAR, impl_corr)
        if len(plan.types) != 1:
            raise errors.SqlError("42601",
                                  "subquery must return only one column")
        cache: list = []

        def impl(cols, batch, _plan=plan, _cache=cache):
            if not _cache:
                from ..exec.plan import ExecContext
                rows = _plan.execute(ExecContext()).rows()
                _cache.append(_json.dumps([r[0] for r in rows]))
            return Column.const(_cache[0], batch.num_rows, dt.VARCHAR)
        return BoundFunc("array_subquery", [], dt.VARCHAR, impl)

    def _bind_correlated_scalar(self, query) -> BoundExpr:
        outer_refs, trial = self._discover_correlation(query)
        if len(trial.types) != 1:
            raise errors.SqlError("42601",
                                  "subquery must return only one column")
        t = trial.types[0]

        _pc: dict = {}

        def impl(cols, batch, _q=query, _refs=outer_refs, _t=t):
            out = []
            for i, rows in self._correlated_rows(_q, _refs, batch, _pc):
                if len(rows) > 1:
                    raise errors.SqlError(
                        "21000", "more than one row returned by a "
                        "subquery used as an expression")
                out.append(rows[0][0] if rows else None)
            return Column.from_pylist(out, _t)
        return BoundFunc("scalar_subquery", [], t, impl)

    def _bind_in_subquery(self, e) -> BoundExpr:
        try:
            plan = self._subplan(e.query)
        except errors.SqlError as err:
            if err.sqlstate != errors.UNDEFINED_COLUMN:
                raise
            return self._bind_correlated_in(e)
        if len(plan.types) != 1:
            raise errors.SqlError("42601",
                                  "subquery must return only one column")
        operand = self.bind(e.operand)
        negated = e.negated
        cache: list = []

        def impl(cols, batch, _plan=plan, _neg=negated, _cache=cache):
            if not _cache:
                from ..exec.plan import ExecContext
                vals = [r[0] for r in _plan.execute(ExecContext()).rows()]
                _cache.append((set(v for v in vals if v is not None),
                               any(v is None for v in vals)))
            values, has_null = _cache[0]
            x = cols[0]
            import numpy as np
            data = np.zeros(batch.num_rows, dtype=bool)
            valid = np.ones(batch.num_rows, dtype=bool)
            empty = not values and not has_null
            xv = x.to_pylist()
            for i, v in enumerate(xv):
                if v is None:
                    # NULL IN (empty set) is false — there is nothing to
                    # compare against; non-empty sets make it NULL
                    valid[i] = empty
                elif v in values:
                    data[i] = True
                elif has_null:
                    valid[i] = False   # x NOT IN set-with-null → NULL
            if _neg:
                data = ~data & valid
            else:
                data = data & valid
            return Column(dt.BOOL, data,
                          None if valid.all() else valid)
        return BoundFunc("in_subquery", [operand], dt.BOOL, impl)

    def _bind_correlated_in(self, e) -> BoundExpr:
        outer_refs, trial = self._discover_correlation(e.query)
        if len(trial.types) != 1:
            raise errors.SqlError("42601",
                                  "subquery must return only one column")
        operand = self.bind(e.operand)
        negated = e.negated

        _pc: dict = {}

        def impl(cols, batch, _q=e.query, _refs=outer_refs, _neg=negated):
            x = cols[0]
            xv = x.to_pylist()
            data = np.zeros(batch.num_rows, dtype=bool)
            valid = np.ones(batch.num_rows, dtype=bool)
            for i, rows in self._correlated_rows(_q, _refs, batch, _pc):
                vals = [r[0] for r in rows]
                if xv[i] is None:
                    valid[i] = not vals   # NULL IN (empty set) = false
                elif xv[i] in set(v for v in vals if v is not None):
                    data[i] = True
                elif any(v is None for v in vals):
                    valid[i] = False
            if _neg:
                data = ~data & valid
            return Column(dt.BOOL, data,
                          None if valid.all() else valid)
        return BoundFunc("in_subquery", [operand], dt.BOOL, impl)

    def _bind_correlated_exists(self, e) -> BoundExpr:
        outer_refs, _ = self._discover_correlation(e.query)

        _pc: dict = {}

        def impl(cols, batch, _q=e.query, _refs=outer_refs,
                 _neg=e.negated):
            data = np.zeros(batch.num_rows, dtype=bool)
            for i, rows in self._correlated_rows(_q, _refs, batch, _pc):
                data[i] = bool(rows)
            if _neg:
                data = ~data
            return Column(dt.BOOL, data)
        return BoundFunc("exists", [], dt.BOOL, impl)

    def _bind_exists(self, e) -> BoundExpr:
        try:
            plan = self._subplan(e.query)
        except errors.SqlError as err:
            if err.sqlstate != errors.UNDEFINED_COLUMN:
                raise
            return self._bind_correlated_exists(e)
        cache: list = []

        def impl(cols, batch, _plan=plan, _neg=e.negated, _cache=cache):
            if not _cache:
                from ..exec.plan import ExecContext
                _cache.append(_plan.execute(ExecContext()).num_rows > 0)
            v = _cache[0] != _neg
            return Column.const(v, batch.num_rows, dt.BOOL)
        return BoundFunc("exists", [], dt.BOOL, impl)

    #: comparison-family functions whose mixed text/typed operands
    #: coerce the TEXT side toward the typed side at BIND time (PG
    #: unknown-literal resolution). Binding once keeps every consumer —
    #: kernels, is_distinct/nullif, btree/PK/geo index claims — on the
    #: same coerced operand, and literal casts fold to typed literals.
    _COERCE_CMP = {"op=", "op<>", "op!=", "op<", "op<=", "op>", "op>=",
                   "is_distinct_from", "is_not_distinct_from", "nullif"}
    _COERCIBLE_IDS = (dt.TypeId.DATE, dt.TypeId.TIMESTAMP,
                      dt.TypeId.INTERVAL)

    def _call(self, name: str, args: list[BoundExpr]) -> BoundExpr:
        if name == "opnot":
            def impl(cols, batch):
                c = cols[0]
                return Column(dt.BOOL, ~c.data.astype(bool), c.validity)
            return BoundFunc("not", args, dt.BOOL, impl)
        if name in self._COERCE_CMP and len(args) == 2:
            a, b = args
            if a.type.is_string != b.type.is_string:
                typed = b if a.type.is_string else a
                if typed.type.is_numeric or typed.type.id in \
                        self._COERCIBLE_IDS:
                    def coerced(arg, _t=typed.type):
                        def impl(cols, batch):
                            return cast_column(cols[0], _t)
                        return _fold_if_const(
                            BoundFunc("cast", [arg], _t, impl))
                    if a.type.is_string:
                        args = [coerced(a), b]
                    else:
                        args = [a, coerced(b)]
        res = fnlib.resolve(name, [a.type for a in args])

        def impl2(cols, batch, _impl=res.impl):
            return _impl(cols, batch.num_rows)
        f = BoundFunc(name, args, res.result_type, impl2)
        return _fold_if_const(f)


from ..functions.volatility import (IMMUTABLE, VOLATILE,  # noqa: E402
                                    VOLATILE_FUNCS, volatility)

#: never constant-fold: each evaluation must run. Kept as a module
#: attribute because exec/plan.py and exec/morsel.py key off membership;
#: the classification itself lives in functions/volatility.py.
_VOLATILE_FUNCS = VOLATILE_FUNCS


def _fold_if_const(f: BoundFunc) -> BoundExpr:
    # STABLE folds here on purpose: binding happens once per statement,
    # so folding now() at bind time IS its statement-stability (PG
    # evaluates stable functions once per statement too)
    if volatility(f.name) is VOLATILE:
        return f
    if all(isinstance(a, BoundLiteral) for a in f.args):
        from ..columnar.column import Batch
        try:
            col = f.eval(Batch(["__one"], [Column.from_pylist([0])]))
            return BoundLiteral(col.decode(0), f.type)
        except Exception:
            # fold errors (1/0, sqrt(-1), ...) must NOT surface at bind
            # time: PG only raises if the row is actually evaluated —
            # CASE WHEN true THEN 1 ELSE 1/0 END returns 1
            return f
    return f


# -- interval extraction (zone-map predicate analysis) ----------------------
#
# exec/zonemap.py turns filter conjuncts into per-block verdicts; these
# helpers own the expression-shape side of that: recognizing a
# `column <cmp> constant` leaf and folding the constant side to a python
# value with the binder's own evaluation semantics.

#: comparison function names the interval analyzer understands, mapped to
#: their mirror when the column sits on the RIGHT (5 < x  ≡  x > 5)
_CMP_MIRROR = {"op=": "op=", "op<>": "op<>", "op!=": "op!=",
               "op<": "op>", "op<=": "op>=", "op>": "op<", "op>=": "op<="}

_CMP_CANON = {"op=": "=", "op<>": "<>", "op!=": "<>", "op<": "<",
              "op<=": "<=", "op>": ">", "op>=": ">="}

_NOT_CONST = object()


def fold_constant(e: BoundExpr):
    """Evaluate a column-free, non-volatile expression to its python
    value (None == SQL NULL). Returns the _NOT_CONST sentinel when the
    expression references columns/aggregates or isn't safely foldable."""
    if isinstance(e, BoundLiteral):
        return e.value
    for sub in e.walk():
        if isinstance(sub, (BoundColumn, BoundAggRef)):
            return _NOT_CONST
        # only IMMUTABLE folds during analysis: a STABLE value folded
        # here could disagree with the per-row evaluation (wall-clock
        # reads, subquery expressions over lazily-cached subplans)
        if isinstance(sub, BoundFunc) and \
                volatility(sub.name) is not IMMUTABLE:
            return _NOT_CONST
    from ..columnar.column import Batch
    try:
        col = e.eval(Batch(["__one"], [Column.from_pylist([0])]))
        if len(col.data) != 1:
            return _NOT_CONST
        return col.decode(0)
    except Exception:
        # fold errors (cast('x' as int), 1/0, ...) leave the leaf opaque
        return _NOT_CONST


def comparison_parts(e: BoundExpr):
    """(column_index, canonical_op, constant) for a comparison leaf of
    shape `column <cmp> constant` (either side), else None. The constant
    is a decoded python value in the column's PHYSICAL value space (str
    for VARCHAR, int days/micros for DATE/TIMESTAMP)."""
    if not isinstance(e, BoundFunc) or e.name not in _CMP_MIRROR or \
            len(e.args) != 2:
        return None
    a, b = e.args
    if isinstance(a, BoundColumn):
        v = fold_constant(b)
        if v is _NOT_CONST:
            return None
        return (a.index, _CMP_CANON[e.name], v)
    if isinstance(b, BoundColumn):
        v = fold_constant(a)
        if v is _NOT_CONST:
            return None
        return (b.index, _CMP_CANON[_CMP_MIRROR[e.name]], v)
    return None


def _agg_result_type(name: str, arg_t: dt.SqlType) -> dt.SqlType:
    if name == "count":
        return dt.BIGINT
    if name in ("sum", "avg", "stddev", "stddev_samp", "var_samp",
                "variance", "stddev_pop", "var_pop") and not (
            arg_t.is_numeric or arg_t.id is dt.TypeId.NULL):
        # without this, the engine would silently aggregate dictionary
        # CODES of a string column (PG: 42883 function sum(text)...)
        raise errors.SqlError(
            errors.UNDEFINED_FUNCTION,
            f"function {name}({arg_t.id.name.lower()}) does not exist")
    if name in ("sum",):
        if arg_t.is_integer:
            return dt.BIGINT
        return dt.DOUBLE if arg_t.id is not dt.TypeId.NULL else dt.DOUBLE
    if name in ("avg", "stddev", "stddev_samp", "var_samp", "variance",
                "stddev_pop", "var_pop"):
        return dt.DOUBLE
    if name in ("min", "max"):
        return arg_t
    if name in ("bool_and", "bool_or"):
        if arg_t.id not in (dt.TypeId.BOOL, dt.TypeId.NULL):
            raise errors.SqlError(
                errors.UNDEFINED_FUNCTION,
                f"function {name}({arg_t.id.name.lower()}) does not exist")
        return dt.BOOL
    if name == "string_agg":
        return dt.VARCHAR
    if name == "array_agg":
        return dt.array_of(arg_t)   # physically a JSON-text array
    raise errors.unsupported(f"aggregate {name}")


def _expr_key(e: Optional[BoundExpr]) -> str:
    if e is None:
        return "<star>"
    parts = []
    for node in e.walk():
        if isinstance(node, BoundColumn):
            parts.append(f"col{node.index}")
        elif isinstance(node, BoundLiteral):
            parts.append(f"lit{node.value!r}")
        elif isinstance(node, BoundFunc):
            parts.append(f"fn{node.name}")
        else:
            parts.append(type(node).__name__)
    return "/".join(parts)


_US_PER = {
    "microsecond": 1, "us": 1,
    "millisecond": 1000, "ms": 1000,
    "second": 1_000_000, "sec": 1_000_000, "s": 1_000_000,
    "minute": 60_000_000, "min": 60_000_000,
    "hour": 3_600_000_000, "h": 3_600_000_000, "hr": 3_600_000_000,
    "day": 86_400_000_000, "d": 86_400_000_000,
    "week": 604_800_000_000, "w": 604_800_000_000,
}
_IVAL_PAIR = re.compile(r"([+-]?\d+(?:\.\d+)?)\s*([a-zA-Z]+)")
_IVAL_CLOCK = re.compile(
    r"^([+-])?(\d+):([0-5]?\d)(?::([0-5]?\d)(\.\d+)?)?$")


def parse_interval(text: str) -> int:
    """'1 day 02:30:00', '90 minutes', '1.5 hours' → microseconds.
    Calendar units (month/year) have no fixed length and are rejected
    rather than silently approximated."""
    t = text.strip().lower()
    m = _IVAL_CLOCK.match(t)
    if m:
        sign = -1 if m.group(1) == "-" else 1
        us = (int(m.group(2)) * 3_600_000_000 +
              int(m.group(3)) * 60_000_000 +
              (int(m.group(4)) if m.group(4) else 0) * 1_000_000 +
              (int(round(float(m.group(5)) * 1e6))
               if m.group(5) else 0))
        return sign * us
    total = 0
    matched = 0
    pos = 0
    for m in _IVAL_PAIR.finditer(t):
        if t[pos:m.start()].strip(" ,"):
            raise ValueError(text)
        pos = m.end()
        qty, unit = float(m.group(1)), m.group(2).rstrip("s") \
            if m.group(2) not in ("s", "us", "ms") else m.group(2)
        if unit in ("month", "mon", "year", "yr", "y"):
            raise errors.unsupported(
                "calendar interval units (month/year) — use fixed units "
                "(days/hours/...)")
        if unit not in _US_PER:
            raise ValueError(text)
        # the remainder may be a clock part ('1 day 02:30:00')
        total += int(round(qty * _US_PER[unit]))
        matched += 1
    rest = t[pos:].strip(" ,")
    if rest:
        cm = _IVAL_CLOCK.match(rest)
        if cm is None:
            raise ValueError(text)
        total += parse_interval(rest)
        matched += 1
    if matched == 0:
        raise ValueError(text)
    return total


def format_interval(us: int) -> str:
    """PG-style rendering with PER-COMPONENT signs ('-1 days -02:30:00'):
    a text round-trip through parse_interval is value-preserving."""
    sign = "-" if us < 0 else ""
    us = abs(int(us))
    days, rem = divmod(us, 86_400_000_000)
    h, rem = divmod(rem, 3_600_000_000)
    mi, rem = divmod(rem, 60_000_000)
    se, frac = divmod(rem, 1_000_000)
    parts = []
    if days:
        # PG pluralizes negative day counts ('-1 days -02:00:00')
        parts.append(f"{sign}{days} day" +
                     ("s" if days != 1 or sign else ""))
    if h or mi or se or frac or not days:
        clock = f"{sign}{h:02d}:{mi:02d}:{se:02d}"
        if frac:
            clock += f".{frac:06d}".rstrip("0")
        parts.append(clock)
    return " ".join(parts)


def format_timestamp(us: int) -> str:
    """PG-style timestamp text: microseconds only when non-zero."""
    s = str(np.datetime64(int(us), "us")).replace("T", " ")
    if s.endswith(".000000"):
        return s[:-7]
    return s.rstrip("0") if "." in s else s


def _array_text_to_json(s: str) -> str:
    """Array text input → the physical JSON form. Accepts the JSON form
    itself and PG '{a,b}' literals (quotes, escapes, NULL, nesting);
    anything else is 22P02."""
    import json as _json
    t = s.strip()
    if t.startswith("["):
        try:
            v = _json.loads(t)
            if isinstance(v, list):
                return _json.dumps(v)
        except _json.JSONDecodeError:
            pass
        raise errors.SqlError("22P02", f"invalid array literal: {s!r}")
    if not t.startswith("{"):
        raise errors.SqlError("22P02", f"invalid array literal: {s!r}")

    pos = [0]

    def parse_list():
        assert t[pos[0]] == "{"
        pos[0] += 1
        out = []
        while True:
            while pos[0] < len(t) and t[pos[0]].isspace():
                pos[0] += 1
            if pos[0] >= len(t):
                raise errors.SqlError("22P02",
                                      f"invalid array literal: {s!r}")
            ch = t[pos[0]]
            if ch == "}":
                pos[0] += 1
                return out
            if ch == "{":
                out.append(parse_list())
            elif ch == '"':
                pos[0] += 1
                buf = []
                while pos[0] < len(t) and t[pos[0]] != '"':
                    if t[pos[0]] == "\\" and pos[0] + 1 < len(t):
                        pos[0] += 1
                    buf.append(t[pos[0]])
                    pos[0] += 1
                if pos[0] >= len(t):
                    raise errors.SqlError(
                        "22P02", f"invalid array literal: {s!r}")
                pos[0] += 1
                out.append("".join(buf))
            else:
                j = pos[0]
                while j < len(t) and t[j] not in ",}":
                    j += 1
                token = t[pos[0]:j].strip()
                pos[0] = j
                if token.upper() == "NULL":
                    out.append(None)
                else:
                    try:
                        out.append(int(token))
                    except ValueError:
                        try:
                            out.append(float(token))
                        except ValueError:
                            out.append(token)
            while pos[0] < len(t) and t[pos[0]].isspace():
                pos[0] += 1
            if pos[0] < len(t) and t[pos[0]] == ",":
                pos[0] += 1
            elif pos[0] < len(t) and t[pos[0]] == "}":
                continue
            elif pos[0] >= len(t):
                raise errors.SqlError("22P02",
                                      f"invalid array literal: {s!r}")

    v = parse_list()
    if t[pos[0]:].strip():
        raise errors.SqlError("22P02", f"invalid array literal: {s!r}")
    import json as _json
    return _json.dumps(v)


def cast_column(col: Column, target: dt.SqlType) -> Column:
    """PG-style CAST between supported types."""
    src = col.type
    if src == target:
        return col
    if dt.TypeId.INTERVAL in (src.id, target.id) and not (
            src.is_string or target.is_string or
            src.id is dt.TypeId.NULL):
        # PG: intervals cast only to/from text (42846) — reinterpreting
        # µs as days/epochs would produce silent garbage
        raise errors.SqlError(
            "42846", f"cannot cast type {src} to {target}")
    validity = col.validity
    _REG = (dt.TypeId.REGCLASS, dt.TypeId.REGTYPE, dt.TypeId.REGPROC,
            dt.TypeId.REGNAMESPACE)
    if target.id in _REG and src.is_string:
        # name → oid resolution against the live catalog ('t'::regclass)
        from ..pgcatalog import (current_db, resolve_namespace_oid,
                                 resolve_proc_oid, resolve_type_oid)
        db = current_db()
        vals = col.to_pylist()
        out = np.zeros(len(vals), dtype=np.int64)
        for i, v in enumerate(vals):
            if v is None:
                continue
            s = str(v).strip()
            if s.lstrip("-").isdigit():
                out[i] = int(s)
            elif target.id is dt.TypeId.REGTYPE:
                out[i] = resolve_type_oid(s)
            elif target.id is dt.TypeId.REGPROC:
                out[i] = resolve_proc_oid(s)
            elif target.id is dt.TypeId.REGNAMESPACE:
                out[i] = resolve_namespace_oid(db, s)
            else:
                if db is None:
                    raise errors.SqlError(errors.UNDEFINED_TABLE,
                                          f'relation "{s}" does not exist')
                out[i] = db.resolve_relation_oid(s)
        return Column(target, out, validity)
    if src.id in _REG and target.is_string:
        from ..pgcatalog import (current_db, namespace_render, proc_name_of,
                                 regclass_render, regtype_render)
        db = current_db()
        vals = col.to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append("")
            elif src.id is dt.TypeId.REGTYPE:
                out.append(regtype_render(int(v)))
            elif src.id is dt.TypeId.REGPROC:
                out.append(proc_name_of(v) or str(int(v)))
            elif src.id is dt.TypeId.REGNAMESPACE:
                out.append(namespace_render(db, int(v)))
            else:
                out.append(regclass_render(db, int(v)))
        from .expr import make_string_column
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  validity)
    if target.id is dt.TypeId.ARRAY:
        # array targets carry the ARRAY type (the generic to-string
        # branch below would degrade INT[] to VARCHAR on INSERT); text
        # input is normalized: PG '{...}' literals parse to the physical
        # JSON form, JSON arrays pass through, garbage raises 22P02
        if src.id is dt.TypeId.ARRAY:
            return Column(target, col.data, validity, col.dictionary)
        if src.is_string:
            from .expr import make_string_column, string_values
            vals = string_values(col)
            ok = col.valid_mask()
            out = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                out[i] = _array_text_to_json(str(v)) if ok[i] else ""
            c2 = make_string_column(out, validity)
            return Column(target, c2.data, validity, c2.dictionary)
        raise errors.SqlError(
            "42846", f"cannot cast type {src} to {target}")
    if target.is_string:
        if src.id is dt.TypeId.TIMESTAMP:
            out = [format_timestamp(v) for v in col.data]
            from .expr import make_string_column
            return make_string_column(
                np.asarray(out, dtype=object).astype(str), validity)
        if src.id is dt.TypeId.DATE:
            out = [str(np.datetime64(int(v), "D")) for v in col.data]
            from .expr import make_string_column
            return make_string_column(
                np.asarray(out, dtype=object).astype(str), validity)
        if src.id is dt.TypeId.INTERVAL:
            out = [format_interval(int(v)) for v in col.data]
            from .expr import make_string_column
            return make_string_column(
                np.asarray(out, dtype=object).astype(str), validity)
        vals = col.to_pylist()
        out = ["" if v is None else _cast_to_text(v, src) for v in vals]
        from .expr import make_string_column
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  validity)
    if src.is_string:
        vals = col.to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            else:
                out.append(_cast_text_to(v, target))
        return Column.from_pylist(out, target)
    if target.id is dt.TypeId.BOOL:
        return Column(target, col.data.astype(bool), validity)
    if target.is_integer:
        info = np.iinfo(target.np_dtype)
        if src.is_float:
            # PG rounds half away from zero (np.round is half-to-even).
            # Upper bound compares against max+1 (exactly representable in
            # float64): 'rounded > float(2**63-1)' would promote the bound
            # to 2.0**63 and let exactly-2**63 slip through and wrap
            x = col.data
            rounded = np.sign(x) * np.floor(np.abs(x) + 0.5)
            bad = (rounded < float(info.min)) | \
                (rounded >= float(info.max) + 1.0) | np.isnan(x)
            # zero out-of-range slots before astype: NULL rows may carry
            # arbitrary fill values that would wrap or warn
            data = np.where(bad | ~np.isfinite(rounded),
                            0.0, rounded).astype(target.np_dtype)
        else:
            x64 = col.data.astype(np.int64)
            bad = (x64 < info.min) | (x64 > info.max)
            data = x64.astype(target.np_dtype)
        if validity is not None:
            bad = bad & col.valid_mask()
        if bad.any():
            kind = {np.dtype(np.int16): "smallint",
                    np.dtype(np.int32): "integer"}.get(
                np.dtype(target.np_dtype), "bigint")
            raise errors.SqlError("22003", f"{kind} out of range")
        return Column(target, data, validity)
    if target.is_float:
        return Column(target, col.data.astype(target.np_dtype), validity)
    if src.id is dt.TypeId.DATE and target.id is dt.TypeId.TIMESTAMP:
        # days → µs at midnight (NOT a raw reinterpretation)
        data = col.data.astype(np.int64) * 86_400_000_000
        return Column(target, data, validity)
    if src.id is dt.TypeId.TIMESTAMP and target.id is dt.TypeId.DATE:
        # µs → days, flooring (negative timestamps floor toward -∞)
        data = np.floor_divide(col.data.astype(np.int64),
                               86_400_000_000).astype(np.int32)
        return Column(target, data, validity)
    if target.id in (dt.TypeId.TIMESTAMP, dt.TypeId.DATE,
                     dt.TypeId.INTERVAL):
        if src.id not in (dt.TypeId.TIMESTAMP, dt.TypeId.DATE,
                          dt.TypeId.INTERVAL, dt.TypeId.NULL):
            raise errors.SqlError(
                "42846", f"cannot cast type {src} to {target}")
        return Column(target, col.data.astype(target.np_dtype), validity)
    raise errors.unsupported(f"cast {src} -> {target}")


def _cast_to_text(v, src: dt.SqlType) -> str:
    if src.id is dt.TypeId.INTERVAL:
        return format_interval(int(v))
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        if v == int(v) and abs(v) < 1e15:
            return f"{v:.1f}" if "." not in repr(v) else repr(v)
        return repr(v)
    return str(v)


def _cast_text_to(v: str, target: dt.SqlType):
    s = v.strip()
    try:
        if target.id is dt.TypeId.BOOL:
            if s.lower() in ("t", "true", "yes", "on", "1"):
                return True
            if s.lower() in ("f", "false", "no", "off", "0"):
                return False
            raise ValueError(s)
        if target.is_integer:
            # PG: text→int accepts only an optional sign + digits; '2.7'
            # is 22P02, never a silent truncation
            if not re.fullmatch(r"[+-]?\d+", s):
                raise ValueError(s)
            return int(s)
        if target.is_float:
            return float(s)
        if target.id is dt.TypeId.TIMESTAMP:
            ts64 = np.datetime64(s)
            if np.isnat(ts64):
                raise ValueError(s)   # '' parses as NaT — PG: 22007
            return int(ts64.astype("datetime64[us]").astype(np.int64))
        if target.id is dt.TypeId.DATE:
            d64 = np.datetime64(s, "D")
            if np.isnat(d64):
                raise ValueError(s)
            return int(d64.astype(np.int64))
        if target.id is dt.TypeId.INTERVAL:
            return parse_interval(s)
    except ValueError:
        raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                              f'invalid input syntax for type {target}: "{v}"')
    raise errors.unsupported(f"cast text -> {target}")
