"""SQL lexer.

Reference analog: the reference parses SQL with its DuckDB fork's PEG parser
(SURVEY.md §3.2 "Parse"); here a small hand-rolled lexer feeds a
recursive-descent parser (sql/parser.py). PG-flavored: '' string escapes,
$$-quoted strings, "ident" quoting, ::casts, PG operators including the
full-text operators (##, @@) the reference exposes
(reference: examples/demo0/README.md, server/connector/functions/ts_*).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from ..errors import SqlError


class T(enum.Enum):
    IDENT = "ident"
    NUMBER = "number"
    STRING = "string"
    PARAM = "param"       # $1, $2 …
    OP = "op"
    EOF = "eof"


@dataclass
class Token:
    kind: T
    value: str
    pos: int

    def __repr__(self):
        return f"{self.kind.name}:{self.value!r}"


_OPERATORS = sorted([
    "::", "<=", ">=", "<>", "!=", "||", "##", "@@", "<->", "<#>", "<=>",
    "~*", "!~*", "!~",
    "->>", "->", "#>>", "#>", "?|", "?&", "?", "@>", "<@", "^",
    "(", ")", ",", ";", "+", "-", "*", "/", "%", "<", ">", "=", ".", "~",
    "[", "]", ":",
    # PG bitwise / math operators: & | # << >> (infix), |/ ||/ @ (prefix)
    "&", "|", "#", "<<", ">>", "|/", "||/", "@",
], key=len, reverse=True)  # longest match first (<=> before <=)


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SqlError("42601", "unterminated /* comment")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlError("42601", "unterminated string literal")
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            toks.append(Token(T.STRING, "".join(buf), i))
            i = j + 1
            continue
        if c == "$" and i + 1 < n and (sql[i + 1] == "$" or sql[i + 1].isalpha()):
            # dollar-quoted string $tag$...$tag$
            j = sql.find("$", i + 1)
            if j < 0:
                raise SqlError("42601", "unterminated dollar-quoted string")
            tag = sql[i:j + 1]
            end = sql.find(tag, j + 1)
            if end < 0:
                raise SqlError("42601", "unterminated dollar-quoted string")
            toks.append(Token(T.STRING, sql[j + 1:end], i))
            i = end + len(tag)
            continue
        if c == "$" and i + 1 < n and sql[i + 1].isdigit():
            j = i + 1
            while j < n and sql[j].isdigit():
                j += 1
            toks.append(Token(T.PARAM, sql[i + 1:j], i))
            i = j
            continue
        if c == '"':
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlError("42601", "unterminated quoted identifier")
                if sql[j] == '"':
                    if j + 1 < n and sql[j + 1] == '"':
                        buf.append('"')
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            toks.append(Token(T.IDENT, "".join(buf), i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    if j + 1 < n and (sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                        seen_exp = True
                        j += 2
                    else:
                        break
                else:
                    break
            toks.append(Token(T.NUMBER, sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            # E'...' escape strings: PG backslash escapes (\n \t \r \b \f
            # \\ \' \xHH \uXXXX); '' still escapes a quote
            if word.upper() == "E" and j < n and sql[j] == "'":
                k = j + 1
                buf = []
                while True:
                    if k >= n:
                        raise SqlError("42601",
                                       "unterminated string literal")
                    ch = sql[k]
                    if ch == "'":
                        if k + 1 < n and sql[k + 1] == "'":
                            buf.append("'")
                            k += 2
                            continue
                        break
                    if ch == "\\" and k + 1 < n:
                        nxt = sql[k + 1]
                        simple = {"n": "\n", "t": "\t", "r": "\r",
                                  "b": "\b", "f": "\f", "\\": "\\",
                                  "'": "'"}
                        if nxt in simple:
                            buf.append(simple[nxt])
                            k += 2
                            continue
                        if nxt in "01234567":
                            # octal \o \oo \ooo
                            m = k + 1
                            while m < min(k + 4, n) and \
                                    sql[m] in "01234567":
                                m += 1
                            buf.append(chr(int(sql[k + 1:m], 8) & 0xFF))
                            k = m
                            continue
                        if nxt in "xX":
                            # \x with 1–2 hex digits (PG rule)
                            m = k + 2
                            while m < min(k + 4, n) and \
                                    sql[m] in "0123456789abcdefABCDEF":
                                m += 1
                            if m > k + 2:
                                buf.append(chr(int(sql[k + 2:m], 16)))
                                k = m
                                continue
                        if nxt in "uU":
                            width = 4 if nxt == "u" else 8
                            hx = sql[k + 2:k + 2 + width]
                            if len(hx) == width:
                                try:
                                    cp = int(hx, 16)
                                except ValueError:
                                    cp = None
                                if cp is not None:
                                    if 0xD800 <= cp <= 0xDFFF or \
                                            cp > 0x10FFFF:
                                        # PG rejects surrogates/overflow
                                        # at parse time — stored lone
                                        # surrogates poison every later
                                        # read of the row
                                        raise SqlError(
                                            "42601",
                                            "invalid Unicode escape "
                                            f"value \\{nxt}{hx}")
                                    buf.append(chr(cp))
                                    k += 2 + width
                                    continue
                        buf.append(nxt)   # unknown escape: literal char
                        k += 2
                        continue
                    buf.append(ch)
                    k += 1
                toks.append(Token(T.STRING, "".join(buf), i))
                i = k + 1
                continue
            toks.append(Token(T.IDENT, word, i))
            i = j
            continue
        for op in _OPERATORS:
            if sql.startswith(op, i):
                toks.append(Token(T.OP, op, i))
                i += len(op)
                break
        else:
            raise SqlError("42601", f"unexpected character {c!r} at position {i}")
    toks.append(Token(T.EOF, "", n))
    return toks
