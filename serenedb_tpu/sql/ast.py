"""Parsed (unbound) AST for SQL statements and expressions."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


# -- expressions -----------------------------------------------------------

class Expr:
    pass


@dataclass
class Literal(Expr):
    value: Any           # python value; None for NULL
    type_hint: Optional[str] = None


@dataclass
class ColumnRef(Expr):
    parts: list[str]     # possibly qualified: [table, column] or [column]


@dataclass
class Star(Expr):
    table: Optional[str] = None


@dataclass
class Param(Expr):
    index: int           # 1-based


@dataclass
class BinaryOp(Expr):
    op: str              # '+', '-', '*', '/', '%', '||', '=', '<>', '<', ...
    left: Expr
    right: Expr


@dataclass
class UnaryOp(Expr):
    op: str              # '-', 'NOT'
    operand: Expr


@dataclass
class Logical(Expr):
    op: str              # 'AND' | 'OR'
    args: list[Expr]


@dataclass
class IsNull(Expr):
    operand: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    operand: Expr
    items: list[Expr]
    negated: bool = False


@dataclass
class Between(Expr):
    operand: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class Like(Expr):
    operand: Expr
    pattern: Expr
    negated: bool = False
    case_insensitive: bool = False
    escape: Optional[str] = None   # LIKE ... ESCAPE 'c' 


@dataclass
class FuncCall(Expr):
    name: str
    args: list[Expr]
    distinct: bool = False
    star: bool = False   # count(*)
    filter: Optional[Expr] = None   # aggregate FILTER (WHERE ...)
    agg_order: Optional[list] = None  # string_agg(x, s ORDER BY ...)


@dataclass
class WindowFunc(Expr):
    func: "FuncCall"
    partition_by: list = None
    order_by: list = None     # list[OrderItem]
    #: ROWS frame as (start, end) row offsets relative to the current
    #: row; None member = unbounded in that direction; whole-field None =
    #: the default frame (RANGE UNBOUNDED PRECEDING .. CURRENT ROW)
    frame: "tuple | None" = None

    def __post_init__(self):
        if self.partition_by is None:
            self.partition_by = []
        if self.order_by is None:
            self.order_by = []


@dataclass
class Cast(Expr):
    operand: Expr
    type_name: str


@dataclass
class Case(Expr):
    operand: Optional[Expr]          # CASE <operand> WHEN ... or searched CASE
    branches: list[tuple[Expr, Expr]]
    else_: Optional[Expr]


@dataclass
class Subquery(Expr):
    query: "Select"
    # EXISTS/IN-subquery support comes with joins


# -- statements ------------------------------------------------------------

class Statement:
    pass


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass
class TableRef:
    pass


@dataclass
class NamedTable(TableRef):
    parts: list[str]                 # [schema, table] or [table]
    alias: Optional[str] = None


@dataclass
class TableFunction(TableRef):
    name: str
    args: list[Expr]
    alias: Optional[str] = None
    col_aliases: Optional[list[str]] = None   # FROM fn(...) t(a, b)


@dataclass
class SubqueryRef(TableRef):
    query: "Select"
    alias: Optional[str] = None
    col_aliases: Optional[list[str]] = None   # FROM (…) v(a, b)


@dataclass
class JoinRef(TableRef):
    kind: str                        # 'inner' | 'left' | 'right' | 'cross'
    left: TableRef
    right: TableRef
    condition: Optional[Expr] = None
    using: Optional[list[str]] = None


@dataclass
class OrderItem:
    expr: Expr
    desc: bool = False
    nulls_first: Optional[bool] = None


@dataclass
class Select(Statement):
    items: list[SelectItem]
    from_: Optional[TableRef] = None
    where: Optional[Expr] = None
    group_by: list[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[Expr] = None
    offset: Optional[Expr] = None
    distinct: bool = False
    distinct_on: Optional[list[Expr]] = None      # DISTINCT ON (exprs)
    ctes: dict = field(default_factory=dict)      # name -> Select (WITH)


@dataclass
class SetOp(Statement):
    op: str                    # 'union' | 'intersect' | 'except'
    all: bool
    left: "Select | SetOp"
    right: "Select | SetOp"
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[Expr] = None
    offset: Optional[Expr] = None
    ctes: dict = field(default_factory=dict)


@dataclass
class CteDef:
    """A WITH binding that needs more than plain inlining: an explicit
    column list and/or RECURSIVE iteration (PG: base UNION [ALL] step)."""
    query: "Select | SetOp"
    cols: Optional[list[str]] = None
    recursive: bool = False


@dataclass
class Exists(Expr):
    query: "Select"
    negated: bool = False


@dataclass
class InSubquery(Expr):
    operand: Expr
    query: "Select"
    negated: bool = False


@dataclass
class ArraySubquery(Expr):
    """ARRAY(SELECT ...): first output column gathered into an array."""
    query: "Select"


@dataclass
class DefaultMarker(Expr):
    """Bare DEFAULT in INSERT VALUES / UPDATE SET — replaced by the
    column's default expression (or NULL) at execution."""


@dataclass
class ColumnDef:
    name: str
    type_name: str
    not_null: bool = False
    primary_key: bool = False
    default: Optional[Expr] = None
    tokenizer: Optional[str] = None   # search-table column analyzer


@dataclass
class CreateTable(Statement):
    name: list[str]
    columns: list[ColumnDef]
    engine: str = "columnar"          # 'columnar' | 'search'  (reference: table_options.h:160)
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)
    as_query: Optional[Select] = None
    primary_key: list[str] = field(default_factory=list)


@dataclass
class CreateIndex(Statement):
    name: Optional[str]
    table: list[str]
    columns: list[str]
    using: str = "inverted"    # 'inverted' | 'btree' | 'ivf' | 'maxsim' | ...
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)
    column_tokenizers: dict = field(default_factory=dict)


@dataclass
class CreateSchema(Statement):
    name: str
    if_not_exists: bool = False


@dataclass
class AlterTable(Statement):
    table: list[str]
    action: str               # add_column | drop_column | rename_column | rename_table
    column: Optional[str] = None
    type_name: Optional[str] = None
    new_name: Optional[str] = None
    if_exists: bool = False          # table-level: ALTER TABLE IF EXISTS
    col_if_exists: bool = False      # column-level: DROP COLUMN IF EXISTS
    if_not_exists: bool = False


@dataclass
class CreateTsDictionary(Statement):
    name: str
    options: dict
    if_not_exists: bool = False


@dataclass
class CreateType(Statement):
    """CREATE TYPE name AS ENUM (labels) / CREATE DOMAIN name AS base."""
    name: str
    kind: str                     # 'enum' | 'domain'
    labels: list = field(default_factory=list)   # enum labels, in order
    base: Optional[str] = None    # domain base type name
    if_not_exists: bool = False


@dataclass
class CreateSequence(Statement):
    name: list[str]
    start: int = 1
    increment: int = 1
    if_not_exists: bool = False


@dataclass
class Drop(Statement):
    kind: str          # 'table' | 'index' | 'schema' | 'view' | 'sequence'
    name: list[str]
    if_exists: bool = False
    cascade: bool = False


@dataclass
class Insert(Statement):
    table: list[str]
    columns: Optional[list[str]]
    values: Optional[list[list[Expr]]]
    query: Optional[Select] = None
    returning: list = field(default_factory=list)   # list[SelectItem]
    #: ON CONFLICT: (action, target_cols, assignments) where action is
    #: "nothing" | "update"; assignments may reference excluded.col
    on_conflict: Optional[tuple] = None


@dataclass
class Delete(Statement):
    table: list[str]
    where: Optional[Expr] = None
    returning: list = field(default_factory=list)
    using_ref: Optional[TableRef] = None   # DELETE ... USING <tables>


@dataclass
class Update(Statement):
    table: list[str]
    assignments: list[tuple[str, Expr]]
    where: Optional[Expr] = None
    returning: list = field(default_factory=list)
    from_ref: Optional[TableRef] = None    # UPDATE ... FROM <tables>


@dataclass
class CreateView(Statement):
    name: list[str]
    query: Select
    or_replace: bool = False


@dataclass
class SetStmt(Statement):
    name: str
    value: Any                        # python literal or 'DEFAULT'


@dataclass
class ShowStmt(Statement):
    name: str                         # setting name or 'all' / 'tables'


@dataclass
class ListenStmt(Statement):
    channel: str
    action: str = "listen"        # listen | unlisten | unlisten_all


@dataclass
class NotifyStmt(Statement):
    channel: str
    payload: str = ""


@dataclass
class Transaction(Statement):
    action: str                       # begin|commit|rollback|savepoint|
                                      # rollback_to|release
    savepoint: Optional[str] = None


@dataclass
class Explain(Statement):
    inner: Statement
    analyze: bool = False
    format: str = "text"              # 'text' | 'json' (PG FORMAT option)


@dataclass
class CopyStmt(Statement):
    table: list[str]
    columns: Optional[list[str]]
    direction: str                    # 'from' | 'to'
    target: str                       # filename or STDIN/STDOUT
    options: dict = field(default_factory=dict)
    query: Optional[Statement] = None  # COPY (SELECT ...) TO ...


@dataclass
class VacuumStmt(Statement):
    table: Optional[list[str]] = None
    verbs: list[str] = field(default_factory=list)   # refresh/compact/cleanup


@dataclass
class Truncate(Statement):
    table: list[str]


@dataclass
class CreateRole(Statement):
    name: str
    password: Optional[str] = None
    login: bool = True
    superuser: bool = False
    if_not_exists: bool = False


@dataclass
class DropRole(Statement):
    name: str
    if_exists: bool = False


@dataclass
class AlterRole(Statement):
    name: str
    set_password: bool = False     # PASSWORD clause present
    password: object = None        # None with set_password = clear it
    login: object = None           # None = unchanged
    superuser: object = None       # None = unchanged


@dataclass
class GrantRevoke(Statement):
    grant: bool                       # True=GRANT, False=REVOKE
    privileges: list[str]             # select/insert/update/delete/all
    table: list[str]                  # [] for role-membership grants
    role: str
    granted_role: Optional[str] = None   # GRANT <granted_role> TO <role>


@dataclass
class SetRole(Statement):
    name: Optional[str]               # None = RESET ROLE
