"""Logical planning: bound SELECT → physical plan tree.

Reference analog: DuckDB planner/optimizer plus SereneDB's optimizer
extensions that claim WHERE conjuncts into the scan
(IResearchPushdownComplexFilter, reference:
server/connector/optimizer/iresearch_plan.cpp:1016-1058). Re-expressed here:
filter conjuncts land in ScanNode.filter (device compilation fuses them into
the scan program), projection pruning keeps the HBM working set minimal, and
ORDER BY / GROUP BY resolve select aliases and positions per PG scoping.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column
from ..exec.plan import (AggregateNode, DropColumnsNode, FilterNode, JoinNode,
                         LimitNode, PlanNode, ProjectNode, ScanNode, SortNode,
                         ValuesNode)
from ..exec.tables import TableProvider
from . import ast
from .binder import AGG_FUNCS, ExprBinder, Scope, ScopeColumn
from .expr import (BoundAggRef, BoundCase, BoundColumn, BoundExpr, BoundFunc,
                   BoundLiteral, kleene_and)


class TableResolver:
    """Interface the planner uses to find tables/table functions."""

    def resolve_table(self, parts: list[str]) -> TableProvider:
        raise NotImplementedError

    def resolve_table_function(self, name: str, args: list) -> TableProvider:
        raise NotImplementedError


@dataclass
class _GroupRef(BoundExpr):
    """Placeholder for a group-key column in post-aggregation expressions."""
    slot: int
    type: dt.SqlType


class PostAggBinder(ExprBinder):
    """Binds post-aggregation expressions (select items, HAVING, ORDER BY):
    group-expression matches become _GroupRef, aggregate calls become
    BoundAggRef (collected), any other bare column is a PG 42803 error."""

    def __init__(self, scope: Scope, params, group_asts: list[ast.Expr],
                 group_types: list[dt.SqlType]):
        super().__init__(scope, params, allow_aggs=True)
        self.group_asts = group_asts
        self.group_types = group_types
        self._in_agg = False

    def bind(self, e: ast.Expr) -> BoundExpr:
        if self._in_agg:
            # inside an aggregate argument: plain base-scope binding
            if isinstance(e, ast.FuncCall) and (e.name in AGG_FUNCS or e.star):
                raise errors.SqlError(
                    "42803", "aggregate function calls cannot be nested")
            return super().bind(e)
        for k, g in enumerate(self.group_asts):
            if _ast_eq(e, g):
                return _GroupRef(k, self.group_types[k])
        if isinstance(e, ast.FuncCall) and (e.name in AGG_FUNCS or e.star):
            self._in_agg = True
            try:
                return self._bind_agg(e)
            finally:
                self._in_agg = False
        if isinstance(e, ast.ColumnRef):
            raise errors.SqlError(
                "42803",
                f'column "{".".join(e.parts)}" must appear in the GROUP BY '
                "clause or be used in an aggregate function")
        return super().bind(e)


def _resolve_post(e: BoundExpr, n_groups: int,
                  out_types: list[dt.SqlType]) -> BoundExpr:
    """Rewrite _GroupRef/BoundAggRef placeholders into BoundColumns over the
    aggregate node's output (groups first, then aggs)."""
    if isinstance(e, _GroupRef):
        return BoundColumn(e.slot, e.type, f"#g{e.slot}")
    if isinstance(e, BoundAggRef):
        return BoundColumn(n_groups + e.index, e.type, f"#agg{e.index}")
    if isinstance(e, BoundFunc):
        e.args = [_resolve_post(a, n_groups, out_types) for a in e.args]
        return e
    if isinstance(e, BoundCase):
        e.branches = [(_resolve_post(c, n_groups, out_types),
                       _resolve_post(v, n_groups, out_types))
                      for c, v in e.branches]
        if e.else_ is not None:
            e.else_ = _resolve_post(e.else_, n_groups, out_types)
        return e
    return e


def _references_cte(node, key: str, depth: int = 0) -> bool:
    """Does the AST subtree reference table `key`? (Generic dataclass
    walk — used to decide whether a WITH RECURSIVE member actually
    iterates.) A nested WITH that rebinds the name shadows it."""
    import dataclasses
    if depth > 200 or node is None:
        return False
    if isinstance(node, ast.NamedTable):
        return len(node.parts) == 1 and node.parts[0].lower() == key
    if isinstance(node, (list, tuple)):
        return any(_references_cte(v, key, depth + 1) for v in node)
    if isinstance(node, dict):
        return any(_references_cte(v, key, depth + 1)
                   for v in node.values())
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        if key in {k.lower() for k in getattr(node, "ctes", {})}:
            return False      # shadowed by an inner WITH
        return any(_references_cte(getattr(node, f.name), key, depth + 1)
                   for f in dataclasses.fields(node))
    return False


@dataclass
class _RecursiveIterRef:
    """CTE-map marker: a self-reference inside a recursive step scans
    this iteration working table."""
    provider: "TableProvider"


class Planner:
    def __init__(self, resolver: TableResolver, params: Optional[list] = None):
        self.resolver = resolver
        self.params = params or []
        self.ctes: dict[str, ast.Select] = {}

    def _binder(self, scope: Scope, allow_aggs: bool = False) -> ExprBinder:
        return ExprBinder(scope, self.params, allow_aggs, planner=self)

    # -- FROM --------------------------------------------------------------

    def plan_select(self, sel) -> PlanNode:
        saved = dict(self.ctes)
        try:
            for name, q in getattr(sel, "ctes", {}).items():
                self.ctes[name] = q
            if isinstance(sel, ast.SetOp):
                return self._plan_setop(sel)
            values_rows = getattr(sel, "values_rows", None)
            if values_rows is not None:
                return self._plan_values(values_rows)
            if sel.from_ is None:
                plan: PlanNode = ValuesNode(
                    Batch(["__dummy"], [Column.from_pylist([0])]))
                scope = Scope([])
            else:
                plan, scope = self._plan_from(sel.from_)
            return self._plan_body(sel, plan, scope)
        finally:
            self.ctes = saved

    def _plan_setop(self, s: ast.SetOp) -> PlanNode:
        from ..exec.plan import LimitNode as _Limit
        from ..exec.plan import SetOpNode, SortNode as _Sort
        left = self.plan_select(s.left)
        right = self.plan_select(s.right)
        if len(left.types) != len(right.types):
            raise errors.SqlError(
                "42601", "each %s query must have the same number of "
                "columns" % s.op.upper())
        plan: PlanNode = SetOpNode(s.op, s.all, left, right)
        if s.order_by:
            indices, descs, nfs = [], [], []
            for oi in s.order_by:
                descs.append(oi.desc)
                nfs.append(oi.nulls_first)
                e = oi.expr
                if isinstance(e, ast.Literal) and isinstance(e.value, int):
                    if not (1 <= e.value <= len(plan.names)):
                        raise errors.SqlError(
                            "42P10",
                            f"ORDER BY position {e.value} is out of range")
                    indices.append(e.value - 1)
                elif isinstance(e, ast.ColumnRef) and len(e.parts) == 1 and \
                        e.parts[0].lower() in [n.lower() for n in plan.names]:
                    indices.append([n.lower() for n in plan.names]
                                   .index(e.parts[0].lower()))
                else:
                    raise errors.unsupported(
                        "ORDER BY over a set operation must use output "
                        "column names or positions")
            plan = _Sort(plan, indices, descs, nfs)
        if s.limit is not None or s.offset is not None:
            limit = _const_int(s.limit, self.params) \
                if s.limit is not None else None
            offset = _const_int(s.offset, self.params) \
                if s.offset is not None else 0
            plan = _Limit(plan, limit, offset)
        return plan

    def _plan_values(self, rows: list[list[ast.Expr]]) -> PlanNode:
        binder = self._binder(Scope([]))
        cols = []
        width = len(rows[0])
        one = Batch(["__dummy"], [Column.from_pylist([0])])
        from ..exec.plan import _unify_setop_type
        from .binder import cast_column
        for k in range(width):
            exprs = [binder.bind(r[k]) for r in rows]
            vals = [e.eval(one).decode(0) for e in exprs]
            # unify across ALL rows (PG: VALUES (1), (2.5) is numeric,
            # not the first row's int). A string literal mixed with one
            # typed row acts as PG's unknown literal: it coerces toward
            # the typed side instead of failing the unification.
            t = dt.NULLTYPE
            strings_seen = False
            for e in exprs:
                et = e.type
                if et.id is dt.TypeId.NULL:
                    continue
                if et.is_string and not (t.is_string or
                                         t.id is dt.TypeId.NULL):
                    strings_seen = True
                    continue
                if t.is_string and not et.is_string:
                    strings_seen = True
                    t = et
                    continue
                t = _unify_setop_type(t, et)
            if strings_seen and not t.is_string:
                col = Column.from_pylist(vals, dt.VARCHAR)
                cols.append(cast_column(col, t))
            else:
                cols.append(Column.from_pylist(vals, t))
        return ValuesNode(Batch([f"col{k}" for k in range(width)], cols))

    def _plan_cte_def(self, key: str, cte: ast.CteDef) -> PlanNode:
        """Plan a CTE with a column list and/or RECURSIVE semantics."""
        from ..exec.plan import RecursiveCteNode, RenameNode
        from ..exec.tables import MemTable
        # WITH RECURSIVE marks the whole WITH list; a member is only
        # iterated when it actually references itself
        if not cte.recursive or not _references_cte(cte.query, key):
            self.ctes.pop(key)
            try:
                inner = self.plan_select(cte.query)
            finally:
                self.ctes[key] = cte
            return RenameNode(inner, cte.cols) if cte.cols else inner
        body = cte.query
        if not isinstance(body, ast.SetOp) or body.op != "union":
            raise errors.SqlError(
                "42P19", f'recursive query "{key}" does not have the form '
                "non-recursive-term UNION [ALL] recursive-term")
        # base term: the CTE name must not be visible (self-reference in
        # the base term is 42P19 in PG; here it resolves to 42P01)
        self.ctes.pop(key)
        try:
            base = self.plan_select(body.left)
        finally:
            self.ctes[key] = cte
        names = cte.cols or list(base.names)
        if cte.cols and len(cte.cols) != len(base.names):
            raise errors.SqlError(
                "42P10", f'recursive query "{key}" column list does not '
                "match the number of output columns")
        work = MemTable(key, Batch(list(names),
                                   [Column.from_pylist([], t)
                                    for t in base.types]))
        saved = self.ctes[key]
        self.ctes[key] = _RecursiveIterRef(work)
        try:
            step = self.plan_select(body.right)
        finally:
            self.ctes[key] = saved
        if len(step.types) != len(base.types):
            raise errors.SqlError(
                "42601", "each UNION query must have the same number of "
                "columns")
        return RecursiveCteNode(names, base, step, work, body.all)

    def _scan_scope(self, provider: TableProvider, alias: str):
        scan = ScanNode(provider, list(provider.column_names), alias)
        scope = Scope([ScopeColumn(alias, n, t, i)
                       for i, (n, t) in enumerate(zip(scan.names, scan.types))])
        return scan, scope

    def _plan_from(self, ref: ast.TableRef) -> tuple[PlanNode, Scope]:
        if isinstance(ref, ast.NamedTable):
            if len(ref.parts) == 1 and ref.parts[0].lower() in self.ctes:
                key = ref.parts[0].lower()
                body = self.ctes[key]
                alias = ref.alias or ref.parts[0]
                if isinstance(body, _RecursiveIterRef):
                    # a self-reference inside a recursive step: scan the
                    # iteration's working table
                    return self._scan_scope(body.provider, alias)
                if isinstance(body, ast.CteDef):
                    inner = self._plan_cte_def(key, body)
                else:
                    # shadow the name while planning its body:
                    # non-recursive WITH must not see itself (PG resolves
                    # to 42P01 there)
                    self.ctes.pop(key)
                    try:
                        inner = self.plan_select(body)
                    finally:
                        self.ctes[key] = body
                scope = Scope([ScopeColumn(alias, n, t, i)
                               for i, (n, t) in enumerate(
                                   zip(inner.names, inner.types))])
                return inner, scope
            provider = self.resolver.resolve_table(ref.parts)
            return self._scan_scope(provider, ref.alias or ref.parts[-1])
        if isinstance(ref, ast.TableFunction):
            binder = self._binder(Scope([]))
            args = []
            for a in ref.args:
                b = binder.bind(a)
                if isinstance(b, BoundLiteral):
                    args.append(b.value)
                else:
                    # constant-fold column-free expressions (e.g.
                    # unnest(ARRAY[1,2,3])) on a one-row dummy batch
                    if _refs_columns(b):
                        raise errors.unsupported(
                            "table function arguments must be constants")
                    one_row = Batch(["__dummy"], [Column.const(0, 1)])
                    args.append(b.eval(one_row).decode(0))
            provider = self.resolver.resolve_table_function(ref.name, args)
            node, scope = self._scan_scope(
                provider, ref.alias or ref.name.split(".")[-1])
            if ref.col_aliases:
                # FROM fn(...) t(a, b): rename output columns (PG)
                if len(ref.col_aliases) > len(scope.columns):
                    raise errors.SqlError(
                        "42P10",
                        f"table function {ref.name} has "
                        f"{len(scope.columns)} columns available but "
                        f"{len(ref.col_aliases)} specified")
                cols2 = []
                exprs = []
                names = []
                for i, c in enumerate(scope.columns):
                    nm = ref.col_aliases[i] if i < len(ref.col_aliases) \
                        else c.name
                    cols2.append(ScopeColumn(c.table, nm, c.type, i))
                    exprs.append(BoundColumn(c.index, c.type, nm))
                    names.append(nm)
                scope = Scope(cols2)
                node = ProjectNode(node, exprs, names)
                return node, scope
            if ref.alias and ref.name in ("unnest", "generate_series") \
                    and len(scope.columns) == 1:
                # PG: an alias on a single-column table function renames
                # the column too (SELECT u FROM unnest(...) AS u)
                c = scope.columns[0]
                scope = Scope([ScopeColumn(c.table, ref.alias, c.type,
                                           c.index)])
                node = ProjectNode(node, [BoundColumn(c.index, c.type,
                                                      ref.alias)],
                                   [ref.alias])
            return node, scope
        if isinstance(ref, ast.SubqueryRef):
            inner = self.plan_select(ref.query)
            alias = ref.alias or "subquery"
            names = list(inner.names)
            if ref.col_aliases:
                if len(ref.col_aliases) > len(names):
                    raise errors.SqlError(
                        errors.SYNTAX_ERROR,
                        f"table \"{alias}\" has {len(names)} columns "
                        f"available but {len(ref.col_aliases)} specified")
                names[:len(ref.col_aliases)] = ref.col_aliases
                inner = ProjectNode(
                    inner, [BoundColumn(i, t, nm) for i, (nm, t) in
                            enumerate(zip(names, inner.types))], names)
            scope = Scope([ScopeColumn(alias, n, t, i)
                           for i, (n, t) in enumerate(
                               zip(names, inner.types))])
            return inner, scope
        if isinstance(ref, ast.JoinRef):
            return self._plan_join(ref)
        raise errors.unsupported(f"FROM {type(ref).__name__}")

    def _plan_join(self, ref: ast.JoinRef) -> tuple[PlanNode, Scope]:
        left, lscope = self._plan_from(ref.left)
        right, rscope = self._plan_from(ref.right)
        n_left = len(lscope.columns)
        combined = Scope(
            [ScopeColumn(c.table, c.name, c.type, c.index, c.hidden)
             for c in lscope.columns] +
            [ScopeColumn(c.table, c.name, c.type, c.index + n_left,
                         c.hidden)
             for c in rscope.columns])
        names = _dedup_names([c.name for c in combined.columns])
        types = [c.type for c in combined.columns]
        left_keys: list[BoundExpr] = []
        right_keys: list[BoundExpr] = []
        residual: Optional[BoundExpr] = None
        merge_pairs: list[tuple[int, int]] = []
        using = ref.using
        kind = ref.kind
        if using == ["*natural*"]:
            # NATURAL JOIN: USING over the column names both sides share,
            # in left-side order (PG). Resolved into LOCALS — the AST is
            # shared by views/prepared statements and must stay pristine
            # so each re-plan sees the current schemas. No shared
            # columns → cross join.
            rnames = {c.name.lower() for c in rscope.columns
                      if not c.hidden}
            shared = []
            seen = set()
            for c in lscope.columns:
                nl = c.name.lower()
                if not c.hidden and nl in rnames and nl not in seen:
                    shared.append(c.name)
                    seen.add(nl)
            using = shared or None
            if using is None:
                kind = "cross"
        if using:
            for col in using:
                lc = lscope.resolve([col])
                rc = rscope.resolve([col])
                left_keys.append(BoundColumn(lc.index, lc.type, lc.name))
                right_keys.append(BoundColumn(rc.index, rc.type, rc.name))
                # PG: USING merges the key column — hide the non-merged
                # side's copy from bare-name resolution and SELECT *
                # (right joins keep the right side, others the left). A
                # FULL join's merged key is COALESCE(l, r): the executor
                # overwrites the left copy with right values on
                # right-only rows (merge_pairs).
                hide_right = kind != "right"
                if kind == "full":
                    merge_pairs.append((lc.index, rc.index))
                for c in combined.columns:
                    if c.name.lower() != col.lower():
                        continue
                    if hide_right and c.index >= n_left:
                        c.hidden = True
                    elif not hide_right and c.index < n_left:
                        c.hidden = True
        elif ref.condition is not None:
            residual_parts = []
            for c in _split_conjuncts(ref.condition):
                pair = self._try_equi_key(c, lscope, rscope)
                if pair is not None:
                    left_keys.append(pair[0])
                    right_keys.append(pair[1])
                else:
                    residual_parts.append(c)
            if residual_parts:
                binder = self._binder(combined)
                bound = [binder.bind(p) for p in residual_parts]
                residual = bound[0] if len(bound) == 1 else BoundFunc(
                    "and", bound, dt.BOOL, lambda cols, b: kleene_and(cols))
        node = JoinNode(kind, left, right, left_keys, right_keys,
                        residual, names, types, merge_pairs=merge_pairs)
        return node, combined

    def _try_equi_key(self, e: ast.Expr, lscope: Scope, rscope: Scope):
        if not (isinstance(e, ast.BinaryOp) and e.op == "="):
            return None
        for a, b in ((e.left, e.right), (e.right, e.left)):
            try:
                lb = self._binder(lscope).bind(a)
                rb = self._binder(rscope).bind(b)
                return (lb, rb)
            except errors.SqlError:
                continue
        return None

    # -- SELECT body -------------------------------------------------------

    def _plan_body(self, sel: ast.Select, plan: PlanNode,
                   scope: Scope) -> PlanNode:
        if sel.where is not None:
            binder = self._binder(scope)
            pred = binder.bind(sel.where)
            plan = self._push_filter(plan, pred)

        # expand stars
        items: list[ast.SelectItem] = []
        for it in sel.items:
            if isinstance(it.expr, ast.Star):
                for c in scope.star_columns(it.expr.table):
                    items.append(ast.SelectItem(
                        ast.ColumnRef([c.table, c.name] if c.table else [c.name]),
                        c.name))
            else:
                items.append(it)
        out_names = _dedup_names(
            [it.alias or _default_name(it.expr) for it in items])

        # window functions: pull them out of the item trees first; they
        # evaluate over the (post-aggregate) input via a WindowNode
        window_asts: list[ast.WindowFunc] = []
        items = [ast.SelectItem(_extract_windows(it.expr, window_asts),
                                it.alias) for it in items]

        has_aggs = bool(sel.group_by) or sel.having is not None or \
            any(_contains_agg(it.expr) for it in items) or \
            any(_contains_agg_list(w.partition_by) or
                _contains_agg_list([oi.expr for oi in w.order_by])
                for w in window_asts)

        if has_aggs:
            # window-referencing items can't bind before the WindowNode
            # exists: swap a placeholder through the aggregate binder and
            # rebind the real expression afterwards (mixing aggregates and
            # window refs in ONE expression is not supported yet)
            for it in items:
                if _mentions_win(it.expr) and _contains_agg(it.expr):
                    raise errors.unsupported(
                        "mixing aggregate and window functions in one "
                        "expression")
            agg_items = [ast.SelectItem(ast.Literal(0), it.alias)
                         if _mentions_win(it.expr) else it for it in items]
            plan, exprs, bind_order = self._plan_aggregate(
                sel, agg_items, plan, scope)
        else:
            binder = self._binder(scope)
            exprs = [BoundLiteral(0, dt.INT) if _mentions_win(it.expr)
                     else binder.bind(it.expr) for it in items]

            def bind_order(e: ast.Expr) -> BoundExpr:
                return self._binder(scope).bind(e)

        if window_asts:
            plan, scope, exprs = self._plan_windows(
                sel, window_asts, plan, scope, items, exprs, bind_order,
                has_aggs)

        # ORDER BY: positions, select aliases, then arbitrary expressions
        sort_exprs: list[BoundExpr] = []
        descs: list[bool] = []
        nfs: list[Optional[bool]] = []
        for oi in sel.order_by:
            descs.append(oi.desc)
            nfs.append(oi.nulls_first)
            e = oi.expr
            if isinstance(e, ast.Literal) and isinstance(e.value, int):
                pos = e.value
                if not (1 <= pos <= len(exprs)):
                    raise errors.SqlError(
                        "42P10", f"ORDER BY position {pos} is out of range")
                sort_exprs.append(exprs[pos - 1])
                continue
            if isinstance(e, ast.ColumnRef) and len(e.parts) == 1:
                matches = [k for k, it in enumerate(items)
                           if it.alias and it.alias.lower() == e.parts[0].lower()]
                if matches:
                    sort_exprs.append(exprs[matches[0]])
                    continue
            # expression over select items (e.g. ORDER BY the same expr text)
            matched = None
            for k, it in enumerate(items):
                if _ast_eq(e, it.expr):
                    matched = exprs[k]
                    break
            sort_exprs.append(matched if matched is not None else bind_order(e))

        proj_exprs = list(exprs)
        proj_names = list(out_names)
        hidden = 0
        sort_indices = []
        for se in sort_exprs:
            found = next((k for k, pe in enumerate(proj_exprs) if pe is se),
                         None)
            if found is None:
                proj_exprs.append(se)
                proj_names.append(f"#sort{hidden}")
                found = len(proj_exprs) - 1
                hidden += 1
            sort_indices.append(found)

        on_indices: list[int] = []
        if sel.distinct_on:
            for e in sel.distinct_on:
                found = None
                for k, it in enumerate(items):
                    if _ast_eq(e, it.expr):
                        found = k
                        break
                if found is None and isinstance(e, ast.ColumnRef) and \
                        len(e.parts) == 1:
                    m = [k for k, it in enumerate(items)
                         if it.alias and
                         it.alias.lower() == e.parts[0].lower()]
                    if m:
                        found = m[0]
                if found is None:
                    proj_exprs.append(bind_order(e))
                    proj_names.append(f"#on{len(on_indices)}")
                    hidden += 1
                    found = len(proj_exprs) - 1
                on_indices.append(found)
            if sort_indices and sort_indices[:len(on_indices)] != on_indices:
                raise errors.SqlError(
                    "42P10", "SELECT DISTINCT ON expressions must match "
                    "initial ORDER BY expressions")

        plan = ProjectNode(plan, proj_exprs, _dedup_names(proj_names))
        if sel.distinct:
            if hidden:
                raise errors.unsupported(
                    "SELECT DISTINCT with ORDER BY on non-selected expression")
            plan = _distinct_node(plan, keep=len(out_names))
        if sort_indices:
            plan = SortNode(plan, sort_indices, descs, nfs)
        if on_indices:
            from ..exec.plan import DistinctOnNode
            plan = DistinctOnNode(plan, on_indices)
        if hidden:
            plan = DropColumnsNode(plan, len(out_names))

        if sel.limit is not None or sel.offset is not None:
            limit = _const_int(sel.limit, self.params) \
                if sel.limit is not None else None
            offset = _const_int(sel.offset, self.params) \
                if sel.offset is not None else 0
            plan = LimitNode(plan, limit, offset)
        return plan

    def _plan_windows(self, sel, window_asts, plan, scope, items, exprs,
                      bind_order, has_aggs):
        """Insert a WindowNode computing #winN columns over the current
        plan; rebind select items in the extended scope."""
        from ..exec.window import (WINDOW_FUNCS, WindowNode, WindowSpec,
                                   window_result_type)
        specs = []
        for w in window_asts:
            fname = w.func.name
            if fname not in WINDOW_FUNCS:
                raise errors.SqlError(
                    errors.UNDEFINED_FUNCTION,
                    f"window function {fname}() does not exist")
            arg = None
            extra = None
            default = None
            if fname == "ntile":
                if not w.func.args or not (
                        isinstance(w.func.args[0], ast.Literal) and
                        isinstance(w.func.args[0].value, int)):
                    raise errors.syntax(
                        "ntile requires a constant integer argument")
                extra = w.func.args[0].value
            elif fname in ("lag", "lead"):
                if not w.func.args:
                    raise errors.syntax(f"{fname} requires an argument")
                arg = bind_order(w.func.args[0])
                if len(w.func.args) > 1:
                    off = w.func.args[1]
                    if not (isinstance(off, ast.Literal) and
                            isinstance(off.value, int)):
                        raise errors.unsupported(
                            f"{fname} offset must be a constant")
                    extra = off.value
                if len(w.func.args) > 2:
                    dv = w.func.args[2]
                    neg = isinstance(dv, ast.UnaryOp) and dv.op == "-"
                    if neg:
                        dv = dv.operand
                    if not isinstance(dv, ast.Literal):
                        raise errors.unsupported(
                            f"{fname} default must be a constant")
                    default = -dv.value if neg else dv.value
                    if isinstance(default, str) or arg.type.is_string:
                        # a numeric default on a dictionary-coded string
                        # column would be injected as a raw code
                        raise errors.unsupported(
                            f"{fname} default over a text column is not "
                            "supported")
            elif fname in ("count",) and (w.func.star or not w.func.args):
                arg = None
            elif w.func.args:
                arg = bind_order(w.func.args[0])
            elif fname in ("sum", "min", "max", "avg", "first_value",
                           "last_value"):
                raise errors.syntax(f"{fname} requires an argument")
            partition = [bind_order(p) for p in w.partition_by]
            order = [(bind_order(oi.expr), oi.desc) for oi in w.order_by]
            specs.append(WindowSpec(
                fname, arg, extra, partition, order,
                window_result_type(fname, arg.type if arg else None),
                default=default, frame=w.frame))
        node = WindowNode(plan, specs)
        # preserve the child scope's table qualifiers; only the appended
        # #winN columns are unqualified
        base_cols = [ScopeColumn(c.table, c.name, c.type, c.index)
                     for c in scope.columns]
        win_cols = [ScopeColumn(None, f"#win{i}", s.type,
                                len(plan.names) + i)
                    for i, s in enumerate(specs)]
        new_scope = Scope(base_cols + win_cols)
        # rebind items: #winN refs now resolve; previous bound exprs for
        # non-window items are re-derived in the extended scope
        binder = self._binder(new_scope)
        new_exprs = []
        for it, old in zip(items, exprs):
            if _mentions_win(it.expr):
                new_exprs.append(binder.bind(it.expr))
            else:
                new_exprs.append(old)
        return node, new_scope, new_exprs

    def _push_filter(self, plan: PlanNode, pred: BoundExpr) -> PlanNode:
        """Claim the predicate into the scan when the input is a bare scan
        (the pushdown the reference does in its pre-optimizer pass)."""
        if isinstance(plan, ScanNode) and plan.filter is None:
            plan.filter = pred
            return plan
        return FilterNode(plan, pred)

    def _plan_aggregate(self, sel: ast.Select, items: list[ast.SelectItem],
                        plan: PlanNode, scope: Scope):
        base = self._binder(scope, allow_aggs=True)
        group_asts: list[ast.Expr] = []
        group_bound: list[BoundExpr] = []
        for g in sel.group_by:
            if isinstance(g, ast.Literal) and isinstance(g.value, int):
                pos = g.value
                if not (1 <= pos <= len(items)):
                    raise errors.SqlError("42P10",
                                          f"GROUP BY position {pos} out of range")
                g = items[pos - 1].expr
            elif isinstance(g, ast.ColumnRef) and len(g.parts) == 1:
                for it in items:
                    if it.alias and it.alias.lower() == g.parts[0].lower():
                        g = it.expr
                        break
            group_asts.append(g)
            group_bound.append(base.bind(g))

        post = PostAggBinder(scope, self.params, group_asts,
                             [b.type for b in group_bound])
        post.planner = self
        bound_items = [post.bind(it.expr) for it in items]
        having_b = post.bind(sel.having) if sel.having is not None else None

        ng = len(group_bound)
        agg_names = [f"#g{k}" for k in range(ng)] + \
                    [f"#agg{k}" for k in range(len(post.aggs))]
        agg_node: PlanNode = AggregateNode(plan, group_bound, post.aggs,
                                           agg_names)
        out_types = agg_node.types
        exprs = [_resolve_post(e, ng, out_types) for e in bound_items]
        if having_b is not None:
            agg_node = FilterNode(agg_node,
                                  _resolve_post(having_b, ng, out_types))

        def bind_order(e: ast.Expr) -> BoundExpr:
            return _resolve_post(post.bind(e), ng, out_types)

        return agg_node, exprs, bind_order


def _extract_windows(e: ast.Expr, out: list) -> ast.Expr:
    """Replace WindowFunc nodes with #winN column refs, collecting specs
    (deduplicated by syntactic equality)."""
    if isinstance(e, ast.WindowFunc):
        for k, w in enumerate(out):
            if _ast_eq(e, w):
                return ast.ColumnRef([f"#win{k}"])
        out.append(e)
        return ast.ColumnRef([f"#win{len(out) - 1}"])
    for attr in ("left", "right", "operand", "low", "high", "pattern"):
        v = getattr(e, attr, None)
        if isinstance(v, ast.Expr):
            setattr(e, attr, _extract_windows(v, out))
    if isinstance(e, ast.Logical):
        e.args = [_extract_windows(a, out) for a in e.args]
    if isinstance(e, ast.FuncCall):
        e.args = [_extract_windows(a, out) for a in e.args]
    if isinstance(e, ast.InList):
        e.items = [_extract_windows(i, out) for i in e.items]
    if isinstance(e, ast.Case):
        e.branches = [(_extract_windows(c, out), _extract_windows(v, out))
                      for c, v in e.branches]
        if e.else_ is not None:
            e.else_ = _extract_windows(e.else_, out)
    if isinstance(e, ast.Cast):
        e.operand = _extract_windows(e.operand, out)
    return e


def _mentions_win(e: ast.Expr) -> bool:
    if isinstance(e, ast.ColumnRef) and e.parts[-1].startswith("#win"):
        return True
    for attr in ("left", "right", "operand", "low", "high", "pattern"):
        v = getattr(e, attr, None)
        if isinstance(v, ast.Expr) and _mentions_win(v):
            return True
    for attr in ("args", "items"):
        for v in getattr(e, attr, []) or []:
            if isinstance(v, ast.Expr) and _mentions_win(v):
                return True
    if isinstance(e, ast.Case):
        parts = [x for br in e.branches for x in br]
        if e.operand:
            parts.append(e.operand)
        if e.else_:
            parts.append(e.else_)
        return any(_mentions_win(p) for p in parts)
    if isinstance(e, ast.Cast):
        return _mentions_win(e.operand)
    return False


def _contains_agg_list(exprs) -> bool:
    return any(_contains_agg(x) for x in exprs or [])


def _ast_eq(a: ast.Expr, b: ast.Expr) -> bool:
    return type(a) is type(b) and repr(a) == repr(b)


def _contains_agg(e: ast.Expr) -> bool:
    if isinstance(e, ast.FuncCall):
        if e.name in AGG_FUNCS or e.star:
            return True
        return any(_contains_agg(a) for a in e.args)
    for attr in ("left", "right", "operand", "low", "high", "pattern"):
        v = getattr(e, attr, None)
        if isinstance(v, ast.Expr) and _contains_agg(v):
            return True
    if isinstance(e, ast.Logical):
        return any(_contains_agg(a) for a in e.args)
    if isinstance(e, ast.InList):
        return _contains_agg(e.operand) or any(_contains_agg(i) for i in e.items)
    if isinstance(e, ast.Case):
        parts = [x for br in e.branches for x in br]
        if e.operand:
            parts.append(e.operand)
        if e.else_:
            parts.append(e.else_)
        return any(_contains_agg(p) for p in parts)
    if isinstance(e, ast.Cast):
        return _contains_agg(e.operand)
    return False


def _refs_columns(e: BoundExpr) -> bool:
    """True if the bound expression reads any batch column (i.e. is not a
    constant-foldable expression)."""
    if isinstance(e, (BoundColumn, BoundAggRef)):
        return True
    return any(_refs_columns(c) for c in e.children())


def _default_name(e: ast.Expr) -> str:
    if isinstance(e, ast.ColumnRef):
        return e.parts[-1]
    if isinstance(e, ast.FuncCall):
        return e.name
    if isinstance(e, ast.Cast):
        return _default_name(e.operand)
    return "?column?"


def _dedup_names(names: list[str]) -> list[str]:
    seen: dict[str, int] = {}
    out = []
    for n in names:
        if n in seen:
            seen[n] += 1
            out.append(f"{n}_{seen[n]}")
        else:
            seen[n] = 0
            out.append(n)
    return out


def _split_conjuncts(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.Logical) and e.op == "AND":
        out = []
        for a in e.args:
            out.extend(_split_conjuncts(a))
        return out
    return [e]


def _const_int(e: ast.Expr, params: list) -> int:
    binder = ExprBinder(Scope([]), params)
    b = binder.bind(e)
    if not isinstance(b, BoundLiteral) or not isinstance(b.value, (int, float)):
        raise errors.syntax("LIMIT/OFFSET must be a constant")
    return int(b.value)


def _distinct_node(plan: PlanNode, keep: int) -> PlanNode:
    """DISTINCT = group by all output columns, no aggregates."""
    exprs = [BoundColumn(i, t, n)
             for i, (n, t) in enumerate(zip(plan.names, plan.types))]
    return AggregateNode(plan, exprs[:keep], [], list(plan.names[:keep]))
