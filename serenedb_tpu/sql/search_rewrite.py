"""Post-planning rewrite: claim full-text predicates into index scans.

Reference analog: the pre-optimizer pass that claims WHERE conjuncts for
iresearch and pushes scorer calls into virtual score columns
(IResearchPushdownComplexFilter / PushdownScorerCall / score-column reuse in
ORDER BY — reference: server/connector/optimizer/iresearch_plan.cpp:
927-1108). Patterns:

1. Scan(filter with ts conjuncts on an indexed column) → SearchScanNode
   (Stream mode), remaining conjuncts as residual.
2. Limit(Sort desc by bm25(col))(Project(Scan(ts-only filter))) →
   SearchScanNode (TopK mode) with a #score output column; bm25()/tfidf()
   calls in the projection are rewired to that column.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..columnar import dtypes as dt
from ..exec.plan import (AggregateNode, DropColumnsNode, FilterNode, JoinNode,
                         LimitNode, PlanNode, ProjectNode, ScanNode, SortNode)
from ..exec.search_scan import SCORE_COL, SearchScanNode
from ..search.index import find_index
from ..search.query import QAnd, QNode, QPhrase, QTerm, parse_query
from .expr import BoundColumn, BoundExpr, BoundFunc, kleene_and

_TS_FUNCS = {"ts_phrase", "ts_query"}
_SCORER_FUNCS = {"bm25", "tfidf", "lm_dirichlet", "jelinek_mercer",
                 "dfi"}


def rewrite_search(plan: PlanNode) -> PlanNode:
    topk = _match_topk(plan)
    if topk is not None:
        return topk
    # Project-over-Scan must be matched BEFORE recursing, or the generic
    # ScanNode branch claims the scan without score wiring
    if isinstance(plan, ProjectNode) and isinstance(plan.child, ScanNode):
        new_child = _try_search_scan(plan.child,
                                     want_score=_has_scorer(plan.exprs),
                                     scorer=_scorer_name(plan.exprs))
        if new_child is not None:
            plan.child = new_child
            if new_child.with_score:
                _rewire_scorers(plan.exprs, new_child)
            return plan
        bt = _try_btree_scan(plan.child) or _try_pk_scan(plan.child) \
            or _try_geo_scan(plan.child)
        if bt is not None:
            plan.child = bt
            return plan
    _rewrite_children(plan)
    if isinstance(plan, ScanNode):
        replaced = _try_search_scan(plan, want_score=False)
        if replaced is None:
            replaced = _try_btree_scan(plan)
        if replaced is None:
            replaced = _try_pk_scan(plan)
        if replaced is None:
            replaced = _try_geo_scan(plan)
        if replaced is not None:
            return replaced
    return plan


def _rewrite_children(plan: PlanNode) -> None:
    for attr in ("child", "left", "right"):
        c = getattr(plan, attr, None)
        if isinstance(c, PlanNode):
            setattr(plan, attr, rewrite_search(c))


# -- pattern 2: scored top-k ----------------------------------------------

_VEC_FUNCS = {"vec_l2", "vec_ip", "vec_cos"}


def _match_topk(plan: PlanNode) -> Optional[PlanNode]:
    limit = plan if isinstance(plan, LimitNode) else None
    if limit is None or limit.limit is None:
        return None
    inner = limit.child
    drop = None
    if isinstance(inner, DropColumnsNode):
        drop = inner
        inner = inner.child
    if not isinstance(inner, SortNode):
        return None
    sort = inner
    if len(sort.key_indices) != 1:
        return None
    if not isinstance(sort.child, ProjectNode):
        return None
    proj = sort.child
    key_expr = proj.exprs[sort.key_indices[0]]
    if not sort.descs[0]:
        return _match_ann_topk(plan, limit, sort, proj, key_expr)
    claimed = _match_maxsim_topk(plan, limit, sort, proj, key_expr)
    if claimed is not None:
        return claimed
    if not (isinstance(key_expr, BoundFunc) and
            key_expr.name in _SCORER_FUNCS and key_expr.args and
            isinstance(key_expr.args[0], BoundColumn)):
        return None
    if not isinstance(proj.child, ScanNode):
        return None
    scan = proj.child
    search_col_idx = key_expr.args[0].index
    search_col = scan.columns[search_col_idx]
    qnode, residual = _claim_ts(scan, search_col)
    if qnode is None or residual is not None:
        # residual conjuncts would filter *after* top-k and break LIMIT
        return None
    k = limit.limit + limit.offset
    node = SearchScanNode(scan.provider, scan.columns, scan.alias,
                          search_col, qnode, None, k, with_score=True,
                          scorer=key_expr.name)
    _rewire_scorers(proj.exprs, node)
    proj.child = node
    return plan


def _match_ann_topk(plan: PlanNode, limit, sort, proj,
                    key_expr) -> Optional[PlanNode]:
    """ORDER BY vec_*(col, 'literal') ASC LIMIT k over an ivf-indexed
    column → IvfScanNode (reference: TryClaimAnnRange)."""
    from ..exec.search_scan import IvfScanNode
    from ..search.ivf import find_ivf_index, parse_vector
    from .expr import BoundLiteral
    if not (isinstance(key_expr, BoundFunc) and
            key_expr.name in _VEC_FUNCS and len(key_expr.args) == 2):
        return None
    col, lit = key_expr.args
    if not (isinstance(col, BoundColumn) and
            isinstance(lit, BoundLiteral) and isinstance(lit.value, str)):
        return None
    if not isinstance(proj.child, ScanNode):
        return None
    scan = proj.child
    if scan.filter is not None:
        return None  # predicate + ANN composition comes later
    vec_col = scan.columns[col.index]
    idx = find_ivf_index(scan.provider, vec_col)
    if idx is None:
        return None
    metric = {"vec_l2": "l2", "vec_ip": "ip", "vec_cos": "cos"}[key_expr.name]
    if idx.metric != metric:
        return None
    qvec = parse_vector(lit.value, idx.dim)
    k = limit.limit + limit.offset
    node = IvfScanNode(scan.provider, scan.columns, scan.alias, vec_col,
                       qvec, k)
    dist_ref = BoundColumn(len(node.columns), dt.DOUBLE, IvfScanNode.DIST_COL)

    def rec(e: BoundExpr) -> BoundExpr:
        if isinstance(e, BoundFunc):
            # only the ordering metric's own function maps to #dist —
            # vec_cos over an l2-ordered scan must keep its CPU value
            if e.name == key_expr.name and len(e.args) == 2 and \
                    isinstance(e.args[0], BoundColumn) and \
                    e.args[0].index == col.index and \
                    isinstance(e.args[1], BoundLiteral) and \
                    e.args[1].value == lit.value:
                return dist_ref
            e.args = [rec(a) for a in e.args]
        return e

    for i in range(len(proj.exprs)):
        proj.exprs[i] = rec(proj.exprs[i])
    proj.child = node
    return plan


def _match_maxsim_topk(plan: PlanNode, limit, sort, proj,
                       key_expr) -> Optional[PlanNode]:
    """ORDER BY vec_maxsim(col, 'literal') DESC LIMIT k over a
    maxsim-indexed column → MaxSimScanNode. The SortNode stays in the
    plan — its stable re-sort over #msim preserves the device's
    (score desc, doc asc) tie order for free."""
    from ..exec.search_scan import MaxSimScanNode
    from ..search.ivf import find_maxsim_index, parse_multi_vector
    from .expr import BoundLiteral
    if not (isinstance(key_expr, BoundFunc) and
            key_expr.name == "vec_maxsim" and len(key_expr.args) == 2):
        return None
    col, lit = key_expr.args
    if not (isinstance(col, BoundColumn) and
            isinstance(lit, BoundLiteral) and isinstance(lit.value, str)):
        return None
    if not isinstance(proj.child, ScanNode):
        return None
    scan = proj.child
    if scan.filter is not None:
        return None  # predicate + late-interaction composition later
    vec_col = scan.columns[col.index]
    idx = find_maxsim_index(scan.provider, vec_col)
    if idx is None:
        return None
    qtoks = parse_multi_vector(lit.value, idx.dim)
    if qtoks is None:
        return None  # empty query scores every doc 0 — not claimable
    k = limit.limit + limit.offset
    node = MaxSimScanNode(scan.provider, scan.columns, scan.alias,
                          vec_col, qtoks, k)
    score_ref = BoundColumn(len(node.columns), dt.DOUBLE,
                            MaxSimScanNode.SCORE_COL)

    def rec(e: BoundExpr) -> BoundExpr:
        if isinstance(e, BoundFunc):
            if e.name == "vec_maxsim" and len(e.args) == 2 and \
                    isinstance(e.args[0], BoundColumn) and \
                    e.args[0].index == col.index and \
                    isinstance(e.args[1], BoundLiteral) and \
                    e.args[1].value == lit.value:
                return score_ref
            e.args = [rec(a) for a in e.args]
        return e

    for i in range(len(proj.exprs)):
        proj.exprs[i] = rec(proj.exprs[i])
    proj.child = node
    return plan


def _has_scorer(exprs: list[BoundExpr]) -> bool:
    return any(isinstance(s, BoundFunc) and s.name in _SCORER_FUNCS
               for e in exprs for s in e.walk())


def _rewire_scorers(exprs: list[BoundExpr], node: SearchScanNode) -> None:
    """Replace calls of the scan's OWN scorer over the searched column with
    the #score output; a different scorer function (the scan computes only
    one) and scorers over other columns keep their default (0.0) — never
    alias one scorer's values onto another's column."""
    score_ref = BoundColumn(len(node.columns), dt.FLOAT, SCORE_COL)
    search_idx = node.columns.index(node.search_column)

    def rec(e: BoundExpr) -> BoundExpr:
        if isinstance(e, BoundFunc):
            if e.name == node.scorer and e.args and \
                    isinstance(e.args[0], BoundColumn) and \
                    e.args[0].index == search_idx:
                return score_ref
            e.args = [rec(a) for a in e.args]
        return e

    for i in range(len(exprs)):
        exprs[i] = rec(exprs[i])


# -- pattern 1: filter pushdown -------------------------------------------

def _scorer_name(exprs: list[BoundExpr]) -> str:
    for e in exprs:
        for s in e.walk():
            if isinstance(s, BoundFunc) and s.name in _SCORER_FUNCS:
                return s.name
    return "bm25"


def _try_btree_scan(scan: ScanNode):
    """col = constant conjunct over a btree-indexed column → point lookup
    (reference: PK lookup fast path)."""
    from ..exec.search_scan import BtreeScanNode
    from ..search.index import find_btree_index
    from .expr import BoundLiteral
    if scan.filter is None:
        return None
    conjuncts = _conjuncts(scan.filter)
    for k, c in enumerate(conjuncts):
        if not (isinstance(c, BoundFunc) and c.name == "op=" and
                len(c.args) == 2):
            continue
        for col, lit in ((c.args[0], c.args[1]), (c.args[1], c.args[0])):
            if not (isinstance(col, BoundColumn) and
                    isinstance(lit, BoundLiteral) and
                    lit.value is not None):
                continue
            col_name = scan.columns[col.index]
            idx = find_btree_index(scan.provider, col_name)
            if idx is None:
                continue
            value = lit.value
            if scan.provider.type_of(col_name).is_string:
                # equality on strings → dictionary code; an absent string
                # maps to the impossible code -1 (empty lookup)
                host = scan.provider.host_column(col_name)
                if host.dictionary is None:
                    continue
                import numpy as _np
                ds = host.dictionary.astype(str)
                pos = int(_np.searchsorted(ds, str(value)))
                value = pos if pos < len(ds) and ds[pos] == str(value) \
                    else -1
            residual = _and_conjuncts(conjuncts[:k] + conjuncts[k + 1:])
            return BtreeScanNode(scan.provider, scan.columns, scan.alias,
                                 col_name, value, residual)
    return None


_GEO_CLAIM_FNS = {"st_intersects", "st_contains", "st_within",
                  "st_covers", "st_coveredby", "st_dwithin"}


def _try_geo_scan(scan: ScanNode):
    """Geo conjunct over a geo-indexed column + a constant geometry →
    cell-term candidate scan with exact post-verification (reference:
    geo_filter_builder.cpp pushing GeoFilter into the inverted index).
    The claimed conjunct stays in the residual — the index only narrows
    the rows it is evaluated over."""
    from ..exec.search_scan import GeoScanNode
    from ..geo import cells as geo_cells
    from ..geo import shapes as geo_shapes
    from ..search.index import find_geo_index
    from .expr import BoundLiteral
    if scan.filter is None:
        return None
    conjuncts = _conjuncts(scan.filter)
    for c in conjuncts:
        if not (isinstance(c, BoundFunc) and c.name in _GEO_CLAIM_FNS
                and len(c.args) >= 2):
            continue
        radius = 0.0
        if c.name == "st_dwithin":
            if len(c.args) < 3 or not isinstance(c.args[2], BoundLiteral) \
                    or c.args[2].value is None:
                continue   # NULL/non-constant radius: unindexed path
            try:
                radius = float(c.args[2].value)
            except (TypeError, ValueError):
                continue
        for col, lit in ((c.args[0], c.args[1]), (c.args[1], c.args[0])):
            if not (isinstance(col, BoundColumn) and
                    isinstance(lit, BoundLiteral) and
                    isinstance(lit.value, str)):
                continue
            col_name = scan.columns[col.index]
            if find_geo_index(scan.provider, col_name) is None:
                continue
            try:
                probe = geo_cells.query_terms(
                    geo_shapes.parse_any(lit.value), radius)
            except Exception:
                continue
            # ALL conjuncts (incl. the claimed one) run over candidates
            return GeoScanNode(scan.provider, scan.columns, scan.alias,
                               col_name, probe, scan.filter)
    return None


_RANGE_OPS = {"op<": "lt", "op<=": "le", "op>": "gt", "op>=": "ge"}


def _try_pk_scan(scan: ScanNode):
    """PK-index claims (reference: key_encoding.cpp order-preserving PK
    terms): equality on EVERY PK column → point lookup; equality/range
    conjuncts on the LEADING PK column → key range scan."""
    from ..columnar import keyenc
    from ..exec.search_scan import PkScanNode
    from .expr import BoundLiteral
    if scan.filter is None:
        return None
    meta = getattr(scan.provider, "table_meta", None) or {}
    pk = meta.get("primary_key") or []
    if not pk:
        return None
    conjuncts = _conjuncts(scan.filter)
    # collect (col_name, op, literal) claims
    claims = []
    for k, c in enumerate(conjuncts):
        if not (isinstance(c, BoundFunc) and len(c.args) == 2 and
                (c.name == "op=" or c.name in _RANGE_OPS)):
            continue
        for a, b, flip in ((c.args[0], c.args[1], False),
                           (c.args[1], c.args[0], True)):
            if isinstance(a, BoundColumn) and isinstance(b, BoundLiteral) \
                    and b.value is not None:
                op = c.name
                if flip and op in _RANGE_OPS:
                    op = {"op<": "op>", "op<=": "op>=", "op>": "op<",
                          "op>=": "op<="}[op]
                claims.append((k, scan.columns[a.index], op, b.value))
                break

    def enc(col, v):
        t = scan.provider.type_of(col)
        try:
            if t.is_integer and not isinstance(v, (int, np.integer)):
                return None
            return keyenc.encode_value(v, t)
        except Exception:
            return None

    # point: one equality per PK column
    eqs = {col: (k, v) for k, col, op, v in claims if op == "op="}
    if all(c in eqs for c in pk):
        parts = []
        used = []
        for c in pk:
            k, v = eqs[c]
            e = enc(c, v)
            if e is None:
                break
            parts.append(e)
            used.append(k)
        else:
            residual = _and_conjuncts(
                [c for k, c in enumerate(conjuncts) if k not in used])
            return PkScanNode(scan.provider, scan.columns, scan.alias,
                              "point", b"".join(parts), None, residual)
    # range on the leading PK column
    lead = pk[0]
    lo = hi = None
    used = []
    for k, col, op, v in claims:
        if col != lead:
            continue
        e = enc(col, v)
        if e is None:
            continue
        if op == "op=":
            lo, hi = e, keyenc.prefix_upper_bound(e)
            used = [k]
            break
        if op in ("op>", "op>="):
            b = e if op == "op>=" else keyenc.prefix_upper_bound(e)
            if b is not None and (lo is None or b > lo):
                lo = b
                used.append(k)
        elif op in ("op<", "op<="):
            b = e if op == "op<" else keyenc.prefix_upper_bound(e)
            if b is not None and (hi is None or b < hi):
                hi = b
                used.append(k)
    if lo is None and hi is None:
        return None
    residual = _and_conjuncts(
        [c for k, c in enumerate(conjuncts) if k not in used])
    return PkScanNode(scan.provider, scan.columns, scan.alias, "range",
                      lo, hi, residual)


def _try_search_scan(scan: ScanNode, want_score: bool,
                     scorer: str = "bm25") -> Optional[SearchScanNode]:
    if scan.filter is None:
        return None
    # find an indexed column among the ts conjuncts
    for col_name in scan.columns:
        if find_index(scan.provider, col_name) is None:
            continue
        qnode, residual = _claim_ts(scan, col_name)
        if qnode is not None:
            return SearchScanNode(scan.provider, scan.columns, scan.alias,
                                  col_name, qnode, residual, None,
                                  with_score=want_score, scorer=scorer)
    return None


def _claim_ts(scan: ScanNode, col_name: str,
              ) -> tuple[Optional[QNode], Optional[BoundExpr]]:
    """Claim ts conjuncts on col_name from the scan filter. Returns
    (query node, residual predicate)."""
    if scan.filter is None:
        return None, None
    idx = find_index(scan.provider, col_name)
    if idx is None:
        return None, None
    col_idx = scan.columns.index(col_name)
    from ..search.analysis import get_analyzer
    an = get_analyzer(idx.analyzer_name_for(col_name))
    claimed: list[QNode] = []
    residual: list[BoundExpr] = []
    for c in _conjuncts(scan.filter):
        q = _to_qnode(c, col_idx, an)
        if q is not None:
            claimed.append(q)
        else:
            residual.append(c)
    if not claimed:
        return None, None
    qnode = claimed[0] if len(claimed) == 1 else QAnd(claimed)
    return qnode, _and_conjuncts(residual)


def _and_conjuncts(exprs: list[BoundExpr]) -> Optional[BoundExpr]:
    if not exprs:
        return None
    if len(exprs) == 1:
        return exprs[0]
    return BoundFunc("and", exprs, dt.BOOL,
                     lambda cols, b: kleene_and(cols))


def _conjuncts(e: BoundExpr) -> list[BoundExpr]:
    if isinstance(e, BoundFunc) and e.name == "and":
        out = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _to_qnode(e: BoundExpr, col_idx: int, analyzer) -> Optional[QNode]:
    from .expr import BoundLiteral
    if isinstance(e, BoundFunc) and e.name == "or":
        # same-column disjunction of ts predicates claims as QOr (the ES
        # query_string path emits these; Lucene BooleanQuery SHOULD).
        # NULL-safe: a NULL document matches no branch under both the
        # index eval and SQL three-valued OR. Cross-column disjunctions
        # stay unclaimed (scoring would need multi-index evaluation).
        from ..search.query import QOr
        subs = [_to_qnode(a, col_idx, analyzer) for a in e.args]
        if subs and all(s is not None for s in subs):
            return QOr(subs)
        return None
    if not (isinstance(e, BoundFunc) and e.name in _TS_FUNCS and
            len(e.args) == 2):
        return None
    col, lit = e.args
    if not (isinstance(col, BoundColumn) and col.index == col_idx and
            isinstance(lit, BoundLiteral) and isinstance(lit.value, str)):
        return None
    if e.name == "ts_phrase":
        from ..search.query import QNothing, QOr, position_groups
        toks = analyzer.tokenize(lit.value)
        groups = position_groups(toks)
        if not groups:
            # zero analyzed terms match nothing (to_tsquery('')), and the
            # claim MUST happen: the brute fallback analyzes with the
            # default analyzer, not this column's dictionary
            return QNothing()
        if len(groups) == 1:
            alts = groups[0]
            return (QTerm(alts[0]) if len(alts) == 1
                    else QOr([QTerm(a) for a in alts]))
        return QPhrase([t.term for t in toks], groups)
    return parse_query(lit.value, analyzer)
