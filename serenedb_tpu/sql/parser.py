"""Recursive-descent SQL parser.

Covers the statement surface the engine executes (SELECT with joins/group/
order/limit, DDL for tables/indexes/schemas/views, INSERT/UPDATE/DELETE,
SET/SHOW, COPY, EXPLAIN, VACUUM, transactions) plus the SereneDB full-text
operators: `col ## 'phrase'` (phrase match) and `col @@ 'query'` (ts query),
mirroring the reference's SQL search surface
(reference: server/connector/functions/ts_*.cpp, examples/demo0/README.md).
"""

from __future__ import annotations

from typing import Optional

from .. import errors
from ..errors import SqlError
from . import ast
from .lexer import T, Token, tokenize

_KEYWORDS_STOP_ALIAS = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION",
    "EXCEPT", "INTERSECT", "ON", "USING", "JOIN", "INNER", "LEFT", "RIGHT",
    "FULL", "CROSS", "NATURAL", "AS", "AND", "OR", "NOT", "SET", "WITH",
    "ASC", "DESC",
    "NULLS", "INTO", "VALUES", "RETURNING", "THEN", "ELSE", "END", "WHEN",
    "CASE", "IS", "IN", "BETWEEN", "LIKE", "ILIKE", "BY",
}

_COMPARE_OPS = {"=", "<>", "!=", "<", "<=", ">", ">=", "##", "@@",
                "<->", "<#>", "<=>", "~", "~*", "!~", "!~*"}


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind is not T.EOF:
            self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind is T.IDENT and t.value.upper() in words

    def accept_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.accept_kw(word):
            raise errors.syntax(
                f"expected {word} near {self.peek().value!r}")

    def at_op(self, op: str) -> bool:
        t = self.peek()
        return t.kind is T.OP and t.value == op

    def accept_op(self, op: str) -> bool:
        if self.at_op(op):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise errors.syntax(f"expected {op!r} near {self.peek().value!r}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind is not T.IDENT:
            raise errors.syntax(f"expected identifier near {t.value!r}")
        self.next()
        return t.value

    def _explain_bool_opt(self) -> bool:
        """Optional boolean value of an EXPLAIN list option (PG: a bare
        option means ON; ON/OFF/TRUE/FALSE/1/0 are accepted values)."""
        t = self.peek()
        if t.kind is T.IDENT and t.value.upper() in (
                "ON", "OFF", "TRUE", "FALSE"):
            self.next()
            return t.value.upper() in ("ON", "TRUE")
        if t.kind is T.NUMBER and t.value in ("0", "1"):
            self.next()
            return t.value == "1"
        return True

    # -- entry points ------------------------------------------------------

    def parse_statements(self) -> list[ast.Statement]:
        stmts = []
        while self.peek().kind is not T.EOF:
            if self.accept_op(";"):
                continue
            start = self.peek().pos
            st = self.parse_statement()
            end = (self.peek().pos if self.peek().kind is not T.EOF
                   else len(self.sql))
            # per-statement source slice (view definitions, pg_stat_activity)
            st.source_sql = self.sql[start:end].rstrip().rstrip(";")
            if getattr(st, "body_pos", None) is not None:
                st.body_sql = self.sql[st.body_pos:end].rstrip().rstrip(";")
            stmts.append(st)
            if self.peek().kind is not T.EOF:
                self.expect_op(";")
        return stmts

    def parse_statement(self) -> ast.Statement:
        if self.at_kw("SELECT", "WITH") or self.at_op("("):
            return self.parse_select()
        if self.at_kw("CREATE"):
            return self.parse_create()
        if self.at_kw("DROP"):
            return self.parse_drop()
        if self.at_kw("INSERT"):
            return self.parse_insert()
        if self.at_kw("DELETE"):
            return self.parse_delete()
        if self.at_kw("UPDATE"):
            return self.parse_update()
        if self.at_kw("SET"):
            return self.parse_set()
        if self.at_kw("RESET"):
            self.next()
            if self.at_kw("ROLE"):
                self.next()
                return ast.SetRole(None)
            name = self.ident()
            return ast.SetStmt(name.lower(), "DEFAULT")
        if self.at_kw("SHOW"):
            self.next()
            parts = [self.ident()]
            while self.accept_op("."):
                parts.append(self.ident())
            return ast.ShowStmt(".".join(parts).lower())
        if self.at_kw("BEGIN", "START"):
            self.next()
            self.accept_kw("TRANSACTION") or self.accept_kw("WORK")
            return ast.Transaction("begin")
        if self.at_kw("COMMIT", "END"):
            self.next()
            self.accept_kw("TRANSACTION") or self.accept_kw("WORK")
            return ast.Transaction("commit")
        if self.at_kw("ROLLBACK", "ABORT"):
            self.next()
            self.accept_kw("TRANSACTION") or self.accept_kw("WORK")
            if self.accept_kw("TO"):
                self.accept_kw("SAVEPOINT")
                return ast.Transaction("rollback_to", self.ident())
            return ast.Transaction("rollback")
        if self.at_kw("SAVEPOINT"):
            self.next()
            return ast.Transaction("savepoint", self.ident())
        if self.at_kw("RELEASE"):
            self.next()
            self.accept_kw("SAVEPOINT")
            return ast.Transaction("release", self.ident())
        if self.at_kw("EXPLAIN"):
            self.next()
            analyze = False
            fmt = "text"
            if self.accept_op("("):
                # PG option-list form: EXPLAIN (ANALYZE [ON|OFF],
                # FORMAT {TEXT|JSON}, ...) — boolean options take an
                # optional value, FORMAT takes a required one
                while True:
                    opt = self.ident().lower()
                    if opt == "format":
                        fmt = self.ident().lower()
                        if fmt not in ("text", "json"):
                            raise errors.unsupported(
                                f"EXPLAIN format {fmt.upper()}")
                    elif opt in ("analyze", "analyse"):
                        analyze = self._explain_bool_opt()
                    elif opt in ("verbose", "costs", "timing",
                                 "summary", "buffers"):
                        self._explain_bool_opt()   # accepted, no-op
                    else:
                        raise errors.syntax(
                            f'unrecognized EXPLAIN option "{opt}"')
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            else:
                analyze = self.accept_kw("ANALYZE")
            return ast.Explain(self.parse_statement(), analyze, fmt)
        if self.at_kw("ALTER"):
            return self.parse_alter()
        if self.at_kw("GRANT", "REVOKE"):
            grant = self.ident().upper() == "GRANT"
            privs = [self.ident().lower()]
            while self.accept_op(","):
                privs.append(self.ident().lower())
            if self.at_kw("TO" if grant else "FROM") and len(privs) == 1:
                # GRANT <role> TO <member> — role membership
                self.next()
                member = self.ident()
                return ast.GrantRevoke(grant, [], [], member,
                                       granted_role=privs[0])
            self.expect_kw("ON")
            self.accept_kw("TABLE")
            table = self.qualified_name()
            self.expect_kw("TO" if grant else "FROM")
            role = self.ident()
            return ast.GrantRevoke(grant, privs, table, role)
        if self.at_kw("COPY"):
            return self.parse_copy()
        if self.at_kw("VACUUM"):
            return self.parse_vacuum()
        if self.at_kw("TRUNCATE"):
            self.next()
            self.accept_kw("TABLE")
            return ast.Truncate(self.qualified_name())
        if self.at_kw("LISTEN"):
            self.next()
            return ast.ListenStmt(self.ident().lower())
        if self.at_kw("UNLISTEN"):
            self.next()
            if self.accept_op("*"):
                return ast.ListenStmt("", "unlisten_all")
            return ast.ListenStmt(self.ident().lower(), "unlisten")
        if self.at_kw("NOTIFY"):
            self.next()
            channel = self.ident().lower()
            payload = ""
            if self.accept_op(","):
                t = self.next()
                if t.kind is not T.STRING:
                    raise errors.syntax("NOTIFY payload must be a string")
                payload = t.value
            return ast.NotifyStmt(channel, payload)
        if self.at_kw("VALUES"):
            return self.parse_select()
        raise errors.syntax(f"unsupported statement near {self.peek().value!r}")

    # -- SELECT ------------------------------------------------------------

    def parse_select(self):
        """SELECT / VALUES / set-operation chain / WITH prologue."""
        ctes: dict = {}
        if self.accept_kw("WITH"):
            recursive = bool(self.accept_kw("RECURSIVE"))
            while True:
                name = self.ident()
                cols = None
                if self.accept_op("("):
                    cols = [self.ident()]
                    while self.accept_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
                self.expect_kw("AS")
                self.expect_op("(")
                body = self.parse_select()
                self.expect_op(")")
                if recursive or cols is not None:
                    body = ast.CteDef(body, cols, recursive)
                ctes[name.lower()] = body
                if not self.accept_op(","):
                    break
        node = self._parse_intersect_chain()
        while self.at_kw("UNION", "EXCEPT"):
            op = self.ident().lower()
            all_ = bool(self.accept_kw("ALL"))
            self.accept_kw("DISTINCT")
            self._reject_unparenthesized_tail(node)
            # INTERSECT binds tighter than UNION/EXCEPT (PG gram.y)
            right = self._parse_intersect_chain()
            node = ast.SetOp(op, all_, node, right)
        if isinstance(node, ast.SetOp):
            # PG grammar: a trailing ORDER BY/LIMIT binds to the whole set
            # operation, but the greedy core parse attaches it to the last
            # arm — steal it back from the rightmost unparenthesized
            # Select (unless that arm was parenthesized)
            last = node.right
            while isinstance(last, ast.SetOp):
                last = last.right
            if isinstance(last, ast.Select) and \
                    not getattr(last, "_parens", False):
                node.order_by = last.order_by
                node.limit = last.limit
                node.offset = last.offset
                last.order_by, last.limit, last.offset = [], None, None
            if self.accept_kw("ORDER"):
                self.expect_kw("BY")
                node.order_by.append(self.parse_order_item())
                while self.accept_op(","):
                    node.order_by.append(self.parse_order_item())
            while self.at_kw("LIMIT", "OFFSET", "FETCH"):
                if self.accept_kw("LIMIT"):
                    if not self.accept_kw("ALL"):
                        node.limit = self.parse_expr()
                elif self.accept_kw("OFFSET"):
                    node.offset = self.parse_expr()
                    self.accept_kw("ROWS") or self.accept_kw("ROW")
                elif self.accept_kw("FETCH"):
                    # FETCH {FIRST|NEXT} [n] {ROW|ROWS} ONLY (SQL std)
                    if not (self.accept_kw("FIRST") or
                            self.accept_kw("NEXT")):
                        raise errors.syntax(
                            "expected FIRST or NEXT after FETCH")
                    if self.at_kw("ROW", "ROWS"):
                        node.limit = ast.Literal(1)
                    else:
                        node.limit = self.parse_expr()
                    self.accept_kw("ROWS") or self.accept_kw("ROW")
                    self.expect_kw("ONLY")
        if ctes:
            # inner (more deeply scoped) CTEs shadow outer ones; never
            # clobber a parenthesized arm's own WITH bindings
            node.ctes = {**ctes, **getattr(node, "ctes", {})}
        return node

    def _parse_intersect_chain(self):
        node = self._parse_select_core()
        while self.at_kw("INTERSECT"):
            self.next()
            all_ = bool(self.accept_kw("ALL"))
            self.accept_kw("DISTINCT")
            self._reject_unparenthesized_tail(node)
            node = ast.SetOp("intersect", all_, node,
                             self._parse_select_core())
        return node

    def _reject_unparenthesized_tail(self, node):
        if isinstance(node, ast.Select) and \
                not getattr(node, "_parens", False) and (
                node.order_by or node.limit is not None or
                node.offset is not None):
            raise errors.syntax(
                "ORDER BY/LIMIT/OFFSET in a set-operation arm needs "
                "parentheses")

    def _parse_select_core(self) -> ast.Select:
        if self.accept_op("("):
            inner = self.parse_select()
            self.expect_op(")")
            inner._parens = True  # its ORDER BY/LIMIT are scoped by parens
            return inner
        if self.at_kw("VALUES"):
            return self._parse_values_select()
        self.expect_kw("SELECT")
        distinct = False
        distinct_on = None
        if self.accept_kw("DISTINCT"):
            if self.accept_kw("ON"):
                self.expect_op("(")
                distinct_on = [self.parse_expr()]
                while self.accept_op(","):
                    distinct_on.append(self.parse_expr())
                self.expect_op(")")
            else:
                distinct = True
        else:
            self.accept_kw("ALL")
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())
        from_ = None
        if self.accept_kw("FROM"):
            from_ = self.parse_from()
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        group_by: list[ast.Expr] = []
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.parse_expr())
            while self.accept_op(","):
                group_by.append(self.parse_expr())
        having = self.parse_expr() if self.accept_kw("HAVING") else None
        order_by: list[ast.OrderItem] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())
        limit = offset = None
        while self.at_kw("LIMIT", "OFFSET", "FETCH"):
            if self.accept_kw("LIMIT"):
                if not self.accept_kw("ALL"):
                    limit = self.parse_expr()
            elif self.accept_kw("OFFSET"):
                offset = self.parse_expr()
                self.accept_kw("ROWS") or self.accept_kw("ROW")
            elif self.accept_kw("FETCH"):
                # FETCH {FIRST|NEXT} [n] {ROW|ROWS} ONLY (SQL std)
                if not (self.accept_kw("FIRST") or
                        self.accept_kw("NEXT")):
                    raise errors.syntax(
                        "expected FIRST or NEXT after FETCH")
                if self.at_kw("ROW", "ROWS"):
                    limit = ast.Literal(1)
                else:
                    limit = self.parse_expr()
                self.accept_kw("ROWS") or self.accept_kw("ROW")
                self.expect_kw("ONLY")
        return ast.Select(items, from_, where, group_by, having, order_by,
                          limit, offset, distinct, distinct_on)

    def _parse_values_select(self) -> ast.Select:
        self.expect_kw("VALUES")
        rows = [self._parse_paren_exprs()]
        while self.accept_op(","):
            rows.append(self._parse_paren_exprs())
        width = len(rows[0])
        if any(len(r) != width for r in rows):
            raise errors.syntax("VALUES lists must all be the same length")
        items = [ast.SelectItem(ast.ColumnRef([f"col{k}"])) for k in range(width)]
        sel = ast.Select(items)
        sel.values_rows = rows  # type: ignore[attr-defined]
        return sel

    def _parse_paren_exprs(self) -> list[ast.Expr]:
        self.expect_op("(")
        exprs = [self.parse_expr()]
        while self.accept_op(","):
            exprs.append(self.parse_expr())
        self.expect_op(")")
        return exprs

    def parse_select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.next()
            return ast.SelectItem(ast.Star())
        start = self.i
        expr = self.parse_expr()
        # tbl.* comes back as ColumnRef with trailing '*' handled in primary
        alias = None
        if self.accept_kw("AS"):
            alias = self.ident()
        elif self.peek().kind is T.IDENT and \
                self.peek().value.upper() not in _KEYWORDS_STOP_ALIAS:
            alias = self.ident()
        del start
        return ast.SelectItem(expr, alias)

    def parse_order_item(self) -> ast.OrderItem:
        e = self.parse_expr()
        desc = False
        if self.accept_kw("DESC"):
            desc = True
        else:
            self.accept_kw("ASC")
        nulls_first = None
        if self.accept_kw("NULLS"):
            if self.accept_kw("FIRST"):
                nulls_first = True
            else:
                self.expect_kw("LAST")
                nulls_first = False
        return ast.OrderItem(e, desc, nulls_first)

    def _parse_like_escape(self):
        if self.accept_kw("ESCAPE"):
            t = self.next()
            # ESCAPE '' is valid PG: it DISABLES escaping
            if t.kind is not T.STRING or len(t.value) > 1:
                raise errors.syntax("ESCAPE must be a single character")
            return t.value
        return None

    def parse_from(self) -> ast.TableRef:
        ref = self.parse_table_ref()
        while True:
            if self.accept_op(","):
                right = self.parse_table_ref()
                ref = ast.JoinRef("cross", ref, right)
                continue
            kind = None
            natural = False
            if self.accept_kw("CROSS"):
                self.expect_kw("JOIN")
                ref = ast.JoinRef("cross", ref, self.parse_table_ref())
                continue
            if self.accept_kw("NATURAL"):
                # NATURAL [INNER|LEFT|RIGHT|FULL [OUTER]] JOIN: USING
                # over the shared column names, resolved at bind time
                natural = True
            if self.accept_kw("INNER"):
                kind = "inner"
                self.expect_kw("JOIN")
            elif self.accept_kw("LEFT"):
                kind = "left"
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
            elif self.accept_kw("RIGHT"):
                kind = "right"
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
            elif self.accept_kw("FULL"):
                kind = "full"
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
            elif self.accept_kw("JOIN"):
                kind = "inner"
            else:
                if natural:
                    raise errors.syntax("expected JOIN after NATURAL")
                break
            right = self.parse_table_ref()
            if natural:
                ref = ast.JoinRef(kind, ref, right, using=["*natural*"])
                continue
            if self.accept_kw("ON"):
                cond = self.parse_expr()
                ref = ast.JoinRef(kind, ref, right, condition=cond)
            elif self.accept_kw("USING"):
                self.expect_op("(")
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                ref = ast.JoinRef(kind, ref, right, using=cols)
            else:
                raise errors.syntax("JOIN requires ON or USING")
        return ref

    def parse_table_ref(self) -> ast.TableRef:
        if self.accept_op("("):
            inner = self.parse_select()
            self.expect_op(")")
            alias = self._table_alias()
            cols = None
            if alias is not None and self.at_op("("):
                # FROM (VALUES …) v(a, b) — column aliases (PG)
                self.next()
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            return ast.SubqueryRef(inner, alias, cols)
        parts = [self.ident()]
        while self.accept_op("."):
            parts.append(self.ident())
        if self.at_op("("):
            self.next()
            args = []
            if not self.at_op(")"):
                args.append(self.parse_expr())
                while self.accept_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            alias = self._table_alias()
            col_aliases = None
            if alias is not None and self.accept_op("("):
                col_aliases = [self.ident()]
                while self.accept_op(","):
                    col_aliases.append(self.ident())
                self.expect_op(")")
            return ast.TableFunction(".".join(parts).lower(), args, alias,
                                     col_aliases)
        alias = self._table_alias()
        return ast.NamedTable(parts, alias)

    def _table_alias(self) -> Optional[str]:
        if self.accept_kw("AS"):
            return self.ident()
        if self.peek().kind is T.IDENT and \
                self.peek().value.upper() not in _KEYWORDS_STOP_ALIAS:
            return self.ident()
        return None

    # -- expressions (precedence climbing) ---------------------------------

    def parse_expr(self) -> ast.Expr:
        return self.parse_or()

    def parse_or(self) -> ast.Expr:
        left = self.parse_and()
        if not self.at_kw("OR"):
            return left
        args = [left]
        while self.accept_kw("OR"):
            args.append(self.parse_and())
        return ast.Logical("OR", args)

    def parse_and(self) -> ast.Expr:
        left = self.parse_not()
        if not self.at_kw("AND"):
            return left
        args = [left]
        while self.accept_kw("AND"):
            args.append(self.parse_not())
        return ast.Logical("AND", args)

    def parse_not(self) -> ast.Expr:
        if self.accept_kw("NOT"):
            return ast.UnaryOp("NOT", self.parse_not())
        return self.parse_predicate()

    #: PG "any other operator" precedence level: below + - , above the
    #: comparisons (gram.y); desugared to functions at parse time
    _OTHER_OPS = {"&": "bitand", "|": "bitor", "#": "bitxor",
                  "<<": "bitshiftleft", ">>": "bitshiftright"}

    def parse_other_ops(self) -> ast.Expr:
        left = self.parse_additive_chain()
        while self.peek().kind is T.OP and \
                self.peek().value in self._OTHER_OPS:
            fn = self._OTHER_OPS[self.next().value]
            left = ast.FuncCall(fn, [left, self.parse_additive_chain()])
        return left

    def parse_predicate(self) -> ast.Expr:
        left = self.parse_other_ops()
        while True:
            if self.accept_kw("IS"):
                negated = bool(self.accept_kw("NOT"))
                if self.accept_kw("NULL"):
                    left = ast.IsNull(left, negated)
                elif self.accept_kw("TRUE"):
                    # IS [NOT] TRUE is null-safe (PG): NULL IS NOT TRUE
                    # is true, not NULL — spell it with DISTINCT FROM
                    left = ast.FuncCall(
                        "is_distinct_from" if negated
                        else "is_not_distinct_from",
                        [left, ast.Literal(True)])
                elif self.accept_kw("FALSE"):
                    left = ast.FuncCall(
                        "is_distinct_from" if negated
                        else "is_not_distinct_from",
                        [left, ast.Literal(False)])
                elif self.accept_kw("UNKNOWN"):
                    # IS [NOT] UNKNOWN == IS [NOT] NULL over a boolean
                    left = ast.IsNull(left, negated)
                elif self.accept_kw("DISTINCT"):
                    self.expect_kw("FROM")
                    right = self.parse_additive_chain()
                    left = ast.FuncCall(
                        "is_not_distinct_from" if negated else "is_distinct_from",
                        [left, right])
                else:
                    raise errors.syntax("expected NULL after IS")
                continue
            negated = False
            save = self.i
            if self.accept_kw("NOT"):
                negated = True
            if self.accept_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH", "VALUES"):
                    sub = self.parse_select()
                    self.expect_op(")")
                    left = ast.InSubquery(left, sub, negated)
                    continue
                items = [self.parse_expr()]
                while self.accept_op(","):
                    items.append(self.parse_expr())
                self.expect_op(")")
                left = ast.InList(left, items, negated)
                continue
            if self.accept_kw("BETWEEN"):
                low = self.parse_additive_chain()
                self.expect_kw("AND")
                high = self.parse_additive_chain()
                left = ast.Between(left, low, high, negated)
                continue
            if self.accept_kw("LIKE"):
                left = ast.Like(left, self.parse_additive_chain(),
                                negated, False,
                                escape=self._parse_like_escape())
                continue
            if self.accept_kw("ILIKE"):
                left = ast.Like(left, self.parse_additive_chain(),
                                negated, True,
                                escape=self._parse_like_escape())
                continue
            if self.at_kw("SIMILAR") and \
                    self.peek(1).kind is T.IDENT and \
                    self.peek(1).value.upper() == "TO":
                self.next()
                self.next()
                e = ast.FuncCall("__similar_to",
                                 [left, self.parse_additive_chain()])
                left = ast.UnaryOp("NOT", e) if negated else e
                continue
            if negated:
                self.i = save
                break
            t = self.peek()
            op = None
            if t.kind is T.IDENT and t.value.upper() == "OPERATOR" and \
                    self.peek(1).kind is T.OP and self.peek(1).value == "(":
                # psql spells operators as OPERATOR(pg_catalog.~)
                self.next()
                self.next()
                while self.peek().kind is T.IDENT:
                    self.ident()
                    self.expect_op(".")
                opt = self.next()
                if opt.kind is not T.OP or opt.value == ")":
                    raise errors.syntax("expected operator in OPERATOR()")
                op = opt.value
                self.expect_op(")")
                if op not in _COMPARE_OPS:
                    raise errors.unsupported(f"OPERATOR({op})")
            elif t.kind is T.OP and t.value in _COMPARE_OPS:
                op = t.value
                self.next()
            if op is None:
                break
            if self.at_kw("ANY", "SOME", "ALL"):
                quant = self.next().value.upper()
                quant = "ANY" if quant == "SOME" else quant
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH", "VALUES"):
                    sub = self.parse_select()
                    self.expect_op(")")
                    if quant == "ANY" and op == "=":
                        left = ast.InSubquery(left, sub, False)
                    elif quant == "ALL" and op in ("<>", "!="):
                        left = ast.InSubquery(left, sub, True)
                    else:
                        # general op ANY/ALL (subquery): gather the
                        # subquery column and fold with the same
                        # three-valued __quant_cmp as the array form
                        left = ast.FuncCall(
                            "__quant_cmp",
                            [ast.Literal(op), ast.Literal(quant), left,
                             ast.ArraySubquery(sub)])
                    continue
                arr = self.parse_expr()
                self.expect_op(")")
                left = ast.FuncCall("__quant_cmp",
                                    [ast.Literal(op), ast.Literal(quant),
                                     left, arr])
                continue
            right = self.parse_other_ops()
            left = ast.BinaryOp(op, left, right)
            continue
        return left

    #: PG json/containment operators desugared to functions at parse time
    #: (reference: DuckDB fork maps -> / ->> onto json_extract family)
    _JSON_OPS = {"->": "json_getelem", "->>": "json_getelem_text",
                 "#>": "json_getpath", "#>>": "json_getpath_text",
                 "@>": "contains_op", "<@": "contained_op",
                 "?": "json_exists_op", "?|": "json_exists_any",
                 "?&": "json_exists_all"}

    def parse_additive_chain(self) -> ast.Expr:
        left = self.parse_multiplicative()
        while True:
            if self.at_op("+") or self.at_op("-") or self.at_op("||"):
                op = self.next().value
                left = ast.BinaryOp(op, left, self.parse_multiplicative())
            elif self.peek().kind is T.OP and \
                    self.peek().value in self._JSON_OPS:
                fn = self._JSON_OPS[self.next().value]
                left = ast.FuncCall(fn, [left, self.parse_multiplicative()])
            else:
                return left

    def parse_multiplicative(self) -> ast.Expr:
        left = self.parse_unary()
        while True:
            if self.at_op("*") or self.at_op("/") or self.at_op("%"):
                op = self.next().value
                left = ast.BinaryOp(op, left, self.parse_unary())
            else:
                return left

    def parse_unary(self) -> ast.Expr:
        # PG precedence: unary minus binds TIGHTER than ^ (gram.y UMINUS),
        # so -2^2 = (-2)^2 = 4; the ^ loop therefore sits ABOVE the unary
        # parser and below * (parse_multiplicative calls parse_unary)
        left = self._parse_signed()
        while self.at_op("^"):
            self.next()
            right = self._parse_signed()
            left = ast.FuncCall("power", [left, right])
        return left

    def _parse_signed(self) -> ast.Expr:
        if self.accept_op("-"):
            return ast.UnaryOp("-", self._parse_signed())
        if self.accept_op("+"):
            return self._parse_signed()
        # PG prefix operators: ~ bitwise not, |/ sqrt, ||/ cbrt, @ abs
        if self.accept_op("~"):
            return ast.FuncCall("bitnot", [self._parse_signed()])
        if self.accept_op("|/"):
            return ast.FuncCall("sqrt", [self._parse_signed()])
        if self.accept_op("||/"):
            return ast.FuncCall("cbrt", [self._parse_signed()])
        if self.accept_op("@"):
            return ast.FuncCall("abs", [self._parse_signed()])
        return self.parse_postfix()

    def parse_postfix(self) -> ast.Expr:
        e = self.parse_primary()
        while True:
            if self.accept_op("::"):
                e = ast.Cast(e, self._type_name())
            elif self.at_kw("COLLATE"):
                # COLLATE pg_catalog.default etc. — single collation, no-op
                self.next()
                self.ident()
                while self.accept_op("."):
                    self.ident()
            elif self.accept_op("["):
                # arr[i] — 1-based element access, desugared to a function
                idx = self.parse_expr()
                self.expect_op("]")
                e = ast.FuncCall("array_get", [e, idx])
            else:
                return e

    def _type_name(self) -> str:
        name = self.ident()
        # psql qualifies pseudo-types: ::pg_catalog.regclass
        while self.at_op(".") and name.upper() in ("PG_CATALOG",
                                                   "INFORMATION_SCHEMA"):
            self.next()
            name = self.ident()
        if name.upper() == "DOUBLE" and self.at_kw("PRECISION"):
            self.next()
            name = "DOUBLE"
        if name.upper() == "TIMESTAMP" and self.at_kw("WITHOUT", "WITH"):
            # TIMESTAMP WITH[OUT] TIME ZONE — single timestamp type
            self.next()
            self.expect_kw("TIME")
            self.expect_kw("ZONE")
        if self.accept_op("("):  # VARCHAR(n), DECIMAL(p,s) — swallow params
            while not self.at_op(")"):
                self.next()
            self.expect_op(")")
        if self.at_op("["):      # INT[] array type
            self.next()
            self.expect_op("]")
            name = name + "[]"
        return name

    def parse_primary(self) -> ast.Expr:
        t = self.peek()
        if t.kind is T.NUMBER:
            self.next()
            text = t.value
            if "." in text or "e" in text or "E" in text:
                return ast.Literal(float(text))
            v = int(text)
            return ast.Literal(v)
        if t.kind is T.STRING:
            self.next()
            return ast.Literal(t.value)
        if t.kind is T.PARAM:
            self.next()
            return ast.Param(int(t.value))
        if self.accept_op("("):
            if self.at_kw("SELECT", "WITH"):
                inner = self.parse_select()
                self.expect_op(")")
                return ast.Subquery(inner)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind is not T.IDENT:
            raise errors.syntax(f"unexpected token {t.value!r}")
        upper = t.value.upper()
        if upper == "NULL":
            self.next()
            return ast.Literal(None)
        if upper == "TRUE":
            self.next()
            return ast.Literal(True)
        if upper == "FALSE":
            self.next()
            return ast.Literal(False)
        if upper == "CASE":
            return self.parse_case()
        if upper in ("CURRENT_USER", "SESSION_USER", "CURRENT_ROLE",
                     "CURRENT_CATALOG", "CURRENT_SCHEMA", "CURRENT_DATE",
                     "CURRENT_TIMESTAMP", "LOCALTIMESTAMP") and not (
                self.peek(1).kind is T.OP and self.peek(1).value == "("):
            # PG reserved niladic functions: bare keyword, no parens
            self.next()
            fname = {"CURRENT_ROLE": "current_user",
                     "LOCALTIMESTAMP": "current_timestamp"}.get(
                upper, upper.lower())
            return ast.FuncCall(fname, [])
        if upper == "ARRAY" and self.peek(1).kind is T.OP and \
                self.peek(1).value == "(":
            # ARRAY(subquery): first output column gathered into an array
            self.next()
            self.expect_op("(")
            sub = self.parse_select()
            self.expect_op(")")
            return ast.ArraySubquery(sub)
        if upper == "ARRAY" and self.peek(1).kind is T.OP and \
                self.peek(1).value == "[":
            self.next()
            self.expect_op("[")
            items = []
            if not self.at_op("]"):
                items.append(self.parse_expr())
                while self.accept_op(","):
                    items.append(self.parse_expr())
            self.expect_op("]")
            # array-ness is syntactic, not sniffed from values: elements
            # that are themselves array-producing expressions splice as
            # nested arrays; plain strings never do
            array_funcs = {"make_array", "__make_array", "array_append", "array_cat",
                           "array_agg", "string_to_array"}
            splice = [i for i, it in enumerate(items)
                      if isinstance(it, ast.FuncCall)
                      and it.name.lower() in array_funcs]
            return ast.FuncCall("__make_array",
                                [ast.Literal(",".join(map(str, splice)))]
                                + items)
        if upper == "EXISTS" and self.peek(1).kind is T.OP and \
                self.peek(1).value == "(":
            self.next()
            self.expect_op("(")
            sub = self.parse_select()
            self.expect_op(")")
            return ast.Exists(sub)
        if upper == "CAST":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            tn = self._type_name()
            self.expect_op(")")
            return ast.Cast(e, tn)
        if upper == "EXTRACT":
            self.next()
            self.expect_op("(")
            t_fld = self.peek()
            fld = self.next().value if t_fld.kind in (T.IDENT, T.STRING) \
                else self.ident()
            self.expect_kw("FROM")
            e = self.parse_expr()
            self.expect_op(")")
            return ast.FuncCall("extract", [ast.Literal(fld.lower()), e])
        if upper == "INTERVAL":
            self.next()
            lit = self.next()
            if lit.kind is not T.STRING:
                raise errors.syntax("INTERVAL requires a string literal")
            return ast.Cast(ast.Literal(lit.value), "INTERVAL")
        if upper == "POSITION" and self.peek(1).kind is T.OP and \
                self.peek(1).value == "(":
            # PG: position(substr IN str) = strpos(str, substr)
            self.next()
            self.expect_op("(")
            sub = self.parse_additive_chain()
            if self.accept_kw("IN"):
                s = self.parse_expr()
                self.expect_op(")")
                return ast.FuncCall("strpos", [s, sub])
            args = [sub]
            while self.accept_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
            return ast.FuncCall("position", args)
        if upper == "TRIM" and self.peek(1).kind is T.OP and \
                self.peek(1).value == "(":
            # PG: trim([LEADING|TRAILING|BOTH] [chars] FROM str)
            #     also trim(str) / trim(str, chars)
            save = self.i
            self.next()
            self.expect_op("(")
            side = "both"
            if self.at_kw("LEADING", "TRAILING", "BOTH"):
                side = self.next().value.lower()
            if self.accept_kw("FROM"):      # trim(LEADING FROM s)
                s = self.parse_expr()
                self.expect_op(")")
                fn = {"leading": "ltrim", "trailing": "rtrim",
                      "both": "btrim"}[side]
                return ast.FuncCall(fn, [s])
            first = self.parse_expr()
            if self.accept_kw("FROM"):
                s = self.parse_expr()
                self.expect_op(")")
                fn = {"leading": "ltrim", "trailing": "rtrim",
                      "both": "btrim"}[side]
                return ast.FuncCall(fn, [s, first])
            if side != "both":
                raise errors.syntax("expected FROM in trim()")
            # plain call form: rewind and let the generic path handle it
            self.i = save
        if upper == "SUBSTRING" and self.peek(1).kind is T.OP and \
                self.peek(1).value == "(":
            # PG: substring(str FROM n [FOR k]) — also plain (s, n[, k])
            self.next()
            self.expect_op("(")
            s = self.parse_expr()
            if self.at_kw("FROM") or self.at_kw("FOR"):
                from_kw = bool(self.accept_kw("FROM"))
                if not from_kw:
                    self.expect_kw("FOR")
                first = self.parse_expr()
                if from_kw:
                    args = [s, first]
                    if self.accept_kw("FOR"):
                        args.append(self.parse_expr())
                else:  # substring(s FOR k) = substr(s, 1, k)
                    args = [s, ast.Literal(1), first]
                self.expect_op(")")
                return ast.FuncCall("substr", args)
            args = [s]
            while self.accept_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
            return ast.FuncCall("substr", args)
        if upper == "OVERLAY" and self.peek(1).kind is T.OP and \
                self.peek(1).value == "(":
            # PG: overlay(str PLACING repl FROM n [FOR k])
            save = self.i
            self.next()
            self.expect_op("(")
            s = self.parse_expr()
            if self.accept_kw("PLACING"):
                repl = self.parse_expr()
                self.expect_kw("FROM")
                start = self.parse_expr()
                args = [s, repl, start]
                if self.accept_kw("FOR"):
                    args.append(self.parse_expr())
                self.expect_op(")")
                return ast.FuncCall("overlay", args)
            self.i = save   # plain overlay(a, b, c[, d]) call form
        if upper in ("DATE", "TIMESTAMP") and self.peek(1).kind is T.STRING:
            self.next()
            lit = self.next()
            return ast.Cast(ast.Literal(lit.value), upper)
        # identifier: column ref or function call
        parts = [self.ident()]
        while self.accept_op("."):
            if self.at_op("*"):
                self.next()
                return ast.Star(table=parts[-1])
            parts.append(self.ident())
        if self.at_op("("):
            self.next()
            if len(parts) > 1 and parts[0].lower() in ("pg_catalog",
                                                       "information_schema"):
                parts = parts[1:]
            name = ".".join(parts).lower()
            distinct = False
            star = False
            args: list[ast.Expr] = []
            if self.at_op("*"):
                self.next()
                star = True
            elif not self.at_op(")"):
                if self.accept_kw("DISTINCT"):
                    distinct = True
                args.append(self.parse_expr())
                while self.accept_op(","):
                    args.append(self.parse_expr())
            agg_order = None
            if self.accept_kw("ORDER"):
                # ordered-set aggregates: string_agg(x, s ORDER BY k)
                self.expect_kw("BY")
                agg_order = [self.parse_order_item()]
                while self.accept_op(","):
                    agg_order.append(self.parse_order_item())
            self.expect_op(")")
            call = ast.FuncCall(name, args, distinct, star,
                                agg_order=agg_order)
            if self.at_kw("FILTER"):
                self.next()
                self.expect_op("(")
                self.expect_kw("WHERE")
                call.filter = self.parse_expr()
                self.expect_op(")")
            if self.at_kw("OVER"):
                if call.filter is not None:
                    raise errors.unsupported("FILTER with window functions")
                if call.agg_order:
                    raise errors.unsupported(
                        "ORDER BY inside a window function call")
                self.next()
                self.expect_op("(")
                partition = []
                order = []
                if self.accept_kw("PARTITION"):
                    self.expect_kw("BY")
                    partition.append(self.parse_expr())
                    while self.accept_op(","):
                        partition.append(self.parse_expr())
                if self.accept_kw("ORDER"):
                    self.expect_kw("BY")
                    order.append(self.parse_order_item())
                    while self.accept_op(","):
                        order.append(self.parse_order_item())
                frame = None
                if self.at_kw("ROWS", "RANGE", "GROUPS"):
                    frame = self.parse_window_frame()
                self.expect_op(")")
                return ast.WindowFunc(call, partition, order, frame)
            return call
        return ast.ColumnRef(parts)

    def parse_window_frame(self):
        """ROWS frames: (start_off, end_off) offsets, None = unbounded.
        RANGE is accepted only in its default-frame spellings; GROUPS is
        unsupported (PG parity: ROWS covers the reference workloads)."""
        mode = self.ident().upper()
        if mode == "GROUPS":
            raise errors.unsupported("GROUPS window frames")

        def bound(is_end: bool):
            if self.accept_kw("UNBOUNDED"):
                if self.accept_kw("PRECEDING"):
                    return None, "preceding"
                self.expect_kw("FOLLOWING")
                return None, "following"
            if self.accept_kw("CURRENT"):
                self.expect_kw("ROW")
                return 0, "current"
            t = self.peek()
            if t.kind is not T.NUMBER:
                raise errors.syntax("expected frame bound")
            nv = self.next().value
            if self.accept_kw("PRECEDING"):
                return -int(nv), "preceding"
            self.expect_kw("FOLLOWING")
            return int(nv), "following"

        if self.accept_kw("BETWEEN"):
            s_off, s_kind = bound(False)
            self.expect_kw("AND")
            e_off, e_kind = bound(True)
        else:
            s_off, s_kind = bound(False)
            e_off, e_kind = 0, "current"
        if s_kind == "following" and s_off is None:
            raise errors.syntax(
                "frame start cannot be UNBOUNDED FOLLOWING")
        if e_kind == "preceding" and e_off is None:
            raise errors.syntax(
                "frame end cannot be UNBOUNDED PRECEDING")
        # PG 42P20: the frame start may not lie after the frame end
        if s_kind == "current" and e_kind == "preceding":
            raise SqlError("42P20", "frame starting from current row "
                                    "cannot have preceding rows")
        if s_kind == "following" and e_kind in ("current", "preceding"):
            raise SqlError("42P20", "frame starting from following row "
                                    "cannot have preceding rows")
        if s_off is not None and e_off is not None and s_off > e_off:
            raise SqlError("42P20", "frame start cannot be after "
                                    "frame end")
        if mode == "RANGE":
            # only the default-frame spellings of RANGE are supported
            if (s_off, e_off) == (None, 0) and s_kind == "preceding":
                return None
            raise errors.unsupported(
                "RANGE window frames (use ROWS)")
        return (s_off, e_off)

    def parse_case(self) -> ast.Expr:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.accept_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            branches.append((cond, self.parse_expr()))
        else_ = self.parse_expr() if self.accept_kw("ELSE") else None
        self.expect_kw("END")
        return ast.Case(operand, branches, else_)

    # -- DDL/DML -----------------------------------------------------------

    def qualified_name(self) -> list[str]:
        parts = [self.ident()]
        while self.accept_op("."):
            parts.append(self.ident())
        return parts

    def parse_create(self) -> ast.Statement:
        self.expect_kw("CREATE")
        or_replace = False
        if self.accept_kw("OR"):
            self.expect_kw("REPLACE")
            or_replace = True
        if self.accept_kw("SCHEMA"):
            ine = self._if_not_exists()
            return ast.CreateSchema(self.ident(), ine)
        if self.accept_kw("VIEW"):
            name = self.qualified_name()
            self.expect_kw("AS")
            body_pos = self.peek().pos   # token-accurate body start —
            # quoted identifiers containing ' as ' can't fool this
            st = ast.CreateView(name, self.parse_select(), or_replace)
            st.body_pos = body_pos
            return st
        if self.accept_kw("INDEX"):
            ine = self._if_not_exists()
            idx_name = None
            if not self.at_kw("ON"):
                idx_name = self.ident()
            self.expect_kw("ON")
            table = self.qualified_name()
            using = None   # default resolved by column type at exec
            if self.accept_kw("USING"):
                using = self.ident().lower()
            self.expect_op("(")
            cols = []
            col_toks: dict = {}
            while True:
                col = self.ident()
                cols.append(col)
                # optional per-column tokenizer/dictionary name — inverted
                # indexes only (reference: USING inverted(text imdb_en));
                # ASC/DESC stay syntax errors for other index types
                if self.peek().kind is T.IDENT and not self.at_op(","):
                    if using is not None and using != "inverted":
                        raise errors.syntax(
                            f"unexpected {self.peek().value!r} in index "
                            "column list")
                    col_toks[col] = self.ident()
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            opts = self._with_options()
            return ast.CreateIndex(idx_name, table, cols, using, ine, opts,
                                   col_toks)
        if self.at_kw("TEXT"):
            # CREATE TEXT SEARCH DICTIONARY name (key = value, ...)
            self.next()
            self.expect_kw("SEARCH")
            self.expect_kw("DICTIONARY")
            ine = self._if_not_exists()
            name = self.ident()
            opts: dict = {}
            if self.accept_op("("):
                while True:
                    key = self.ident().lower()
                    self.expect_op("=")
                    t = self.next()
                    if t.kind is T.NUMBER:
                        opts[key] = float(t.value) if "." in t.value \
                            else int(t.value)
                    elif t.kind is T.IDENT and t.value.upper() in \
                            ("TRUE", "FALSE"):
                        opts[key] = t.value.upper() == "TRUE"
                    else:
                        opts[key] = t.value
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            return ast.CreateTsDictionary(name, opts, ine)
        if self.accept_kw("ROLE") or self.accept_kw("USER"):
            ine = self._if_not_exists()
            name = self.ident()
            password = None
            login = True
            superuser = False
            while True:
                if self.accept_kw("PASSWORD"):
                    t = self.next()
                    password = t.value
                elif self.accept_kw("LOGIN"):
                    login = True
                elif self.accept_kw("NOLOGIN"):
                    login = False
                elif self.accept_kw("SUPERUSER"):
                    superuser = True
                elif self.accept_kw("WITH"):
                    continue
                else:
                    break
            return ast.CreateRole(name, password, login, superuser, ine)
        if self.accept_kw("TYPE"):
            ine = self._if_not_exists()
            name = self.ident()
            self.expect_kw("AS")
            self.expect_kw("ENUM")
            self.expect_op("(")
            labels = []
            if not self.at_op(")"):
                t = self.next()
                if t.kind is not T.STRING:
                    raise errors.syntax("enum labels must be string literals")
                labels.append(t.value)
                while self.accept_op(","):
                    t = self.next()
                    if t.kind is not T.STRING:
                        raise errors.syntax(
                            "enum labels must be string literals")
                    labels.append(t.value)
            self.expect_op(")")
            return ast.CreateType(name, "enum", labels, None, ine)
        if self.accept_kw("DOMAIN"):
            ine = self._if_not_exists()
            name = self.ident()
            self.expect_kw("AS")
            base = self._type_name()
            return ast.CreateType(name, "domain", [], base, ine)
        if self.accept_kw("SEQUENCE"):
            ine = self._if_not_exists()
            name = self.qualified_name()
            start = 1
            increment = 1
            while self.peek().kind is T.IDENT and \
                    self.peek().value.upper() in ("START", "INCREMENT"):
                word = self.ident().upper()
                self.accept_kw("WITH") or self.accept_kw("BY")
                sign = -1 if self.accept_op("-") else 1
                t = self.next()
                if t.kind is not T.NUMBER:
                    raise errors.syntax("expected number in SEQUENCE options")
                if word == "START":
                    start = sign * int(t.value)
                else:
                    increment = sign * int(t.value)
            return ast.CreateSequence(name, start, increment, ine)
        self.expect_kw("TABLE")
        ine = self._if_not_exists()
        name = self.qualified_name()
        if self.at_kw("AS") or (self.at_kw("USING", "WITH") and False):
            pass
        columns: list[ast.ColumnDef] = []
        pk: list[str] = []
        if self.accept_op("("):
            while True:
                if self.accept_kw("PRIMARY"):
                    self.expect_kw("KEY")
                    self.expect_op("(")
                    pk = [self.ident()]
                    while self.accept_op(","):
                        pk.append(self.ident())
                    self.expect_op(")")
                else:
                    columns.append(self._column_def())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        engine = "columnar"
        if self.accept_kw("USING"):
            engine = self.ident().lower()
        opts = self._with_options()
        if "engine" in opts:
            engine = str(opts.pop("engine")).lower()
        as_query = None
        if self.accept_kw("AS"):
            as_query = self.parse_select()
        pk = pk or [c.name for c in columns if c.primary_key]
        return ast.CreateTable(name, columns, engine, ine, opts, as_query, pk)

    def _column_def(self) -> ast.ColumnDef:
        name = self.ident()
        type_name = self._type_name()
        d = ast.ColumnDef(name, type_name)
        while True:
            if self.accept_kw("NOT"):
                self.expect_kw("NULL")
                d.not_null = True
            elif self.accept_kw("NULL"):
                pass
            elif self.accept_kw("PRIMARY"):
                self.expect_kw("KEY")
                d.primary_key = True
                d.not_null = True
            elif self.accept_kw("DEFAULT"):
                d.default = self.parse_expr()
            elif self.accept_kw("TOKENIZER"):  # search-table column analyzer
                d.tokenizer = self.next().value
            else:
                break
        return d

    def _if_not_exists(self) -> bool:
        if self.accept_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def _with_options(self) -> dict:
        opts: dict = {}
        if self.accept_kw("WITH"):
            self.expect_op("(")
            while True:
                key = self.ident().lower()
                self.expect_op("=")
                t = self.next()
                if t.kind is T.NUMBER:
                    opts[key] = float(t.value) if "." in t.value else int(t.value)
                else:
                    opts[key] = t.value
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return opts

    def parse_drop(self) -> ast.Drop:
        self.expect_kw("DROP")
        if self.accept_kw("TABLE"):
            kind = "table"
        elif self.accept_kw("INDEX"):
            kind = "index"
        elif self.accept_kw("SCHEMA"):
            kind = "schema"
        elif self.accept_kw("VIEW"):
            kind = "view"
        elif self.accept_kw("SEQUENCE"):
            kind = "sequence"
        elif self.accept_kw("TYPE") or self.accept_kw("DOMAIN"):
            kind = "type"
        elif self.accept_kw("ROLE") or self.accept_kw("USER"):
            if_exists = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                if_exists = True
            return ast.DropRole(self.ident(), if_exists)
        elif self.at_kw("TEXT"):
            self.next()
            self.expect_kw("SEARCH")
            self.expect_kw("DICTIONARY")
            if_exists = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                if_exists = True
            return ast.Drop("tsdictionary", [self.ident()], if_exists,
                            False)
        else:
            raise errors.unsupported("DROP of that object kind")
        if_exists = False
        if self.accept_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        name = self.qualified_name()
        cascade = bool(self.accept_kw("CASCADE"))
        self.accept_kw("RESTRICT")
        return ast.Drop(kind, name, if_exists, cascade)

    def parse_insert(self) -> ast.Insert:
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        table = self.qualified_name()
        columns = None
        if self.accept_op("("):
            columns = [self.ident()]
            while self.accept_op(","):
                columns.append(self.ident())
            self.expect_op(")")
        if self.at_kw("VALUES"):
            self.next()
            rows = [self._parse_insert_row()]
            while self.accept_op(","):
                rows.append(self._parse_insert_row())
            oc = self._parse_on_conflict()
            return ast.Insert(table, columns, rows,
                              returning=self._parse_returning(),
                              on_conflict=oc)
        if self.at_kw("SELECT"):
            q = self.parse_select()
            oc = self._parse_on_conflict()
            return ast.Insert(table, columns, None, q,
                              returning=self._parse_returning(),
                              on_conflict=oc)
        raise errors.syntax("expected VALUES or SELECT in INSERT")

    def _parse_insert_row(self) -> list[ast.Expr]:
        """A VALUES row where a bare DEFAULT element is allowed."""
        self.expect_op("(")
        exprs = []
        while True:
            if self.at_kw("DEFAULT"):
                self.next()
                exprs.append(ast.DefaultMarker())
            else:
                exprs.append(self.parse_expr())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return exprs

    def _parse_on_conflict(self) -> Optional[tuple]:
        if not self.at_kw("ON"):
            return None
        self.next()
        self.expect_kw("CONFLICT")
        target = []
        if self.accept_op("("):
            target.append(self.ident().lower())
            while self.accept_op(","):
                target.append(self.ident().lower())
            self.expect_op(")")
        self.expect_kw("DO")
        if self.accept_kw("NOTHING"):
            return ("nothing", target, [])
        self.expect_kw("UPDATE")
        self.expect_kw("SET")
        assigns = []
        while True:
            col = self.ident()
            self.expect_op("=")
            assigns.append((col, self.parse_expr()))
            if not self.accept_op(","):
                break
        return ("update", target, assigns)

    def _parse_returning(self) -> list:
        if not self.accept_kw("RETURNING"):
            return []
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())
        return items

    def parse_delete(self) -> ast.Delete:
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        table = self.qualified_name()
        using_ref = None
        if self.accept_kw("USING"):
            using_ref = self.parse_from()
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        return ast.Delete(table, where,
                          returning=self._parse_returning(),
                          using_ref=using_ref)

    def parse_update(self) -> ast.Update:
        self.expect_kw("UPDATE")
        table = self.qualified_name()
        self.expect_kw("SET")
        assigns = []
        while True:
            col = self.ident()
            self.expect_op("=")
            if self.at_kw("DEFAULT"):
                self.next()
                assigns.append((col, ast.DefaultMarker()))
            else:
                assigns.append((col, self.parse_expr()))
            if not self.accept_op(","):
                break
        from_ref = None
        if self.accept_kw("FROM"):
            from_ref = self.parse_from()
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        return ast.Update(table, assigns, where,
                          returning=self._parse_returning(),
                          from_ref=from_ref)

    def parse_set(self) -> ast.Statement:
        self.expect_kw("SET")
        self.accept_kw("SESSION") or self.accept_kw("LOCAL")
        if self.at_kw("ROLE"):
            self.next()
            if self.accept_kw("NONE"):
                return ast.SetRole(None)
            return ast.SetRole(self.ident())
        name = self.ident().lower()
        if not (self.accept_op("=") or self.accept_kw("TO")):
            raise errors.syntax("expected = or TO in SET")
        t = self.peek()
        if t.kind is T.IDENT and t.value.upper() == "DEFAULT":
            self.next()
            return ast.SetStmt(name, "DEFAULT")
        if t.kind is T.STRING:
            self.next()
            return ast.SetStmt(name, t.value)
        if t.kind is T.NUMBER:
            self.next()
            return ast.SetStmt(name, float(t.value) if "." in t.value else int(t.value))
        if t.kind is T.OP and t.value == "-":
            # negative numeric value (PG: SET log_min_duration... = -1)
            self.next()
            t2 = self.peek()
            if t2.kind is T.NUMBER:
                self.next()
                return ast.SetStmt(
                    name, -float(t2.value) if "." in t2.value
                    else -int(t2.value))
            raise errors.syntax("bad SET value")
        if t.kind is T.IDENT:
            self.next()
            v = t.value
            if v.upper() in ("ON", "TRUE"):
                return ast.SetStmt(name, True)
            if v.upper() in ("OFF", "FALSE"):
                return ast.SetStmt(name, False)
            return ast.SetStmt(name, v)
        raise errors.syntax("bad SET value")

    def parse_alter(self):
        self.expect_kw("ALTER")
        if self.accept_kw("ROLE") or self.accept_kw("USER"):
            name = self.ident()
            set_pw, password = False, None
            login = superuser = None
            n_opts = 0
            while True:
                n_opts += 1
                if self.accept_kw("PASSWORD"):
                    if set_pw:
                        raise errors.syntax(
                            "conflicting or redundant options")
                    set_pw = True
                    if self.accept_kw("NULL"):
                        password = None
                    else:
                        t = self.next()
                        if t.kind is not T.STRING:
                            raise errors.syntax(
                                "PASSWORD requires a string or NULL")
                        password = t.value
                elif self.accept_kw("LOGIN", "NOLOGIN"):
                    if login is not None:
                        raise errors.syntax(
                            "conflicting or redundant options")
                    login = self.toks[self.i - 1].value.upper() == "LOGIN"
                elif self.accept_kw("SUPERUSER", "NOSUPERUSER"):
                    if superuser is not None:
                        raise errors.syntax(
                            "conflicting or redundant options")
                    superuser = self.toks[self.i - 1].value.upper() == \
                        "SUPERUSER"
                elif n_opts == 1 and self.accept_kw("WITH"):
                    continue
                else:
                    n_opts -= 1
                    break
            if n_opts == 0:
                raise errors.syntax("ALTER ROLE requires at least one option")
            return ast.AlterRole(name, set_pw, password, login, superuser)
        self.expect_kw("TABLE")
        if_exists = False
        if self.accept_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        table = self.qualified_name()
        if self.accept_kw("ADD"):
            self.accept_kw("COLUMN")
            ine = self._if_not_exists()
            col = self.ident()
            tn = self._type_name()
            return ast.AlterTable(table, "add_column", col, tn,
                                  if_exists=if_exists, if_not_exists=ine)
        if self.accept_kw("DROP"):
            self.accept_kw("COLUMN")
            ife2 = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                ife2 = True
            col = self.ident()
            return ast.AlterTable(table, "drop_column", col,
                                  if_exists=if_exists, col_if_exists=ife2)
        if self.accept_kw("RENAME"):
            if self.accept_kw("COLUMN"):
                col = self.ident()
                self.expect_kw("TO")
                return ast.AlterTable(table, "rename_column", col,
                                      new_name=self.ident(),
                                      if_exists=if_exists)
            self.expect_kw("TO")
            return ast.AlterTable(table, "rename_table",
                                  new_name=self.ident(), if_exists=if_exists)
        raise errors.unsupported("that ALTER TABLE action")

    def parse_copy(self) -> ast.CopyStmt:
        self.expect_kw("COPY")
        query = None
        table: list[str] = []
        columns = None
        if self.at_op("("):
            # COPY ( query ) TO ... (PG: queries export, never import)
            self.accept_op("(")
            query = self.parse_select()
            self.expect_op(")")
        else:
            table = self.qualified_name()
            if self.accept_op("("):
                columns = [self.ident()]
                while self.accept_op(","):
                    columns.append(self.ident())
                self.expect_op(")")
        if self.accept_kw("FROM"):
            if query is not None:
                raise errors.syntax("COPY query is only allowed with TO")
            direction = "from"
        else:
            self.expect_kw("TO")
            direction = "to"
        t = self.peek()
        if t.kind is T.STRING:
            target = self.next().value
        elif self.accept_kw("STDIN"):
            target = "STDIN"
        elif self.accept_kw("STDOUT"):
            target = "STDOUT"
        else:
            raise errors.syntax("expected filename, STDIN or STDOUT")
        opts: dict = {}
        if self.accept_op("("):
            while True:
                key = self.ident().lower()
                if self.peek().kind in (T.IDENT, T.STRING, T.NUMBER):
                    opts[key] = self.next().value
                else:
                    opts[key] = True
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        elif self.accept_kw("WITH"):
            if self.accept_op("("):
                while True:
                    key = self.ident().lower()
                    if self.peek().kind in (T.IDENT, T.STRING, T.NUMBER):
                        opts[key] = self.next().value
                    else:
                        opts[key] = True
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
        return ast.CopyStmt(table, columns, direction, target, opts,
                            query=query)

    def parse_vacuum(self) -> ast.VacuumStmt:
        self.expect_kw("VACUUM")
        verbs = []
        while self.at_kw("REFRESH", "COMPACT", "CLEANUP", "FULL", "ANALYZE"):
            verbs.append(self.ident().lower())
        table = None
        if self.peek().kind is T.IDENT:
            table = self.qualified_name()
        return ast.VacuumStmt(table, verbs)


_PARSE_CACHE: dict = {}
_PARSE_CACHE_MAX = 512


def parse(sql: str) -> list[ast.Statement]:
    """Parse with a copy-on-read AST cache (the reference caches parse
    trees the same way: PEG parser cache, server_engine.cpp:310-314).
    Deep copies are handed out because the planner mutates ASTs."""
    import copy
    cached = _PARSE_CACHE.get(sql)
    if cached is None:
        cached = Parser(sql).parse_statements()
        if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
            _PARSE_CACHE.clear()
        _PARSE_CACHE[sql] = cached
    return copy.deepcopy(cached)


def parse_one(sql: str) -> ast.Statement:
    stmts = parse(sql)
    if len(stmts) != 1:
        raise errors.syntax("expected a single statement")
    return stmts[0]
