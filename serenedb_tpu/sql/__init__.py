from . import ast, binder, expr, lexer, parser, planner

__all__ = ["ast", "binder", "expr", "lexer", "parser", "planner"]
