"""Bound (typed) expressions with CPU evaluation.

The CPU path is the exactness/parity oracle (PG three-valued NULL logic,
sorted-dictionary string comparisons); exec/device.py compiles the numeric
subset of the same IR to jnp for the TPU path, and test parity between the
two is part of the test strategy (SURVEY.md §4: `any/` files must match PG).

Evaluation operates on columnar.Batch and returns columnar.Column.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column, _encode_dictionary


class BoundExpr:
    type: dt.SqlType

    def eval(self, batch: Batch) -> Column:
        raise NotImplementedError

    def children(self) -> list["BoundExpr"]:
        return []

    def walk(self):
        yield self
        for c in self.children():
            yield from c.walk()


@dataclass
class BoundLiteral(BoundExpr):
    value: Any
    type: dt.SqlType

    def eval(self, batch: Batch) -> Column:
        return Column.const(self.value, batch.num_rows, self.type)


@dataclass
class BoundColumn(BoundExpr):
    index: int
    type: dt.SqlType
    name: str

    def eval(self, batch: Batch) -> Column:
        return batch.columns[self.index]


@dataclass
class BoundFunc(BoundExpr):
    name: str
    args: list[BoundExpr]
    type: dt.SqlType
    fn: Callable  # (list[Column], Batch) -> Column

    def eval(self, batch: Batch) -> Column:
        return self.fn([a.eval(batch) for a in self.args], batch)

    def children(self):
        return self.args


@dataclass
class BoundCase(BoundExpr):
    branches: list[tuple[BoundExpr, BoundExpr]]
    else_: Optional[BoundExpr]
    type: dt.SqlType

    def eval(self, batch: Batch) -> Column:
        """Lazy, per-row-masked evaluation (PG semantics): a branch's
        condition runs only over still-undecided rows and its value only
        over the rows that branch selected, so errors in untaken branches
        never fire (CASE WHEN x <> 0 THEN y/x ... must not divide by the
        zeros)."""
        n = batch.num_rows
        decided = np.zeros(n, dtype=bool)
        result_vals: list = [None] * n
        for cond, val in self.branches:
            undecided = ~decided
            if not undecided.any():
                break
            all_rows = bool(undecided.all())
            sub = batch if all_rows else batch.filter(undecided)
            rows = np.flatnonzero(undecided)
            c = cond.eval(sub)
            hitl = c.valid_mask() & c.data.astype(bool)
            if hitl.any():
                hit_rows = rows[hitl]
                subhit = sub if hitl.all() else sub.filter(hitl)
                vals = val.eval(subhit).to_pylist()
                for j, i in enumerate(hit_rows):
                    result_vals[i] = vals[j]
                decided[hit_rows] = True
        if self.else_ is not None:
            rest = ~decided
            if rest.any():
                sub = batch if rest.all() else batch.filter(rest)
                vals = self.else_.eval(sub).to_pylist()
                for j, i in enumerate(np.flatnonzero(rest)):
                    result_vals[i] = vals[j]
        return Column.from_pylist(result_vals, self.type)

    def children(self):
        out = [c for b in self.branches for c in b]
        if self.else_ is not None:
            out.append(self.else_)
        return out


@dataclass
class BoundAggRef(BoundExpr):
    """Placeholder referencing the i-th aggregate result inside post-agg
    projections (HAVING / select exprs over aggregates)."""
    index: int
    type: dt.SqlType

    def eval(self, batch: Batch) -> Column:
        # post-aggregation batches carry agg results as columns named #agg{i}
        return batch.column(f"#agg{self.index}")


@dataclass
class AggSpec:
    """One aggregate computation: func over an argument expression."""
    func: str                      # count/sum/min/max/avg/count_star/...
    arg: Optional[BoundExpr]
    distinct: bool
    type: dt.SqlType
    sep: Optional[str] = None      # string_agg separator
    filter: Optional[BoundExpr] = None   # FILTER (WHERE ...) predicate
    order_by: Optional[list] = None      # [(BoundExpr, desc)] agg ORDER BY


# -- NULL-aware kernels used by the function library -----------------------

def kleene_and(cols: list[Column]) -> Column:
    """SQL three-valued AND: FALSE dominates NULL."""
    n = len(cols[0])
    any_false = np.zeros(n, dtype=bool)
    any_null = np.zeros(n, dtype=bool)
    for c in cols:
        v = c.data.astype(bool)
        cv = c.valid_mask()
        any_false |= cv & ~v
        any_null |= ~cv
    value = ~any_false
    valid = any_false | ~any_null
    return Column(dt.BOOL, value & valid, None if valid.all() else valid)


def kleene_or(cols: list[Column]) -> Column:
    """SQL three-valued OR: TRUE dominates NULL."""
    n = len(cols[0])
    any_true = np.zeros(n, dtype=bool)
    any_null = np.zeros(n, dtype=bool)
    for c in cols:
        v = c.data.astype(bool)
        cv = c.valid_mask()
        any_true |= cv & v
        any_null |= ~cv
    valid = any_true | ~any_null
    return Column(dt.BOOL, any_true, None if valid.all() else valid)


def propagate_nulls(cols: list[Column]) -> Optional[np.ndarray]:
    """Standard strict-function null propagation: NULL in → NULL out."""
    validity = None
    for c in cols:
        if c.validity is not None:
            validity = c.validity if validity is None else (validity & c.validity)
    return validity


def string_values(col: Column) -> np.ndarray:
    """Materialize VARCHAR column as numpy str array (CPU string ops)."""
    if col.dictionary is None:
        return col.data.astype(str)
    return col.dictionary.astype(str)[col.data]


def make_string_column(strs: np.ndarray, validity: Optional[np.ndarray]) -> Column:
    dictionary, codes = _encode_dictionary([str(s) for s in strs])
    return Column(dt.VARCHAR, codes, validity, dictionary)
