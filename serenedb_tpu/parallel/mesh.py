"""Device-mesh parallel execution of scans, aggregates, and scoring.

Reference analog: the reference's intra-node parallelism (morsel-driven
pipelines, parallel top-k collectors, parallel sinks — SURVEY.md §2.11) has
no cross-device component; on TPU the same roles map onto a
`jax.sharding.Mesh`: row blocks shard across devices ("data parallel" scan),
per-device partial aggregates combine with psum over ICI, and per-device
top-k merges via all_gather — XLA inserts the collectives.

The mesh axis is named "shard". Multi-host scaling uses the same programs
over a larger mesh (jax handles DCN vs ICI placement).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.device import LANES

AXIS = "shard"


def _mesh_key(mesh: "Mesh") -> tuple:
    """Compile-ledger key component for a mesh: its device ids (two
    meshes over the same devices trace to the same program).

    The step builders below key on the mesh (+ scalar params) only, not
    on input shapes — one ledger entry holds a SHAPE-POLYMORPHIC jit
    wrapper whose internal per-shape executables accumulate like the
    module-level @jax.jit kernels in ops/ (jit's own cache), and the
    retraces are invisible to the compile ledger. Acceptable for these
    test/bench/dryrun-facing builders (the engine's query-path programs
    all key on full shape signatures); evicting the wrapper still frees
    every shape variant at once."""
    return tuple(d.id for d in mesh.devices.flat)

#: process-wide cache of data-axis meshes by device count — Mesh
#: construction is cheap but identity-stable meshes keep shard_map
#: program caches (keyed on the jitted callable) from re-tracing
_MESH_CACHE: dict[int, Mesh] = {}


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    note_backend_initialized()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (AXIS,))


#: engine-owned "a device dispatch already initialized the backend"
#: flag, noted at the upload/mesh choke points — the drift-proof
#: fallback for device_count_if_initialized if a jax upgrade moves the
#: introspection API (without it, auto would silently read host
#: forever on a multi-chip box)
_BACKEND_NOTED = False


def note_backend_initialized() -> None:
    global _BACKEND_NOTED
    _BACKEND_NOTED = True


def device_count_if_initialized() -> int:
    """Number of jax devices IF a backend is already initialized in
    this process, else 0 — NEVER triggers backend initialization.
    Passive callers (the sharded search merge deciding whether a device
    combine is even worth it) must not be the ones to pay backend init:
    on a box whose device backend is a tunneled TPU, initialization
    during a tunnel outage is a hard hang, and a pure-host query path
    should stay jax-free. Probes xla_bridge.backends_are_initialized()
    (falling back to the engine-noted flag on jax-internal drift)."""
    if not _BACKEND_NOTED:
        try:
            from jax._src import xla_bridge
            if not xla_bridge.backends_are_initialized():
                return 0
        except Exception:  # noqa: BLE001 — private-API drift: trust
            return 0       # only the engine-noted flag (False here)
    return len(jax.devices())


def data_mesh(n_shards: int) -> Mesh:
    """THE data-axis mesh of the sharded execution tier's in-program
    combine (serene_shard_combine=device): one axis named `shard` over
    min(n_shards, device count) devices — shards beyond the device
    count stack on the leading axis and reduce locally before the
    psum/pmin/pmax hop. Cached per width so repeat queries reuse the
    identical Mesh object."""
    n = max(1, min(int(n_shards), len(jax.devices())))
    mesh = _MESH_CACHE.get(n)
    if mesh is None:
        mesh = _MESH_CACHE[n] = make_mesh(n)
    return mesh


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """NamedSharding splitting the LEADING axis over the mesh's shard
    axis (the stacked-shards layout): committed inputs land one shard
    group per device, so the collective dispatch never re-shuffles."""
    return NamedSharding(mesh, P(AXIS, *([None] * (ndim - 1))))


def apply_axis_combines(outs: tuple, kinds: list, fuse_sums: bool = False):
    """Cross-shard reduction of a program's per-device outputs over the
    mesh axis, by kind: 'sum' → psum (counts, int limb stacks, direct
    int sums), 'min'/'max' → pmin/pmax (selection partials), 'rows' →
    left sharded (per-row outputs the out_spec concatenates). Integer
    adds and min/max selections are exact in ANY reduction order, so
    the collective result is bit-identical to the host-side combine —
    the sharded tier's parity contract. Shared by the fused collective
    pipeline (exec/device_pipeline.py) and the mesh-wrapped device
    aggregate (exec/device_agg.py).

    `fuse_sums` batches every same-dtype/same-leading-dim 'sum' output
    into ONE psum (flatten trailing dims, concatenate, reduce, split):
    each all-reduce is a cross-device rendezvous, so N tiny psums cost
    N synchronizations where one fused psum costs one — element-wise
    identical either way (psum is independent per element)."""
    import jax.lax as lax
    import jax.numpy as jnp
    fused: dict[int, object] = {}
    if fuse_sums:
        sums = [(i, o) for i, (o, kind) in enumerate(zip(outs, kinds))
                if kind == "sum"]
        if len(sums) > 1 and len({o.dtype for _, o in sums}) == 1 and \
                len({o.shape[0] for _, o in sums}) == 1:
            flat = [o.reshape(o.shape[0], -1) for _, o in sums]
            red = lax.psum(jnp.concatenate(flat, axis=1), AXIS)
            at = 0
            for (i, o), f in zip(sums, flat):
                fused[i] = red[:, at:at + f.shape[1]].reshape(o.shape)
                at += f.shape[1]
    combined: list = []
    for i, (o, kind) in enumerate(zip(outs, kinds)):
        if i in fused:
            combined.append(fused[i])
        elif kind == "sum":
            combined.append(lax.psum(o, AXIS))
        elif kind == "min":
            combined.append(lax.pmin(o, AXIS))
        elif kind == "max":
            combined.append(lax.pmax(o, AXIS))
        else:                               # 'rows': stays sharded
            combined.append(o)
    return tuple(combined)


def shard_devices(n_shards: int) -> Optional[list]:
    """Data-axis placement for the sharded execution tier
    (exec/shard.py): shard s's per-shard program inputs commit to
    device s % n_devices along the mesh's shard axis, so concurrent
    shard dispatches land on distinct devices of the same mesh a
    shard_map program would span. None on a single-device host — the
    shards then share the default device and fan out as worker-pool
    tasks only."""
    devs = jax.devices()
    if len(devs) <= 1 or n_shards <= 1:
        return None
    return [devs[s % len(devs)] for s in range(n_shards)]


def pad_to_multiple(arr, n: int, fill=0):
    """Pad the leading axis to a multiple of n (THE shard-padding helper:
    data pads with `fill`, masks with False — padded rows never count).
    Works on numpy and jax arrays alike."""
    rows = arr.shape[0]
    pad = (-rows) % n
    if not pad:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    if isinstance(arr, np.ndarray):
        return np.pad(arr, widths, constant_values=fill)
    return jnp.pad(arr, widths, constant_values=fill)


def shard_rows(arr: np.ndarray, mesh: Mesh) -> np.ndarray:
    """Pad the leading (row-block) axis to a multiple of the mesh size."""
    return pad_to_multiple(arr, mesh.shape[AXIS])


def sharded_agg_step(mesh: Mesh):
    """Build a jitted sharded filter+aggregate step:
    (vals (R,128) i32, mask (R,128) bool, lo, hi) →
    (total count, per-row-block [hi16, lo16] int32 partial sums (R, 2)).

    Each 128-lane partial is exact in int32 (lo ≤ 128·65535, hi ≤ 128·2^15);
    the caller combines them on host as (Σhi << 16) + Σlo in int64 —
    device-side whole-shard int32 accumulation would wrap (int64 reductions
    are emulated on TPU, so the exact combine stays on host)."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), P(), P()),
        out_specs=(P(), P(AXIS, None)))
    def step(vals, mask, lo, hi):
        sel = jnp.logical_and(mask,
                              jnp.logical_and(vals >= lo, vals < hi))
        cnt = jnp.sum(sel, dtype=jnp.int32)
        v = jnp.where(sel, vals, 0).astype(jnp.int32)
        loh = (v & 0xFFFF).astype(jnp.int32)
        hih = jnp.right_shift(v, 16)
        partials = jnp.stack([jnp.sum(hih, axis=1, dtype=jnp.int32),
                              jnp.sum(loh, axis=1, dtype=jnp.int32)], axis=1)
        return jax.lax.psum(cnt, AXIS), partials

    from ..obs import device as obs_device
    return obs_device.compiled("mesh_agg", (_mesh_key(mesh),),
                               lambda: step)


def combine_agg_partials(partials: np.ndarray) -> int:
    """(R, 2) int32 [hi16, lo16] row partials → exact int64 total."""
    p = np.asarray(partials).astype(np.int64)
    return int((p[:, 0].sum() << 16) + p[:, 1].sum())


def sharded_bm25_topk(mesh: Mesh, ndocs_pad: int, k: int,
                      k1: float = 1.2, b: float = 0.75):
    """Build a jitted sharded BM25 top-k: posting blocks shard across
    devices; each scores its blocks into a local dense accumulator; psum
    merges accumulators (doc space is replicated), then one top-k."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(), P(AXIS, None), P(AXIS), P(), P()),
        out_specs=(P(), P()))
    def step(flat_docs, flat_tfs, norms, gidx, block_term, idf, avgdl):
        valid = gidx >= 0
        safe = jnp.where(valid, gidx, 0)
        docs = flat_docs[safe]
        tfs = flat_tfs[safe].astype(jnp.float32)
        dl = norms[docs].astype(jnp.float32)
        w = idf[block_term][:, None]
        denom = tfs + k1 * (1.0 - b + b * dl / jnp.maximum(avgdl, 1e-9))
        contrib = jnp.where(valid, w * (k1 + 1.0) * tfs /
                            jnp.maximum(denom, 1e-9), 0.0)
        local = jnp.zeros((ndocs_pad,), dtype=jnp.float32)
        local = local.at[docs.reshape(-1)].add(contrib.reshape(-1))
        scores = jax.lax.psum(local, AXIS)
        return tuple(jax.lax.top_k(scores, k))

    from ..obs import device as obs_device
    return obs_device.compiled(
        "mesh_bm25_topk", (_mesh_key(mesh), ndocs_pad, k, k1, b),
        lambda: step)


def sharded_query_step(mesh: Mesh, num_groups: int):
    """The full "training step" equivalent: one sharded query combining a
    filtered grouped aggregate with BM25 scoring — exercises scatter, matmul
    one-hot, and psum/all-reduce over the mesh in a single jitted program."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), P(AXIS, None),
                  P(), P(), P(AXIS, None), P(AXIS)),
        out_specs=(P(), P(), P()))
    def step(vals, mask, codes, flat_docs, flat_tfs, gidx, block_term):
        # grouped count + sum over the row shard
        sel = jnp.logical_and(mask, vals >= 0)
        oh = jax.nn.one_hot(jnp.clip(codes, 0, num_groups - 1), num_groups,
                            dtype=jnp.float32)
        oh = oh * sel.astype(jnp.float32)[..., None]
        counts = jax.lax.psum(jnp.einsum("rbg->g", oh), AXIS)
        sums = jax.lax.psum(
            jnp.einsum("rbg,rb->g", oh,
                       jnp.where(sel, vals, 0).astype(jnp.float32)), AXIS)
        # BM25-ish scoring over the posting shard
        valid = gidx >= 0
        safe = jnp.where(valid, gidx, 0)
        docs = flat_docs[safe]
        tfs = flat_tfs[safe].astype(jnp.float32)
        contrib = jnp.where(valid, tfs / (tfs + 1.2), 0.0)
        local = jnp.zeros_like(flat_docs, dtype=jnp.float32)
        local = local.at[docs.reshape(-1)].add(contrib.reshape(-1))
        scores = jax.lax.psum(local, AXIS)
        return counts, sums, scores

    from ..obs import device as obs_device
    return obs_device.compiled("mesh_query",
                               (_mesh_key(mesh), num_groups),
                               lambda: step)
