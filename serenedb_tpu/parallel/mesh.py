"""Device-mesh parallel execution of scans, aggregates, and scoring.

Reference analog: the reference's intra-node parallelism (morsel-driven
pipelines, parallel top-k collectors, parallel sinks — SURVEY.md §2.11) has
no cross-device component; on TPU the same roles map onto a
`jax.sharding.Mesh`: row blocks shard across devices ("data parallel" scan),
per-device partial aggregates combine with psum over ICI, and per-device
top-k merges via all_gather — XLA inserts the collectives.

The mesh axis is named "shard". Multi-host scaling uses the same programs
over a larger mesh (jax handles DCN vs ICI placement).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.device import LANES

AXIS = "shard"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (AXIS,))


def shard_devices(n_shards: int) -> Optional[list]:
    """Data-axis placement for the sharded execution tier
    (exec/shard.py): shard s's per-shard program inputs commit to
    device s % n_devices along the mesh's shard axis, so concurrent
    shard dispatches land on distinct devices of the same mesh a
    shard_map program would span. None on a single-device host — the
    shards then share the default device and fan out as worker-pool
    tasks only."""
    devs = jax.devices()
    if len(devs) <= 1 or n_shards <= 1:
        return None
    return [devs[s % len(devs)] for s in range(n_shards)]


def pad_to_multiple(arr, n: int, fill=0):
    """Pad the leading axis to a multiple of n (THE shard-padding helper:
    data pads with `fill`, masks with False — padded rows never count).
    Works on numpy and jax arrays alike."""
    rows = arr.shape[0]
    pad = (-rows) % n
    if not pad:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    if isinstance(arr, np.ndarray):
        return np.pad(arr, widths, constant_values=fill)
    return jnp.pad(arr, widths, constant_values=fill)


def shard_rows(arr: np.ndarray, mesh: Mesh) -> np.ndarray:
    """Pad the leading (row-block) axis to a multiple of the mesh size."""
    return pad_to_multiple(arr, mesh.shape[AXIS])


def sharded_agg_step(mesh: Mesh):
    """Build a jitted sharded filter+aggregate step:
    (vals (R,128) i32, mask (R,128) bool, lo, hi) →
    (total count, per-row-block [hi16, lo16] int32 partial sums (R, 2)).

    Each 128-lane partial is exact in int32 (lo ≤ 128·65535, hi ≤ 128·2^15);
    the caller combines them on host as (Σhi << 16) + Σlo in int64 —
    device-side whole-shard int32 accumulation would wrap (int64 reductions
    are emulated on TPU, so the exact combine stays on host)."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), P(), P()),
        out_specs=(P(), P(AXIS, None)))
    def step(vals, mask, lo, hi):
        sel = jnp.logical_and(mask,
                              jnp.logical_and(vals >= lo, vals < hi))
        cnt = jnp.sum(sel, dtype=jnp.int32)
        v = jnp.where(sel, vals, 0).astype(jnp.int32)
        loh = (v & 0xFFFF).astype(jnp.int32)
        hih = jnp.right_shift(v, 16)
        partials = jnp.stack([jnp.sum(hih, axis=1, dtype=jnp.int32),
                              jnp.sum(loh, axis=1, dtype=jnp.int32)], axis=1)
        return jax.lax.psum(cnt, AXIS), partials

    return jax.jit(step)


def combine_agg_partials(partials: np.ndarray) -> int:
    """(R, 2) int32 [hi16, lo16] row partials → exact int64 total."""
    p = np.asarray(partials).astype(np.int64)
    return int((p[:, 0].sum() << 16) + p[:, 1].sum())


def sharded_bm25_topk(mesh: Mesh, ndocs_pad: int, k: int,
                      k1: float = 1.2, b: float = 0.75):
    """Build a jitted sharded BM25 top-k: posting blocks shard across
    devices; each scores its blocks into a local dense accumulator; psum
    merges accumulators (doc space is replicated), then one top-k."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(), P(AXIS, None), P(AXIS), P(), P()),
        out_specs=(P(), P()))
    def step(flat_docs, flat_tfs, norms, gidx, block_term, idf, avgdl):
        valid = gidx >= 0
        safe = jnp.where(valid, gidx, 0)
        docs = flat_docs[safe]
        tfs = flat_tfs[safe].astype(jnp.float32)
        dl = norms[docs].astype(jnp.float32)
        w = idf[block_term][:, None]
        denom = tfs + k1 * (1.0 - b + b * dl / jnp.maximum(avgdl, 1e-9))
        contrib = jnp.where(valid, w * (k1 + 1.0) * tfs /
                            jnp.maximum(denom, 1e-9), 0.0)
        local = jnp.zeros((ndocs_pad,), dtype=jnp.float32)
        local = local.at[docs.reshape(-1)].add(contrib.reshape(-1))
        scores = jax.lax.psum(local, AXIS)
        return tuple(jax.lax.top_k(scores, k))

    return jax.jit(step)


def sharded_query_step(mesh: Mesh, num_groups: int):
    """The full "training step" equivalent: one sharded query combining a
    filtered grouped aggregate with BM25 scoring — exercises scatter, matmul
    one-hot, and psum/all-reduce over the mesh in a single jitted program."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), P(AXIS, None),
                  P(), P(), P(AXIS, None), P(AXIS)),
        out_specs=(P(), P(), P()))
    def step(vals, mask, codes, flat_docs, flat_tfs, gidx, block_term):
        # grouped count + sum over the row shard
        sel = jnp.logical_and(mask, vals >= 0)
        oh = jax.nn.one_hot(jnp.clip(codes, 0, num_groups - 1), num_groups,
                            dtype=jnp.float32)
        oh = oh * sel.astype(jnp.float32)[..., None]
        counts = jax.lax.psum(jnp.einsum("rbg->g", oh), AXIS)
        sums = jax.lax.psum(
            jnp.einsum("rbg,rb->g", oh,
                       jnp.where(sel, vals, 0).astype(jnp.float32)), AXIS)
        # BM25-ish scoring over the posting shard
        valid = gidx >= 0
        safe = jnp.where(valid, gidx, 0)
        docs = flat_docs[safe]
        tfs = flat_tfs[safe].astype(jnp.float32)
        contrib = jnp.where(valid, tfs / (tfs + 1.2), 0.0)
        local = jnp.zeros_like(flat_docs, dtype=jnp.float32)
        local = local.at[docs.reshape(-1)].add(contrib.reshape(-1))
        scores = jax.lax.psum(local, AXIS)
        return counts, sums, scores

    return jax.jit(step)
