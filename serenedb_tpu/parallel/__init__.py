from .mesh import (combine_agg_partials, make_mesh, sharded_agg_step,
                   sharded_bm25_topk, sharded_query_step, shard_rows)
from .pool import (WorkerPool, get_pool, parallel_map, session_workers)

__all__ = ["combine_agg_partials", "make_mesh", "sharded_agg_step",
           "sharded_bm25_topk", "sharded_query_step", "shard_rows",
           "WorkerPool", "get_pool", "parallel_map", "session_workers"]
