"""Shared morsel worker pool: one process-wide set of execution threads.

Reference analog: the reference runs ALL intra-node parallelism over shared
thread pools (DuckDB's TaskScheduler morsel workers plus the iresearch
search/consolidation pools; SURVEY.md §3.2). Concurrent sessions therefore
share ONE pool instead of spawning per-query threads and oversubscribing
the host — the same policy here: a lazily-started singleton sized by the
`serene_workers` global (default = CPU count).

Scheduling has two modes. With `serene_fair_share` OFF it is the
original work-stealing design scaled to morsel granularity: each worker
owns a deque, submissions land round-robin, and an idle worker steals
from the opposite end of a sibling's deque — global FIFO, so one heavy
statement's backlog runs entirely before every later statement's first
task. With `serene_fair_share` ON (the default) tagged tasks instead
land in per-STATEMENT queues and workers pick by stride scheduling:
each statement holds a pass value advanced by `stride = SCALE /
serene_priority` per task run, and the picker takes the head of the
lowest-pass queue — so a dashboard query arriving behind a 6M-row
aggregate waits ~one morsel, not the whole backlog, and a weight-2w
statement gets twice the pool share of a weight-w one. A newly arrived
statement joins at the current minimum pass (it inherits no credit and
owes no debt). Tasks capture the submitter's contextvars
(`contextvars.copy_context`), so executor-level facilities keyed on the
current connection — cooperative cancellation (`plan.check_cancel`),
statement-stable `now()` — keep working on worker threads exactly as
they do inline; the scheduling tag rides the same captured context
(sched.CURRENT_SCHED override, else the connection's per-statement
`_sched` pair).

Determinism contract: the pool never reorders RESULTS, in either mode.
`map_ordered` returns results in submission order and raises the
lowest-index failure after every submitted task has drained, so a
cancelled/failed query can never leave orphan morsels behind to poison
a later query. Fair-share picking therefore changes WHEN morsels run,
never what a query returns (ARCHITECTURE.md §25).
"""

from __future__ import annotations

import collections
import contextvars
import os
import threading
import time
from concurrent.futures import CancelledError, Future
from typing import Callable, Iterable, Optional, Sequence

from ..utils import metrics

_TRACE_VAR = None
_SCHED_VAR = None
_CONN_VAR = None


def _trace_var():
    """The obs-layer CURRENT_TRACE contextvar, imported once on first
    use (keeps pool importable without the obs package initialized)."""
    global _TRACE_VAR
    if _TRACE_VAR is None:
        from ..obs.trace import CURRENT_TRACE
        _TRACE_VAR = CURRENT_TRACE
    return _TRACE_VAR


def _sched_var():
    """The sched-layer CURRENT_SCHED override contextvar (lazy for the
    same import-order reason as _trace_var)."""
    global _SCHED_VAR
    if _SCHED_VAR is None:
        from ..sched.governor import CURRENT_SCHED
        _SCHED_VAR = CURRENT_SCHED
    return _SCHED_VAR


def _conn_var():
    global _CONN_VAR
    if _CONN_VAR is None:
        from ..engine import CURRENT_CONNECTION
        _CONN_VAR = CURRENT_CONNECTION
    return _CONN_VAR


def fair_share_enabled() -> bool:
    """The `serene_fair_share` global, read at submit time so a toggle
    applies to new submissions immediately (queued tasks drain from
    whichever structure they landed in)."""
    from ..utils.config import REGISTRY
    try:
        return bool(REGISTRY.get_global("serene_fair_share"))
    except KeyError:                    # pragma: no cover — always declared
        return False


class _Task:
    __slots__ = ("fn", "args", "future", "ctx", "t_submit_ns", "seq")

    def __init__(self, fn, args):
        self.fn = fn
        self.args = args
        self.future: Future = Future()
        self.ctx = contextvars.copy_context()
        self.t_submit_ns = time.perf_counter_ns()
        self.seq = 0                    # global submit order (set by pool)

    def sched(self) -> Optional[tuple]:
        """(tag, weight) scheduling identity from the captured context:
        the explicit CURRENT_SCHED override wins, else the submitting
        connection's per-statement `_sched` pair, else None (untagged —
        FIFO like before)."""
        s = self.ctx.get(_sched_var())
        if s is not None:
            return s
        conn = self.ctx.get(_conn_var())
        if conn is not None:
            return getattr(conn, "_sched", None)
        return None


#: stride scale: weights are clamped to 1..10000 (serene_priority), so
#: strides span SCALE/10000 .. SCALE with integer math throughout
_STRIDE_SCALE = 10_000_000


class _FairQueue:
    """One statement's queued tasks + stride state (guarded by the
    pool's lock)."""

    __slots__ = ("tasks", "pass_", "stride")

    def __init__(self, pass_: int, weight: int):
        self.tasks: collections.deque = collections.deque()
        self.pass_ = pass_
        self.stride = _STRIDE_SCALE // max(1, min(10000, int(weight)))


class WorkerPool:
    """Work-stealing thread pool; see module docstring for the contract."""

    def __init__(self, size: int):
        self.size = max(1, int(size))
        self._deques: list[collections.deque] = [
            collections.deque() for _ in range(self.size)]
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._threads: list[threading.Thread] = []
        self._worker_ids: set[int] = set()
        self._rr = 0
        self._seq = 0
        self._shutdown = False
        # fair-share state (serene_fair_share): per-statement-tag task
        # queues + stride bookkeeping, all under the pool lock. Tags
        # leave the dict the moment their queue drains; a returning tag
        # re-joins at the floor (the last dispatched pass), so pausing
        # between morsel windows accrues neither credit nor debt.
        self._fair: dict[object, _FairQueue] = {}
        self._fair_floor = 0

    # -- lifecycle ---------------------------------------------------------

    def ensure_started(self) -> "WorkerPool":
        with self._lock:
            if self._threads or self._shutdown:
                return self
            for wid in range(self.size):
                t = threading.Thread(target=self._worker, args=(wid,),
                                     name=f"sdb-morsel-{wid}", daemon=True)
                self._threads.append(t)
            for t in self._threads:
                t.start()
        return self

    def shutdown(self):
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()

    @property
    def in_worker(self) -> bool:
        """True when the calling thread IS a pool worker — nested fan-out
        must run inline (a saturated pool waiting on itself deadlocks)."""
        return threading.get_ident() in self._worker_ids

    # -- submission --------------------------------------------------------

    def submit(self, fn: Callable, *args) -> Future:
        task = _Task(fn, args)
        sched = task.sched() if fair_share_enabled() else None
        with self._cv:
            if self._shutdown:
                raise RuntimeError("worker pool is shut down")
            self._seq += 1
            task.seq = self._seq
            if sched is not None:
                self._fair_push(task, sched[0], sched[1])
            else:
                self._deques[self._rr % self.size].append(task)
                self._rr += 1
            metrics.POOL_QUEUE_DEPTH.add()
            self._cv.notify()
        if not self._threads:
            self.ensure_started()
        return task.future

    # -- fair-share structure (all under self._lock) -----------------------

    def _fair_push(self, task: _Task, tag, weight) -> None:
        q = self._fair.get(tag)
        if q is None:
            # join at the current minimum pass: the newcomer's next pick
            # competes on equal terms — no banked credit from having
            # been absent, no debt from others' progress
            base = min((fq.pass_ for fq in self._fair.values()),
                       default=self._fair_floor)
            q = self._fair[tag] = _FairQueue(base, weight)
        q.tasks.append(task)

    def _pop_fair(self) -> Optional[_Task]:
        """Stride pick: head of the lowest-pass queue (ties broken by
        the head task's global submit order — deterministic, and exact
        FIFO when every weight is equal and passes tie). Counts a
        preemption whenever the pick is NOT the FIFO-oldest queued
        task — each one is an interleave plain FIFO would not have
        done."""
        if not self._fair:
            return None
        best = None
        best_key = None
        fifo_seq = None
        for tag, q in self._fair.items():
            head_seq = q.tasks[0].seq
            key = (q.pass_, head_seq)
            if best_key is None or key < best_key:
                best_key, best = key, tag
            if fifo_seq is None or head_seq < fifo_seq:
                fifo_seq = head_seq
        q = self._fair[best]
        task = q.tasks.popleft()
        q.pass_ += q.stride
        self._fair_floor = q.pass_
        if not q.tasks:
            del self._fair[best]
        if task.seq != fifo_seq:
            metrics.SCHED_PREEMPTIONS.add()
        return task

    def map_ordered(self, fn: Callable, items: Sequence,
                    parallelism: Optional[int] = None) -> list:
        """Run fn over items on the pool; results in ITEM order.

        Every submitted task drains (runs or is cancelled-before-start)
        before this returns or raises; on failure the lowest-index
        exception is raised. parallelism bounds this CALL's in-flight
        tasks (per-session `serene_workers` cap) without resizing the
        shared pool.
        """
        items = list(items)
        cap = self.size if parallelism is None else min(parallelism, self.size)
        if len(items) <= 1 or cap <= 1 or self.in_worker:
            return [fn(it) for it in items]
        # window == cap: at most `cap` tasks in flight (queued + running),
        # so a session's serene_workers cap truly bounds its parallelism
        # even when more pool workers are idle
        window = cap
        futs: list[Optional[Future]] = [None] * len(items)
        results: list = [None] * len(items)
        first_exc: Optional[BaseException] = None
        submitted = 0

        def pump():
            nonlocal submitted
            while submitted < len(items) and first_exc is None and \
                    submitted - drained < window:
                futs[submitted] = self.submit(fn, items[submitted])
                submitted += 1

        drained = 0
        pump()
        while drained < submitted:
            f = futs[drained]
            try:
                if not f.done():
                    # live wait-event feed for pg_stat_activity: the
                    # session blocks here while its morsel tasks queue
                    # or run — the live counterpart of the queue_wait
                    # span the worker stamps retrospectively
                    from ..obs.resources import wait_scope
                    with wait_scope("IPC", "PoolTaskWait"):
                        results[drained] = f.result()
                else:
                    results[drained] = f.result()
            except CancelledError:
                pass  # cancelled after an earlier failure: already drained
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_exc is None:
                    first_exc = e
                    for g in futs[drained + 1:submitted]:
                        if g is not None:
                            g.cancel()
            drained += 1
            pump()
        if first_exc is not None:
            raise first_exc
        return results

    # -- worker loop -------------------------------------------------------

    def _pop_task(self, wid: int) -> Optional[_Task]:
        task = None
        dq = self._deques[wid]
        if dq:
            task = dq.popleft()
        else:
            for off in range(1, self.size):
                other = self._deques[(wid + off) % self.size]
                if other:
                    task = other.pop()   # steal from the opposite end
                    metrics.POOL_STEALS.add()
                    break
        if task is None:
            # fair-share tier: tagged tasks live in per-statement
            # queues picked by stride, not in the worker deques (the
            # deques keep serving untagged/legacy submissions, and
            # drain a mid-toggle backlog either way)
            task = self._pop_fair()
        if task is not None:
            # the task left the queue (will run or was cancelled while
            # queued) — the live-depth gauge drops either way
            metrics.POOL_QUEUE_DEPTH.sub()
        return task

    def _worker(self, wid: int):
        self._worker_ids.add(threading.get_ident())
        while True:
            with self._cv:
                task = self._pop_task(wid)
                while task is None and not self._shutdown:
                    self._cv.wait()
                    task = self._pop_task(wid)
                if task is None:   # shutdown
                    return
            f = task.future
            if not f.set_running_or_notify_cancel():
                continue           # cancelled while queued: drained, no run
            t0 = time.perf_counter_ns()
            wait_ns = t0 - task.t_submit_ns
            metrics.POOL_QUEUE_WAIT_US.add(wait_ns // 1000)
            metrics.POOL_TASK_WAIT_NS.add(wait_ns)
            metrics.POOL_QUEUE_WAIT_HIST.observe_ns(wait_ns)
            # timeline attribution: the submitter's trace rides the
            # task's captured context — one mapping lookup per TASK
            # (morsel-sized, never per row), two span appends when a
            # traced statement submitted it
            trace = task.ctx.get(_trace_var())
            if trace is not None:
                trace.add("queue_wait", "pool", task.t_submit_ns, t0)
            metrics.POOL_RUNNING.add()
            try:
                result = task.ctx.run(task.fn, *task.args)
                exc = None
            except BaseException as e:  # noqa: BLE001 — delivered via future
                exc = e
            t1 = time.perf_counter_ns()
            metrics.POOL_RUNNING.sub()
            metrics.POOL_MORSELS.add()
            metrics.POOL_BUSY_US.add((t1 - t0) // 1000)
            # the task span MUST be in the ring before the future
            # resolves: delivering the result wakes the statement
            # thread, which may finalize the trace immediately — a span
            # stamped after that is lost (or outlives the timeline)
            if trace is not None:
                trace.add("task", "pool", t0, t1)
            if exc is not None:
                f.set_exception(exc)
            else:
                f.set_result(result)


# -- process-wide singleton -------------------------------------------------

_POOL: Optional[WorkerPool] = None
_POOL_LOCK = threading.Lock()


def default_workers() -> int:
    return os.cpu_count() or 1


def get_pool() -> WorkerPool:
    """The process-wide shared pool, sized from the `serene_workers`
    GLOBAL at first use (sessions cap their own parallelism per query via
    the session-scope value; the pool itself is shared and fixed).
    Floor of 2: a single-thread pool would silently disable every
    parallel tier even for sessions that raise their own
    serene_workers — on a 1-core host the GIL-releasing numpy morsel
    work still overlaps, and sessions that want inline execution say
    `SET serene_workers = 1`, which bypasses the pool entirely."""
    global _POOL
    pool = _POOL
    if pool is not None:
        return pool
    with _POOL_LOCK:
        if _POOL is None:
            from ..utils.config import REGISTRY
            try:
                size = int(REGISTRY.get_global("serene_workers"))
            except KeyError:
                size = default_workers()
            _POOL = WorkerPool(max(2, size))
        return _POOL


def session_workers(settings) -> int:
    """Per-query parallelism cap (>=1). settings=None → the executing
    connection's session settings when inside a statement, else the
    global default (library callers outside any session)."""
    if settings is None:
        from ..engine import CURRENT_CONNECTION
        conn = CURRENT_CONNECTION.get()
        if conn is not None:
            settings = conn.settings
    try:
        if settings is not None:
            w = int(settings.get("serene_workers"))
        else:
            from ..utils.config import REGISTRY
            w = int(REGISTRY.get_global("serene_workers"))
    except KeyError:
        w = default_workers()
    return max(1, w)


def parallel_map(settings, fn: Callable, items: Iterable) -> list:
    """map_ordered over the shared pool, capped by the session's
    `serene_workers`; runs inline when the cap (or item count) is 1."""
    items = list(items)
    cap = session_workers(settings)
    if cap <= 1 or len(items) <= 1:
        return [fn(it) for it in items]
    return get_pool().ensure_started().map_ordered(fn, items, cap)
