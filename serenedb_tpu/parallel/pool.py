"""Shared morsel worker pool: one process-wide set of execution threads.

Reference analog: the reference runs ALL intra-node parallelism over shared
thread pools (DuckDB's TaskScheduler morsel workers plus the iresearch
search/consolidation pools; SURVEY.md §3.2). Concurrent sessions therefore
share ONE pool instead of spawning per-query threads and oversubscribing
the host — the same policy here: a lazily-started singleton sized by the
`serene_workers` global (default = CPU count).

Scheduling is a work-stealing design scaled to morsel granularity: each
worker owns a deque, submissions land round-robin, and an idle worker
steals from the opposite end of a sibling's deque. Tasks capture the
submitter's contextvars (`contextvars.copy_context`), so executor-level
facilities keyed on the current connection — cooperative cancellation
(`plan.check_cancel`), statement-stable `now()` — keep working on worker
threads exactly as they do inline.

Determinism contract: the pool never reorders RESULTS. `map_ordered`
returns results in submission order and raises the lowest-index failure
after every submitted task has drained, so a cancelled/failed query can
never leave orphan morsels behind to poison a later query.
"""

from __future__ import annotations

import collections
import contextvars
import os
import threading
import time
from concurrent.futures import CancelledError, Future
from typing import Callable, Iterable, Optional, Sequence

from ..utils import metrics

_TRACE_VAR = None


def _trace_var():
    """The obs-layer CURRENT_TRACE contextvar, imported once on first
    use (keeps pool importable without the obs package initialized)."""
    global _TRACE_VAR
    if _TRACE_VAR is None:
        from ..obs.trace import CURRENT_TRACE
        _TRACE_VAR = CURRENT_TRACE
    return _TRACE_VAR


class _Task:
    __slots__ = ("fn", "args", "future", "ctx", "t_submit_ns")

    def __init__(self, fn, args):
        self.fn = fn
        self.args = args
        self.future: Future = Future()
        self.ctx = contextvars.copy_context()
        self.t_submit_ns = time.perf_counter_ns()


class WorkerPool:
    """Work-stealing thread pool; see module docstring for the contract."""

    def __init__(self, size: int):
        self.size = max(1, int(size))
        self._deques: list[collections.deque] = [
            collections.deque() for _ in range(self.size)]
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._threads: list[threading.Thread] = []
        self._worker_ids: set[int] = set()
        self._rr = 0
        self._shutdown = False

    # -- lifecycle ---------------------------------------------------------

    def ensure_started(self) -> "WorkerPool":
        with self._lock:
            if self._threads or self._shutdown:
                return self
            for wid in range(self.size):
                t = threading.Thread(target=self._worker, args=(wid,),
                                     name=f"sdb-morsel-{wid}", daemon=True)
                self._threads.append(t)
            for t in self._threads:
                t.start()
        return self

    def shutdown(self):
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()

    @property
    def in_worker(self) -> bool:
        """True when the calling thread IS a pool worker — nested fan-out
        must run inline (a saturated pool waiting on itself deadlocks)."""
        return threading.get_ident() in self._worker_ids

    # -- submission --------------------------------------------------------

    def submit(self, fn: Callable, *args) -> Future:
        task = _Task(fn, args)
        with self._cv:
            if self._shutdown:
                raise RuntimeError("worker pool is shut down")
            self._deques[self._rr % self.size].append(task)
            self._rr += 1
            metrics.POOL_QUEUE_DEPTH.add()
            self._cv.notify()
        if not self._threads:
            self.ensure_started()
        return task.future

    def map_ordered(self, fn: Callable, items: Sequence,
                    parallelism: Optional[int] = None) -> list:
        """Run fn over items on the pool; results in ITEM order.

        Every submitted task drains (runs or is cancelled-before-start)
        before this returns or raises; on failure the lowest-index
        exception is raised. parallelism bounds this CALL's in-flight
        tasks (per-session `serene_workers` cap) without resizing the
        shared pool.
        """
        items = list(items)
        cap = self.size if parallelism is None else min(parallelism, self.size)
        if len(items) <= 1 or cap <= 1 or self.in_worker:
            return [fn(it) for it in items]
        # window == cap: at most `cap` tasks in flight (queued + running),
        # so a session's serene_workers cap truly bounds its parallelism
        # even when more pool workers are idle
        window = cap
        futs: list[Optional[Future]] = [None] * len(items)
        results: list = [None] * len(items)
        first_exc: Optional[BaseException] = None
        submitted = 0

        def pump():
            nonlocal submitted
            while submitted < len(items) and first_exc is None and \
                    submitted - drained < window:
                futs[submitted] = self.submit(fn, items[submitted])
                submitted += 1

        drained = 0
        pump()
        while drained < submitted:
            f = futs[drained]
            try:
                if not f.done():
                    # live wait-event feed for pg_stat_activity: the
                    # session blocks here while its morsel tasks queue
                    # or run — the live counterpart of the queue_wait
                    # span the worker stamps retrospectively
                    from ..obs.resources import wait_scope
                    with wait_scope("IPC", "PoolTaskWait"):
                        results[drained] = f.result()
                else:
                    results[drained] = f.result()
            except CancelledError:
                pass  # cancelled after an earlier failure: already drained
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_exc is None:
                    first_exc = e
                    for g in futs[drained + 1:submitted]:
                        if g is not None:
                            g.cancel()
            drained += 1
            pump()
        if first_exc is not None:
            raise first_exc
        return results

    # -- worker loop -------------------------------------------------------

    def _pop_task(self, wid: int) -> Optional[_Task]:
        task = None
        dq = self._deques[wid]
        if dq:
            task = dq.popleft()
        else:
            for off in range(1, self.size):
                other = self._deques[(wid + off) % self.size]
                if other:
                    task = other.pop()   # steal from the opposite end
                    metrics.POOL_STEALS.add()
                    break
        if task is not None:
            # the task left the queue (will run or was cancelled while
            # queued) — the live-depth gauge drops either way
            metrics.POOL_QUEUE_DEPTH.sub()
        return task

    def _worker(self, wid: int):
        self._worker_ids.add(threading.get_ident())
        while True:
            with self._cv:
                task = self._pop_task(wid)
                while task is None and not self._shutdown:
                    self._cv.wait()
                    task = self._pop_task(wid)
                if task is None:   # shutdown
                    return
            f = task.future
            if not f.set_running_or_notify_cancel():
                continue           # cancelled while queued: drained, no run
            t0 = time.perf_counter_ns()
            wait_ns = t0 - task.t_submit_ns
            metrics.POOL_QUEUE_WAIT_US.add(wait_ns // 1000)
            metrics.POOL_TASK_WAIT_NS.add(wait_ns)
            metrics.POOL_QUEUE_WAIT_HIST.observe_ns(wait_ns)
            # timeline attribution: the submitter's trace rides the
            # task's captured context — one mapping lookup per TASK
            # (morsel-sized, never per row), two span appends when a
            # traced statement submitted it
            trace = task.ctx.get(_trace_var())
            if trace is not None:
                trace.add("queue_wait", "pool", task.t_submit_ns, t0)
            metrics.POOL_RUNNING.add()
            try:
                result = task.ctx.run(task.fn, *task.args)
                exc = None
            except BaseException as e:  # noqa: BLE001 — delivered via future
                exc = e
            t1 = time.perf_counter_ns()
            metrics.POOL_RUNNING.sub()
            metrics.POOL_MORSELS.add()
            metrics.POOL_BUSY_US.add((t1 - t0) // 1000)
            # the task span MUST be in the ring before the future
            # resolves: delivering the result wakes the statement
            # thread, which may finalize the trace immediately — a span
            # stamped after that is lost (or outlives the timeline)
            if trace is not None:
                trace.add("task", "pool", t0, t1)
            if exc is not None:
                f.set_exception(exc)
            else:
                f.set_result(result)


# -- process-wide singleton -------------------------------------------------

_POOL: Optional[WorkerPool] = None
_POOL_LOCK = threading.Lock()


def default_workers() -> int:
    return os.cpu_count() or 1


def get_pool() -> WorkerPool:
    """The process-wide shared pool, sized from the `serene_workers`
    GLOBAL at first use (sessions cap their own parallelism per query via
    the session-scope value; the pool itself is shared and fixed).
    Floor of 2: a single-thread pool would silently disable every
    parallel tier even for sessions that raise their own
    serene_workers — on a 1-core host the GIL-releasing numpy morsel
    work still overlaps, and sessions that want inline execution say
    `SET serene_workers = 1`, which bypasses the pool entirely."""
    global _POOL
    pool = _POOL
    if pool is not None:
        return pool
    with _POOL_LOCK:
        if _POOL is None:
            from ..utils.config import REGISTRY
            try:
                size = int(REGISTRY.get_global("serene_workers"))
            except KeyError:
                size = default_workers()
            _POOL = WorkerPool(max(2, size))
        return _POOL


def session_workers(settings) -> int:
    """Per-query parallelism cap (>=1). settings=None → the executing
    connection's session settings when inside a statement, else the
    global default (library callers outside any session)."""
    if settings is None:
        from ..engine import CURRENT_CONNECTION
        conn = CURRENT_CONNECTION.get()
        if conn is not None:
            settings = conn.settings
    try:
        if settings is not None:
            w = int(settings.get("serene_workers"))
        else:
            from ..utils.config import REGISTRY
            w = int(REGISTRY.get_global("serene_workers"))
    except KeyError:
        w = default_workers()
    return max(1, w)


def parallel_map(settings, fn: Callable, items: Iterable) -> list:
    """map_ordered over the shared pool, capped by the session's
    `serene_workers`; runs inline when the cap (or item count) is 1."""
    items = list(items)
    cap = session_workers(settings)
    if cap <= 1 or len(items) <= 1:
        return [fn(it) for it in items]
    return get_pool().ensure_started().map_ordered(fn, items, cap)
