"""PG error model: SQLSTATE-coded exceptions.

Reference analog: libs/pg/{errcodes.h,sql_exception.h} + THROW_SQL_ERROR
macros (SURVEY.md §2.3). Codes follow the PostgreSQL SQLSTATE space so the
wire layer can emit proper ErrorResponse fields.
"""

from __future__ import annotations


class SqlError(Exception):
    def __init__(self, sqlstate: str, message: str, detail: str = "",
                 hint: str = ""):
        super().__init__(message)
        self.sqlstate = sqlstate
        self.message = message
        self.detail = detail
        self.hint = hint


# common SQLSTATEs
SYNTAX_ERROR = "42601"
UNDEFINED_TABLE = "42P01"
UNDEFINED_COLUMN = "42703"
UNDEFINED_FUNCTION = "42883"
DUPLICATE_TABLE = "42P07"
DUPLICATE_OBJECT = "42710"
AMBIGUOUS_COLUMN = "42702"
DATATYPE_MISMATCH = "42804"
INVALID_TEXT_REPRESENTATION = "22P02"
DIVISION_BY_ZERO = "22012"
NUMERIC_OUT_OF_RANGE = "22003"
FEATURE_NOT_SUPPORTED = "0A000"
INSUFFICIENT_PRIVILEGE = "42501"
UNDEFINED_OBJECT = "42704"
IN_FAILED_TRANSACTION = "25P02"
INVALID_REGULAR_EXPRESSION = "2201B"
QUERY_CANCELED = "57014"
# workload governor (sched/governor.py): PG's class-53 "insufficient
# resources" codes — 53300 for an admission queue at capacity (PG uses
# it for too_many_connections; same resource, statement granularity),
# 53200 for a statement aborted over its serene_work_mem budget
TOO_MANY_CONNECTIONS = "53300"
OUT_OF_MEMORY = "53200"


def syntax(msg: str) -> SqlError:
    return SqlError(SYNTAX_ERROR, msg)


def unsupported(msg: str) -> SqlError:
    return SqlError(FEATURE_NOT_SUPPORTED, msg)
