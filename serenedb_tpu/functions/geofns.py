"""ST_* geometry SQL functions over the geo shape layer.

Reference analog: server/connector/functions/geo.cpp (S2-backed GEO_*
/ ST_* functions) + libs/geo codecs. Registered on import from scalar.py;
evaluates whole columns per call with a per-call parse memo (geometry
arguments are usually constant literals)."""

from __future__ import annotations

import json

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Column
from ..geo import ops as geo_ops
from ..geo import shapes as geo_shapes
from ..sql.expr import make_string_column, propagate_nulls, string_values
from .scalar import FunctionResolution, _REGISTRY, _result, _stringish


def _parse_cached(text: str, cache: dict) -> geo_shapes.Geometry:
    g = cache.get(text)
    if g is None:
        g = cache[text] = geo_shapes.parse_any(text)
    return g


def _geom_pair_resolver(fn, result_type=dt.BOOL, name="st_fn"):
    """(geom_text, geom_text) -> scalar via fn(Geometry, Geometry)."""
    def resolver(ts):
        if len(ts) < 2 or not all(_stringish(t) for t in ts[:2]):
            return None

        def impl(cols, n):
            a = string_values(cols[0])
            b = string_values(cols[1])
            valid = propagate_nulls(cols)
            cache: dict = {}
            if result_type is dt.BOOL:
                out = np.zeros(n, dtype=bool)
            else:
                out = np.zeros(n, dtype=np.float64)
            for i in range(n):
                if valid is not None and not valid[i]:
                    continue
                out[i] = fn(_parse_cached(a[i], cache),
                            _parse_cached(b[i], cache))
            return _result(result_type, out, cols[:2])
        return FunctionResolution(result_type, impl)
    return resolver


def _geom_unary_resolver(fn, result_type, to_text=False):
    def resolver(ts):
        if not ts or not _stringish(ts[0]):
            return None

        def impl(cols, n):
            a = string_values(cols[0])
            valid = propagate_nulls(cols)
            cache: dict = {}
            if to_text:
                out = []
                for i in range(n):
                    if valid is not None and not valid[i]:
                        out.append("")
                        continue
                    out.append(fn(_parse_cached(a[i], cache)))
                return make_string_column(
                    np.asarray(out, dtype=object).astype(str), valid)
            out = np.zeros(n, dtype=result_type.np_dtype)
            for i in range(n):
                if valid is not None and not valid[i]:
                    continue
                out[i] = fn(_parse_cached(a[i], cache))
            return _result(result_type, out, cols[:1])
        return FunctionResolution(result_type, impl)
    return resolver


# constructors / converters -------------------------------------------------

_REGISTRY["st_geomfromtext"] = _geom_unary_resolver(
    lambda g: geo_shapes.to_wkt(g), dt.VARCHAR, to_text=True)
_REGISTRY["st_geometryfromtext"] = _REGISTRY["st_geomfromtext"]
_REGISTRY["st_astext"] = _REGISTRY["st_geomfromtext"]

_REGISTRY["st_asgeojson"] = _geom_unary_resolver(
    lambda g: json.dumps(geo_shapes.to_geojson(g)), dt.VARCHAR,
    to_text=True)
_REGISTRY["st_geomfromgeojson"] = _geom_unary_resolver(
    lambda g: geo_shapes.to_wkt(g), dt.VARCHAR, to_text=True)

_REGISTRY["st_asbinary"] = _geom_unary_resolver(
    lambda g: geo_shapes.to_wkb(g).hex(), dt.VARCHAR, to_text=True)
_REGISTRY["st_aswkb"] = _REGISTRY["st_asbinary"]


def _from_wkb_resolver(ts):
    if not ts or not _stringish(ts[0]):
        return None

    def impl(cols, n):
        a = string_values(cols[0])
        valid = propagate_nulls(cols)
        out = []
        for i in range(n):
            if valid is not None and not valid[i]:
                out.append("")
                continue
            try:
                raw = bytes.fromhex(a[i].strip().removeprefix("\\x"))
            except ValueError:
                raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                                      "invalid WKB hex")
            out.append(geo_shapes.to_wkt(geo_shapes.from_wkb(raw)))
        return make_string_column(
            np.asarray(out, dtype=object).astype(str), valid)
    return FunctionResolution(dt.VARCHAR, impl)


_REGISTRY["st_geomfromwkb"] = _from_wkb_resolver

# predicates ---------------------------------------------------------------

_REGISTRY["st_contains"] = _geom_pair_resolver(geo_ops.contains)
_REGISTRY["st_covers"] = _geom_pair_resolver(geo_ops.contains)
_REGISTRY["st_within"] = _geom_pair_resolver(
    lambda a, b: geo_ops.contains(b, a))
_REGISTRY["st_coveredby"] = _REGISTRY["st_within"]
_REGISTRY["st_intersects"] = _geom_pair_resolver(geo_ops.intersects)
_REGISTRY["st_disjoint"] = _geom_pair_resolver(
    lambda a, b: not geo_ops.intersects(a, b))


def _st_dwithin(ts):
    if len(ts) != 3 or not all(_stringish(t) for t in ts[:2]) or not (
            ts[2].is_numeric or ts[2].id is dt.TypeId.NULL):
        return None

    def impl(cols, n):
        a = string_values(cols[0])
        b = string_values(cols[1])
        dist = cols[2].data.astype(np.float64)
        valid = propagate_nulls(cols)
        cache: dict = {}
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            if valid is not None and not valid[i]:
                continue
            out[i] = geo_ops.distance_m(
                _parse_cached(a[i], cache),
                _parse_cached(b[i], cache)) <= dist[i]
        return _result(dt.BOOL, out, cols)
    return FunctionResolution(dt.BOOL, impl)


_REGISTRY["st_dwithin"] = _st_dwithin

# general-geometry distance replaces the point-only fast path (same
# spherical radius; distance_m(point, point) IS the haversine formula)
_REGISTRY["st_distance"] = _geom_pair_resolver(geo_ops.distance_m,
                                               dt.DOUBLE)
_REGISTRY["st_distance_sphere"] = _REGISTRY["st_distance"]

# measures -----------------------------------------------------------------

_REGISTRY["st_area"] = _geom_unary_resolver(geo_ops.area_m2, dt.DOUBLE)
_REGISTRY["st_length"] = _geom_unary_resolver(geo_ops.length_m, dt.DOUBLE)
_REGISTRY["st_perimeter"] = _geom_unary_resolver(geo_ops.perimeter_m,
                                                 dt.DOUBLE)
_REGISTRY["st_npoints"] = _geom_unary_resolver(
    lambda g: len(g.points()), dt.INT)
_REGISTRY["st_geometrytype"] = _geom_unary_resolver(
    lambda g: "ST_" + geo_shapes._GJ_NAME[g.kind], dt.VARCHAR,
    to_text=True)
_REGISTRY["st_centroid"] = _geom_unary_resolver(
    lambda g: geo_shapes.to_wkt(
        geo_shapes.Geometry("point", geo_ops.centroid(g))),
    dt.VARCHAR, to_text=True)
_REGISTRY["st_envelope"] = _geom_unary_resolver(
    lambda g: geo_shapes.to_wkt(geo_ops.envelope(g)), dt.VARCHAR,
    to_text=True)
