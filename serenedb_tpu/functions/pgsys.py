"""PG system/introspection functions backing the psql \\d-family and ORM
introspection (reference: server/pg/pg_catalog/ support functions and
server/query/server_engine.cpp:61-216 pseudo-type plumbing).

These are catalog-cardinality functions (rows ≈ number of tables/columns),
so row-wise Python is the right tool — none of this is on the TPU hot path.
"""

from __future__ import annotations

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Column
from ..sql.expr import make_string_column, propagate_nulls, string_values
from .scalar import FunctionResolution, _REGISTRY, register


def _strings_out(values, validity):
    return make_string_column(
        np.asarray(["" if v is None else str(v) for v in values],
                   dtype=object).astype(str),
        validity)


def _rowwise_str(fn, n_args=None):
    """Build a resolver for a row-wise function returning text.
    fn(row_values: tuple) -> Optional[str]; NULL args propagate."""
    def resolver(ts):
        if n_args is not None and len(ts) not in n_args:
            return None

        def impl(cols, n):
            pys = [c.to_pylist() for c in cols]
            out, nulls = [], np.zeros(n, dtype=bool)
            for i in range(n):
                row = tuple(p[i] for p in pys)
                if any(v is None for v in row):
                    out.append(None)
                    nulls[i] = True
                    continue
                v = fn(row)
                out.append(v)
                nulls[i] = v is None
            validity = ~nulls if nulls.any() else propagate_nulls(cols)
            return _strings_out(out, validity)
        return FunctionResolution(dt.VARCHAR, impl)
    return resolver


def _const_fn(name, value_fn, typ=dt.VARCHAR):
    @register(name)
    def _f(ts, _v=value_fn, _t=typ):
        def impl(cols, n):
            v = _v()
            return Column.from_pylist([v] * max(n, 1), _t)
        return FunctionResolution(_t, impl)


def _db():
    from ..pgcatalog import current_db
    return current_db()


# -- format_type / visibility ---------------------------------------------

@register("format_type")
def _format_type(ts):
    from ..pgcatalog import format_type_oid

    def impl(cols, n):
        oids = cols[0].to_pylist()
        mods = (cols[1].to_pylist() if len(cols) > 1 else [None] * n)
        out = [None if o is None else format_type_oid(int(o), mods[i])
               for i, o in enumerate(oids)]
        validity = np.asarray([v is not None for v in out], dtype=bool)
        return _strings_out(out, validity if not validity.all() else None)
    return FunctionResolution(dt.VARCHAR, impl)


def _vis(ts):
    def impl(cols, n):
        return Column(dt.BOOL, np.ones(n, dtype=bool),
                      propagate_nulls(cols))
    return FunctionResolution(dt.BOOL, impl)


for _name in ("pg_table_is_visible", "pg_type_is_visible",
              "pg_function_is_visible", "pg_operator_is_visible"):
    _REGISTRY[_name] = _vis


# -- pg_get_* --------------------------------------------------------------

def _index_lookup(oid):
    db = _db()
    if db is None:
        return None
    hit = db.oid_lookup(oid)
    if hit is None or hit[0] != "index":
        return None
    _, schema, iname = hit
    with db.lock:
        s = db.schemas.get(schema)
        if s is None:
            return None
        for tname, t in s.tables.items():
            idx = getattr(t, "indexes", {}).get(iname)
            if idx is not None:
                return schema, tname, iname, idx
    return None


def _pg_get_indexdef_row(row):
    oid = int(row[0])
    colno = int(row[1]) if len(row) > 1 else 0
    hit = _index_lookup(oid)
    if hit is None:
        return None
    schema, tname, iname, idx = hit
    cols = list(getattr(idx, "columns", []))
    if colno > 0:
        return cols[colno - 1] if colno <= len(cols) else ""
    qual = tname if schema == "main" else f"{schema}.{tname}"
    return (f"CREATE INDEX {iname} ON {qual} "
            f"USING {idx.using} ({', '.join(cols)})")


_REGISTRY["pg_get_indexdef"] = _rowwise_str(_pg_get_indexdef_row,
                                            n_args={1, 2, 3})


def _pg_get_viewdef_row(row):
    db = _db()
    if db is None:
        return None
    v = row[0]
    hit = db.oid_lookup(int(v)) if not isinstance(v, str) or \
        str(v).isdigit() else None
    if hit is None and isinstance(v, str):
        try:
            hit = db.oid_lookup(db.resolve_relation_oid(v))
        except errors.SqlError:
            return None
    if hit is None or hit[0] != "view":
        return None
    _, schema, vname = hit
    with db.lock:
        s = db.schemas.get(schema)
        vd = s.views.get(vname) if s else None
    return (getattr(vd, "sql", "") or "") if vd is not None else None


_REGISTRY["pg_get_viewdef"] = _rowwise_str(_pg_get_viewdef_row,
                                           n_args={1, 2})


def _pg_get_userbyid_row(row):
    db = _db()
    if db is not None:
        hit = db.oid_lookup(int(row[0]))
        if hit is not None and hit[0] == "role":
            return hit[2]
    return "serene"


_REGISTRY["pg_get_userbyid"] = _rowwise_str(_pg_get_userbyid_row,
                                            n_args={1})

# pg_get_expr(adbin, adrelid[, pretty]): we store expression *text* in
# adbin, so rendering is identity on the first argument
_REGISTRY["pg_get_expr"] = _rowwise_str(lambda row: str(row[0]),
                                        n_args={2, 3})


def _pg_get_constraintdef_row(row):
    db = _db()
    if db is None:
        return None
    hit = db.oid_lookup(int(row[0]))
    if hit is None or hit[0] != "constraint":
        return None
    _, schema, cname = hit
    tname = cname[:-5] if cname.endswith("_pkey") else cname
    with db.lock:
        s = db.schemas.get(schema)
        t = s.tables.get(tname) if s else None
    if t is None:
        return None
    pk = (getattr(t, "table_meta", {}) or {}).get("primary_key") or []
    return f"PRIMARY KEY ({', '.join(pk)})"


_REGISTRY["pg_get_constraintdef"] = _rowwise_str(
    _pg_get_constraintdef_row, n_args={1, 2})


def _null_resolver(ts):
    def impl(cols, n):
        return Column(dt.VARCHAR, np.zeros(n, dtype=np.int32),
                      np.zeros(n, dtype=bool), np.asarray([""]))
    return FunctionResolution(dt.VARCHAR, impl)


for _name in ("obj_description", "col_description", "shobj_description",
              "pg_get_function_result", "pg_get_function_arguments",
              "pg_get_function_identity_arguments", "pg_get_triggerdef",
              "pg_get_partkeydef", "pg_get_statisticsobjdef"):
    _REGISTRY[_name] = _null_resolver


# -- quoting ---------------------------------------------------------------

_SAFE_IDENT = __import__("re").compile(r"^[a-z_][a-z0-9_$]*$")

# reserved words that must be quoted even when lexically safe (PG's
# quote_ident quotes anything in its reserved-keyword list)
_RESERVED = frozenset("""
    all analyse analyze and any array as asc asymmetric between binary both
    case cast check collate column constraint create cross current_catalog
    current_date current_role current_time current_timestamp current_user
    default deferrable desc distinct do else end except false fetch for
    foreign freeze from full grant group having ilike in initially inner
    intersect into is isnull join lateral leading left like limit localtime
    localtimestamp natural not notnull null offset on only or order outer
    overlaps placing primary references returning right select session_user
    similar some symmetric table then to trailing true union unique user
    using variadic verbose when where window with
""".split())


def _quote_ident_row(row):
    s = str(row[0])
    if _SAFE_IDENT.match(s) and s not in _RESERVED:
        return s
    return '"' + s.replace('"', '""') + '"'


_REGISTRY["quote_ident"] = _rowwise_str(_quote_ident_row, n_args={1})


@register("quote_literal")
def _quote_literal(ts):
    def impl(cols, n):
        vals = cols[0].to_pylist()
        out = [None if v is None
               else "'" + str(v).replace("'", "''") + "'" for v in vals]
        return _strings_out(out, propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("quote_nullable")
def _quote_nullable(ts):
    def impl(cols, n):
        vals = cols[0].to_pylist()
        out = ["NULL" if v is None
               else "'" + str(v).replace("'", "''") + "'" for v in vals]
        return _strings_out(out, None)
    return FunctionResolution(dt.VARCHAR, impl)


# -- sizes -----------------------------------------------------------------

def _rel_size(oid) -> int:
    db = _db()
    if db is None:
        return 0
    hit = db.oid_lookup(int(oid))
    if hit is None:
        return 0
    kind, schema, name = hit
    with db.lock:
        s = db.schemas.get(schema)
        t = s.tables.get(name) if s else None
    if t is None:
        return 0
    total = 0
    b = t.full_batch(None)
    for c in b.columns:
        total += int(c.data.nbytes)
        if getattr(c, "dictionary", None) is not None:
            total += sum(len(str(x)) for x in c.dictionary)
    return total


def _size_resolver(ts):
    def impl(cols, n):
        vals = cols[0].to_pylist()
        data = np.asarray([0 if v is None else _rel_size(v) for v in vals],
                          dtype=np.int64)
        return Column(dt.BIGINT, data, propagate_nulls(cols))
    return FunctionResolution(dt.BIGINT, impl)


for _name in ("pg_relation_size", "pg_total_relation_size",
              "pg_table_size", "pg_indexes_size"):
    _REGISTRY[_name] = _size_resolver


@register("pg_size_pretty")
def _pg_size_pretty(ts):
    def fmt(v):
        v = float(v)
        for unit in ("bytes", "kB", "MB", "GB", "TB"):
            if abs(v) < 10240 or unit == "TB":
                return (f"{int(v)} {unit}" if unit == "bytes"
                        else f"{v:.0f} {unit}")
            v /= 1024.0
    def impl(cols, n):
        vals = cols[0].to_pylist()
        out = [None if v is None else fmt(v) for v in vals]
        return _strings_out(out, propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


# -- session / server state -----------------------------------------------

def _current_role():
    from ..engine import CURRENT_CONNECTION
    conn = CURRENT_CONNECTION.get()
    return getattr(conn, "current_role", None) or "serene"


_const_fn("current_database", lambda: "serene")
_const_fn("current_catalog", lambda: "serene")
_const_fn("current_user", _current_role)
_const_fn("session_user", _current_role)
_const_fn("user", _current_role)
_const_fn("pg_backend_pid", lambda: 1, dt.INT)
_const_fn("pg_is_in_recovery", lambda: False, dt.BOOL)
_const_fn("txid_current", lambda: 1, dt.BIGINT)
_const_fn("pg_postmaster_start_time", lambda: "2026-01-01 00:00:00")
_const_fn("inet_server_addr", lambda: "127.0.0.1")
_const_fn("inet_client_addr", lambda: "127.0.0.1")
_const_fn("pg_conf_load_time", lambda: "2026-01-01 00:00:00")


@register("current_schemas")
def _current_schemas(ts):
    import json

    def impl(cols, n):
        include_implicit = True
        if cols:
            v = cols[0].to_pylist()
            include_implicit = bool(v[0]) if v else True
        arr = (["pg_catalog", "main"] if include_implicit else ["main"])
        s = json.dumps(arr)
        return Column.from_pylist([s] * max(n, 1), dt.VARCHAR)
    return FunctionResolution(dt.VARCHAR, impl)


@register("pg_encoding_to_char")
def _pg_encoding_to_char(ts):
    enc = {6: "UTF8", 0: "SQL_ASCII"}

    def impl(cols, n):
        vals = cols[0].to_pylist()
        out = [None if v is None else enc.get(int(v), "UTF8") for v in vals]
        return _strings_out(out, propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


def _priv_resolver(ts):
    def impl(cols, n):
        return Column(dt.BOOL, np.ones(n, dtype=bool), None)
    return FunctionResolution(dt.BOOL, impl)


for _name in ("has_table_privilege", "has_schema_privilege",
              "has_database_privilege", "has_column_privilege",
              "has_function_privilege", "has_sequence_privilege",
              "pg_has_role"):
    _REGISTRY[_name] = _priv_resolver


@register("to_regclass")
def _to_regclass(ts):
    def impl(cols, n):
        db = _db()
        vals = string_values(cols[0])
        out = np.zeros(n, dtype=np.int64)
        bad = np.zeros(n, dtype=bool)
        for i, v in enumerate(vals):
            try:
                out[i] = db.resolve_relation_oid(str(v)) if db else 0
                bad[i] = db is None
            except errors.SqlError:
                bad[i] = True
        validity = propagate_nulls(cols)
        if bad.any():
            validity = (validity if validity is not None
                        else np.ones(n, dtype=bool)) & ~bad
        return Column(dt.REGCLASS, out, validity)
    return FunctionResolution(dt.REGCLASS, impl)
